"""Benchmark: log-lines/sec classified against 1k regex rules (BASELINE.json).

Measures the device half of the TPU matcher — the batched NFA match that
replaces the reference's serial per-(line, rule) regexp loop
(/root/reference/internal/regex_rate_limiter.go:216-269) — on whatever
accelerator is attached (the real TPU chip under the driver; CPU otherwise),
plus the end-to-end TpuMatcher consume_lines path for context.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "lines/sec", "vs_baseline": N / 5e6}
vs_baseline is against the BASELINE.md north-star target of 5M lines/sec
@1k rules on v5e-1 (the reference itself publishes no numbers — see
BASELINE.md; its serial Go loop is the functional, not numerical, baseline).
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time

import numpy as np


N_RULES = 1000
BATCH = 8192
MAX_LEN = 128
WARMUP = 3
ITERS = 10

BACKEND_PROBE_TIMEOUT_S = 150
BACKEND_PROBE_RETRIES = 2


def _probe_backend() -> "tuple[str, str | None]":
    """Decide the backend before jax initializes in this process.

    TPU-tunnel init can hang indefinitely rather than raise, so the probe
    runs `jax.devices()` in a subprocess under a timeout, with retry +
    backoff. On repeated failure the bench falls back to host CPU so the
    driver still gets its one JSON line, with the failure recorded in
    "backend_error"."""
    if os.environ.get("BENCH_CPU"):
        return "cpu", None
    err = None
    for attempt in range(BACKEND_PROBE_RETRIES):
        if attempt:
            time.sleep(5 * attempt)
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True,
                timeout=BACKEND_PROBE_TIMEOUT_S,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1], None
            err = f"probe rc={r.returncode}: {r.stderr.strip()[-300:]}"
        except subprocess.TimeoutExpired:
            err = (f"probe timeout after {BACKEND_PROBE_TIMEOUT_S}s "
                   "(backend init hang)")
    return "cpu", err


def generate_rules(n: int, seed: int = 7) -> list:
    """OWASP-CRS-shaped synthetic ruleset (BASELINE.json configs[2]):
    literal attack paths, method+path prefixes, scanner UA tokens, char
    classes and bounded quantifiers — the pattern shapes of
    banjax-config.yaml's production rules."""
    rng = random.Random(seed)
    words = [
        "admin", "login", "wp", "xmlrpc", "shell", "config", "backup", "env",
        "passwd", "phpmyadmin", "setup", "install", "api", "token", "debug",
        "console", "cgi", "bin", "upload", "include", "vendor", "composer",
    ]
    exts = ["php", "asp", "aspx", "jsp", "cgi", "sh", "bak", "sql", "old"]
    patterns = []
    while len(patterns) < n:
        kind = rng.random()
        w1, w2 = rng.choice(words), rng.choice(words)
        ext = rng.choice(exts)
        if kind < 0.3:
            p = rf"GET /{w1}-{w2}/[a-z0-9_-]+\.{ext}"
        elif kind < 0.5:
            p = rf"(GET|POST) /{w1}/{w2}\.{ext}"
        elif kind < 0.65:
            p = rf"POST /{w1}[a-z]*/{w2}{rng.randint(0, 99)}"
        elif kind < 0.8:
            p = rf"/{w1}\.{ext}\?[a-z]+={rng.randint(0, 9)}[0-9]{{1,4}}"
        elif kind < 0.9:
            p = rf"(?i){w1}scan|{w2}bot/{rng.randint(1, 9)}\.[0-9]+"
        else:
            p = rf"^(GET|POST|HEAD) [a-z.-]+\.(com|org|net) .*/{w1}{w2}"
        patterns.append(p)
    return patterns


def synthesize_match(pattern: str, rng: random.Random) -> str:
    """Build a string the compiled rule actually matches (attack traffic)."""
    from banjax_tpu.matcher.rulec import compile_rule

    prog = compile_rule(pattern)
    if not prog.branches:
        return "GET example.com GET / HTTP/1.1 x -"
    br = rng.choice(prog.branches)
    chars = []
    for pos in br.positions:
        # prefer printable ASCII members of the byte class
        for lo, hi in ((0x61, 0x7A), (0x30, 0x39), (0x20, 0x7E)):
            cands = [b for b in range(lo, hi + 1) if (pos.cs >> b) & 1]
            if cands:
                break
        chars.append(chr(rng.choice(cands or [0x61])))
    body = "".join(chars)
    prefix = "" if br.anchored_start else "GET example.com "
    suffix = "" if br.anchored_end else " HTTP/1.1 ua -"
    return prefix + body + suffix


def generate_lines(n: int, patterns: list, seed: int = 11, attack_rate: float = 0.02) -> list:
    """Mostly benign traffic with ~attack_rate lines synthesized to match a
    random rule — the realistic shape of the tailer's input stream."""
    rng = random.Random(seed)
    hosts = ["example.com", "site.org", "news.net", "shop.com"]
    paths = [
        "/", "/index.html", "/assets/app.js", "/img/logo.png", "/about",
        "/api/v1/items", "/search?q=red4321", "/contact", "/news/2026/07",
    ]
    uas = ["Mozilla/5.0 (X11; Linux x86_64)", "curl/8.1", "Safari/604.1"]
    out = []
    for _ in range(n):
        if patterns and rng.random() < attack_rate:
            out.append(synthesize_match(rng.choice(patterns), rng))
            continue
        method = rng.choice(["GET", "GET", "GET", "POST", "HEAD"])
        out.append(
            f"{method} {rng.choice(hosts)} {method} {rng.choice(paths)} "
            f"HTTP/1.1 {rng.choice(uas)} -"
        )
    return out


def _time_chained(step, args, batch):
    """Throughput with a serial dependency between iterations (the popcount
    carries), so pipelined dispatch can't fake the timing."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    s = step(jnp.int32(0), *args)
    s.block_until_ready()
    first_call_s = time.perf_counter() - t0
    for _ in range(WARMUP):
        s = step(s, *args)
    s.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(ITERS):
        s = step(s, *args)
    s.block_until_ready()
    elapsed = time.perf_counter() - t0
    return batch * ITERS / elapsed, elapsed / ITERS, first_call_s


def run_bench(jax) -> dict:
    import jax.numpy as jnp

    from banjax_tpu.matcher import nfa_jax
    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.matcher.kernels import nfa_match
    from banjax_tpu.matcher.rulec import compile_rules

    backend = jax.devices()[0].platform
    patterns = generate_rules(N_RULES)

    t0 = time.perf_counter()
    compiled = compile_rules(patterns)
    compiled_sharded = compile_rules(patterns, n_shards="auto")
    compile_s = time.perf_counter() - t0
    n_device = int(compiled.device_ok.sum())

    lines = generate_lines(BATCH, patterns)
    cls_ids, lens, host_eval = encode_for_match(compiled_sharded, lines, MAX_LEN)
    assert not host_eval.any()
    # sort by length and trim the scan to the batch max, exactly as
    # match_batch_pallas does internally for the production runner path
    order = np.argsort(lens, kind="stable")
    cls_ids, lens = cls_ids[order], lens[order]
    lines = [lines[i] for i in order]  # keep the raw lines aligned
    L_p = max(8, -(-int(lens.max()) // 32) * 32)
    cls_ids = np.ascontiguousarray(cls_ids[:, :L_p])
    lens_dev = jax.device_put(lens)

    # --- Pallas kernel path (the flagship): one-hot MXU gather + VPU
    # shift-and, state resident in VMEM (matcher/kernels/nfa_match.py).
    # Off-TPU the kernel only runs in interpret mode, far too slow to time
    # at this batch size — the XLA path carries the off-TPU number and a
    # small interpret-mode slice keeps the parity check.
    pallas_ok = backend == "tpu"
    interpret = False
    prep = None
    try:
        prep = nfa_match.prepare(compiled_sharded)
        if not pallas_ok:
            raise nfa_match.PallasUnsupported("non-TPU backend: interpret-only")
        dev_fn = nfa_match.device_matcher(prep, BATCH, L_p,
                                          interpret=interpret)
        cls_t_dev = jax.device_put(np.ascontiguousarray(cls_ids.T))

        @jax.jit
        def chained_pallas(s, cls_t, ln):
            out = dev_fn(cls_t, ln)
            return s + out.astype(jnp.int32).sum()

        pallas_lps, pallas_lat, pallas_first = _time_chained(
            chained_pallas, (cls_t_dev, lens_dev), BATCH
        )
    except nfa_match.PallasUnsupported:
        pallas_ok = False

    # --- XLA scan path (the fallback backend), for comparison
    params = nfa_jax.match_params(compiled_sharded)
    cls_dev = jax.device_put(cls_ids)

    @jax.jit
    def chained_xla(s, cls, ln):
        out = nfa_jax.match_batch(params, cls, ln, compiled_sharded.n_rules)
        return s + out.astype(jnp.int32).sum()

    xla_lps, xla_lat, xla_first = _time_chained(
        chained_xla, (cls_dev, lens_dev), BATCH
    )

    out = np.asarray(
        nfa_jax.match_batch(params, cls_dev, lens_dev, compiled_sharded.n_rules)
    )
    match_rate = float(out.any(axis=1).mean())
    if pallas_ok:
        got = nfa_match.match_batch_pallas(prep, cls_ids, lens)
        assert (got == out).all(), "pallas/XLA match bitmap divergence"
    elif prep is not None:
        n_check = 256  # interpret mode: parity on a slice, no timing
        got = nfa_match.match_batch_pallas(
            prep, cls_ids[:n_check], lens[:n_check], interpret=True
        )
        assert (got == out[:n_check]).all(), "pallas/XLA match bitmap divergence"

    # --- two-stage literal prefilter (matcher/prefilter.py): END-TO-END
    # host-side throughput — encode + stage-1 scan of every line + stage-2
    # full NFA on candidate lines + bitmap merge, host orchestration
    # included. This is what the production runner path does per batch.
    from banjax_tpu.matcher.prefilter import PrefilterMatcher, build_plan

    pf_lps = pf_lat = None
    cand_frac = None
    plan = build_plan(patterns)
    if plan is not None:
        pf = PrefilterMatcher(
            plan, "pallas" if pallas_ok else "xla", MAX_LEN, max_batch=BATCH
        )
        bits_pf, he = pf.match_bits(lines)
        want = out.copy()
        for rid in plan.unsupported:
            want[:, rid] = 0
        assert (bits_pf == want).all(), "two-stage/single-stage divergence"
        cand_frac = float(
            np.count_nonzero(bits_pf[:, plan.f_idx].any(axis=1))
        ) / BATCH  # lower bound on true candidate rate; reported for context
        for _ in range(WARMUP):
            pf.match_bits(lines)
        t0 = time.perf_counter()
        for _ in range(ITERS):
            pf.match_bits(lines)
        elapsed = time.perf_counter() - t0
        pf_lps = BATCH * ITERS / elapsed
        pf_lat = elapsed / ITERS

    best_lps = max(pallas_lps, xla_lps) if pallas_ok else xla_lps
    best_lat = min(pallas_lat, xla_lat) if pallas_ok else xla_lat
    if pf_lps is not None and pf_lps > best_lps:
        best_lps, best_lat = pf_lps, pf_lat
    return {
        "metric": "log-lines/sec classified @1k rules (device NFA match)",
        "value": round(best_lps, 1),
        "unit": "lines/sec",
        "vs_baseline": round(best_lps / 5_000_000, 4),
        "backend": backend,
        "batch": BATCH,
        "batch_latency_ms": round(best_lat * 1e3, 3),
        "pallas_lines_per_sec": round(pallas_lps, 1) if pallas_ok else None,
        "xla_lines_per_sec": round(xla_lps, 1),
        "prefilter_e2e_lines_per_sec": round(pf_lps, 1) if pf_lps else None,
        "prefilter_candidate_fraction": (
            round(cand_frac, 4) if cand_frac is not None else None
        ),
        "prefilter_stage1_words": plan.stage1.n_words if plan else None,
        "prefilter_stage2_words": plan.stage2.n_words if plan else None,
        "rules_total": N_RULES,
        "rules_on_device": n_device,
        "nfa_words": compiled.n_words,
        "nfa_shards": compiled_sharded.n_shards,
        "rule_compile_s": round(compile_s, 2),
        "first_call_s": round(pallas_first if pallas_ok else xla_first, 2),
        "line_match_rate": round(match_rate, 4),
    }


def run_ladder() -> dict:
    """BENCH_LADDER=1: run the five BASELINE.json configs (tests/perf
    shapes) on the attached backend and fold their numbers into the JSON."""
    import io
    from contextlib import redirect_stdout

    from tests.perf import test_baseline_ladder as ladder

    out = {}
    for n, fn in (
        (1, ladder.test_config1_single_rule_replay_cpu_reference),
        (2, ladder.test_config2_default_ruleset_batch),
        (3, ladder.test_config3_1k_rules_batch),
        (4, ladder.test_config4_fused_ua_path_100k_ips),
        (5, ladder.test_config5_kafka_fed_stream_device_windows),
    ):
        buf = io.StringIO()
        try:
            with redirect_stdout(buf):
                fn()
            out[f"config{n}"] = json.loads(
                buf.getvalue().strip().splitlines()[-1]
            )["lines_per_sec"]
        except Exception as exc:  # noqa: BLE001 — one config failing keeps the rest
            # keep the measured number if the JSON line printed before the
            # failure (e.g. a floor assertion on a loaded host)
            measured = None
            for line in reversed(buf.getvalue().strip().splitlines()):
                try:
                    measured = json.loads(line).get("lines_per_sec")
                    break
                except (json.JSONDecodeError, AttributeError):
                    continue
            out[f"config{n}"] = {
                "lines_per_sec": measured,
                "error": f"{type(exc).__name__}: {exc}",
            }
    return out


def main() -> None:
    requested, backend_error = _probe_backend()

    result: dict
    try:
        import jax

        if requested == "cpu":
            # the axon sitecustomize pins jax_platforms to the TPU tunnel;
            # the config knob (not the env var) is what actually overrides it
            jax.config.update("jax_platforms", "cpu")
        result = run_bench(jax)
        if os.environ.get("BENCH_LADDER"):
            result["ladder"] = run_ladder()
    except Exception as exc:  # always emit the one JSON line, never a traceback
        result = {
            "metric": "log-lines/sec classified @1k rules (device NFA match)",
            "value": 0.0,
            "unit": "lines/sec",
            "vs_baseline": 0.0,
            "error": f"{type(exc).__name__}: {exc}",
        }
    if backend_error:
        result["backend_error"] = backend_error
    print(json.dumps(result))


if __name__ == "__main__":
    main()
