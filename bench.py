"""Benchmark: log-lines/sec classified against 1k regex rules (BASELINE.json).

Measures, on whatever accelerator is attached (the real TPU chip under the
driver; CPU otherwise), the replacement for the reference's serial
per-(line, rule) regexp loop (/root/reference/internal/regex_rate_limiter.go:216-269):

  * the single-stage Pallas NFA kernel (device-resident, chained) and the
    XLA-scan fallback — the raw device classification rate;
  * the fused two-stage prefilter (matcher/prefilter.py FusedPrefilter),
    both device-resident AND pipelined through submit/collect — the rate
    INCLUDING host<->device transport, which on the tunneled chip costs
    ~65 ms fixed per device→host pull and must be overlapped to matter;
  * the end-to-end TpuMatcher consume_lines path (native C parse + encode
    + fused match + device windows + Banner), with per-batch latency
    p50/p99 — the production numbers BASELINE.md names;
  * the sharded mesh path (parallel/mesh.py) executed compiled (not
    interpreted) on the attached chip with a degenerate dp=1/rp=1 mesh;
  * the five-config BASELINE.json ladder (tests/perf shapes).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "lines/sec", "vs_baseline": N / 5e6, ...}
vs_baseline is against the BASELINE.md north-star target of 5M lines/sec
@1k rules on v5e-1 (the reference itself publishes no numbers — see
BASELINE.md; its serial Go loop is the functional, not numerical, baseline).

Wedged-tunnel resilience (the r1-r3 failure mode): the measurements run in
a WORKER subprocess that persists every section's result to
BENCH_partial.json the moment it completes (atomic rename), stamped with
the backend it ran on and when. The supervisor (this file's main) never
touches the device itself: it probes, launches the worker under a hard
timeout, and composes the final JSON from the partial file — preferring
TPU-measured sections over CPU ones and labeling every merged section with
its measurement time. A tunnel that wedges mid-round (or mid-worker) can
therefore cost at most the section in flight, never the whole artifact.
Sections whose data came from an earlier process run (not the live worker)
are listed in `merged_from_partial`, and `final_probe_backend` records
what the end-of-round probe actually saw.

Env knobs: BENCH_CPU=1 forces the host backend; BENCH_NO_LADDER=1 skips the
ladder; BENCH_BUDGET_S caps worker wall time (default 480 s) — sections
past the deadline are skipped and marked; BENCH_SECTIONS=a,b runs only
those sections (worker dev loop).

Streaming modes: `bench.py --sync` / `bench.py --pipeline` measure the
end-to-end tailer-shaped feed through the synchronous consume path vs
the streaming pipeline scheduler (banjax_tpu/pipeline/), emit the same
one-line JSON schema, and merge both rows (plus the speedup) into
BENCH_pipeline.json.  Knobs: BENCH_STREAM_{RULES,LINES,CHUNK,BUDGET_MS},
BENCH_CPU=1 for the host backend.

Single-kernel mode: `bench.py --single-kernel` A/Bs the one-program
fused match+window path (pallas_single_kernel on — one dispatch, one
pull, no program-B turn) against the two-program A/B path on the
--fused-pipeline stream shape, banking lines/s, d2h bytes/batch and
the resolve-pull elimination into BENCH_single_kernel.json.  Knobs:
the BENCH_STREAM_* set, BENCH_CPU=1 for the host backend.

Host-parallel mode: `bench.py --host-parallel` A/Bs the sharded
encode-worker pool (workers 0 vs N) and the native slot manager (C vs
Python dict) at the all-distinct-IP host worst case, merging
core-count-keyed rows into BENCH_host_parallel.json.  Knobs:
BENCH_HOST_{LINES,WORKERS,ITERS,SLOT_BATCH}.

Trace-overhead mode: `bench.py --trace-overhead` A/Bs the pipelined
stream with the span recorder (obs/trace.py) off vs on — off → on →
off so run-order effects don't masquerade as recorder cost — banking
both rows and the delta into BENCH_trace_overhead.json (PERF round 9).
Knobs: BENCH_TRACE_{RING,ITERS} plus the BENCH_STREAM_* set.

Provenance-overhead mode: `bench.py --provenance-overhead` — the same
off → on → off protocol for the decision provenance ledger
(obs/provenance.py), on a ban-heavy IP rotation so the ledger actually
records, banked into BENCH_provenance_overhead.json.  The acceptance
gate (ISSUE 6): the ledger-on row must sit inside the off-run noise
band on the --pipeline-shaped feed.

Sketch-overhead mode: `bench.py --sketch-overhead` — the same
off → on → off protocol for the device traffic sketch (obs/sketch.py:
count-min heavy hitters + HLL cardinality + rule pressure), on the
ban-storm IP rotation so the sketch is actually populated (the banked
on-row carries sketch_lines/top1 as the witness), banked into
BENCH_sketch_overhead.json.  Acceptance gate (ISSUE 8): the sketch-on
row inside the off-run noise band.

Scenario mode: `bench.py --scenarios` — the adversarial scenario
harness (banjax_tpu/scenarios/): one row per named attack shape (flash
crowd, slow drip, rotating proxies, command flood, challenge storm,
log rotation through a real tailer, benign) with lines/s, shed ratio,
ban precision/recall vs the generator's oracle and SLO burn peaks,
plus a seeded chaos-soak row with per-failpoint-episode evidence —
banked into BENCH_scenarios.json.  Knobs: BENCH_SCEN_{SCALE,SEED},
BENCH_CPU=1.

Mega-state mode: `bench.py --mega-state` — the mega-state tiering A/B
(README "Mega-state tiering"): the streaming 10M-distinct-IP rotation
(scenarios/shapes.py mega_rotating_proxies_stream) driven through
consume_lines with the slot-admission gate OFF then ON, same stream,
slot capacity pinned at the 65k worst-case shape.  Banks both rows —
lines/s, ban precision/recall vs the offender-only oracle, slot
refusals, sketch admissions + FP rate, warm-tier spill/refill — into
BENCH_mega_state.json.  Acceptance (ISSUE 14): p/r 1.0 both rows and
the admission-on row's lines/s >= the admission-off row's.  Knobs:
BENCH_MEGA_{DISTINCT,CHUNK,SEED,CAPACITY,SKETCH_WIDTH}, BENCH_CPU=1.

Fabric mode: `bench.py --fabric` — the multi-host decision fabric
scaling run (banjax_tpu/fabric/harness.py): one dryrun episode per
shard count (N=1 baseline; N=2 and N=4 with one shard SIGKILLed
mid-flood and consistent-hash takeover), banking per-N lines/s plus
the takeover-window shed ratio into BENCH_fabric.json.  Every row is
recall-gated at 1.0 vs the oracle.  Knobs:
BENCH_FABRIC_{SHAPE,SEED,SCALE,NS}.

Fleet-obs mode: `bench.py --fleet-obs` — fleet observability overhead
on the N=2 fabric feed: off → on → off where "on" arms origin trace
propagation on every forwarded frame plus the worker fleet surfaces
(T_EXPLAIN / T_FLIGHTREC / T_STATS metrics).  The on-arm ban log is
byte-compared against off, and the banked row carries a live-plane
witness: a forwarded-line ban whose explain provenance joins the
origin trace id allocated at the tailing shard's admission.  Banked
into BENCH_fleet_obs.json.  Knobs: BENCH_FABRIC_{SHAPE,SEED,SCALE}.

Challenge mode: `bench.py --challenge` — the challenge plane
(banjax_tpu/challenge/): (a) PoW cookie verification throughput
(cookies/s) as a CPU-reference vs device-batched A/B over the same
pre-solved cookie set, accept counts forced identical; (b) a
challenge_storm row driving >= 1M DISTINCT cookieless challengers plus
scripted repeat offenders through the real decision-chain stage
(send_or_validate_sha_challenge), gated on bounded failure state
(entries <= challenge_failure_state_max) and failed-challenge ban
precision/recall 1.0 vs the scripted oracle.  Banked into
BENCH_challenge.json.  Knobs:
BENCH_CHAL_{COOKIES,ZERO_BITS,BATCH,DISTINCT,OFFENDERS,STATE_MAX,SEED},
BENCH_CPU=1.

Serve mode: `bench.py --serve` — the compiled /auth_request serving
path (httpapi/fastpath.py + native/decisiontable.c): (a) an in-process
decision-stage A/B (userspace nine-step chain vs shm-table template
path, identical already-decided workload) gated at fast path >= 5x
chain rps; (b) a byte-identity witness over a mixed allow / block /
challenge / expiring workload including live expiry-boundary
crossings, gated at 0 mismatches; (c) the real standalone server
driven by a concurrent raw-socket keepalive capacity client, chain-only
vs fast-path config, with rps + p50/p99 + the per-tier hit / per-reason
miss counters.  Banked into BENCH_serve.json.  Knobs:
BENCH_SERVE_{SEED,ITERS,WITNESS,NPC,CONC,TABLE_CAP}.
"""

from __future__ import annotations

import json
import os
import random
import subprocess
import sys
import time

import numpy as np


N_RULES = 1000
MAX_LEN = 128
WARMUP = 3
ITERS = 10
TARGET = 5_000_000.0

_DIR = os.path.dirname(os.path.abspath(__file__))
PARTIAL_PATH = os.path.join(_DIR, "BENCH_partial.json")

# Workload fingerprint: partial-file sections are only trusted when they
# were measured on the same workload this bench would run.
WORKLOAD = {"n_rules": N_RULES, "max_len": MAX_LEN, "rule_seed": 7}

SECTIONS = ("single_stage", "fused", "e2e", "mesh", "http", "ladder")

# A hung axon init can wedge on the terminal side; killing a client
# mid-device-op can ALSO wedge the terminal session for later clients
# (observed r3: a timeout-killed Mosaic compile left jax.devices() hanging
# for every subsequent process). So: probe in a subprocess with a GENEROUS
# timeout, retry with backoff, and fall back to CPU rather than kill
# aggressively.
BACKEND_PROBE_TIMEOUT_S = 240
BACKEND_PROBE_RETRIES = 2


def _probe_backend() -> "tuple[str, str | None]":
    """Decide the backend without initializing jax in this process."""
    if os.environ.get("BENCH_CPU"):
        return "cpu", None
    err = None
    for attempt in range(BACKEND_PROBE_RETRIES):
        if attempt:
            time.sleep(20 * attempt)
        try:
            r = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                capture_output=True, text=True,
                timeout=BACKEND_PROBE_TIMEOUT_S,
            )
            if r.returncode == 0 and r.stdout.strip():
                return r.stdout.strip().splitlines()[-1], None
            err = f"probe rc={r.returncode}: {r.stderr.strip()[-300:]}"
        except subprocess.TimeoutExpired:
            err = (f"probe timeout after {BACKEND_PROBE_TIMEOUT_S}s "
                   "(backend init hang — terminal session likely wedged)")
    return "cpu", err


# ---------------------------------------------------------------------------
# workload generation (imported by tests/perf and the unit suites)
# ---------------------------------------------------------------------------

def generate_rules(n: int, seed: int = 7) -> list:
    """OWASP-CRS-shaped synthetic ruleset (BASELINE.json configs[2]):
    literal attack paths, method+path prefixes, scanner UA tokens, char
    classes and bounded quantifiers — the pattern shapes of
    banjax-config.yaml's production rules."""
    rng = random.Random(seed)
    words = [
        "admin", "login", "wp", "xmlrpc", "shell", "config", "backup", "env",
        "passwd", "phpmyadmin", "setup", "install", "api", "token", "debug",
        "console", "cgi", "bin", "upload", "include", "vendor", "composer",
    ]
    exts = ["php", "asp", "aspx", "jsp", "cgi", "sh", "bak", "sql", "old"]
    patterns = []
    while len(patterns) < n:
        kind = rng.random()
        w1, w2 = rng.choice(words), rng.choice(words)
        ext = rng.choice(exts)
        if kind < 0.3:
            p = rf"GET /{w1}-{w2}/[a-z0-9_-]+\.{ext}"
        elif kind < 0.5:
            p = rf"(GET|POST) /{w1}/{w2}\.{ext}"
        elif kind < 0.65:
            p = rf"POST /{w1}[a-z]*/{w2}{rng.randint(0, 99)}"
        elif kind < 0.8:
            p = rf"/{w1}\.{ext}\?[a-z]+={rng.randint(0, 9)}[0-9]{{1,4}}"
        elif kind < 0.9:
            p = rf"(?i){w1}scan|{w2}bot/{rng.randint(1, 9)}\.[0-9]+"
        else:
            p = rf"^(GET|POST|HEAD) [a-z.-]+\.(com|org|net) .*/{w1}{w2}"
        patterns.append(p)
    return patterns


def synthesize_match(pattern: str, rng: random.Random) -> str:
    """Build a string the compiled rule actually matches (attack traffic)."""
    from banjax_tpu.matcher.rulec import compile_rule

    prog = compile_rule(pattern)
    if not prog.branches:
        return "GET example.com GET / HTTP/1.1 x -"
    br = rng.choice(prog.branches)
    chars = []
    for pos in br.positions:
        # prefer printable ASCII members of the byte class
        for lo, hi in ((0x61, 0x7A), (0x30, 0x39), (0x20, 0x7E)):
            cands = [b for b in range(lo, hi + 1) if (pos.cs >> b) & 1]
            if cands:
                break
        chars.append(chr(rng.choice(cands or [0x61])))
    body = "".join(chars)
    prefix = "" if br.anchored_start else "GET example.com "
    suffix = "" if br.anchored_end else " HTTP/1.1 ua -"
    return prefix + body + suffix


def generate_lines(n: int, patterns: list, seed: int = 11, attack_rate: float = 0.02) -> list:
    """Mostly benign traffic with ~attack_rate lines synthesized to match a
    random rule — the realistic shape of the tailer's input stream."""
    rng = random.Random(seed)
    hosts = ["example.com", "site.org", "news.net", "shop.com"]
    paths = [
        "/", "/index.html", "/assets/app.js", "/img/logo.png", "/about",
        "/api/v1/items", "/search?q=red4321", "/contact", "/news/2026/07",
    ]
    uas = ["Mozilla/5.0 (X11; Linux x86_64)", "curl/8.1", "Safari/604.1"]
    out = []
    for _ in range(n):
        if patterns and rng.random() < attack_rate:
            out.append(synthesize_match(rng.choice(patterns), rng))
            continue
        method = rng.choice(["GET", "GET", "GET", "POST", "HEAD"])
        out.append(
            f"{method} {rng.choice(hosts)} {method} {rng.choice(paths)} "
            f"HTTP/1.1 {rng.choice(uas)} -"
        )
    return out


def _time_chained(step, args, batch, iters=ITERS):
    """Throughput with a serial dependency between iterations (the popcount
    carries), so pipelined dispatch can't fake the timing."""
    import jax.numpy as jnp

    t0 = time.perf_counter()
    s = step(jnp.int32(0), *args)
    s.block_until_ready()
    first_call_s = time.perf_counter() - t0
    for _ in range(WARMUP):
        s = step(s, *args)
    s.block_until_ready()
    t0 = time.perf_counter()
    for _ in range(iters):
        s = step(s, *args)
    s.block_until_ready()
    elapsed = time.perf_counter() - t0
    return batch * iters / elapsed, elapsed / iters, first_call_s


# ---------------------------------------------------------------------------
# partial-file persistence
# ---------------------------------------------------------------------------

def _load_partial() -> dict:
    try:
        with open(PARTIAL_PATH) as f:
            p = json.load(f)
        if p.get("workload") != WORKLOAD:
            return {"workload": WORKLOAD, "sections": {}}
        return p
    except (OSError, json.JSONDecodeError):
        return {"workload": WORKLOAD, "sections": {}}


def _save_section(name: str, backend: str, data: dict) -> None:
    """Merge one section into BENCH_partial.json (atomic rename).

    Best-evidence rule: a CPU measurement never clobbers an existing TPU
    one; TPU overwrites TPU (newer code wins); CPU overwrites CPU."""
    p = _load_partial()
    prev = p["sections"].get(name)
    # 'meta' is bookkeeping (skip lists) and 'http' never touches the
    # device — neither is chip evidence, so newest always wins for them
    # (also migrates any http row a pre-fix tpu worker mislabeled).
    if (name not in ("meta", "http") and prev
            and prev.get("backend") == "tpu" and backend != "tpu"):
        return
    p["sections"][name] = {
        "backend": backend,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "data": data,
    }
    tmp = PARTIAL_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(p, f, indent=1)
    os.replace(tmp, PARTIAL_PATH)


# ---------------------------------------------------------------------------
# worker sections (run inside the worker subprocess, jax initialized)
# ---------------------------------------------------------------------------

class _Deadline:
    def __init__(self, budget_s: float):
        self.t0 = time.monotonic()
        self.budget = budget_s
        self.skipped: list = []

    def over(self, section: str) -> bool:
        if time.monotonic() - self.t0 > self.budget:
            self.skipped.append(section)
            return True
        return False


def _sec_single_stage(jax, ctx, backend, deadline, out) -> dict:
    """Single-stage device NFA classification (the r1/r2 headline path)."""
    import jax.numpy as jnp

    from banjax_tpu.matcher import nfa_jax
    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.matcher.kernels import nfa_match
    from banjax_tpu.matcher.rulec import compile_rules

    patterns = ctx["patterns"]
    batch = ctx["batch"]
    t0 = time.perf_counter()
    compiled = compile_rules(patterns, n_shards="auto")
    out["rule_compile_s"] = round(time.perf_counter() - t0, 2)
    out["rules_on_device"] = int(compiled.device_ok.sum())
    out["nfa_words"] = compiled.n_words
    out["nfa_shards"] = compiled.n_shards
    ctx["compiled"] = compiled

    lines = generate_lines(batch, patterns)
    cls_ids, lens, host_eval = encode_for_match(compiled, lines, MAX_LEN)
    assert not host_eval.any()
    order = np.argsort(lens, kind="stable")
    cls_ids, lens = cls_ids[order], lens[order]
    L_p = max(32, -(-int(lens.max()) // 32) * 32)
    cls_ids = np.ascontiguousarray(cls_ids[:, :L_p])
    lens_dev = jax.device_put(lens)

    params = nfa_jax.match_params(compiled)
    cls_dev = jax.device_put(cls_ids)

    @jax.jit
    def chained_xla(s, cls, ln):
        o = nfa_jax.match_batch(params, cls, ln, compiled.n_rules)
        return s + o.astype(jnp.int32).sum()

    xla_lps, xla_lat, xla_first = _time_chained(
        chained_xla, (cls_dev, lens_dev), batch
    )
    out["xla_lines_per_sec"] = round(xla_lps, 1)
    out["xla_batch_latency_ms"] = round(xla_lat * 1e3, 3)

    want = np.asarray(
        nfa_jax.match_batch(params, cls_dev, lens_dev, compiled.n_rules)
    )
    out["line_match_rate"] = round(float(want.any(axis=1).mean()), 4)
    out["first_call_s"] = round(xla_first, 2)
    out["pallas_lines_per_sec"] = None

    if backend == "tpu" and not deadline.over("pallas_single_stage"):
        prep = nfa_match.prepare(compiled)
        dev_fn = nfa_match.device_matcher(prep, batch, L_p, 512, cols=32)
        cls_t_dev = jax.device_put(np.ascontiguousarray(cls_ids.T))

        @jax.jit
        def chained_pallas(s, cls_t, ln):
            o = dev_fn(cls_t, ln)
            return s + o.astype(jnp.int32).sum()

        pallas_lps, pallas_lat, pallas_first = _time_chained(
            chained_pallas, (cls_t_dev, lens_dev), batch
        )
        out["pallas_lines_per_sec"] = round(pallas_lps, 1)
        out["pallas_batch_latency_ms"] = round(pallas_lat * 1e3, 3)
        out["first_call_s"] = round(pallas_first, 2)
        got = nfa_match.match_batch_pallas(prep, cls_ids, lens, cols=32)
        assert (got == want).all(), "pallas/XLA match bitmap divergence"
    return out


def _sec_fused(jax, ctx, backend, deadline, out) -> dict:
    """Fused two-stage prefilter: device-resident (chained, no per-iter
    transport) AND pipelined submit/collect (the honest
    classified-through-transport rate)."""
    import jax.numpy as jnp

    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.matcher.prefilter import FusedPrefilter, build_plan
    from banjax_tpu.matcher import nfa_jax
    from banjax_tpu.matcher.rulec import compile_rules

    patterns = ctx["patterns"]
    compiled = ctx.get("compiled")
    if compiled is None:
        compiled = compile_rules(patterns, n_shards="auto")
        ctx["compiled"] = compiled

    plan = build_plan(
        patterns, byte_classes=(compiled.byte_to_class, compiled.n_classes)
    )
    if plan is None:
        return out
    out["prefilter_stage1_words"] = plan.stage1.n_words
    out["prefilter_stage2_words"] = plan.stage2.n_words
    fp = FusedPrefilter(plan, "pallas" if backend == "tpu" else "xla")
    ctx["plan"] = plan

    batch = ctx["batch"]
    lines = generate_lines(batch, patterns, seed=23)
    cls_ids, lens, _ = encode_for_match(compiled, lines, MAX_LEN)
    bits = fp.match_bits_encoded(cls_ids, lens)  # compile + parity data
    # parity vs the single-stage oracle on this batch
    params = nfa_jax.match_params(compiled)
    want = np.asarray(
        nfa_jax.match_batch(
            params, jax.device_put(cls_ids), jax.device_put(lens),
            compiled.n_rules,
        )
    )
    for rid in plan.unsupported:
        want[:, rid] = 0
    assert (bits == want).all(), "fused/single-stage divergence"
    out["prefilter_candidate_fraction"] = round(
        float(want.any(axis=1).mean()), 4
    )
    if getattr(fp, "last_n_cand", None) is not None:
        # stage-1 gate rate: what fraction of lines actually reached
        # stage 2 (true matches + factor/superimposition false positives)
        out["prefilter_gate_fraction"] = round(fp.last_n_cand / batch, 4)

    # --- device-resident rate: the input uploaded once, chained on-device;
    # what the kernels deliver with transport out of the picture entirely
    best_resident = None
    for dr_batch in ctx["resident_batches"]:
        if deadline.over(f"fused_resident_{dr_batch}"):
            break
        dlines = generate_lines(dr_batch, patterns, seed=29)
        dcls, dlens, _ = encode_for_match(compiled, dlines, MAX_LEN)
        combined, Bp, L_p = fp._assemble(dcls, dlens)
        fn, K, P = fp._fused(Bp, L_p)
        dev_in = jax.device_put(combined)

        @jax.jit
        def chained(s, x):
            # sum the WHOLE output buffer: a partial slice would let XLA
            # dead-code-eliminate the stages that don't feed it
            return s + fn(x).astype(jnp.int32).sum()

        lps, lat, _ = _time_chained(chained, (dev_in,), dr_batch, iters=6)
        out[f"fused_device_resident_{dr_batch}"] = round(lps, 1)
        if best_resident is None or lps > best_resident:
            best_resident = lps
            out["fused_device_resident_lines_per_sec"] = round(lps, 1)
            out["fused_device_resident_batch"] = dr_batch
            out["fused_device_resident_latency_ms"] = round(lat * 1e3, 3)

    # --- pipelined submit/collect at the largest resident batch that fits
    # the budget: throughput INCLUDING transport, pulls overlapped
    pipe_batch = out.get("fused_device_resident_batch", batch)
    if pipe_batch != batch:
        plines = generate_lines(pipe_batch, patterns, seed=23)
        cls_ids, lens, _ = encode_for_match(compiled, plines, MAX_LEN)
    for _ in range(2):  # warm
        fp.collect(fp.submit(cls_ids, lens))
    n_iters = 8
    t0 = time.perf_counter()
    pend = fp.submit(cls_ids, lens)
    for _ in range(n_iters - 1):
        nxt = fp.submit(cls_ids, lens)
        fp.collect(pend)
        pend = nxt
    fp.collect(pend)
    elapsed = time.perf_counter() - t0
    lps = pipe_batch * n_iters / elapsed
    out["fused_pipelined_lines_per_sec"] = round(lps, 1)
    out["fused_pipelined_batch"] = pipe_batch
    out["fused_batch_latency_ms"] = round(elapsed / n_iters * 1e3, 3)
    return out


def _sec_e2e(jax, ctx, backend, deadline, out) -> dict:
    """End-to-end consume_lines: native parse + encode + fused device match
    + device windows + Banner replay. Reports throughput and the per-batch
    latency distribution (p50/p99) — the p99 Decision latency proxy: a
    line's decision lands at most one batch window behind its arrival."""
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from tests.mock_banner import MockBanner

    patterns = ctx["patterns"]
    # one consume_lines burst of several chunks exercises the overlapped
    # two-program pipeline (chunk N's pulls hide behind N+1's compute)
    batch = ctx["e2e_batch"] if backend == "tpu" else 2048
    burst_chunks = ctx["e2e_chunks"] if backend == "tpu" else 3
    n_batches = 6 if backend == "tpu" else 3
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    cfg = config_from_yaml_text(rules_yaml)
    cfg.matcher_batch_lines = batch
    cfg.matcher_device_windows = True
    banner = MockBanner()
    m = TpuMatcher(cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates())

    now = time.time()
    burst = batch * burst_chunks
    rests = generate_lines(burst, patterns, seed=31)
    lines = [
        f"{now:.6f} 10.{i % 64}.{(i >> 6) % 256}.{(i >> 14) % 256} {r}"
        for i, r in enumerate(rests)
    ]
    m.consume_lines(lines[:256], now)  # warm compile
    m.consume_lines(lines, now)
    lats = []
    t0 = time.perf_counter()
    for _ in range(n_batches):
        tb = time.perf_counter()
        m.consume_lines(lines, now)
        lats.append(time.perf_counter() - tb)
    elapsed = time.perf_counter() - t0
    lats.sort()
    out["e2e_lines_per_sec"] = round(burst * n_batches / elapsed, 1)
    out["e2e_batch"] = batch
    out["e2e_burst_chunks"] = burst_chunks
    # burst latencies measured as-is (dividing by chunks would silently
    # change the meaning of the old per-batch keys)
    out["e2e_burst_latency_ms_p50"] = round(lats[len(lats) // 2] * 1e3, 2)
    out["e2e_burst_latency_ms_p99"] = round(lats[-1] * 1e3, 2)
    out["e2e_staleness_budget_used"] = round(
        lats[-1] / 10.0, 4
    )  # full burst latency vs the 10 s drop window
    fw = getattr(m, "_fw_pipeline", None)
    if fw is not None:
        out["e2e_pipeline_fused"] = fw.fused_batches
        out["e2e_pipeline_fallback"] = fw.fallback_batches

    # realistic-traffic variant: heavy IP repetition (2k distinct) — the
    # default burst above is near-worst-case (every line a fresh IP, the
    # config4 shape), which stresses the per-distinct-ip host work; real
    # edges see orders of magnitude more reuse
    lines_r = [
        f"{now:.6f} 10.9.{(i % 2048) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]
    m.consume_lines(lines_r, now)
    t0 = time.perf_counter()
    for _ in range(n_batches):
        m.consume_lines(lines_r, now)
    out["e2e_repeat_ip_lines_per_sec"] = round(
        burst * n_batches / (time.perf_counter() - t0), 1
    )
    return out


def _sec_mesh(jax, ctx, backend, deadline, out) -> dict:
    """The sharded mesh path executed COMPILED on the attached backend with
    a degenerate dp=1/rp=1 mesh — the execution record that parallel/mesh.py
    runs the same code path the 8-device dryrun validates, on real silicon
    when a chip is attached."""
    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.parallel import mesh as pmesh
    from banjax_tpu.matcher.prefilter import build_plan
    from banjax_tpu.matcher.rulec import compile_rules

    patterns = ctx["patterns"]
    compiled = ctx.get("compiled")
    if compiled is None:
        compiled = compile_rules(patterns, n_shards="auto")
    # the mesh fused path needs stage 2 packed for exactly rp shards
    plan = build_plan(
        patterns, byte_classes=(compiled.byte_to_class, compiled.n_classes),
        stage2_shards=1,
    )
    m = pmesh.make_mesh(1, rp=1)
    be = pmesh.ShardedMatchBackend(
        compiled, m, MAX_LEN,
        backend="pallas" if backend == "tpu" else "xla",
        block_b=128, plan=plan,
    )
    batch = 16384 if backend == "tpu" else 2048
    lines = generate_lines(batch, patterns, seed=37)
    cls_ids, lens, _ = encode_for_match(compiled, lines, MAX_LEN)
    be.match_bits(cls_ids, lens)  # compile
    n = 4
    t0 = time.perf_counter()
    for _ in range(n):
        be.match_bits(cls_ids, lens)
    elapsed = time.perf_counter() - t0
    # labeled single-device row: this is NOT a parallel measurement — it
    # proves the sharded code path compiles + runs on the attached silicon
    out["mesh_singledev_lines_per_sec"] = round(batch * n / elapsed, 1)
    out["mesh_singledev_shape"] = {"dp": 1, "rp": 1}
    out["mesh_singledev_backend"] = backend
    out["mesh_batch"] = batch
    out["mesh_fused_batches"] = be.fused_batches

    # the real multi-device execution record: dp=2 x rp=4 COMPILED (XLA,
    # non-interpret) over 8 virtual CPU devices in a fresh subprocess.
    # Scaling numbers on virtual devices are meaningless (one physical
    # core) — the row proves compiled multi-device execution and is
    # labeled with its backend so it can never masquerade as a chip number.
    if deadline.over("mesh_multidev"):
        out["mesh_multidev"] = None
        return out
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        flags = (flags + " --xla_force_host_platform_device_count=8").strip()
    env = dict(os.environ, JAX_PLATFORMS="cpu", XLA_FLAGS=flags)
    env.pop("BENCH_SECTIONS", None)
    try:
        r = subprocess.run(
            [sys.executable, "-c", _MESH_MULTIDEV_CHILD, _DIR],
            capture_output=True, text=True, timeout=600, env=env,
        )
        if r.returncode == 0:
            out["mesh_multidev"] = json.loads(
                r.stdout.strip().splitlines()[-1]
            )
        else:
            out["mesh_multidev"] = {"error": (r.stderr or "no output")[-500:]}
    except Exception as exc:  # noqa: BLE001 — empty stdout / timeout /
        # bad JSON must not zero the section's singledev row
        out["mesh_multidev"] = {"error": f"{type(exc).__name__}: {exc}"}
    return out


_MESH_MULTIDEV_CHILD = r"""
import json, sys, time
sys.path.insert(0, sys.argv[1])
import jax
jax.config.update("jax_platforms", "cpu")
import bench
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.prefilter import build_plan
from banjax_tpu.matcher.rulec import compile_rules
from banjax_tpu.parallel import mesh as pmesh

assert len(jax.devices()) >= 8, jax.devices()
patterns = bench.generate_rules(bench.N_RULES)
# the rp axis shards the packed word dimension: compile with n_shards=rp
# so every shard is padded to the same width (what the dryrun does too)
compiled = compile_rules(patterns, n_shards=4)
plan = build_plan(
    patterns, byte_classes=(compiled.byte_to_class, compiled.n_classes),
    stage2_shards=4,
)
m = pmesh.make_mesh(8, rp=4)
be = pmesh.ShardedMatchBackend(
    compiled, m, bench.MAX_LEN, backend="xla", block_b=128, plan=plan,
)
batch = 4096
lines = bench.generate_lines(batch, patterns, seed=41)
cls_ids, lens, _ = encode_for_match(compiled, lines, bench.MAX_LEN)
be.match_bits(cls_ids, lens)  # compile
n = 3
t0 = time.perf_counter()
for _ in range(n):
    be.match_bits(cls_ids, lens)
elapsed = time.perf_counter() - t0
print(json.dumps({
    "lines_per_sec": round(batch * n / elapsed, 1),
    "shape": {"dp": 2, "rp": 4},
    "backend": "cpu-virtual-8dev",
    "compiled": True,
    "interpret": False,
    "batch": batch,
    "fused_batches": be.fused_batches,
}))
"""


def _sec_ladder(jax, ctx, backend, deadline, out) -> dict:
    """The five BASELINE.json configs (tests/perf shapes) on the attached
    backend; one config failing keeps the rest."""
    import io
    from contextlib import redirect_stdout

    from tests.perf import test_baseline_ladder as ladder

    lad = {}
    for n, fn in (
        (1, ladder.test_config1_single_rule_replay_cpu_reference),
        (2, ladder.test_config2_default_ruleset_batch),
        (3, ladder.test_config3_1k_rules_batch),
        (4, ladder.test_config4_fused_ua_path_100k_ips),
        (5, ladder.test_config5_kafka_fed_stream_device_windows),
    ):
        if deadline.over(f"ladder_config{n}"):
            lad[f"config{n}"] = None
            out["ladder"] = lad
            continue
        buf = io.StringIO()
        try:
            with redirect_stdout(buf):
                fn()
            lps = json.loads(
                buf.getvalue().strip().splitlines()[-1]
            )["lines_per_sec"]
            lad[f"config{n}"] = lps
            lad[f"config{n}_target_fraction"] = round((lps or 0) / TARGET, 4)
            out["ladder"] = lad
        except Exception as exc:  # noqa: BLE001 — one config failing keeps the rest
            measured = None
            for line in reversed(buf.getvalue().strip().splitlines()):
                try:
                    measured = json.loads(line).get("lines_per_sec")
                    break
                except (json.JSONDecodeError, AttributeError):
                    continue
            lad[f"config{n}"] = {
                "lines_per_sec": measured,
                "error": f"{type(exc).__name__}: {exc}",
            }
            lad[f"config{n}_target_fraction"] = round(
                (measured or 0) / TARGET, 4
            )
            out["ladder"] = lad
    # machine-readable progress toward BASELINE.md's >=5M lines/s: the
    # best ladder fraction (config3 is the 1k-rule north-star shape)
    fracs = [v for k, v in lad.items() if k.endswith("_target_fraction")]
    out["ladder_best_target_fraction"] = max(fracs) if fracs else None
    return out


def _sec_http(jax, ctx, backend, deadline, out) -> dict:
    """The reference's OWN headline harnesses (BenchmarkAuthRequest /
    BenchmarkProtectedPaths, banjax_performance_test.go:18-67) through the
    real standalone server — recorded as requests/sec."""
    import io
    from contextlib import redirect_stdout

    import pytest as _pytest

    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = _pytest.main([
            os.path.join(_DIR, "tests", "perf", "test_http_benchmarks.py"),
            "-q", "-s", "-p", "no:cacheprovider",
        ])
    for line in buf.getvalue().splitlines():
        # pytest's progress dots can prefix the payload ('.{"benchmark"...')
        brace = line.find("{")
        if brace < 0:
            continue
        try:
            row = json.loads(line[brace:])
        except json.JSONDecodeError:
            continue
        if row.get("benchmark") == "auth_request":
            out["auth_request_rps"] = row["rps"]
        elif row.get("benchmark") == "protected_paths":
            out["protected_paths_rps"] = row["rps"]
        elif row.get("benchmark") == "auth_request_capacity":
            out["auth_request_capacity_rps"] = row["rps"]
            out["http_cpu_count"] = row.get("cpu_count")
        elif row.get("benchmark") == "auth_request_capacity_workers":
            out["auth_request_capacity_workers_rps"] = row["rps"]
            out["http_workers"] = row.get("http_workers")
    out["http_bench_rc"] = int(rc)
    return out


_SECTION_FNS = {
    "single_stage": _sec_single_stage,
    "fused": _sec_fused,
    "e2e": _sec_e2e,
    "mesh": _sec_mesh,
    "http": _sec_http,
    "ladder": _sec_ladder,
}


def worker_main(backend: str, budget_s: float, only: "list | None") -> None:
    import jax

    if backend == "cpu":
        # the axon sitecustomize pins jax_platforms to the TPU tunnel;
        # the config knob (not the env var) is what actually overrides it
        jax.config.update("jax_platforms", "cpu")
    actual = jax.devices()[0].platform
    deadline = _Deadline(budget_s)
    ctx = {
        "patterns": generate_rules(N_RULES),
        "batch": 32768 if actual == "tpu" else 8192,
        "resident_batches": (65536, 131072) if actual == "tpu" else (8192,),
        "e2e_batch": 32768,
        "e2e_chunks": 3,
    }
    sections = [s for s in SECTIONS if not only or s in only]
    if os.environ.get("BENCH_NO_LADDER") and "ladder" in sections:
        sections.remove("ladder")
    for name in sections:
        if deadline.over(name):
            continue
        data: dict = {}
        try:
            _SECTION_FNS[name](jax, ctx, actual, deadline, data)
        except Exception as exc:  # noqa: BLE001 — persist the failure AND
            # whatever the section measured before it (e.g. the XLA numbers
            # survive a Mosaic lowering reject later in the same section)
            data["error"] = f"{type(exc).__name__}: {exc}"
        data["section_elapsed_s"] = round(time.monotonic() - deadline.t0, 1)
        # the http section never touches the device: label it cpu always,
        # so a tpu-worker run can't freeze it under the best-evidence rule
        _save_section(name, "cpu" if name == "http" else actual, data)
        print(f"[bench-worker] {name} done on {actual}", file=sys.stderr)
    if deadline.skipped:
        _save_section(
            "meta", actual, {"sections_skipped_on_budget": deadline.skipped}
        )


# ---------------------------------------------------------------------------
# streaming modes: --pipeline vs --sync (the scheduler's acceptance bench)
# ---------------------------------------------------------------------------

STREAM_PATH = os.path.join(_DIR, "BENCH_pipeline.json")
FUSED_STREAM_PATH = os.path.join(_DIR, "BENCH_fused_pipeline.json")
HOST_PARALLEL_PATH = os.path.join(_DIR, "BENCH_host_parallel.json")
TRACE_OVERHEAD_PATH = os.path.join(_DIR, "BENCH_trace_overhead.json")


def _trace_overhead_mode() -> None:
    """`bench.py --trace-overhead`: A/B the pipelined stream with the
    span recorder (obs/trace.py) disabled vs enabled and bank both rows
    plus the relative delta into BENCH_trace_overhead.json.

    The acceptance gate is the OFF row: the instrumented hot path with
    `trace_enabled: false` must cost ≤1% vs enabled tracing being the
    only difference — the disabled fast path is one attribute check per
    call site.  Same workload shape as `--pipeline` (tailer-shaped
    chunks through the scheduler), fresh matcher per mode, warm pass
    before every timed pass so compiles never land in the timing.
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.obs import trace as trace_mod
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "32768"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "64"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))
    ring_size = int(os.environ.get("BENCH_TRACE_RING", "4096"))
    iters = int(os.environ.get("BENCH_TRACE_ITERS", "3"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    rests = generate_lines(total, patterns, seed=43)
    lines = [
        f"{now:.6f} 10.9.{(i % 2048) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    def run_mode(enabled: bool) -> dict:
        trace_mod.configure(enabled=enabled, ring_size=ring_size)
        cfg = config_from_yaml_text(rules_yaml)
        matcher = TpuMatcher(
            cfg, MockBanner(), StaticDecisionLists(cfg),
            RegexRateLimitStates()
        )
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        for c in chunks:  # warm pass: compiles + sizer settle
            sched.submit(c)
        assert sched.flush(600), "trace-overhead warm pass did not drain"
        best = 0.0
        for _ in range(iters):
            t0 = time.perf_counter()
            for c in chunks:
                sched.submit(c)
            assert sched.flush(600), "trace-overhead pass did not drain"
            best = max(best, total / (time.perf_counter() - t0))
        spans = len(trace_mod.get_tracer().snapshot())
        sched.stop()
        matcher.close()
        trace_mod.configure(enabled=False)
        return {
            "trace_enabled": enabled,
            "value": round(best, 1),
            "unit": "lines/sec",
            "backend": backend,
            "n_rules": n_rules,
            "n_lines": total,
            "feed_chunk_lines": feed_chunk,
            "iters_best_of": iters,
            "spans_in_ring": spans,
        }

    # off → on → off: the second off run controls for run-order effects
    # (in-process compile caches, sizer settle, thermal drift) that can
    # otherwise dwarf the ≤1% effect being measured; each mode reports
    # its best pass, off takes the best of both bracketing runs
    off_a = run_mode(False)
    on = run_mode(True)
    off_b = run_mode(False)
    off = max(off_a, off_b, key=lambda r: r["value"])
    book = {
        "metric": "pipelined lines/sec, span recorder off vs on",
        "off": off,
        "on": on,
        "off_runs": [off_a["value"], off_b["value"]],
        "trace_ring_size": ring_size,
        # on-vs-off: the full cost of RECORDING every stage span;
        # negative = within run-to-run noise
        "on_vs_off_overhead_pct": round(
            (off["value"] - on["value"]) / off["value"] * 100.0, 2
        ),
    }
    tmp = TRACE_OVERHEAD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, TRACE_OVERHEAD_PATH)
    print(json.dumps(book))


PROVENANCE_OVERHEAD_PATH = os.path.join(
    _DIR, "BENCH_provenance_overhead.json"
)


def _provenance_overhead_mode() -> None:
    """`bench.py --provenance-overhead`: A/B the pipelined stream with
    the decision provenance ledger (obs/provenance.py) disabled vs
    enabled, same off → on → off bracketing protocol as
    --trace-overhead, banked into BENCH_provenance_overhead.json.

    Unlike the trace A/B, the workload must actually FIRE bans or the
    ledger sits idle and the measurement is vacuous: the feed rotates a
    small IP pool (BENCH_PROV_IPS, default 256) against a low
    hits_per_interval so every IP bans repeatedly through the run —
    `records_in_ledger` in the banked row witnesses the exercised path.
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.obs import provenance as prov_mod
    from banjax_tpu.obs import trace as trace_mod
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    trace_mod.configure(enabled=False)  # isolate the ledger's cost
    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "32768"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "64"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))
    ring_size = int(os.environ.get("BENCH_PROV_RING", "2048"))
    n_ips = int(os.environ.get("BENCH_PROV_IPS", "256"))
    hits_per_interval = int(os.environ.get("BENCH_PROV_HITS", "10"))
    attack_rate = float(os.environ.get("BENCH_PROV_ATTACK", "0.05"))
    iters = int(os.environ.get("BENCH_TRACE_ITERS", "3"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": hits_per_interval,
             "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    # rate limiting is per (ip, rule): the generic 2% attack mix spread
    # over 1000 rules never re-hits one pair, so the ledger would sit
    # idle.  Concentrate attack_rate of the stream on rule 0 from a
    # small rotating IP pool — every IP re-crosses the threshold again
    # and again, which is exactly the ban-storm shape the ledger must
    # absorb without slowing the pipeline.
    rng = random.Random(43)
    benign = generate_lines(total, patterns, seed=43, attack_rate=0.0)
    attack_rest = synthesize_match(patterns[0], rng)
    rests = [
        attack_rest if rng.random() < attack_rate else benign[i]
        for i in range(total)
    ]
    lines = [
        f"{now:.6f} 10.9.{(i % n_ips) >> 8}.{(i % n_ips) & 0xFF} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    def run_mode(enabled: bool) -> dict:
        prov_mod.configure(enabled=enabled, ring_size=ring_size)
        cfg = config_from_yaml_text(rules_yaml)
        matcher = TpuMatcher(
            cfg, MockBanner(), StaticDecisionLists(cfg),
            RegexRateLimitStates()
        )
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        for c in chunks:  # warm pass: compiles + sizer settle
            sched.submit(c)
        assert sched.flush(600), "provenance warm pass did not drain"
        best = 0.0
        for _ in range(iters):
            t0 = time.perf_counter()
            for c in chunks:
                sched.submit(c)
            assert sched.flush(600), "provenance pass did not drain"
            best = max(best, total / (time.perf_counter() - t0))
        records = prov_mod.get_ledger().total_records()
        sched.stop()
        matcher.close()
        prov_mod.configure(enabled=True)
        return {
            "provenance_enabled": enabled,
            "value": round(best, 1),
            "unit": "lines/sec",
            "backend": backend,
            "n_rules": n_rules,
            "n_lines": total,
            "n_distinct_ips": n_ips,
            "hits_per_interval": hits_per_interval,
            "feed_chunk_lines": feed_chunk,
            "iters_best_of": iters,
            "records_in_ledger": records,
        }

    # off → on → off bracketing, exactly like --trace-overhead: the
    # second off run controls for run-order effects (compile caches,
    # sizer settle) that can dwarf the effect being measured
    off_a = run_mode(False)
    on = run_mode(True)
    off_b = run_mode(False)
    off = max(off_a, off_b, key=lambda r: r["value"])
    noise_band_pct = round(
        abs(off_a["value"] - off_b["value"])
        / max(off_a["value"], off_b["value"]) * 100.0, 2
    )
    overhead_pct = round(
        (off["value"] - on["value"]) / off["value"] * 100.0, 2
    )
    book = {
        "metric": "pipelined lines/sec, provenance ledger off vs on",
        "off": off,
        "on": on,
        "off_runs": [off_a["value"], off_b["value"]],
        "provenance_ring_size": ring_size,
        "on_vs_off_overhead_pct": overhead_pct,
        # the off↔off spread IS the noise band; the acceptance gate is
        # on_within_off_noise_band (ISSUE 6)
        "off_run_noise_band_pct": noise_band_pct,
        "on_within_off_noise_band": bool(
            overhead_pct <= max(noise_band_pct, 1.0)
        ),
    }
    tmp = PROVENANCE_OVERHEAD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, PROVENANCE_OVERHEAD_PATH)
    print(json.dumps(book))


SKETCH_OVERHEAD_PATH = os.path.join(_DIR, "BENCH_sketch_overhead.json")


def _sketch_overhead_mode() -> None:
    """`bench.py --sketch-overhead`: A/B the pipelined stream with the
    device traffic sketch (obs/sketch.py) disabled vs enabled, same
    off → on → off bracketing protocol as --provenance-overhead, on the
    SAME ban-storm shape (rotating IP pool, concentrated single-rule
    attack) so the sketch actually works: heavy hitters recur, slots
    churn the hash table, and rule pressure accumulates.  The banked
    row carries a populated-sketch witness (`sketch_lines`, `top1`) so
    an accidentally-idle sketch can't bank a vacuous "no overhead"."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.obs import trace as trace_mod
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    trace_mod.configure(enabled=False)  # isolate the sketch's cost
    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "32768"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "64"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))
    n_ips = int(os.environ.get("BENCH_PROV_IPS", "256"))
    hits_per_interval = int(os.environ.get("BENCH_PROV_HITS", "10"))
    attack_rate = float(os.environ.get("BENCH_PROV_ATTACK", "0.05"))
    iters = int(os.environ.get("BENCH_TRACE_ITERS", "3"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": hits_per_interval,
             "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    rng = random.Random(43)
    benign = generate_lines(total, patterns, seed=43, attack_rate=0.0)
    attack_rest = synthesize_match(patterns[0], rng)
    rests = [
        attack_rest if rng.random() < attack_rate else benign[i]
        for i in range(total)
    ]
    lines = [
        f"{now:.6f} 10.9.{(i % n_ips) >> 8}.{(i % n_ips) & 0xFF} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    def run_mode(enabled: bool) -> dict:
        cfg = config_from_yaml_text(rules_yaml)
        # the sketch rides the device-windows fused path (its update keys
        # on the window slot ids) — both arms run that path
        cfg.matcher_device_windows = True
        cfg.traffic_sketch_enabled = enabled
        matcher = TpuMatcher(
            cfg, MockBanner(), StaticDecisionLists(cfg),
            RegexRateLimitStates()
        )
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        for c in chunks:  # warm pass: compiles + sizer settle
            sched.submit(c)
        assert sched.flush(600), "sketch warm pass did not drain"
        best = 0.0
        for _ in range(iters):
            t0 = time.perf_counter()
            for c in chunks:
                sched.submit(c)
            assert sched.flush(600), "sketch pass did not drain"
            best = max(best, total / (time.perf_counter() - t0))
        row = {
            "sketch_enabled": enabled,
            "value": round(best, 1),
            "unit": "lines/sec",
            "backend": backend,
            "n_rules": n_rules,
            "n_lines": total,
            "n_distinct_ips": n_ips,
            "hits_per_interval": hits_per_interval,
            "feed_chunk_lines": feed_chunk,
            "iters_best_of": iters,
        }
        if enabled:
            # the populated-sketch witness: lines actually folded, and a
            # ranked heavy hitter with a conservative estimate
            summary = matcher.traffic_sketch.pull(force=True)
            row["sketch_lines"] = matcher.traffic_sketch.lines_total
            row["top1"] = summary["top"][0] if summary["top"] else None
            row["distinct_ips_estimate"] = summary["distinct_ips_estimate"]
            row["rule_pressure_events"] = sum(
                r["events"] for r in summary["rule_pressure"]
            )
        sched.stop()
        matcher.close()
        return row

    # off → on → off bracketing, exactly like --provenance-overhead: the
    # second off run controls for run-order effects (compile caches,
    # sizer settle) that can dwarf the effect being measured
    off_a = run_mode(False)
    on = run_mode(True)
    off_b = run_mode(False)
    off = max(off_a, off_b, key=lambda r: r["value"])
    noise_band_pct = round(
        abs(off_a["value"] - off_b["value"])
        / max(off_a["value"], off_b["value"]) * 100.0, 2
    )
    overhead_pct = round(
        (off["value"] - on["value"]) / off["value"] * 100.0, 2
    )
    book = {
        "metric": "pipelined lines/sec, traffic sketch off vs on",
        "off": off,
        "on": on,
        "off_runs": [off_a["value"], off_b["value"]],
        "on_vs_off_overhead_pct": overhead_pct,
        # the off↔off spread IS the noise band; the acceptance gate is
        # on_within_off_noise_band (ISSUE 8)
        "off_run_noise_band_pct": noise_band_pct,
        "on_within_off_noise_band": bool(
            overhead_pct <= max(noise_band_pct, 1.0)
        ),
    }
    tmp = SKETCH_OVERHEAD_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, SKETCH_OVERHEAD_PATH)
    print(json.dumps(book))


def _host_parallel_mode() -> None:
    """`bench.py --host-parallel`: A/B the two host-path optimizations.

    (a) encode stage, workers 0 vs N: times the scheduler's host stage
        (parse + gate + encode, matcher.pipeline_begin) directly —
        single-thread vs the sharded worker pool — on the all-distinct-IP
        worst case from PERF round 4.  Device time is deliberately out of
        the measurement: this is the stage the PR parallelizes.
    (b) slot manager, native C vs Python dict: per-batch cost of
        slots_for_unique_ips at the all-distinct-IP shape (every batch
        all-new ips — the ~15 ms/batch residual in PERF r4's table), plus
        the all-hit warm shape.

    Provenance is honest by construction: rows are keyed by the host's
    core count, so the 1-core CI row (where worker scaling CANNOT
    manifest — the acceptance there is "within noise") never masquerades
    as the multi-core chip-host row hw_session.sh banks.
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.pipeline import PipelineScheduler
    from banjax_tpu.pipeline.scheduler import resolve_encode_workers
    from tests.mock_banner import MockBanner

    backend = jax.devices()[0].platform
    cores = os.cpu_count() or 1
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    n_lines = int(os.environ.get("BENCH_HOST_LINES", "32768"))
    workers = int(os.environ.get(
        "BENCH_HOST_WORKERS", str(max(2, resolve_encode_workers(-1)))
    ))
    iters = int(os.environ.get("BENCH_HOST_ITERS", "6"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    cfg = config_from_yaml_text(rules_yaml)
    matcher = TpuMatcher(
        cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates()
    )
    now = time.time()
    rests = generate_lines(n_lines, patterns, seed=53)
    # all-distinct IPs: the host-stage worst case (PERF r4) — every line
    # a fresh entry in the unique-IP table
    lines = [
        f"{now:.6f} 10.{(i >> 16) & 63}.{(i >> 8) & 255}.{i & 255} {r}"
        for i, r in enumerate(rests)
    ]

    # --- (a) encode stage: workers 0 vs N over the identical batch ---
    # resolved_default_workers is what encode_workers=-1 (the config
    # default) picks on THIS host: 0 on a 1-core box — the A/B's forced
    # worker row there measures pure fan-out overhead a production
    # deployment never pays
    encode = {
        "n_lines": n_lines,
        "workers_ab": workers,
        "resolved_default_workers": resolve_encode_workers(-1),
    }
    for w in (0, workers):
        sched = PipelineScheduler(lambda: matcher, encode_workers=w,
                                  now_fn=lambda: now)
        sched.start()  # creates the worker pool; stage threads idle
        for _ in range(2):
            sched._begin_state(matcher, lines)  # warm (parse caches, jit)
        t0 = time.perf_counter()
        for _ in range(iters):
            sched._begin_state(matcher, lines)
        elapsed = time.perf_counter() - t0
        snap = sched.stats.snapshot()
        sched.stop()
        key = "workers0" if w == 0 else f"workers{w}"
        encode[f"{key}_lines_per_sec"] = round(n_lines * iters / elapsed, 1)
        encode[f"{key}_batch_ms"] = round(elapsed / iters * 1e3, 2)
        if w:
            encode["sharded_batches"] = snap["EncodeShardedBatches"]
            encode["shard_ms_max"] = snap["EncodeShardMsMax"]
            encode["worker_utilization"] = snap["EncodeWorkerUtilization"]
    encode["workers_speedup"] = round(
        encode[f"workers{workers}_lines_per_sec"]
        / max(1.0, encode["workers0_lines_per_sec"]), 3
    )

    # --- (b) slot manager: native vs dict at the all-distinct shape ---
    from banjax_tpu.matcher.windows import DeviceWindows
    from banjax_tpu.native import slotmgr as _slotmgr

    slot_batch = int(os.environ.get("BENCH_HOST_SLOT_BATCH", "65536"))
    slot_iters = 4
    slotmgr = {
        "batch_unique_ips": slot_batch,
        "native_available": _slotmgr.create(8) is not None,
    }
    for native in ((True, False) if slotmgr["native_available"] else (False,)):
        dw = DeviceWindows(
            [matcher._entries[0][1]],
            capacity=slot_batch * slot_iters, native_slotmgr=native,
        )
        mode = "native" if native else "python"
        ip_batches = [
            [f"{j}.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
             for i in range(slot_batch)]
            for j in range(slot_iters)
        ]
        # cold: every batch all-new ips (miss + placement per entry)
        t0 = time.perf_counter()
        for ips in ip_batches:
            slots = dw.slots_for_unique_ips(ips)
            dw.release_pins(slots)
        slotmgr[f"{mode}_all_distinct_ms_per_batch"] = round(
            (time.perf_counter() - t0) / slot_iters * 1e3, 2
        )
        # warm: the same ips again (pure hit path)
        t0 = time.perf_counter()
        for ips in ip_batches:
            slots = dw.slots_for_unique_ips(ips)
            dw.release_pins(slots)
        slotmgr[f"{mode}_all_hit_ms_per_batch"] = round(
            (time.perf_counter() - t0) / slot_iters * 1e3, 2
        )
    if slotmgr["native_available"]:
        slotmgr["native_vs_python_cost_ratio"] = round(
            slotmgr["native_all_distinct_ms_per_batch"]
            / max(1e-9, slotmgr["python_all_distinct_ms_per_batch"]), 3
        )

    row = {
        "backend": backend,
        "cpu_count": cores,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "n_rules": n_rules,
        "encode": encode,
        "slotmgr": slotmgr,
        "provenance_note": (
            "1-core host: the worker pool CANNOT scale here (acceptance "
            "is 'within noise of single-thread'); scaling evidence must "
            "come from a multi-core row"
            if cores == 1 else
            f"{cores}-core host: workers_speedup is a real scaling "
            "measurement"
        ),
    }
    try:
        with open(HOST_PARALLEL_PATH) as f:
            book = json.load(f)
    except (OSError, json.JSONDecodeError):
        book = {}
    book.setdefault(
        "metric",
        "host-path A/B: sharded encode workers + native slot manager",
    )
    # rows keyed by core count: the 1-core CI row and the multi-core
    # chip-host row coexist instead of clobbering each other
    book[f"{cores}core"] = row
    tmp = HOST_PARALLEL_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, HOST_PARALLEL_PATH)
    print(json.dumps({"metric": book["metric"], **row}))


def _fused_pipeline_mode() -> None:
    """`bench.py --fused-pipeline`: the streaming pipeline with DEVICE
    WINDOWS on, fused two-phase (program A at submit, window commit at
    drain — matcher/fused_windows.py driven by pipeline/scheduler.py)
    versus the classic bitmap split protocol (pipeline_fused: false),
    same chunk stream.  Records both rows plus the h2d bytes/batch
    witness into BENCH_fused_pipeline.json: the fused row must match or
    beat the classic rate AND show the dense [B, n_rules] re-upload
    (~16 MB per 65k batch at 1k rules) gone from the h2d counter."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "16384"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "256"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    rests = generate_lines(total, patterns, seed=47)
    lines = [
        f"{now:.6f} 10.7.{(i % 2048) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    rows = {}
    for label, fused in (("fused", True), ("classic", False)):
        cfg = config_from_yaml_text(rules_yaml)
        cfg.matcher_device_windows = True
        cfg.pipeline_fused = fused
        matcher = TpuMatcher(
            cfg, MockBanner(), StaticDecisionLists(cfg),
            RegexRateLimitStates(),
        )
        assert matcher._fw_pipeline is not None, (
            "fused matcher+windows pipeline did not engage"
        )
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        for c in chunks:  # warm pass: compile every bucket
            sched.submit(c)
        assert sched.flush(600), f"{label} warm pass did not drain"
        h2d0 = matcher.stats.h2d_bytes_total
        batches0 = matcher.stats.batches_total
        t0 = time.perf_counter()
        for c in chunks:
            sched.submit(c)
        assert sched.flush(600), f"{label} timed pass did not drain"
        elapsed = time.perf_counter() - t0
        snap = sched.snapshot()
        sched.stop()
        n_batches = max(1, matcher.stats.batches_total - batches0)
        rows[label] = {
            "mode": f"pipeline+device_windows ({label})",
            "backend": backend,
            "value": round(total / elapsed, 1),
            "unit": "lines/sec",
            "vs_baseline": round(total / elapsed / TARGET, 4),
            "elapsed_s": round(elapsed, 2),
            "n_rules": n_rules,
            "n_lines": total,
            "h2d_bytes_per_batch": round(
                (matcher.stats.h2d_bytes_total - h2d0) / n_batches, 1
            ),
            "pipelined_fused_chunks": matcher.pipelined_fused_chunks,
            "pipelined_fused_fallbacks": matcher.pipelined_fused_fallbacks,
            "pipeline_batches": snap.get("PipelineBatches"),
            "pipeline_shed_lines": snap.get("PipelineShedLines"),
        }

    book = {
        "metric": "log-lines/sec, streaming pipeline + device windows "
                  "(fused two-phase vs classic bitmap)",
        "fused": rows["fused"],
        "classic": rows["classic"],
        "fused_vs_classic_speedup": round(
            rows["fused"]["value"] / max(1.0, rows["classic"]["value"]), 3
        ),
        # the fusion-win witness: classic re-uploads the dense bitmap
        # (n_rules bytes/line); fused must not
        "dense_reupload_eliminated": (
            rows["fused"]["h2d_bytes_per_batch"]
            < 0.5 * rows["classic"]["h2d_bytes_per_batch"]
        ),
    }
    tmp = FUSED_STREAM_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, FUSED_STREAM_PATH)
    print(json.dumps(book))


SINGLE_KERNEL_PATH = os.path.join(_DIR, "BENCH_single_kernel.json")
SCENARIOS_PATH = os.path.join(_DIR, "BENCH_scenarios.json")


def _scenarios_mode() -> None:
    """`bench.py --scenarios`: one banked row per named attack shape
    (banjax_tpu/scenarios/) plus a seeded chaos-soak row.

    Every row carries lines/s, shed ratio, ban precision/recall against
    the generator's ground-truth oracle, per-SLO peak burn rates, and
    the structural-invariant verdicts — so every future perf PR is
    judged on hostile shapes, not just the happy-path feed.  The chaos
    row additionally records each injected failpoint episode (point,
    fired count, flight-recorder bundle).  Knobs: BENCH_SCEN_SCALE
    (default 1.0), BENCH_SCEN_SEED, BENCH_CPU=1 for the host backend.
    """
    import tempfile

    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from banjax_tpu.scenarios import (
        SHAPES,
        ChaosSchedule,
        ScenarioRunner,
        generate,
    )

    backend = jax.devices()[0].platform
    scale = float(os.environ.get("BENCH_SCEN_SCALE", "1.0"))
    seed = int(os.environ.get("BENCH_SCEN_SEED", "20260804"))

    rows = {}
    with tempfile.TemporaryDirectory(prefix="bench-scen-") as scen_tmp:
        for name in sorted(SHAPES):
            sc = generate(name, seed=seed, scale=scale)
            kwargs = {}
            if name == "log_rotation":
                # the rotation shape runs through a REAL file + tailer so
                # the banked row exercises the reopen-by-inode path
                kwargs = {
                    "via_tailer": True,
                    "tmp_dir": os.path.join(scen_tmp, name),
                }
                os.makedirs(kwargs["tmp_dir"], exist_ok=True)
            rep = ScenarioRunner(sc, **kwargs).run()
            rows[name] = rep.row()
            print(json.dumps({
                "scenario": name,
                "lines_per_sec": rep.lines_per_sec,
                "shed_ratio": rep.shed_ratio,
                "precision": rep.precision,
                "recall": rep.recall,
                "invariants_ok": rep.ok(),
            }), flush=True)

    # the seeded chaos soak: failpoint episodes over the rotating-proxy
    # worst case, flight recorder armed — banked with per-episode
    # evidence (this is the row the breaker/shed defaults derive from)
    chaos_rows = {}
    with tempfile.TemporaryDirectory() as fr_dir:
        for name in ("flash_crowd", "rotating_proxies"):
            sc = generate(name, seed=seed + 1, scale=scale)
            chaos = ChaosSchedule(
                seed=seed + 1, n_events=len(sc.events), episodes=5
            )
            rep = ScenarioRunner(
                sc, chaos=chaos,
                flightrec_dir=os.path.join(fr_dir, name),
            ).run()
            chaos_rows[name] = rep.row()

    # derived defaults (PERF.md round 13): breaker window from the
    # observed episode cadence, latency budget from the clean-shape
    # device p99 discipline (3x p99, floor 50 ms — the PR 2 rule, now
    # fed by hostile-shape data instead of a guess)
    burn_peaks = [
        max(r["slo_burn_peak"].values() or [0.0])
        for r in rows.values()
    ]
    book = {
        "metric": "scenario harness: per-shape rows + seeded chaos soak",
        "backend": backend,
        "seed": seed,
        "scale": scale,
        "measured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "scenarios": rows,
        "chaos": chaos_rows,
        "summary": {
            "shapes": len(rows),
            "all_invariants_ok": all(
                all(r["invariants"].values())
                for r in list(rows.values()) + list(chaos_rows.values())
            ),
            "clean_precision_min": min(
                r["precision"] for r in rows.values()
            ),
            "clean_recall_min": min(r["recall"] for r in rows.values()),
            "benign_slo_breached": any(
                rows["benign"]["slo_breached"].values()
            ),
            "max_clean_burn_peak": max(burn_peaks) if burn_peaks else 0.0,
            "chaos_episodes": sum(
                len(r["episodes"]) for r in chaos_rows.values()
            ),
            "chaos_bundles": sum(
                sum(1 for ep in r["episodes"] if ep["bundle"])
                for r in chaos_rows.values()
            ),
        },
    }
    tmp = SCENARIOS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, SCENARIOS_PATH)
    print(json.dumps({"metric": book["metric"], **book["summary"]}))


MEGA_STATE_PATH = os.path.join(_DIR, "BENCH_mega_state.json")


def _mega_state_mode() -> None:
    """`bench.py --mega-state`: the mega-state tiering A/B.

    One streamed pass of the 10M-distinct rotation per arm (admission
    off, then on — same generator args, so byte-identical streams),
    slot capacity pinned at 65536 (the ISSUE 14 worst-case shape) so
    the OFF arm actually pays the all-distinct slot churn the gate
    exists to remove.  Both arms run the warm tier and a sketch wide
    enough that the refused-fold mass (one row per distinct IP) keeps
    conservative estimates under the derived admission threshold —
    width is a knob so the banked row records the sizing that held.
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.scenarios import oracle as oracle_mod
    from banjax_tpu.scenarios.runtime import RecordingBanner
    from banjax_tpu.scenarios.shapes import (
        RULES_YAML,
        RUN_NOW,
        mega_offenders,
        mega_rotating_proxies_stream,
    )

    backend = jax.devices()[0].platform
    n_distinct = int(os.environ.get("BENCH_MEGA_DISTINCT", "10000000"))
    chunk = int(os.environ.get("BENCH_MEGA_CHUNK", "16384"))
    seed = int(os.environ.get("BENCH_MEGA_SEED", "20260804"))
    capacity = int(os.environ.get("BENCH_MEGA_CAPACITY", "65536"))
    sketch_width = int(
        os.environ.get("BENCH_MEGA_SKETCH_WIDTH", str(1 << 22))
    )

    def build(admission: bool):
        cfg = config_from_yaml_text(RULES_YAML)
        cfg.matcher = "tpu"
        cfg.matcher_device_windows = True
        cfg.matcher_batch_lines = chunk
        cfg.matcher_window_capacity = capacity
        cfg.traffic_sketch_enabled = True
        cfg.traffic_sketch_width = sketch_width
        cfg.slot_admission_enabled = admission
        cfg.warm_tier_enabled = True
        banner = RecordingBanner()
        matcher = TpuMatcher(
            cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates()
        )
        return cfg, matcher, banner

    # the oracle: offenders only — the mega noise is rule-neutral by
    # construction, so per-(ip, rule) fixed windows make the full
    # stream's expected multiset equal the offender sub-stream's
    oracle_cfg = config_from_yaml_text(RULES_YAML)
    oracle_bans = oracle_mod.expected_bans(
        mega_offenders(seed), oracle_cfg
    )

    rows = {}
    for arm in ("admission_off", "admission_on"):
        admission = arm == "admission_on"
        cfg, matcher, banner = build(admission)
        n_lines = 0
        t0 = time.perf_counter()
        for lines in mega_rotating_proxies_stream(
            seed, n_distinct, chunk=chunk
        ):
            matcher.consume_lines(lines, now_unix=RUN_NOW)
            n_lines += len(lines)
        elapsed = time.perf_counter() - t0
        dw = matcher.device_windows
        precision, recall, _ = oracle_mod.precision_recall(
            banner.regex_ban_logs, oracle_bans
        )
        rows[arm] = {
            "lines": n_lines,
            "distinct_ips": n_distinct,
            "elapsed_s": round(elapsed, 3),
            "lines_per_sec": round(n_lines / elapsed, 1),
            "engine_bans": len(banner.regex_ban_logs),
            "oracle_bans": len(oracle_bans),
            "precision": precision,
            "recall": recall,
            "slot_refusals": dw.slot_refusals,
            "sketch_admissions": dw.sketch_admissions,
            "sketch_admission_fp_rate": round(
                dw.sketch_admission_fp_rate, 6
            ),
            "slot_occupancy": dw.occupancy,
            "slot_capacity": capacity,
            "warm_spills": dw.warm_spills,
            "warm_refills": dw.warm_refills,
            "warm_dropped": dw.warm_dropped,
            "warm_occupancy": dw.warm_occupancy,
        }
        matcher.close()
        print(json.dumps({"arm": arm, **rows[arm]}), flush=True)

    on, off = rows["admission_on"], rows["admission_off"]
    book = {
        "metric": (
            "mega-state tiering: sketch-gated slot admission A/B at "
            f"{n_distinct} distinct IPs"
        ),
        "backend": backend,
        "seed": seed,
        "chunk_lines": chunk,
        "sketch_width": sketch_width,
        "sketch_depth": int(oracle_cfg.traffic_sketch_depth),
        "admission_min_estimate_derived": (
            min(
                r.hits_per_interval
                for r in oracle_cfg.regexes_with_rates
            )
            + 1
        ),
        "measured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "rows": rows,
        "summary": {
            "speedup_on_vs_off": round(
                on["lines_per_sec"] / off["lines_per_sec"], 4
            ),
            "acceptance_on_not_slower": (
                on["lines_per_sec"] >= off["lines_per_sec"]
            ),
            "acceptance_ban_parity": all(
                r[k] == 1.0
                for r in (on, off)
                for k in ("precision", "recall")
            ),
        },
    }
    tmp = MEGA_STATE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, MEGA_STATE_PATH)
    print(json.dumps({"metric": book["metric"], **book["summary"]}))


FABRIC_PATH = os.path.join(_DIR, "BENCH_fabric.json")


def _forward_micro(transport: str, n_lines: int) -> dict:
    """Forwarding-plane micro-row: one shard's transport to one remote
    peer (FabricNode on a loopback socket / shm ring), fed ONE LINE at
    a time.  The json arm is PR 11's wire verbatim — a synchronous
    JSON request/response per line, so every line pays a full RTT plus
    two JSON codecs.  The v2/shm arms push the same per-line stream
    through the windowed LinePipe: submissions coalesce into binary
    batched frames and up to 8 ride unacked, so the RTT amortizes
    across thousands of lines.  Matching layers on both sides (no
    router, no matcher) keeps this a measurement of the wire alone.
    Best-of-3: on the 1-core bench box a single scheduler hiccup inside
    the timed loop can swing an arm 30%+, so each arm runs three times
    and banks its fastest trial (per-trial numbers are kept for the
    variance-curious)."""
    import threading

    from banjax_tpu.fabric import wire as fwire
    from banjax_tpu.fabric.node import FabricNode
    from banjax_tpu.fabric.peer import LinePipe, PeerClient
    from banjax_tpu.fabric.stats import FabricStats

    lines = [
        f"{1000 + i * 0.001:.3f} 10.{(i >> 8) & 255}.{i & 255}.{i % 251} "
        f"GET fwd.example GET /a HTTP/1.1 x -"
        for i in range(n_lines)
    ]

    def _once():
        got = {"lines": 0, "frames": 0}
        lock = threading.Lock()

        def h_lines(payload):
            batch = payload.get("lines", [])
            with lock:
                got["lines"] += len(batch)
                got["frames"] += 1
            ack = {"n": len(batch)}
            if "seq" in payload:
                ack["seq"] = payload["seq"]
            return fwire.T_ACK, ack

        def h_lines_v2(fr):
            with lock:
                got["lines"] += len(fr.lines)
                got["frames"] += 1
            return fwire.T_ACK, {"seq": fr.seq, "n": len(fr.lines)}

        node = FabricNode("127.0.0.1", 0, handlers={
            fwire.T_LINES: h_lines, fwire.T_LINES_V2: h_lines_v2,
        }).start()
        stats = FabricStats()
        if transport == "json":
            client = PeerClient("b", "127.0.0.1", node.port)
            t0 = time.perf_counter()
            for ln in lines:
                client.request(fwire.T_LINES, {"lines": [ln]})
            dt = time.perf_counter() - t0
            client.close()
        else:
            pipe = LinePipe(
                "b", "127.0.0.1", node.port, node_id="a",
                shm=(transport == "shm"), stats=stats,
            )
            t0 = time.perf_counter()
            for ln in lines:
                pipe.submit([ln])
            assert pipe.flush(300.0), f"{transport}: flush did not drain"
            dt = time.perf_counter() - t0
            pipe.close()
        node.stop()
        assert got["lines"] == n_lines, (
            f"{transport}: {got['lines']} of {n_lines} lines crossed"
        )
        peek = stats.peek()
        return {
            "transport": transport,
            "lines": n_lines,
            "seconds": round(dt, 3),
            "lines_per_sec": round(n_lines / dt, 1),
            "frames": got["frames"],
            "lines_per_frame": round(n_lines / max(1, got["frames"]), 1),
            "frame_bytes_sent": peek.get("FabricFrameBytes", 0),
        }

    trials = [_once() for _ in range(3)]
    best = max(trials, key=lambda r: r["lines_per_sec"])
    best["trial_lines_per_sec"] = [r["lines_per_sec"] for r in trials]
    return best


def _fabric_mode() -> None:
    """`bench.py --fabric`: the multi-host decision fabric scaling run.

    One dryrun episode per shard count — N=1 (no kill: the single-shard
    baseline, every line local), N=2 and N=4 (one shard SIGKILLed
    mid-flood, consistent-hash takeover) — over the same seeded scenario
    stream, banking lines/s per N plus the takeover-window shed ratio
    (lines shed between the kill and the successors finishing the
    journal replay, over lines fed in that window).  Every row must hold
    recall 1.0 vs the oracle; the kill rows must also prove the takeover
    happened and duplicates were suppressed.

    Churn rows (`churn_n2`/`churn_n4`) run the gossip-membership episode
    on top: SIGKILL with the feed paused (detection is gossip's alone —
    the kill→confirmed-dead seconds per survivor are banked as the
    detection distribution), an automatic join with snapshot sync and no
    fleet restart, a slow-node suspect/refute cycle, and a graceful
    leave with zero shed / zero replay.  Knobs:
    Forward-path rows (ISSUE 18): `forward_path` pits the wire v2
    windowed transport (tcp + shm-ring arms) against the PR 11
    per-line sync-JSON wire on a pure forwarding workload, in-process
    (the v2 arm is gated at >= 10x the json arm); `forward_path_e2e`
    repeats the shape through real worker processes at chunk
    granularity, where the synchronous driver RTT — not the wire —
    bounds every arm (banked for honesty, see PERF.md round 17).
    Knobs: BENCH_FABRIC_{SHAPE,SEED,SCALE,NS,CHURN_NS,FWD_LINES},
    BENCH_CPU=1 (workers always pin the CPU backend themselves)."""
    from banjax_tpu.fabric.harness import run_fabric, run_forward_path

    shape = os.environ.get("BENCH_FABRIC_SHAPE", "flash_crowd")
    seed = int(os.environ.get("BENCH_FABRIC_SEED", "20260804"))
    scale = float(os.environ.get("BENCH_FABRIC_SCALE", "1.0"))
    ns = [
        int(n)
        for n in os.environ.get("BENCH_FABRIC_NS", "1,2,4").split(",")
    ]
    churn_ns = [
        int(n)
        for n in os.environ.get("BENCH_FABRIC_CHURN_NS", "2,4").split(",")
        if n.strip()
    ]

    rows = {}
    for n in ns:
        kill = n > 1
        report = run_fabric(
            n_workers=n, shape=shape, seed=seed, scale=scale, kill=kill,
        )
        bad = [k for k, ok in report["invariants"].items() if not ok]
        assert not bad, f"fabric invariants failed at n={n}: {bad}"
        takeover = report.get("takeover") or {}
        rows[f"n{n}"] = {
            "n_workers": n,
            "transport": report["transport"],
            "killed": report["killed"],
            "lines": report["n_lines"],
            "feed_s": report["feed_s"],
            "lines_per_sec": report["lines_per_sec"],
            "engine_bans": report["engine_bans"],
            "oracle_bans": report["oracle_bans"],
            "precision": report["precision"],
            "recall": report["recall"],
            "duplicates_suppressed": report["duplicates_suppressed"],
            "takeover_window_s": takeover.get("window_s"),
            "takeover_shed_ratio": takeover.get("shed_ratio_in_window"),
            "takeover_replayed_lines": (
                takeover.get("driver_replayed_lines")
            ),
        }
        if kill:
            # the n2 duplicate-ban regression gate: takeover replay
            # must never mint a ban the oracle doesn't have
            assert report["precision"] == 1.0, (
                f"n={n}: precision {report['precision']} != 1.0 "
                f"({report['engine_bans']} vs {report['oracle_bans']})"
            )
        print(json.dumps({"arm": f"n{n}", **rows[f"n{n}"]}), flush=True)

    for n in churn_ns:
        report = run_fabric(
            n_workers=n, shape=shape, seed=seed, scale=scale, churn=True,
        )
        bad = [k for k, ok in report["invariants"].items() if not ok]
        assert not bad, f"fabric churn invariants failed at n={n}: {bad}"
        takeover = report.get("takeover") or {}
        detect = takeover.get("detect_s") or {}
        rows[f"churn_n{n}"] = {
            "n_workers": n,
            "mode": "membership_churn",
            "killed": report["killed"],
            "recall": report["recall"],
            "precision": report["precision"],
            "detection_s": detect,
            "max_detection_s": takeover.get("max_detect_s"),
            "suspect_timeout_s": takeover.get("suspect_timeout_s"),
            "gossip_interval_s": takeover.get("gossip_interval_s"),
            "takeover_window_s": takeover.get("window_s"),
            "join_synced_decisions": report["join"]["synced_decisions"],
            "join_wave_exactly_once": (
                report["join"]["invariants"]["wave_exactly_once"]
            ),
            "refuted": report["suspect_refute"]["refuted_delta"],
            "leave_zero_shed": (
                report["leave"]["invariants"]["zero_shed"]
            ),
            "leave_zero_replay": (
                report["leave"]["invariants"]["zero_replay"]
            ),
            "leave_drain_ms": report["leave"]["drain_ms"],
        }
        assert report["precision"] == 1.0, (
            f"churn n={n}: precision {report['precision']} != 1.0"
        )
        print(json.dumps(
            {"arm": f"churn_n{n}", **rows[f"churn_n{n}"]}
        ), flush=True)

    # forwarding-plane micro: per-line submission, 100% remote lines
    fwd_lines = int(os.environ.get("BENCH_FABRIC_FWD_LINES", "50000"))
    fwd = {t: _forward_micro(t, fwd_lines) for t in ("json", "v2", "shm")}
    speedup = round(
        fwd["v2"]["lines_per_sec"] / fwd["json"]["lines_per_sec"], 1
    )
    rows["forward_path"] = {
        "mode": "in_process_per_line",
        "arms": fwd,
        "v2_over_json": speedup,
        "shm_over_json": round(
            fwd["shm"]["lines_per_sec"] / fwd["json"]["lines_per_sec"], 1
        ),
    }
    assert speedup >= 10.0, (
        f"forward_path: v2 {fwd['v2']['lines_per_sec']} l/s is only "
        f"{speedup}x the per-line JSON wire "
        f"({fwd['json']['lines_per_sec']} l/s); gate is 10x"
    )
    print(json.dumps({"arm": "forward_path", **rows["forward_path"]}),
          flush=True)

    # same shape end-to-end through real worker processes, chunked:
    # banked so nobody mistakes the micro for an e2e claim — at chunk
    # granularity the sync driver's RTT bounds all three arms alike
    e2e = {}
    for t in ("json", "v2", "shm"):
        r = run_forward_path(transport=t)
        assert all(r["invariants"].values()), f"forward e2e {t}: {r}"
        e2e[t] = {
            "lines_per_sec": r["lines_per_sec"],
            "n_lines": r["n_lines"],
            "chunk_lines": r["chunk_lines"],
            "peer_transport": r["peer_transport"],
            "frames_sent": r["frames_sent"],
        }
    rows["forward_path_e2e"] = {
        "mode": "worker_processes_chunked",
        "note": (
            "driver-RTT-bound: the synchronous chunk feed, not the "
            "wire, is the bottleneck at this granularity"
        ),
        "arms": e2e,
    }
    print(json.dumps({"arm": "forward_path_e2e", **rows["forward_path_e2e"]}),
          flush=True)

    kill_rows = [r for r in rows.values() if r.get("killed")]
    book = {
        "metric": (
            "decision fabric: lines/s vs shard count with one shard "
            "SIGKILLed mid-flood (N>1), recall gated at 1.0"
        ),
        "shape": shape,
        "seed": seed,
        "scale": scale,
        "measured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "rows": rows,
        "summary": {
            "recall_one_all_rows": all(
                r["recall"] == 1.0 for r in rows.values()
                if "recall" in r
            ),
            "forward_path_v2_over_json": rows["forward_path"][
                "v2_over_json"
            ],
            "max_takeover_shed_ratio": max(
                (r.get("takeover_shed_ratio") or 0.0) for r in kill_rows
            ) if kill_rows else None,
            "max_takeover_window_s": max(
                (r.get("takeover_window_s") or 0.0) for r in kill_rows
            ) if kill_rows else None,
            "max_gossip_detection_s": max(
                (r.get("max_detection_s") or 0.0) for r in kill_rows
            ) if kill_rows else None,
        },
    }
    tmp = FABRIC_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, FABRIC_PATH)
    print(json.dumps({"metric": book["metric"], **book["summary"]}))


FLEET_OBS_PATH = os.path.join(_DIR, "BENCH_fleet_obs.json")


def _fleet_obs_witness(tmp_dir: str) -> dict:
    """Non-vacuity witness for the fleet-obs rows: two real workers with
    trace propagation armed, a probe flood tailed at w0 whose IP hashes
    to w1, and the resulting ban's provenance on w1 joined back to w0's
    `fabric.route` admission span by origin trace id.  Returns the
    joined evidence; raises if the join never happens — an idle
    observability plane must not bank a vacuous "no overhead"."""
    from banjax_tpu.fabric import wire as fwire
    from banjax_tpu.fabric.harness import _fake_broker, _spawn
    from banjax_tpu.fabric.hashring import ConsistentHashRing
    from banjax_tpu.scenarios.shapes import T0

    ring = ConsistentHashRing(("w0", "w1"), vnodes=64)
    i = 0
    while True:
        ip = f"10.{(i >> 8) & 255}.{i & 255}.7"
        if ring.owner(ip) == "w1":
            break
        i += 1

    broker = _fake_broker()
    broker.start()
    workers = {}
    try:
        for wid in ("w0", "w1"):
            workers[wid] = _spawn(
                wid, broker.port, os.path.join(tmp_dir, f"{wid}.err"),
                extra_args=("--trace-propagation", "1"),
            )
        for w in workers.values():
            w.read_ready(420.0)
        hello = {
            "peers": {
                w.wid: ["127.0.0.1", w.port] for w in workers.values()
            },
            "vnodes": 64, "send_timeout_ms": 2000.0, "grace_ms": 200.0,
            "inflight_frames": 8, "wire_v2": True, "shm": False,
            "trace_propagation": True,
        }
        for w in workers.values():
            w.request(fwire.T_HELLO, hello)
        lines = [
            f"{T0 + j * 0.1:.6f} {ip} GET example.com GET "
            "/wp-login.php HTTP/1.1 scanner -"
            for j in range(20)
        ]
        workers["w0"].request(fwire.T_LINES, {"lines": lines, "route": True})
        for w in workers.values():
            w.request(fwire.T_FLUSH, {"timeout": 600})
        explain = {}
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            explain = workers["w1"].request(fwire.T_EXPLAIN, {"ip": ip})
            if explain.get("records"):
                break
            time.sleep(0.25)
        recs = [
            r for r in explain.get("records", ())
            if r.get("origin_node") == "w0"
        ]
        assert recs, f"no forwarded-line ban recorded for {ip}: {explain}"
        origin_tid = recs[0]["origin_trace_id"]
        assert origin_tid > 0, recs[0]
        cap = workers["w0"].request(
            fwire.T_FLIGHTREC, {"incident": "bench-witness", "from": "b"}
        )
        route_tids = {
            e["args"]["trace_id"]
            for e in json.loads(cap["files"]["trace.json"])["traceEvents"]
            if e["name"] == "fabric.route"
        }
        assert origin_tid in route_tids, (origin_tid, route_tids)
        return {
            "banned_ip": ip,
            "origin_node": recs[0]["origin_node"],
            "origin_trace_id": origin_tid,
            "explain_joins_origin_trace": True,
            "decision": recs[0].get("decision"),
        }
    finally:
        for w in workers.values():
            try:
                w.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                w.proc.kill()
        broker.stop()


def _fleet_obs_mode() -> None:
    """`bench.py --fleet-obs`: fleet observability overhead on the N=2
    fabric feed — the same off → on → off bracketing protocol as the
    other obs A/Bs, where "on" arms origin trace propagation on every
    forwarded frame plus the worker-side fleet surfaces
    (T_EXPLAIN / T_FLIGHTREC / T_STATS metrics).  Decisions must not
    change: the on-arm ban log is byte-compared against the off arm.
    The banked witness row proves the plane was live — a forwarded-line
    ban on w1 whose /decisions/explain provenance joins the origin
    trace id allocated at w0's admission.  Banked into
    BENCH_fleet_obs.json.  Knobs: BENCH_FABRIC_{SHAPE,SEED,SCALE}."""
    import tempfile

    from banjax_tpu.fabric.harness import run_fabric

    shape = os.environ.get("BENCH_FABRIC_SHAPE", "flash_crowd")
    seed = int(os.environ.get("BENCH_FABRIC_SEED", "20260804"))
    scale = float(os.environ.get("BENCH_FABRIC_SCALE", "1.0"))

    def run_arm(fleet_obs: bool) -> dict:
        report = run_fabric(
            n_workers=2, shape=shape, seed=seed, scale=scale,
            kill=False, fleet_obs=fleet_obs,
        )
        bad = [k for k, ok in report["invariants"].items() if not ok]
        assert not bad, f"fleet-obs arm invariants failed: {bad}"
        return report

    def row(report: dict, fleet_obs: bool) -> dict:
        return {
            "fleet_obs": fleet_obs,
            "lines_per_sec": report["lines_per_sec"],
            "lines": report["n_lines"],
            "feed_s": report["feed_s"],
            "engine_bans": report["engine_bans"],
            "oracle_bans": report["oracle_bans"],
            "precision": report["precision"],
            "recall": report["recall"],
        }

    def ban_log_bytes(report: dict) -> bytes:
        return ("\n".join(report["ban_log"]) + "\n").encode()

    off_a_rep = run_arm(False)
    on_rep = run_arm(True)
    off_b_rep = run_arm(False)
    assert ban_log_bytes(on_rep) == ban_log_bytes(off_a_rep), (
        "fleet-obs changed the ban log"
    )
    off_a, on, off_b = (
        row(off_a_rep, False), row(on_rep, True), row(off_b_rep, False)
    )
    off = max(off_a, off_b, key=lambda r: r["lines_per_sec"])
    noise_band_pct = round(
        abs(off_a["lines_per_sec"] - off_b["lines_per_sec"])
        / max(off_a["lines_per_sec"], off_b["lines_per_sec"]) * 100.0, 2
    )
    overhead_pct = round(
        (off["lines_per_sec"] - on["lines_per_sec"])
        / off["lines_per_sec"] * 100.0, 2
    )

    with tempfile.TemporaryDirectory() as td:
        witness = _fleet_obs_witness(td)

    book = {
        "metric": (
            "N=2 fabric feed lines/s, fleet observability off vs on "
            "(origin trace propagation + fleet surfaces)"
        ),
        "shape": shape,
        "seed": seed,
        "scale": scale,
        "measured_at": time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime()
        ),
        "off": off,
        "on": on,
        "off_runs": [off_a["lines_per_sec"], off_b["lines_per_sec"]],
        "on_vs_off_overhead_pct": overhead_pct,
        "off_run_noise_band_pct": noise_band_pct,
        "on_within_off_noise_band": bool(
            overhead_pct <= max(noise_band_pct, 1.0)
        ),
        "ban_log_byte_identical": True,
        "witness": witness,
    }
    tmp = FLEET_OBS_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, FLEET_OBS_PATH)
    print(json.dumps({
        "metric": book["metric"],
        "off_lines_per_sec": off["lines_per_sec"],
        "on_lines_per_sec": on["lines_per_sec"],
        "on_vs_off_overhead_pct": overhead_pct,
        "off_run_noise_band_pct": noise_band_pct,
        "on_within_off_noise_band": book["on_within_off_noise_band"],
        "witness": witness,
    }))


CHALLENGE_PATH = os.path.join(_DIR, "BENCH_challenge.json")


def _challenge_mode() -> None:
    """`bench.py --challenge`: the challenge plane A/B + storm row.

    Throughput: one pre-solved cookie population (every cookie passes
    the wire stage, so the arms diverge only at the PoW zero-bit count),
    verified once with the pure-CPU reference (`verify_sha_inv`,
    device=None) and once through the device-batched path (wire stage
    inline + `DeviceVerifier.verify_batch` kernel dispatches).  Accepts
    must agree lane for lane — the A/B is about speed, never decisions.

    Storm: >= 1M distinct cookieless challengers (each fails once —
    below any threshold, so the oracle bans NONE of them) interleaved
    with scripted repeat offenders who fail past the threshold inside
    one rate window.  Everything flows through the REAL
    send_or_validate_sha_challenge stage with the bounded failure state
    from the deploy config, so the banked row witnesses the ISSUE 17
    acceptance: entries <= challenge_failure_state_max under 1M+
    distinct clients AND ban precision/recall 1.0 vs the scripted
    oracle (bounded-state drops may delay nothing here — offender
    evidence rides the spill tier).
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from banjax_tpu.challenge.failures import make_failed_challenge_states
    from banjax_tpu.challenge.verifier import DeviceVerifier, verify_sha_inv
    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.crypto.challenge import (
        new_challenge_cookie_at,
        parse_cookie,
        solve_challenge_for_testing,
        validate_expiration_and_hmac,
    )
    from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
    from banjax_tpu.decisions.model import FailAction
    from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.httpapi.decision_chain import (
        ChainState,
        RequestInfo,
        send_or_validate_sha_challenge,
    )
    from banjax_tpu.scenarios.runtime import RecordingBanner

    backend = jax.devices()[0].platform
    n_cookies = int(os.environ.get("BENCH_CHAL_COOKIES", "2048"))
    zero_bits = int(os.environ.get("BENCH_CHAL_ZERO_BITS", "8"))
    batch_max = int(os.environ.get("BENCH_CHAL_BATCH", "256"))
    n_distinct = int(os.environ.get("BENCH_CHAL_DISTINCT", "1000000"))
    n_offenders = int(os.environ.get("BENCH_CHAL_OFFENDERS", "64"))
    state_max = int(os.environ.get("BENCH_CHAL_STATE_MAX", "65536"))
    seed = int(os.environ.get("BENCH_CHAL_SEED", "20260804"))
    secret = f"bench-challenge-{seed}"

    # ---- throughput A/B: one solved population, two PoW paths ----
    now = int(time.time())
    cookies = []
    t0 = time.perf_counter()
    for k in range(n_cookies):
        ip = f"198.51.{(k >> 8) & 0xFF}.{k & 0xFF}"
        cookies.append((ip, solve_challenge_for_testing(
            new_challenge_cookie_at(secret, now + 3600, ip), zero_bits
        )))
    solve_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    cpu_accepts = 0
    for ip, cookie in cookies:
        verify_sha_inv(secret, cookie, now, ip, zero_bits, device=None)
        cpu_accepts += 1
    cpu_s = time.perf_counter() - t0

    from banjax_tpu.matcher.kernels.pow_verify import _default_interpret

    device = DeviceVerifier(batch_max=batch_max)
    assert device.available(), device.counters()["disabled_reason"]
    interpret = _default_interpret()
    t0 = time.perf_counter()
    payloads = []
    for ip, cookie in cookies:
        hmac_bytes, solution, expiry = parse_cookie(cookie)
        validate_expiration_and_hmac(secret, expiry, now, hmac_bytes, ip)
        payloads.append(hmac_bytes + solution)
    bits = device.verify_batch(payloads)
    device_s = time.perf_counter() - t0
    device_accepts = sum(1 for b in bits if b >= zero_bits)
    assert device_accepts == cpu_accepts == n_cookies, (
        device_accepts, cpu_accepts
    )
    dev_counters = device.counters()
    assert dev_counters["faults"] == 0, dev_counters

    verify_rows = {
        "cpu": {
            "cookies": n_cookies,
            "elapsed_s": round(cpu_s, 4),
            "cookies_per_sec": round(n_cookies / cpu_s, 1),
            "accepts": cpu_accepts,
        },
        "device": {
            "cookies": n_cookies,
            "elapsed_s": round(device_s, 4),
            "cookies_per_sec": round(n_cookies / device_s, 1),
            "accepts": device_accepts,
            "batch_max": batch_max,
            "kernel_dispatches": dev_counters["dispatches"],
            "lanes_verified": dev_counters["lanes_verified"],
            # interpret-mode rows (cpu backend) measure the kernel
            # EMULATOR, not device silicon — not a speedup claim
            "kernel_interpret_mode": interpret,
        },
    }
    print(json.dumps({"section": "verify_ab", "solve_s": round(solve_s, 2),
                      **verify_rows}), flush=True)

    # ---- storm row: >= 1M distinct challengers, bounded state ----
    cfg = config_from_yaml_text(f"""
regexes_with_rates: []
too_many_failed_challenges_interval_seconds: 3600
too_many_failed_challenges_threshold: 3
sha_inv_cookie_ttl_seconds: 300
sha_inv_expected_zero_bits: {zero_bits}
hmac_secret: {secret}
disable_kafka: true
challenge_failure_state_max: {state_max}
""")
    threshold = cfg.too_many_failed_challenges_threshold
    banner = RecordingBanner()
    dyn = DynamicDecisionLists(start_sweeper=False)
    chain = ChainState(
        config=cfg,
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=dyn,
        protected_paths=PasswordProtectedPaths(cfg),
        failed_challenge_states=make_failed_challenge_states(cfg),
        banner=banner,
        challenge_verifier=None,  # cookieless storm never reaches PoW
    )
    offender_ips = [
        f"203.0.{k >> 8}.{k & 0xFF}" for k in range(n_offenders)
    ]
    # each offender fails threshold+1 times, round-robin through live
    # eviction churn.  An offender re-fails every n_offenders * stride
    # churners; keeping that gap under the LRU cap is the precision-
    # safety shape (a retrying bot re-touches its window entry before
    # cap-many distinct clients push it out), so the oracle comparison
    # stays exact while eviction pressure runs the whole time.
    offender_stream = offender_ips * (threshold + 1)
    stride = max(1, state_max // (2 * n_offenders))

    t0 = time.perf_counter()
    n_requests = 0
    oi = 0
    for i in range(n_distinct):
        req = RequestInfo(
            client_ip=f"10.{(i >> 16) & 0xFF}.{(i >> 8) & 0xFF}.{i & 0xFF}",
            requested_host="bench.example", requested_path="/",
            cookies={},
        )
        send_or_validate_sha_challenge(chain, req, FailAction.BLOCK)
        n_requests += 1
        if i % stride == stride - 1 and oi < len(offender_stream):
            req = RequestInfo(
                client_ip=offender_stream[oi], requested_host="bench.example",
                requested_path="/", cookies={},
            )
            send_or_validate_sha_challenge(chain, req, FailAction.BLOCK)
            n_requests += 1
            oi += 1
    while oi < len(offender_stream):  # drain any tail offender failures
        req = RequestInfo(
            client_ip=offender_stream[oi], requested_host="bench.example",
            requested_path="/", cookies={},
        )
        send_or_validate_sha_challenge(chain, req, FailAction.BLOCK)
        n_requests += 1
        oi += 1
    storm_s = time.perf_counter() - t0

    banned = {ip for ip, _ in banner.failed_challenge_ban_logs}
    oracle = set(offender_ips)
    precision = (len(banned & oracle) / len(banned)) if banned else 1.0
    recall = (len(banned & oracle) / len(oracle)) if oracle else 1.0
    state_counters = chain.failed_challenge_states.counters()
    storm_row = {
        "distinct_challengers": n_distinct + n_offenders,
        "requests": n_requests,
        "elapsed_s": round(storm_s, 3),
        "requests_per_sec": round(n_requests / storm_s, 1),
        "offenders": n_offenders,
        "banned": len(banned),
        "ban_precision": precision,
        "ban_recall": recall,
        "failure_state_entries": state_counters["entries"],
        "failure_state_max": state_max,
        "evictions_total": state_counters["evictions_total"],
        "spill_writes": state_counters["spill_writes"],
        "spill_refills": state_counters["spill_refills"],
        "gate_skips": state_counters["gate_skips"],
    }
    print(json.dumps({"section": "challenge_storm", **storm_row}),
          flush=True)

    book = {
        "metric": (
            "challenge plane: PoW verify CPU vs device A/B + "
            f"{n_distinct + n_offenders}-distinct challenger storm"
        ),
        "backend": backend,
        "seed": seed,
        "zero_bits": zero_bits,
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "rows": {"verify": verify_rows, "challenge_storm": storm_row},
        "summary": {
            "cpu_cookies_per_sec": verify_rows["cpu"]["cookies_per_sec"],
            "device_cookies_per_sec": (
                verify_rows["device"]["cookies_per_sec"]
            ),
            "speedup_device_vs_cpu": round(
                verify_rows["device"]["cookies_per_sec"]
                / verify_rows["cpu"]["cookies_per_sec"], 4
            ),
            "acceptance_accepts_identical": device_accepts == cpu_accepts,
            "acceptance_state_bounded": (
                storm_row["failure_state_entries"] <= state_max
            ),
            "acceptance_ban_parity": precision == 1.0 and recall == 1.0,
            "acceptance_distinct_1m": (
                storm_row["distinct_challengers"] >= 1_000_000
            ),
        },
    }
    tmp = CHALLENGE_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, CHALLENGE_PATH)
    print(json.dumps({"metric": book["metric"], **book["summary"]}))


SERVE_PATH = os.path.join(_DIR, "BENCH_serve.json")


def _serve_mode() -> None:
    """`bench.py --serve`: the compiled /auth_request serving path.

    Three sections banked into BENCH_serve.json:

      decision_stage — the per-request serving cost in process: the
      userspace nine-step chain (decision_for_nginx + the decision-log
      serialization + serialize_response, exactly what
      fastserve._auth_request runs) vs the compiled fast path
      (AuthFastPath.try_serve: one shm decision-table probe, one
      session HMAC, a template splice) over the identical
      already-decided workload.  The ISSUE 19 acceptance gate lives
      here: fast path >= 5x the chain's requests/sec.

      witness — decision identity over a mixed allow / block /
      challenge / expiring workload, including live expiry-boundary
      crossings: every fast-path response must byte-equal the chain's
      for the same request (minted session cookies and challenge
      payloads normalized — both sides draw fresh randomness);
      `mismatches` must be 0.

      http_capacity — the end-to-end number: the REAL standalone server
      on 127.0.0.1:8081 (BanjaxApp, fastserve layout) driven by a
      concurrent raw-socket keepalive client over the same workload
      mix, chain-only config vs fast-path config — rps, per-request
      p50/p99, and the per-tier hit / per-reason miss counters from
      httpapi/serve_stats on the fast-path arm.
    """
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import asyncio
    import re
    import shutil
    import tempfile
    import types

    from banjax_tpu.config.holder import _PAGES_DIR
    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.crypto.session import new_session_cookie
    from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
    from banjax_tpu.decisions.model import Decision
    from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
    from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.httpapi.decision_chain import (
        ChainState,
        DecisionListResult,
        RequestInfo,
        decision_for_nginx,
    )
    from banjax_tpu.httpapi.fastpath import AuthFastPath
    from banjax_tpu.httpapi.fastserve import serialize_response
    from banjax_tpu.httpapi.serve_stats import get_stats
    from banjax_tpu.native.decisiontable import available, create_decision_table
    from banjax_tpu.scenarios.runtime import RecordingBanner
    from banjax_tpu.utils import go_query_escape, go_query_unescape

    seed = int(os.environ.get("BENCH_SERVE_SEED", "20260807"))
    iters = int(os.environ.get("BENCH_SERVE_ITERS", "20000"))
    witness_n = int(os.environ.get("BENCH_SERVE_WITNESS", "400"))
    n_per_conn = int(os.environ.get("BENCH_SERVE_NPC", "300"))
    conc = int(os.environ.get("BENCH_SERVE_CONC", "16"))
    table_cap = int(os.environ.get("BENCH_SERVE_TABLE_CAP", "65536"))
    rng = random.Random(seed)
    session_secret = "bench-serve-session-secret"

    cfg = config_from_yaml_text(f"""
config_version: bench-serve-1
global_decision_lists:
  allow:
    - 20.20.20.20
iptables_ban_seconds: 10
kafka_brokers: [localhost:9092]
server_log_file: /tmp/banjax-bench-serve.log
expiring_decision_ttl_seconds: 300
too_many_failed_challenges_interval_seconds: 60
too_many_failed_challenges_threshold: 1000000
password_cookie_ttl_seconds: 14400
sha_inv_cookie_ttl_seconds: 14400
sha_inv_expected_zero_bits: 10
hmac_secret: bench-serve-hmac
session_cookie_hmac_secret: {session_secret}
session_cookie_ttl_seconds: 3600
disable_kafka: true
""")
    cfg.challenger_bytes = (
        _PAGES_DIR / "sha-inverse-challenge.html").read_bytes()

    dyn = DynamicDecisionLists(start_sweeper=False)
    table = create_decision_table(capacity=table_cap)
    dyn.set_mirror(table)

    class _Holder:
        def get(self):
            return cfg

    deps = types.SimpleNamespace(
        config_holder=_Holder(),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=dyn,
        protected_paths=PasswordProtectedPaths(cfg),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=RecordingBanner(),
        challenge_verifier=None,
        decision_table=table,
    )
    fp = AuthFastPath(deps)
    chain_state = ChainState(
        config=cfg, static_lists=deps.static_lists, dynamic_lists=dyn,
        protected_paths=deps.protected_paths,
        failed_challenge_states=deps.failed_challenge_states,
        banner=deps.banner, challenge_verifier=None,
    )

    class _Req:
        __slots__ = ("headers", "method", "keep_alive")

        def __init__(self, headers, method="GET"):
            self.headers = headers
            self.method = method
            self.keep_alive = True

        def header(self, name):
            return self.headers.get(name, "")

    def chain_serve(req):
        """What fastserve._auth_request runs for /auth_request: cookie
        parse, RequestInfo, the nine-step chain, the decision-log
        serialization, wire serialization."""
        cookies = {}
        raw = req.headers.get("cookie", "")
        if raw:
            for part in raw.split(";"):
                name, eq, value = part.strip().partition("=")
                if not eq:
                    continue
                try:
                    cookies[name] = go_query_unescape(value)
                except ValueError:
                    continue
        info = RequestInfo(
            client_ip=req.headers.get("x-client-ip", ""),
            requested_host=req.headers.get("x-requested-host", ""),
            requested_path=req.headers.get("x-requested-path", ""),
            client_user_agent=req.headers.get("x-client-user-agent", ""),
            method=req.method,
            cookies=cookies,
        )
        resp, result = decision_for_nginx(chain_state, info)
        if result.decision_list_result != DecisionListResult.NO_MENTION:
            result.to_json()  # the decision-log line fastserve emits
        return serialize_response(resp, req.keep_alive,
                                  head_only=req.method == "HEAD")

    def _hdrs(ip, host="bench.example.net", **extra):
        h = {
            "x-client-ip": ip, "x-requested-host": host,
            "x-requested-path": "/", "x-client-user-agent": "mozilla",
        }
        h.update(extra)
        return h

    def _clean_cookie(ip, secret=session_secret, ttl=3600):
        # base64 cookies can carry '+', which QueryUnescape turns into
        # a space on the echo path (both layouts share the mangle);
        # draw until clean so echoed bytes are deterministic
        while True:
            c = new_session_cookie(secret, ttl, ip)
            if "+" not in c and "%" not in c:
                return c

    # ---- seed the decided population (the mirror fills the table) ----
    now = time.time()
    allow_ips = [f"10.1.{k >> 8}.{k & 0xFF}" for k in range(256)]
    block_ips = [f"10.2.0.{k}" for k in range(64)]
    for ip in allow_ips:
        dyn.update(ip, now + 3600, Decision.ALLOW, False, "bench")
    for ip in block_ips:
        dyn.update(ip, now + 3600, Decision.NGINX_BLOCK, False, "bench")

    # ---- decision_stage A/B: identical ring through both arms ----
    ring = []
    for k in range(512):
        ip = allow_ips[k % 256] if k % 4 else block_ips[(k // 4) % 64]
        ring.append(_Req(_hdrs(
            ip, cookie=f"deflect_session={go_query_escape(_clean_cookie(ip))}"
        )))
    for req in ring[:64]:  # warm both arms
        assert fp.try_serve(req) is not None, "fast path must hit the ring"
        chain_serve(req)
    t0 = time.perf_counter()
    for i in range(iters):
        chain_serve(ring[i % 512])
    chain_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for i in range(iters):
        fp.try_serve(ring[i % 512])
    fast_s = time.perf_counter() - t0
    decision_row = {
        "iters": iters,
        "chain_rps": round(iters / chain_s, 1),
        "fastpath_rps": round(iters / fast_s, 1),
        "speedup": round(chain_s / fast_s, 2),
        "native_table": available(),
    }
    print(json.dumps({"section": "decision_stage", **decision_row}),
          flush=True)

    # ---- witness: byte identity over the mixed workload ----
    # minted session cookies and challenge payloads are fresh randomness
    # on BOTH sides; mask exactly those spans before comparing
    _cpat = re.compile(
        rb"(deflect_challenge3=)([^;]+)|(X-Deflect-Session: )(\S+)"
        rb"|(deflect_session=)([^;]+)"
    )

    def _norm(b):
        return _cpat.sub(
            lambda m: (m.group(1) or m.group(3) or m.group(5)) + b"<X>", b)

    mismatches = 0
    witness_requests = 0

    def _compare(headers, normalize=False, expect_hit=None):
        nonlocal mismatches, witness_requests
        witness_requests += 1
        fast = fp.try_serve(_Req(dict(headers)))   # prod order: fast first,
        cb = chain_serve(_Req(dict(headers)))      # chain lazy-expires after
        if fast is None:
            if expect_hit:
                mismatches += 1
            return None
        a, b = (_norm(fast[0]), _norm(cb)) if normalize else (fast[0], cb)
        if a != b:
            mismatches += 1
        return fast

    tiers = {"allow": 0, "block": 0, "challenge": 0, "expired": 0, "miss": 0}
    expired_ips = [f"10.5.0.{k}" for k in range(8)]
    for ip in expired_ips:
        dyn.update(ip, now - 1.0, Decision.NGINX_BLOCK, False, "bench")
    chal_n = 0
    for _ in range(witness_n):
        p = rng.random()
        if p < 0.40:
            ip = rng.choice(allow_ips)
            _compare(_hdrs(ip, cookie=(
                f"deflect_session={go_query_escape(_clean_cookie(ip))}"
            )), expect_hit=True)
            tiers["allow"] += 1
        elif p < 0.55:
            _compare(_hdrs(rng.choice(allow_ips)), normalize=True,
                     expect_hit=True)  # cookieless: both arms mint
            tiers["allow"] += 1
        elif p < 0.72:
            ip = rng.choice(block_ips)
            _compare(_hdrs(ip, cookie=(
                f"deflect_session={go_query_escape(_clean_cookie(ip))}"
            )), expect_hit=True)
            tiers["block"] += 1
        elif p < 0.82:
            ip = f"10.3.{chal_n >> 8}.{chal_n & 0xFF}"
            chal_n += 1
            dyn.update(ip, now + 3600, Decision.CHALLENGE, False, "bench")
            _compare(_hdrs(ip), normalize=True, expect_hit=True)
            tiers["challenge"] += 1
        elif p < 0.92:
            _compare(_hdrs(rng.choice(expired_ips)), normalize=True)
            tiers["expired"] += 1
        else:
            _compare(_hdrs(
                f"172.16.{rng.randint(0, 255)}.{rng.randint(1, 254)}"
            ), normalize=True)
            tiers["miss"] += 1

    # live expiry-boundary crossing: entries expire mid-sweep; every
    # sample must agree (hit -> identical bytes, then both flip to the
    # post-expiry decision)
    boundary_ips = [f"10.4.0.{k}" for k in range(4)]
    flip_at = time.time() + 1.0
    for ip in boundary_ips:
        dyn.update(ip, flip_at, Decision.ALLOW, False, "bench")
    boundary_samples = 0
    boundary_flips = 0
    was_hit = dict.fromkeys(boundary_ips)
    while time.time() < flip_at + 0.4:
        for ip in boundary_ips:
            fast = _compare(_hdrs(ip), normalize=True)
            hit = fast is not None
            if was_hit[ip] and not hit:
                boundary_flips += 1
            was_hit[ip] = hit
            boundary_samples += 1
        time.sleep(0.03)

    witness_row = {
        "requests": witness_requests,
        "mismatches": mismatches,
        "tiers": tiers,
        "boundary_samples": boundary_samples,
        "boundary_flips": boundary_flips,
        "fastpath_counters": get_stats().prom_snapshot(),
    }
    print(json.dumps({"section": "witness", **witness_row}), flush=True)

    get_stats().reset()
    dyn.close()
    table.close()
    if hasattr(table, "unlink"):
        table.unlink()

    # ---- http_capacity: the real server, chain-only vs fast path ----
    fixture = os.path.join(_DIR, "tests", "fixtures",
                           "banjax-config-test.yaml")
    with open(fixture) as f:
        base_yaml = f.read()

    def _http_arm(enabled):
        from banjax_tpu.cli import BanjaxApp

        tmp_dir = tempfile.mkdtemp(prefix="bench-serve-")
        cwd = os.getcwd()
        os.chdir(tmp_dir)
        cfg_path = os.path.join(tmp_dir, "banjax-config.yaml")
        with open(cfg_path, "w") as f:
            f.write(base_yaml + "\nserve_fastpath_enabled: "
                    + ("true" if enabled else "false") + "\n")
        get_stats().reset()
        app = BanjaxApp(cfg_path, standalone_testing=True, debug=False)
        app.start_background()
        try:
            now2 = time.time()
            h_allow = [f"10.11.{k >> 8}.{k & 0xFF}" for k in range(64)]
            h_block = [f"10.12.0.{k}" for k in range(16)]
            h_chal = [f"10.13.0.{k}" for k in range(4)]
            h_expired = [f"10.14.0.{k}" for k in range(8)]
            for ip in h_allow:
                app.dynamic_lists.update(ip, now2 + 3600, Decision.ALLOW,
                                         False, "bench")
            for ip in h_block:
                app.dynamic_lists.update(ip, now2 + 3600,
                                         Decision.NGINX_BLOCK, False, "bench")
            for ip in h_chal:
                app.dynamic_lists.update(ip, now2 + 3600, Decision.CHALLENGE,
                                         False, "bench")
            for ip in h_expired:
                app.dynamic_lists.update(ip, now2 - 1.0, Decision.ALLOW,
                                         False, "bench")

            def _raw(ip, cookie=None):
                lines = [
                    "GET /auth_request?path=%2F HTTP/1.1",
                    "Host: bench.example.net",
                    f"X-Client-IP: {ip}",
                ]
                if cookie is not None:
                    lines.append(
                        f"Cookie: deflect_session={go_query_escape(cookie)}")
                lines.append("Connection: keep-alive")
                return ("\r\n".join(lines) + "\r\n\r\n").encode()

            arm_rng = random.Random(seed + 1)  # same workload both arms
            reqs = []
            for _ in range(1024):
                p = arm_rng.random()
                if p < 0.70:
                    ip = arm_rng.choice(h_allow)
                    reqs.append(_raw(ip, _clean_cookie(ip, "session_secret")))
                elif p < 0.78:
                    reqs.append(_raw(arm_rng.choice(h_allow)))
                elif p < 0.88:
                    ip = arm_rng.choice(h_block)
                    reqs.append(_raw(ip, _clean_cookie(ip, "session_secret")))
                elif p < 0.92:
                    reqs.append(_raw(arm_rng.choice(h_expired)))
                elif p < 0.97:
                    reqs.append(_raw(
                        f"172.17.{arm_rng.randint(0, 255)}"
                        f".{arm_rng.randint(1, 254)}"))
                else:
                    reqs.append(_raw(arm_rng.choice(h_chal)))

            async def _worker(items, lats):
                reader, writer = await asyncio.open_connection(
                    "127.0.0.1", 8081)
                for raw in items:
                    t_req = time.perf_counter()
                    writer.write(raw)
                    await writer.drain()
                    hdr = await reader.readuntil(b"\r\n\r\n")
                    clen = 0
                    for line in hdr.split(b"\r\n"):
                        if line.lower().startswith(b"content-length:"):
                            clen = int(line.split(b":")[1])
                    if clen:
                        await reader.readexactly(clen)
                    lats.append(time.perf_counter() - t_req)
                writer.close()

            async def _drive(n_each):
                lats = []
                t_run = time.perf_counter()
                await asyncio.gather(*[
                    _worker([reqs[(w * 131 + i) % 1024]
                             for i in range(n_each)], lats)
                    for w in range(conc)
                ])
                return lats, time.perf_counter() - t_run

            asyncio.run(_drive(40))  # warm
            get_stats().reset()
            if getattr(app, "decision_table", None) is not None:
                get_stats().set_table(app.decision_table)
            lats, elapsed = asyncio.run(_drive(n_per_conn))
            lats.sort()
            row = {
                "requests": len(lats),
                "rps": round(len(lats) / elapsed, 1),
                "p50_us": round(lats[len(lats) // 2] * 1e6, 1),
                "p99_us": round(lats[min(len(lats) - 1,
                                         int(len(lats) * 0.99))] * 1e6, 1),
                "conc": conc,
                "n_per_conn": n_per_conn,
                "fastpath_enabled": enabled,
            }
            if enabled:
                row["fastpath_counters"] = get_stats().prom_snapshot()
            return row
        finally:
            app.stop_background()
            os.chdir(cwd)
            shutil.rmtree(tmp_dir, ignore_errors=True)

    row_chain = _http_arm(False)
    print(json.dumps({"section": "http_chain_only", **row_chain}), flush=True)
    row_fast = _http_arm(True)
    print(json.dumps({"section": "http_fastpath", **row_fast}), flush=True)

    book = {
        "metric": ("compiled /auth_request fast path vs userspace chain "
                   "(shm decision table + byte templates)"),
        "measured_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "seed": seed,
        "rows": {
            "decision_stage": decision_row,
            "witness": witness_row,
            "http_capacity": {
                "chain_only": row_chain,
                "fastpath": row_fast,
                "speedup": round(row_fast["rps"] / row_chain["rps"], 3),
            },
        },
        "summary": {
            "chain_rps": decision_row["chain_rps"],
            "fastpath_rps": decision_row["fastpath_rps"],
            "speedup_fastpath_vs_chain": decision_row["speedup"],
            "witness_requests": witness_requests,
            "witness_mismatches": mismatches,
            "http_rps_chain_only": row_chain["rps"],
            "http_rps_fastpath": row_fast["rps"],
            "acceptance_speedup_5x": decision_row["speedup"] >= 5.0,
            "acceptance_witness_clean": mismatches == 0,
        },
    }
    tmp_path = SERVE_PATH + ".tmp"
    with open(tmp_path, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp_path, SERVE_PATH)
    print(json.dumps({"metric": book["metric"], **book["summary"]}))


def _single_kernel_mode() -> None:
    """`bench.py --single-kernel`: the streaming pipeline + device
    windows with the single-kernel fused program ON (one dispatch, one
    pull, no program-B turn) vs OFF (the two-program A/B path with its
    depth-2 resolve-ahead), same chunk stream.  Banks both rows into
    BENCH_single_kernel.json with the acceptance witnesses: lines/s (the
    on-row must match or beat the banked --fused-pipeline row), d2h
    bytes/batch (one combined buffer vs A+B pulls), and the resolve-pull
    elimination — the off-row's DrainResolveOverlapMs is the decode+
    replay wall the two-program drain hides behind program B; the on-row
    has no B left to hide behind, so the metric stays unset (≈ 0)."""
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "16384"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "256"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    rests = generate_lines(total, patterns, seed=47)
    lines = [
        f"{now:.6f} 10.7.{(i % 2048) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    rows = {}
    for label, mode in (("single_kernel", "on"), ("two_program", "off")):
        cfg = config_from_yaml_text(rules_yaml)
        cfg.matcher_device_windows = True
        cfg.pallas_single_kernel = mode
        matcher = TpuMatcher(
            cfg, MockBanner(), StaticDecisionLists(cfg),
            RegexRateLimitStates(),
        )
        fw = matcher._fw_pipeline
        assert fw is not None, "fused matcher+windows pipeline missing"
        assert fw.single_kernel == (mode == "on"), (
            f"pallas_single_kernel={mode} did not resolve as requested "
            "(Pallas window-scan unavailable on this backend?)"
        )
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        # warm until compiles AND the adaptive sizer settle: the
        # single-kernel program compiles one bigger variant per (rows,
        # line-len) bucket than A/B, and a first-visit compile poisons
        # the sizer's per-line record for that bucket until its decay
        # retry (sizer._RETRY_BLOCKED) — one warm pass would bank the
        # convergence transient, not steady state.  Fixed pass count
        # (non-adaptive: the transient plateaus, so a rate-delta exit
        # fires early); both rows use the identical protocol.
        warm_passes = int(os.environ.get("BENCH_SK_WARM_PASSES", "6"))
        for _ in range(max(1, warm_passes)):
            for c in chunks:
                sched.submit(c)
            assert sched.flush(600), f"{label} warm pass did not drain"
        # several timed passes, best banked: on the 1-core build box the
        # adaptive sizer's trajectory wobbles batch sizes between passes
        # (PERF round 9 measured 6.6% run-to-run spread on this exact
        # workload) — the best pass is the steady-state estimate, the
        # full list is kept for the spread
        timed_passes = int(os.environ.get("BENCH_SK_TIMED_PASSES", "3"))
        pass_rates = []
        h2d0 = matcher.stats.h2d_bytes_total
        d2h0 = matcher.stats.d2h_bytes_total
        batches0 = matcher.stats.batches_total
        elapsed_total = 0.0
        for _ in range(max(1, timed_passes)):
            t0 = time.perf_counter()
            for c in chunks:
                sched.submit(c)
            assert sched.flush(600), f"{label} timed pass did not drain"
            dt = time.perf_counter() - t0
            elapsed_total += dt
            pass_rates.append(round(total / dt, 1))
        snap = sched.snapshot()
        sched.stop()
        overlap = matcher.drain_resolve_overlap_ms_ewma
        rows[label] = {
            "mode": f"pipeline+device_windows ({label})",
            "backend": backend,
            "value": max(pass_rates),
            "unit": "lines/sec",
            "vs_baseline": round(max(pass_rates) / TARGET, 4),
            "pass_rates": pass_rates,
            "elapsed_s": round(elapsed_total, 2),
            "n_rules": n_rules,
            "n_lines": total,
            "h2d_bytes_per_batch": round(
                (matcher.stats.h2d_bytes_total - h2d0)
                / max(1, matcher.stats.batches_total - batches0), 1
            ),
            "d2h_bytes_per_batch": round(
                (matcher.stats.d2h_bytes_total - d2h0)
                / max(1, matcher.stats.batches_total - batches0), 1
            ),
            "pipelined_fused_chunks": matcher.pipelined_fused_chunks,
            "pipelined_fused_fallbacks": matcher.pipelined_fused_fallbacks,
            "single_kernel_chunks": fw.sk_chunks,
            "single_kernel_fallbacks": fw.sk_fallbacks,
            "drain_resolve_overlap_ms": (
                None if overlap is None else round(overlap, 3)
            ),
            "pipeline_batches": snap.get("PipelineBatches"),
            "pipeline_shed_lines": snap.get("PipelineShedLines"),
        }

    banked_fused = None
    try:
        with open(FUSED_STREAM_PATH) as f:
            banked_fused = json.load(f).get("fused", {}).get("value")
    except (OSError, ValueError):
        pass
    on, off = rows["single_kernel"], rows["two_program"]
    book = {
        "metric": "log-lines/sec, streaming pipeline + device windows "
                  "(single-kernel fused program vs two-program A/B)",
        "single_kernel": on,
        "two_program": off,
        "single_vs_two_program_speedup": round(
            on["value"] / max(1.0, off["value"]), 3
        ),
        # the resolve-pull witness: the off row's drain hides this many
        # ms of decode+replay behind program B per chunk; the on row has
        # no B dispatch — the pull is GONE from the drain critical path,
        # not overlapped (DrainResolveOverlapMs ≈ 0 / unset)
        "resolve_pull_ms_eliminated": off["drain_resolve_overlap_ms"],
        "resolve_pull_removed": on["drain_resolve_overlap_ms"] in (None, 0),
        # acceptance vs the banked --fused-pipeline row (same stream
        # shape): >= 1.0 means the single-kernel row matches or beats it
        "banked_fused_pipeline_lines_per_sec": banked_fused,
        "vs_banked_fused_pipeline": (
            None if not banked_fused
            else round(on["value"] / banked_fused, 3)
        ),
    }
    tmp = SINGLE_KERNEL_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, SINGLE_KERNEL_PATH)
    print(json.dumps(book))


def _stream_mode(mode: str) -> None:
    """End-to-end throughput of the tailer→matcher path under a
    tailer-shaped feed.

    `feed_chunk_lines` models ARRIVAL granularity: the reference consumes
    per line (regex_rate_limiter.go:58-76); a poll-based tailer keeping up
    with its stream delivers small reads (default 16 lines — one 50 ms
    poll at moderate rate).  The two modes consume the identical chunk
    stream:

    --sync     : the pre-pipeline behavior — one synchronous
                 consume_lines call per arriving chunk, so batch size is
                 COUPLED to arrival granularity and every chunk pays the
                 full submit→wait→collect fixed cost.
    --pipeline : the same chunks through banjax_tpu/pipeline/ — the
                 scheduler coalesces arrivals into adaptive batches
                 (decoupling batch size from arrival granularity, the
                 continuous-batching move) and overlaps
                 encode/device/drain across its stage threads.

    Emits one JSON line in the BENCH_r0x schema and merges the row into
    BENCH_pipeline.json (plus the pipeline/sync speedup once both modes
    have run on the same backend).
    """
    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.pipeline import PipelineScheduler
    from tests.mock_banner import MockBanner

    backend = jax.devices()[0].platform
    n_rules = int(os.environ.get("BENCH_STREAM_RULES", str(N_RULES)))
    total = int(os.environ.get(
        "BENCH_STREAM_LINES", "131072" if backend == "tpu" else "32768"
    ))
    feed_chunk = int(os.environ.get("BENCH_STREAM_CHUNK", "16"))
    budget_ms = float(os.environ.get("BENCH_STREAM_BUDGET_MS", "180"))

    patterns = generate_rules(n_rules)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    cfg = config_from_yaml_text(rules_yaml)
    # BENCH_STREAM_DEVICE_WINDOWS=1: run the stream against the
    # device-resident window counters — with --pipeline this drives the
    # fused two-phase path (see --fused-pipeline for the full A/B)
    device_windows = bool(os.environ.get("BENCH_STREAM_DEVICE_WINDOWS"))
    cfg.matcher_device_windows = device_windows
    banner = MockBanner()
    matcher = TpuMatcher(
        cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates()
    )
    now = time.time()
    rests = generate_lines(total, patterns, seed=43)
    lines = [
        f"{now:.6f} 10.8.{(i % 2048) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]
    chunks = [lines[i : i + feed_chunk] for i in range(0, total, feed_chunk)]

    out = {
        "metric": f"log-lines/sec end-to-end, tailer-shaped feed ({mode})",
        "unit": "lines/sec",
        "mode": mode,
        "backend": backend,
        "n_rules": n_rules,
        "n_lines": total,
        "feed_chunk_lines": feed_chunk,
        "latency_budget_ms": budget_ms,
    }
    if mode == "sync":
        matcher.consume_lines(chunks[0], now)  # warm compile at the chunk bucket
        t0 = time.perf_counter()
        for c in chunks:
            matcher.consume_lines(c, now)
        elapsed = time.perf_counter() - t0
    else:
        sched = PipelineScheduler(
            lambda: matcher, latency_budget_ms=budget_ms,
            buffer_lines=max(131072, total), now_fn=lambda: now,
        )
        sched.start()
        # warm pass: compiles every bucket the sizer will settle through,
        # so the timed pass measures steady state, not Mosaic/XLA compiles
        for c in chunks:
            sched.submit(c)
        assert sched.flush(600), "pipeline warm pass did not drain"
        t0 = time.perf_counter()
        for c in chunks:
            sched.submit(c)
        assert sched.flush(600), "pipeline timed pass did not drain"
        elapsed = time.perf_counter() - t0
        snap = sched.snapshot()
        sched.stop()
        out["pipeline_batch_target"] = snap.get("PipelineBatchTarget")
        out["pipeline_batches"] = snap.get("PipelineBatches")
        out["pipeline_shed_lines"] = snap.get("PipelineShedLines")
        out["pipeline_stale_dropped"] = snap.get("PipelineStaleDroppedLines")
        out["pipeline_device_p99_ms"] = snap.get("PipelineDeviceP99Ms")
        for k in ("Encode", "Device", "Drain"):
            out[f"pipeline_stage_{k.lower()}_ewma_ms"] = snap.get(
                f"PipelineStage{k}EwmaMs"
            )
        if device_windows:
            out["device_windows"] = True
            out["pipelined_fused_chunks"] = matcher.pipelined_fused_chunks
            out["pipelined_fused_fallbacks"] = (
                matcher.pipelined_fused_fallbacks
            )
            out["h2d_bytes_per_batch"] = round(
                matcher.stats.h2d_bytes_per_batch(), 1
            )
    lps = total / elapsed
    out["value"] = round(lps, 1)
    out["vs_baseline"] = round(lps / TARGET, 4)
    out["elapsed_s"] = round(elapsed, 2)
    if mode == "pipeline" and device_windows:
        # the acceptance row: --pipeline with device windows banks a
        # fused-pipelined row in BENCH_fused_pipeline.json — and ONLY
        # there: its workload (device windows on) is not comparable to
        # BENCH_pipeline.json's host-window sync row, so it must not
        # clobber that book's pipeline row or its speedup
        try:
            with open(FUSED_STREAM_PATH) as f:
                fbook = json.load(f)
        except (OSError, json.JSONDecodeError):
            fbook = {}
        fbook["pipeline_device_windows_row"] = out
        ftmp = FUSED_STREAM_PATH + ".tmp"
        with open(ftmp, "w") as f:
            json.dump(fbook, f, indent=1)
        os.replace(ftmp, FUSED_STREAM_PATH)
        head = ["metric", "value", "unit", "vs_baseline", "backend", "mode"]
        ordered = {k: out[k] for k in head if k in out}
        ordered.update({k: v for k, v in out.items() if k not in ordered})
        print(json.dumps(ordered))
        return

    # merge into BENCH_pipeline.json (atomic) and report the speedup when
    # both modes have been measured on this backend
    try:
        with open(STREAM_PATH) as f:
            book = json.load(f)
    except (OSError, json.JSONDecodeError):
        book = {}
    book[mode] = out
    other = book.get("pipeline" if mode == "sync" else "sync")
    if other and other.get("backend") == backend and other.get("value"):
        pipe = out["value"] if mode == "pipeline" else other["value"]
        sync = out["value"] if mode == "sync" else other["value"]
        book["pipeline_vs_sync_speedup"] = round(pipe / sync, 2)
        out["pipeline_vs_sync_speedup"] = book["pipeline_vs_sync_speedup"]
    tmp = STREAM_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(book, f, indent=1)
    os.replace(tmp, STREAM_PATH)

    head = ["metric", "value", "unit", "vs_baseline", "backend", "mode"]
    ordered = {k: out[k] for k in head if k in out}
    ordered.update({k: v for k, v in out.items() if k not in ordered})
    print(json.dumps(ordered))


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def _compose(partial: dict, live_sections: "set", probe: str,
             probe_err: "str | None") -> dict:
    secs = partial.get("sections", {})
    out: dict = {}
    merged_from_partial = []
    sec_meta = {}
    any_tpu = False
    for name in (*SECTIONS, "meta"):
        ent = secs.get(name)
        if not ent:
            continue
        out.update(ent["data"])
        sec_meta[name] = {
            "backend": ent["backend"], "measured_at": ent["measured_at"],
        }
        if ent["backend"] == "tpu" and name != "meta":
            any_tpu = True
        if name not in live_sections:
            merged_from_partial.append(name)

    out["backend"] = "tpu" if any_tpu else probe
    out["final_probe_backend"] = probe
    if probe_err:
        out["backend_error"] = probe_err
    if merged_from_partial:
        out["merged_from_partial"] = merged_from_partial
    out["section_provenance"] = sec_meta

    candidates = [
        out.get("pallas_lines_per_sec"),
        out.get("xla_lines_per_sec"),
        out.get("fused_device_resident_lines_per_sec"),
        out.get("fused_pipelined_lines_per_sec"),
    ]
    candidates = [v for v in candidates if v]
    best = max(candidates) if candidates else 0.0
    out["value"] = round(best, 1)
    out["vs_baseline"] = round(best / TARGET, 4)
    out["metric"] = "log-lines/sec classified @1k rules (device NFA match)"
    out["unit"] = "lines/sec"
    out["batch_latency_ms"] = (
        out.get("fused_device_resident_latency_ms")
        or out.get("pallas_batch_latency_ms")
        or out.get("fused_batch_latency_ms")
        or out.get("xla_batch_latency_ms")
    )
    return out


def main() -> None:
    if "--trace-overhead" in sys.argv:
        _trace_overhead_mode()
        return
    if "--provenance-overhead" in sys.argv:
        _provenance_overhead_mode()
        return
    if "--sketch-overhead" in sys.argv:
        _sketch_overhead_mode()
        return
    if "--host-parallel" in sys.argv:
        _host_parallel_mode()
        return
    if "--fused-pipeline" in sys.argv:
        _fused_pipeline_mode()
        return
    if "--single-kernel" in sys.argv:
        _single_kernel_mode()
        return
    if "--mega-state" in sys.argv:
        _mega_state_mode()
        return
    if "--fabric" in sys.argv:
        _fabric_mode()
        return
    if "--fleet-obs" in sys.argv:
        _fleet_obs_mode()
        return
    if "--challenge" in sys.argv:
        _challenge_mode()
        return
    if "--serve" in sys.argv:
        _serve_mode()
        return
    if "--scenarios" in sys.argv:
        _scenarios_mode()
        return
    if "--pipeline" in sys.argv:
        _stream_mode("pipeline")
        return
    if "--sync" in sys.argv:
        _stream_mode("sync")
        return
    if "--worker" in sys.argv:
        backend = "cpu"
        if "--backend" in sys.argv:
            backend = sys.argv[sys.argv.index("--backend") + 1]
        budget = float(os.environ.get("BENCH_BUDGET_S", "480"))
        only = None
        if os.environ.get("BENCH_SECTIONS"):
            only = os.environ["BENCH_SECTIONS"].split(",")
        worker_main(backend, budget, only)
        return

    probe, probe_err = _probe_backend()
    budget = float(os.environ.get("BENCH_BUDGET_S", "480"))
    live_sections: set = set()

    before = _load_partial().get("sections", {})
    before_stamp = {
        k: v.get("measured_at") for k, v in before.items()
    }
    try:
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker",
             "--backend", probe],
            timeout=budget + 180, capture_output=True, text=True,
        )
        if r.returncode != 0:
            probe_err = probe_err or (
                f"worker rc={r.returncode}: {r.stderr.strip()[-300:]}"
            )
    except subprocess.TimeoutExpired:
        probe_err = probe_err or (
            f"worker timeout after {budget + 180:.0f}s — composing from "
            "sections persisted before the hang"
        )
    after = _load_partial()
    for k, v in after.get("sections", {}).items():
        if before_stamp.get(k) != v.get("measured_at"):
            live_sections.add(k)

    result = _compose(after, live_sections, probe, probe_err)
    # key order: metric/value first for human eyeballs
    head = ["metric", "value", "unit", "vs_baseline", "backend"]
    ordered = {k: result[k] for k in head if k in result}
    ordered.update({k: v for k, v in result.items() if k not in ordered})
    print(json.dumps(ordered))


if __name__ == "__main__":
    main()
