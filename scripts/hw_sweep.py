"""Hardware tuning sweep — run AFTER scripts/hw_session.sh has banked the
headline sections. Sweeps the fused prefilter's (block_b, cols) tiling and
the device-resident batch size on the real chip, printing one JSON line per
configuration; the best configuration can then be pinned in
prefilter.FusedPrefilter's defaults and bench re-run.

Usage: python scripts/hw_sweep.py [budget_seconds]
"""

import json
import sys
import time

sys.path.insert(0, ".")


def main() -> None:
    budget = float(sys.argv[1]) if len(sys.argv) > 1 else 600.0
    t_start = time.monotonic()

    import os

    import jax

    if os.environ.get("BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import bench
    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.matcher.prefilter import FusedPrefilter, build_plan
    from banjax_tpu.matcher.rulec import compile_rules

    backend = jax.devices()[0].platform
    print(json.dumps({"sweep": "start", "backend": backend}))
    patterns = bench.generate_rules(1000)
    compiled = compile_rules(patterns, n_shards="auto")
    plan = build_plan(
        patterns, byte_classes=(compiled.byte_to_class, compiled.n_classes)
    )

    def measure(B, block_b, cols):
        lines = bench.generate_lines(B, patterns, seed=29)
        cls, lens, _ = encode_for_match(compiled, lines, 128)
        fp = FusedPrefilter(
            plan, "pallas" if backend == "tpu" else "xla",
            block_b=block_b, cols=cols,
        )
        combined, Bp, L_p = fp._assemble(cls, lens)
        fn, K, P = fp._fused(Bp, L_p)
        dev_in = jax.device_put(combined)

        @jax.jit
        def chained(s, x):
            return s + fn(x).astype(jnp.int32).sum()

        lps, lat, first = bench._time_chained(chained, (dev_in,), B, iters=6)
        return lps, lat, first

    results = []
    # tiling sweep at the r3 reference batch, then batch sweep at the best
    for block_b, cols in ((512, 32), (512, 64), (1024, 32), (256, 32),
                          (512, 16), (1024, 16)):
        if time.monotonic() - t_start > budget:
            break
        try:
            lps, lat, first = measure(65536, block_b, cols)
            row = {"B": 65536, "block_b": block_b, "cols": cols,
                   "lines_per_sec": round(lps, 1),
                   "latency_ms": round(lat * 1e3, 2),
                   "first_call_s": round(first, 1)}
        except Exception as exc:  # noqa: BLE001 — one config failing keeps the sweep
            row = {"B": 65536, "block_b": block_b, "cols": cols,
                   "error": f"{type(exc).__name__}: {exc}"[:200]}
        results.append(row)
        print(json.dumps(row), flush=True)

    ok = [r for r in results if "lines_per_sec" in r]
    if ok:
        best = max(ok, key=lambda r: r["lines_per_sec"])
        for B in (32768, 131072, 262144):
            if time.monotonic() - t_start > budget:
                break
            try:
                lps, lat, first = measure(B, best["block_b"], best["cols"])
                row = {"B": B, "block_b": best["block_b"],
                       "cols": best["cols"],
                       "lines_per_sec": round(lps, 1),
                       "latency_ms": round(lat * 1e3, 2)}
            except Exception as exc:  # noqa: BLE001
                row = {"B": B, "error": f"{type(exc).__name__}: {exc}"[:200]}
            print(json.dumps(row), flush=True)


if __name__ == "__main__":
    main()
