#!/usr/bin/env bash
# Multi-host decision fabric dry run: N real banjax worker PROCESSES on
# real sockets (one box), one shard SIGKILLed mid-flood, consistent-hash
# takeover + snapshot-sync rejoin — the fabric analogue of the
# dryrun_multichip device harness (__graft_entry__.dryrun_fabric).
#
# Usage: scripts/dryrun_fabric.sh [N]      (default N=2, ~30 s)
#
# Every worker is pinned to the CPU backend (a dry-run shard must never
# grab a real accelerator out from under the host); the short N=2 pass
# is tier-1 (tests/soak/test_fabric_soak.py), the N=4 chaos pass rides
# behind `-m slow`.

set -euo pipefail
cd "$(dirname "$0")/.."

N="${1:-2}"
exec env JAX_PLATFORMS=cpu python -c "
import __graft_entry__ as g
g.dryrun_fabric(${N})
"
