#!/bin/bash
# Hardware bench session — run the moment the axon tunnel comes up.
# Ordered so a mid-session tunnel death costs the least: a fast Mosaic
# parity check (catches a 32-word-alignment lowering reject immediately,
# with the env fallback to flip), then the headline sections, each
# persisted to BENCH_partial.json as it completes (bench.py worker).
set -x
cd "$(dirname "$0")/.."

# 1. fast compiled-kernel parity at the new 32-word alignment (~2 min)
timeout 600 python - <<'EOF'
import time
t0 = time.time()
import jax
print("devices:", jax.devices(), "in", round(time.time() - t0, 1), "s")
import numpy as np
from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.kernels import nfa_match
from banjax_tpu.matcher.rulec import compile_rules
import bench

patterns = bench.generate_rules(60)
compiled = compile_rules(patterns, n_shards="auto")
prep = nfa_match.prepare(compiled)
print("wps_p:", prep.wps_p, "shards:", prep.n_shards)
lines = bench.generate_lines(1024, patterns, seed=5, attack_rate=0.2)
cls, lens, _ = encode_for_match(compiled, lines, 128)
got = nfa_match.match_batch_pallas(prep, cls, lens, cols=32)
params = nfa_jax.match_params(compiled)
import jax.numpy as jnp
want = np.asarray(nfa_jax.match_batch(params, jnp.asarray(cls), jnp.asarray(lens), compiled.n_rules))
assert (got == want).all(), "ALIGN-32 COMPILED PARITY FAILED — set BANJAX_NFA_WORD_ALIGN=128"
print("align-32 compiled parity OK")
EOF
rc=$?
if [ $rc -ne 0 ]; then
  echo "!!! parity step failed (rc=$rc) — retrying the whole session with"
  echo "    the conservative 128-word alignment"
  export BANJAX_NFA_WORD_ALIGN=128
  timeout 600 python - <<'EOF' || exit 1
import jax, numpy as np, jax.numpy as jnp
from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.kernels import nfa_match
from banjax_tpu.matcher.rulec import compile_rules
import bench
patterns = bench.generate_rules(60)
compiled = compile_rules(patterns, n_shards="auto")
prep = nfa_match.prepare(compiled)
lines = bench.generate_lines(1024, patterns, seed=5, attack_rate=0.2)
cls, lens, _ = encode_for_match(compiled, lines, 128)
got = nfa_match.match_batch_pallas(prep, cls, lens, cols=32)
params = nfa_jax.match_params(compiled)
want = np.asarray(nfa_jax.match_batch(params, jnp.asarray(cls), jnp.asarray(lens), compiled.n_rules))
assert (got == want).all(), "align-128 parity ALSO failed - investigate before benching"
print("align-128 compiled parity OK; continuing with the fallback alignment")
EOF
fi

# 2. headline sections, worker-persisted (single_stage + fused first)
BENCH_SECTIONS=single_stage,fused BENCH_BUDGET_S=600 timeout 900 python bench.py

# 3. e2e + mesh + ladder
BENCH_SECTIONS=e2e,mesh BENCH_BUDGET_S=600 timeout 900 python bench.py
BENCH_SECTIONS=ladder BENCH_BUDGET_S=900 timeout 1200 python bench.py

# 4. bounded tiling/batch sweep (per-config JSON lines to the session log;
# the headline sections above are already banked, so a wedge here costs
# nothing)
timeout 900 python scripts/hw_sweep.py 600 || true

# 4b. streaming-pipeline acceptance rows on the real chip: classic-bitmap
# pipelined vs sync (BENCH_pipeline.json), then the fused two-phase vs
# classic A/B with device windows (BENCH_fused_pipeline.json — the
# h2d-bytes witness that the dense re-upload is gone rides along), then
# the sharded pipelined dryrun record. Each step banks its own artifact,
# so a tunnel wedge costs at most the row in flight.
timeout 900 python bench.py --sync || true
timeout 900 python bench.py --pipeline || true
timeout 900 python bench.py --fused-pipeline || true
BENCH_STREAM_DEVICE_WINDOWS=1 timeout 900 python bench.py --pipeline || true
timeout 600 python __graft_entry__.py || true

# 4d. single-kernel fused A/B (one-program match+window commit vs the
# two-program A/B path, device windows on): banks lines/s, d2h
# bytes/batch and the resolve-pull elimination into
# BENCH_single_kernel.json — the ROADMAP chip-attached round reads the
# on-row against the banked --fused-pipeline row and checks
# DrainResolveOverlapMs stays unset (no program-B dispatch left to
# overlap). Also the first compiled-Mosaic exercise of the Pallas
# window-scan kernel: a lowering failure shows up as the on-row
# asserting (single-kernel did not resolve) — the matcher itself
# degrades to two-program with a health note, so it costs the row, not
# correctness.
timeout 1200 python bench.py --single-kernel || true

# 4c. host-parallel A/B (sharded encode workers + native slot manager):
# banks the multi-core chip-host row into BENCH_host_parallel.json next
# to the 1-core CI row (rows are keyed by core count, so neither
# clobbers the other)
timeout 900 python bench.py --host-parallel || true

# 5. re-bank the two headline sections (tpu rows overwrite tpu rows,
# newest wins; a re-run with warm compile caches is usually the cleaner
# number)
BENCH_SECTIONS=single_stage,fused BENCH_BUDGET_S=480 timeout 700 python bench.py
