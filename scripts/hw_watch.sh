#!/bin/bash
# Poll for the axon TPU tunnel; the moment a probe succeeds, run the full
# hardware bench session (scripts/hw_session.sh) exactly once.
#
# Probe = `jax.devices()` in a subprocess with a hard timeout: when the
# tunnel is down, backend init blocks forever, so a short timeout is the
# only reliable liveness signal.  Logs to scripts/hw_watch.log.
cd "$(dirname "$0")/.."
LOG=scripts/hw_watch.log
echo "[hw_watch] start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if timeout 60 python -c "
import jax
ds = jax.devices()
assert any(d.platform == 'tpu' for d in ds), ds
print('tpu up:', ds)
" >> "$LOG" 2>&1; then
    echo "[hw_watch] TPU answered $(date -u +%FT%TZ) — running session" >> "$LOG"
    bash scripts/hw_session.sh >> scripts/hw_session.log 2>&1
    echo "[hw_watch] session done rc=$? $(date -u +%FT%TZ)" >> "$LOG"
    exit 0
  fi
  echo "[hw_watch] probe failed $(date -u +%FT%TZ); retry in 90s" >> "$LOG"
  sleep 90
done
