#!/bin/bash
# Poll for the axon TPU tunnel; the moment a probe succeeds, run the full
# hardware bench session (scripts/hw_session.sh) exactly once.
#
# Two-stage probe: a cheap TCP connect to the relay ports (8082/8083 —
# closed whenever the tunnel is down) every 20 s, then a real
# `jax.devices()` in a subprocess with a hard timeout (backend init blocks
# forever when the relay half-answers, so the timeout is the only reliable
# liveness signal).  Logs to scripts/hw_watch.log.
cd "$(dirname "$0")/.."
LOG=scripts/hw_watch.log
echo "[hw_watch] start $(date -u +%FT%TZ)" >> "$LOG"
while true; do
  if timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8082' 2>/dev/null \
     || timeout 3 bash -c 'exec 3<>/dev/tcp/127.0.0.1/8083' 2>/dev/null; then
    echo "[hw_watch] relay port open $(date -u +%FT%TZ) — jax probe" >> "$LOG"
    if timeout 120 python -c "
import jax
ds = jax.devices()
assert any(d.platform == 'tpu' for d in ds), ds
print('tpu up:', ds)
" >> "$LOG" 2>&1; then
      echo "[hw_watch] TPU answered $(date -u +%FT%TZ) — running session" >> "$LOG"
      bash scripts/hw_session.sh >> scripts/hw_session.log 2>&1
      echo "[hw_watch] session done rc=$? $(date -u +%FT%TZ)" >> "$LOG"
      exit 0
    fi
    echo "[hw_watch] port open but jax probe failed $(date -u +%FT%TZ)" >> "$LOG"
  fi
  sleep 20
done
