#!/usr/bin/env bash
# Tier-1 verify: the gate every PR must keep green (ROADMAP.md).
#
# Two stages, fail-fast:
#   1. exposition-schema / docs sync — scripts/check_metrics_docs.py in
#      CHECK mode: a renamed Prometheus family or an undocumented
#      registry entry fails HERE, not on a dashboard.  (The pytest
#      schema-stability suite, tests/unit/test_exposition.py, re-asserts
#      the same registry against real snapshots in stage 2.)
#   2. the full tier-1 pytest run (slow-marked tests excluded).  This
#      includes tests/soak/ — the SHORT seeded chaos pass (bounded
#      wall-clock, ~25 s) runs on every PR; the full-length soak across
#      every attack shape at scale 1.0 is slow-marked (`-m slow`).
#
# Usage: scripts/tier1.sh [extra pytest args]

set -o pipefail
cd "$(dirname "$0")/.."

echo "== tier1: metrics docs / registry sync =="
python scripts/check_metrics_docs.py || {
    echo "tier1: metrics docs out of sync (run scripts/check_metrics_docs.py --write)" >&2
    exit 1
}

echo "== tier1: pytest (not slow) =="
exec env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider "$@"
