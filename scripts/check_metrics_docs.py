#!/usr/bin/env python3
"""Cross-check README's documented metrics table against the exposition
registry (banjax_tpu/obs/registry.py).

The README "Observability" section carries a markdown table of every
Prometheus family between the `<!-- metrics-table-start -->` /
`<!-- metrics-table-end -->` markers.  This script fails (exit 1) when
the table and the registry disagree — a renamed/added/dropped family
must touch both, so dashboards never chase undocumented metrics.  Run
with `--write` to regenerate the table from the registry in place.

Wired into the test suite (tests/unit/test_exposition.py), so `pytest`
is the CI gate; it also runs standalone:

    python scripts/check_metrics_docs.py [--write] [README.md]
"""

from __future__ import annotations

import os
import re
import sys

_DIR = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _DIR)

START = "<!-- metrics-table-start -->"
END = "<!-- metrics-table-end -->"
_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|\s*(\S+)\s*\|\s*(.*?)\s*\|$")


def registry_rows():
    from banjax_tpu.obs.registry import FAMILIES

    rows = []
    for fam in FAMILIES:
        if not fam.prom:
            continue
        labels = (
            " (labels: " + ", ".join(f"`{l}`" for l in fam.labels) + ")"
            if fam.labels else ""
        )
        rows.append((fam.prom, fam.kind, fam.help + labels))
    return rows


def render_table(rows) -> str:
    lines = ["| family | type | help |", "|---|---|---|"]
    for prom, kind, help_text in rows:
        # pipes inside help would split the row
        lines.append(f"| `{prom}` | {kind} | {help_text.replace('|', '/')} |")
    return "\n".join(lines)


def parse_readme_table(text: str):
    try:
        start = text.index(START) + len(START)
        end = text.index(END)
    except ValueError:
        raise SystemExit(
            f"README is missing the {START} / {END} markers"
        ) from None
    rows = []
    for raw in text[start:end].strip().splitlines():
        m = _ROW_RE.match(raw.strip())
        if m:
            rows.append((m.group(1), m.group(2), m.group(3)))
    return rows


def check(readme_path: str, write: bool = False) -> int:
    with open(readme_path, encoding="utf-8") as f:
        text = f.read()
    want = registry_rows()
    if write:
        start = text.index(START) + len(START)
        end = text.index(END)
        new_text = text[:start] + "\n" + render_table(want) + "\n" + text[end:]
        with open(readme_path, "w", encoding="utf-8") as f:
            f.write(new_text)
        print(f"wrote {len(want)} families into {readme_path}")
        return 0
    have = parse_readme_table(text)
    have_names = {r[0] for r in have}
    want_names = {r[0] for r in want}
    problems = []
    for missing in sorted(want_names - have_names):
        problems.append(f"registry family not documented: {missing}")
    for extra in sorted(have_names - want_names):
        problems.append(f"documented family not in registry: {extra}")
    want_by_name = {r[0]: r for r in want}
    for name, kind, _help in have:
        if name in want_by_name and kind != want_by_name[name][1]:
            problems.append(
                f"{name}: documented type {kind!r} != registry "
                f"{want_by_name[name][1]!r}"
            )
    if problems:
        for p in problems:
            print(f"check_metrics_docs: {p}", file=sys.stderr)
        print(
            "check_metrics_docs: run `python scripts/check_metrics_docs.py "
            "--write` to regenerate the README table from the registry",
            file=sys.stderr,
        )
        return 1
    print(f"check_metrics_docs: {len(want)} families in sync")
    return 0


def main(argv) -> int:
    write = "--write" in argv
    paths = [a for a in argv if not a.startswith("--")]
    readme = paths[0] if paths else os.path.join(_DIR, "README.md")
    return check(readme, write=write)


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
