"""Seeded chaos soak: the scenario harness driving the REAL engine.

Tier-1 runs the short pass on every PR (bounded wall-clock: small
scales, two chaos runs); the full-length pass across every shape at
scale 1.0 rides behind `-m slow`.

What every run asserts (ScenarioReport.invariants):

  * admitted == processed + shed + drain_errors  (the PR 2 contract)
  * zero leaked fused order turns, zero leaked device-window slot pins
  * benign shapes: zero bans AND banjax_slo_breached == 0 end to end
  * chaos runs: one flight-recorder bundle per injected episode
"""

import json
import os

import pytest

from banjax_tpu.resilience import failpoints
from banjax_tpu.scenarios import ChaosSchedule, ScenarioRunner, generate
from banjax_tpu.scenarios.chaos import KAFKA_POINTS, TAILER_POINTS
from tests.fake_kafka_broker import FakeKafkaBroker

SEED = 20260804  # the committed soak seed: every CI run replays it


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _assert_invariants(report):
    assert report.invariants, "no invariants evaluated"
    bad = {k: v for k, v in report.invariants.items() if not v}
    assert not bad, (
        f"scenario {report.name} invariant failures: {bad}\n"
        f"{json.dumps(report.row(), indent=1, default=str)}"
    )


def test_clean_flash_crowd_matches_oracle_exactly():
    rep = ScenarioRunner(generate("flash_crowd", SEED, scale=0.25)).run()
    _assert_invariants(rep)
    assert rep.precision == 1.0 and rep.recall == 1.0
    assert rep.oracle_bans > 0  # non-vacuous
    assert rep.shed_lines == 0 and rep.drain_error_lines == 0


def test_clean_slow_drip_does_not_ban_the_paced_drippers():
    """Precision bait: 90+ paced drippers stay unbanned, the greedy
    set bans — exactly the oracle's multiset."""
    rep = ScenarioRunner(generate("slow_drip", SEED, scale=0.3)).run()
    _assert_invariants(rep)
    assert rep.precision == 1.0 and rep.recall == 1.0
    assert 0 < rep.oracle_bans < 10  # only the greedy few


def test_benign_scenario_zero_bans_on_both_fused_protocols():
    """The differential check: the benign shape produces ZERO bans and
    a clean SLO board on BOTH fused device protocols (single-kernel and
    the two-program oracle path)."""
    for mode in ("auto", "off"):
        rep = ScenarioRunner(
            generate("benign", SEED, scale=0.1), single_kernel=mode
        ).run()
        _assert_invariants(rep)
        assert rep.engine_bans == 0, mode
        assert not any(rep.slo_breached.values()), mode


def test_challenge_storm_drives_the_real_challenge_plane(tmp_path):
    """challenge_storm's second act: every storm client goes through the
    REAL issuance -> solve -> verify -> failure loop (decision_chain +
    challenge/*), not a simulation.  Scripted solvers must all pass,
    every non-solver must ban (exact precision/recall vs the scripted
    split), the bounded failure state must hold its cap with zero
    recall loss, and the eviction storm must leave a loadable
    flight-recorder bundle."""
    rep = ScenarioRunner(
        generate("challenge_storm", SEED, scale=0.25),
        flightrec_dir=str(tmp_path / "flightrec"),
        # cap far below the attacker count so the LRU + spill machinery
        # is actually on trial during the bans
        cfg_overrides={"challenge_failure_state_max": 4},
    ).run()
    _assert_invariants(rep)  # includes challenge_ban_exact + bounded
    ch = rep.challenge
    assert ch is not None
    assert ch["solvers"] > 0 and ch["attackers"] > 0
    assert ch["solver_passes"] == ch["solvers"]
    assert ch["banned"] == ch["attackers"]
    assert ch["ban_precision"] == 1.0 and ch["ban_recall"] == 1.0
    assert ch["failure_state_entries"] <= 4
    # the storm's eviction pressure left at least one complete bundle
    assert rep.incidents >= 1
    fdir = str(tmp_path / "flightrec")
    bundles = [n for n in os.listdir(fdir) if not n.startswith(".")]
    assert bundles
    with open(os.path.join(fdir, bundles[0], "meta.json")) as f:
        meta = json.load(f)
    assert meta["reason"]


def test_command_flood_drains_every_command_in_take_max_batches():
    rep = ScenarioRunner(generate("command_flood", SEED, scale=0.3)).run()
    _assert_invariants(rep)
    assert rep.command_items == rep.n_commands > 0
    assert rep.precision == 1.0 and rep.recall == 1.0


def test_command_flood_through_real_kafka_reader():
    """The PR 9 chaos gap, clean half: command_flood produced into an
    in-process broker and drained by a REAL KafkaReader over the wire
    protocol into the pipeline — every command lands, every per-batch
    report comes back out through the KafkaWriter."""
    broker = FakeKafkaBroker().start()
    try:
        rep = ScenarioRunner(
            generate("command_flood", SEED, scale=0.3), kafka_broker=broker
        ).run()
        _assert_invariants(rep)
        assert rep.mode == "kafka"
        assert rep.command_items == rep.n_commands > 0
        assert rep.precision == 1.0 and rep.recall == 1.0
        assert broker.log_end_offset("scenario.reports", 0) > 0
    finally:
        broker.stop()


def test_kafka_chaos_soak_fires_kafka_failpoints(tmp_path):
    """The PR 9 chaos gap, chaotic half: kafka.read/kafka.send episodes
    over the kafka-fed command_flood — the reconnect and held-report
    loops take faults while real traffic flows, invariants hold, every
    episode leaves a bundle.  Arming only the two kafka points makes
    the shuffled rotation cover both within the shape's few events
    (KAFKA_POINTS mixes in the pipeline points for longer soaks)."""
    sc = generate("command_flood", SEED, scale=0.3)
    assert set(KAFKA_POINTS) >= {"kafka.read", "kafka.send"}
    chaos = ChaosSchedule(
        seed=SEED + 2, n_events=len(sc.events),
        points=("kafka.read", "kafka.send"),
        episodes=min(4, len(sc.events) - 1),
    )
    broker = FakeKafkaBroker().start()
    try:
        rep = ScenarioRunner(
            sc, chaos=chaos, kafka_broker=broker,
            flightrec_dir=str(tmp_path / "flightrec"),
        ).run()
    finally:
        broker.stop()
    _assert_invariants(rep)
    assert all(ep["bundle"] for ep in rep.episodes)
    armed_points = {ep["point"] for ep in rep.episodes}
    assert {"kafka.read", "kafka.send"} <= armed_points
    # the writer's held-report retry converges: every produced report
    # reached the broker despite kafka.send faults
    assert broker.log_end_offset("scenario.reports", 0) > 0


def test_short_seeded_chaos_soak(tmp_path):
    """The tier-1 chaos pass: a seeded failpoint schedule over the
    flash-crowd shape, flight recorder armed — invariants hold, every
    injected episode leaves a bundle, armed episodes actually fired."""
    sc = generate("flash_crowd", SEED, scale=0.25)
    chaos = ChaosSchedule(seed=SEED, n_events=len(sc.events), episodes=3)
    rep = ScenarioRunner(
        sc, chaos=chaos, flightrec_dir=str(tmp_path / "flightrec")
    ).run()
    _assert_invariants(rep)
    assert len(rep.episodes) >= 2
    assert all(ep["bundle"] for ep in rep.episodes)
    assert sum(ep["fired"] for ep in rep.episodes) > 0
    assert rep.incidents >= len(rep.episodes)
    # nothing left armed after the soak
    assert failpoints.snapshot() == [] or all(
        fp["count"] == 0 for fp in failpoints.snapshot()
    )
    # bundles are complete (rename-atomic contract): each has meta.json
    fdir = str(tmp_path / "flightrec")
    for name in os.listdir(fdir):
        assert not name.startswith(".")
        assert os.path.exists(os.path.join(fdir, name, "meta.json"))


def test_chaos_over_tailer_rotation(tmp_path):
    """Chaos + a real rotating log file: tailer.open faults and pipeline
    faults layered over the rotation scenario — the accounting and leak
    invariants must still hold, and nothing the tailer delivered may
    vanish silently (admitted == processed + shed holds by invariant)."""
    sc = generate("log_rotation", SEED, scale=0.2)
    chaos = ChaosSchedule(
        seed=SEED + 1, n_events=len(sc.events),
        points=TAILER_POINTS, episodes=3,
    )
    rep = ScenarioRunner(
        sc, chaos=chaos, via_tailer=True, tmp_dir=str(tmp_path),
        flightrec_dir=str(tmp_path / "flightrec"),
    ).run()
    _assert_invariants(rep)
    assert all(ep["bundle"] for ep in rep.episodes)


@pytest.mark.slow
def test_full_soak_every_shape_clean_and_chaotic(tmp_path):
    """The full-length soak (-m slow): every named shape at scale 1.0
    clean, then chaos passes over the two nastiest shapes."""
    from banjax_tpu.scenarios import SHAPES

    for name in sorted(SHAPES):
        rep = ScenarioRunner(generate(name, SEED, scale=1.0)).run()
        _assert_invariants(rep)
        if not rep.name == "benign":
            assert rep.precision == 1.0 and rep.recall == 1.0, name
    for name in ("rotating_proxies", "command_flood"):
        sc = generate(name, SEED, scale=1.0)
        chaos = ChaosSchedule(
            seed=SEED, n_events=len(sc.events), episodes=6
        )
        rep = ScenarioRunner(
            sc, chaos=chaos,
            flightrec_dir=str(tmp_path / f"fr-{name}"),
        ).run()
        _assert_invariants(rep)
        assert all(ep["bundle"] for ep in rep.episodes)
