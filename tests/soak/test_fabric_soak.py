"""Fabric dryrun soak: N real banjax worker PROCESSES on real sockets,
one SIGKILLed mid-flood (ISSUE 15 acceptance).

Tier-1 runs the short N=2 pass on every PR (~30 s: spawn two engines,
flood, SIGKILL one, takeover, rejoin).  The N=4 chaos pass — takeover
with multiple successors, plus an armed fabric.takeover failpoint in
every worker — rides behind `-m slow`.

What the harness proves (FabricDryrun invariants, all asserted here):

  * recall 1.0 vs the oracle with a shard SIGKILLed mid-flood —
    zero-lost-ban handoff (double-processing may only ADD bans)
  * fabric-wide accounting: fed == acked, and per worker
    local + forwarded + shed == received + replayed, with the pipeline's
    admitted == processed + shed + drain_errors inside each shard
  * duplicate decision inserts suppressed or idempotent
  * rejoin: snapshot sync applied idempotently, the handed-back wave
    processed exactly once fabric-wide
"""

import json

import pytest

from banjax_tpu.fabric.harness import run_fabric

SEED = 20260804  # the committed soak seed: every CI run replays it


def _assert_invariants(report):
    bad = [k for k, ok in report["invariants"].items() if not ok]
    rejoin = report.get("rejoin")
    if rejoin is not None:
        bad += [
            f"rejoin.{k}" for k, ok in rejoin["invariants"].items()
            if not ok
        ]
    assert not bad, (
        f"fabric invariants failed: {bad}\n"
        f"{json.dumps(report, indent=1, default=str)}"
    )


def test_two_shards_kill_one_mid_flood_then_rejoin():
    """The tier-1 short pass (scripts/dryrun_fabric.sh shape): two real
    worker processes, w1 SIGKILLed at 45% of the flood, w0 takes over
    its range and re-derives every ban, then w1 rejoins from a snapshot
    sync and takes back its range without double-processing."""
    report = run_fabric(
        n_workers=2, shape="flash_crowd", seed=SEED, kill=True,
        rejoin=True,
    )
    _assert_invariants(report)
    assert report["recall"] == 1.0
    assert report["oracle_bans"] > 0          # non-vacuous
    assert report["fed_lines"] == report["acked_lines"]
    assert report["duplicates_suppressed"] > 0
    takeover = report["takeover"]
    assert takeover["victim"] == "w1"
    # the zero-lost-ban window: anything shed during takeover is
    # counted, and the committed seed sheds nothing
    assert takeover["shed_ratio_in_window"] == 0.0
    rejoin = report["rejoin"]
    assert rejoin["snapshot_decisions"] > 0
    assert rejoin["sync_applied"] == rejoin["snapshot_decisions"]
    assert rejoin["newcomer_local_lines"] > 0


def test_two_shards_membership_churn_gossip_detect_join_leave():
    """ISSUE 16 acceptance (tier-1, ~25 s): the full churn episode on
    two shards — SIGKILL with the feed PAUSED (detection must be
    gossip's alone, no forwarded line involved), automatic join of a
    brand-new worker (T_JOIN announce + snapshot sync, zero survivor
    restarts), a slow-node suspect/refute cycle, and a planned leave
    with zero shed / zero replay."""
    report = run_fabric(
        n_workers=2, shape="flash_crowd", seed=SEED, churn=True,
    )
    _assert_invariants(report)
    assert report["recall"] == 1.0
    assert report["oracle_bans"] > 0
    assert report["fed_lines"] == report["acked_lines"]
    takeover = report["takeover"]
    assert takeover["mode"] == "gossip"
    # every survivor confirmed the death within the suspect window
    # (plus generous probe-scheduling and CI slack)
    bound = (
        takeover["suspect_timeout_s"]
        + 10 * takeover["gossip_interval_s"] + 10.0
    )
    assert 0 < takeover["max_detect_s"] <= bound, takeover
    # the victim's journaled lines were replayed, none lost
    assert takeover["driver_replayed_lines"] > 0
    join = report["join"]
    assert join["synced_decisions"] > 0
    assert join["joiner_local_lines"] > 0
    assert join["wave_locals_sum"] == join["wave_lines"]
    sr = report["suspect_refute"]
    assert sr["suspects_delta"] >= 1 and sr["refuted_delta"] >= 1
    leave = report["leave"]
    assert leave["shed_leaver"] == 0 and leave["shed_rest"] == 0
    assert leave["replayed_lines"] == 0
    # the seeded schedule drove it and every op recorded its outcome
    sched = {row["op"]: row for row in report["churn_schedule"]}
    assert set(sched) == {"kill", "join", "slow_node", "leave"}
    assert all(row["outcome"] is not None for row in sched.values())


@pytest.mark.slow
def test_four_shard_membership_churn_full_scale():
    """The N=4 churn pass (-m slow): gossip-confirmed death with three
    survivors converging independently, join/slow-node/leave on the
    larger fleet."""
    report = run_fabric(
        n_workers=4, shape="flash_crowd", seed=SEED, scale=1.0,
        churn=True,
    )
    _assert_invariants(report)
    assert report["recall"] == 1.0
    takeover = report["takeover"]
    # all three survivors independently gossip-confirmed the death
    assert len(takeover["detect_s"]) == 3
    bound = (
        takeover["suspect_timeout_s"]
        + 10 * takeover["gossip_interval_s"] + 10.0
    )
    assert 0 < takeover["max_detect_s"] <= bound, takeover
    assert report["join"]["wave_locals_sum"] == report["join"]["wave_lines"]
    assert report["leave"]["replayed_lines"] == 0


@pytest.mark.slow
def test_four_shard_chaos_takeover_with_armed_takeover_failpoint():
    """The full chaos pass (-m slow): four shards, one SIGKILLed, the
    dead range splits across MULTIPLE consistent-hash successors, at
    full scale."""
    report = run_fabric(
        n_workers=4, shape="flash_crowd", seed=SEED, scale=1.0,
        kill=True, rejoin=True,
    )
    _assert_invariants(report)
    assert report["recall"] == 1.0
    # more than one survivor participated in the flood after the kill
    survivors = [w for w in report["per_worker"] if w != report["killed"]]
    assert len(survivors) == 3
    assert all(
        report["per_worker"][w]["fabric"]["FabricTakeovers"] >= 1
        for w in survivors
    )
