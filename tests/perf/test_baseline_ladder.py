"""The BASELINE.json measurement ladder as enforced perf floors.

Five configs (BASELINE.md "Measurement ladder"), each timed and asserted
against a conservative CPU floor so a perf regression fails CI instead of
passing silently (VERDICT r1 weak #6). Full-scale numbers come from
bench.py on the real chip; here the shapes are identical but line counts
are CI-sized unless BANJAX_PERF_FULL=1.

Every config prints one JSON line {"config": N, "lines_per_sec": ...} so CI
logs double as a coarse perf history.
"""

import json
import os
import time
from pathlib import Path

import numpy as np
import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from tests.mock_banner import MockBanner

FULL = bool(os.environ.get("BANJAX_PERF_FULL"))
FIXTURES = Path(__file__).resolve().parents[1] / "fixtures"

# Floors per backend (VERDICT r2 item 7). CPU floors sit at roughly 1/3 of
# the r3 measured CPU numbers (42.9k / 10.3k / 3.8k / 2.9k / 2.4k) — loose
# enough for ~3x CI-machine variance, tight enough that an accidental
# per-line recompile or a lost vectorized replay path fails CI. TPU floors
# apply when the attached backend is really a TPU (bench.py's ladder on
# hardware): config 1 is the serial CPU reference either way.
# config1 is measured in a fresh subprocess (it was the one config whose
# floor full-suite jit-cache/GC pressure could sink — isolation restores
# the honest 14k floor instead of loosening it)
CPU_FLOORS = {1: 14_000, 2: 3_500, 3: 1_200, 4: 900, 5: 800}
TPU_FLOORS = {1: 14_000, 2: 8_000, 3: 20_000, 4: 5_000, 5: 5_000}


def _floors():
    import jax

    return TPU_FLOORS if jax.default_backend() == "tpu" else CPU_FLOORS


def _report(config_n: int, n_lines: int, elapsed: float) -> float:
    lps = n_lines / elapsed
    floor = _floors()[config_n]
    print(json.dumps({
        "config": config_n, "lines": n_lines,
        "lines_per_sec": round(lps, 1), "full_scale": FULL,
    }))
    assert lps >= floor, (
        f"BASELINE config {config_n}: {lps:.0f} lines/s below the "
        f"{floor} floor"
    )
    return lps


def _drive(matcher, lines, now, batch=4096):
    t0 = time.perf_counter()
    for start in range(0, len(lines), batch):
        matcher.consume_lines(lines[start : start + batch], now)
    return time.perf_counter() - t0


def _make_matcher(yaml_text, cls=TpuMatcher, **cfg_overrides):
    cfg = config_from_yaml_text(yaml_text)
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    banner = MockBanner()
    m = cls(cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates())
    return m, banner


def _access_log_lines(n, now, n_ips, seed=0, attack_path_every=0):
    rng = np.random.default_rng(seed)
    hosts = ["example.com", "site.org"]
    paths = ["/", "/index.html", "/api/v1/items", "/news/2026"]
    uas = ["Mozilla/5.0 (X11; Linux x86_64)", "curl/8.1", "sqlmap/1.7"]
    out = []
    for i in range(n):
        ip = f"10.{(i % n_ips) >> 16 & 255}.{(i % n_ips) >> 8 & 255}.{i % n_ips & 255}"
        path = paths[rng.integers(len(paths))]
        if attack_path_every and i % attack_path_every == 0:
            path = "/challengeme"
        method = "GET" if rng.random() < 0.8 else "POST"
        out.append(
            f"{now:.6f} {ip} {method} {hosts[i % 2]} {method} {path} "
            f"HTTP/1.1 {uas[rng.integers(len(uas))]} | 200"
        )
    return out


_CONFIG1_CHILD = r"""
import json, sys, time
sys.path.insert(0, sys.argv[1])
from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from tests.mock_banner import MockBanner
from tests.perf.test_baseline_ladder import _access_log_lines

yaml_text = open(sys.argv[2]).read()
cfg = config_from_yaml_text(yaml_text)
m = CpuMatcher(cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates())
now = time.time()
n = int(sys.argv[3])
lines = _access_log_lines(n, now, n_ips=64)
t0 = time.perf_counter()
for line in lines:  # the reference is line-at-a-time by design
    m.consume_line(line, now)
print(json.dumps({"elapsed": time.perf_counter() - t0}))
"""


def test_config1_single_rule_replay_cpu_reference():
    """Config 1: the regex-banner fixture (1 rule) x 10k-line replay through
    the serial CPU reference matcher.  Runs in a FRESH subprocess — the
    measurement must not pay the parent suite's accumulated jit-cache/GC
    pressure (that pressure once halved this floor; isolation is the fix,
    not loosening)."""
    import subprocess
    import sys as _sys

    n = 100_000 if FULL else 10_000
    repo_root = str(Path(__file__).resolve().parents[2])
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [_sys.executable, "-c", _CONFIG1_CHILD, repo_root,
         str(FIXTURES / "banjax-config-test-regex-banner.yaml"), str(n)],
        capture_output=True, text=True, timeout=300, env=env,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    elapsed = json.loads(r.stdout.strip().splitlines()[-1])["elapsed"]
    _report(1, n, elapsed)


DEFAULT_RULESET = """
regexes_with_rates:
  - rule: "All GET requests"
    regex: '^GET'
    interval: 30
    hits_per_interval: 800
    decision: nginx_block
  - rule: "POST flood"
    regex: '^POST'
    interval: 60
    hits_per_interval: 45
    decision: iptables_block
  - rule: "wp-login brute force"
    regex: 'POST [^ ]* POST /wp-login\\.php'
    interval: 300
    hits_per_interval: 10
    decision: iptables_block
  - rule: "xmlrpc"
    regex: '(GET|POST) [^ ]* (GET|POST) /xmlrpc\\.php'
    interval: 300
    hits_per_interval: 10
    decision: iptables_block
  - rule: "env probe"
    regex: '/\\.env'
    interval: 60
    hits_per_interval: 0
    decision: iptables_block
  - rule: "scanner UA"
    regex: '(?i)sqlmap|nikto|nessus'
    interval: 60
    hits_per_interval: 2
    decision: challenge
  - rule: "instant challenge"
    regex: '.*challengeme.*'
    interval: 1
    hits_per_interval: 0
    decision: challenge
"""


def test_config2_default_ruleset_batch():
    """Config 2: a default-banjax-config-shaped ruleset x 1M-line synthetic
    batch (CI-scaled) through the TPU matcher path."""
    m, _ = _make_matcher(DEFAULT_RULESET)
    now = time.time()
    n = 1_000_000 if FULL else 50_000
    lines = _access_log_lines(n, now, n_ips=1024, attack_path_every=997)
    _report(2, n, _drive(m, lines, now))


def test_config3_1k_rules_batch():
    """Config 3: 1k OWASP-CRS-shaped rules x 10M-line batch (CI-scaled) —
    the NFA compile + batch-match stress, via the production TpuMatcher."""
    import yaml as _yaml

    from bench import generate_lines, generate_rules

    patterns = generate_rules(1000)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    m, _ = _make_matcher(rules_yaml, matcher_batch_lines=4096)
    now = time.time()
    n = 200_000 if FULL else 8_192
    rests = generate_lines(n, patterns)
    lines = [f"{now:.6f} 10.0.{i % 256}.{(i >> 8) % 256} {r}"
             for i, r in enumerate(rests)]
    # warm the jit caches before timing (compile time is reported by bench.py)
    m.consume_lines(lines[:256], now)
    _report(3, n, _drive(m, lines, now))


def test_config4_fused_ua_path_100k_ips():
    """Config 4: fused UA+path matching with 100k distinct client IPs
    (CI-scaled to 20k) and device windows on — the eviction-pressure
    scenario of VERDICT weak #7."""
    ua_yaml = DEFAULT_RULESET + """
global_user_agent_decision_lists:
  challenge:
    - 'Mozilla/4\\.[0-9]'
    - scanner
  nginx_block:
    - 'sqlmap|nikto'
"""
    n_ips = 100_000 if FULL else 20_000
    m, _ = _make_matcher(
        ua_yaml, matcher_device_windows=True, matcher_window_capacity=0
    )
    assert m.device_windows is not None
    now = time.time()
    n = 500_000 if FULL else 20_000
    lines = _access_log_lines(n, now, n_ips=n_ips)
    elapsed = _drive(m, lines, now)
    lps = _report(4, n, elapsed)
    # auto-sizing (matcher_window_capacity: 0) must absorb the distinct-IP
    # cardinality without ever evicting — the ladder's north-star config
    # runs at full speed, not in spill/restore mode (VERDICT r3 item 4);
    # eviction-pressure behavior itself is covered by
    # tests/unit/test_device_windows.py with pinned small capacities
    assert m.device_windows.eviction_count == 0, (
        f"auto-sized windows still evicted "
        f"{m.device_windows.eviction_count}x at {n_ips} distinct IPs"
    )
    if n_ips > m.device_windows.AUTO_START_CAPACITY:
        assert m.device_windows.grow_count > 0
        assert m.device_windows.capacity >= n_ips
    # the fused ruleset side: UA patterns ride the same device pass
    from banjax_tpu.decisions.ua_lists import build_ua_rules, check_ua_decision
    from banjax_tpu.matcher.fused import DeviceUAMatcher

    rules = build_ua_rules({
        "challenge": ["Mozilla/4\\.[0-9]", "scanner"],
        "nginx_block": ["sqlmap|nikto"],
    })
    dm = DeviceUAMatcher(rules)
    uas = [l.split(" HTTP/1.1 ")[1].rsplit(" | ", 1)[0] for l in lines[:2048]]
    got = dm.check_batch(uas)
    want = [check_ua_decision(rules, ua) for ua in uas]
    assert got == want


def test_staleness_budget_under_sustained_load():
    """End-to-end staleness (VERDICT r2 item 7): under a sustained stream at
    the matcher's batch size, the per-batch processing latency must stay far
    inside the 10 s stale-line drop window
    (/root/reference/internal/regex_rate_limiter.go:164-167) — otherwise the
    matcher itself would age lines into the drop cutoff and silently
    unprotect the site. Budget: a line waits at most one batch fill + one
    batch processing; we assert the slowest observed batch stays under 25 %
    of the window, leaving the rest for fill/queueing headroom."""
    batch = 2048
    m, _ = _make_matcher(DEFAULT_RULESET, matcher_batch_lines=batch,
                         matcher_device_windows=True)
    now = time.time()
    n_batches = 8 if not FULL else 40
    lines = _access_log_lines(batch, now, n_ips=2048, attack_path_every=499)
    # warm at the FULL batch shape: jit programs key on the bucketed batch
    # size, so a smaller warm-up would leave the first measured batch
    # paying the one-time compiles (which are startup, not staleness)
    m.consume_lines(lines, now)
    worst = 0.0
    for i in range(n_batches):
        t0 = time.perf_counter()
        m.consume_lines(lines, now + i)
        worst = max(worst, time.perf_counter() - t0)
    print(json.dumps({"staleness_worst_batch_s": round(worst, 3)}))
    assert worst < 0.25 * 10.0, (
        f"worst batch {worst:.2f}s eats >25% of the 10s staleness window"
    )


def test_config5_kafka_fed_stream_device_windows():
    """Config 5: log lines streamed through a live Kafka broker socket into
    the matcher with device windows; Decisions emit through the Banner."""
    from banjax_tpu.ingest.kafka_wire import WireKafkaTransport
    from tests.fake_kafka_broker import FakeKafkaBroker

    broker = FakeKafkaBroker(mode="modern").start()
    try:
        m, banner = _make_matcher(
            DEFAULT_RULESET, matcher_device_windows=True
        )
        cfg = config_from_yaml_text(
            f"kafka_brokers:\n  - 127.0.0.1:{broker.port}\n"
            "kafka_command_topic: lines\nkafka_max_wait_ms: 50\n"
        )
        now = time.time()
        n = 200_000 if FULL else 10_000
        lines = _access_log_lines(n, now, n_ips=512, attack_path_every=499)
        batch = 2048
        tx = WireKafkaTransport()
        it = tx.read_messages(cfg, "lines", 0)
        for start in range(0, n, batch):
            broker.append(
                "lines", 0, "\n".join(lines[start : start + batch]).encode()
            )
        consumed = 0
        t0 = time.perf_counter()
        while consumed < n:
            chunk = next(it).decode().split("\n")
            m.consume_lines(chunk, now)
            consumed += len(chunk)
        elapsed = time.perf_counter() - t0
        tx.close()
        _report(5, n, elapsed)
        assert banner.bans  # decisions actually emitted
    finally:
        broker.stop()
