"""The reference's OWN headline benchmark harnesses, mirrored.

/root/reference/banjax_performance_test.go:18-31 (BenchmarkAuthRequest) and
:33-67 (BenchmarkProtectedPaths) drive the real HTTP server: b.N GETs of
/auth_request with a random client IP, and a 12-path-variant protected-path
classification loop. The reference records no numbers (BASELINE.md) — CI
runs the harness as a smoke; here each prints a requests/sec JSON line and
asserts a conservative floor so a server-path perf regression fails CI.
"""

import json
import random
import time

import pytest
import requests

BASE = "http://localhost:8081"

# requests/sec floors on a 1-core CI box driving via python-requests (the
# client itself costs ~1 ms/req; the reference's Go harness records nothing
# to compare against, so the floor only guards OUR regressions)
AUTH_FLOOR_RPS = 150
PROTECTED_FLOOR_RPS = 150


@pytest.fixture()
def app(app_factory):
    return app_factory("banjax-config-test.yaml")


def _rand_ip(rng):
    return f"{rng.randint(1, 251)}.{rng.randint(0, 255)}.{rng.randint(0, 255)}.{rng.randint(1, 254)}"


def test_benchmark_auth_request(app):
    """BenchmarkAuthRequest (banjax_performance_test.go:18-31): sustained
    GET /auth_request with a random X-Client-IP per request."""
    rng = random.Random(9)
    s = requests.Session()
    for _ in range(20):  # warm
        s.get(f"{BASE}/auth_request",
              headers={"X-Client-IP": _rand_ip(rng)}, timeout=5)
    n = 300
    t0 = time.perf_counter()
    for _ in range(n):
        r = s.get(f"{BASE}/auth_request",
                  headers={"X-Client-IP": _rand_ip(rng)}, timeout=5)
        assert r.status_code in (200, 429, 403)
    rps = n / (time.perf_counter() - t0)
    print(json.dumps({"benchmark": "auth_request", "rps": round(rps, 1)}))
    assert rps >= AUTH_FLOOR_RPS


def test_benchmark_protected_paths(app):
    """BenchmarkProtectedPaths (banjax_performance_test.go:33-67): the 12
    protected/exception path variants, classified per iteration."""
    rng = random.Random(10)
    paths = [
        "wp-admin", "/wp-admin", "/wp-admin//", "wp-admin/admin.php",
        "wp-admin/admin.php#test", "wp-admin/admin.php?a=1&b=2",
        "wp-admin/admin-ajax.php", "/wp-admin/admin-ajax.php",
        "/wp-admin/admin-ajax.php?a=1", "/wp-admin/admin-ajax.php?a=1&b=2",
        "/wp-admin/admin-ajax.php#test", "wp-admin/admin-ajax.php/",
    ]
    s = requests.Session()
    for p in paths:  # warm
        s.get(f"{BASE}/auth_request", params={"path": p},
              headers={"X-Client-IP": _rand_ip(rng)}, timeout=5)
    iters = 25
    t0 = time.perf_counter()
    for _ in range(iters):
        for p in paths:
            r = s.get(f"{BASE}/auth_request", params={"path": p},
                      headers={"X-Client-IP": _rand_ip(rng)}, timeout=5)
            assert r.status_code in (200, 401, 429)
    rps = iters * len(paths) / (time.perf_counter() - t0)
    print(json.dumps({"benchmark": "protected_paths", "rps": round(rps, 1)}))
    assert rps >= PROTECTED_FLOOR_RPS
