"""The reference's OWN headline benchmark harnesses, mirrored.

/root/reference/banjax_performance_test.go:18-31 (BenchmarkAuthRequest) and
:33-67 (BenchmarkProtectedPaths) drive the real HTTP server: b.N GETs of
/auth_request with a random client IP, and a 12-path-variant protected-path
classification loop. The reference records no numbers (BASELINE.md) — CI
runs the harness as a smoke; here each prints a requests/sec JSON line and
asserts a conservative floor so a server-path perf regression fails CI.
"""

import asyncio
import json
import os
import random
import time

import pytest

BASE = "http://localhost:8081"

# serial requests/sec floors on a 1-core CI box driving via http.client
# keepalive (~3-4.5k measured; the reference's Go harness records nothing
# to compare against, so the floors only guard OUR regressions — set at
# ~1/4 of measured for full-suite/CI-box pressure)
AUTH_FLOOR_RPS = 800
PROTECTED_FLOOR_RPS = 700
# server-capacity floor: concurrent raw-socket keepalive client (~30
# us/req of client cost) — the number comparable to driving the
# reference's Go server with its Go client. fastserve measures 5.6-7.6k
# on the 1-core build box (client sharing the core); 2k still fails on
# any fast-path regression while leaving ~3x for CI noise
CAPACITY_FLOOR_RPS = 2_000


async def _capacity_worker(n: int, results: list, rand_ip) -> None:
    reader, writer = await asyncio.open_connection("127.0.0.1", 8081)
    for _ in range(n):
        writer.write(
            (
                f"GET /auth_request HTTP/1.1\r\nHost: localhost\r\n"
                f"X-Client-IP: {rand_ip()}\r\nConnection: keep-alive\r\n\r\n"
            ).encode()
        )
        await writer.drain()
        hdr = await reader.readuntil(b"\r\n\r\n")
        clen = 0
        for line in hdr.split(b"\r\n"):
            if line.lower().startswith(b"content-length:"):
                clen = int(line.split(b":")[1])
        if clen:
            await reader.readexactly(clen)
        results[0] += 1
    writer.close()


def measure_capacity(n_per_conn: int = 400, conc: int = 16,
                     seed: int = 11) -> float:
    """Sustained /auth_request throughput with a cheap concurrent client
    (the serial http.client mirrors above measure latency, not
    capacity)."""
    rng = random.Random(seed)

    def rand_ip():
        return (
            f"{rng.randint(1, 251)}.{rng.randint(0, 255)}"
            f".{rng.randint(0, 255)}.{rng.randint(1, 254)}"
        )

    async def run() -> float:
        results = [0]
        t0 = time.perf_counter()
        await asyncio.gather(
            *[_capacity_worker(n_per_conn, results, rand_ip) for _ in range(conc)]
        )
        return results[0] / (time.perf_counter() - t0)

    return asyncio.run(run())


@pytest.fixture()
def app(app_factory):
    return app_factory("banjax-config-test.yaml")


def _rand_ip(rng):
    return f"{rng.randint(1, 251)}.{rng.randint(0, 255)}.{rng.randint(0, 255)}.{rng.randint(1, 254)}"


def _serial_get(conn, path, ip):
    """One request over a kept-alive http.client connection — the closest
    Python analogue of the reference harness's Go http.Client (~50 us of
    client cost, vs python-requests' ~1 ms which hid the server behind
    the client on a shared core)."""
    conn.request("GET", path, headers={"X-Client-IP": ip})
    r = conn.getresponse()
    r.read()
    return r.status


def test_benchmark_auth_request(app):
    """BenchmarkAuthRequest (banjax_performance_test.go:18-31): sustained
    serial GET /auth_request with a random X-Client-IP per request."""
    import http.client

    rng = random.Random(9)
    conn = http.client.HTTPConnection("localhost", 8081, timeout=5)
    for _ in range(20):  # warm
        _serial_get(conn, "/auth_request", _rand_ip(rng))
    n = 600
    t0 = time.perf_counter()
    for _ in range(n):
        status = _serial_get(conn, "/auth_request", _rand_ip(rng))
        assert status in (200, 429, 403)
    rps = n / (time.perf_counter() - t0)
    conn.close()
    print(json.dumps({"benchmark": "auth_request", "rps": round(rps, 1)}))
    assert rps >= AUTH_FLOOR_RPS


def test_benchmark_protected_paths(app):
    """BenchmarkProtectedPaths (banjax_performance_test.go:33-67): the 12
    protected/exception path variants, classified per iteration."""
    rng = random.Random(10)
    paths = [
        "wp-admin", "/wp-admin", "/wp-admin//", "wp-admin/admin.php",
        "wp-admin/admin.php#test", "wp-admin/admin.php?a=1&b=2",
        "wp-admin/admin-ajax.php", "/wp-admin/admin-ajax.php",
        "/wp-admin/admin-ajax.php?a=1", "/wp-admin/admin-ajax.php?a=1&b=2",
        "/wp-admin/admin-ajax.php#test", "wp-admin/admin-ajax.php/",
    ]
    import http.client
    from urllib.parse import quote

    conn = http.client.HTTPConnection("localhost", 8081, timeout=5)
    targets = [f"/auth_request?path={quote(p, safe='')}" for p in paths]
    for t in targets:  # warm
        _serial_get(conn, t, _rand_ip(rng))
    iters = 40
    t0 = time.perf_counter()
    for _ in range(iters):
        for t in targets:
            status = _serial_get(conn, t, _rand_ip(rng))
            assert status in (200, 401, 429)
    rps = iters * len(paths) / (time.perf_counter() - t0)
    conn.close()
    print(json.dumps({"benchmark": "protected_paths", "rps": round(rps, 1)}))
    assert rps >= PROTECTED_FLOOR_RPS


def test_benchmark_auth_request_capacity(app):
    """Server capacity (single process): the concurrent keepalive client
    measures the handler path itself, not the python-requests client."""
    measure_capacity(n_per_conn=40, conc=8)  # warm
    rps = measure_capacity()
    print(json.dumps({
        "benchmark": "auth_request_capacity", "rps": round(rps, 1),
        "http_workers": 0, "cpu_count": os.cpu_count(),
    }))
    assert rps >= CAPACITY_FLOOR_RPS


@pytest.mark.skipif((os.cpu_count() or 1) < 2,
                    reason="SO_REUSEPORT workers need >1 core to scale")
def test_benchmark_auth_request_capacity_workers(app_factory, tmp_path):
    """Server capacity in multi-worker mode (httpapi/workers.py):
    http_workers = cpu count, one SO_REUSEPORT process per core."""
    from pathlib import Path

    n_workers = os.cpu_count()
    fixtures = Path(__file__).resolve().parent.parent / "fixtures"
    custom = tmp_path / "banjax-config-workers.yaml"
    custom.write_text(
        (fixtures / "banjax-config-test.yaml").read_text()
        + f"\nhttp_workers: {n_workers}\n"
    )
    # app_factory joins against the fixtures dir; an absolute path wins
    app_factory(str(custom))
    time.sleep(2.0)  # let workers bind
    measure_capacity(n_per_conn=40, conc=8)  # warm
    rps = measure_capacity(conc=32)
    print(json.dumps({
        "benchmark": "auth_request_capacity_workers", "rps": round(rps, 1),
        "http_workers": n_workers, "cpu_count": os.cpu_count(),
    }))
    assert rps >= CAPACITY_FLOOR_RPS
