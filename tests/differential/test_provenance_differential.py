"""Differential proof of zero behavior change from the attribution
layer: a pipelined run with the provenance ledger, the SLO engine, and
the flight recorder all enabled produces byte-identical ban-log /
result-stream / window-state output to a run with all three disabled —
the ledger is passive by construction (ISSUE 6 acceptance)."""

import random
import threading
import time

import pytest

from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs import flightrec, provenance, trace
from banjax_tpu.obs.flightrec import FlightRecorder
from banjax_tpu.obs.slo import SloEngine
from banjax_tpu.pipeline import PipelineScheduler
from tests.differential.test_pipeline_differential import (
    ChurnSizer,
    _build,
    _gen_lines,
)
from tests.differential.test_tpu_matcher import result_key


@pytest.fixture(autouse=True)
def _obs_reset_after():
    yield
    provenance.configure(enabled=True)
    flightrec.install(None)
    trace.configure(enabled=False)


def _run_pipelined(lines, now, device_windows, seed, obs_on, tmp_path):
    matcher, states, dyn, ban_log = _build(TpuMatcher, device_windows)
    engine = None
    if obs_on:
        provenance.configure(enabled=True, ring_size=8192)
        engine = SloEngine(
            matcher_getter=lambda: matcher,
            pipeline_getter=lambda: sched,  # noqa: F821 — bound below
            batch_budget_s_fn=lambda: 0.25,
        )
        flightrec.install(FlightRecorder(
            str(tmp_path / f"inc-{seed}"), min_interval_s=0.0,
            slo_getter=lambda: engine,
        ))
    else:
        provenance.configure(enabled=False)
        flightrec.install(None)

    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: matcher, on_results=sink, now_fn=lambda: now
    )
    sched._sizer = ChurnSizer(seed=seed)
    sched.start()
    rng = random.Random(31)
    i = 0
    n_sampled = 0
    while i < len(lines):
        step = rng.randrange(1, 90)
        sched.submit(lines[i : i + step])
        i += step
        if engine is not None and i // 400 > n_sampled:
            n_sampled += 1
            engine.sample()  # live sampling mid-stream, like production
    assert sched.flush(120)
    if engine is not None:
        engine.sample()
    sched.stop()
    matcher.close()
    results = {}
    for batch_lines, batch_results in collected:
        if batch_results is None:
            continue
        for line, res in zip(batch_lines, batch_results):
            results.setdefault(line, []).append(result_key(res))
    return results, ban_log.getvalue(), states.format_states()


@pytest.mark.parametrize("device_windows", [False, True])
def test_provenance_slo_flightrec_on_off_byte_identical(
    device_windows, tmp_path
):
    now = time.time()
    lines = _gen_lines(1200, now)

    off_results, off_log, off_states = _run_pipelined(
        lines, now, device_windows, seed=7, obs_on=False, tmp_path=tmp_path
    )
    on_results, on_log, on_states = _run_pipelined(
        lines, now, device_windows, seed=7, obs_on=True, tmp_path=tmp_path
    )
    assert on_log == off_log          # ban-log bytes identical
    assert on_results == off_results  # per-line result stream identical
    assert on_states == off_states    # rate-limit window state identical
    # ... and the enabled run actually ledgered the bans it fired
    assert provenance.get_ledger().total_records() > 0
    banned_ips = {
        (rec["ip"], rec["rule"])
        for src in ("rate_limit",)
        for rec in provenance.get_ledger().tail(10_000)
        if rec["source"] == src
    }
    assert banned_ips, "no rate-limit provenance recorded on the on-run"
