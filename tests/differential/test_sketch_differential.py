"""Differential proof that the traffic sketch is read-only telemetry:
a pipelined run with the sketch enabled produces byte-identical
ban-log / result-stream / window-state output to a run with it
disabled, under adversarial batch churn, on BOTH fused device
protocols — and the enabled run actually populated the sketch (the
non-vacuity witness, ISSUE 8)."""

import io
import random
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from tests.differential.test_pipeline_differential import (
    ChurnSizer,
    _gen_lines,
)
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key


def _build(sketch_on: bool, single_kernel: bool):
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = True
    config.traffic_sketch_enabled = sketch_on
    config.pallas_single_kernel = "auto" if single_kernel else "off"
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    matcher = TpuMatcher(
        config, banner, StaticDecisionLists(config), states
    )
    return matcher, states, ban_log


def _run_pipelined(lines, now, seed, sketch_on, single_kernel):
    matcher, states, ban_log = _build(sketch_on, single_kernel)
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: matcher, on_results=sink, now_fn=lambda: now
    )
    sched._sizer = ChurnSizer(seed=seed)
    sched.start()
    rng = random.Random(23)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, 90)
        sched.submit(lines[i : i + step])
        i += step
    assert sched.flush(120)
    sched.stop()
    sketch = matcher.traffic_sketch
    # the authoritative window state with device windows on is the
    # device-backed shadow, not the bypassed host RegexRateLimitStates
    dw_states = matcher.device_windows.format_states()
    matcher.close()
    results = {}
    for batch_lines, batch_results in collected:
        if batch_results is None:
            continue
        for line, res in zip(batch_lines, batch_results):
            results.setdefault(line, []).append(result_key(res))
    return results, ban_log.getvalue(), dw_states, sketch


@pytest.mark.parametrize("single_kernel", [True, False])
def test_sketch_on_off_byte_identical(single_kernel):
    """Both fused device protocols: single-kernel (commit at submit —
    where the sketch update rides) and the two-program oracle path."""
    now = time.time()
    lines = _gen_lines(1200, now)

    off_results, off_log, off_states, off_sketch = _run_pipelined(
        lines, now, seed=13, sketch_on=False, single_kernel=single_kernel
    )
    assert off_sketch is None

    on_results, on_log, on_states, on_sketch = _run_pipelined(
        lines, now, seed=13, sketch_on=True, single_kernel=single_kernel
    )

    assert on_log == off_log          # ban-log bytes identical
    assert on_results == off_results  # per-line result stream identical
    assert on_states == off_states    # rate-limit window state identical

    # non-vacuity: the enabled run folded real traffic and can name a
    # heavy hitter with a conservative estimate
    assert on_sketch is not None
    assert on_sketch.lines_total > 0
    summary = on_sketch.pull(force=True)
    assert summary["top"], "sketch saw traffic but has no heavy hitters"
    assert summary["distinct_ips_estimate"] > 0
