"""Differential proofs for the mega-state tiering (README "Mega-state
tiering"): the warm tier is a lossless state home and the slot-admission
gate never changes WHAT gets banned, on BOTH fused device protocols.

  * admission OFF + warm tier ON is byte-identical to the ungated
    engine (same ban-log bytes, same per-line result stream, same final
    per-IP window states) under eviction churn that actually spills;
  * admission ON preserves the ban multiset AND every per-IP ban
    sequence exactly.  Stronger than the ISSUE's bounded-delay floor:
    a refused row that matches a rule still steps the same window math
    host-side (apply_host_events), so per-IP ban TIMING is identical
    too — only cross-IP interleaving may differ (refused rows of a
    batch replay before admitted rows);
  * the gated run is non-vacuous: rows were refused, refused-IP state
    went warm, and a warm IP that came back was admitted by refill.

CONFIG_YAML's cheapest rule has hits_per_interval 0, so the DERIVED
admission threshold would be 1 (admit everything): these tests pin
slot_admission_min_estimate explicitly to exercise real refusals.
"""

import io
import random
import threading
import time
from collections import Counter

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from tests.differential.test_pipeline_differential import ChurnSizer
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key

MIN_EST = 4      # explicit gate threshold (see module docstring)
CAPACITY = 64    # small hot tier => real eviction churn at this scale


def _gen_tier_lines(n, now, seed):
    """The full gate surface: a long tail of DISTINCT one-shot IPs whose
    single row MATCHES rule1 (refused when gated, and their window state
    must therefore live in the warm tier), warm repeaters that cross the
    threshold mid-stream, hot offenders, instant per-site blocks on
    first-ever rows, the allowlisted IP, garbage, and stale lines."""
    rng = random.Random(seed)
    lines = []
    for i in range(n):
        kind = rng.random()
        if kind < 0.40:   # distinct cold IPs, one matching row each
            ip = f"21.{(i >> 16) & 0xFF}.{(i >> 8) & 0xFF}.{i & 0xFF}"
            lines.append(
                f"{now:f} {ip} GET example.com GET /cold{i} HTTP/1.1 ua -"
            )
        elif kind < 0.55:  # warm repeaters: a few rows each, some ban
            ip = f"22.0.0.{rng.randrange(40)}"
            lines.append(
                f"{now:f} {ip} GET example.com GET /warm HTTP/1.1 ua -"
            )
        elif kind < 0.65:  # hot offenders: ban over and over
            ip = f"23.0.0.{rng.randrange(4)}"
            lines.append(
                f"{now:f} {ip} GET example.com GET /hot HTTP/1.1 ua -"
            )
        elif kind < 0.71:  # rule2 (hits 1): second POST in window bans
            ip = f"24.0.0.{rng.randrange(6)}"
            lines.append(
                f"{now:f} {ip} POST example.com POST /s HTTP/1.1 ua -"
            )
        elif kind < 0.76:  # instant per-site block on a FIRST-EVER row:
            #                the refused path must fire it on that row
            ip = f"25.{(i >> 8) & 0xFF}.0.{i & 0xFF}"
            lines.append(
                f"{now:f} {ip} GET per-site.com GET /blockme HTTP/1.1 ua -"
            )
        elif kind < 0.80:
            lines.append(
                f"{now:f} 12.12.12.12 GET example.com GET /a HTTP/1.1 ua -"
            )
        elif kind < 0.84:
            lines.append("short garbage")
        elif kind < 0.87:
            ip = f"26.0.0.{rng.randrange(9)}"
            lines.append(
                f"{now - 100:f} {ip} GET example.com GET /old HTTP/1.1 ua -"
            )
        else:             # distinct, matches nothing
            ip = f"27.{(i >> 8) & 0xFF}.0.{i & 0xFF}"
            lines.append(
                f"{now:f} {ip} GET news.net GET /benign HTTP/1.1 ua -"
            )
    return lines


def _build(admission, warm, single_kernel):
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = True
    config.matcher_window_capacity = CAPACITY
    config.traffic_sketch_enabled = True
    config.slot_admission_enabled = admission
    config.slot_admission_min_estimate = MIN_EST
    config.warm_tier_enabled = warm
    config.warm_tier_capacity = 4096
    config.pallas_single_kernel = "auto" if single_kernel else "off"
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    matcher = TpuMatcher(
        config, banner, StaticDecisionLists(config), states
    )
    return matcher, ban_log


def _run_pipelined(lines, now, seed, admission, warm, single_kernel):
    matcher, ban_log = _build(admission, warm, single_kernel)
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: matcher, on_results=sink, now_fn=lambda: now
    )
    sched._sizer = ChurnSizer(seed=seed)
    sched.start()
    rng = random.Random(29)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, 90)
        sched.submit(lines[i: i + step])
        i += step
    assert sched.flush(120)
    sched.stop()
    dw = matcher.device_windows
    stats = {
        "refusals": dw.slot_refusals,
        "admissions": dw.sketch_admissions,
        "spills": dw.warm_spills,
        "refills": dw.warm_refills,
        "dropped": dw.warm_dropped,
        "states": dw.format_states(),
    }
    matcher.close()
    results = {}
    for batch_lines, batch_results in collected:
        if batch_results is None:
            continue
        for line, res in zip(batch_lines, batch_results):
            results.setdefault(line, []).append(result_key(res))
    return results, ban_log.getvalue(), stats


def _parse_states(text):
    """format_states -> {ip: {rule: state-line}}, order-insensitive: the
    same IP's state may be shadow-resident in one run and warm-resident
    in the other, which permutes the rendering order but must never
    change a single (ip, rule) vector."""
    out = {}
    ip = rule = None
    for line in text.splitlines():
        if not line:
            continue
        if not line.startswith("\t"):
            ip = line.rstrip(":")
            out[ip] = {}
        elif not line.startswith("\t\t"):
            rule = line.strip().rstrip(":")
        else:
            out[ip][rule] = line.strip()
    return out


def _per_ip_bans(log_text):
    out = {}
    for ln in log_text.splitlines():
        parts = ln.split()
        # banjax-format: "<ts>, <ip>, matched ..." — key on the ip token
        ip = parts[1].rstrip(",") if len(parts) > 1 else ln
        out.setdefault(ip, []).append(ln)
    return out


@pytest.mark.parametrize("single_kernel", [True, False])
def test_warm_tier_byte_identical_under_eviction_churn(single_kernel):
    """Admission OFF both sides; warm tier OFF vs ON.  Eviction churn
    (CAPACITY 64 << distinct IPs) spills real state into the warm tier,
    and nothing observable may move: ban-log bytes, per-line results,
    final per-IP window states."""
    now = time.time()
    lines = _gen_tier_lines(1500, now, seed=3)

    off_results, off_log, off_stats = _run_pipelined(
        lines, now, 13, admission=False, warm=False,
        single_kernel=single_kernel,
    )
    on_results, on_log, on_stats = _run_pipelined(
        lines, now, 13, admission=False, warm=True,
        single_kernel=single_kernel,
    )

    assert on_log == off_log            # identical processing order =>
    assert on_results == off_results    # byte-identical everything
    assert _parse_states(on_stats["states"]) == _parse_states(
        off_stats["states"]
    )
    # non-vacuity: the warm run actually spilled and refilled
    assert on_stats["spills"] > 0
    assert on_stats["refills"] > 0
    assert on_stats["dropped"] == 0


@pytest.mark.parametrize("single_kernel", [True, False])
def test_admission_on_preserves_ban_multiset_and_per_ip_order(
    single_kernel,
):
    """Admission ON vs OFF (warm tier on for both): the ban multiset,
    every per-IP ban sequence, the per-line result stream, and the final
    per-IP window states are all identical — the gate only reorders
    cross-IP processing inside a batch, it never changes an outcome or
    delays a ban for a row that reached the engine."""
    now = time.time()
    lines = _gen_tier_lines(1500, now, seed=5)

    off_results, off_log, off_stats = _run_pipelined(
        lines, now, 17, admission=False, warm=True,
        single_kernel=single_kernel,
    )
    on_results, on_log, on_stats = _run_pipelined(
        lines, now, 17, admission=True, warm=True,
        single_kernel=single_kernel,
    )

    assert Counter(on_log.splitlines()) == Counter(off_log.splitlines())
    assert _per_ip_bans(on_log) == _per_ip_bans(off_log)
    assert on_results == off_results
    assert _parse_states(on_stats["states"]) == _parse_states(
        off_stats["states"]
    )
    # non-vacuity: the gate refused rows, refused state went warm, and
    # returning warm IPs were admitted by refill
    assert on_stats["refusals"] > 0
    assert on_stats["spills"] > 0
    assert on_stats["refills"] > 0
    assert off_stats["refusals"] == 0
