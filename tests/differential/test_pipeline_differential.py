"""Pipelined vs synchronous path: byte-identical outputs under random
batch-size churn (the tentpole's ordering contract).

The pipelined scheduler must produce, for the same line stream:
  * the identical ConsumeLineResult stream, in admission order;
  * byte-identical ban-log lines (the real Banner writing to in-memory
    files, not just the mock's tuples);
  * identical dynamic-list decisions and rate-limit window state —
even while the adaptive sizer is replaced with an adversarial one that
picks a random batch size per take, so batch boundaries land everywhere.
"""

import io
import random
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.pipeline.sizer import AdaptiveBatchSizer
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key
from tests.mock_banner import MockBanner


class ChurnSizer(AdaptiveBatchSizer):
    """Adversarial sizing: a random power-of-two-ish target per take, so
    batch boundaries fall at every possible offset of the stream."""

    def __init__(self, seed: int):
        super().__init__(budget_ms=1000.0)
        self._rng = random.Random(seed)

    def target(self) -> int:
        return self._rng.choice([1, 2, 3, 5, 8, 13, 32, 64, 100, 256])


def _gen_lines(n, now, seed=5):
    rng = random.Random(seed)
    lines = []
    for i in range(n):
        kind = rng.random()
        ip = f"1.2.{rng.randrange(4)}.{rng.randrange(6)}"
        if kind < 0.08:
            lines.append(f"{now:f} {ip} POST example.com POST /submit HTTP/1.1 ua -")
        elif kind < 0.3:
            lines.append(f"{now:f} {ip} GET example.com GET /page{i % 7} HTTP/1.1 ua -")
        elif kind < 0.38:
            lines.append(f"{now:f} {ip} GET per-site.com GET /blockme HTTP/1.1 ua -")
        elif kind < 0.45:
            lines.append(f"{now:f} {ip} DELETE skipme.com DELETE /x HTTP/1.1 ua -")
        elif kind < 0.5:
            lines.append(f"{now:f} 12.12.12.12 GET example.com GET /allowed HTTP/1.1 ua -")
        elif kind < 0.54:
            lines.append("short garbage")
        elif kind < 0.58:
            lines.append(f"{now - 100:f} {ip} GET example.com GET /old HTTP/1.1 ua -")
        else:
            lines.append(f"{now:f} {ip} GET news.net GET /benign/{i % 11} HTTP/1.1 ua -")
    return lines


def _build(matcher_cls, device_windows=False):
    """One matcher over its own fresh state with the REAL Banner writing
    ban logs into StringIO — the byte-identical comparison surface."""
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = device_windows
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    ban_log_temp = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, ban_log_temp, ipset_instance=None)
    matcher = matcher_cls(config, banner, StaticDecisionLists(config), states)
    return matcher, states, dyn, ban_log


@pytest.mark.parametrize("device_windows", [False, True])
def test_pipelined_stream_is_byte_identical_to_sync(device_windows):
    now = time.time()
    lines = _gen_lines(1500, now)

    # oracle 1: the CPU reference, line at a time
    cpu, cpu_states, cpu_dyn, cpu_log = _build(CpuMatcher)
    cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]

    # oracle 2: the synchronous TPU batch path
    sync, sync_states, sync_dyn, sync_log = _build(TpuMatcher, device_windows)
    sync_results = sync.consume_lines(lines, now_unix=now)

    # the pipelined path with adversarial batch churn
    pipe, pipe_states, pipe_dyn, pipe_log = _build(TpuMatcher, device_windows)
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: pipe, on_results=sink, now_fn=lambda: now
    )
    sched._sizer = ChurnSizer(seed=99)
    sched.start()
    rng = random.Random(17)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, 120)
        sched.submit(lines[i : i + step])
        i += step
    assert sched.flush(120)
    sched.stop()

    pipe_lines = [l for ls, _ in collected for l in ls]
    pipe_results = [r for _, rs in collected for r in rs]
    assert pipe_lines == lines, "admission order broken across batches"
    assert len(pipe_results) == len(lines)

    for i, (c, s, p) in enumerate(
        zip(cpu_results, sync_results, pipe_results)
    ):
        assert result_key(c) == result_key(s), f"sync diverged at line {i}"
        assert result_key(c) == result_key(p), f"pipeline diverged at line {i}"

    # ban-log BYTES and dynamic-list decisions, against both oracles
    assert pipe_log.getvalue() == cpu_log.getvalue()
    assert pipe_log.getvalue() == sync_log.getvalue()
    assert pipe_dyn.metrics() == cpu_dyn.metrics()

    # rate-limit window state (host dict or device counters)
    cpu_view = cpu_states.format_states()
    sync_view = (
        sync.device_windows if device_windows else sync_states
    ).format_states()
    pipe_view = (
        pipe.device_windows if device_windows else pipe_states
    ).format_states()
    assert cpu_view == sync_view == pipe_view

    # nothing shed, nothing stale in a fixed-now run
    snap = sched.snapshot()
    assert snap["PipelineShedLines"] == 0
    assert snap["PipelineStaleDroppedLines"] == 0
    assert snap["PipelineProcessedLines"] == len(lines)


def test_repeated_streams_accumulate_identically():
    """Window state spans batches and streams: feeding the same stream
    twice through the pipeline must equal feeding it twice synchronously
    (exceeded-counters keep counting, in order)."""
    now = time.time()
    lines = _gen_lines(400, now, seed=23)

    sync, sync_states, _, sync_log = _build(TpuMatcher)
    sync.consume_lines(lines, now_unix=now)
    sync.consume_lines(lines, now_unix=now)

    pipe, pipe_states, _, pipe_log = _build(TpuMatcher)
    sched = PipelineScheduler(lambda: pipe, now_fn=lambda: now)
    sched._sizer = ChurnSizer(seed=3)
    sched.start()
    for _ in range(2):
        for i in range(0, len(lines), 37):
            sched.submit(lines[i : i + 37])
    assert sched.flush(120)
    sched.stop()

    assert pipe_log.getvalue() == sync_log.getvalue()
    assert pipe_states.format_states() == sync_states.format_states()
