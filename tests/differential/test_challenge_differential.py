"""Differential proofs for the challenge plane (README "Challenge
plane"): the device-batched PoW verifier is byte-identical to the pure
CPU reference on the full request surface.

  * the SAME scripted request stream — solved cookies, under-target
    solutions, expired cookies, torn cookies, wrong-binding cookies,
    cookieless hits — run once with the device verifier (Pallas sha256
    kernel, interpret mode) and once with device=None must produce the
    identical per-request (status, result, exceeded) stream AND
    byte-identical ban-log lines from the REAL effectors Banner;
  * the bounded failure state (challenge/failures.py) slotted in for the
    reference's unbounded dict changes nothing on the same stream;
  * verify_sha_inv raises the reference's exact CookieError text for
    every reject, device or not (the crypto oracle is
    validate_sha_inv_cookie itself);
  * a breaker trip mid-stream (challenge.device_verify fault) degrades
    to CPU without changing a single decision or ban-log byte.

Ban-time formatting is pinned (monkeypatched) so byte comparison is
about content, not the wall clock second the line landed on.
"""

import dataclasses
import io
import random
import time

import pytest

from banjax_tpu.challenge.failures import make_failed_challenge_states
from banjax_tpu.challenge.verifier import DeviceVerifier, cpu_zero_bits, verify_sha_inv
from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.crypto.challenge import (
    CookieError,
    new_challenge_cookie_at,
    solve_challenge_for_testing,
    validate_sha_inv_cookie,
)
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import FailAction
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    RequestInfo,
    send_or_validate_sha_challenge,
)
from banjax_tpu.httpapi.rewrite import CHALLENGE_COOKIE_NAME
from banjax_tpu.resilience import failpoints

SECRET = "differential-secret"
ZERO_BITS = 8          # cheap deterministic solves (~256 hashes each)
THRESHOLD = 2
HOST = "diff.example"

CONFIG_YAML = f"""
regexes_with_rates: []
too_many_failed_challenges_interval_seconds: 120
too_many_failed_challenges_threshold: {THRESHOLD}
sha_inv_cookie_ttl_seconds: 300
sha_inv_expected_zero_bits: {ZERO_BITS}
hmac_secret: {SECRET}
disable_kafka: true
"""


@pytest.fixture(autouse=True)
def _pin_ban_time(monkeypatch):
    """Both runs of a differential must serialize the same timestring;
    the comparison is about content, not which second each run ran in."""
    monkeypatch.setattr(
        "banjax_tpu.effectors.banner._format_ban_time",
        lambda unix_seconds: "2026-01-01T00:00:00",
    )
    failpoints.disarm()
    yield
    failpoints.disarm()


def _solve_below_target(cookie: str) -> str:
    """First brute-force counter whose hash has FEWER leading zero bits
    than the target — the not-enough-zero-bits reject, deterministically."""
    import base64

    raw = bytearray(base64.standard_b64decode(cookie))
    for counter in range(1 << 20):
        raw[44:52] = counter.to_bytes(8, "big")
        if cpu_zero_bits(bytes(raw[0:52])) < ZERO_BITS:
            return base64.standard_b64encode(bytes(raw)).decode()
    raise AssertionError("no under-target solution found")


def _scripted_requests(seed: int, n_clients: int = 24):
    """The full reject surface as one interleaved request stream.
    Failing clients repeat past the ban threshold so the ban-log (the
    byte-identity target) is non-vacuous."""
    rng = random.Random(seed)
    now = int(time.time())
    kinds = ("solved", "under_target", "expired", "torn",
             "wrong_binding", "no_cookie")
    stream = []
    for k in range(n_clients):
        ip = f"77.0.{k >> 8}.{k & 0xFF}"
        kind = kinds[k % len(kinds)]
        fresh = new_challenge_cookie_at(SECRET, now + 300, ip)
        if kind == "solved":
            cookie = solve_challenge_for_testing(fresh, ZERO_BITS)
            repeats = 1
        elif kind == "under_target":
            cookie = _solve_below_target(fresh)
            repeats = THRESHOLD + 1
        elif kind == "expired":
            stale = new_challenge_cookie_at(SECRET, now - 10, ip)
            cookie = solve_challenge_for_testing(stale, ZERO_BITS)
            repeats = THRESHOLD + 1
        elif kind == "torn":
            cookie = solve_challenge_for_testing(fresh, ZERO_BITS)[:40]
            repeats = THRESHOLD + 1
        elif kind == "wrong_binding":
            other = new_challenge_cookie_at(SECRET, now + 300, "8.8.8.8")
            cookie = solve_challenge_for_testing(other, ZERO_BITS)
            repeats = THRESHOLD + 1
        else:  # no_cookie
            cookie = None
            repeats = THRESHOLD + 1
        for _ in range(repeats):
            cookies = {} if cookie is None else {CHALLENGE_COOKIE_NAME: cookie}
            stream.append(RequestInfo(
                client_ip=ip, requested_host=HOST, requested_path="/login",
                client_user_agent=f"DiffBot-{k}", cookies=cookies,
            ))
    rng.shuffle(stream)  # interleave clients; same order for every run
    return stream


def _run_stream(requests, device, cfg=None):
    """One full pass of the stream through the REAL chain stage with the
    REAL Banner writing to a buffer; returns (per-request outcomes,
    ban-log bytes, final window-state rendering)."""
    cfg = cfg if cfg is not None else config_from_yaml_text(CONFIG_YAML)
    dyn = DynamicDecisionLists(start_sweeper=False)
    ban_log = io.StringIO()
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    state = ChainState(
        config=cfg,
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=dyn,
        protected_paths=PasswordProtectedPaths(cfg),
        failed_challenge_states=make_failed_challenge_states(cfg),
        banner=banner,
        challenge_verifier=device,
    )
    outcomes = []
    for req in requests:
        resp, result, rate = send_or_validate_sha_challenge(
            state, req, FailAction.BLOCK
        )
        outcomes.append(
            (req.client_ip, resp.status, int(result), rate.exceeded)
        )
    return outcomes, ban_log.getvalue(), state.failed_challenge_states.format_states()


def _strip_intervals(states_text: str) -> list:
    """format_states minus the per-run interval_start timestamps (wall
    clock ns differ between two sequential runs by construction)."""
    out = []
    for line in states_text.splitlines():
        ip, _, rest = line.partition(",: interval_start: ")
        out.append((ip, rest.split("num hits: ")[1]))
    return out


def test_device_and_cpu_runs_are_byte_identical():
    """The headline differential: device-batched PoW vs pure CPU on the
    same scripted stream — same statuses, same results, same exceeded
    flags, byte-identical ban-log lines, same final hit counts."""
    requests = _scripted_requests(seed=11)
    device = DeviceVerifier(batch_max=4, interpret=True)

    dev_out, dev_log, dev_states = _run_stream(requests, device)
    cpu_out, cpu_log, cpu_states = _run_stream(requests, None)

    assert dev_out == cpu_out
    assert dev_log == cpu_log                       # byte identity
    assert _strip_intervals(dev_states) == _strip_intervals(cpu_states)
    # non-vacuous: accepts happened, bans happened, on the device path
    assert any(status == 200 for _, status, _, _ in dev_out)
    assert '"rule_type":"failed_challenge"' in dev_log
    assert '"trigger":"failed challenge sha_inv"' in dev_log
    counters = device.counters()
    assert counters["dispatches"] > 0 and counters["faults"] == 0


def test_bounded_failure_state_changes_nothing_on_this_stream():
    """The bounded drop-in vs the reference dict, device path on both:
    with the cap above the distinct-client count (no forced drops — the
    only permitted divergence source) everything is identical."""
    requests = _scripted_requests(seed=13)
    bounded_cfg = config_from_yaml_text(CONFIG_YAML)
    bounded_cfg.challenge_failure_state_max = 1024

    ref_out, ref_log, ref_states = _run_stream(
        requests, DeviceVerifier(batch_max=8, interpret=True)
    )
    b_out, b_log, b_states = _run_stream(
        requests, DeviceVerifier(batch_max=8, interpret=True), cfg=bounded_cfg
    )

    assert b_out == ref_out
    assert b_log == ref_log
    # the LRU tier renders in recency order, the reference dict in
    # insertion order — same (ip, hits) content either way
    assert sorted(_strip_intervals(b_states)) == sorted(
        _strip_intervals(ref_states)
    )


def test_verify_sha_inv_reject_text_matches_crypto_oracle_exactly():
    """Every reject raises the reference's exact CookieError text —
    device path, CPU path, and the crypto oracle agree byte for byte."""
    now = int(time.time())
    device = DeviceVerifier(batch_max=4, interpret=True)
    fresh = new_challenge_cookie_at(SECRET, now + 300, "1.2.3.4")
    cases = [
        solve_challenge_for_testing(fresh, ZERO_BITS),        # accept
        _solve_below_target(fresh),                           # zero bits
        solve_challenge_for_testing(
            new_challenge_cookie_at(SECRET, now - 5, "1.2.3.4"), ZERO_BITS
        ),                                                    # expired
        fresh[:40],                                           # torn
        "@@not-base64@@",                                     # bad b64
        solve_challenge_for_testing(
            new_challenge_cookie_at(SECRET, now + 300, "9.9.9.9"), ZERO_BITS
        ),                                                    # bad hmac
    ]
    for cookie in cases:
        results = []
        for verifier in (
            lambda c: verify_sha_inv(SECRET, c, time.time(), "1.2.3.4",
                                     ZERO_BITS, device=device),
            lambda c: verify_sha_inv(SECRET, c, time.time(), "1.2.3.4",
                                     ZERO_BITS, device=None),
            lambda c: validate_sha_inv_cookie(SECRET, c, time.time(),
                                              "1.2.3.4", ZERO_BITS),
        ):
            try:
                verifier(cookie)
                results.append(("accept", ""))
            except CookieError as e:
                results.append(("reject", str(e)))
        assert results[0] == results[1] == results[2], cookie


def test_breaker_trip_mid_stream_keeps_decisions_identical():
    """challenge.device_verify faults trip the breaker mid-stream; the
    degraded run must match the pure-CPU run decision for decision and
    byte for byte in the ban log — resilience never changes an answer."""
    requests = _scripted_requests(seed=17)
    cpu_out, cpu_log, _ = _run_stream(requests, None)

    device = DeviceVerifier(
        batch_max=4, interpret=True, breaker_threshold=3,
        breaker_cooldown_s=3600.0,
    )
    failpoints.arm("challenge.device_verify", mode="error")
    try:
        dev_out, dev_log, _ = _run_stream(requests, device)
    finally:
        failpoints.disarm()

    assert dev_out == cpu_out
    assert dev_log == cpu_log
    assert not device.available()  # the breaker actually opened
    assert device.counters()["breaker_trips"] >= 1
