"""Single-kernel fused path vs the two-program oracle: byte-identical
(this PR's tentpole contract).

The single-kernel mode (pallas_single_kernel, kernels/
fused_match_window.py) collapses the fused path's two device programs —
and the host resolve between them — into one dispatch whose overflow
handling is gated in-kernel and whose window commit happens at submit.
These tests prove the collapse changes NOTHING observable: for the same
stimulus, single-kernel == two-program == CPU reference on

  * the per-line result stream (victim/refusal sequences),
  * ban-log bytes,
  * dynamic-decision metrics,
  * the full window counter state (format_states — spills included),

across slot-eviction churn, overflow bursts (the chain-gate composition),
mid-pipeline staleness, breaker trips, and mid-pipeline aborts."""

import io
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.resilience import failpoints
from tests.differential.test_pipeline_differential import ChurnSizer, _gen_lines
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _build(matcher_cls, **cfg_overrides):
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = True
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    matcher = matcher_cls(config, banner, StaticDecisionLists(config), states)
    return matcher, states, dyn, ban_log


def _pair(**cfg):
    """(single-kernel matcher, two-program matcher) with identical cfg."""
    sk = _build(TpuMatcher, pallas_single_kernel="on", **cfg)
    tp = _build(TpuMatcher, pallas_single_kernel="off", **cfg)
    assert sk[0]._fw_pipeline is not None and sk[0]._fw_pipeline.single_kernel
    assert tp[0]._fw_pipeline is not None and not tp[0]._fw_pipeline.single_kernel
    return sk, tp


def _run_pipelined(matcher, phases, now_box, sizer_seed=7):
    """Drive `phases` (lists of lines) through the scheduler, flushing
    between phases so a mutated now_box['now'] applies to whole phases
    deterministically (encode/submit/drain all see the same clock)."""
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(lambda: matcher, on_results=sink,
                              now_fn=lambda: now_box["now"])
    sched._sizer = ChurnSizer(seed=sizer_seed)
    sched.start()
    for phase in phases:
        for i in range(0, len(phase), 97):
            sched.submit(phase[i : i + 97])
        assert sched.flush(180)
    sched.stop()
    return [r for _, rs in collected for r in rs], sched


def _assert_identical(tag, a_results, b_results, a, b):
    (am, _, adyn, alog) = a
    (bm, _, bdyn, blog) = b
    assert [result_key(r) for r in a_results] == \
        [result_key(r) for r in b_results], f"{tag}: result stream diverged"
    assert alog.getvalue() == blog.getvalue(), f"{tag}: ban-log bytes diverged"
    assert adyn.metrics() == bdyn.metrics(), f"{tag}: decision metrics diverged"
    assert am.device_windows.format_states() == \
        bm.device_windows.format_states(), f"{tag}: window state diverged"


def test_churn_stream_byte_identical_and_cpu_exact():
    """Adversarial batch churn with shared IPs crossing chunk boundaries
    plus a CPU-reference anchor: single-kernel == two-program == CPU."""
    now = time.time()
    lines = _gen_lines(1500, now)

    cpu, _, _, cpu_log = _build(CpuMatcher)
    cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]

    sk, tp = _pair()
    sk_results, _ = _run_pipelined(sk[0], [lines], {"now": now}, sizer_seed=7)
    tp_results, _ = _run_pipelined(tp[0], [lines], {"now": now}, sizer_seed=7)

    for i, (c, s) in enumerate(zip(cpu_results, sk_results)):
        assert result_key(c) == result_key(s), f"single-kernel diverged at {i}"
    _assert_identical("churn", sk_results, tp_results, sk, tp)
    assert sk[3].getvalue() == cpu_log.getvalue()
    assert sk[0]._fw_pipeline.sk_chunks > 0, "single kernel never engaged"


def test_eviction_churn_byte_identical():
    """Slot capacity far below the distinct-IP load: spill/restore churn
    under both modes stays lossless and identical."""
    now = time.time()
    lines = _gen_lines(900, now, seed=19)
    sk, tp = _pair(matcher_window_capacity=16, matcher_batch_lines=64,
                   matcher_prefilter_cand_frac=1.0)
    sk_results, _ = _run_pipelined(sk[0], [lines], {"now": now}, sizer_seed=3)
    tp_results, _ = _run_pipelined(tp[0], [lines], {"now": now}, sizer_seed=3)
    _assert_identical("evict", sk_results, tp_results, sk, tp)
    assert sk[0].device_windows.eviction_count > 0


def test_overflow_bursts_with_phase_gaps():
    """All-matching bursts (candidate overflow) alternating with benign
    phases, flushed between phases: the chain gate replays the poisoned
    tail classically and reseeds at each quiescent gap — identical
    output, and the single kernel demonstrably commits again after every
    burst (both counters move)."""
    now = time.time()
    phases = []
    for burst in range(6):
        if burst % 2:
            phases.append([
                f"{now:f} 7.7.{burst}.{i} POST example.com POST /x{i} "
                "HTTP/1.1 ua -"
                for i in range(80)
            ])
        else:
            phases.append(_gen_lines(120, now, seed=300 + burst))

    sk, tp = _pair(matcher_batch_lines=64, matcher_prefilter_cand_frac=0.125)
    sk_results, _ = _run_pipelined(sk[0], phases, {"now": now}, sizer_seed=5)
    tp_results, _ = _run_pipelined(tp[0], phases, {"now": now}, sizer_seed=5)
    _assert_identical("overflow", sk_results, tp_results, sk, tp)
    fw = sk[0]._fw_pipeline
    assert fw.sk_fallbacks > 0, "overflow never hit the in-kernel gate"
    assert fw.sk_chunks > 0, "chain never reseeded across phase gaps"


def test_mixed_path_batches_keep_window_order():
    """The cross-batch ordering hazard of commit-at-submit: a batch with
    host-eval rows (garbage line) takes the classic pend path and applies
    its window updates at its DRAIN turn; a later single-kernel batch
    would commit at SUBMIT — before that drain — unless the order gate
    (runner._single_kernel_ordered) routes it classic too.  Shared IPs
    hammer the same rules near their thresholds so one reordered window
    update shifts which exact hit fires — the oracle comparison catches
    a single slip."""
    now = time.time()
    lines = []
    for k in range(600):
        if k % 90 == 44:
            lines.append("short garbage")  # host-eval → classic batch
        lines.append(
            f"{now + k * 1e-4:f} 3.3.3.{k % 4} GET per-site.com GET "
            "/blockme HTTP/1.1 ua -"
        )

    sk, tp = _pair(matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0)
    sk_results, _ = _run_pipelined(sk[0], [lines], {"now": now}, sizer_seed=21)
    tp_results, _ = _run_pipelined(tp[0], [lines], {"now": now}, sizer_seed=21)
    _assert_identical("mixed-path", sk_results, tp_results, sk, tp)
    assert sk[0]._fw_pipeline.sk_chunks > 0
    # the drain-apply gate fully released (no leaked slots)
    assert sk[0]._drain_window_batches == 0
    assert tp[0]._drain_window_batches == 0


def test_mid_pipeline_staleness_identical():
    """Lines fresh at encode but past the 10 s cutoff at commit: the
    single-kernel path cuts at submit (live-mask input), the two-program
    path at its drain resolve — same observable drop, same surviving
    commits, driven through the split protocol directly so both clocks
    are pinned to the same instant."""
    now = time.time()
    old = [
        f"{now - 8:f} 9.9.9.{i} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(6)
    ]
    fresh = [
        f"{now:f} 8.8.8.{i} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(6)
    ]
    lines = old + fresh
    sk, tp = _pair()

    s = sk[0].pipeline_begin(lines, now)
    assert s.get("fused_eligible")
    sk[0].pipeline_submit(s, now=now + 3)  # old rows now 11 s stale
    sk[0].pipeline_collect(s)
    sk_results, sk_stale = sk[0].pipeline_finish(s, now + 3)

    t = tp[0].pipeline_begin(lines, now)
    tp[0].pipeline_submit(t, now=now + 3)
    tp[0].pipeline_collect(t)
    tp_results, tp_stale = tp[0].pipeline_finish(t, now + 3)

    assert sk_stale == tp_stale == 6
    _assert_identical("stale", sk_results, tp_results, sk, tp)
    assert all(r.old_line for r in sk_results[:6])
    assert all(r.rule_results for r in sk_results[6:])


def test_breaker_trip_mid_stream_identical():
    """Phase 2 runs with the breaker OPEN (CPU reference drain), then the
    breaker recovers: both modes route the same batches to the same
    paths, so the streams stay identical end to end."""
    now = time.time()
    phase1 = _gen_lines(300, now, seed=41)
    phase2 = _gen_lines(200, now, seed=43)
    phase3 = _gen_lines(300, now, seed=47)

    def run(m):
        box = {"now": now}
        collected = []
        lock = threading.Lock()

        def sink(ls, rs):
            with lock:
                collected.append((ls, rs))

        sched = PipelineScheduler(lambda: m, on_results=sink,
                                  now_fn=lambda: box["now"])
        sched.start()
        for i in range(0, len(phase1), 37):
            sched.submit(phase1[i : i + 37])
        assert sched.flush(120)
        for _ in range(m.breaker.failure_threshold):
            m.breaker.record_failure()
        assert not m.breaker.allow()
        for i in range(0, len(phase2), 37):
            sched.submit(phase2[i : i + 37])
        assert sched.flush(120)
        m.breaker.record_success()
        for i in range(0, len(phase3), 37):
            sched.submit(phase3[i : i + 37])
        assert sched.flush(120)
        sched.stop()
        return [r for _, rs in collected for r in rs]

    sk, tp = _pair(matcher_prefilter_cand_frac=1.0)
    sk_results = run(sk[0])
    tp_results = run(tp[0])
    _assert_identical("breaker", sk_results, tp_results, sk, tp)
    assert sk[0].fallback_batches > 0  # phase 2 really took the CPU path
    assert sk[0]._fw_pipeline.sk_chunks > 0


def test_mid_pipeline_abort_identical():
    """pipeline.submit failpoint mid-stream: the aborted batch dies
    BEFORE any device dispatch on both paths (no commit anywhere), drains
    generically through the classic protocol, and everything after it
    stays byte-identical."""
    now = time.time()
    phases = [
        _gen_lines(300, now, seed=61),
        _gen_lines(300, now, seed=67),
    ]

    def run(m, seed):
        box = {"now": now}
        collected = []
        lock = threading.Lock()

        def sink(ls, rs):
            with lock:
                collected.append((ls, rs))

        sched = PipelineScheduler(lambda: m, on_results=sink,
                                  now_fn=lambda: box["now"])
        sched._sizer = ChurnSizer(seed=seed)
        sched.start()
        for i in range(0, len(phases[0]), 97):
            sched.submit(phases[0][i : i + 97])
        assert sched.flush(120)
        # the NEXT batch's submit fails before dispatch → generic drain
        failpoints.arm("pipeline.submit", count=1)
        for i in range(0, len(phases[1]), 97):
            sched.submit(phases[1][i : i + 97])
        assert sched.flush(120)
        failpoints.disarm()
        sched.stop()
        snap = sched.stats.peek()
        assert snap["PipelineAdmittedLines"] == \
            snap["PipelineProcessedLines"] + snap["PipelineShedLines"] + \
            snap["PipelineDrainErrorLines"]
        return [r for _, rs in collected for r in rs]

    sk, tp = _pair(matcher_prefilter_cand_frac=1.0)
    sk_results = run(sk[0], seed=9)
    tp_results = run(tp[0], seed=9)
    _assert_identical("abort", sk_results, tp_results, sk, tp)
    assert sk[0]._fw_pipeline.sk_chunks > 0
