"""Differential proof of zero behavior change from tracing: a pipelined
run with `trace_enabled: true` produces byte-identical ban-log/effector
output to `trace_enabled: false`, and the recorded trace contains spans
for all five pipeline stages with consistent parent/child/trace ids."""

import random
import threading
import time

import pytest

from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs import trace
from banjax_tpu.pipeline import PipelineScheduler
from tests.differential.test_pipeline_differential import (
    ChurnSizer,
    _build,
    _gen_lines,
)
from tests.differential.test_tpu_matcher import result_key


@pytest.fixture(autouse=True)
def _tracer_off_after():
    yield
    trace.configure(enabled=False)


def _run_pipelined(lines, now, device_windows, seed):
    matcher, states, dyn, ban_log = _build(TpuMatcher, device_windows)
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: matcher, on_results=sink, now_fn=lambda: now
    )
    sched._sizer = ChurnSizer(seed=seed)
    sched.start()
    rng = random.Random(31)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, 90)
        sched.submit(lines[i : i + step])
        i += step
    assert sched.flush(120)
    sched.stop()
    results = {}
    for batch_lines, batch_results in collected:
        if batch_results is None:
            continue
        for line, res in zip(batch_lines, batch_results):
            results.setdefault(line, []).append(result_key(res))
    return results, ban_log.getvalue(), states.format_states()


@pytest.mark.parametrize("device_windows", [False, True])
def test_trace_on_off_byte_identical(device_windows):
    now = time.time()
    lines = _gen_lines(1200, now)

    trace.configure(enabled=False)
    off_results, off_log, off_states = _run_pipelined(
        lines, now, device_windows, seed=7
    )
    trace.configure(enabled=True, ring_size=8192)
    on_results, on_log, on_states = _run_pipelined(
        lines, now, device_windows, seed=7
    )
    assert on_log == off_log          # ban-log bytes identical
    assert on_results == off_results  # per-line result stream identical
    assert on_states == off_states    # rate-limit window state identical
    # and the traced run actually recorded spans
    assert trace.get_tracer().snapshot()


def test_synthetic_run_records_all_five_stages_consistently():
    """Acceptance: spans for admission, encode-shard, submit, collect,
    drain present with parent/child ids consistent per trace."""
    tracer = trace.configure(enabled=True, ring_size=16384)
    now = time.time()
    lines = _gen_lines(600, now)
    matcher, states, dyn, ban_log = _build(TpuMatcher, device_windows=True)
    sched = PipelineScheduler(lambda: matcher, now_fn=lambda: now)
    sched.start()
    for i in range(0, len(lines), 100):
        sched.submit(lines[i : i + 100])
    assert sched.flush(120)
    sched.stop()

    spans = tracer.snapshot()
    by_id = {s["span_id"]: s for s in spans}
    names = {s["name"] for s in spans}
    for stage in ("admission", "encode", "encode-shard", "submit",
                  "collect", "drain"):
        assert stage in names, f"missing {stage} spans; have {sorted(names)}"

    roots = [s for s in spans if s["name"] == "admission"]
    assert roots, "no admission root spans"
    for s in spans:
        if s["dur_us"] is None:
            continue  # instant events carry no parent
        if s["parent_id"]:
            parent = by_id.get(s["parent_id"])
            # parent may have rotated out of the ring only if the ring
            # wrapped; sized here so it never does
            assert parent is not None, f"dangling parent for {s}"
            assert parent["trace_id"] == s["trace_id"], (
                f"span {s['name']} crosses traces: {s} vs {parent}"
            )
        if s["name"] in ("encode", "submit", "collect", "drain"):
            assert by_id[s["parent_id"]]["name"] == "admission", s
        if s["name"] == "encode-shard":
            assert by_id[s["parent_id"]]["name"] == "encode", s
        if s["name"] in ("program-a", "program-ab-fused"):
            # the single-kernel path's one fused span replaces the
            # program-a/program-b pair; both belong to the submit stage
            assert by_id[s["parent_id"]]["name"] == "submit", s
        if s["name"] in ("program-b", "effector-replay"):
            assert by_id[s["parent_id"]]["name"] == "drain", s

    # every traced batch has exactly one root whose stages share its id
    for root in roots:
        tid = root["trace_id"]
        stages = [s["name"] for s in spans if s["trace_id"] == tid
                  and s["parent_id"] == root["span_id"]]
        assert "encode" in stages and "drain" in stages, (tid, stages)

    # chrome export of a real run is well-formed and Perfetto-shaped
    import json

    out = tracer.export_chrome()
    json.dumps(out)
    phases = {e["ph"] for e in out["traceEvents"]}
    assert "X" in phases and "M" in phases
