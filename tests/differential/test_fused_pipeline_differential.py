"""Pipelined FUSED two-phase path vs the synchronous fused path: byte
identical under adversarial conditions (this PR's tentpole ordering
contract).

The streaming pipeline now drives the fused matcher+windows two-program
path — program A (stateless match) dispatched ahead at the submit stage,
the window commit (program B) deferred to the drain stage in admission
order.  These tests prove the deferred commit changes NOTHING observable:

  * adversarial batch churn with shared IPs crossing every batch/chunk
    boundary (window counters must accumulate in exact log order);
  * overflow chunks interleaved with ok chunks (the classic mid-pipeline
    replay, order turns held);
  * drain-time staleness composed with the deferred commit (live mask);
  * breaker-OPEN mid-stream draining through the CPU reference matcher;
  * the h2d witness: the pipelined fused path must move FAR fewer bytes
    host→device than the classic bitmap path (no dense re-upload).
"""

import io
import random
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from tests.differential.test_pipeline_differential import ChurnSizer, _gen_lines
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key


def _build(matcher_cls, fused=True, **cfg_overrides):
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = True
    config.pipeline_fused = fused
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    matcher = matcher_cls(config, banner, StaticDecisionLists(config), states)
    return matcher, states, dyn, ban_log


def _run_pipelined(matcher, lines, now, sizer_seed=7, submit_seed=11):
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(lambda: matcher, on_results=sink,
                              now_fn=lambda: now)
    sched._sizer = ChurnSizer(seed=sizer_seed)
    sched.start()
    rng = random.Random(submit_seed)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, 120)
        sched.submit(lines[i : i + step])
        i += step
    assert sched.flush(180)
    sched.stop()
    pipe_lines = [l for ls, _ in collected for l in ls]
    pipe_results = [r for _, rs in collected for r in rs]
    assert pipe_lines == lines, "admission order broken"
    return pipe_results, sched


def test_pipelined_fused_is_byte_identical_and_kills_dense_upload():
    """The tentpole acceptance: fused+pipelined output == sync fused ==
    CPU reference (results, ban-log bytes, window state), the two-phase
    path actually engaged, and the h2d byte counter shows the dense
    bitmap re-upload gone relative to the classic pipelined path."""
    now = time.time()
    lines = _gen_lines(1500, now)

    cpu, _, cpu_dyn, cpu_log = _build(CpuMatcher)
    cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]

    sync, _, _, sync_log = _build(TpuMatcher)
    sync_results = sync.consume_lines(lines, now_unix=now)

    fused, _, fused_dyn, fused_log = _build(TpuMatcher)
    fused_results, _ = _run_pipelined(fused, lines, now)

    classic, _, _, classic_log = _build(TpuMatcher, fused=False)
    classic_results, _ = _run_pipelined(classic, lines, now)

    for i, (c, s, f, k) in enumerate(zip(
        cpu_results, sync_results, fused_results, classic_results
    )):
        assert result_key(c) == result_key(s), f"sync diverged at {i}"
        assert result_key(c) == result_key(f), f"fused-pipelined diverged at {i}"
        assert result_key(c) == result_key(k), f"classic-pipelined diverged at {i}"
    assert fused_log.getvalue() == cpu_log.getvalue() == sync_log.getvalue()
    assert classic_log.getvalue() == cpu_log.getvalue()
    assert fused_dyn.metrics() == cpu_dyn.metrics()
    assert fused.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert fused.device_windows.format_states() == \
        classic.device_windows.format_states()

    # the two-phase path really ran (this stream has host-eval-free
    # batches; some batches legitimately take the classic path when a
    # garbage line defers)
    assert fused.pipelined_fused_chunks > 0, "two-phase path never engaged"
    assert classic.pipelined_fused_chunks == 0  # pipeline_fused=false honored


def test_h2d_witness_dense_reupload_gone_at_rule_scale():
    """The fusion-win witness at a realistic rule count: the classic
    pipelined path re-uploads a dense [B, n_rules] bitmap for the drain
    commit (n_rules bytes per line — the ~16 MB/batch at 1k rules / 65k
    lines); the two-phase path uploads only the encoded classes + a
    per-row live mask.  At 200 rules the classic h2d must exceed fused by
    roughly the bitmap's size."""
    import yaml as _yaml

    from bench import generate_lines, generate_rules

    patterns = generate_rules(200)
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"crs{i}", "regex": p, "interval": 60,
             "hits_per_interval": 50, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })
    now = time.time()
    rests = generate_lines(1024, patterns, seed=51)
    lines = [
        f"{now:.6f} 10.6.{(i % 512) >> 8}.{i % 256} {r}"
        for i, r in enumerate(rests)
    ]

    def run(fused_flag):
        config = config_from_yaml_text(rules_yaml)
        config.matcher_device_windows = True
        config.pipeline_fused = fused_flag
        states = RegexRateLimitStates()
        dyn = DynamicDecisionLists(start_sweeper=False)
        banner = Banner(dyn, io.StringIO(), io.StringIO(), ipset_instance=None)
        m = TpuMatcher(config, banner, StaticDecisionLists(config), states)
        assert m._fw_pipeline is not None
        sched = PipelineScheduler(
            lambda: m, now_fn=lambda: now, min_batch=256, max_batch=256,
        )
        sched.start()
        for i in range(0, len(lines), 256):
            sched.submit(lines[i : i + 256])
        assert sched.flush(300)
        sched.stop()
        return m

    fused = run(True)
    classic = run(False)
    assert fused.pipelined_fused_chunks > 0
    fused_h2d = fused.stats.h2d_bytes_per_batch()
    classic_h2d = classic.stats.h2d_bytes_per_batch()
    # the dense bitmap is 200 B/line; everything else is shared — demand
    # at least half that delta to stay robust to bucketing noise
    assert classic_h2d - fused_h2d > 0.5 * 200 * 256, (
        fused_h2d, classic_h2d
    )


def test_overflow_chunks_interleaved_with_ok_chunks():
    """Bursts of all-matching traffic (candidate overflow → classic
    mid-pipeline replay) interleaved with benign chunks: byte-identical,
    fallbacks counted, pins/turns never leak (the flush would hang).
    Two-program path pinned — its resolve turns let benign chunks BEHIND
    an overflow still commit fused, which the chunk-counter assertions
    below encode; the single-kernel chain-gate composition of this shape
    lives in tests/differential/test_single_kernel_differential.py."""
    now = time.time()
    rng = random.Random(3)
    lines = []
    for burst in range(30):
        if burst % 3 == 0:
            # every line matches 'POST .*' → stage-1 gate passes them all
            # → candidate capacity exceeded → PipelineOverflow mid-stream
            lines += [
                f"{now:f} 7.7.{burst}.{i} POST example.com POST /x{i} HTTP/1.1 ua -"
                for i in range(40)
            ]
        else:
            lines += _gen_lines(40, now, seed=100 + burst)

    sync, _, _, sync_log = _build(TpuMatcher, pallas_single_kernel="off")
    sync_results = sync.consume_lines(lines, now_unix=now)

    pipe, _, _, pipe_log = _build(TpuMatcher, pallas_single_kernel="off")
    pipe_results, _ = _run_pipelined(pipe, lines, now, sizer_seed=5)

    assert [result_key(r) for r in pipe_results] == \
        [result_key(r) for r in sync_results]
    assert pipe_log.getvalue() == sync_log.getvalue()
    assert pipe.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert pipe.pipelined_fused_fallbacks > 0, (
        "overflow fallback never exercised — the burst should overflow"
    )
    assert pipe.pipelined_fused_chunks > 0


def test_breaker_open_mid_stream_drains_via_cpu_reference():
    """Phase 2 runs with the breaker OPEN: those batches drain through
    the CPU reference matcher (host window counters), then the breaker
    recovers and the fused path resumes — identical to a sync run that
    trips at the same stream offsets."""
    now = time.time()
    phase1 = _gen_lines(300, now, seed=41)
    phase2 = _gen_lines(200, now, seed=43)
    phase3 = _gen_lines(300, now, seed=47)

    def trip(m):
        # default recovery (30 s) keeps OPEN for the whole phase
        for _ in range(m.breaker.failure_threshold):
            m.breaker.record_failure()
        assert not m.breaker.allow()

    def recover(m):
        # record_success force-closes from any state (deterministic, no
        # wall-clock dependence)
        m.breaker.record_success()
        assert m.breaker.allow()

    # cand_frac 1.0: this mix matches often; give stage 2 full capacity
    # so the phases commit through program B, not the overflow fallback
    sync, _, _, sync_log = _build(
        TpuMatcher, matcher_prefilter_cand_frac=1.0
    )
    sync.consume_lines(phase1, now_unix=now)
    trip(sync)
    sync.consume_lines(phase2, now_unix=now)  # breaker-guarded → CPU ref
    recover(sync)
    sync.consume_lines(phase3, now_unix=now)

    pipe, _, _, pipe_log = _build(
        TpuMatcher, matcher_prefilter_cand_frac=1.0
    )
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(lambda: pipe, on_results=sink,
                              now_fn=lambda: now)
    sched.start()
    for i in range(0, len(phase1), 37):
        sched.submit(phase1[i : i + 37])
    assert sched.flush(120)
    trip(pipe)
    for i in range(0, len(phase2), 37):
        sched.submit(phase2[i : i + 37])
    assert sched.flush(120)
    recover(pipe)
    for i in range(0, len(phase3), 37):
        sched.submit(phase3[i : i + 37])
    assert sched.flush(120)
    sched.stop()

    assert pipe_log.getvalue() == sync_log.getvalue()
    assert pipe.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert pipe.fallback_batches > 0  # phase 2 really took the CPU path
    # phases 1/3 went through the two-phase path (commit or its counted
    # overflow fallback — this mix can still overflow the pair budget)
    assert pipe.pipelined_fused_chunks + pipe.pipelined_fused_fallbacks > 0
    snap = sched.snapshot()
    assert snap["PipelineProcessedLines"] == len(phase1) + len(phase2) + len(phase3)
    assert snap["PipelineShedLines"] == 0


def test_drain_stale_composes_with_deferred_commit():
    """Lines that age past the 10 s cutoff while queued are dropped at
    the drain commit via the live mask: no window update, no Banner
    effect, marked old_line — while fresh lines in the SAME chunk commit
    normally.  (Two-program path pinned: the single-kernel path takes
    the staleness cut at submit instead — see
    tests/differential/test_single_kernel_differential.py.)"""
    now = time.time()
    m, states, _, ban_log = _build(TpuMatcher, pallas_single_kernel="off")
    # 8 s old at encode (fresh), drained at now+3 → 11 s old → stale
    old = [
        f"{now - 8:f} 9.9.9.{i} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(5)
    ]
    fresh = [
        f"{now:f} 8.8.8.{i} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(5)
    ]
    state = m.pipeline_begin(old + fresh, now)
    assert state.get("fused_eligible")
    m.pipeline_submit(state)
    assert state.get("fused"), "two-phase entries missing"
    m.pipeline_collect(state)
    results, n_stale = m.pipeline_finish(state, now + 3)
    assert n_stale == 5
    assert all(r.old_line and not r.rule_results for r in results[:5])
    assert all(not r.old_line and r.rule_results for r in results[5:])
    # only the fresh IPs ever touched the device windows
    view = m.device_windows.format_states()
    assert "9.9.9.0" not in view and "8.8.8.0" in view
    # instant-block rule fired for fresh lines only
    assert ban_log.getvalue().count("instant block") == 5


@pytest.mark.slow
def test_repeated_fused_streams_accumulate_identically():
    now = time.time()
    lines = _gen_lines(500, now, seed=29)
    sync, _, _, sync_log = _build(TpuMatcher)
    sync.consume_lines(lines, now_unix=now)
    sync.consume_lines(lines, now_unix=now)

    pipe, _, _, pipe_log = _build(TpuMatcher)
    sched = PipelineScheduler(lambda: pipe, now_fn=lambda: now)
    sched._sizer = ChurnSizer(seed=13)
    sched.start()
    for _ in range(2):
        for i in range(0, len(lines), 41):
            sched.submit(lines[i : i + 41])
    assert sched.flush(180)
    sched.stop()
    assert pipe_log.getvalue() == sync_log.getvalue()
    assert pipe.device_windows.format_states() == \
        sync.device_windows.format_states()
