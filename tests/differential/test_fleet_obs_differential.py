"""Fleet observability on vs off: the same seeded scenario through
real worker processes must produce a byte-identical ban log whether or
not forwarded chunks carry origin trace context (ISSUE 20 satellite).

``fleet_obs=True`` arms ``--trace-propagation 1`` on every worker AND
the origin section on every forwarded frame — the observability plane
rides the data path, so this A/B proves it is *pure* observation:
same decisions, same fabric ledger, with and without it.  The kill
arm (slow) adds a SIGKILL mid-flood: takeover + journal replay must
converge identically with origin sections riding the replayed frames.
"""

import pytest

from banjax_tpu.fabric.harness import run_fabric

_SEED = 20260807
_SHAPE = "flash_crowd"

_reports = {}


def _run(fleet_obs, kill):
    key = (fleet_obs, kill)
    if key not in _reports:
        _reports[key] = run_fabric(
            n_workers=2, shape=_SHAPE, seed=_SEED, scale=0.5,
            kill=kill, fleet_obs=fleet_obs,
        )
    return _reports[key]


def _assert_clean(report):
    bad = [k for k, ok in report["invariants"].items() if not ok]
    assert not bad, bad
    assert report["fed_lines"] == report["acked_lines"]


def _ban_log_bytes(report):
    return ("\n".join(report["ban_log"]) + "\n").encode()


def test_fleet_obs_on_vs_off_ban_log_byte_identical_clean_run():
    ref = _run(fleet_obs=False, kill=False)
    obs = _run(fleet_obs=True, kill=False)
    _assert_clean(ref)
    _assert_clean(obs)
    assert ref["oracle_bans"] > 0
    assert _ban_log_bytes(obs) == _ban_log_bytes(ref)


def test_fleet_obs_fabric_ledger_identical_clean_run():
    """Origin sections must not change WHAT moves — only annotate it:
    the per-worker routed/forwarded/shed ledger matches exactly."""
    ref = _run(fleet_obs=False, kill=False)
    obs = _run(fleet_obs=True, kill=False)
    for w, ref_w in ref["per_worker"].items():
        obs_fab = obs["per_worker"][w]["fabric"]
        for k in ("FabricReceivedLines", "FabricLocalLines",
                  "FabricForwardedLines", "FabricShedLines"):
            assert obs_fab.get(k, 0) == ref_w["fabric"].get(k, 0), (
                f"{w}.{k}"
            )


@pytest.mark.slow
def test_fleet_obs_sigkill_mid_flood_converges_identically():
    ref = _run(fleet_obs=False, kill=True)
    obs = _run(fleet_obs=True, kill=True)
    _assert_clean(ref)
    _assert_clean(obs)
    for r in (ref, obs):
        assert r["recall"] == 1.0
        assert r["precision"] == 1.0
        assert r["takeover"]["victim"] == r["killed"]
    assert _ban_log_bytes(obs) == _ban_log_bytes(ref)
