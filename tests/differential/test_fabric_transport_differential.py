"""Wire v2 / shm-ring transports vs the PR 11 sync-JSON oracle: the
same seeded scenario through real worker processes must produce a
byte-identical ban log and the same fabric ledger no matter which
encoding moved the lines (ISSUE 18 satellite).

`transport="json"` pins `fabric_inflight_frames=0` + `wire_v2=0` on
every worker — literally the PR 11 data path — so these runs are a
true A/B of the transport alone: same ring, same chunk feed, same
scenario seed.  The kill arms include a SIGKILL mid-flood: takeover +
replay must converge both encodings to the same decisions (recall 1.0,
precision 1.0 — the n2 duplicate-ban regression gate rides here too).
"""

import pytest

from banjax_tpu.fabric.harness import run_fabric

_SEED = 20260807
_SHAPE = "flash_crowd"

# the fabric counters that must be transport-invariant; frame/byte/ack
# counters legitimately differ (coalescing is the whole point)
_LEDGER_KEYS = (
    "FabricReceivedLines", "FabricLocalLines", "FabricForwardedLines",
    "FabricShedLines", "FabricReplayedLines", "FabricReplaySkippedLines",
)

_reports = {}


def _run(transport, kill):
    key = (transport, kill)
    if key not in _reports:
        _reports[key] = run_fabric(
            n_workers=2, shape=_SHAPE, seed=_SEED, scale=0.5,
            kill=kill, transport=transport,
        )
    return _reports[key]


def _assert_clean(report):
    bad = [k for k, ok in report["invariants"].items() if not ok]
    assert not bad, f"{report['transport']}: {bad}"
    assert report["fed_lines"] == report["acked_lines"]


def _ban_log_bytes(report):
    return ("\n".join(report["ban_log"]) + "\n").encode()


def test_v2_vs_json_ban_log_byte_identical_clean_run():
    ref = _run("json", kill=False)
    v2 = _run("v2", kill=False)
    _assert_clean(ref)
    _assert_clean(v2)
    assert ref["oracle_bans"] > 0
    assert _ban_log_bytes(v2) == _ban_log_bytes(ref)


def test_v2_vs_json_ledger_sums_identical_clean_run():
    """Without churn the routing is fully deterministic, so the whole
    per-worker fabric ledger — not just its invariant — must match the
    sync oracle exactly."""
    ref = _run("json", kill=False)
    v2 = _run("v2", kill=False)
    for w, ref_w in ref["per_worker"].items():
        v2_fab = v2["per_worker"][w]["fabric"]
        for k in _LEDGER_KEYS:
            assert v2_fab.get(k, 0) == ref_w["fabric"].get(k, 0), (
                f"{w}.{k}: v2={v2_fab.get(k, 0)} "
                f"json={ref_w['fabric'].get(k, 0)}"
            )
    # and the v2 run actually used the binary path
    frames = sum(
        v2["per_worker"][w]["fabric"].get("FabricFramesSent", 0)
        for w in v2["per_worker"]
    )
    assert frames > 0


@pytest.mark.slow
def test_v2_vs_json_sigkill_mid_flood_converges_identically():
    """Behind -m slow for tier-1 wall-clock: the n2 duplicate-ban
    regression is still gated in tier-1 by the fabric soak kill test,
    the router dedupe unit tests, and the bench precision asserts."""
    ref = _run("json", kill=True)
    v2 = _run("v2", kill=True)
    _assert_clean(ref)
    _assert_clean(v2)
    for r in (ref, v2):
        assert r["recall"] == 1.0, r["transport"]
        assert r["precision"] == 1.0, r["transport"]
        assert r["takeover"]["victim"] == r["killed"]
    assert _ban_log_bytes(v2) == _ban_log_bytes(ref)


@pytest.mark.slow
def test_shm_vs_json_sigkill_mid_flood_converges_identically():
    """Same A/B with the co-located shm-ring transport carrying the
    forwards (rings die with the SIGKILLed victim, exactly like its
    sockets — takeover must not care which transport was attached)."""
    ref = _run("json", kill=True)
    shm = run_fabric(
        n_workers=2, shape=_SHAPE, seed=_SEED, scale=0.5,
        kill=True, transport="shm",
    )
    _assert_clean(ref)
    _assert_clean(shm)
    assert shm["recall"] == 1.0 and shm["precision"] == 1.0
    assert _ban_log_bytes(shm) == _ban_log_bytes(ref)
