"""Differential harness: TpuMatcher vs CpuMatcher, byte-identical outputs.

This is the end-to-end acceptance bar from BASELINE.json ("Decision output
byte-identical to the Go path") and the generalization of the reference's
generative stress test (regex_rate_limiter_test.go:298-360): identical
ConsumeLineResult streams, identical Banner side-effect sequences, and
identical rate-limit counter states for the same input line stream.
"""

import random
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from tests.mock_banner import MockBanner


CONFIG_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: 'rule1'
    regex: 'GET example\.com GET .*'
    interval: 5
    hits_per_interval: 2
  - decision: challenge
    rule: 'rule2'
    regex: 'POST .*'
    interval: 5
    hits_per_interval: 1
  - decision: iptables_block
    rule: 'skip-rule'
    regex: 'DELETE '
    interval: 5
    hits_per_interval: 0
    hosts_to_skip:
      skipme.com: true
per_site_regexes_with_rates:
  per-site.com:
    - decision: nginx_block
      hits_per_interval: 0
      interval: 1
      regex: .*blockme.*
      rule: "instant block"
global_decision_lists:
  allow:
    - 12.12.12.12
"""


def make_pair(yaml_text=CONFIG_YAML):
    """Three matchers over independent state, same config text: the CPU
    oracle, the TPU matcher with host windows, and the TPU matcher with
    device-resident windows (matcher/windows.py)."""
    out = []
    for cls, dev_windows in ((CpuMatcher, False), (TpuMatcher, False),
                             (TpuMatcher, True)):
        config = config_from_yaml_text(yaml_text)
        config.matcher_device_windows = dev_windows
        states = RegexRateLimitStates()
        banner = MockBanner()
        matcher = cls(config, banner, StaticDecisionLists(config), states)
        out.append((matcher, states, banner))
    return out


def result_key(r):
    return (
        r.error,
        r.old_line,
        r.exempted,
        tuple(
            (
                rr.rule_name,
                rr.regex_match,
                rr.skip_host,
                rr.seen_ip,
                None
                if rr.rate_limit_result is None
                else (int(rr.rate_limit_result.match_type), rr.rate_limit_result.exceeded),
            )
            for rr in r.rule_results
        ),
    )


def assert_identical_consumption(lines, yaml_text=CONFIG_YAML):
    (cpu, cpu_states, cpu_banner), host_win, dev_win = make_pair(yaml_text)
    now = time.time()
    cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]
    for label, (tpu, tpu_states, tpu_banner) in (
        ("host-windows", host_win), ("device-windows", dev_win),
    ):
        tpu_results = tpu.consume_lines(lines, now_unix=now)
        for i, (a, b) in enumerate(zip(cpu_results, tpu_results)):
            assert result_key(a) == result_key(b), (
                f"{label} line {i}: {lines[i]!r}"
            )
        assert [(b.ip, b.decision, b.domain) for b in cpu_banner.bans] == [
            (b.ip, b.decision, b.domain) for b in tpu_banner.bans
        ], label
        assert cpu_banner.regex_ban_logs == tpu_banner.regex_ban_logs, label
        view = tpu.device_windows if tpu.device_windows is not None else tpu_states
        assert cpu_states.format_states() == view.format_states(), label
    return host_win[0]


def ts(offset):
    return time.time() + offset


class TestByteIdenticalStreams:
    def test_mixed_stream(self):
        lines = [
            f"{ts(0):f} 1.2.3.4 GET example.com GET /page HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET example.com GET /page2 HTTP/1.1 UA -",
            f"{ts(0.2):f} 1.2.3.4 GET example.com GET /page3 HTTP/1.1 UA -",  # exceeds rule1
            f"{ts(0.3):f} 5.6.7.8 POST example.com POST /form HTTP/1.1 UA -",
            f"{ts(0.4):f} 5.6.7.8 POST example.com POST /form HTTP/1.1 UA -",  # exceeds rule2
            f"{ts(0.5):f} 12.12.12.12 GET example.com GET /x HTTP/1.1 UA -",  # allowlisted
            "not enough words",
            f"{ts(-100):f} 9.9.9.9 GET example.com GET /old HTTP/1.1 UA -",  # stale
            "badts 1.1.1.1 GET example.com GET /x HTTP/1.1 UA -",
            f"{ts(0.6):f} 2.2.2.2 GET per-site.com GET /blockme HTTP/1.1 UA -",  # per-site instant
            f"{ts(0.7):f} 3.3.3.3 DELETE skipme.com DELETE /x HTTP/1.1 UA -",  # hosts_to_skip
            f"{ts(0.8):f} 3.3.3.3 DELETE other.com DELETE /x HTTP/1.1 UA -",  # instant iptables
        ]
        assert_identical_consumption(lines)

    def test_window_restart_semantics(self):
        base = time.time()
        mk = lambda off, ip="1.2.3.4": (
            f"{base + off:f} {ip} GET example.com GET /p HTTP/1.1 UA -"
        )
        lines = [mk(0), mk(4), mk(5.5), mk(6), mk(6.1), mk(6.2), mk(6.3)]
        assert_identical_consumption(lines)

    def test_nan_inf_timestamps_are_per_line_errors(self):
        # int(nan * 1e9) raises; must mark only that line, not drop the batch
        lines = [
            "nan 1.2.3.4 GET example.com GET /x HTTP/1.1 UA -",
            "inf 1.2.3.4 GET example.com GET /x HTTP/1.1 UA -",
            f"{ts(0):f} 1.2.3.4 GET example.com GET /ok HTTP/1.1 UA -",
        ]
        assert_identical_consumption(lines)

    def test_control_whitespace_matches_python_re(self):
        # \x1c-\x1f are \s in Python re and must be in the device class too
        yaml_text = r"""
regexes_with_rates:
  - decision: challenge
    rule: 'ws'
    regex: 'a\sb'
    interval: 5
    hits_per_interval: 0
"""
        lines = [
            f"{ts(0):f} 1.2.3.4 GET example.com GET /a\x1cb HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET example.com GET /axb HTTP/1.1 UA -",
        ]
        assert_identical_consumption(lines, yaml_text)

    def test_non_ascii_line_falls_back_to_host(self):
        lines = [
            f"{ts(0):f} 1.2.3.4 GET example.com GET /péage HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET example.com GET /ok HTTP/1.1 UA -",
        ]
        assert_identical_consumption(lines)

    def test_overlong_line_falls_back_to_host(self):
        long_path = "/x" * 400
        lines = [f"{ts(0):f} 1.2.3.4 GET example.com GET {long_path} HTTP/1.1 UA -"]
        tpu = assert_identical_consumption(lines)
        assert len(lines[0].split(" ", 2)[2]) > tpu.config.matcher_max_line_len

    def test_unsupported_rule_falls_back_to_host(self):
        yaml_text = r"""
per_site_regexes_with_rates:
  unsupported.com:
    - decision: challenge
      hits_per_interval: 0
      interval: 1
      regex: '(GET /a)+x'
      rule: "group-repeat"
"""
        lines = [
            f"{ts(0):f} 1.2.3.4 GET unsupported.com GET /aGET /ax HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET unsupported.com GET /b HTTP/1.1 UA -",
        ]
        tpu = assert_identical_consumption(lines, yaml_text)
        assert len(tpu._host_rule_idx) == 1


class TestGenerativeStress:
    """Scaled-down port of TestPerSiteRegexStress: every generated line must
    trip exactly its own generated rule, on both matchers identically."""

    def test_per_site_stress(self):
        rng = random.Random(42)
        n_rules = 200
        sites = []
        rule_yaml = ["per_site_regexes_with_rates:"]
        for i in range(n_rules):
            site = f"site-{i}.com"
            token = "".join(rng.choice("abcdefghij") for _ in range(8))
            sites.append((site, token))
            rule_yaml.append(f"  {site}:")
            rule_yaml.append("    - decision: nginx_block")
            rule_yaml.append("      hits_per_interval: 0")
            rule_yaml.append("      interval: 1")
            rule_yaml.append(f"      regex: 'GET /{token}'")
            rule_yaml.append(f"      rule: 'rule-{i}'")
        yaml_text = "\n".join(rule_yaml)

        base = time.time()
        lines = []
        for i, (site, token) in enumerate(sites):
            lines.append(
                f"{base + i * 0.001:f} 10.0.{i // 256}.{i % 256} "
                f"GET {site} GET /{token} HTTP/1.1 UA -"
            )
        rng.shuffle(lines)

        (cpu, _, cpu_banner), *tpu_variants = make_pair(yaml_text)
        now = time.time()
        cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]
        for tpu, _, tpu_banner in tpu_variants:
            tpu_results = tpu.consume_lines(lines, now_unix=now)
            for a, b in zip(cpu_results, tpu_results):
                assert result_key(a) == result_key(b)
            # every line tripped exactly one rule
            assert all(len(r.rule_results) == 1 for r in tpu_results)
            assert cpu_banner.regex_ban_logs == tpu_banner.regex_ban_logs
            assert len(tpu_banner.bans) == n_rules


def test_native_parse_path_identical_to_python_parse_path():
    """TpuMatcher with the native C parse pass vs matcher_native_parse:
    false — identical result streams on a stream salted with every parse
    corner case (errors, stale, exotic timestamps, non-ASCII, over-length)."""
    from banjax_tpu import native

    if not native.available():
        pytest.skip("no C compiler")
    now = time.time()
    lines = [
        f"{now:.6f} 10.1.1.{i % 5} GET example.com GET /p{i} HTTP/1.1 UA"
        for i in range(40)
    ] + [
        "garbage",
        f"{now - 60:.6f} 1.1.1.1 GET example.com GET /old HTTP/1.1 UA",
        f"1_{int(now)} 2.2.2.2 GET example.com GET /underscore HTTP/1.1 UA",
        "nan 3.3.3.3 GET example.com GET /nan HTTP/1.1 UA",
        f"{now:.6f} 4.4.4.4 GET example.com GET /café HTTP/1.1 UA",
        f"{now:.6f} 12.12.12.12 GET example.com GET /allowlisted HTTP/1.1 UA",
        f"{now:.6f} 5.5.5.5 POST example.com POST /{'x' * 300} HTTP/1.1 UA",
        f"{now:.6f} 6.6.6.6 DELETE skipme.com DELETE /y HTTP/1.1 UA",
    ]

    outs = []
    for native_on in (True, False):
        config = config_from_yaml_text(CONFIG_YAML)
        config.matcher_native_parse = native_on
        states = RegexRateLimitStates()
        banner = MockBanner()
        m = TpuMatcher(config, banner, StaticDecisionLists(config), states)
        assert m._native == (native_on and native.available())
        results = m.consume_lines(lines, now)
        outs.append((
            [result_key(r) for r in results],
            [(b.ip, b.decision, b.domain) for b in banner.bans],
            states.format_states(),
        ))
    assert outs[0] == outs[1]


class TestHostileSpans:
    """The columnar gate (matcher/workset.py + fp_dedup_spans) must keep
    byte-identical streams on adversarial span content: NUL bytes, spans
    past any window width, non-ASCII blobs (which disable the text
    fast-slice path), and colliding prefixes."""

    def test_nul_bytes_and_long_hosts(self):
        long_host = "h" * 200 + ".com"
        almost = "h" * 200 + ".net"  # same 200-char prefix, distinct tail
        lines = [
            f"{ts(0):f} 1.2.3.4 GET {long_host} GET /a HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET {almost} GET /a HTTP/1.1 UA -",
            f"{ts(0.2):f} 2.2.2.2 GET example.com GET /\x00nul HTTP/1.1 UA -",
            f"{ts(0.3):f} 2.2.2.2 GET example.com GET /\x00nul HTTP/1.1 UA -",
            f"{ts(0.4):f} 3.3.3\x00 GET example.com GET /x HTTP/1.1 UA -",
            f"{ts(0.5):f} 3.3.30 GET example.com GET /x HTTP/1.1 UA -",
        ]
        assert_identical_consumption(lines)

    def test_non_ascii_blob_disables_text_slicing(self):
        # one non-ASCII byte anywhere forces the per-span decode path for
        # the WHOLE batch's unique tables; results must not change
        lines = [
            f"{ts(0):f} 1.2.3.4 GET example.com GET /café HTTP/1.1 UA -",
            f"{ts(0.1):f} 1.2.3.4 GET example.com GET /page HTTP/1.1 UA -",
            f"{ts(0.2):f} 5.6.7.8 GET example.com GET /page HTTP/1.1 UA -",
            f"{ts(0.3):f} 5.6.7.8 POST example.com POST /form HTTP/1.1 UA -",
            f"{ts(0.4):f} 5.6.7.8 POST example.com POST /form HTTP/1.1 UA -",
        ]
        assert_identical_consumption(lines)

    def test_generative_hostile_bytes(self):
        rng = __import__("random").Random(77)
        ips = ["1.1.1.1", "2.2.2.2", "3.3.3.3", "\x00weird", "ip" * 40]
        hosts = ["example.com", "per-site.com", "h" * 120, "héhé.com",
                 "skipme.com"]
        paths = ["/p", "/blockme", "/x\x00y", "/" + "q" * 90, "/ok"]
        lines = []
        for i in range(120):
            lines.append(
                f"{ts(i * 0.01):f} {rng.choice(ips)} GET "
                f"{rng.choice(hosts)} GET {rng.choice(paths)} HTTP/1.1 UA -"
            )
        assert_identical_consumption(lines)
