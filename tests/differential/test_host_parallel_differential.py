"""Parallel host path vs the serial path: byte-identical outputs.

Three surfaces of the host-parallel PR are proven here against the same
oracles the pipeline suites use (CPU reference / sync TPU batch path):

  * sharded encode workers — the scheduler splits each admission batch
    into row shards parsed/gated concurrently and merged in strict line
    order; adversarial shard boundaries (same IP straddling shards,
    all-distinct IPs, garbage/stale/deferred/non-ASCII rows landing on
    every boundary) must not perturb results, ban-log bytes, window
    state, or the unique-IP first-appearance order that slot LRU
    assignment depends on;
  * the native slot manager — runs underneath both paths here (it is on
    by default); its dedicated parity fuzz lives in
    tests/unit/test_slotmgr.py;
  * depth-2 resolve-ahead drain — multi-chunk fused batches drained
    with the window commit of chunk i+1 dispatched while chunk i's
    events decode, vs the serial depth-1 drain.
"""

import io
import random
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.effectors.banner import Banner
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.pipeline import scheduler as sched_mod
from tests.differential.test_pipeline_differential import (
    ChurnSizer,
    _gen_lines,
)
from tests.differential.test_tpu_matcher import CONFIG_YAML, result_key


def _build(matcher_cls, device_windows=True, **cfg_overrides):
    config = config_from_yaml_text(CONFIG_YAML)
    config.matcher_device_windows = device_windows
    for k, v in cfg_overrides.items():
        setattr(config, k, v)
    states = RegexRateLimitStates()
    ban_log = io.StringIO()
    dyn = DynamicDecisionLists(start_sweeper=False)
    banner = Banner(dyn, ban_log, io.StringIO(), ipset_instance=None)
    matcher = matcher_cls(config, banner, StaticDecisionLists(config), states)
    return matcher, states, dyn, ban_log


def _run_pipelined(matcher, lines, now, workers=0, sizer=None,
                   submit_step=120, seed=11):
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: matcher, on_results=sink, now_fn=lambda: now,
        encode_workers=workers,
    )
    if sizer is not None:
        sched._sizer = sizer
    sched.start()
    rng = random.Random(seed)
    i = 0
    while i < len(lines):
        step = rng.randrange(1, submit_step)
        sched.submit(lines[i : i + step])
        i += step
    assert sched.flush(180)
    snap = sched.snapshot()
    sched.stop()
    pipe_lines = [l for ls, _ in collected for l in ls]
    pipe_results = [r for _, rs in collected for r in rs]
    assert pipe_lines == lines, "admission order broken"
    return pipe_results, snap


@pytest.fixture
def small_shards(monkeypatch):
    """Shrink the shard floor so the worker path engages on test-sized
    batches (production floor: 2048 rows/shard)."""
    monkeypatch.setattr(sched_mod, "_MIN_SHARD_LINES", 8)


class BigSizer(ChurnSizer):
    """Random but LARGE takes, so batches span several shards (and, with
    a small matcher_batch_lines, several fused chunks)."""

    def target(self) -> int:
        return self._rng.choice([64, 100, 160, 256, 384])


@pytest.mark.parametrize("device_windows", [False, True])
def test_sharded_encode_byte_identical(small_shards, device_windows):
    """workers=3 sharded encode vs the sync oracle and the CPU
    reference: results, ban-log bytes, window state — identical, and the
    sharded path actually engaged."""
    now = time.time()
    lines = _gen_lines(1500, now)

    cpu, _, cpu_dyn, cpu_log = _build(CpuMatcher, device_windows=False)
    cpu_results = [cpu.consume_line(l, now_unix=now) for l in lines]

    sync, sync_states, _, sync_log = _build(TpuMatcher, device_windows)
    sync_results = sync.consume_lines(lines, now_unix=now)

    par, par_states, par_dyn, par_log = _build(TpuMatcher, device_windows)
    par_results, snap = _run_pipelined(
        par, lines, now, workers=3, sizer=BigSizer(seed=99)
    )

    for i, (c, s, p) in enumerate(
        zip(cpu_results, sync_results, par_results)
    ):
        assert result_key(c) == result_key(s), f"sync diverged at {i}"
        assert result_key(c) == result_key(p), f"sharded diverged at {i}"
    assert par_log.getvalue() == cpu_log.getvalue() == sync_log.getvalue()
    assert par_dyn.metrics() == cpu_dyn.metrics()
    sync_view = (
        sync.device_windows if device_windows else sync_states
    ).format_states()
    par_view = (
        par.device_windows if device_windows else par_states
    ).format_states()
    assert sync_view == par_view
    assert snap["EncodeShardedBatches"] > 0, "worker path never engaged"
    assert snap["PipelineProcessedLines"] == len(lines)


def test_sharded_encode_all_distinct_ips_with_eviction_churn(small_shards):
    """The adversarial host shape from PERF r4: every line a distinct IP,
    so every unique-table merge crosses shard boundaries and (with a tiny
    fixed slot capacity) the slot manager churns through evictions and
    restores.  Byte-identity must hold, and the merged unique-IP
    first-appearance order must produce the same slot LRU sequence."""
    now = time.time()
    lines = []
    for i in range(900):
        ip = f"9.{i >> 16 & 255}.{i >> 8 & 255}.{i & 255}"
        if i % 3 == 0:
            lines.append(
                f"{now:f} {ip} GET example.com GET /page HTTP/1.1 ua -"
            )
        elif i % 7 == 0:
            # repeat ips straddling shard boundaries
            lines.append(
                f"{now:f} 8.8.8.8 GET example.com GET /page HTTP/1.1 ua -"
            )
        else:
            lines.append(
                f"{now:f} {ip} GET news.net GET /benign HTTP/1.1 ua -"
            )

    sync, _, _, sync_log = _build(
        TpuMatcher, True, matcher_window_capacity=64
    )
    sync_results = sync.consume_lines(lines, now_unix=now)

    par, _, _, par_log = _build(
        TpuMatcher, True, matcher_window_capacity=64
    )
    par_results, snap = _run_pipelined(
        par, lines, now, workers=4, sizer=BigSizer(seed=5)
    )

    assert [result_key(r) for r in par_results] == \
        [result_key(r) for r in sync_results]
    assert par_log.getvalue() == sync_log.getvalue()
    assert par.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert par.device_windows.eviction_count > 0, (
        "capacity 64 under distinct-IP flood should churn evictions"
    )
    assert snap["EncodeShardedBatches"] > 0


def test_shard_boundary_rows_with_flags(small_shards):
    """Garbage, stale, deferred-timestamp, and non-ASCII (host_eval)
    rows planted so shard boundaries land on and around them: the merge
    must rebase flagged results to global rows and fall back correctly
    when a shard's pre-encoded arrays are missing."""
    now = time.time()
    lines = []
    for i in range(600):
        k = i % 10
        ip = f"1.2.{i % 4}.{i % 6}"
        if k == 0:
            lines.append("short garbage")
        elif k == 1:
            lines.append(
                f"{now - 100:f} {ip} GET example.com GET /old HTTP/1.1 ua -"
            )
        elif k == 2:
            # underscone-separator float: C parse defers to Python
            lines.append(
                f"1_0.5 {ip} GET example.com GET /defer HTTP/1.1 ua -"
            )
        elif k == 3:
            # non-ASCII rest → host_eval row (fused ineligible batch)
            lines.append(
                f"{now:f} {ip} GET example.com GET /café HTTP/1.1 ua -"
            )
        else:
            lines.append(
                f"{now:f} {ip} GET example.com GET /page{i % 7} HTTP/1.1 ua -"
            )

    sync, _, _, sync_log = _build(TpuMatcher, True)
    sync_results = sync.consume_lines(lines, now_unix=now)

    par, _, _, par_log = _build(TpuMatcher, True)
    par_results, snap = _run_pipelined(
        par, lines, now, workers=3, sizer=BigSizer(seed=42)
    )

    assert [result_key(r) for r in par_results] == \
        [result_key(r) for r in sync_results]
    assert par_log.getvalue() == sync_log.getvalue()
    assert par.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert snap["EncodeShardedBatches"] > 0


@pytest.mark.parametrize("depth", [1, 2, 3])
def test_resolve_ahead_depth_byte_identical(small_shards, depth):
    """Multi-chunk fused batches (matcher_batch_lines=64 under 256-line
    takes) drained at resolve-ahead depth 1/2/3: byte-identical results,
    ban-log bytes, and window state; the two-phase path engaged; at
    depth >= 2 the overlap metric records that replay ran while the next
    chunk's window program was in flight."""
    now = time.time()
    lines = _gen_lines(1200, now, seed=31)

    sync, _, _, sync_log = _build(
        TpuMatcher, True, pallas_single_kernel="off"
    )
    sync_results = sync.consume_lines(lines, now_unix=now)

    # cand_frac=1.0: small (64-line) chunks must not overflow the
    # prefilter's candidate capacity — this test wants the two-phase
    # commit, not the fallback (that composition is tested below).
    # pallas_single_kernel=off: resolve-ahead is the TWO-PROGRAM drain's
    # machinery (the single-kernel path has no program-B dispatch left
    # to overlap, so the overlap metric legitimately stays unset there).
    par, _, _, par_log = _build(
        TpuMatcher, True,
        matcher_batch_lines=64, drain_resolve_depth=depth,
        matcher_prefilter_cand_frac=1.0, pallas_single_kernel="off",
    )
    par_results, _ = _run_pipelined(
        par, lines, now, workers=0, sizer=BigSizer(seed=7)
    )

    assert [result_key(r) for r in par_results] == \
        [result_key(r) for r in sync_results]
    assert par_log.getvalue() == sync_log.getvalue()
    assert par.device_windows.format_states() == \
        sync.device_windows.format_states()
    assert par.pipelined_fused_chunks > 0, "two-phase path never engaged"
    if depth >= 2:
        assert par.drain_resolve_overlap_ms_ewma is not None, (
            "resolve-ahead never overlapped a replay"
        )


def test_depth2_with_stale_and_overflow(small_shards):
    """Staleness masks and overflow fallbacks composed with the depth-2
    resolve-ahead: all-matching bursts (candidate overflow → classic
    mid-pipeline replay) plus lines that age out in flight, vs the same
    stream drained at depth 1."""
    now = time.time()
    lines = []
    for burst in range(20):
        if burst % 3 == 0:
            lines += [
                f"{now:f} 7.7.{burst}.{i} POST example.com POST /x{i} HTTP/1.1 ua -"
                for i in range(40)
            ]
        else:
            lines += _gen_lines(40, now, seed=200 + burst)

    d1, _, _, d1_log = _build(
        TpuMatcher, True, matcher_batch_lines=64, drain_resolve_depth=1,
        matcher_prefilter_cand_frac=0.5, pallas_single_kernel="off",
    )
    d1_results, _ = _run_pipelined(
        d1, lines, now, workers=0, sizer=BigSizer(seed=3)
    )

    d2, _, _, d2_log = _build(
        TpuMatcher, True, matcher_batch_lines=64, drain_resolve_depth=2,
        matcher_prefilter_cand_frac=0.5, pallas_single_kernel="off",
    )
    d2_results, _ = _run_pipelined(
        d2, lines, now, workers=0, sizer=BigSizer(seed=3)
    )

    assert [result_key(r) for r in d2_results] == \
        [result_key(r) for r in d1_results]
    assert d2_log.getvalue() == d1_log.getvalue()
    assert d2.device_windows.format_states() == \
        d1.device_windows.format_states()
    assert d2.pipelined_fused_fallbacks > 0, (
        "overflow fallback never exercised under depth-2"
    )
    assert d2.pipelined_fused_chunks > 0


def test_depth2_drain_stale_masks_per_chunk():
    """Drain-time staleness under resolve-ahead: a multi-chunk batch
    whose chunks are fully-stale (abandoned mid-window), mixed, and
    fully-fresh — driven through the split protocol directly so the
    drain happens 3 s after encode.  Per-chunk live masks must compose
    with the deferred commits exactly as at depth 1."""
    now = time.time()
    # two-program path pinned: resolve-ahead + drain-time live masks are
    # ITS machinery (the single-kernel path commits at submit and takes
    # the staleness cut there)
    m, _, _, ban_log = _build(
        TpuMatcher, True,
        matcher_batch_lines=64, drain_resolve_depth=2,
        matcher_prefilter_cand_frac=1.0, pallas_single_kernel="off",
    )
    # chunk 0: all stale at drain; chunk 1: half and half; chunk 2: fresh
    old = [
        f"{now - 8:f} 9.9.{i >> 8}.{i & 255} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(96)
    ]
    fresh = [
        f"{now:f} 8.8.{i >> 8}.{i & 255} GET per-site.com GET /blockme HTTP/1.1 ua -"
        for i in range(96)
    ]
    lines = old + fresh
    state = m.pipeline_begin(lines, now)
    assert state.get("fused_eligible")
    m.pipeline_submit(state)
    assert state.get("fused") and len(state["fused"]) == 3
    m.pipeline_collect(state)
    results, n_stale = m.pipeline_finish(state, now + 3)
    assert n_stale == 96
    assert all(r.old_line and not r.rule_results for r in results[:96])
    assert all(not r.old_line and r.rule_results for r in results[96:])
    view = m.device_windows.format_states()
    assert "9.9.0.0" not in view and "8.8.0.0" in view
    assert ban_log.getvalue().count("instant block") == 96
    # later batches still drain (no leaked order turns from the
    # abandoned fully-stale chunk)
    state2 = m.pipeline_begin(fresh, now)
    m.pipeline_submit(state2)
    m.pipeline_collect(state2)
    results2, _ = m.pipeline_finish(state2, now)
    assert all(r.rule_results for r in results2)
