"""MockBanner: records effects instead of touching dynamic lists or ipset.

Port of the reference's test mock (regex_rate_limiter_test.go:27-75); the
BannerInterface exists exactly so tests can swap this in (banjax.go:119-123
author comment).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Tuple

from banjax_tpu.config.schema import Config
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.effectors.banner import BannerInterface


@dataclasses.dataclass
class RecordedBan:
    ip: str
    decision: Decision
    domain: str


class MockBanner(BannerInterface):
    def __init__(self, dynamic_lists: Optional[DynamicDecisionLists] = None):
        self.bans: List[RecordedBan] = []
        self.regex_ban_logs: List[Tuple[str, str]] = []  # (ip, rule_name)
        self.failed_challenge_ban_logs: List[Tuple[str, str]] = []  # (ip, type)
        self.ipset: set = set()
        self.dynamic_lists = dynamic_lists

    def ban_or_challenge_ip(self, config: Config, ip: str, decision: Decision, domain: str) -> None:
        self.bans.append(RecordedBan(ip, decision, domain))
        if self.dynamic_lists is not None:
            import time
            self.dynamic_lists.update(
                ip, time.time() + config.expiring_decision_ttl_seconds, decision, False, domain
            )

    def log_regex_ban(self, config, log_time_unix, ip, rule_name, log_line_rest, decision):
        self.regex_ban_logs.append((ip, rule_name))

    def log_failed_challenge_ban(self, config, ip, challenge_type, host, path,
                                 too_many_failed_challenges_threshold, user_agent,
                                 decision, method):
        self.failed_challenge_ban_logs.append((ip, challenge_type))

    def ipset_add(self, config: Config, ip: str) -> None:
        self.ipset.add(ip)

    def ipset_test(self, config: Config, ip: str) -> bool:
        return ip in self.ipset

    def ipset_list(self) -> list:
        return sorted(self.ipset)

    def ipset_del(self, ip: str) -> None:
        self.ipset.discard(ip)
