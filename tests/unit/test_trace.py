"""obs/trace.py: the ring-buffered span recorder.

Covers the no-op fast path (disabled tracing must allocate nothing and
record nothing), ambient parenting, cross-thread begin/end, ring wrap,
instant events, and the Chrome trace_event export contract Perfetto
needs (X/i phases, thread_name metadata, parent ids in args)."""

import json
import threading

import pytest

from banjax_tpu.obs import trace


@pytest.fixture()
def tracer():
    t = trace.configure(enabled=True, ring_size=64)
    yield t
    trace.configure(enabled=False)


def test_disabled_tracer_is_noop_everywhere():
    trace.configure(enabled=False)
    assert trace.new_trace() == 0
    assert trace.begin("admission", 0) is trace.NOOP_SPAN
    assert trace.span("encode") is trace.NOOP_SPAN
    assert trace.span("encode", 7, 3) is trace.NOOP_SPAN
    # the noop span is inert as a context manager and as a note sink
    with trace.span("x") as sp:
        sp.note("k", "v")
    trace.instant("shed", {"lines": 3})
    trace.end(trace.NOOP_SPAN)
    assert trace.get_tracer().snapshot() == []


def test_span_parenting_explicit_and_ambient(tracer):
    tid = tracer.new_trace()
    root = tracer.begin("admission", tid)
    with tracer.span("encode", tid, parent=root.span_id) as enc:
        with tracer.span("encode-shard") as shard:  # ambient parent
            shard.note("rows", 10)
    tracer.end(root)
    spans = {s["name"]: s for s in tracer.snapshot()}
    assert set(spans) == {"admission", "encode", "encode-shard"}
    assert spans["encode"]["parent_id"] == spans["admission"]["span_id"]
    assert spans["encode-shard"]["parent_id"] == spans["encode"]["span_id"]
    assert all(s["trace_id"] == tid for s in spans.values())
    assert spans["encode-shard"]["args"]["rows"] == 10
    # record order: children complete before parents
    names = [s["name"] for s in tracer.snapshot()]
    assert names.index("encode-shard") < names.index("encode")


def test_ambient_span_without_parent_records_nothing(tracer):
    # library instrumentation (matcher/mesh) outside a traced batch
    with tracer.span("program-b") as sp:
        assert sp is trace.NOOP_SPAN
    assert tracer.snapshot() == []


def test_cross_thread_begin_end(tracer):
    tid = tracer.new_trace()
    root = tracer.begin("admission", tid, args={"items": 5})
    done = threading.Event()

    def drain_thread():
        root.note("ok", True)
        tracer.end(root)
        done.set()

    t = threading.Thread(target=drain_thread)
    t.start()
    t.join(5)
    assert done.is_set()
    (span,) = tracer.snapshot()
    assert span["name"] == "admission"
    assert span["args"] == {"items": 5, "ok": True}
    assert span["dur_us"] >= 0


def test_ring_wraps_keeping_newest():
    tracer = trace.configure(enabled=True, ring_size=16)
    try:
        tid = tracer.new_trace()
        for i in range(50):
            with tracer.span(f"s{i}", tid, parent=0):
                pass
        spans = tracer.snapshot()
        assert len(spans) == 16
        assert [s["name"] for s in spans] == [f"s{i}" for i in range(34, 50)]
    finally:
        trace.configure(enabled=False)


def test_instant_events_and_clear(tracer):
    tracer.instant("breaker-trip", {"breaker": "matcher-device"})
    tracer.instant("shed", {"lines": 100}, trace_id=3)
    events = tracer.snapshot()
    assert [e["name"] for e in events] == ["breaker-trip", "shed"]
    assert all(e["dur_us"] is None for e in events)
    assert events[1]["trace_id"] == 3
    tracer.clear()
    assert tracer.snapshot() == []


def test_chrome_export_contract(tracer):
    tid = tracer.new_trace()
    root = tracer.begin("admission", tid)
    with tracer.span("drain", tid, parent=root.span_id):
        pass
    tracer.end(root)
    tracer.instant("shed", {"lines": 2})
    out = tracer.export_chrome()
    json.dumps(out)  # must be JSON-serializable as-is
    events = out["traceEvents"]
    metas = [e for e in events if e["ph"] == "M"]
    xs = [e for e in events if e["ph"] == "X"]
    instants = [e for e in events if e["ph"] == "i"]
    assert metas and all(e["name"] == "thread_name" for e in metas)
    assert {e["name"] for e in xs} == {"admission", "drain"}
    assert all("dur" in e and "ts" in e for e in xs)
    drain = next(e for e in xs if e["name"] == "drain")
    adm = next(e for e in xs if e["name"] == "admission")
    assert drain["args"]["parent_span_id"] == adm["args"]["span_id"]
    assert instants[0]["name"] == "shed"
    assert instants[0]["s"] == "g"
    assert out["otherData"]["ring_size"] == 64


def test_concurrent_recording_is_consistent(tracer):
    """Many threads recording concurrently: no crash, every surviving
    record well-formed (the lock-cheap claim's sanity check)."""
    def worker(k):
        for i in range(200):
            tid = tracer.new_trace()
            root = tracer.begin("admission", tid)
            with tracer.span("encode", tid, parent=root.span_id):
                pass
            tracer.end(root)

    threads = [threading.Thread(target=worker, args=(k,)) for k in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(10)
    spans = tracer.snapshot()
    assert len(spans) == 64  # full ring
    for s in spans:
        assert s["name"] in ("admission", "encode")
        assert s["span_id"] > 0
        assert s["dur_us"] is not None


def test_step_annotation_noop_paths(tracer):
    # bridge off: shared noop
    assert tracer.step_annotation(5) is trace.NOOP_SPAN
    t2 = trace.configure(enabled=True, ring_size=32, jax_annotations=True)
    try:
        ctx = t2.step_annotation(5)
        with ctx:  # jax present in this env: real annotation; else noop
            pass
        assert t2.step_annotation(0) is trace.NOOP_SPAN
    finally:
        trace.configure(enabled=False)


def test_atomic_snapshot_clear_drains_exactly_once():
    tracer = trace.configure(enabled=True, ring_size=128)
    try:
        tid = tracer.new_trace()
        with tracer.span("drain", tid, parent=0):
            pass
        first = tracer.snapshot(clear=True)
        assert [s["name"] for s in first] == ["drain"]
        assert tracer.snapshot() == []  # the clear emptied the ring
    finally:
        trace.configure(enabled=False)


def test_clear_during_concurrent_dump_no_drop_or_dup():
    """Regression (ISSUE 6 satellite): /debug/trace?clear=1 racing a
    concurrent scrape must neither drop nor duplicate spans.  Writers
    record spans with unique ids while two dumper threads hammer the
    atomic snapshot(clear=True); every span id must surface in exactly
    one dump."""
    tracer = trace.configure(enabled=True, ring_size=16384)
    try:
        n_writers, per_writer = 2, 1500  # total 3000 << ring: no wrap loss
        seen = []
        seen_lock = threading.Lock()
        stop = threading.Event()

        def writer():
            tid = tracer.new_trace()
            for _ in range(per_writer):
                with tracer.span("drain", tid, parent=0):
                    pass

        def dumper():
            while not stop.is_set():
                spans = tracer.snapshot(clear=True)
                if spans:
                    with seen_lock:
                        seen.extend(s["span_id"] for s in spans)

        dumpers = [threading.Thread(target=dumper) for _ in range(2)]
        writers = [threading.Thread(target=writer) for _ in range(n_writers)]
        for t in dumpers + writers:
            t.start()
        for t in writers:
            t.join(30)
        stop.set()
        for t in dumpers:
            t.join(10)
        # final drain for anything recorded after the dumpers stopped
        seen.extend(s["span_id"] for s in tracer.snapshot(clear=True))

        total = n_writers * per_writer
        assert len(seen) == total, "a clear dropped or duplicated spans"
        assert len(set(seen)) == total  # exactly-once, no duplicates
    finally:
        trace.configure(enabled=False)
