"""Challenge-cookie crypto (reference: internal/challenge_response_test.go)."""

import base64
import time

import pytest

from banjax_tpu.crypto.challenge import (
    CookieError,
    compute_hmac,
    count_zero_bits_from_left,
    new_challenge_cookie,
    parse_cookie,
    solve_challenge_for_testing,
    validate_password_cookie,
    validate_sha_inv_cookie,
)
import hashlib


def test_count_zero_bits():
    assert count_zero_bits_from_left(b"\x80") == 0
    assert count_zero_bits_from_left(b"\x40") == 1
    assert count_zero_bits_from_left(b"\x01") == 7
    assert count_zero_bits_from_left(b"\x00\x80") == 8
    assert count_zero_bits_from_left(b"\x00\x00") == 16
    assert count_zero_bits_from_left(b"") == 0


def test_hmac_is_deterministic_and_bound():
    t = int(time.time()) + 100
    h1 = compute_hmac("secret", t, "1.2.3.4")
    h2 = compute_hmac("secret", t, "1.2.3.4")
    assert h1 == h2
    assert len(h1) == 20
    assert compute_hmac("secret", t, "5.6.7.8") != h1
    assert compute_hmac("other", t, "1.2.3.4") != h1
    assert compute_hmac("secret", t + 1, "1.2.3.4") != h1


def test_cookie_roundtrip_format():
    cookie = new_challenge_cookie("secret", 100, "1.2.3.4")
    hmac_b, solution, expiry = parse_cookie(cookie)
    assert len(hmac_b) == 20
    assert solution == b"\x00" * 32
    assert len(expiry) == 8


def test_parse_cookie_bad_base64_and_length():
    with pytest.raises(CookieError):
        parse_cookie("!!!notbase64!!!")
    with pytest.raises(CookieError):
        parse_cookie(base64.standard_b64encode(b"too short").decode())


def test_parse_cookie_plus_to_space_workaround():
    cookie = new_challenge_cookie("secret", 100, "1.2.3.4")
    mangled = cookie.replace("+", " ")
    # even if the proxy mangled '+' into ' ', parsing succeeds
    parse_cookie(mangled)


def test_sha_inv_cookie_full_lifecycle():
    now = time.time()
    fresh = new_challenge_cookie("secret", 100, "1.2.3.4")
    # unsolved cookie fails at difficulty 10 (overwhelmingly likely)
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", fresh, now, "1.2.3.4", 10)
    solved = solve_challenge_for_testing(fresh, zero_bits=10)
    validate_sha_inv_cookie("secret", solved, now, "1.2.3.4", 10)
    # wrong binding fails the hmac
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", solved, now, "5.6.7.8", 10)
    # wrong secret fails the hmac
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("other", solved, now, "1.2.3.4", 10)
    # higher difficulty than solved-for (54 bits) is essentially impossible
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", solved, now, "1.2.3.4", 54)


def test_expired_cookie_rejected():
    cookie = new_challenge_cookie("secret", -10, "1.2.3.4")
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", cookie, time.time(), "1.2.3.4", 0)


def test_password_cookie_lifecycle():
    hashed_password = hashlib.sha256(b"password").digest()
    now = time.time()
    fresh = new_challenge_cookie("secret", 100, "1.2.3.4")
    hmac_b, _, expiry = parse_cookie(fresh)
    # build the solution exactly like the client JS does:
    # solution = sha256(hmac ‖ sha256(password))
    solution = hashlib.sha256(hmac_b + hashed_password).digest()
    solved = base64.standard_b64encode(hmac_b + solution + expiry).decode()
    validate_password_cookie("secret", solved, now, "1.2.3.4", hashed_password)
    # wrong password hash rejected
    with pytest.raises(CookieError):
        validate_password_cookie(
            "secret", solved, now, "1.2.3.4", hashlib.sha256(b"wrong").digest()
        )
    # unsolved cookie rejected
    with pytest.raises(CookieError):
        validate_password_cookie("secret", fresh, now, "1.2.3.4", hashed_password)
