"""Challenge-cookie crypto (reference: internal/challenge_response_test.go)."""

import base64
import time

import pytest

from banjax_tpu.crypto.challenge import (
    CookieError,
    compute_hmac,
    count_zero_bits_from_left,
    new_challenge_cookie,
    new_challenge_cookie_at,
    parse_cookie,
    solve_challenge_for_testing,
    validate_password_cookie,
    validate_sha_inv_cookie,
)
import hashlib
import struct


def _count_zero_bits_reference(data: bytes) -> int:
    """The reference's per-byte/per-bit loop (challenge_response.go:37-49),
    retained verbatim as the oracle for the O(1) implementation."""
    count = 0
    for byte in data:
        for bit_index in range(7, -1, -1):
            if byte & (1 << bit_index) == 0:
                count += 1
            else:
                return count
    return count


def test_count_zero_bits():
    assert count_zero_bits_from_left(b"\x80") == 0
    assert count_zero_bits_from_left(b"\x40") == 1
    assert count_zero_bits_from_left(b"\x01") == 7
    assert count_zero_bits_from_left(b"\x00\x80") == 8
    assert count_zero_bits_from_left(b"\x00\x00") == 16
    assert count_zero_bits_from_left(b"") == 0


def test_count_zero_bits_exhaustive_vs_reference_loop():
    # every single-byte pattern
    for b0 in range(256):
        data = bytes([b0])
        assert count_zero_bits_from_left(data) == _count_zero_bits_reference(data), data
    # every two-byte pattern with a leading zero/low byte (the region where
    # the count crosses the byte boundary), plus every byte behind \x00
    for b0 in (0x00, 0x01, 0x02, 0x0F, 0x7F, 0x80, 0xFF):
        for b1 in range(256):
            data = bytes([b0, b1])
            assert count_zero_bits_from_left(data) == _count_zero_bits_reference(data), data
    # digest-shaped inputs: all-zero prefixes of every length up to 32 bytes
    for n_zero in range(33):
        for tail in (b"", b"\x01", b"\x80", b"\xff" * 3):
            data = b"\x00" * n_zero + tail
            assert count_zero_bits_from_left(data) == _count_zero_bits_reference(data), data


def test_hmac_is_deterministic_and_bound():
    t = int(time.time()) + 100
    h1 = compute_hmac("secret", t, "1.2.3.4")
    h2 = compute_hmac("secret", t, "1.2.3.4")
    assert h1 == h2
    assert len(h1) == 20
    assert compute_hmac("secret", t, "5.6.7.8") != h1
    assert compute_hmac("other", t, "1.2.3.4") != h1
    assert compute_hmac("secret", t + 1, "1.2.3.4") != h1


def test_cookie_roundtrip_format():
    cookie = new_challenge_cookie("secret", 100, "1.2.3.4")
    hmac_b, solution, expiry = parse_cookie(cookie)
    assert len(hmac_b) == 20
    assert solution == b"\x00" * 32
    assert len(expiry) == 8


def test_parse_cookie_bad_base64_and_length():
    with pytest.raises(CookieError):
        parse_cookie("!!!notbase64!!!")
    with pytest.raises(CookieError):
        parse_cookie(base64.standard_b64encode(b"too short").decode())


def test_parse_cookie_plus_to_space_workaround():
    cookie = new_challenge_cookie("secret", 100, "1.2.3.4")
    mangled = cookie.replace("+", " ")
    # even if the proxy mangled '+' into ' ', parsing succeeds
    parse_cookie(mangled)


def test_plus_to_space_workaround_end_to_end():
    """A solved cookie whose base64 contains '+' must validate bit-for-bit
    after a query-unescaping proxy turns every '+' into ' ' — and the
    unmangled and mangled forms must parse to identical bytes."""
    now = time.time()
    # walk bindings until the solved cookie's base64 actually contains '+'
    cookie = None
    for i in range(512):
        binding = f"10.0.0.{i}"
        fresh = new_challenge_cookie("secret", 100, binding)
        solved = solve_challenge_for_testing(fresh, zero_bits=4)
        if "+" in solved:
            cookie = (solved, binding)
            break
    assert cookie is not None, "no '+' in 512 cookies — b64 alphabet broken?"
    solved, binding = cookie
    mangled = solved.replace("+", " ")
    assert mangled != solved
    assert parse_cookie(mangled) == parse_cookie(solved)
    validate_sha_inv_cookie("secret", mangled, now, binding, 4)


def test_expiry_boundary_exact_second():
    """`expiration_int < now` is strictly-less: a cookie validated at
    exactly its expiry second still passes; any instant after it fails."""
    expiry = int(time.time()) + 50
    cookie = new_challenge_cookie_at("secret", expiry, "1.2.3.4")
    validate_sha_inv_cookie("secret", cookie, float(expiry), "1.2.3.4", 0)
    with pytest.raises(CookieError):
        validate_sha_inv_cookie(
            "secret", cookie, float(expiry) + 1e-3, "1.2.3.4", 0
        )


def test_expiry_eight_byte_big_endian_wraparound():
    """The expiry field is 8 bytes big-endian: issuance masks to 64 bits, so
    an expiry of 2^64 + t wraps to t on the wire and the HMAC is computed
    over the wrapped value — issuance and validation stay consistent."""
    now = time.time()
    t_future = int(now) + 100
    wrapped = new_challenge_cookie_at("secret", (1 << 64) + t_future, "1.2.3.4")
    plain = new_challenge_cookie_at("secret", t_future, "1.2.3.4")
    assert wrapped == plain  # byte-identical after the wrap
    validate_sha_inv_cookie("secret", wrapped, now, "1.2.3.4", 0)
    # max representable expiry (0xFF * 8) is "never expires" on the wire
    max_expiry = (1 << 64) - 1
    hmac_b = compute_hmac("secret", max_expiry, "1.2.3.4")
    raw = hmac_b[0:20] + b"\x00" * 32 + struct.pack(">Q", max_expiry)
    forever = base64.standard_b64encode(raw).decode()
    validate_sha_inv_cookie("secret", forever, now, "1.2.3.4", 0)
    # a wrapped-to-the-past expiry ((1<<64) + small) is rejected
    stale = new_challenge_cookie_at("secret", (1 << 64) + 5, "1.2.3.4")
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", stale, now, "1.2.3.4", 0)


def test_sha_inv_cookie_full_lifecycle():
    now = time.time()
    fresh = new_challenge_cookie("secret", 100, "1.2.3.4")
    # unsolved cookie fails at difficulty 10 (overwhelmingly likely)
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", fresh, now, "1.2.3.4", 10)
    solved = solve_challenge_for_testing(fresh, zero_bits=10)
    validate_sha_inv_cookie("secret", solved, now, "1.2.3.4", 10)
    # wrong binding fails the hmac
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", solved, now, "5.6.7.8", 10)
    # wrong secret fails the hmac
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("other", solved, now, "1.2.3.4", 10)
    # higher difficulty than solved-for (54 bits) is essentially impossible
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", solved, now, "1.2.3.4", 54)


def test_expired_cookie_rejected():
    cookie = new_challenge_cookie("secret", -10, "1.2.3.4")
    with pytest.raises(CookieError):
        validate_sha_inv_cookie("secret", cookie, time.time(), "1.2.3.4", 0)


def test_password_cookie_lifecycle():
    hashed_password = hashlib.sha256(b"password").digest()
    now = time.time()
    fresh = new_challenge_cookie("secret", 100, "1.2.3.4")
    hmac_b, _, expiry = parse_cookie(fresh)
    # build the solution exactly like the client JS does:
    # solution = sha256(hmac ‖ sha256(password))
    solution = hashlib.sha256(hmac_b + hashed_password).digest()
    solved = base64.standard_b64encode(hmac_b + solution + expiry).decode()
    validate_password_cookie("secret", solved, now, "1.2.3.4", hashed_password)
    # wrong password hash rejected
    with pytest.raises(CookieError):
        validate_password_cookie(
            "secret", solved, now, "1.2.3.4", hashlib.sha256(b"wrong").digest()
        )
    # unsolved cookie rejected
    with pytest.raises(CookieError):
        validate_password_cookie("secret", fresh, now, "1.2.3.4", hashed_password)
