"""AuthFastPath unit coverage: gates, memos, fail-open plumbing.

Byte-identity against the chain is proven end to end by the integration
differential (tests/integration/test_fastpath_differential.py) and the
bench witness (`bench.py --serve`); this file pins the pieces those
drive through: the eligibility gates and miss reasons, the per-
generation memo caches (session validation, QueryUnescape, global-list
probes) and their bounds/invalidation, and the fail-open exits.
"""

import time
import types

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.crypto.session import new_session_cookie
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi import fastpath as fp_mod
from banjax_tpu.httpapi.fastpath import AuthFastPath, _Gen
from banjax_tpu.httpapi.serve_stats import get_stats
from banjax_tpu.native.decisiontable import PyDecisionTable
from banjax_tpu.scenarios.runtime import RecordingBanner
from banjax_tpu.utils import go_query_escape

SECRET = "unit-secret"

BASE_YAML = f"""
config_version: t
session_cookie_hmac_secret: {SECRET}
session_cookie_ttl_seconds: 3600
disable_kafka: true
"""


class Holder:
    def __init__(self, cfg):
        self.cfg = cfg

    def get(self):
        return self.cfg


class Req:
    method = "GET"
    keep_alive = True

    def __init__(self, ip, host="eligible.example.net", cookie=None, ua="mozilla"):
        self.headers = {
            "x-client-ip": ip,
            "x-requested-host": host,
            "x-requested-path": "/",
            "x-client-user-agent": ua,
        }
        if cookie:
            self.headers["cookie"] = cookie

    def header(self, name):
        return self.headers.get(name, "")


def build(yaml_extra=""):
    cfg = config_from_yaml_text(BASE_YAML + yaml_extra)
    lists = DynamicDecisionLists(start_sweeper=False)
    table = PyDecisionTable(capacity=64)
    lists.set_mirror(table)
    deps = types.SimpleNamespace(
        config_holder=Holder(cfg),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=lists,
        protected_paths=PasswordProtectedPaths(cfg),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=RecordingBanner(),
        challenge_verifier=None,
        decision_table=table,
    )
    return AuthFastPath(deps), lists, table


@pytest.fixture(autouse=True)
def _clean_stats():
    get_stats().reset()
    yield
    get_stats().reset()


def _cookie(ip, ttl=3600):
    return go_query_escape(new_session_cookie(SECRET, ttl, ip))


def test_no_table_and_disabled_return_none():
    fp, _, _ = build()
    fp.deps.decision_table = None
    assert fp.try_serve(Req("1.2.3.4")) is None

    fp, lists, _ = build("serve_fastpath_enabled: false\n")
    lists.update("1.2.3.4", time.time() + 60, Decision.ALLOW, False, "d")
    assert fp.try_serve(Req("1.2.3.4")) is None


def test_allow_hit_mints_and_echoes():
    fp, lists, _ = build()
    lists.update("1.2.3.4", time.time() + 60, Decision.ALLOW, False, "d")

    raw, status = fp.try_serve(Req("1.2.3.4"))
    assert status == 200
    assert raw.startswith(b"HTTP/1.1 200 OK\r\n")
    assert b"X-Banjax-Decision: ExpiringAccessGranted\r\n" in raw
    assert b"X-Accel-Redirect: @access_granted\r\n" in raw
    assert b"X-Deflect-Session-New: true\r\n" in raw
    assert b"Set-Cookie: deflect_session=" in raw
    assert raw.endswith(b"access granted\n")

    cookie = _cookie("1.2.3.4")
    raw, status = fp.try_serve(
        Req("1.2.3.4", cookie=f"deflect_session={cookie}")
    )
    assert status == 200
    assert b"X-Deflect-Session-New: false\r\n" in raw
    assert b"Set-Cookie" not in raw
    assert get_stats().prom_snapshot()["hits"]["allow"] == 2


def test_block_hit_and_expired_miss():
    fp, lists, _ = build()
    lists.update("5.6.7.8", time.time() + 60, Decision.NGINX_BLOCK, False, "d")
    raw, status = fp.try_serve(Req("5.6.7.8"))
    assert status == 403
    assert b"X-Banjax-Decision: ExpiringBlock\r\n" in raw
    assert b"X-Accel-Redirect: @access_denied\r\n" in raw
    assert raw.endswith(b"access denied\n")

    # past-expiry entry: a MISS (the chain performs the lazy delete)
    lists.update("9.9.9.9", time.time() + 60, Decision.ALLOW, False, "d")
    fp.deps.decision_table.put("9.9.9.9", int(Decision.ALLOW),
                               time.time() - 1)
    assert fp.try_serve(Req("9.9.9.9")) is None
    assert get_stats().prom_snapshot()["misses"]["expired"] == 1


def test_eligibility_miss_reasons():
    fp, lists, _ = build(
        "password_protected_paths:\n  pw.example.net: [admin]\n"
        "password_protected_path_exceptions:\n  pw.example.net: []\n"
        "per_site_decision_lists:\n  site.example.net:\n    allow: [44.44.44.44]\n"
    )
    now = time.time()
    for ip in ("1.0.0.1", "1.0.0.2", "1.0.0.3", "1.0.0.4"):
        lists.update(ip, now + 60, Decision.ALLOW, False, "d")

    assert fp.try_serve(Req("1.0.0.1", host="pw.example.net")) is None
    assert fp.try_serve(Req("1.0.0.2", host="site.example.net")) is None
    assert fp.try_serve(
        Req("1.0.0.3", cookie="deflect_password3=whatever")
    ) is None
    fp.deps.decision_table.session_add(1)
    assert fp.try_serve(
        Req("1.0.0.4", cookie=f"deflect_session={_cookie('1.0.0.4')}")
    ) is None
    misses = get_stats().prom_snapshot()["misses"]
    assert misses["ineligible"] == 2
    assert misses["password"] == 1
    assert misses["session_guard"] == 1


def test_session_validation_memo_hits_until_expiry(monkeypatch):
    fp, lists, _ = build()
    lists.update("1.2.3.4", time.time() + 600, Decision.ALLOW, False, "d")
    cookie = _cookie("1.2.3.4")
    req = Req("1.2.3.4", cookie=f"deflect_session={cookie}")

    calls = []
    real = fp_mod.validate_session_cookie

    def counting(*a, **k):
        calls.append(a)
        return real(*a, **k)

    monkeypatch.setattr(fp_mod, "validate_session_cookie", counting)
    first, _ = fp.try_serve(req)
    second, _ = fp.try_serve(req)
    assert first == second
    assert len(calls) == 1  # second request rode the memo

    # the memo honors the expiry embedded in the cookie bytes: push the
    # cached expiry into the past and the HMAC runs again
    gen = fp._gen
    (key,) = list(gen.session_cache)
    gen.session_cache[key] = time.time() - 1
    third, _ = fp.try_serve(req)
    assert third == first
    assert len(calls) == 2


def test_unescape_memo_covers_reject_and_bound(monkeypatch):
    fp, lists, _ = build()
    lists.update("1.2.3.4", time.time() + 600, Decision.ALLOW, False, "d")

    # a malformed escape is memoized as a reject (cookie skipped) and
    # the request still serves — twice, the second off the cache
    bad = Req("1.2.3.4", cookie="deflect_session=bad%zz")
    raw1, _ = fp.try_serve(bad)
    assert b"X-Deflect-Session-New: true\r\n" in raw1
    gen = fp._gen
    assert gen.unescape_cache.get("bad%zz", "sentinel") is None
    raw2, _ = fp.try_serve(bad)
    assert b"X-Deflect-Session-New: true\r\n" in raw2

    # the bound clears rather than growing without limit
    monkeypatch.setattr(_Gen, "CACHE_MAX", 2)
    for i in range(6):
        fp.try_serve(Req("1.2.3.4", cookie=f"deflect_session=v%2B{i}"))
    assert len(gen.unescape_cache) <= 2


def test_global_list_memo_and_miss(monkeypatch):
    fp, lists, _ = build(
        "global_decision_lists:\n  nginx_block: [70.70.70.70]\n"
    )
    now = time.time()
    lists.update("70.70.70.70", now + 60, Decision.ALLOW, False, "d")
    lists.update("1.2.3.4", now + 60, Decision.ALLOW, False, "d")

    # globally-listed IP: the chain owns it, memoized either way
    assert fp.try_serve(Req("70.70.70.70")) is None
    assert fp.try_serve(Req("70.70.70.70")) is None
    assert get_stats().prom_snapshot()["misses"]["global_list"] == 2
    gen = fp._gen
    assert gen.global_ip_cache["70.70.70.70"] is True
    assert gen.global_ip_cache.get("1.2.3.4") is None

    calls = []
    real = fp.deps.static_lists.check_global

    def counting(ip):
        calls.append(ip)
        return real(ip)

    monkeypatch.setattr(fp.deps.static_lists, "check_global", counting)
    raw, status = fp.try_serve(Req("1.2.3.4"))
    assert status == 200
    assert calls == ["1.2.3.4"]
    fp.try_serve(Req("1.2.3.4"))
    assert calls == ["1.2.3.4"]  # second probe rode the memo


def test_generation_swap_rebuilds_memos():
    fp, lists, _ = build()
    lists.update("1.2.3.4", time.time() + 600, Decision.ALLOW, False, "d")
    fp.try_serve(Req("1.2.3.4", cookie=f"deflect_session={_cookie('1.2.3.4')}"))
    old_gen = fp._gen
    assert old_gen.session_cache

    # hot reload swaps the config object: fresh generation, empty memos
    fp.deps.config_holder.cfg = config_from_yaml_text(BASE_YAML)
    raw, status = fp.try_serve(Req("1.2.3.4"))
    assert status == 200
    assert fp._gen is not old_gen
    assert fp._gen.session_cache == {}


def test_unknown_decision_byte_falls_open():
    fp, lists, table = build()
    table.put("1.2.3.4", 99, time.time() + 60)
    assert fp.try_serve(Req("1.2.3.4")) is None
    assert get_stats().prom_snapshot()["misses"]["table"] == 1


def test_lookup_exception_is_a_counted_fault(monkeypatch):
    fp, lists, _ = build()
    lists.update("1.2.3.4", time.time() + 60, Decision.ALLOW, False, "d")
    monkeypatch.setattr(
        AuthFastPath, "_lookup",
        lambda *a, **k: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert fp.try_serve(Req("1.2.3.4")) is None
    assert get_stats().prom_snapshot()["faults_total"] == 1
