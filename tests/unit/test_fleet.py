"""Fleet observability plane units (ISSUE 20): exposition merge
semantics (counter-sum / gauge-instance-label / histogram-bucket-add),
the strict round-trip of the merged payload, the origin index the
cross-host trace join rides on, health-bit packing, the federated
scraper's partial-but-honest degradation, and the incident capture
fan-out."""

import pytest

from banjax_tpu.fabric import wire
from banjax_tpu.obs import registry
from banjax_tpu.obs.exposition import parse_text_format
from banjax_tpu.obs.fleet import (
    HEALTH_BREAKER_HALF_OPEN,
    HEALTH_BREAKER_OPEN,
    HEALTH_SLO_BREACHED,
    FleetScraper,
    OriginIndex,
    capture_fleet,
    compute_health_bits,
    local_capture_files,
    merge_expositions,
)
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN


def _samples(parsed, fam):
    return {
        (name, tuple(sorted(labels.items()))): value
        for name, labels, value in parsed[fam]["samples"]
    }


# ---------------------------------------------------------------- merge


COUNTER_A = (
    "# HELP banjax_x_total things\n"
    "# TYPE banjax_x_total counter\n"
    'banjax_x_total{kind="a"} 3\n'
    'banjax_x_total{kind="b"} 10\n'
)
COUNTER_B = (
    "# HELP banjax_x_total things\n"
    "# TYPE banjax_x_total counter\n"
    'banjax_x_total{kind="a"} 4\n'
)


def test_merge_counters_sum_per_labelset_without_instance_label():
    merged = merge_expositions({"w0": COUNTER_A, "w1": COUNTER_B})
    parsed = parse_text_format(merged)
    sams = _samples(parsed, "banjax_x_total")
    assert sams[("banjax_x_total", (("kind", "a"),))] == 7
    assert sams[("banjax_x_total", (("kind", "b"),))] == 10
    # the fleet total carries NO instance label: single-node alert
    # rules keep matching the cluster aggregate
    for _, labels, _v in parsed["banjax_x_total"]["samples"]:
        assert "instance" not in labels


def test_merge_gauges_labeled_per_instance_never_summed():
    g = (
        "# HELP banjax_g current state\n"
        "# TYPE banjax_g gauge\n"
        "banjax_g 5\n"
    )
    g2 = g.replace(" 5", " 7")
    merged = merge_expositions({"w0": g, "w1": g2})
    parsed = parse_text_format(merged)
    sams = _samples(parsed, "banjax_g")
    assert sams[("banjax_g", (("instance", "w0"),))] == 5
    assert sams[("banjax_g", (("instance", "w1"),))] == 7


HIST_A = (
    "# HELP banjax_h_seconds latency\n"
    "# TYPE banjax_h_seconds histogram\n"
    'banjax_h_seconds_bucket{le="0.5"} 1\n'
    'banjax_h_seconds_bucket{le="+Inf"} 2\n'
    "banjax_h_seconds_sum 0.9\n"
    "banjax_h_seconds_count 2\n"
)
HIST_B = (
    "# HELP banjax_h_seconds latency\n"
    "# TYPE banjax_h_seconds histogram\n"
    'banjax_h_seconds_bucket{le="1.0"} 3\n'
    'banjax_h_seconds_bucket{le="+Inf"} 3\n'
    "banjax_h_seconds_sum 1.0\n"
    "banjax_h_seconds_count 3\n"
)


def test_merge_histograms_union_bounds_carry_forward_and_sum():
    merged = merge_expositions({"w0": HIST_A, "w1": HIST_B})
    parsed = parse_text_format(merged)
    by_le = {
        labels["le"]: value
        for name, labels, value in parsed["banjax_h_seconds"]["samples"]
        if name == "banjax_h_seconds_bucket"
    }
    # union of bounds: 0.5 from A, 1.0 from B, +Inf from both.
    # At 0.5: A=1, B has no bound below -> 0.  At 1.0: A carries its
    # 0.5 count forward (1), B=3 -> 4.  At +Inf: 2+3.
    assert by_le["0.5"] == 1
    assert by_le["1.0"] == 4
    assert by_le["+Inf"] == 5
    sams = _samples(parsed, "banjax_h_seconds")
    assert sams[("banjax_h_seconds_sum", ())] == pytest.approx(1.9)
    assert sams[("banjax_h_seconds_count", ())] == 5


def test_merge_output_round_trips_the_strict_parser():
    # the parser enforces: trailing newline, TYPE before samples,
    # histogram monotonicity + sum/count consistency, counter
    # non-negativity — the merged text must satisfy ALL of it
    merged = merge_expositions({
        "w0": COUNTER_A + HIST_A,
        "w1": COUNTER_B + HIST_B,
    })
    parsed = parse_text_format(merged)
    assert set(parsed) == {"banjax_x_total", "banjax_h_seconds"}


def test_merge_single_instance_is_semantically_identity():
    merged = merge_expositions({"w0": COUNTER_A + HIST_A})
    parsed = parse_text_format(merged)
    assert _samples(parsed, "banjax_x_total") == _samples(
        parse_text_format(COUNTER_A), "banjax_x_total"
    )


# --------------------------------------------------------- origin index


def test_origin_index_note_resolve_and_lru_eviction():
    idx = OriginIndex(max_entries=16)
    for i in range(20):
        idx.note(f"1.2.3.{i}", "w0", 100 + i)
    assert len(idx) == 16
    # the 4 oldest attributions were evicted
    assert idx.resolve("1.2.3.0") is None
    assert idx.resolve("1.2.3.19") == ("w0", 119)


def test_origin_index_renote_moves_to_back_and_overwrites():
    idx = OriginIndex(max_entries=16)
    idx.note("9.9.9.9", "w0", 1)
    for i in range(15):
        idx.note(f"1.2.3.{i}", "w1", i)
    idx.note("9.9.9.9", "w2", 2)  # re-noted: now the newest
    idx.note("1.2.3.99", "w1", 99)  # evicts 1.2.3.0, not 9.9.9.9
    assert idx.resolve("9.9.9.9") == ("w2", 2)
    assert idx.resolve("1.2.3.0") is None


def test_origin_index_empty_origin_is_a_noop():
    idx = OriginIndex()
    idx.note("1.2.3.4", "", 7)
    assert idx.resolve("1.2.3.4") is None


# ----------------------------------------------------------- health bits


class _Slo:
    def __init__(self, breached):
        self._b = breached

    def breached(self):
        return {"shed": self._b}


class _Matcher:
    def __init__(self, state):
        self.breaker = type("B", (), {"state": state})()


def test_compute_health_bits_packs_slo_and_breaker():
    assert compute_health_bits() == 0
    assert compute_health_bits(slo=_Slo(True)) == HEALTH_SLO_BREACHED
    assert compute_health_bits(matcher=_Matcher(OPEN)) == HEALTH_BREAKER_OPEN
    assert compute_health_bits(
        matcher=_Matcher(HALF_OPEN)
    ) == HEALTH_BREAKER_HALF_OPEN
    assert compute_health_bits(
        slo=_Slo(True), matcher=_Matcher(OPEN)
    ) == HEALTH_SLO_BREACHED | HEALTH_BREAKER_OPEN
    assert compute_health_bits(slo=_Slo(False),
                               matcher=_Matcher(CLOSED)) == 0


def test_compute_health_bits_swallows_provider_bugs():
    class Bad:
        def breached(self):
            raise RuntimeError("boom")

    assert compute_health_bits(slo=Bad()) == 0


# -------------------------------------------------------------- scraper


LOCAL = COUNTER_A


def _fleet_gauges(text, fam):
    parsed = parse_text_format(text)
    return {
        labels["instance"]: value
        for _n, labels, value in parsed[fam]["samples"]
    }


def test_scraper_merges_local_and_fresh_peer():
    scraper = FleetScraper(
        "w0", lambda: LOCAL, peers_fn=lambda: {"w1": lambda: COUNTER_B}
    )
    text = scraper.scrape()
    parsed = parse_text_format(text)
    sams = _samples(parsed, "banjax_x_total")
    assert sams[("banjax_x_total", (("kind", "a"),))] == 7
    unreach = _fleet_gauges(text, "banjax_fleet_peer_unreachable")
    assert unreach == {"w0": 0, "w1": 0}
    stale = _fleet_gauges(text, "banjax_fleet_peer_staleness_seconds")
    assert stale == {"w0": 0, "w1": 0}


def test_scraper_dead_peer_is_partial_but_honest_never_a_raise():
    clock = [100.0]
    calls = {"n": 0}

    def pull():
        calls["n"] += 1
        if calls["n"] > 1:
            raise OSError("peer died")
        return COUNTER_B

    scraper = FleetScraper(
        "w0", lambda: LOCAL, peers_fn=lambda: {"w1": pull},
        clock=lambda: clock[0],
    )
    scraper.scrape()  # caches w1
    clock[0] = 107.5
    text = scraper.scrape()  # w1 now dead -> cached + flagged
    parsed = parse_text_format(text)  # still strictly parseable
    assert _samples(parsed, "banjax_x_total")[
        ("banjax_x_total", (("kind", "a"),))
    ] == 7  # cached snapshot still merged in
    assert _fleet_gauges(text, "banjax_fleet_peer_unreachable")["w1"] == 1
    assert _fleet_gauges(
        text, "banjax_fleet_peer_staleness_seconds"
    )["w1"] == pytest.approx(7.5)


def test_scraper_dead_peer_with_no_cache_drops_out_flagged():
    def pull():
        raise OSError("never reachable")

    scraper = FleetScraper(
        "w0", lambda: LOCAL, peers_fn=lambda: {"w1": pull}
    )
    text = scraper.scrape()
    parsed = parse_text_format(text)
    assert _samples(parsed, "banjax_x_total")[
        ("banjax_x_total", (("kind", "a"),))
    ] == 3  # local only
    assert _fleet_gauges(text, "banjax_fleet_peer_unreachable")["w1"] == 1


def test_scraper_corrupt_peer_payload_treated_as_unreachable():
    scraper = FleetScraper(
        "w0", lambda: LOCAL,
        peers_fn=lambda: {"w1": lambda: "not a prometheus payload"},
    )
    text = scraper.scrape()
    parse_text_format(text)
    assert _fleet_gauges(text, "banjax_fleet_peer_unreachable")["w1"] == 1


def test_scraper_pull_failpoint_degrades_that_peer():
    try:
        failpoints.arm("obs.fleet.pull", count=1)
        scraper = FleetScraper(
            "w0", lambda: LOCAL,
            peers_fn=lambda: {"w1": lambda: COUNTER_B},
        )
        text = scraper.scrape()
        assert _fleet_gauges(
            text, "banjax_fleet_peer_unreachable"
        )["w1"] == 1
        text = scraper.scrape()  # failpoint exhausted -> fresh again
        assert _fleet_gauges(
            text, "banjax_fleet_peer_unreachable"
        )["w1"] == 0
    finally:
        failpoints.disarm()


def test_fleet_collect_sums_pipeline_counters_across_instances():
    def node(admitted, processed, shed, drain_err, stale):
        return (
            "# HELP banjax_pipeline_admitted_lines_total a\n"
            "# TYPE banjax_pipeline_admitted_lines_total counter\n"
            f"banjax_pipeline_admitted_lines_total {admitted}\n"
            "# HELP banjax_pipeline_processed_lines_total p\n"
            "# TYPE banjax_pipeline_processed_lines_total counter\n"
            f"banjax_pipeline_processed_lines_total {processed}\n"
            "# HELP banjax_pipeline_shed_lines_total s\n"
            "# TYPE banjax_pipeline_shed_lines_total counter\n"
            f"banjax_pipeline_shed_lines_total {shed}\n"
            "# HELP banjax_pipeline_drain_error_lines_total d\n"
            "# TYPE banjax_pipeline_drain_error_lines_total counter\n"
            f"banjax_pipeline_drain_error_lines_total {drain_err}\n"
            "# HELP banjax_pipeline_stale_dropped_lines_total st\n"
            "# TYPE banjax_pipeline_stale_dropped_lines_total counter\n"
            f"banjax_pipeline_stale_dropped_lines_total {stale}\n"
        )

    scraper = FleetScraper(
        "w0", lambda: node(100, 90, 5, 1, 4),
        peers_fn=lambda: {"w1": lambda: node(50, 48, 2, 0, 0)},
    )
    assert scraper.fleet_collect() == {}  # no scrape yet
    scraper.scrape()
    got = scraper.fleet_collect()
    assert got["admitted"] == 150
    assert got["processed"] == 138
    assert got["shed"] == 8  # shed + drain_error, both instances
    assert got["stale"] == 4


# -------------------------------------------------------------- capture


def test_local_capture_files_shapes():
    files = local_capture_files(
        metrics_text_fn=lambda: LOCAL,
        fabric_fn=lambda: {"enabled": True, "node_id": "w0"},
    )
    assert set(files) == {
        "trace.json", "metrics.prom", "provenance.json", "fabric.json"
    }
    assert files["metrics.prom"] == LOCAL


def test_capture_fleet_failed_peer_contributes_error_txt():
    def peers():
        return {
            "w1": lambda incident: {"metrics.prom": LOCAL},
            "w2": lambda incident: (_ for _ in ()).throw(OSError("dead")),
        }

    out = capture_fleet("inc-1", peers)
    assert out["w1"] == {"metrics.prom": LOCAL}
    assert list(out["w2"]) == ["error.txt"]
    assert "dead" in out["w2"]["error.txt"]


def test_capture_fleet_failpoint_and_filename_sanitization():
    try:
        failpoints.arm("obs.fleet.capture", count=1)
        out = capture_fleet(
            "inc-2", lambda: {"w1": lambda i: {"metrics.prom": "x\n"}}
        )
        assert list(out["w1"]) == ["error.txt"]
    finally:
        failpoints.disarm()
    out = capture_fleet(
        "inc-3",
        lambda: {"w1": lambda i: {
            "../escape": "no", "/abs": "no", "ok.json": "yes",
        }},
    )
    assert out["w1"] == {"ok.json": "yes"}


# ------------------------------------------------- wire origin sections


def test_wire_v2_origin_roundtrip_both_frames():
    lines = ["a", "b", "c"]
    buf = wire.encode_lines_v2(
        7, lines, origin_node="w0", origin_runs=((0, 2), (2, 1)),
        origin_t_read=123.5,
    )
    fr = wire.decode_lines_v2(buf[wire._HEADER.size:])
    assert fr.lines == tuple(lines)
    assert fr.origin_node == "w0"
    assert fr.origin_runs == ((0, 2), (2, 1))
    assert fr.origin_t_read == pytest.approx(123.5)
    # no origin -> empty section, decodes to the defaults
    buf = wire.encode_lines_v2(8, lines)
    fr = wire.decode_lines_v2(buf[wire._HEADER.size:])
    assert fr.origin_node == ""
    assert fr.origin_runs == ()
    assert fr.origin_t_read == 0.0


def test_wire_v2_origin_defaults_whole_chunk_run():
    buf = wire.encode_lines_v2(9, ["x", "y"], origin_node="w3")
    fr = wire.decode_lines_v2(buf[wire._HEADER.size:])
    assert fr.origin_runs == ((0, 2),)


# ------------------------------------------------------ registry schema


def test_fleet_families_declared_in_registry():
    fams = registry.PROM_FAMILIES
    assert fams["banjax_fabric_peer_health"].kind == "gauge"
    assert fams["banjax_fabric_peer_health"].labels == ("node",)
    assert fams["banjax_fleet_peer_unreachable"].kind == "gauge"
    assert fams["banjax_fleet_peer_unreachable"].labels == ("instance",)
    assert fams["banjax_fleet_peer_staleness_seconds"].kind == "gauge"
    assert fams["banjax_e2e_latency_seconds"].kind == "histogram"
    assert fams["banjax_e2e_latency_seconds"].labels == ("hop",)


def test_fleet_failpoint_sites_are_known():
    assert "obs.fleet.pull" in failpoints.KNOWN_SITES
    assert "obs.fleet.capture" in failpoints.KNOWN_SITES
