"""Static decision lists: exact IP + CIDR matching (reference: internal/decision.go:88-374)."""

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.model import Decision, FailAction
from banjax_tpu.decisions.static_lists import StaticDecisionLists


YAML = """
global_decision_lists:
  allow:
    - 20.20.20.20
    - 10.0.0.0/8
  iptables_block:
    - 30.40.50.60
  nginx_block:
    - 70.80.90.100
    - 192.168.0.0/16
  challenge:
    - 8.8.8.8
per_site_decision_lists:
  example.com:
    allow:
      - 90.90.90.90
    challenge:
      - 91.91.91.91
      - 172.16.0.0/12
sitewide_sha_inv_list:
  example.com: block
  foobar.com: no_block
"""


def make_lists():
    return StaticDecisionLists(config_from_yaml_text(YAML))


def test_global_exact():
    lists = make_lists()
    assert lists.check_global("20.20.20.20") == (Decision.ALLOW, True)
    assert lists.check_global("30.40.50.60") == (Decision.IPTABLES_BLOCK, True)
    assert lists.check_global("8.8.8.8") == (Decision.CHALLENGE, True)
    assert lists.check_global("1.1.1.1") == (None, False)


def test_global_cidr():
    lists = make_lists()
    assert lists.check_global("10.1.2.3") == (Decision.ALLOW, True)
    assert lists.check_global("192.168.55.1") == (Decision.NGINX_BLOCK, True)


def test_per_site():
    lists = make_lists()
    assert lists.check_per_site("example.com", "90.90.90.90") == (Decision.ALLOW, True)
    assert lists.check_per_site("example.com", "91.91.91.91") == (Decision.CHALLENGE, True)
    assert lists.check_per_site("example.com", "172.20.1.1") == (Decision.CHALLENGE, True)
    assert lists.check_per_site("example.com", "1.1.1.1") == (None, False)
    assert lists.check_per_site("other.com", "90.90.90.90") == (None, False)


def test_sitewide_sha_inv():
    lists = make_lists()
    assert lists.check_sitewide_sha_inv("example.com") == (FailAction.BLOCK, True)
    assert lists.check_sitewide_sha_inv("foobar.com") == (FailAction.NO_BLOCK, True)
    fa, ok = lists.check_sitewide_sha_inv("nope.com")
    assert not ok


def test_check_is_allowed():
    lists = make_lists()
    # global exact allow
    assert lists.check_is_allowed("anything.com", "20.20.20.20")
    # global CIDR allow
    assert lists.check_is_allowed("anything.com", "10.9.9.9")
    # per-site exact allow
    assert lists.check_is_allowed("example.com", "90.90.90.90")
    # challenge is not allow
    assert not lists.check_is_allowed("anything.com", "8.8.8.8")
    assert not lists.check_is_allowed("example.com", "91.91.91.91")
    assert not lists.check_is_allowed("anything.com", "4.4.4.4")


def test_hot_reload_swaps_snapshot():
    lists = make_lists()
    assert lists.check_global("20.20.20.20") == (Decision.ALLOW, True)
    new_cfg = config_from_yaml_text(
        """
global_decision_lists:
  nginx_block:
    - 20.20.20.20
"""
    )
    lists.update_from_config(new_cfg)
    assert lists.check_global("20.20.20.20") == (Decision.NGINX_BLOCK, True)
    assert lists.check_global("30.40.50.60") == (None, False)


def test_filter_order_allow_wins_over_block():
    # an IP covered by both an allow CIDR and a block CIDR: the filter scan
    # order Allow→Challenge→NginxBlock→IptablesBlock means allow wins
    cfg = config_from_yaml_text(
        """
global_decision_lists:
  iptables_block:
    - 10.0.0.0/8
  allow:
    - 10.1.0.0/16
"""
    )
    lists = StaticDecisionLists(cfg)
    assert lists.check_global("10.1.2.3") == (Decision.ALLOW, True)
    assert lists.check_global("10.2.2.3") == (Decision.IPTABLES_BLOCK, True)


def test_has_any_allow_entries():
    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.static_lists import StaticDecisionLists

    base = """
regexes_with_rates: []
"""
    sl = StaticDecisionLists(config_from_yaml_text(base))
    assert not sl.has_any_allow_entries()

    for yaml_frag in (
        "global_decision_lists:\n  allow:\n    - 1.1.1.1\n",
        "global_decision_lists:\n  allow:\n    - 10.0.0.0/8\n",
        "per_site_decision_lists:\n  a.com:\n    allow:\n      - 2.2.2.2\n",
        "per_site_decision_lists:\n  a.com:\n    allow:\n      - 2.2.0.0/16\n",
    ):
        sl2 = StaticDecisionLists(config_from_yaml_text(base + yaml_frag))
        assert sl2.has_any_allow_entries(), yaml_frag
    # non-allow lists alone do not count
    sl3 = StaticDecisionLists(config_from_yaml_text(
        base + "global_decision_lists:\n  nginx_block:\n    - 3.3.3.3\n"
    ))
    assert not sl3.has_any_allow_entries()


def test_ipfilter_fast_path_differential():
    """The inet_pton membership fast path agrees with the ipaddress-module
    slow path on every accept/reject edge case (IPFilter.allowed)."""
    import ipaddress

    from banjax_tpu.decisions.static_lists import IPFilter

    entries = [
        "20.20.20.20", "10.0.0.0/8", "192.168.1.0/24", "2001:db8::1",
        "2001:db8:1::/48", "255.255.255.255", "0.0.0.0/0 oops", "garbage",
    ]
    f = IPFilter([e for e in entries if "oops" not in e])

    def slow(ip_string):
        try:
            addr = ipaddress.ip_address(ip_string)
        except ValueError:
            return False
        nets = [
            ipaddress.ip_network(e, strict=False)
            for e in entries
            if "/" in e and "oops" not in e
        ]
        singles = {
            ipaddress.ip_address(e)
            for e in entries
            if "/" not in e and e not in ("garbage",)
        }
        return addr in singles or any(addr in n for n in nets)

    cases = [
        "20.20.20.20", "20.20.20.21", "10.1.2.3", "11.1.2.3",
        "192.168.1.77", "192.168.2.77", "2001:db8::1", "2001:db8::2",
        "2001:db8:1::ffff", "2001:db8:2::ffff", "255.255.255.255",
        # reject-form edge cases: both paths must agree on rejection
        "01.2.3.4", "1.2.3", "1.2.3.4.5", " 1.2.3.4", "1.2.3.4 ",
        "256.1.1.1", "1.2.3.04", "", "::", "::1", "not-an-ip",
        "10.0.0.0/8",  # a CIDR is not an address
        "0x0a.1.2.3",
    ]
    import random

    rng = random.Random(5)
    for _ in range(500):
        cases.append(
            f"{rng.randint(0, 299)}.{rng.randint(0, 299)}"
            f".{rng.randint(0, 299)}.{rng.randint(0, 299)}"
        )
    for ip in cases:
        assert f.allowed(ip) == slow(ip), ip


def test_ipfilter_scoped_ipv6_slow_path():
    """Scoped IPv6 input falls back to ipaddress-module semantics."""
    from banjax_tpu.decisions.static_lists import IPFilter

    f = IPFilter(["fe80::1"])
    assert f.allowed("fe80::1") is True
    # a scoped input is not equal to the unscoped single (ipaddress
    # equality includes the zone), so it must NOT match
    assert f.allowed("fe80::1%eth0") is False
