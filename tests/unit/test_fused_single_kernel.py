"""Single-kernel fused match+window path (matcher/kernels/
fused_match_window.py + the `single_kernel` dispatch mode of
matcher/fused_windows.py), interpret-mode on CPU — tier-1.

Covers the kernel itself (the Pallas window-scan vs the lax.scan it must
reproduce bit-for-bit), the threshold-fire edges of the fixed-window
recurrence, the in-kernel overflow flag routing to the classic fallback,
the submit-time live-mask staleness cut, chain reseeding after an
overflow burst, and the config key's auto/on/off resolution."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import yaml

import bench
from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher import windows as W
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.kernels import fused_match_window as fmw
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.resilience.health import HealthRegistry, HealthStatus
from tests.mock_banner import MockBanner


def _rules_yaml(patterns, hits=3, interval=20):
    return yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"r{i}", "regex": p, "interval": interval,
             "hits_per_interval": hits, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })


def _mk(cls, yaml_text, health=None, **ov):
    cfg = config_from_yaml_text(yaml_text)
    for k, v in ov.items():
        setattr(cfg, k, v)
    banner = MockBanner()
    kwargs = {"health": health} if health is not None else {}
    return cls(
        cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates(),
        **kwargs,
    ), banner


def _key(res):
    return [
        (x.rule_name, x.regex_match, x.skip_host, x.seen_ip,
         None if x.rate_limit_result is None else
         (int(x.rate_limit_result.match_type), x.rate_limit_result.exceeded))
        for x in res.rule_results
    ]


# ---------------------------------------------------------------------------
# the Pallas window-scan kernel itself
# ---------------------------------------------------------------------------


def test_window_scan_kernel_matches_lax_scan_randomized():
    """The interpret-mode kernel must reproduce the XLA lax.scan over
    windows._window_step bit-for-bit — including boundaries, invalid
    segments, pads, restarts, and the reset-to-0-on-exceed quirk."""
    rng = np.random.default_rng(123)
    for E in (8, 64, 256):
        pad = np.zeros(E, dtype=bool)
        pad[rng.integers(0, E, max(1, E // 5))] = True
        xs = (
            jnp.asarray(rng.integers(0, 2, E).astype(bool)),
            jnp.asarray(rng.integers(0, 9, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 100, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 10**9, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 2, E).astype(bool)),
            jnp.asarray(rng.integers(0, 120, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 10**9, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 5, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 30, E).astype(np.int32)),
            jnp.asarray(rng.integers(0, 10**9, E).astype(np.int32)),
            jnp.asarray(pad),
        )
        init = (jnp.int32(0), jnp.int32(0), jnp.int32(0))
        _, want = jax.lax.scan(W._window_step, init, xs)
        got = fmw.window_scan(True)(init, xs)
        for name, w, g in zip(
            ("hits", "ss", "sns", "mtype", "exceeded"), want, got
        ):
            assert np.array_equal(np.asarray(w), np.asarray(g)), (E, name)


def test_scan_selftest_passes_and_is_cheap():
    fmw.scan_selftest(True)       # the matcher-construction gate
    fmw.scan_selftest(True, E=8)  # smallest shape


def test_window_scan_under_jit():
    """The kernel must compose inside an outer jit — that is how the
    single program uses it (windows._apply_core scan_fn)."""
    E = 32
    xs = tuple(
        jnp.asarray(np.zeros(E, dtype=np.int32)) for _ in range(11)
    )

    @jax.jit
    def run(xs):
        return fmw.window_scan(True)(None, xs)

    out = run(xs)
    assert all(np.asarray(o).shape == (E,) for o in out)


# ---------------------------------------------------------------------------
# threshold-fire edges (driven through the full single-kernel matcher)
# ---------------------------------------------------------------------------


def _edge_pair(interval, hits):
    y = _rules_yaml([r"GET /edge.*"], hits=hits, interval=interval)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(TpuMatcher, y, matcher_device_windows=True,
                  matcher_prefilter_cand_frac=1.0)
    assert tpu._fw_pipeline is not None and tpu._fw_pipeline.single_kernel
    return cpu, cb, tpu, tb


@pytest.mark.parametrize("offsets,hits", [
    # exactly-at-interval is INSIDE (window restart is strictly greater
    # than interval); epsilon past it restarts the window
    ((0.0, 5.0, 5.25), 2),
    # hits_per_interval=0: the very first hit fires, counter resets to 0
    ((0.0, 0.25, 0.5), 0),
    # hits=1: every second hit inside the window fires
    ((0.0, 0.25, 0.5, 0.75), 1),
])
def test_threshold_fire_edges(offsets, hits):
    cpu, cb, tpu, tb = _edge_pair(interval=5, hits=hits)
    now = 1_700_000_000.0  # integer-second base: offsets stay float-exact
    lines = [
        f"{now + off:.6f} 6.6.6.6 GET h.com GET /edge{k} HTTP/1.1 ua -"
        for k, off in enumerate(offsets)
    ]
    want = [cpu.consume_line(l, now + 9) for l in lines]
    got = tpu.consume_lines(lines, now + 9)
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans and cb.regex_ban_logs == tb.regex_ban_logs
    assert cpu.rate_limit_states.format_states() == \
        tpu.device_windows.format_states()
    assert tpu._fw_pipeline.sk_chunks > 0  # really took the single kernel


# ---------------------------------------------------------------------------
# overflow flags → classic fallback (in-kernel gate)
# ---------------------------------------------------------------------------


def test_event_overflow_flag_routes_to_classic_fallback():
    """More window events than max_events: the kernel's gate drops every
    state write (the donated state passes through untouched) and the
    chunk replays classically — output identical to the CPU oracle."""
    patterns = bench.generate_rules(30, seed=33) + [r".*"]
    now = time.time()
    rests = bench.generate_lines(256, patterns[:-1], seed=3, attack_rate=0.1)
    lines = [
        f"{now + i * 0.0005:.6f} 10.9.{i % 24}.1 {r}"
        for i, r in enumerate(rests)
    ]
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(TpuMatcher, y, matcher_device_windows=True,
                  matcher_batch_lines=256, matcher_prefilter_cand_frac=1.0)
    assert tpu._fw_pipeline.single_kernel
    tpu.device_windows.max_events = max(tpu.compiled.n_rules, 64)
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert tpu._fw_pipeline.sk_fallbacks > 0


def test_candidate_overflow_flag_with_tight_slot_capacity():
    """Candidate overflow (all-matching burst) composed with a slot table
    too small for the distinct-IP load (eviction churn + split retries):
    the overflow flag routes to the single-stage recompute and spill
    stays lossless — byte-identical to the oracle."""
    patterns = bench.generate_rules(20, seed=36)
    now = time.time()
    rests = bench.generate_lines(300, patterns, seed=10, attack_rate=1.0)
    lines = [
        f"{now + i * 0.0005:.6f} 10.9.{i % 90}.1 {r}"
        for i, r in enumerate(rests)
    ]
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(
        TpuMatcher, y, matcher_device_windows=True,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0 / 64,
        matcher_window_capacity=16,
    )
    assert tpu._fw_pipeline.single_kernel
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert tpu._fw_pipeline.sk_fallbacks > 0
    assert tpu.device_windows.eviction_count > 0
    assert cpu.rate_limit_states.format_states() == \
        tpu.device_windows.format_states()


def test_chain_reseeds_after_quiescence():
    """An overflow poisons the device-side ok chain for in-flight chunks;
    once the burst drains (quiescence), the chain reseeds and the next
    batch commits through the single kernel again."""
    patterns = [r"POST /x[a-z0-9]*"]
    now = time.time()
    y = _rules_yaml(patterns, hits=50)
    tpu, _ = _mk(TpuMatcher, y, matcher_device_windows=True,
                 matcher_batch_lines=64,
                 matcher_prefilter_cand_frac=1.0 / 64)
    assert tpu._fw_pipeline.single_kernel
    flood = [
        f"{now:.6f} 7.7.7.{i % 9} POST h.com POST /x{i} HTTP/1.1 ua -"
        for i in range(128)
    ]
    tpu.consume_lines(flood, now)  # every chunk overflows candidates
    assert tpu._fw_pipeline.sk_fallbacks > 0
    benign = [
        f"{now:.6f} 8.8.8.{i % 9} GET h.com GET /quiet{i} HTTP/1.1 ua -"
        for i in range(64)
    ]
    before = tpu._fw_pipeline.sk_chunks
    tpu.consume_lines(benign, now)  # quiescent start → fresh chain
    assert tpu._fw_pipeline.sk_chunks > before, "chain never reseeded"


# ---------------------------------------------------------------------------
# live-mask staleness (submit-time cut on the split protocol)
# ---------------------------------------------------------------------------


def test_live_mask_staleness_at_submit():
    """The single-kernel analog of the drain-stale test: the 10 s cutoff
    is evaluated at SUBMIT (the kernel's live-mask input) — rows already
    old there contribute no event and no state write, fresh rows in the
    SAME chunk commit normally, and the drain replays from the carried
    mask (no drain-time re-cut)."""
    patterns = [r"GET /blockme.*"]
    now = time.time()
    y = _rules_yaml(patterns, hits=0, interval=1)
    m, banner = _mk(TpuMatcher, y, matcher_device_windows=True,
                    matcher_prefilter_cand_frac=1.0)
    assert m._fw_pipeline.single_kernel
    old = [
        f"{now - 8:.6f} 9.9.9.{i} GET h.com GET /blockme HTTP/1.1 ua -"
        for i in range(5)
    ]
    fresh = [
        f"{now:.6f} 8.8.8.{i} GET h.com GET /blockme HTTP/1.1 ua -"
        for i in range(5)
    ]
    state = m.pipeline_begin(old + fresh, now)
    assert state.get("fused_eligible")
    m.pipeline_submit(state, now=now + 3)  # old rows now 11 s stale
    assert state.get("fused"), "single-kernel entries missing"
    m.pipeline_collect(state)
    results, n_stale = m.pipeline_finish(state, now + 3)
    assert n_stale == 5
    assert all(r.old_line and not r.rule_results for r in results[:5])
    assert all(not r.old_line and r.rule_results for r in results[5:])
    view = m.device_windows.format_states()
    assert "9.9.9.0" not in view and "8.8.8.0" in view
    assert len(banner.bans) == 5  # hits=0: every fresh line fires once


def test_fully_stale_chunk_commits_nothing():
    patterns = [r"GET /blockme.*"]
    now = time.time()
    y = _rules_yaml(patterns, hits=0, interval=1)
    m, banner = _mk(TpuMatcher, y, matcher_device_windows=True,
                    matcher_prefilter_cand_frac=1.0)
    lines = [
        f"{now - 8:.6f} 9.9.9.{i} GET h.com GET /blockme HTTP/1.1 ua -"
        for i in range(8)
    ]
    state = m.pipeline_begin(lines, now)
    m.pipeline_submit(state, now=now + 5)
    m.pipeline_collect(state)
    results, n_stale = m.pipeline_finish(state, now + 5)
    assert n_stale == 8
    assert all(r.old_line for r in results)
    assert banner.bans == []
    assert len(m.device_windows) == 0
    # a later fresh batch still drains (no leaked turns/pins)
    fresh = [
        f"{now + 5:.6f} 8.8.8.{i} GET h.com GET /blockme HTTP/1.1 ua -"
        for i in range(4)
    ]
    state2 = m.pipeline_begin(fresh, now + 5)
    m.pipeline_submit(state2, now=now + 5)
    m.pipeline_collect(state2)
    results2, _ = m.pipeline_finish(state2, now + 5)
    assert all(r.rule_results for r in results2)
    assert (m.device_windows._pin_counts == 0).all()


# ---------------------------------------------------------------------------
# config resolution + downgrade note
# ---------------------------------------------------------------------------


def test_config_auto_engages_on_cpu_and_off_pins_two_program():
    y = _rules_yaml([r"GET /a.*"])
    health = HealthRegistry()
    auto, _ = _mk(TpuMatcher, y, health=health,
                  matcher_device_windows=True)
    assert auto._fw_pipeline is not None
    assert auto._fw_pipeline.single_kernel  # auto: interpret on CPU
    comp = health.get("matcher-single-kernel")
    assert comp is not None
    assert comp.effective_status()[0] == HealthStatus.HEALTHY

    off, _ = _mk(TpuMatcher, y, matcher_device_windows=True,
                 pallas_single_kernel="off")
    assert off._fw_pipeline is not None
    assert not off._fw_pipeline.single_kernel


def test_downgrade_leaves_health_note(monkeypatch):
    """A window-scan kernel that cannot lower must downgrade to the
    two-program path and leave a DEGRADED note on the health registry —
    never fail matcher construction."""
    from banjax_tpu.matcher.kernels import fused_match_window

    def boom(*a, **k):
        raise RuntimeError("synthetic lowering failure")

    monkeypatch.setattr(fused_match_window, "scan_selftest", boom)
    y = _rules_yaml([r"GET /a.*"])
    health = HealthRegistry()
    m, _ = _mk(TpuMatcher, y, health=health, matcher_device_windows=True,
               pallas_single_kernel="on")
    assert m._fw_pipeline is not None
    assert not m._fw_pipeline.single_kernel
    comp = health.get("matcher-single-kernel")
    status, detail, _ = comp.effective_status()
    assert status == HealthStatus.DEGRADED
    assert "two-program" in detail


def test_config_validation_rejects_bad_value():
    with pytest.raises(ValueError, match="pallas_single_kernel"):
        config_from_yaml_text("pallas_single_kernel: maybe\n")
