"""Scenario-harness unit tier: generator determinism, oracle semantics,
and the banjax_scenario_* exposition — no engine spin-up here (the
engine-backed scenario runs live in tests/soak/)."""

import hashlib
import json

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.obs.exposition import parse_text_format, render_prometheus
from banjax_tpu.obs import registry
from banjax_tpu.scenarios import SHAPES, expected_bans, generate
from banjax_tpu.scenarios.chaos import ChaosSchedule
from banjax_tpu.scenarios.oracle import precision_recall
from banjax_tpu.scenarios.shapes import (
    RULES_YAML,
    CommandBatch,
    LineChunk,
    Rotation,
    Scenario,
)
from banjax_tpu.scenarios.stats import get_stats


def _stream_digest(sc) -> str:
    """Byte-level fingerprint of the COMPLETE event stream (lines,
    command payloads, rotation markers, in order)."""
    h = hashlib.sha256()
    for ev in sc.events:
        if isinstance(ev, LineChunk):
            h.update(b"L")
            for line in ev.lines:
                h.update(line.encode())
                h.update(b"\n")
        elif isinstance(ev, CommandBatch):
            h.update(b"C")
            for raw in ev.raws:
                h.update(raw)
        elif isinstance(ev, Rotation):
            h.update(b"R")
    return h.hexdigest()


def test_every_shape_is_seed_deterministic():
    """Same (name, seed, scale) → byte-identical stream AND identical
    oracle, for every named shape."""
    cfg = config_from_yaml_text(RULES_YAML)
    for name in SHAPES:
        a = generate(name, seed=99, scale=0.2)
        b = generate(name, seed=99, scale=0.2)
        assert _stream_digest(a) == _stream_digest(b), name
        assert expected_bans(a, cfg) == expected_bans(b, cfg), name


def test_different_seed_changes_the_stream():
    a = generate("flash_crowd", seed=1, scale=0.2)
    b = generate("flash_crowd", seed=2, scale=0.2)
    assert _stream_digest(a) != _stream_digest(b)


def test_unknown_shape_rejected():
    with pytest.raises(ValueError, match="unknown scenario"):
        generate("nope")


def test_shape_roster_covers_the_named_attacks():
    assert set(SHAPES) >= {
        "flash_crowd", "slow_drip", "rotating_proxies", "command_flood",
        "challenge_storm", "log_rotation", "benign",
    }
    assert len(SHAPES) >= 6


def test_benign_oracle_is_empty_and_flagged():
    sc = generate("benign", seed=3, scale=0.2)
    cfg = config_from_yaml_text(sc.rules_yaml)
    assert sc.benign
    assert expected_bans(sc, cfg) == []


def test_timestamps_sorted_and_inside_staleness_window():
    from banjax_tpu.scenarios.shapes import RUN_NOW

    for name in SHAPES:
        sc = generate(name, seed=5, scale=0.2)
        ts = [float(line.split(" ", 1)[0]) for line in sc.lines()]
        assert ts == sorted(ts), name
        assert all(RUN_NOW - t <= 10.0 for t in ts), name


def test_oracle_reproduces_the_reference_window_quirks():
    """Hand-built stream: strict-greater window restart, strict-greater
    exceed, and the reset-to-0-not-1 quirk — checked against the real
    reference port (decisions/rate_limit.py) AND by hand."""
    cfg = config_from_yaml_text("""
regexes_with_rates:
  - rule: r
    regex: 'GET /x'
    interval: 2
    hits_per_interval: 2
    decision: nginx_block
""")
    t0 = 1_700_000_000.0

    def line(off, ip="7.7.7.7"):
        return f"{t0 + off:.6f} {ip} GET h.com GET /x HTTP/1.1 ua -"

    # hits at +0, +1, +2 (inside: 2.0 - 0.0 is NOT > 2.0) → count 3 > 2
    # → ban, reset to 0; +3 (inside vs start 0? 3-0>2 → restart, count 1);
    # +4, +4.5 → counts 2, 3 → 3 > 2 → second ban
    sc = Scenario(
        name="hand", seed=0, scale=1.0, rules_yaml="", benign=False,
        events=[LineChunk((line(0), line(1), line(2), line(3), line(4),
                           line(4.5)))],
    )
    bans = expected_bans(sc, cfg)
    assert bans == [("7.7.7.7", "r"), ("7.7.7.7", "r")]

    # differential against the reference port itself
    states = RegexRateLimitStates()
    rule = cfg.regexes_with_rates[0]
    got = []
    for ln in sc.lines():
        ts_ns = int(float(ln.split(" ", 1)[0]) * 1e9)
        _, res = states.apply("7.7.7.7", rule, ts_ns)
        if res.exceeded:
            got.append(("7.7.7.7", "r"))
    assert got == bans


def test_precision_recall_multiset_math():
    eng = [("a", "r"), ("a", "r"), ("b", "r")]
    orc = [("a", "r"), ("b", "r"), ("c", "r")]
    p, r, tp = precision_recall(eng, orc)
    assert tp == 2
    assert p == pytest.approx(2 / 3)
    assert r == pytest.approx(2 / 3)
    assert precision_recall([], []) == (1.0, 1.0, 0)
    assert precision_recall([("x", "r")], []) == (0.0, 1.0, 0)
    assert precision_recall([], [("x", "r")]) == (1.0, 0.0, 0)


def test_command_flood_chops_past_take_max():
    sc = generate("command_flood", seed=4, scale=1.0)
    batches = [ev for ev in sc.events if isinstance(ev, CommandBatch)]
    assert batches, "command_flood must carry command batches"
    # at least one batch bigger than the default take bound, so the
    # encode stage must chop it
    assert max(len(b.raws) for b in batches) > 1024
    for raw in batches[0].raws[:4]:
        cmd = json.loads(raw)
        assert cmd["Name"] in ("block_ip", "challenge_ip")
        assert len(cmd["Value"]) > 4


def test_log_rotation_carries_markers_and_same_oracle_as_flash_crowd():
    cfg = config_from_yaml_text(RULES_YAML)
    rot = generate("log_rotation", seed=6, scale=0.5)
    flash = generate("flash_crowd", seed=6, scale=0.5)
    assert sum(isinstance(e, Rotation) for e in rot.events) >= 2
    # rotation must not change WHAT is expected, only how it is fed
    assert expected_bans(rot, cfg) == expected_bans(flash, cfg)


def test_chaos_schedule_is_seed_deterministic():
    a = ChaosSchedule(seed=21, n_events=40, episodes=5)
    b = ChaosSchedule(seed=21, n_events=40, episodes=5)
    assert a.rows() == b.rows()
    assert len(a.episodes) == 5
    sites = [ep.at_event for ep in a.episodes]
    assert sites == sorted(sites) and len(set(sites)) == len(sites)


def test_scenario_families_render_and_declare():
    """The banjax_scenario_* families: declared in the registry,
    rendered from the stats module, strictly parseable."""
    stats = get_stats()
    stats.reset()
    try:
        stats.note_run(
            "flash_crowd",
            {"lines_per_sec": 1234.5, "shed_ratio": 0.01,
             "precision": 1.0, "recall": 0.98, "slo_burn_peak": 2.5},
            episodes=3, invariant_failures=0,
        )
        text = render_prometheus(
            DynamicDecisionLists(start_sweeper=False),
            RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        )
        fams = parse_text_format(text)
        for name in (
            "banjax_scenario_runs_total",
            "banjax_scenario_injected_episodes_total",
            "banjax_scenario_invariant_failures_total",
            "banjax_scenario_lines_per_sec",
            "banjax_scenario_shed_ratio",
            "banjax_scenario_ban_precision",
            "banjax_scenario_ban_recall",
            "banjax_scenario_slo_burn_peak",
        ):
            assert name in fams, name
            assert name in registry.PROM_FAMILIES, name
        samples = {
            (s[0], tuple(sorted(s[1].items()))): s[2]
            for ent in fams.values() for s in ent["samples"]
        }
        key = ("banjax_scenario_ban_recall",
               (("scenario", "flash_crowd"),))
        assert samples[key] == pytest.approx(0.98)
        assert samples[("banjax_scenario_injected_episodes_total",
                        ())] == 3
    finally:
        stats.reset()


def test_scenario_families_absent_when_never_ran():
    get_stats().reset()
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), FailedChallengeRateLimitStates(),
    )
    assert "banjax_scenario_" not in text
