"""Deployment-shell contract tests (no docker needed).

The compose harness can only run where docker exists; these tests lock the
parts of deploy/ that the product code depends on: the banjax_format log
line nginx writes must parse into exactly the fields the tailer/matcher
expect, and the shipped container config must load and build a working
matcher."""

import re
import time
from pathlib import Path

from banjax_tpu.config.holder import ConfigHolder
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.encode import parse_line
from tests.mock_banner import MockBanner

DEPLOY = Path(__file__).resolve().parents[2] / "deploy"


def test_nginx_conf_carries_the_tailer_log_format():
    conf = (DEPLOY / "nginx" / "nginx.conf").read_text()
    want = (
        "log_format banjax_format '$msec $remote_addr $request_method "
        "$host $request_method $uri $server_protocol $http_user_agent "
        "| $status';"
    )
    assert want in conf
    # the auth subrequest contract: all four X-* headers + body off + target
    for needle in (
        "proxy_set_header X-Requested-Host $host;",
        "proxy_set_header X-Client-IP $remote_addr;",
        "proxy_set_header X-Requested-Path $request_uri;",
        "proxy_set_header X-Client-User-Agent $http_user_agent;",
        "proxy_pass_request_body off;",
        "proxy_pass http://127.0.0.1:8081/auth_request?;",
        "location @access_granted",
        "location @access_denied",
        "location @fail_open",
        "location @fail_closed",
    ):
        assert needle in conf, needle


def test_banjax_format_line_parses_and_matches():
    """A line exactly as nginx banjax_format renders it goes through
    parse_line and trips the deploy config's demo challenge rule."""
    now = time.time()
    line = (
        f"{now:.3f} 203.0.113.7 GET localhost GET /challengeme HTTP/1.1 "
        "Mozilla/5.0 (X11; Linux x86_64) | 404"
    )
    p = parse_line(line, now)
    assert not p.error and not p.old_line
    assert p.ip == "203.0.113.7"
    assert p.host == "localhost"
    assert p.rest.startswith("GET localhost GET /challengeme")

    holder = ConfigHolder(
        str(DEPLOY / "banjax-config.yaml"), standalone_testing=True, debug=False
    )
    cfg = holder.get()
    matcher = CpuMatcher(
        cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates()
    )
    result = matcher.consume_line(line, now)
    hits = [r for r in result.rule_results if r.regex_match]
    assert any(r.rule_name == "instant challenge (demo)" for r in hits)
    # hits_per_interval 0 → first hit exceeds → Banner fired
    assert any(
        r.rate_limit_result is not None and r.rate_limit_result.exceeded
        for r in hits
    )


def test_deploy_config_loads_with_validation():
    holder = ConfigHolder(
        str(DEPLOY / "banjax-config.yaml"), standalone_testing=False, debug=False
    )
    cfg = holder.get()
    assert cfg.matcher == "tpu"
    assert cfg.server_log_file == "/var/log/banjax/banjax-format.log"
    assert cfg.password_hashes.get("localhost") == (
        "5e884898da28047151d0e56f8dc6292773603d0d6aabbdd62a11ef721d1542d8"
    )


def test_compose_and_entrypoint_shape():
    compose = (DEPLOY / "docker-compose.yml").read_text()
    assert 'network_mode: "service:nginx"' in compose  # iptables in the right netns
    assert "NET_ADMIN" in compose
    for svc in ("banjax-tpu:", "nginx:", "test-origin:"):
        assert svc in compose
    entry = (DEPLOY / "entrypoint.sh").read_text()
    assert "python -m banjax_tpu.cli" in entry
