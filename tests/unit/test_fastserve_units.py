"""Direct unit coverage for fastserve's serializer and parser helpers
(the wire behavior is covered end-to-end by the integration differential;
these pin the units for debuggability)."""

from banjax_tpu.httpapi.decision_chain import Response, SetCookie
from banjax_tpu.httpapi.fastserve import _ParsedRequest, serialize_response


def test_serialize_basic():
    raw = serialize_response(
        Response(status=200, body=b"hi", content_type="text/plain",
                 headers={"X-Banjax-Decision": "NoMention"}),
        keep_alive=True,
    )
    head, _, body = raw.partition(b"\r\n\r\n")
    assert head.startswith(b"HTTP/1.1 200 OK\r\n")
    assert b"Content-Type: text/plain\r\n" in head + b"\r\n"
    assert b"Content-Length: 2" in head
    assert b"X-Banjax-Decision: NoMention" in head
    assert b"Connection: keep-alive" in head
    assert body == b"hi"


def test_serialize_cookie_attributes_and_escaping():
    raw = serialize_response(
        Response(cookies=[SetCookie(
            name="deflect_session", value="a+b/c=", max_age=3600,
            path="/", domain="example.com", secure=True, http_only=True,
        )]),
        keep_alive=False,
    )
    line = [l for l in raw.split(b"\r\n") if l.startswith(b"Set-Cookie")][0]
    # gin QueryEscape of the value, then the attribute set
    assert line == (
        b"Set-Cookie: deflect_session=a%2Bb%2Fc%3D; Max-Age=3600; "
        b"Domain=example.com; Path=/; Secure; HttpOnly"
    )
    assert b"Connection: close" in raw


def test_serialize_head_only_keeps_length_drops_body():
    raw = serialize_response(
        Response(status=200, body=b"x" * 37), keep_alive=True, head_only=True
    )
    assert b"Content-Length: 37" in raw
    assert raw.endswith(b"\r\n\r\n")


def test_parsed_request_query_param_percent_decoding():
    req = _ParsedRequest(
        "GET", "/auth_request", "path=%2Fwp-admin%2Fx&y=a+b",
        {"host": "h"}, b"", True, b"",
    )
    assert req.query_param("path") == "/wp-admin/x"
    assert req.query_param("y") == "a b"
    assert req.query_param("absent") == ""


def test_parsed_request_header_lookup():
    req = _ParsedRequest("GET", "/", "", {"x-client-ip": "1.2.3.4"},
                         b"", True, b"")
    assert req.header("x-client-ip") == "1.2.3.4"
    assert req.header("missing") == ""


def test_serialize_max_age_zero_matches_aiohttp_layout():
    """Max-Age=0 (immediate expiry, e.g. a zero cookie-TTL config) must
    reach the wire exactly like the aiohttp layout emits it — the old
    `if c.max_age:` guard silently turned it into a session cookie
    (ADVICE r5).  Differential against aiohttp's set_cookie."""
    from banjax_tpu.httpapi.server import _to_web_response

    resp = Response(cookies=[SetCookie(name="c", value="v", max_age=0)])
    raw = serialize_response(resp, keep_alive=False)
    line = [l for l in raw.split(b"\r\n") if l.startswith(b"Set-Cookie")][0]
    assert b"Max-Age=0" in line

    web_resp = _to_web_response(resp)
    morsel = web_resp.cookies["c"]
    assert morsel["max-age"] == "0"  # both layouts agree

    # and None still omits the attribute on the fast layout
    resp = Response(cookies=[SetCookie(name="c", value="v", max_age=None)])
    raw = serialize_response(resp, keep_alive=False)
    line = [l for l in raw.split(b"\r\n") if l.startswith(b"Set-Cookie")][0]
    assert b"Max-Age" not in line


def test_serialize_response_sanitizes_crlf_in_headers():
    """Response-splitting guard: CR/LF in a header value (the fail-open
    path's X-Banjax-Error carries raw exception text) must not break the
    head apart (ADVICE r5)."""
    raw = serialize_response(
        Response(status=500, headers={
            "X-Banjax-Error": "boom\r\nX-Injected: owned\r\n\r\nfake-body",
        }),
        keep_alive=False,
    )
    head, _, body = raw.partition(b"\r\n\r\n")
    lines = head.split(b"\r\n")
    assert not any(l.startswith(b"X-Injected") for l in lines)
    err = [l for l in lines if l.startswith(b"X-Banjax-Error")][0]
    assert b"boom" in err and b"owned" in err
    assert body == b""
