"""Control-plane message round trips (httpapi/workers.py).

The primary's ReplicatedDynamicLists emits delta dicts; a worker's
WorkerControl applies them to its replica.  These tests wire the emit
side directly into the apply side (no sockets) and assert the replicas
converge — the schema is the contract that crosses the process boundary,
so a field rename on one side must fail here."""

import time

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.httpapi.workers import ReplicatedDynamicLists, WorkerControl


class _Wired:
    """Primary lists wired straight to a replica via the real codecs."""

    def __init__(self):
        self.primary = ReplicatedDynamicLists(start_sweeper=False)
        self.replica = DynamicDecisionLists(start_sweeper=False)
        # reuse WorkerControl's _apply without sockets, but keep the real
        # wire codec (JSON round trip) in the path
        import json

        apply = WorkerControl._apply.__get__(
            type("W", (), {"_replica": self.replica,
                           "_on_reload": staticmethod(lambda: None)})()
        )
        self.primary.set_broadcast(lambda m: apply(json.loads(json.dumps(m))))

    def close(self):
        self.primary.close()
        self.replica.close()


def test_update_round_trips():
    w = _Wired()
    try:
        expires = time.time() + 60
        w.primary.update("1.2.3.4", expires, Decision.NGINX_BLOCK, True, "d.com")
        got, ok = w.replica.check("", "1.2.3.4")
        assert ok and got.decision == Decision.NGINX_BLOCK
        assert got.expires == expires
        assert got.from_baskerville is True
    finally:
        w.close()


def test_session_update_and_remove_round_trip():
    w = _Wired()
    try:
        expires = time.time() + 60
        w.primary.update_by_session_id(
            "1.2.3.4", "sess-1", expires, Decision.CHALLENGE, False, "d.com"
        )
        got, ok = w.replica.check("sess-1", "9.9.9.9")
        assert ok and got.decision == Decision.CHALLENGE

        w.primary.update("5.5.5.5", expires, Decision.IPTABLES_BLOCK, False, "d")
        w.primary.remove_by_ip("5.5.5.5")
        _, ok = w.replica.check("", "5.5.5.5")
        assert not ok
    finally:
        w.close()


def test_clear_round_trips():
    w = _Wired()
    try:
        w.primary.update("7.7.7.7", time.time() + 60, Decision.CHALLENGE,
                         False, "d")
        w.primary.clear()
        _, ok = w.replica.check("", "7.7.7.7")
        assert not ok
    finally:
        w.close()


def test_monotonic_severity_survives_echo():
    """A replica applying its own origin's echo (the worker-local insert
    followed by the primary broadcast) must not downgrade severity."""
    w = _Wired()
    try:
        expires = time.time() + 60
        # replica already holds the stronger decision (worker-local insert)
        w.replica.update("8.8.8.8", expires, Decision.IPTABLES_BLOCK, False, "d")
        # primary's broadcast echoes a weaker one (e.g. ordering skew)
        w.primary.update("8.8.8.8", expires, Decision.CHALLENGE, False, "d")
        got, ok = w.replica.check("", "8.8.8.8")
        assert ok and got.decision == Decision.IPTABLES_BLOCK
    finally:
        w.close()
