"""Control-plane message round trips (httpapi/workers.py).

The primary's ReplicatedDynamicLists emits delta dicts; a worker's
WorkerControl applies them to its replica.  These tests wire the emit
side directly into the apply side (no sockets) and assert the replicas
converge — the schema is the contract that crosses the process boundary,
so a field rename on one side must fail here."""

import time

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.httpapi.workers import ReplicatedDynamicLists, WorkerControl


class _Wired:
    """Primary lists wired straight to a replica via the real codecs."""

    def __init__(self):
        self.primary = ReplicatedDynamicLists(start_sweeper=False)
        self.replica = DynamicDecisionLists(start_sweeper=False)
        # reuse WorkerControl's _apply without sockets, but keep the real
        # wire codec (JSON round trip) in the path
        import json

        apply = WorkerControl._apply.__get__(
            type("W", (), {"_replica": self.replica,
                           "_on_reload": staticmethod(lambda: None)})()
        )
        self.primary.set_broadcast(lambda m: apply(json.loads(json.dumps(m))))

    def close(self):
        self.primary.close()
        self.replica.close()


def test_update_round_trips():
    w = _Wired()
    try:
        expires = time.time() + 60
        w.primary.update("1.2.3.4", expires, Decision.NGINX_BLOCK, True, "d.com")
        got, ok = w.replica.check("", "1.2.3.4")
        assert ok and got.decision == Decision.NGINX_BLOCK
        assert got.expires == expires
        assert got.from_baskerville is True
    finally:
        w.close()


def test_session_update_and_remove_round_trip():
    w = _Wired()
    try:
        expires = time.time() + 60
        w.primary.update_by_session_id(
            "1.2.3.4", "sess-1", expires, Decision.CHALLENGE, False, "d.com"
        )
        got, ok = w.replica.check("sess-1", "9.9.9.9")
        assert ok and got.decision == Decision.CHALLENGE

        w.primary.update("5.5.5.5", expires, Decision.IPTABLES_BLOCK, False, "d")
        w.primary.remove_by_ip("5.5.5.5")
        _, ok = w.replica.check("", "5.5.5.5")
        assert not ok
    finally:
        w.close()


def test_clear_round_trips():
    w = _Wired()
    try:
        w.primary.update("7.7.7.7", time.time() + 60, Decision.CHALLENGE,
                         False, "d")
        w.primary.clear()
        _, ok = w.replica.check("", "7.7.7.7")
        assert not ok
    finally:
        w.close()


def test_monotonic_severity_survives_echo():
    """A replica applying its own origin's echo (the worker-local insert
    followed by the primary broadcast) must not downgrade severity."""
    w = _Wired()
    try:
        expires = time.time() + 60
        # replica already holds the stronger decision (worker-local insert)
        w.replica.update("8.8.8.8", expires, Decision.IPTABLES_BLOCK, False, "d")
        # primary's broadcast echoes a weaker one (e.g. ordering skew)
        w.primary.update("8.8.8.8", expires, Decision.CHALLENGE, False, "d")
        got, ok = w.replica.check("", "8.8.8.8")
        assert ok and got.decision == Decision.IPTABLES_BLOCK
    finally:
        w.close()


def test_worker_control_survives_garbage_datagrams(tmp_path):
    """Bad broadcasts (not JSON, wrong fields, unknown ops) must never
    kill a worker's control thread — the replica keeps applying
    subsequent valid deltas."""
    import json
    import socket

    replica = DynamicDecisionLists(start_sweeper=False)
    ctrl = WorkerControl(str(tmp_path), 0, replica, on_reload=lambda: None)
    try:
        send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        path = f"{tmp_path}/worker-0.sock"
        for payload in (b"not json", b"{}", b'{"op": "wat"}',
                        b'{"op": "dyn_update"}',  # missing fields
                        b'{"op": "dyn_update", "ip": 5, "expires": "x", '
                        b'"decision": 99, "from_baskerville": 0, "domain": 1}'):
            send.sendto(payload, path)
        good = {
            "op": "dyn_update", "ip": "6.6.6.6",
            "expires": time.time() + 60, "decision": int(Decision.CHALLENGE),
            "from_baskerville": False, "domain": "d",
        }
        send.sendto(json.dumps(good).encode(), path)
        deadline = time.time() + 5
        ok = False
        while time.time() < deadline:
            _, ok = replica.check("", "6.6.6.6")
            if ok:
                break
            time.sleep(0.05)
        assert ok, "valid delta not applied after garbage datagrams"
        send.close()
    finally:
        ctrl.stop()
        replica.close()


def test_control_plane_send_to_dead_socket_drops_silently(tmp_path):
    """_send_json to an absent peer must drop, not raise (the kafka
    drop-don't-block discipline)."""
    import socket

    from banjax_tpu.httpapi.workers import _send_json

    s = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
    s.setblocking(False)
    _send_json(s, f"{tmp_path}/nonexistent.sock", {"op": "dyn_clear"})
    s.close()
