"""UA decision lists (reference: internal/user_agent_decision_test.go)."""

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.decisions.ua_lists import UAPattern, check_ua_decision


def test_match_user_agent_substring():
    p = UAPattern("GPTBot")
    assert p.compiled is None
    assert p.matches("Mozilla/5.0 (compatible; GPTBot/1.0; +https://openai.com/gptbot)")
    assert not p.matches("Mozilla/5.0 (compatible; Googlebot/2.1)")


def test_match_user_agent_regex():
    p = UAPattern(r"Macintosh.*Firefox/\d+")
    assert p.compiled is not None
    assert p.matches("Mozilla/5.0 (Macintosh; Intel Mac OS X 10.15; rv:149.0) Gecko/20100101 Firefox/149.0")
    assert not p.matches("Mozilla/5.0 (Windows NT 10.0; Win64; x64; rv:149.0) Gecko/20100101 Firefox/149.0")


def test_match_user_agent_regex_case_insensitive():
    p = UAPattern("(?i)scrapy|mechanize")
    assert p.compiled is not None
    assert p.matches("Scrapy/2.11.2 (+https://scrapy.org)")
    assert p.matches("Python-Mechanize/0.4.9")
    assert not p.matches("Mozilla/5.0 (compatible; Googlebot/2.1)")


def test_invalid_regex_raises():
    with pytest.raises(ValueError):
        UAPattern("(?invalid")


def test_check_ua_decision_severity_order():
    rules = {
        Decision.ALLOW: [UAPattern("TestBot")],
        Decision.NGINX_BLOCK: [UAPattern("TestBot")],
    }
    decision, ok = check_ua_decision(rules, "TestBot/1.0")
    assert ok
    assert decision is Decision.NGINX_BLOCK


def test_check_ua_decision_no_match():
    rules = {Decision.NGINX_BLOCK: [UAPattern("AhrefsBot")]}
    _, ok = check_ua_decision(rules, "Mozilla/5.0 (compatible; Googlebot/2.1)")
    assert not ok


UA_LISTS_YAML = r"""
global_user_agent_decision_lists:
  nginx_block:
    - "AhrefsBot"
    - "SemrushBot"
  challenge:
    - "(?i)scrapy|mechanize"
  allow:
    - "Googlebot"
per_site_user_agent_decision_lists:
  "example.com":
    allow:
      - "GPTBot"
    nginx_block:
      - "AhrefsBot"
  "other.com":
    challenge:
      - "Macintosh.*Firefox/\\d+"
"""


@pytest.fixture()
def lists():
    return StaticDecisionLists(config_from_yaml_text(UA_LISTS_YAML))


def test_check_global_user_agent(lists):
    decision, ok = lists.check_global_user_agent("Mozilla/5.0 (compatible; AhrefsBot/7.0)")
    assert ok and decision is Decision.NGINX_BLOCK

    decision, ok = lists.check_global_user_agent("Mozilla/5.0 (compatible; SemrushBot/7.0)")
    assert ok and decision is Decision.NGINX_BLOCK

    decision, ok = lists.check_global_user_agent("Scrapy/2.11.2 (+https://scrapy.org)")
    assert ok and decision is Decision.CHALLENGE

    decision, ok = lists.check_global_user_agent("Mozilla/5.0 (compatible; Googlebot/2.1)")
    assert ok and decision is Decision.ALLOW

    _, ok = lists.check_global_user_agent("Mozilla/5.0 (compatible; GPTBot/1.0)")
    assert not ok


def test_check_per_site_user_agent(lists):
    decision, ok = lists.check_per_site_user_agent("example.com", "Mozilla/5.0 (compatible; GPTBot/1.0)")
    assert ok and decision is Decision.ALLOW

    decision, ok = lists.check_per_site_user_agent("example.com", "Mozilla/5.0 (compatible; AhrefsBot/7.0)")
    assert ok and decision is Decision.NGINX_BLOCK

    decision, ok = lists.check_per_site_user_agent(
        "other.com",
        "Mozilla/5.0 (Macintosh; Intel Mac OS X 10.15; rv:149.0) Gecko/20100101 Firefox/149.0",
    )
    assert ok and decision is Decision.CHALLENGE

    _, ok = lists.check_per_site_user_agent(
        "other.com",
        "Mozilla/5.0 (Windows NT 10.0; Win64; x64; rv:149.0) Gecko/20100101 Firefox/149.0",
    )
    assert not ok

    _, ok = lists.check_per_site_user_agent("unknown.com", "Mozilla/5.0 (compatible; AhrefsBot/7.0)")
    assert not ok


def test_invalid_ua_decision_in_config():
    cfg = config_from_yaml_text(
        """
global_user_agent_decision_lists:
  bad_decision:
    - "SomeBot"
"""
    )
    with pytest.raises(ValueError):
        StaticDecisionLists(cfg)


def test_invalid_ua_regex_in_config():
    cfg = config_from_yaml_text(
        """
global_user_agent_decision_lists:
  nginx_block:
    - "(?invalid"
"""
    )
    with pytest.raises(ValueError):
        StaticDecisionLists(cfg)
