"""Metrics line (reference schema + additive TPU keys) and the
profile-endpoint wiring (`profile: true`, VERDICT r1 weak #9)."""

import io
import json
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs.metrics import write_metrics_line
from tests.mock_banner import MockBanner

RULES_YAML = """
regexes_with_rates:
  - decision: nginx_block
    rule: r
    regex: 'GET .*'
    interval: 5
    hits_per_interval: 100
"""

REFERENCE_KEYS = {
    "Time", "LenExpiringChallenges", "LenExpiringBlocks",
    "LenIpToRegexStates", "LenFailedChallengeStates",
}


def _line(matcher=None):
    out = io.StringIO()
    write_metrics_line(
        out,
        DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(),
        FailedChallengeRateLimitStates(),
        matcher,
    )
    return json.loads(out.getvalue())


def test_reference_schema_unchanged_without_matcher():
    assert set(_line()) == REFERENCE_KEYS


def test_matcher_keys_are_additive():
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    m = TpuMatcher(cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates())
    now = time.time()
    m.consume_lines(
        [f"{now:.6f} 9.9.9.{i} GET h.com GET /x HTTP/1.1" for i in range(10)], now
    )
    line = _line(m)
    assert REFERENCE_KEYS < set(line)  # reference keys all still present
    assert line["MatcherLinesTotal"] == 10
    assert line["MatcherBatchesTotal"] == 1
    assert line["MatcherLinesPerSec"] > 0
    assert line["MatcherBatchLatencyP50Ms"] > 0
    assert line["MatcherBatchLatencyP99Ms"] >= line["MatcherBatchLatencyP50Ms"]
    assert line["DeviceWindowsOccupancy"] == 10
    # capacity 0 in config = auto-size; the line reports the ACTUAL table
    assert line["DeviceWindowsCapacity"] == m.device_windows.capacity > 0
    assert line["DeviceWindowsEvictions"] == 0
    assert line["DeviceWindowsEvictionsPerInterval"] == 0
    assert line["DeviceWindowsGrows"] == 0
    # the lines/sec window resets per snapshot
    line2 = _line(m)
    assert line2["MatcherLinesPerSec"] == 0


@pytest.mark.parametrize("profile_on", [False, True])
def test_profile_routes_registered_only_when_enabled(profile_on, monkeypatch):
    from banjax_tpu.httpapi import server as server_mod

    cfg = config_from_yaml_text(RULES_YAML)
    cfg.profile = profile_on
    cfg.standalone_testing = True

    class Holder:
        def get(self):
            return cfg

    from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths

    deps = server_mod.ServerDeps(
        config_holder=Holder(),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=DynamicDecisionLists(start_sweeper=False),
        protected_paths=PasswordProtectedPaths(cfg),
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(),
    )
    app = server_mod.build_app(deps)
    routes = {r.resource.canonical for r in app.router.routes()}
    assert ("/debug/pprof/profile" in routes) == profile_on
    assert ("/debug/pprof/threads" in routes) == profile_on
    assert ("/debug/jax/trace" in routes) == profile_on


def test_pprof_endpoints_respond():
    """Drive the profile endpoints through a real aiohttp test client."""
    import asyncio

    from aiohttp.test_utils import TestClient, TestServer

    from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
    from banjax_tpu.httpapi import server as server_mod

    cfg = config_from_yaml_text(RULES_YAML)
    cfg.profile = True
    cfg.standalone_testing = True

    class Holder:
        def get(self):
            return cfg

    deps = server_mod.ServerDeps(
        config_holder=Holder(),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=DynamicDecisionLists(start_sweeper=False),
        protected_paths=PasswordProtectedPaths(cfg),
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(),
    )

    async def drive():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/debug/pprof/profile", params={"seconds": "0.1"})
            assert r.status == 200
            assert "cumulative" in await r.text()
            r = await client.get("/debug/pprof/threads")
            assert r.status == 200
            assert "thread" in await r.text()
        finally:
            await client.close()

    asyncio.run(drive())


def test_snapshot_window_reset_is_atomic_under_concurrency():
    """Regression for the snapshot race window: window counters used to
    be read and the eviction delta updated outside the stats lock, so a
    concurrent snapshot could double-count or lose an interval delta.
    Hammer record/snapshot from many threads and assert the deltas
    telescope exactly (conservation) and totals never regress."""
    import threading

    from banjax_tpu.obs.stats import MatcherStats

    class FakeWindows:
        """Minimal device_windows surface with a racing eviction count."""

        capacity = 64
        occupancy = 10
        grow_count = 0
        eviction_count = 0

        def __len__(self):
            return 10

    stats = MatcherStats()
    windows = FakeWindows()
    stop = threading.Event()
    snapshots = []
    snap_lock = threading.Lock()

    def recorder():
        while not stop.is_set():
            stats.record_batch(10, 0.001)
            stats.note_xfer(100, 50)
            windows.eviction_count += 1  # single mutator thread

    def snapshotter():
        while not stop.is_set():
            s = stats.snapshot(windows)
            with snap_lock:
                snapshots.append(s)

    threads = [threading.Thread(target=recorder)] + [
        threading.Thread(target=snapshotter) for _ in range(3)
    ]
    for t in threads:
        t.start()
    time.sleep(0.5)
    stop.set()
    for t in threads:
        t.join(10)

    final = stats.snapshot(windows)
    snapshots.append(final)
    # conservation: interval eviction deltas telescope to the final
    # absolute count with nothing lost or double-counted
    assert sum(
        s["DeviceWindowsEvictionsPerInterval"] for s in snapshots
    ) == final["DeviceWindowsEvictions"]
    assert final["MatcherLinesTotal"] == 10 * final["MatcherBatchesTotal"]
    assert final["MatcherH2dBytesTotal"] == 100 * final["MatcherBatchesTotal"]


def test_supervisor_keys_are_additive():
    """Multi-worker serving health keys appear only when a supervisor is
    passed (the reference schema stays untouched otherwise)."""
    import types

    sup = types.SimpleNamespace(n_workers=2, respawn_count=3)
    out = io.StringIO()
    write_metrics_line(
        out,
        DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(),
        FailedChallengeRateLimitStates(),
        None,
        sup,
    )
    line = json.loads(out.getvalue())
    assert set(line) == REFERENCE_KEYS | {
        "HttpWorkers", "HttpWorkerRespawns", "HttpFcDropped",
    }
    assert line["HttpWorkers"] == 2
    assert line["HttpWorkerRespawns"] == 3
    assert line["HttpFcDropped"] == 0  # python limiter has no drop counter
