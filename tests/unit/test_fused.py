"""Fused UA+path matcher vs the serial reference semantics."""

import numpy as np
import pytest

from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.ua_lists import build_ua_rules, check_ua_decision
from banjax_tpu.matcher.fused import DeviceUAMatcher, ua_patterns_in_severity_order

RAW = {
    "allow": ["GoodBot", "curl/[78]"],
    "challenge": ["Mozilla/4", "scanner"],
    "nginx_block": [r"sqlmap|nikto", "BadBot/2.0"],
    "iptables_block": ["EvilBot"],
}

UAS = [
    "Mozilla/5.0 (X11; Linux x86_64)",
    "Mozilla/4.0 (compatible; MSIE 6.0)",
    "sqlmap/1.7-dev",
    "EvilBot scanner",          # iptables beats challenge (severity order)
    "GoodBot scanner",          # allow is checked LAST: challenge wins
    "curl/8.1.2",
    "BadBot/2.0 (+http://x)",
    "",
    "nothing notable",
]


@pytest.mark.parametrize("backend", ["xla", "pallas-interpret"])
def test_device_ua_matches_serial_reference(backend):
    rules = build_ua_rules(RAW)
    dm = DeviceUAMatcher(rules, backend=backend)
    got = dm.check_batch(UAS)
    want = [check_ua_decision(rules, ua) for ua in UAS]
    assert got == want


def test_severity_order_flattening():
    rules = build_ua_rules(RAW)
    rows = ua_patterns_in_severity_order(rules)
    decisions = [d for d, _ in rows]
    assert decisions == sorted(decisions, reverse=True)  # severity descending
    # substring patterns are escaped ("BadBot/2.0" has a metachar-free dot? no:
    # '.' IS a metachar, so it stays a regex; "EvilBot" is a substring → escaped
    flat = dict((rx, d) for d, rx in rows)
    assert "EvilBot" in flat  # re.escape("EvilBot") == "EvilBot"


def test_fused_extra_rules_share_the_pass():
    """Rate rules and UA patterns coexist in one compiled ruleset: columns
    [0, n_extra) are the rate rules, the rest the UA patterns."""
    rules = build_ua_rules(RAW)
    dm = DeviceUAMatcher(
        rules, backend="xla",
        extra_rules=[r"GET /wp-login\.php", r"POST /xmlrpc\.php"],
    )
    lines = [
        "GET example.com GET /wp-login.php HTTP/1.1 sqlmap/1.7",
        "POST example.com POST /xmlrpc.php HTTP/1.1 Mozilla/5.0",
        "GET example.com GET / HTTP/1.1 GoodBot",
    ]
    bits = dm.match_bits(lines)
    assert bits.shape[1] == 2 + sum(len(v) for v in RAW.values())
    assert bits[0, 0] == 1 and bits[1, 1] == 1 and not bits[2, :2].any()
    ua_decisions = dm.decide(bits[:, 2:])
    assert ua_decisions[0] == (Decision.NGINX_BLOCK, True)   # sqlmap
    assert ua_decisions[2] == (Decision.ALLOW, True)         # GoodBot
