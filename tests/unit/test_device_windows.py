"""DeviceWindows vs RegexRateLimitStates differential (SURVEY.md §4 carry-over
(d): generalize the reference's generative stress test into a byte-identical
harness for the device path — here for the window counters of
/root/reference/internal/rate_limit.go:37-78)."""

import random
import re

import numpy as np
import pytest

from banjax_tpu.config.schema import RegexWithRate, Decision
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.matcher.windows import DeviceWindows, split_ns

NS = 1_000_000_000


def make_rule(name: str, interval_s: float, hits: int) -> RegexWithRate:
    return RegexWithRate(
        rule=name,
        regex_string="x",
        regex=re.compile("x"),
        interval_ns=int(interval_s * NS),
        hits_per_interval=hits,
        decision=Decision.NGINX_BLOCK,
    )


def drive_oracle(rules, batches):
    """Replay (ip, rule_id, ts_ns) events through the host-semantics class."""
    states = RegexRateLimitStates()
    out = []
    for bits, ips, ts in batches:
        for line in range(bits.shape[0]):
            for rid in range(bits.shape[1]):
                if not bits[line, rid]:
                    continue
                seen, res = states.apply(ips[line], rules[rid], int(ts[line]))
                out.append((line, rid, int(res.match_type), res.exceeded, seen))
    return states, out


def drive_device(rules, batches, capacity=64, max_events=512, **kw):
    dw = DeviceWindows(rules, capacity=capacity, max_events=max_events,
                       **kw)
    active = np.ones((1, len(rules)), dtype=bool)
    out = []

    def apply(bits, ips, ts, base=0):
        """Mirror the runner: split when allocation refuses (more distinct
        IPs than free+evictable slots in one batch)."""
        slots = dw.slots_for_ips(ips)
        if slots is None:
            assert len(ips) > 1, "single line must always fit"
            mid = len(ips) // 2
            return (apply(bits[:mid], ips[:mid], ts[:mid], base)
                    + apply(bits[mid:], ips[mid:], ts[mid:], base + mid))
        ts_s, ts_ns = split_ns(ts)
        events = dw.apply_bitmap(
            bits, slots, ts_s, ts_ns, active,
            np.zeros(len(ips), dtype=np.int32),
        )
        return [
            (e.line + base, e.rule_id, int(e.match_type), e.exceeded, e.seen_ip)
            for e in events
        ]

    for bits, ips, ts in batches:
        out.extend(apply(bits, ips, ts))
    return dw, out


def random_batches(rng, n_rules, n_ips, n_batches, batch, density=0.2,
                   base_ns=1_700_000_000 * NS):
    ips = [f"10.0.0.{i}" for i in range(n_ips)]
    t = base_ns
    batches = []
    for _ in range(n_batches):
        bits = (rng.random((batch, n_rules)) < density).astype(np.uint8)
        ip_col = [ips[rng.integers(0, n_ips)] for _ in range(batch)]
        ts = []
        for _ in range(batch):
            t += rng.integers(0, 2 * NS)  # 0..2s steps, ns granularity
            ts.append(t)
        batches.append((bits, ip_col, np.array(ts, dtype=np.int64)))
    return batches


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_differential_random(seed):
    rng = np.random.default_rng(seed)
    rules = [
        make_rule("fast", 1.0, 2),
        make_rule("slow", 30.0, 5),
        make_rule("zero", 0.5, 0),   # hits_per_interval 0: every hit exceeds
        make_rule("wide", 300.0, 3),
    ]
    batches = random_batches(rng, len(rules), n_ips=6, n_batches=4, batch=40)
    states, want = drive_oracle(rules, batches)
    dw, got = drive_device(rules, batches)
    assert got == want

    # final counter state identical per (ip, rule)
    for i in range(6):
        ip = f"10.0.0.{i}"
        host_states, host_ok = states.get(ip)
        dev_states, dev_ok = dw.get(ip)
        assert host_ok == dev_ok
        assert set(host_states) == set(dev_states)
        for rule, s in host_states.items():
            d = dev_states[rule]
            assert (s.num_hits, s.interval_start_time_ns) == (
                d.num_hits, d.interval_start_time_ns
            ), (ip, rule)


def test_window_restart_and_reset_quirk():
    """Window restarts strictly after interval; exceed resets hits to 0."""
    rules = [make_rule("r", 10.0, 2)]
    base = 1_700_000_000 * NS
    one = np.ones((1, 1), dtype=np.uint8)
    # 4 hits inside one window: 1,2,3>2 → exceeded, reset to 0; then 1
    ts_list = [base, base + 1 * NS, base + 2 * NS, base + 3 * NS,
               # exactly interval later than start: NOT outside (strict >)
               base + 10 * NS,
               # strictly beyond: restart
               base + 10 * NS + 1]
    batches = [(one, ["1.2.3.4"], np.array([t], dtype=np.int64)) for t in ts_list]
    _, want = drive_oracle(rules, batches)
    _, got = drive_device(rules, batches)
    assert got == want
    exceeded_seq = [e[3] for e in got]
    assert exceeded_seq == [False, False, True, False, False, False]


def test_active_table_masks_events():
    """Per-host applicability: masked rules produce no events or state."""
    rules = [make_rule("a", 5.0, 1), make_rule("b", 5.0, 1)]
    dw = DeviceWindows(rules, capacity=8)
    active = np.array([[True, False], [True, True]])  # host 0 masks rule b
    bits = np.ones((2, 2), dtype=np.uint8)
    ts = np.array([1_700_000_000 * NS, 1_700_000_000 * NS + 1], dtype=np.int64)
    slots = dw.slots_for_ips(["a.a", "b.b"])
    ts_s, ts_ns = split_ns(ts)
    events = dw.apply_bitmap(
        bits, slots, ts_s, ts_ns, active, np.array([0, 1], dtype=np.int32)
    )
    assert [(e.line, e.rule_id) for e in events] == [(0, 0), (1, 0), (1, 1)]
    states, ok = dw.get("a.a")
    assert ok and set(states) == {"a"}


def test_overflow_splits_batch():
    """More events than max_events → recursive halving, same results."""
    rules = [make_rule("r", 10.0, 3)]
    batches_rng = np.random.default_rng(7)
    batches = random_batches(batches_rng, 1, n_ips=3, n_batches=2, batch=64,
                             density=1.0)
    _, want = drive_oracle(rules, batches)
    _, got = drive_device(rules, batches, capacity=16, max_events=16)
    assert got == want


def test_eviction_spills_and_restores():
    """LRU eviction spills counters to the host shadow; re-admission
    restores them, so state is NEVER forgotten (rate_limit.go:37-78 — the
    reference host dict never forgets; VERDICT r2 weak #5)."""
    rules = [make_rule("r", 10.0, 100)]
    dw = DeviceWindows(rules, capacity=2)
    one = np.ones((1, 1), dtype=np.uint8)
    active = np.ones((1, 1), dtype=bool)
    base = 1_700_000_000 * NS

    def hit(ip, t):
        slots = dw.slots_for_ips([ip])
        ts_s, ts_ns = split_ns(np.array([t], dtype=np.int64))
        ev = dw.apply_bitmap(one, slots, ts_s, ts_ns, active,
                             np.zeros(1, dtype=np.int32))
        return ev[0]

    hit("ip-a", base)
    hit("ip-a", base + 1)
    hit("ip-b", base + 2)
    e = hit("ip-c", base + 3)       # evicts ip-a (LRU)
    assert e.seen_ip is False       # ip-c itself is genuinely new
    assert dw.eviction_count == 1
    states, ok = dw.get("ip-a")
    assert ok and states["r"].num_hits == 2  # spilled, not forgotten
    e = hit("ip-a", base + 4)        # evicts ip-b; ip-a RESTORES
    assert e.seen_ip is True
    assert int(e.match_type) == 2    # INSIDE_INTERVAL: the window survived
    states, ok = dw.get("ip-a")
    assert ok and states["r"].num_hits == 3
    # ip-b's counters also survived its eviction
    states, ok = dw.get("ip-b")
    assert ok and states["r"].num_hits == 1
    assert len(dw) == 3              # every IP with state counts


def test_batch_slot_pinning():
    """slots_for_ips never evicts a slot pinned by the same batch (the
    within-batch reuse would merge two IPs' counters into one key), and the
    TpuMatcher recovers by splitting the batch — here we check the refusal."""
    rules = [make_rule("r", 10.0, 100)]
    dw = DeviceWindows(rules, capacity=2)
    assert dw.slots_for_ips(["a", "b", "c"]) is None  # 3 distinct IPs, 2 slots
    slots = dw.slots_for_ips(["a", "b", "a", "b"])    # repeats are fine
    assert slots is not None and slots[0] == slots[2] and slots[1] == slots[3]


def test_capacity_overflow_batch_splits_identically():
    """End-to-end: more distinct IPs than capacity still matches the oracle
    (the TpuMatcher splits work; here we emulate by per-line batches)."""
    rules = [make_rule("r", 10.0, 2)]
    rng = np.random.default_rng(3)
    batches = random_batches(rng, 1, n_ips=10, n_batches=1, batch=50, density=0.9)
    # split each 50-line batch into per-line batches for the 4-slot device
    bits, ips, ts = batches[0]
    per_line = [
        (bits[i : i + 1], [ips[i]], ts[i : i + 1]) for i in range(len(ips))
    ]
    _, want = drive_oracle(rules, per_line)
    _, got = drive_device(rules, per_line, capacity=4)
    # spill/restore makes eviction lossless: FULL equality with the host
    # oracle even at 10 IPs > 4 slots (VERDICT r2 item 6: no excluded fields)
    assert got == want


def test_stale_restore_does_not_resurrect_into_new_owner():
    """A restore queued for (slot, ip) must be dropped if the slot has been
    re-evicted and handed to a DIFFERENT ip before maintenance ran —
    otherwise an innocent new IP inherits the old IP's counters."""
    rules = [make_rule("r", 30.0, 100)]
    dw = DeviceWindows(rules, capacity=2)
    one = np.ones((1, 1), dtype=np.uint8)
    active = np.ones((1, 1), dtype=bool)
    base = 1_700_000_000 * NS

    def hit(ip, t):
        slots = dw.slots_for_ips([ip])
        ts_s, ts_ns = split_ns(np.array([t], dtype=np.int64))
        return dw.apply_bitmap(one, slots, ts_s, ts_ns, active,
                               np.zeros(1, dtype=np.int32))[0]

    hit("X", base)
    hit("X", base + 1)          # X: 2 hits
    hit("Y", base + 2)
    hit("Z", base + 3)          # evicts X
    # X re-admitted by a lookup that never reaches apply_bitmap (the
    # runner's pre-handoff failure path): restore stays queued
    slots = dw.slots_for_ips(["X"])   # evicts Y, queues restore for X
    dw.release_pins(slots)
    hit("Z", base + 4)          # Z most recent; X is LRU again
    e = hit("A", base + 5)      # evicts X; A takes X's old slot
    assert e.seen_ip is False and int(e.match_type) == 0, (
        "new IP must not inherit the evicted IP's restored counters"
    )
    states, ok = dw.get("A")
    assert ok and states["r"].num_hits == 1
    # X's state is still intact in the shadow for ITS next admission
    states, ok = dw.get("X")
    assert ok and states["r"].num_hits == 2


@pytest.mark.parametrize("seed", [11, 12])
def test_eviction_churn_differential(seed):
    """Sustained rotation through many more IPs than slots — heavy
    evict/spill/restore churn — still matches the host oracle exactly."""
    rules = [make_rule("fast", 5.0, 2), make_rule("slow", 60.0, 4)]
    rng = np.random.default_rng(seed)
    batches = random_batches(rng, 2, n_ips=24, n_batches=6, batch=16,
                             density=0.5)
    _, want = drive_oracle(rules, batches)
    dw, got = drive_device(rules, batches, capacity=8)
    assert dw.eviction_count > 0, "test must actually exercise eviction"
    assert got == want


def test_varying_batch_sizes_share_one_compile():
    """apply_bitmap buckets B to powers of two before the jitted step, so
    traffic with varying batch sizes must not grow the jit cache per size
    (ADVICE r1: unbounded recompiles of the segmented-scan program)."""
    from banjax_tpu.matcher import windows as W

    rules = [make_rule("r", 10.0, 100)]
    dw = DeviceWindows(rules, capacity=64)
    active = np.ones((1, 1), dtype=bool)
    base = 1_700_000_000 * NS
    count_before = W._apply_step._cache_size()
    for i, b in enumerate([1, 3, 5, 17, 33, 63, 64]):  # all bucket to 64
        bits = np.ones((b, 1), dtype=np.uint8)
        ips = [f"9.9.{i}.{j}" for j in range(b)]
        slots = dw.slots_for_ips(ips)
        ts = np.arange(b, dtype=np.int64) + base + i * NS
        ts_s, ts_ns = split_ns(ts)
        events = dw.apply_bitmap(
            bits, slots, ts_s, ts_ns, active, np.zeros(b, dtype=np.int32)
        )
        assert len(events) == b
        assert all(0 <= e.line < b for e in events)
    assert W._apply_step._cache_size() - count_before == 1


def test_in_flight_slots_not_evicted_and_pins_release():
    """Slots assigned by slots_for_ips stay pinned (unevictable) until their
    apply_bitmap runs, then the pins release so eviction works again."""
    rules = [make_rule("r", 10.0, 100)]
    dw = DeviceWindows(rules, capacity=2)
    active = np.ones((1, 1), dtype=bool)
    base = 1_700_000_000 * NS

    slots_ab = dw.slots_for_ips(["a", "b"])  # fills capacity, pins both
    assert dw.slots_for_ips(["c"]) is None   # nothing evictable while pinned
    assert dw.eviction_count == 0

    ts_s, ts_ns = split_ns(np.array([base, base + 1], dtype=np.int64))
    dw.apply_bitmap(np.ones((2, 1), dtype=np.uint8), slots_ab, ts_s, ts_ns,
                    active, np.zeros(2, dtype=np.int32))
    slots_c = dw.slots_for_ips(["c"])        # pins released → LRU evictable
    assert slots_c is not None
    assert dw.eviction_count == 1


def test_auto_grow_absorbs_distinct_ip_pressure():
    """capacity=0 (auto-size): the slot table doubles on pressure instead
    of evicting, existing counters and slot ids survive the growth, and
    the ceiling still evicts (VERDICT r3 item 4)."""
    rules = [make_rule("r", 10.0, 100)]
    dw = DeviceWindows(rules, capacity=0)
    assert dw.auto_grow and dw.capacity == dw.AUTO_START_CAPACITY
    # shrink the knobs so the test exercises growth cheaply
    dw.capacity = 2
    dw.max_capacity = 4
    dw._free = [1, 0]
    dw._pin_counts = np.zeros(2, dtype=np.int32)
    dw._last_used = np.zeros(2, dtype=np.int64)
    dw._state = dw._fresh_state()
    if dw._sm is not None:  # rebuild the native manager at the shrunk size
        from banjax_tpu.native import slotmgr as _slotmgr

        dw._sm.close()
        dw._sm = _slotmgr.create(2)
    one = np.ones((1, 1), dtype=np.uint8)
    active = np.ones((1, 1), dtype=bool)
    base = 1_700_000_000 * NS

    def hit(ip, t):
        slots = dw.slots_for_ips([ip])
        ts_s, ts_ns = split_ns(np.array([t], dtype=np.int64))
        dw.apply_bitmap(one, slots, ts_s, ts_ns, active,
                        np.zeros(1, dtype=np.int32))

    hit("ip-a", base)
    hit("ip-b", base + 1)
    hit("ip-c", base + 2)            # pressure → grow 2→4, NOT evict
    assert dw.grow_count == 1 and dw.capacity == 4
    assert dw.eviction_count == 0
    hit("ip-d", base + 3)
    # earlier counters survived the growth in place (no spill/restore)
    states, ok = dw.get("ip-a")
    assert ok and states["r"].num_hits == 1
    hit("ip-a", base + 4)
    states, ok = dw.get("ip-a")
    assert ok and states["r"].num_hits == 2
    # at the ceiling the LRU spill path takes over
    hit("ip-e", base + 5)
    assert dw.capacity == 4 and dw.eviction_count == 1
    assert len(dw) == 5


def test_concurrent_consume_reload_metrics_soak():
    """Race-detection soak (SURVEY.md §5): consume_lines on one thread,
    static-list hot reloads (allow-cache invalidation) and metrics
    snapshots on others. No exceptions, no torn state, and the allowlist
    flip must take effect on the batch after the reload."""
    import threading
    import time as _time

    import yaml as _yaml

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from banjax_tpu.obs.stats import MatcherStats  # noqa: F401 — via matcher
    from tests.mock_banner import MockBanner

    base = {
        "regexes_with_rates": [
            {"rule": "hit", "regex": ".*attackpath.*", "interval": 60,
             "hits_per_interval": 2, "decision": "nginx_block"},
        ],
    }
    cfg = config_from_yaml_text(_yaml.safe_dump(base))
    cfg.matcher_device_windows = True
    cfg.matcher_batch_lines = 256
    sl = StaticDecisionLists(cfg)
    m = TpuMatcher(cfg, MockBanner(), sl, RegexRateLimitStates())
    now = _time.time()
    lines = [
        f"{now:.6f} 10.1.{i % 16}.{i % 7} GET h.com GET "
        f"/{'attackpath' if i % 9 == 0 else 'ok'}{i} HTTP/1.1 UA -"
        for i in range(512)
    ]
    errors = []
    stop = threading.Event()

    def reloader():
        flip = False
        while not stop.is_set():
            try:
                alt = dict(base)
                if flip:
                    alt = {**base, "global_decision_lists": {
                        "allow": ["10.1.0.0", "10.1.1.1"]}}
                sl.update_from_config(
                    config_from_yaml_text(_yaml.safe_dump(alt))
                )
                flip = not flip
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            _time.sleep(0.002)

    def metrics():
        while not stop.is_set():
            try:
                m.stats.snapshot(m.device_windows, m)
                m.device_windows.occupancy
                len(m.device_windows)
            except Exception as e:  # noqa: BLE001
                errors.append(e)
            _time.sleep(0.001)

    threads = [threading.Thread(target=reloader),
               threading.Thread(target=metrics)]
    for t in threads:
        t.start()
    try:
        for _ in range(30):
            m.consume_lines(lines, now)
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert not errors, errors[:3]

    # determinism epilogue: with the allow list pinned ON, the flip must
    # be visible immediately (generation-keyed cache)
    sl.update_from_config(config_from_yaml_text(_yaml.safe_dump(
        {**base, "global_decision_lists": {"allow": ["10.1.2.2"]}}
    )))
    r = m.consume_lines(
        [f"{now:.6f} 10.1.2.2 GET h.com GET /attackpathZ HTTP/1.1 UA -"],
        now,
    )[0]
    assert r.exempted


# ---------------------------------------------------------------- warm tier


def test_warm_tier_round_trip_byte_identical():
    """Eviction spill into the warm tier and re-admission refill carry
    the per-rule (num_hits, interval_start) vectors BYTE-identically —
    the ISSUE 14 lossless-spill contract, asserted on the raw entry
    tuples, not just on continued-counting behavior."""
    rules = [make_rule("fast", 5.0, 100), make_rule("slow", 60.0, 100)]
    dw = DeviceWindows(rules, capacity=2, warm_tier_enabled=True,
                       warm_tier_capacity=64)
    assert dw._warm is not None
    active = np.ones((1, 2), dtype=bool)
    base = 1_700_000_000 * NS + 123_456_789  # odd ns: both words matter

    def hit(ip, t, bits):
        slots = dw.slots_for_ips([ip])
        ts_s, ts_ns = split_ns(np.array([t], dtype=np.int64))
        return dw.apply_bitmap(
            np.array([bits], dtype=np.uint8), slots, ts_s, ts_ns,
            active, np.zeros(1, dtype=np.int32),
        )

    hit("ip-a", base, [1, 1])
    hit("ip-a", base + 7, [1, 0])      # fast=2, slow=1, starts at base
    hit("ip-b", base + 8, [0, 1])
    snap = {r: (s.num_hits, s.interval_start_time_ns)
            for r, s in dw.get("ip-a")[0].items()}
    assert snap == {"fast": (2, base), "slow": (1, base)}

    hit("ip-c", base + 9, [1, 0])      # evicts ip-a -> SPILL to warm
    assert dw.warm_spills == 1
    assert dw.warm_occupancy == 1
    ent = dw._warm.peek("ip-a")
    assert ent is not None
    got = {rules[rid].rule: (h, s * NS + ns) for rid, h, s, ns in ent}
    assert got == snap                  # the raw spilled vectors
    assert "ip-a" not in dw._shadow     # warm is the home, not a copy

    hit("ip-a", base + 10, [1, 1])     # returns -> REFILL from warm;
    #                                    its slot claim evicts ip-b,
    #                                    which spills in turn
    assert dw.warm_refills == 1
    assert dw.warm_spills == 2
    assert dw.warm_occupancy == 1       # take(), not a copy: only ip-b
    assert dw._warm.peek("ip-a") is None
    assert dw._warm.peek("ip-b") is not None
    after = {r: (s.num_hits, s.interval_start_time_ns)
             for r, s in dw.get("ip-a")[0].items()}
    assert after == {"fast": (3, base), "slow": (2, base)}


@pytest.mark.parametrize("seed", [3, 4])
def test_warm_tier_churn_differential(seed):
    """The eviction-churn differential with the warm tier as the spill
    home: event streams and final per-(ip, rule) state still match the
    host oracle exactly, and the run actually spilled and refilled."""
    rules = [make_rule("fast", 5.0, 2), make_rule("slow", 60.0, 4)]
    rng = np.random.default_rng(seed)
    batches = random_batches(rng, 2, n_ips=24, n_batches=6, batch=16,
                             density=0.5)
    states, want = drive_oracle(rules, batches)
    dw, got = drive_device(rules, batches, capacity=8,
                           warm_tier_enabled=True, warm_tier_capacity=64)
    assert dw.eviction_count > 0
    assert dw.warm_spills > 0, "churn never spilled into the warm tier"
    assert dw.warm_refills > 0, "no returning IP ever refilled"
    assert got == want
    for i in range(24):
        ip = f"10.0.0.{i}"
        host_states, host_ok = states.get(ip)
        dev_states, dev_ok = dw.get(ip)
        assert host_ok == dev_ok, ip
        for rule, s in host_states.items():
            d = dev_states[rule]
            assert (s.num_hits, s.interval_start_time_ns) == (
                d.num_hits, d.interval_start_time_ns
            ), (ip, rule)


def test_warm_tier_drop_keeps_shadow_entry():
    """When the warm tier cannot place a spill (probe window full of
    live records), the shadow KEEPS the entry — pre-tiering lossless
    behavior — and the tier's dropped counter surfaces the pressure."""
    rules = [make_rule("r", 600.0, 100)]  # wide window: no expiry steals
    dw = DeviceWindows(rules, capacity=2, warm_tier_enabled=True,
                       warm_tier_capacity=1)  # tiny tier: drops fast
    active = np.ones((1, 1), dtype=bool)
    one = np.ones((1, 1), dtype=np.uint8)
    base = 1_700_000_000 * NS

    def hit(ip, t):
        slots = dw.slots_for_ips([ip])
        ts_s, ts_ns = split_ns(np.array([t], dtype=np.int64))
        dw.apply_bitmap(one, slots, ts_s, ts_ns, active,
                        np.zeros(1, dtype=np.int32))

    n = 12
    for i in range(n):  # constant churn: every placement evicts
        hit(f"ip-{i}", base + i)
    spilled_or_kept = 0
    for i in range(n - 2):  # the last 2 are hot-resident
        states, ok = dw.get(f"ip-{i}")
        assert ok and states["r"].num_hits == 1, f"ip-{i} state lost"
        spilled_or_kept += 1
    assert spilled_or_kept == n - 2
    assert dw.warm_dropped > 0, "tiny tier never reported drop pressure"
    # every dropped spill fell back to the shadow (lossless)
    assert dw.warm_spills + len(dw._shadow) >= n - 2
