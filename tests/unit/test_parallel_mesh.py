"""Multi-device sharded matcher vs single-device reference (8-dev CPU mesh).

conftest.py forces xla_force_host_platform_device_count=8, the same
mechanism the driver uses to validate multi-chip sharding without hardware.
"""

import re

import jax
import numpy as np
import pytest

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.rulec import compile_rules
from banjax_tpu.parallel.mesh import make_mesh, shard_params, sharded_match_fn

PATTERNS = [
    r"GET /wp-login\.php",
    r"POST /xmlrpc\.php",
    r"(GET|POST) /[a-z-]*\.php",
    r"^GET .* HTTP/1\.1$",
    r"Mozilla/\d+\.\d+",
    r"a+b",
    r"[0-9]{2,4}",
    r".*",
    r"^$",
    r"wp-admin",
]

LINES = [
    "GET example.com GET /wp-login.php HTTP/1.1",
    "POST example.com POST /xmlrpc.php HTTP/1.1",
    "GET example.com GET / HTTP/1.1",
    "aaab and 123",
    "Mozilla/5.0 something",
    "",
    "nothing interesting here",
    "GET site.org GET /wp-admin/panel HTTP/1.1",
] * 4  # 32 lines, divisible by dp


@pytest.mark.parametrize("dp,rp", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_matches_single_device(dp, rp):
    if len(jax.devices()) < dp * rp:
        pytest.skip("needs 8 virtual devices")
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(dp * rp, rp=rp)
    fn = sharded_match_fn(compiled, mesh)
    params = shard_params(compiled, mesh)
    cls_ids, lens, host_eval = encode_for_match(compiled, LINES, 128)
    assert not host_eval.any()
    got = np.asarray(fn(params, cls_ids, lens))

    ref_compiled = compile_rules(PATTERNS, n_shards=1)
    ref = np.asarray(
        nfa_jax.match_batch(
            nfa_jax.match_params(ref_compiled),
            *encode_for_match(ref_compiled, LINES, 128)[:2],
            ref_compiled.n_rules,
        )
    )
    assert (got == ref).all()
    # and both equal the re oracle
    for j, pat in enumerate(PATTERNS):
        rx = re.compile(pat)
        for i, line in enumerate(LINES):
            assert bool(got[i, j]) == (rx.search(line) is not None)


@pytest.mark.parametrize("dp,rp", [(4, 2), (2, 4)])
def test_sharded_pallas_backend_matches_oracle(dp, rp):
    """The production mesh path: Pallas kernel per device (interpret mode on
    the CPU mesh), via the batch-level ShardedMatchBackend."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < dp * rp:
        pytest.skip("needs 8 virtual devices")
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(dp * rp, rp=rp)
    backend = ShardedMatchBackend(
        compiled, mesh, 128, backend="pallas-interpret", block_b=8
    )
    cls_ids, lens, host_eval = encode_for_match(compiled, LINES, 128)
    assert not host_eval.any()
    got = backend.match_bits(cls_ids, lens)
    for j, pat in enumerate(PATTERNS):
        rx = re.compile(pat)
        for i, line in enumerate(LINES):
            assert bool(got[i, j]) == (rx.search(line) is not None), (pat, line)


@pytest.mark.parametrize("n_lines", [1, 3, 7, 13])
def test_sharded_backend_dp_remainder(n_lines):
    """Batches not divisible by dp * block_b pad transparently and return
    results in input order."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rp = 2
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(8, rp=rp)
    backend = ShardedMatchBackend(
        compiled, mesh, 128, backend="pallas-interpret", block_b=8
    )
    lines = LINES[:n_lines]
    cls_ids, lens, _ = encode_for_match(compiled, lines, 128)
    got = backend.match_bits(cls_ids, lens)
    assert got.shape == (n_lines, compiled.n_rules)
    for j, pat in enumerate(PATTERNS):
        rx = re.compile(pat)
        for i, line in enumerate(lines):
            assert bool(got[i, j]) == (rx.search(line) is not None), (pat, line)


def test_sharded_backend_xla_parity():
    """XLA mesh body and Pallas mesh body agree bit-for-bit."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rp = 4
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(8, rp=rp)
    cls_ids, lens, _ = encode_for_match(compiled, LINES, 128)
    a = ShardedMatchBackend(
        compiled, mesh, 128, backend="pallas-interpret", block_b=8
    ).match_bits(cls_ids, lens)
    b = ShardedMatchBackend(compiled, mesh, 128, backend="xla").match_bits(
        cls_ids, lens
    )
    assert (a == b).all()


@pytest.mark.parametrize("backend,dp,rp", [
    ("xla", 4, 2), ("xla", 2, 4), ("pallas-interpret", 4, 2),
])
def test_fused_mesh_prefilter_parity(backend, dp, rp):
    """VERDICT r2 item 5: the mesh path runs stage-1 gating — the fused
    two-stage sharded matcher must be bit-identical to the single-stage
    sharded matcher (and to Python re) on a filterable ruleset, including
    always-rules and empty lines."""
    import bench as _bench

    from banjax_tpu.matcher.prefilter import build_plan
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < dp * rp:
        pytest.skip("needs 8 virtual devices")
    patterns = _bench.generate_rules(40, seed=5) + [r".*", r"^$"]
    lines = _bench.generate_lines(64, patterns, seed=6, attack_rate=0.3) + [""]
    compiled = compile_rules(patterns, n_shards=rp)
    plan = build_plan(
        patterns,
        byte_classes=(compiled.byte_to_class, compiled.n_classes),
        stage2_shards=rp,
    )
    assert plan is not None and plan.n_always >= 2
    mesh = make_mesh(dp * rp, rp=rp)
    block = 8
    fused = ShardedMatchBackend(
        compiled, mesh, 128, backend=backend, block_b=block, plan=plan,
        cand_frac=1.0,
    )
    single = ShardedMatchBackend(
        compiled, mesh, 128, backend=backend, block_b=block
    )
    cls_ids, lens, host_eval = encode_for_match(compiled, lines, 128)
    assert not host_eval.any()
    got = fused.match_bits(cls_ids, lens)
    want = single.match_bits(cls_ids, lens)
    for rid in plan.unsupported:
        want[:, rid] = 0
    np.testing.assert_array_equal(got, want)
    assert fused.fused_batches == 1 and fused.fallback_batches == 0


def test_fused_mesh_overflow_falls_back():
    """Per-dp-shard candidate overflow reruns the batch single-stage —
    identical output, fallback counter ticks."""
    import bench as _bench

    from banjax_tpu.matcher.prefilter import build_plan
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    patterns = _bench.generate_rules(30, seed=8)
    # every line matches: candidates exceed any fractional capacity
    lines = _bench.generate_lines(64, patterns, seed=9, attack_rate=1.0)
    rp = 2
    compiled = compile_rules(patterns, n_shards=rp)
    plan = build_plan(
        patterns,
        byte_classes=(compiled.byte_to_class, compiled.n_classes),
        stage2_shards=rp,
    )
    assert plan is not None
    mesh = make_mesh(8, rp=rp)
    fused = ShardedMatchBackend(
        compiled, mesh, 128, backend="xla", block_b=8, plan=plan,
        cand_frac=1.0 / 64,
    )
    single = ShardedMatchBackend(compiled, mesh, 128, backend="xla", block_b=8)
    cls_ids, lens, _ = encode_for_match(compiled, lines, 128)
    got = fused.match_bits(cls_ids, lens)
    want = single.match_bits(cls_ids, lens)
    for rid in plan.unsupported:
        want[:, rid] = 0
    np.testing.assert_array_equal(got, want)
    assert fused.fallback_batches == 1


def test_rp_mismatch_rejected():
    """A ruleset compiled for K shards cannot ride a mesh with rp != K."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend, sharded_pallas_fn
    from banjax_tpu.matcher.kernels import nfa_match

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    compiled = compile_rules(PATTERNS, n_shards=2)
    mesh = make_mesh(8, rp=4)
    with pytest.raises(ValueError, match="shards"):
        sharded_match_fn(compiled, mesh)
    with pytest.raises(ValueError, match="shards"):
        sharded_pallas_fn(nfa_match.prepare(compiled), mesh, 32, 8, 8)


def test_mesh_tpu_matcher_consume_lines_matches_cpu_oracle():
    """TpuMatcher in mesh mode (the config-driven product path) produces the
    identical ConsumeLineResult stream + Banner effects as CpuMatcher."""
    import time

    from tests.mesh_oracle import assert_mesh_matches_cpu_oracle

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    yaml_text = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: 'rule1'
    regex: 'GET example\.com GET .*'
    interval: 5
    hits_per_interval: 2
  - decision: challenge
    rule: 'rule2'
    regex: 'POST .*'
    interval: 5
    hits_per_interval: 1
"""
    now = time.time()
    lines = [
        f"{now:.6f} 10.1.1.{i % 4} GET example.com GET /x{i} HTTP/1.1"
        for i in range(20)
    ] + [
        f"{now:.6f} 10.1.1.9 POST example.com POST /submit HTTP/1.1"
        for _ in range(4)
    ]
    assert_mesh_matches_cpu_oracle(yaml_text, lines, now, 8, 2, interpret=True)


def test_mesh_long_line_near_max_len():
    """A line at exactly matcher_max_line_len survives the L_p trim (the
    mesh path must column-slice both sides of the copy)."""
    import time

    from tests.mesh_oracle import assert_mesh_matches_cpu_oracle

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    yaml_text = (
        "regexes_with_rates:\n"
        "  - decision: nginx_block\n"
        "    rule: tail\n"
        "    regex: 'zzz$'\n"
        "    interval: 5\n"
        "    hits_per_interval: 2\n"
        "matcher_max_line_len: 100\n"
    )
    now = time.time()
    rest = "GET h.com GET /" + "a" * 82 + "zzz"  # rest is exactly 100 chars
    assert len(rest) == 100
    lines = [f"{now:.6f} 5.6.7.8 {rest}", f"{now:.6f} 5.6.7.8 GET h.com GET /"]
    assert_mesh_matches_cpu_oracle(yaml_text, lines, now, 8, 2, interpret=True)


def test_mesh_more_devices_than_available_degrades():
    """matcher_mesh_devices beyond the attached device count falls back to
    the single-device path with a warning, not a crash."""
    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from tests.mock_banner import MockBanner

    cfg = config_from_yaml_text(
        "regexes_with_rates:\n"
        "  - decision: nginx_block\n"
        "    rule: r\n"
        "    regex: 'GET .*'\n"
        "    interval: 5\n"
        "    hits_per_interval: 2\n"
    )
    cfg.matcher_mesh_devices = 4096
    m = TpuMatcher(
        cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates()
    )
    assert m._mesh_matcher is None
    r = m.consume_line(f"{__import__('time').time():.6f} 1.2.3.4 GET h.com GET /")
    assert not r.error


def test_sharded_backend_bounded_compile_cache():
    """Varying batch sizes and line lengths share power-of-two buckets, so
    the per-(Bp, L_p) jit cache stays bounded in the hot path."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rp = 2
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(8, rp=rp)
    backend = ShardedMatchBackend(
        compiled, mesh, 128, backend="pallas-interpret", block_b=8
    )
    for n in (1, 3, 9, 17, 25, 31, 32):
        lines = LINES[:n]
        cls_ids, lens, _ = encode_for_match(compiled, lines, 128)
        out = backend.match_bits(cls_ids, lens)
        assert out.shape == (n, compiled.n_rules)
    assert len(backend._fns) == 1  # all bucket to (32, 64)


def test_sharded_submit_collect_split_and_shard_merge():
    """The pipeline's sharded submit/drain seam: submit dispatches without
    forcing, overlapped submits stay independent, collect merges per-shard
    pulls back into caller line order identically to match_bits, and the
    per-shard merge latencies/counters are recorded."""
    from banjax_tpu.parallel.mesh import ShardedMatchBackend

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rp = 2
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(8, rp=rp)
    backend = ShardedMatchBackend(
        compiled, mesh, 128, backend="pallas-interpret", block_b=8
    )
    cls_ids, lens, _ = encode_for_match(compiled, LINES, 128)
    want = backend.match_bits(cls_ids, lens)

    # two batches in flight at once, collected out of submit order
    p1 = backend.submit(cls_ids, lens)
    p2 = backend.submit(cls_ids[:7], lens[:7])
    got2 = backend.collect(p2)
    got1 = backend.collect(p1)
    assert (got1 == want).all()
    assert (got2 == want[:7]).all()

    # per-shard merge really happened: one timed pull per dp member
    assert len(backend.last_shard_merge_ms) >= 1
    assert backend.submit_ms_ewma is not None
    assert backend.merge_ms_ewma is not None
    assert p1["h2d_bytes"] > 0 and p1["d2h_bytes"] > 0


def test_pipelined_mesh_stream_matches_cpu_oracle():
    """The full tentpole seam on the 8-device CPU mesh: the streaming
    pipeline scheduler driving a mesh-mode TpuMatcher — sharded submit,
    per-shard merge at collect, ordered device-window commit at drain —
    byte-identical to the CPU reference (shared harness with the driver's
    dryrun_multichip)."""
    import time as _time

    import yaml as _yaml

    from tests.mesh_oracle import assert_pipelined_mesh_matches_cpu_oracle

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    rules_yaml = _yaml.safe_dump({
        "regexes_with_rates": [
            {"decision": "nginx_block", "rule": f"rule{j}", "regex": pat,
             "interval": 5, "hits_per_interval": 2}
            for j, pat in enumerate(PATTERNS)
        ]
    })
    now = _time.time()
    log_lines = [
        f"{now:.6f} 10.0.0.{i % 3} {line}" for i, line in enumerate(LINES)
    ]
    assert_pipelined_mesh_matches_cpu_oracle(
        rules_yaml, log_lines, now, 8, 2,
        interpret=True, device_windows=True,
    )
