"""Multi-device sharded matcher vs single-device reference (8-dev CPU mesh).

conftest.py forces xla_force_host_platform_device_count=8, the same
mechanism the driver uses to validate multi-chip sharding without hardware.
"""

import re

import jax
import numpy as np
import pytest

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.rulec import compile_rules
from banjax_tpu.parallel.mesh import make_mesh, shard_params, sharded_match_fn

PATTERNS = [
    r"GET /wp-login\.php",
    r"POST /xmlrpc\.php",
    r"(GET|POST) /[a-z-]*\.php",
    r"^GET .* HTTP/1\.1$",
    r"Mozilla/\d+\.\d+",
    r"a+b",
    r"[0-9]{2,4}",
    r".*",
    r"^$",
    r"wp-admin",
]

LINES = [
    "GET example.com GET /wp-login.php HTTP/1.1",
    "POST example.com POST /xmlrpc.php HTTP/1.1",
    "GET example.com GET / HTTP/1.1",
    "aaab and 123",
    "Mozilla/5.0 something",
    "",
    "nothing interesting here",
    "GET site.org GET /wp-admin/panel HTTP/1.1",
] * 4  # 32 lines, divisible by dp


@pytest.mark.parametrize("dp,rp", [(8, 1), (4, 2), (2, 4), (1, 8)])
def test_sharded_matches_single_device(dp, rp):
    if len(jax.devices()) < dp * rp:
        pytest.skip("needs 8 virtual devices")
    compiled = compile_rules(PATTERNS, n_shards=rp)
    mesh = make_mesh(dp * rp, rp=rp)
    fn = sharded_match_fn(compiled, mesh)
    params = shard_params(compiled, mesh)
    cls_ids, lens, host_eval = encode_for_match(compiled, LINES, 128)
    assert not host_eval.any()
    got = np.asarray(fn(params, cls_ids, lens))

    ref_compiled = compile_rules(PATTERNS, n_shards=1)
    ref = np.asarray(
        nfa_jax.match_batch(
            nfa_jax.match_params(ref_compiled),
            *encode_for_match(ref_compiled, LINES, 128)[:2],
            ref_compiled.n_rules,
        )
    )
    assert (got == ref).all()
    # and both equal the re oracle
    for j, pat in enumerate(PATTERNS):
        rx = re.compile(pat)
        for i, line in enumerate(LINES):
            assert bool(got[i, j]) == (rx.search(line) is not None)
