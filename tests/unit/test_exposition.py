"""Exposition-schema stability: every key the 29 s line emits and every
/metrics family is declared in the one registry (obs/registry.py), the
reference's five keys stay byte-identical, /metrics parses under the
strict text-format parser, and the README metrics table stays in
lock-step with the registry (scripts/check_metrics_docs.py)."""

import io
import json
import os
import subprocess
import sys
import time
import types

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs import registry
from banjax_tpu.obs.exposition import (
    ExpositionError,
    parse_text_format,
    render_prometheus,
)
from banjax_tpu.obs.metrics import write_metrics_line
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.resilience.health import HealthRegistry
from tests.mock_banner import MockBanner

RULES_YAML = """
regexes_with_rates:
  - decision: nginx_block
    rule: r
    regex: 'GET .*'
    interval: 5
    hits_per_interval: 100
"""

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))


@pytest.fixture(scope="module")
def loaded_system():
    """A matcher + drained pipeline with device windows on — the fullest
    legitimately reachable snapshot surface."""
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    m = TpuMatcher(cfg, MockBanner(), StaticDecisionLists(cfg),
                   RegexRateLimitStates())
    now = time.time()
    m.consume_lines(
        [f"{now:.6f} 9.9.9.{i} GET h.com GET /x HTTP/1.1" for i in range(8)],
        now,
    )
    sched = PipelineScheduler(lambda: m, now_fn=lambda: now)
    sched.start()
    sched.submit(
        [f"{now:.6f} 8.8.8.{i % 40} GET h.com GET /y HTTP/1.1"
         for i in range(256)]
    )
    assert sched.flush(60)
    # sharded-encode stats so the per-worker gauges have data
    sched.stats.note_encode_shards([4.0, 5.0], 5.5)
    sched.stats.note_encode_shards([3.0, 6.0], 6.5)
    health = HealthRegistry()
    health.register("tailer").ok()
    health.register("pipeline").degraded("test")
    sup = types.SimpleNamespace(n_workers=2, respawn_count=1)
    yield m, sched, health, sup
    sched.stop()


def _full_line(m, sched, health, sup) -> dict:
    out = io.StringIO()
    write_metrics_line(
        out, DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        m, sup, health, sched,
    )
    return json.loads(out.getvalue())


def test_every_line_key_is_declared(loaded_system):
    line = _full_line(*loaded_system)
    undeclared = [k for k in line if not registry.is_declared_line_key(k)]
    assert not undeclared, (
        f"29s-line keys missing from obs/registry.py: {undeclared} — "
        "declare them (name, type, help) or the dashboards chase ghosts"
    )


def test_reference_five_keys_byte_identical(loaded_system):
    line = _full_line(*loaded_system)
    for key in registry.REFERENCE_LINE_KEYS:
        assert key in line, f"reference key {key} missing"
    # the declared tuple itself is the reference's exact bytes
    assert registry.REFERENCE_LINE_KEYS == (
        "Time", "LenExpiringChallenges", "LenExpiringBlocks",
        "LenIpToRegexStates", "LenFailedChallengeStates",
    )


def test_metrics_families_all_declared_and_parse(loaded_system):
    m, sched, health, sup = loaded_system
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), matcher=m, pipeline=sched,
        health=health, supervisor=sup,
    )
    fams = parse_text_format(text)  # strict: raises on any malformation
    undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
    assert not undeclared, f"/metrics families not in registry: {undeclared}"
    # declared type matches emitted type
    for name, ent in fams.items():
        assert ent["type"] == registry.PROM_FAMILIES[name].kind, name
    # core families present with plausible values
    samples = {
        s[0]: s[2] for ent in fams.values() for s in ent["samples"]
        if not s[1]
    }
    assert samples["banjax_matcher_lines_total"] >= 8
    assert samples["banjax_pipeline_processed_lines_total"] == 256
    assert samples["banjax_health_status"] == 1  # degraded component


def test_breaker_state_is_one_hot(loaded_system):
    m, sched, health, sup = loaded_system
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), matcher=m,
    )
    fams = parse_text_format(text)
    states = {
        s[1]["state"]: s[2]
        for s in fams["banjax_matcher_breaker_state"]["samples"]
    }
    assert set(states) == {"closed", "open", "half-open"}
    assert sum(states.values()) == 1
    assert states["closed"] == 1


def test_per_worker_busy_fraction_and_skew(loaded_system):
    m, sched, health, sup = loaded_system
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), pipeline=sched,
    )
    fams = parse_text_format(text)
    workers = {
        s[1]["worker"]: s[2]
        for s in fams["banjax_encode_worker_busy_fraction"]["samples"]
    }
    assert set(workers) == {"0", "1"}
    assert 0.0 < workers["0"] <= 1.0 and 0.0 < workers["1"] <= 1.0
    # shard 1 is the consistently slower one in the fixture data
    assert workers["1"] > workers["0"]
    (skew,) = [
        s[2] for s in fams["banjax_encode_shard_skew_max"]["samples"]
    ]
    assert skew > 1.0


def test_scrape_does_not_steal_line_windows(loaded_system):
    """peek()-based exposition must leave the 29 s line's interval
    windows untouched: scrape between two lines, the line still sees the
    full interval delta."""
    m, sched, health, sup = loaded_system
    now = time.time()
    m.consume_lines(
        [f"{now:.6f} 7.7.7.{i} GET h.com GET /z HTTP/1.1" for i in range(5)],
        now,
    )
    for _ in range(3):  # scrapes between line snapshots
        render_prometheus(
            DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
            FailedChallengeRateLimitStates(), matcher=m, pipeline=sched,
        )
    line = _full_line(m, sched, health, sup)
    # the interval window still holds the 5 lines: scrapes didn't reset it
    assert line["MatcherLinesPerSec"] > 0


def test_parser_rejects_malformed_exposition():
    bad_cases = [
        "banjax_x 1\n",                      # sample without TYPE
        "# TYPE banjax_x counter\nbanjax_x 1",  # missing trailing newline
        "# TYPE banjax_x counter\nbanjax_x notanumber\n",
        "# TYPE banjax_x counter\n# TYPE banjax_x counter\nbanjax_x 1\n",
        '# TYPE banjax_x counter\nbanjax_x{bad-label="v"} 1\n',
        "# TYPE banjax_x counter\nbanjax_x -3\n",  # negative counter
    ]
    for text in bad_cases:
        with pytest.raises(ExpositionError):
            parse_text_format(text)


def test_parser_rejects_bad_histograms():
    head = "# TYPE banjax_h histogram\n"
    no_inf = head + (
        'banjax_h_bucket{le="1.0"} 1\nbanjax_h_sum 1\nbanjax_h_count 1\n'
    )
    non_monotone = head + (
        'banjax_h_bucket{le="1.0"} 5\nbanjax_h_bucket{le="+Inf"} 3\n'
        "banjax_h_sum 1\nbanjax_h_count 3\n"
    )
    inf_ne_count = head + (
        'banjax_h_bucket{le="1.0"} 1\nbanjax_h_bucket{le="+Inf"} 2\n'
        "banjax_h_sum 1\nbanjax_h_count 3\n"
    )
    for text in (no_inf, non_monotone, inf_ne_count):
        with pytest.raises(ExpositionError):
            parse_text_format(text)


def test_histogram_observations_land_in_buckets(loaded_system):
    m, sched, health, sup = loaded_system
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), matcher=m, pipeline=sched,
    )
    fams = parse_text_format(text)
    batch = fams["banjax_batch_latency_seconds"]["samples"]
    count = [v for n, l, v in batch if n.endswith("_count")][0]
    assert count >= 1  # consume_lines recorded batches
    stages = {
        s[1].get("stage") for s in
        fams["banjax_stage_duration_seconds"]["samples"]
        if s[0].endswith("_bucket")
    }
    assert {"encode", "device", "drain"} <= stages


def test_check_metrics_docs_passes_and_catches_drift(tmp_path):
    script = os.path.join(_REPO, "scripts", "check_metrics_docs.py")
    r = subprocess.run(
        [sys.executable, script], capture_output=True, text=True,
        cwd=_REPO, timeout=120,
    )
    assert r.returncode == 0, r.stdout + r.stderr
    # drift detection: drop one documented row -> nonzero exit
    with open(os.path.join(_REPO, "README.md"), encoding="utf-8") as f:
        readme = f.read()
    drifted = readme.replace("| `banjax_matcher_lines_total` |", "| `x` |", 1)
    p = tmp_path / "README.md"
    p.write_text(drifted, encoding="utf-8")
    r = subprocess.run(
        [sys.executable, script, str(p)], capture_output=True, text=True,
        cwd=_REPO, timeout=120,
    )
    assert r.returncode == 1
    assert "banjax_matcher_lines_total" in r.stderr


def test_provenance_slo_flightrec_families_render_and_declare(
    loaded_system, tmp_path
):
    """The ISSUE 6 families: banjax_decision_inserts_total{source,
    decision}, banjax_slo_burn_rate{slo,window}, the one-hot
    banjax_slo_breached, banjax_matcher_budget_trips_total and
    banjax_flightrec_incidents_total all render from real objects,
    parse strictly, and are registry-declared."""
    from banjax_tpu.obs import provenance
    from banjax_tpu.obs.flightrec import FlightRecorder
    from banjax_tpu.obs.slo import SloEngine

    m, sched, health, sup = loaded_system
    provenance.configure(enabled=True, ring_size=64)
    try:
        provenance.record(provenance.SOURCE_KAFKA, "1.2.3.4", "NginxBlock",
                          rule="block_ip")
        provenance.record(provenance.SOURCE_RATE_LIMIT, "1.2.3.4",
                          "Challenge", rule="r")
        m.budget_trips += 2
        engine = SloEngine(
            matcher_getter=lambda: m, pipeline_getter=lambda: sched,
            batch_budget_s_fn=lambda: 0.25,
        )
        engine.sample()
        engine.sample()
        rec = FlightRecorder(str(tmp_path / "inc"), min_interval_s=0.0)
        rec.notify("test")
        text = render_prometheus(
            DynamicDecisionLists(start_sweeper=False),
            RegexRateLimitStates(), FailedChallengeRateLimitStates(),
            matcher=m, pipeline=sched, health=health, supervisor=sup,
            slo=engine, flightrec=rec,
        )
        fams = parse_text_format(text)
        undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
        assert not undeclared, undeclared

        inserts = {
            (s[1]["source"], s[1]["decision"]): s[2]
            for s in fams["banjax_decision_inserts_total"]["samples"]
        }
        assert inserts[("kafka", "NginxBlock")] == 1
        assert inserts[("rate_limit", "Challenge")] == 1

        burn = {
            (s[1]["slo"], s[1]["window"])
            for s in fams["banjax_slo_burn_rate"]["samples"]
        }
        assert ("batch_latency", "5m") in burn
        assert ("shed_ratio", "5m") in burn
        breached = {
            s[1]["slo"]: s[2]
            for s in fams["banjax_slo_breached"]["samples"]
        }
        assert set(breached) == {
            "batch_latency", "shed_ratio", "stale_ratio", "breaker_open",
            "budget_trips",
        }
        scalars = {
            s[0]: s[2] for ent in fams.values() for s in ent["samples"]
            if not s[1]
        }
        assert scalars["banjax_matcher_budget_trips_total"] == 2
        assert scalars["banjax_flightrec_incidents_total"] == 1
    finally:
        provenance.configure(enabled=True)


def test_budget_trips_on_the_29s_line(loaded_system):
    line = _full_line(*loaded_system)
    assert "MatcherBudgetTrips" in line
    assert registry.is_declared_line_key("MatcherBudgetTrips")


def test_traffic_families_render_and_declare(loaded_system):
    """The ISSUE 8 families: scalar banjax_traffic_* gauges/counters
    ride the line-key map, the labeled banjax_traffic_rule_pressure
    comes from the sketch's pulled summary, and everything parses
    strictly and is registry-declared."""
    m, sched, health, sup = loaded_system
    assert m.traffic_sketch is not None
    m.traffic_sketch.pull(force=True)  # a fresh summary for the render
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), matcher=m, pipeline=sched,
        health=health, supervisor=sup,
    )
    fams = parse_text_format(text)
    undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
    assert not undeclared, undeclared
    scalars = {
        s[0]: s[2] for ent in fams.values() for s in ent["samples"]
        if not s[1]
    }
    assert scalars["banjax_traffic_sketch_lines_total"] >= 8
    assert scalars["banjax_traffic_distinct_ips_estimate"] > 0
    assert scalars["banjax_traffic_sketch_pull_bytes_total"] > 0
    assert "banjax_traffic_sketch_pull_age_seconds" in fams
    # the fixture's rule ("GET .*") fires on every line: pressure renders
    pressure = {
        s[1]["rule"]: s[2]
        for s in fams["banjax_traffic_rule_pressure"]["samples"]
    }
    assert pressure.get("r", 0) > 0
    # ... and the line keys are declared too
    line = _full_line(m, sched, health, sup)
    for key in ("TrafficSketchLines", "TrafficDistinctIpsEst",
                "TrafficHeavyHitterShare", "TrafficSketchPullBytes",
                "TrafficSketchPullAgeSeconds"):
        assert key in line, key
        assert registry.is_declared_line_key(key), key


def test_single_kernel_depth_ignored_on_line_and_metrics(loaded_system):
    """The PR 7 silent-ignore satellite: drain_resolve_depth configured
    (default 2) + single-kernel active => the gauge flags the no-op on
    both surfaces."""
    m, sched, health, sup = loaded_system
    if not (m._fw_pipeline is not None and m._fw_pipeline.single_kernel):
        pytest.skip("single-kernel path unavailable on this backend")
    line = _full_line(m, sched, health, sup)
    assert line["SingleKernelDepthIgnored"] is True
    assert registry.is_declared_line_key("SingleKernelDepthIgnored")
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(), matcher=m,
    )
    fams = parse_text_format(text)
    (v,) = [
        s[2] for s in fams["banjax_single_kernel_depth_ignored"]["samples"]
    ]
    assert v == 1


def test_challenge_families_render_and_declare():
    """The ISSUE 17 families: drive the real challenge plane — stateless
    issuance, an accepted device-path verification, a rejected one, and
    a bounded failure state under eviction pressure — then require every
    banjax_challenge_* family and Challenge* line key on both surfaces,
    registry-declared, with the values the drive produced."""
    from banjax_tpu.challenge import issuer, verifier
    from banjax_tpu.challenge.failures import BoundedFailedChallengeStates
    from banjax_tpu.challenge.stats import get_stats as challenge_stats
    from banjax_tpu.crypto.challenge import (
        CookieError,
        solve_challenge_for_testing,
    )

    challenge_stats().reset()
    secret, binding = "expo-secret", "5.6.7.8"
    cookie = issuer.issue(secret, 300, binding)
    solved = solve_challenge_for_testing(cookie, zero_bits=6)
    dv = verifier.DeviceVerifier(batch_max=16, interpret=True)
    now = time.time()
    verifier.verify_sha_inv(secret, solved, now, binding, 6, device=dv)
    with pytest.raises(CookieError):
        verifier.verify_sha_inv(secret, solved, now, binding, 250, device=dv)

    fc = BoundedFailedChallengeStates(4)
    cfg = config_from_yaml_text(RULES_YAML)
    for i in range(12):
        fc.apply(f"6.6.6.{i}", cfg)
    assert len(fc) == 4
    assert fc.evictions_total == 8

    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        fc,
    )
    fams = parse_text_format(text)
    undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
    assert not undeclared, undeclared
    scalars = {
        s[0]: s[2] for ent in fams.values() for s in ent["samples"]
        if not s[1]
    }
    assert scalars["banjax_challenge_issued_total"] == 1
    assert scalars["banjax_challenge_failure_state_entries"] == 4
    assert scalars["banjax_challenge_failure_evictions_total"] == 8
    verif = {
        (s[1]["result"], s[1]["path"]): s[2]
        for s in fams["banjax_challenge_verifications_total"]["samples"]
    }
    assert verif[("accept", "device")] == 1
    assert verif[("reject", "device")] == 1
    hist = fams["banjax_challenge_verify_batch_size"]["samples"]
    count = [v for n, l, v in hist if n.endswith("_count")][0]
    assert count == 2  # one dispatch per verification above

    out = io.StringIO()
    write_metrics_line(
        out, DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), fc,
    )
    line = json.loads(out.getvalue())
    for key in ("ChallengeIssued", "ChallengeVerifications",
                "ChallengeFailureStateEntries", "ChallengeFailureEvictions"):
        assert key in line, key
        assert registry.is_declared_line_key(key), key
    assert line["ChallengeIssued"] == 1
    assert line["ChallengeVerifications"] == 2
    assert line["ChallengeFailureStateEntries"] == 4
    # the reference length key reports the bounded exact tier
    assert line["LenFailedChallengeStates"] == 4


def test_challenge_quiet_process_stays_schema_clean(loaded_system):
    """A process that never touched the challenge plane must emit no
    Challenge* line keys and no banjax_challenge_* families — the
    reference's exact key set is preserved."""
    from banjax_tpu.challenge.stats import get_stats as challenge_stats

    challenge_stats().reset()
    line = _full_line(*loaded_system)
    assert not [k for k in line if k.startswith("Challenge")]
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False), RegexRateLimitStates(),
        FailedChallengeRateLimitStates(),
    )
    assert "banjax_challenge_" not in text


def test_mega_state_families_render_and_declare():
    """The ISSUE 14 tiering families: a gated matcher whose unseen IPs
    all land BELOW the derived admission threshold (the fixture rule
    needs 101 hits) refuses every slot claim, homes the refused-row
    window state in the warm tier, and must surface all of it on both
    exposition surfaces with every name registry-declared."""
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    cfg.matcher_window_capacity = 64
    cfg.traffic_sketch_enabled = True
    cfg.slot_admission_enabled = True   # min_estimate 0 -> derived 101
    cfg.warm_tier_enabled = True
    cfg.warm_tier_capacity = 1024
    m = TpuMatcher(cfg, MockBanner(), StaticDecisionLists(cfg),
                   RegexRateLimitStates())
    try:
        now = time.time()
        m.consume_lines(
            [f"{now:.6f} 7.7.{i >> 8}.{i & 255} GET h.com GET /x HTTP/1.1"
             for i in range(48)],
            now,
        )
        dw = m.device_windows
        assert dw.slot_refusals >= 48      # every unseen IP refused
        assert dw.warm_spills > 0          # refused state homes warm
        text = render_prometheus(
            DynamicDecisionLists(start_sweeper=False),
            RegexRateLimitStates(), FailedChallengeRateLimitStates(),
            matcher=m,
        )
        fams = parse_text_format(text)
        undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
        assert not undeclared, undeclared
        scalars = {
            s[0]: s[2] for ent in fams.values() for s in ent["samples"]
            if not s[1]
        }
        assert scalars["banjax_slot_refusals_total"] >= 48
        assert scalars["banjax_sketch_admissions_total"] == 0
        assert scalars["banjax_sketch_admission_fp_rate"] == 0
        assert scalars["banjax_warm_tier_spills_total"] > 0
        assert scalars["banjax_warm_tier_refills_total"] == 0
        assert scalars["banjax_warm_tier_dropped_total"] == 0
        assert scalars["banjax_warm_tier_occupancy"] > 0
        assert scalars["banjax_warm_tier_capacity"] == 1024
        out = io.StringIO()
        write_metrics_line(
            out, DynamicDecisionLists(start_sweeper=False),
            RegexRateLimitStates(), FailedChallengeRateLimitStates(), m,
        )
        line = json.loads(out.getvalue())
        for key in ("SlotRefusals", "SketchAdmissions",
                    "SketchAdmissionFpRate", "WarmTierSpills",
                    "WarmTierRefills", "WarmTierDropped",
                    "WarmTierOccupancy", "WarmTierCapacity"):
            assert key in line, key
            assert registry.is_declared_line_key(key), key
        assert line["SlotRefusals"] >= 48
        assert line["WarmTierCapacity"] == 1024
    finally:
        m.close()
