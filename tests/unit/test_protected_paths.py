"""Password-protected path classification (reference: internal/config_test.go:35-81,
password_protected_path.go)."""

import hashlib

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths, PathType


YAML = """
password_protected_paths:
  "example.com":
    - wp-admin
    - /secret/
password_protected_path_exceptions:
  "example.com":
    - wp-admin/admin-ajax.php
password_hashes:
  "example.com": 5e884898da28047151d0e56f8dc6292773603d0d6aabbdd62a11ef721d1542d8
password_hash_roaming:
  sub.example.com: example.com
"""


def make_paths():
    return PasswordProtectedPaths(config_from_yaml_text(YAML))


def test_classify_protected_prefix():
    paths = make_paths()
    assert paths.classify_path("example.com", "/wp-admin") is PathType.PASSWORD_PROTECTED
    assert paths.classify_path("example.com", "/wp-admin/post.php") is PathType.PASSWORD_PROTECTED
    assert paths.classify_path("example.com", "/secret/x") is PathType.PASSWORD_PROTECTED


def test_classify_exception_beats_protected():
    paths = make_paths()
    assert (
        paths.classify_path("example.com", "/wp-admin/admin-ajax.php")
        is PathType.PASSWORD_PROTECTED_EXCEPTION
    )


def test_classify_unprotected():
    paths = make_paths()
    assert paths.classify_path("example.com", "/index.html") is PathType.NOT_PASSWORD_PROTECTED
    assert paths.classify_path("other.com", "/wp-admin") is PathType.NOT_PASSWORD_PROTECTED


def test_password_hash_decoding():
    paths = make_paths()
    h, ok = paths.get_password_hash("example.com")
    assert ok
    assert h == hashlib.sha256(b"password").digest()
    _, ok = paths.get_password_hash("other.com")
    assert not ok


def test_roaming_hash_inherits_root():
    paths = make_paths()
    h, ok = paths.get_roaming_password_hash("sub.example.com")
    assert ok
    assert h == hashlib.sha256(b"password").digest()
    # roaming flips the root's expand-cookie-domain flag
    flag, ok = paths.get_expand_cookie_domain("example.com")
    assert ok and flag
    _, ok = paths.get_expand_cookie_domain("sub.example.com")
    assert not ok


def test_is_exception_exact_only():
    paths = make_paths()
    assert paths.is_exception("example.com", "/wp-admin/admin-ajax.php")
    assert not paths.is_exception("example.com", "/wp-admin/admin-ajax.php/extra")
    assert not paths.is_exception("other.com", "/wp-admin/admin-ajax.php")


def test_bad_hash_raises():
    with pytest.raises(ValueError):
        PasswordProtectedPaths(
            config_from_yaml_text(
                """
password_hashes:
  "example.com": not-hex
"""
            )
        )


def test_hot_reload():
    paths = make_paths()
    paths.update_from_config(config_from_yaml_text("password_protected_paths: {}"))
    assert paths.classify_path("example.com", "/wp-admin") is PathType.NOT_PASSWORD_PROTECTED
