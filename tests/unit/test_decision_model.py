"""Decision enum semantics (reference: internal/decision.go:20-85)."""

import pytest

from banjax_tpu.decisions.model import (
    Decision,
    FailAction,
    parse_decision,
    parse_fail_action,
)


def test_severity_ordering():
    assert Decision.ALLOW < Decision.CHALLENGE < Decision.NGINX_BLOCK < Decision.IPTABLES_BLOCK


def test_parse_decision():
    assert parse_decision("allow") is Decision.ALLOW
    assert parse_decision("challenge") is Decision.CHALLENGE
    assert parse_decision("nginx_block") is Decision.NGINX_BLOCK
    assert parse_decision("iptables_block") is Decision.IPTABLES_BLOCK
    with pytest.raises(ValueError):
        parse_decision("nonsense")


def test_decision_string():
    assert str(Decision.ALLOW) == "Allow"
    assert str(Decision.CHALLENGE) == "Challenge"
    assert str(Decision.NGINX_BLOCK) == "NginxBlock"
    assert str(Decision.IPTABLES_BLOCK) == "IptablesBlock"


def test_parse_fail_action():
    assert parse_fail_action("block") is FailAction.BLOCK
    assert parse_fail_action("no_block") is FailAction.NO_BLOCK
    with pytest.raises(ValueError):
        parse_fail_action("whatever")
