"""The /auth_request decision chain (reference: internal/http_server.go:861-1136,
integration cases from banjax_integration_test.go)."""

import base64
import hashlib
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.crypto.challenge import (
    new_challenge_cookie,
    parse_cookie,
    solve_challenge_for_testing,
)
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    DecisionListResult,
    RequestInfo,
    decision_for_nginx,
)
from tests.mock_banner import MockBanner


CONFIG_YAML = r"""
config_version: test-1
global_decision_lists:
  allow:
    - 20.20.20.20
  nginx_block:
    - 70.80.90.100
  challenge:
    - 8.8.8.8
per_site_decision_lists:
  "example.com":
    allow:
      - 90.90.90.90
    challenge:
      - 91.91.91.91
    nginx_block:
      - 92.92.92.92
per_site_user_agent_decision_lists:
  "example.com":
    allow:
      - "GoodBot"
global_user_agent_decision_lists:
  nginx_block:
    - "BadBot"
password_protected_paths:
  "example.com":
    - wp-admin
password_protected_path_exceptions:
  "example.com":
    - wp-admin/admin-ajax.php
password_hashes:
  "example.com": 5e884898da28047151d0e56f8dc6292773603d0d6aabbdd62a11ef721d1542d8
sitewide_sha_inv_list:
  shainv.com: block
  noblock.com: no_block
sha_inv_path_exceptions:
  "example.com":
    - /no_challenge
sites_to_disable_baskerville:
  nobask.com: false
iptables_ban_seconds: 10
kafka_brokers: [localhost:9092]
server_log_file: /tmp/banjax-chain-test.log
expiring_decision_ttl_seconds: 10
too_many_failed_challenges_interval_seconds: 10
too_many_failed_challenges_threshold: 2
password_cookie_ttl_seconds: 14400
sha_inv_cookie_ttl_seconds: 14400
sha_inv_expected_zero_bits: 10
hmac_secret: secret
session_cookie_hmac_secret: session_secret
session_cookie_ttl_seconds: 3600
disable_kafka: true
"""


def load_config(yaml_text):
    """config_from_yaml_text + the page-embed step ConfigHolder would do."""
    from banjax_tpu.config.holder import _PAGES_DIR

    config = config_from_yaml_text(yaml_text)
    config.challenger_bytes = (_PAGES_DIR / "sha-inverse-challenge.html").read_bytes()
    config.password_page_bytes = (_PAGES_DIR / "password-protected-path.html").read_bytes()
    return config


@pytest.fixture()
def state():
    config = load_config(CONFIG_YAML)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    return ChainState(
        config=config,
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(dynamic),
    )


def req(ip="1.1.1.1", host="nothing.com", path="/", ua="mozilla", method="GET", cookies=None):
    return RequestInfo(
        client_ip=ip, requested_host=host, requested_path=path,
        client_user_agent=ua, method=method, cookies=cookies or {},
    )


def solved_sha_cookie(config, binding):
    fresh = new_challenge_cookie(config.hmac_secret, 100, binding)
    return solve_challenge_for_testing(fresh, 10)


def solved_password_cookie(config, binding, password=b"password"):
    fresh = new_challenge_cookie(config.hmac_secret, 100, binding)
    hmac_b, _, expiry = parse_cookie(fresh)
    solution = hashlib.sha256(hmac_b + hashlib.sha256(password).digest()).digest()
    return base64.standard_b64encode(hmac_b + solution + expiry).decode()


# ---- default allow ----

def test_no_mention_access_granted(state):
    resp, result = decision_for_nginx(state, req())
    assert resp.status == 200
    assert resp.headers["X-Accel-Redirect"] == "@access_granted"
    assert resp.headers["X-Banjax-Decision"] == "NoMention"
    assert result.decision_list_result is DecisionListResult.NO_MENTION
    # session cookie issued on every response
    assert resp.headers["X-Deflect-Session-New"] == "true"
    assert any(c.name == "deflect_session" for c in resp.cookies)


# ---- static IP lists ----

def test_global_allow(state):
    resp, result = decision_for_nginx(state, req(ip="20.20.20.20"))
    assert resp.status == 200
    assert result.decision_list_result is DecisionListResult.GLOBAL_ACCESS_GRANTED


def test_global_block(state):
    resp, result = decision_for_nginx(state, req(ip="70.80.90.100"))
    assert resp.status == 403
    assert resp.headers["X-Accel-Redirect"] == "@access_denied"
    assert result.decision_list_result is DecisionListResult.GLOBAL_BLOCK


def test_global_challenge_serves_page(state):
    resp, result = decision_for_nginx(state, req(ip="8.8.8.8"))
    assert resp.status == 429
    assert result.decision_list_result is DecisionListResult.GLOBAL_CHALLENGE
    assert b"new_solver(10)" in resp.body  # config difficulty == page default
    assert b"max-age=14400" in resp.body  # rewrite applied
    assert any(c.name == "deflect_challenge3" for c in resp.cookies)


def test_global_challenge_passes_with_solved_cookie(state):
    cookie = solved_sha_cookie(state.config, "8.8.8.8")
    resp, result = decision_for_nginx(
        state, req(ip="8.8.8.8", cookies={"deflect_challenge3": cookie})
    )
    assert resp.status == 200
    assert resp.headers["X-Banjax-Decision"] == "ShaChallengePassed"
    # integrity bot-score headers are emitted on sha-challenge outcomes
    assert resp.headers["X-Banjax-Bot-Score"] == "1.000000"
    assert resp.headers["X-Banjax-Bot-Score-Top-Factor"] == "no_payload"


def test_per_site_beats_global(state):
    # 90.90.90.90 allowed on example.com even though not in global
    resp, result = decision_for_nginx(state, req(ip="90.90.90.90", host="example.com"))
    assert result.decision_list_result is DecisionListResult.PER_SITE_ACCESS_GRANTED
    resp, result = decision_for_nginx(state, req(ip="92.92.92.92", host="example.com"))
    assert result.decision_list_result is DecisionListResult.PER_SITE_BLOCK


# ---- UA lists ----

def test_per_site_ua_allow_overrides_global_ip_challenge(state):
    # reference integration case (banjax_integration_test.go:409-463):
    # per-site IP list is checked BEFORE per-site UA... but a per-site UA
    # allow fires before the GLOBAL IP challenge
    resp, result = decision_for_nginx(
        state, req(ip="8.8.8.8", host="example.com", ua="GoodBot/1.0")
    )
    assert result.decision_list_result is DecisionListResult.PER_SITE_UA_ACCESS_GRANTED


def test_global_ip_challenge_fires_before_global_ua_block(state):
    resp, result = decision_for_nginx(state, req(ip="8.8.8.8", ua="BadBot/1.0"))
    assert result.decision_list_result is DecisionListResult.GLOBAL_CHALLENGE


def test_global_ua_block(state):
    resp, result = decision_for_nginx(state, req(ua="BadBot/1.0"))
    assert resp.status == 403
    assert result.decision_list_result is DecisionListResult.GLOBAL_UA_BLOCK


# ---- password-protected paths ----

def test_password_protected_path_serves_password_page(state):
    resp, result = decision_for_nginx(state, req(host="example.com", path="/wp-admin/x"))
    assert resp.status == 401
    assert result.decision_list_result is DecisionListResult.PASSWORD_PROTECTED_PATH
    assert any(c.name == "deflect_password3" for c in resp.cookies)
    assert b"deflect_password3" in resp.body


def test_password_protected_exception_passes(state):
    resp, result = decision_for_nginx(
        state, req(host="example.com", path="/wp-admin/admin-ajax.php")
    )
    assert resp.status == 200
    assert result.decision_list_result is DecisionListResult.PASSWORD_PROTECTED_PATH_EXCEPTION


def test_password_cookie_priority_pass(state):
    # a valid password cookie passes even on a non-protected path/challenge IP
    cookie = solved_password_cookie(state.config, "8.8.8.8")
    resp, result = decision_for_nginx(
        state, req(ip="8.8.8.8", host="example.com", cookies={"deflect_password3": cookie})
    )
    assert resp.status == 200
    assert result.decision_list_result is DecisionListResult.PASSWORD_PROTECTED_PRIORITY_PASS


def test_password_challenge_passes_with_valid_cookie(state):
    cookie = solved_password_cookie(state.config, "5.5.5.5")
    resp, result = decision_for_nginx(
        state,
        req(ip="5.5.5.5", host="example.com", path="/wp-admin/x",
            cookies={"deflect_password3": cookie}),
    )
    assert resp.status == 200
    assert resp.headers["X-Banjax-Decision"] == "PasswordProtectedPriorityPass"


# ---- failed-challenge lockout (401,401,...,403) ----

def test_too_many_failed_password_challenges_blocks(state):
    # threshold=2: two fails are 401s, the third (hits=3 > 2) bans
    r = req(ip="6.6.6.6", host="example.com", path="/wp-admin/x",
            cookies={"deflect_password3": "garbage"})
    statuses = []
    for _ in range(3):
        resp, result = decision_for_nginx(state, r)
        statuses.append(resp.status)
    assert statuses == [401, 401, 403]
    banner = state.banner
    assert banner.bans and banner.bans[0].ip == "6.6.6.6"
    assert banner.bans[0].decision is Decision.IPTABLES_BLOCK
    assert banner.failed_challenge_ban_logs[0] == ("6.6.6.6", "password")


def test_allowlisted_ip_gets_nginx_block_not_iptables(state):
    # per-site allow → failed challenges escalate to NginxBlock instead
    r = req(ip="90.90.90.90", host="example.com", path="/wp-admin/x",
            cookies={"deflect_password3": "garbage"})
    for _ in range(3):
        resp, _ = decision_for_nginx(state, r)
    assert state.banner.bans[0].decision is Decision.NGINX_BLOCK


# ---- expiring (dynamic) lists ----

def test_expiring_challenge_and_path_exception(state):
    state.dynamic_lists.update(
        "3.3.3.3", time.time() + 60, Decision.CHALLENGE, False, "example.com"
    )
    resp, result = decision_for_nginx(state, req(ip="3.3.3.3", host="example.com"))
    assert resp.status == 429
    assert result.decision_list_result is DecisionListResult.EXPIRING_CHALLENGE

    # sha_inv_path_exceptions passes straight through
    resp, result = decision_for_nginx(
        state, req(ip="3.3.3.3", host="example.com", path="/no_challenge/x")
    )
    assert resp.status == 200
    assert result.decision_list_result is DecisionListResult.PER_SITE_SHA_INV_PATH_EXCEPTION


def test_expiring_block(state):
    state.dynamic_lists.update(
        "4.4.4.4", time.time() + 60, Decision.NGINX_BLOCK, False, "x.com"
    )
    resp, result = decision_for_nginx(state, req(ip="4.4.4.4"))
    assert resp.status == 403
    assert result.decision_list_result is DecisionListResult.EXPIRING_BLOCK


def test_baskerville_disabled_falls_through(state):
    # baskerville-sourced block on a disabled site falls through to allow
    state.dynamic_lists.update(
        "5.5.5.5", time.time() + 60, Decision.NGINX_BLOCK, True, "nobask.com"
    )
    resp, result = decision_for_nginx(state, req(ip="5.5.5.5", host="nobask.com"))
    assert resp.status == 200
    assert result.decision_list_result is DecisionListResult.NO_MENTION

    # but a non-baskerville block still blocks there
    state.dynamic_lists.update(
        "5.5.5.5", time.time() + 60, Decision.IPTABLES_BLOCK, False, "nobask.com"
    )
    resp, result = decision_for_nginx(state, req(ip="5.5.5.5", host="nobask.com"))
    assert resp.status == 403


def test_session_id_decision_applies(state):
    from banjax_tpu.crypto.session import new_session_cookie
    sess = new_session_cookie(
        state.config.session_cookie_hmac_secret, 3600, "7.7.7.7"
    )
    state.dynamic_lists.update_by_session_id(
        "7.7.7.7", sess, time.time() + 60, Decision.NGINX_BLOCK, True, "x.com"
    )
    resp, result = decision_for_nginx(
        state, req(ip="7.7.7.7", cookies={"deflect_session": sess})
    )
    assert resp.status == 403
    assert result.decision_list_result is DecisionListResult.EXPIRING_BLOCK


# ---- sitewide SHA-inv ----

def test_sitewide_sha_inv_challenges(state):
    resp, result = decision_for_nginx(state, req(host="shainv.com"))
    assert resp.status == 429
    assert result.decision_list_result is DecisionListResult.SITE_WIDE_CHALLENGE


def test_sitewide_no_block_keeps_challenging_on_failure(state):
    # no_block fail action: failures never escalate to a ban
    r = req(ip="9.9.9.9", host="noblock.com", cookies={"deflect_challenge3": "garbage"})
    for _ in range(5):
        resp, _ = decision_for_nginx(state, r)
        assert resp.status == 429
    assert state.banner.bans == []


def test_sitewide_sha_inv_exception_via_password_exceptions(state):
    cfg_yaml = CONFIG_YAML.replace(
        'sitewide_sha_inv_list:\n  shainv.com: block',
        'sitewide_sha_inv_list:\n  shainv.com: block\n  example.com: block',
    )
    config = load_config(cfg_yaml)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    st = ChainState(
        config=config,
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(dynamic),
    )
    resp, result = decision_for_nginx(
        st, req(host="example.com", path="/wp-admin/admin-ajax.php")
    )
    # exception path: classified as PasswordProtectedPathException first
    assert resp.status == 200


def test_sha_challenge_solved_for_sitewide(state):
    cookie = solved_sha_cookie(state.config, "2.2.2.2")
    resp, result = decision_for_nginx(
        state, req(ip="2.2.2.2", host="shainv.com", cookies={"deflect_challenge3": cookie})
    )
    assert resp.status == 200
    assert resp.headers["X-Banjax-Decision"] == "ShaChallengePassed"


# ---- use_user_agent_in_cookie binding ----

def test_ua_bound_cookie():
    config = load_config(
        CONFIG_YAML + "\nuse_user_agent_in_cookie:\n  'uabound.com': true\n"
    )
    dynamic = DynamicDecisionLists(start_sweeper=False)
    st = ChainState(
        config=config,
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(dynamic),
    )
    # cookie bound to the UA, not the IP: solving with UA binding passes even
    # if the IP changes
    fresh = new_challenge_cookie(config.hmac_secret, 100, "special-agent")
    cookie = solve_challenge_for_testing(fresh, 10)
    dynamic.update("1.2.3.4", time.time() + 60, Decision.CHALLENGE, False, "uabound.com")
    dynamic.update("5.6.7.8", time.time() + 60, Decision.CHALLENGE, False, "uabound.com")
    for ip in ("1.2.3.4", "5.6.7.8"):
        resp, _ = decision_for_nginx(
            st,
            req(ip=ip, host="uabound.com", ua="special-agent",
                cookies={"deflect_challenge3": cookie}),
        )
        assert resp.status == 200
