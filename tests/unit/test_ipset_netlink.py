"""Netlink ipset wire format + batch writer hardening — no root needed.

The encoders are pure bytes-in/bytes-out, golden-tested against a
hand-decoded AF_NETLINK / NFNL_SUBSYS_IPSET frame (nlmsghdr + nfgenmsg
+ the nested attribute tree `ipset add` emits).  The IpsetBatchWriter
tests drive the queue/flush machinery against a fake netlink socket and
a recording fallback shim, pinning the hardening contract: enqueue
never blocks or raises, overflow sheds the OLDEST entries (counted),
any netlink failure falls back losslessly to per-entry subprocess adds,
and the breaker routes around a broken netlink instead of paying a
failed syscall per batch.
"""

import struct
import threading
import time

import pytest

from banjax_tpu.effectors import ipset_netlink as nl
from banjax_tpu.effectors.ipset_stats import get_stats
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CircuitBreaker

# `ipset add banjax 1.2.3.4 timeout 300`, seq 7 — decoded by hand:
#   nlmsghdr  40000000 len=64 | 0906 type=(NFNL_SUBSYS_IPSET<<8)|ADD
#             | 0500 REQUEST|ACK | seq=7 | pid=0
#   nfgenmsg  02 AF_INET | 00 v0 | 0000 res_id
#   NLA PROTOCOL(1)=6, SETNAME(2)="banjax\0",
#   NLA DATA(7|NESTED){ IP(1|NESTED){ IPADDR_IPV4|NET_BYTEORDER 01020304 },
#                       TIMEOUT(6)|NET_BYTEORDER >I 300 }
GOLDEN_ADD = bytes.fromhex(
    "400000000906050007000000000000000200000005000100060000000b000200"
    "62616e6a61780000180007800c0001800800014001020304080006400000012c"
)


@pytest.fixture(autouse=True)
def _clean():
    get_stats().reset()
    failpoints.disarm()
    yield
    failpoints.disarm()
    get_stats().reset()


def test_encode_ipset_add_golden_frame():
    assert nl.encode_ipset_add("banjax", "1.2.3.4", 300, seq=7) == GOLDEN_ADD


def test_encode_ipset_add_fields_move_with_inputs():
    frame = nl.encode_ipset_add("banjax", "10.20.30.40", 60, seq=9)
    length, msg_type, flags, seq, pid = struct.unpack_from("=IHHII", frame, 0)
    assert length == len(frame) == len(GOLDEN_ADD)  # same set name length
    assert msg_type == (nl.NFNL_SUBSYS_IPSET << 8) | nl.IPSET_CMD_ADD
    assert flags == nl.NLM_F_REQUEST | nl.NLM_F_ACK
    assert (seq, pid) == (9, 0)
    assert bytes([10, 20, 30, 40]) in frame
    assert struct.pack(">I", 60) in frame
    # set name is NUL-terminated inside its attribute
    assert b"banjax\x00" in frame

    with pytest.raises(OSError):
        nl.encode_ipset_add("banjax", "::1", 60, seq=1)  # inet set: IPv4 only
    with pytest.raises(OSError):
        nl.encode_ipset_add("banjax", "not-an-ip", 60, seq=1)


def test_encode_batch_concatenates_and_routes_non_ipv4():
    buf, skipped = nl.encode_batch(
        "banjax",
        [("1.2.3.4", 300), ("::1", 60), ("garbage", 60), ("1.2.3.4", 300)],
        seq_start=7,
    )
    assert skipped == ["::1", "garbage"]
    assert buf[: len(GOLDEN_ADD)] == GOLDEN_ADD
    # second encodable entry got the NEXT sequence number (7, then 8)
    second = buf[len(GOLDEN_ADD):]
    assert struct.unpack_from("=IHHII", second, 0)[3] == 8
    assert nl.encode_batch("s", [], 1) == (b"", [])


def _ack(err: int, seq: int = 1) -> bytes:
    return struct.pack("=IHHII", 20, nl.NLMSG_ERROR, 0, seq, 0) + struct.pack(
        "=i", err
    )


def test_parse_acks():
    buf = _ack(0, 1) + _ack(-17, 2) + _ack(0, 3)
    assert nl.parse_acks(buf) == [0, -17, 0]
    # non-error messages are skipped; truncated tails don't raise
    other = struct.pack("=IHHII", 16, 0x42, 0, 9, 0)
    assert nl.parse_acks(other + _ack(0, 1)) == [0]
    assert nl.parse_acks(buf[:-7]) == [0, -17]
    assert nl.parse_acks(b"") == []
    assert nl.parse_acks(struct.pack("=IHHII", 2, 0, 0, 0, 0)) == []


# ------------------------------------------------------------- writer


class FakeSock:
    """Stands in for the AF_NETLINK socket: records sends, acks every
    message in the buffer (or fails, per `fail`)."""

    def __init__(self, fail=False, nack=0):
        self.sent = []
        self.fail = fail
        self.nack = nack  # how many entries to NACK per batch

    def send(self, buf):
        if self.fail:
            raise OSError(1, "EPERM")
        self.sent.append(buf)

    def recv(self, _n):
        n_msgs = sum(1 for _ in _iter_msgs(self.sent[-1]))
        out = b""
        for i in range(n_msgs):
            out += _ack(-17 if i < self.nack else 0, i + 1)
        return out

    def close(self):
        pass


def _iter_msgs(buf):
    off = 0
    while off + 16 <= len(buf):
        (length,) = struct.unpack_from("=I", buf, off)
        yield off
        off += (length + 3) & ~3


class FakeIpset:
    """The subprocess shim stand-in: records per-entry fallback adds."""

    name = "banjax"

    def __init__(self, fail=False):
        self.added = []
        self.fail = fail

    def add(self, ip, timeout):
        if self.fail:
            raise RuntimeError("ipset binary missing")
        self.added.append((ip, timeout))


def _writer(ipset, sock, **kw):
    kw.setdefault("flush_interval", 0.01)
    w = nl.IpsetBatchWriter(ipset, **kw)
    w._socket = lambda: sock
    return w


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.01)
    assert pred(), "condition not reached"


def test_batched_sends_coalesce_and_count():
    ipset, sock = FakeIpset(), FakeSock()
    w = _writer(ipset, sock)
    try:
        for i in range(10):
            w.enqueue(f"10.0.0.{i}", 300)
        _wait(lambda: get_stats().prom_snapshot()["batch_entries_total"] == 10)
        snap = get_stats().prom_snapshot()
        # coalesced: far fewer sendmsg calls than entries
        assert snap["batch_sends_total"] <= len(sock.sent) <= 10
        assert snap["batch_sends_total"] >= 1
        assert snap["errors_total"] == 0
        assert ipset.added == []  # nothing fell back
        assert w.queue_depth() == 0
    finally:
        w.close()


def test_netlink_failure_falls_back_losslessly():
    ipset, sock = FakeIpset(), FakeSock(fail=True)
    w = _writer(ipset, sock)
    try:
        w.enqueue("10.0.0.1", 300)
        w.enqueue("10.0.0.2", 60)
        _wait(lambda: len(ipset.added) == 2)
        assert sorted(ipset.added) == [("10.0.0.1", 300), ("10.0.0.2", 60)]
        snap = get_stats().prom_snapshot()
        assert snap["errors"].get("netlink", 0) >= 1
        assert snap["fallback_total"] == 2
        assert snap["batch_sends_total"] == 0
    finally:
        w.close()


def test_per_entry_nack_reroutes_batch():
    """A kernel NACK on any entry re-routes the whole batch through the
    idempotent subprocess path — double-applying acked adds is harmless,
    losing the NACKed one is not."""
    ipset, sock = FakeIpset(), FakeSock(nack=1)
    w = _writer(ipset, sock)
    try:
        w.enqueue("10.0.0.1", 300)
        w.enqueue("10.0.0.2", 300)
        _wait(lambda: len(ipset.added) == 2)
        snap = get_stats().prom_snapshot()
        assert snap["errors"].get("netlink", 0) == 1
        assert snap["fallback_total"] == 2
    finally:
        w.close()


def test_non_ipv4_rides_fallback_even_on_healthy_netlink():
    ipset, sock = FakeIpset(), FakeSock()
    w = _writer(ipset, sock)
    try:
        w.enqueue("10.0.0.1", 300)
        w.enqueue("2001:db8::1", 300)
        _wait(lambda: len(ipset.added) == 1)
        assert ipset.added == [("2001:db8::1", 300)]
        _wait(lambda: get_stats().prom_snapshot()["batch_entries_total"] == 1)
    finally:
        w.close()


def test_open_breaker_routes_straight_to_subprocess():
    ipset, sock = FakeIpset(), FakeSock(fail=True)
    breaker = CircuitBreaker(failure_threshold=1, recovery_seconds=3600.0,
                             name="t-ipset")
    w = _writer(ipset, sock, breaker=breaker)
    try:
        w.enqueue("10.0.0.1", 300)
        _wait(lambda: len(ipset.added) == 1)
        assert not breaker.allow()
        sends_before = len(sock.sent)
        netlink_errors = get_stats().prom_snapshot()["errors"].get("netlink", 0)
        w.enqueue("10.0.0.2", 300)
        _wait(lambda: len(ipset.added) == 2)
        # breaker open: no new netlink attempt, no new netlink error
        assert len(sock.sent) == sends_before
        assert get_stats().prom_snapshot()["errors"].get(
            "netlink", 0
        ) == netlink_errors
    finally:
        w.close()


def test_overflow_sheds_oldest_never_blocks():
    ipset, sock = FakeIpset(), FakeSock()
    # a long flush interval keeps the drain thread asleep while we flood
    w = _writer(ipset, sock, max_queue=4, flush_interval=30.0)
    try:
        for i in range(10):
            w.enqueue(f"10.0.0.{i}", 300)  # returns immediately, never raises
        assert w.queue_depth() == 4
        assert get_stats().prom_snapshot()["queue_shed_total"] == 6
        with w._lock:
            kept = [ip for ip, _ in w._queue]
        assert kept == ["10.0.0.6", "10.0.0.7", "10.0.0.8", "10.0.0.9"]
    finally:
        w.close()  # final drain flushes the survivors
    assert get_stats().prom_snapshot()["batch_entries_total"] == 4


def test_subprocess_fallback_failure_counted_never_raised():
    ipset, sock = FakeIpset(fail=True), FakeSock(fail=True)
    w = _writer(ipset, sock)
    try:
        w.enqueue("10.0.0.1", 300)
        _wait(lambda: get_stats().prom_snapshot()["errors"].get(
            "subprocess", 0) == 1)
        snap = get_stats().prom_snapshot()
        assert snap["errors"].get("netlink", 0) >= 1
    finally:
        w.close()


def test_queue_depth_gauge_wired_to_stats():
    ipset, sock = FakeIpset(), FakeSock()
    w = _writer(ipset, sock, max_queue=8, flush_interval=30.0)
    try:
        for i in range(3):
            w.enqueue(f"10.0.0.{i}", 300)
        assert get_stats().prom_snapshot()["queue_depth"] == 3
    finally:
        w.close()
    assert get_stats().prom_snapshot()["queue_depth"] == 0


def test_close_drains_queue():
    ipset, sock = FakeIpset(), FakeSock()
    w = _writer(ipset, sock, flush_interval=30.0)
    for i in range(5):
        w.enqueue(f"10.0.0.{i}", 300)
    w.close()
    assert get_stats().prom_snapshot()["batch_entries_total"] == 5
    assert not w._thread.is_alive()


def test_enqueue_concurrent_producers():
    ipset, sock = FakeIpset(), FakeSock()
    w = _writer(ipset, sock, max_queue=10_000)
    try:
        def produce(base):
            for i in range(200):
                w.enqueue(f"10.{base}.{i // 250}.{i % 250}", 60)

        threads = [threading.Thread(target=produce, args=(b,))
                   for b in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        _wait(lambda: get_stats().prom_snapshot()["batch_entries_total"]
              == 800)
        assert get_stats().prom_snapshot()["queue_shed_total"] == 0
    finally:
        w.close()
