"""The bench artifact machinery (bench.py supervisor/worker persistence).

BENCH_r{N}.json is the round's evidence of record; these tests pin the
rules that keep it honest: per-section best-evidence persistence (a CPU
rerun never clobbers TPU data; TPU overwrites TPU), workload-fingerprint
invalidation, and supervisor composition (provenance labels, headline
selection, tpu-if-any-tpu backend)."""

import importlib
import json

import pytest

import bench


@pytest.fixture()
def partial(tmp_path, monkeypatch):
    path = tmp_path / "BENCH_partial.json"
    monkeypatch.setattr(bench, "PARTIAL_PATH", str(path))
    return path


def test_save_section_best_evidence(partial):
    bench._save_section("fused", "tpu", {"fused_pipelined_lines_per_sec": 2.5e6})
    # cpu must NOT clobber tpu
    bench._save_section("fused", "cpu", {"fused_pipelined_lines_per_sec": 9e3})
    p = bench._load_partial()
    assert p["sections"]["fused"]["backend"] == "tpu"
    assert p["sections"]["fused"]["data"]["fused_pipelined_lines_per_sec"] == 2.5e6
    # tpu overwrites tpu (newer code wins)
    bench._save_section("fused", "tpu", {"fused_pipelined_lines_per_sec": 3e6})
    p = bench._load_partial()
    assert p["sections"]["fused"]["data"]["fused_pipelined_lines_per_sec"] == 3e6
    # cpu overwrites cpu
    bench._save_section("e2e", "cpu", {"e2e_lines_per_sec": 1.0})
    bench._save_section("e2e", "cpu", {"e2e_lines_per_sec": 2.0})
    assert bench._load_partial()["sections"]["e2e"]["data"][
        "e2e_lines_per_sec"] == 2.0


def test_workload_fingerprint_discards_stale_sections(partial):
    stale = {
        "workload": {"n_rules": 7, "max_len": 1, "rule_seed": 0},
        "sections": {"fused": {"backend": "tpu", "measured_at": "x",
                               "data": {"fused_pipelined_lines_per_sec": 1}}},
    }
    partial.write_text(json.dumps(stale))
    assert bench._load_partial()["sections"] == {}


def test_compose_provenance_and_headline(partial):
    bench._save_section(
        "single_stage", "tpu",
        {"pallas_lines_per_sec": 900_000.0, "xla_lines_per_sec": 70_000.0},
    )
    bench._save_section(
        "fused", "tpu",
        {"fused_device_resident_lines_per_sec": 4_000_000.0,
         "fused_pipelined_lines_per_sec": 2_000_000.0,
         "fused_device_resident_latency_ms": 16.0},
    )
    bench._save_section("e2e", "cpu", {"e2e_lines_per_sec": 14_000.0})
    out = bench._compose(
        bench._load_partial(), live_sections={"e2e"},
        probe="cpu", probe_err="probe timeout",
    )
    # any tpu section ⇒ the artifact says tpu, with the probe recorded
    assert out["backend"] == "tpu"
    assert out["final_probe_backend"] == "cpu"
    assert out["backend_error"] == "probe timeout"
    # headline = best device number; vs_baseline against the 5M target
    assert out["value"] == 4_000_000.0
    assert out["vs_baseline"] == round(4_000_000.0 / 5_000_000.0, 4)
    assert out["batch_latency_ms"] == 16.0
    # sections NOT run by the live worker are labeled
    assert sorted(out["merged_from_partial"]) == ["fused", "single_stage"]
    prov = out["section_provenance"]
    assert prov["fused"]["backend"] == "tpu"
    assert prov["e2e"]["backend"] == "cpu"


def test_compose_all_cpu_stays_cpu(partial):
    bench._save_section("single_stage", "cpu", {"xla_lines_per_sec": 2e3})
    out = bench._compose(
        bench._load_partial(), live_sections={"single_stage"},
        probe="cpu", probe_err=None,
    )
    assert out["backend"] == "cpu"
    assert "merged_from_partial" not in out
    assert out["value"] == 2e3


def test_corrupt_partial_resets_cleanly(partial):
    partial.write_text("{not json")
    p = bench._load_partial()
    assert p["sections"] == {} and p["workload"] == bench.WORKLOAD
