"""Literal prefilter: factor soundness + two-stage bitmap equivalence.

The invariant under test (matcher/prefilter.py): for every pattern, every
match of a branch contains its required factor's classes consecutively, so
gating stage 2 on "any factor hit" never drops a true match — the two-stage
bitmap equals the single-stage one bit for bit.
"""

import random
import re

import numpy as np
import pytest

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.prefilter import PrefilterMatcher, build_plan
from banjax_tpu.matcher.rulec import (
    compile_rule,
    compile_rules,
    required_factors,
)


def factor_to_str(factor):
    """Pick one concrete byte per class (for eyeballing/containment checks)."""
    return "".join(chr(min(b for b in range(256) if (p.cs >> b) & 1))
                   for p in factor)


class TestRequiredFactors:
    def test_plain_literal(self):
        f = required_factors(compile_rule(r"GET /wp-login\.php"))
        assert f is not None and len(f) == 1
        assert factor_to_str(f[0]) in "GET /wp-login.php"

    def test_alternation_has_factor_per_branch(self):
        f = required_factors(compile_rule(r"(GET|POST) /xmlrpc\.php"))
        assert f is not None and len(f) == 2

    def test_runs_break_at_selfloop(self):
        # `admin[a-z]+panel` — the + position may repeat, so no factor may
        # span it; both sides are valid factors though
        f = required_factors(compile_rule(r"admin[a-z]+panel"))
        assert f is not None
        assert factor_to_str(f[0]) in ("admin", "panel")

    def test_wide_class_blocks_factor(self):
        assert required_factors(compile_rule(r"[a-z]{8}")) is None
        assert required_factors(compile_rule(r"ab[0-9]cd")) is None  # runs of 2

    def test_case_fold_pairs_allowed(self):
        f = required_factors(compile_rule(r"(?i)sqlmap"))
        assert f is not None
        assert factor_to_str(f[0]).lower() in "sqlmap"

    def test_always_match_rule_has_no_factor(self):
        assert required_factors(compile_rule(r".*")) is None

    def test_truncation_keeps_middle(self):
        f = required_factors(compile_rule("a" * 30), max_len=8)
        assert f is not None and len(f[0]) == 8

    def test_factor_is_contained_in_random_matches(self):
        """Generative soundness: synthesize matches, assert factor presence."""
        rng = random.Random(5)
        patterns = [
            r"GET /admin/[a-z]+\.php", r"(?i)nikto|nessus",
            r"POST /login[0-9]{1,3}", r"^HEAD /x\.cgi$",
        ]
        for pat in patterns:
            prog = compile_rule(pat)
            factors = required_factors(prog)
            assert factors is not None, pat
            for br, factor in zip(prog.branches, factors):
                # synthesize a concrete match for this branch
                s = ""
                for p in br.positions:
                    b = min(b for b in range(256) if (p.cs >> b) & 1)
                    s += chr(b) * (1 + (2 if p.loop and rng.random() < 0.5 else 0))
                assert re.search(pat, s), (pat, s)
                # the factor's classes must appear consecutively somewhere
                ok = any(
                    all((factor[j].cs >> ord(s[k + j])) & 1 for j in range(len(factor)))
                    for k in range(len(s) - len(factor) + 1)
                )
                assert ok, (pat, s, factor_to_str(factor))


class TestTwoStageEquivalence:
    def _bench_rules_and_lines(self, n_rules=60, n_lines=500, seed=9):
        import bench

        patterns = bench.generate_rules(n_rules, seed=seed)
        lines = bench.generate_lines(n_lines, patterns, seed=seed + 1,
                                     attack_rate=0.3)
        return patterns, lines

    def test_plan_builds_for_crs_shaped_rules(self):
        patterns, _ = self._bench_rules_and_lines()
        plan = build_plan(patterns)
        assert plan is not None
        assert plan.stage1.n_words < plan.stage2.n_words
        # stage 1 packs word-aligned so the kernel drops the cross-word
        # carry; factors are <= 12 positions so this must always hold
        assert plan.stage1.carry_free
        assert plan.n_always + len(plan.f_idx) == len(
            [p for i, p in enumerate(patterns) if i not in plan.unsupported]
        )

    @pytest.mark.parametrize("backend", ["xla", "pallas-interpret"])
    def test_bitmap_equals_single_stage(self, backend):
        patterns, lines = self._bench_rules_and_lines()
        plan = build_plan(patterns)
        assert plan is not None
        pf = PrefilterMatcher(plan, backend, max_len=128, max_batch=256)
        bits, host_eval = pf.match_bits(lines)
        assert not host_eval.any()

        compiled = compile_rules(patterns)
        params = nfa_jax.match_params(compiled)
        cls_ids, lens, he = encode_for_match(compiled, lines, 128)
        want = np.asarray(
            nfa_jax.match_batch(params, cls_ids, lens, compiled.n_rules)
        )
        for rid in plan.unsupported:
            want[:, rid] = 0  # host-fallback columns are zero in both paths
        assert (bits == want).all()

    def test_default_rule_lands_in_always_group(self):
        patterns = [r".*", r"GET /wp-login\.php", r"POST /xmlrpc\.php",
                    r"/\.env", r"(?i)sqlmap"]
        plan = build_plan(patterns, min_filterable_fraction=0.5)
        assert plan is not None
        assert 0 in set(plan.a_idx)
        bits, _ = PrefilterMatcher(plan, "xla", max_len=64).match_bits(
            ["GET x.com GET / HTTP/1.1"]
        )
        assert bits[0, 0] == 1  # .* matches everything, no factor needed

    def test_unprofitable_ruleset_returns_none(self):
        assert build_plan([r".*", r"[a-z]+", r"\d+"]) is None


def _shared_plan(patterns, **plan_kw):
    """compiled + plan with shared byte classes (FusedPrefilter contract)."""
    compiled = compile_rules(patterns, n_shards="auto")
    plan = build_plan(
        patterns,
        byte_classes=(compiled.byte_to_class, compiled.n_classes),
        **plan_kw,
    )
    return compiled, plan


def _single_stage_oracle(compiled, plan, lines, max_len=128):
    """(cls_ids, lens, host_eval, want-bitmap) with unsupported columns
    zeroed — the invariant every fused path must reproduce."""
    params = nfa_jax.match_params(compiled)
    cls_ids, lens, he = encode_for_match(compiled, lines, max_len)
    want = np.asarray(
        nfa_jax.match_batch(params, cls_ids, lens, compiled.n_rules)
    )
    for rid in plan.unsupported:
        want[:, rid] = 0
    return cls_ids, lens, he, want


class TestFusedFuzz:
    """Generative soundness sweep: random RE2-subset rulesets and random
    line streams through FusedPrefilter vs the single-stage oracle. Catches
    factor-extraction unsoundness (a factor that is not actually required
    would silently drop matches) across pattern shapes no hand-written
    case enumerates."""

    @pytest.mark.parametrize("seed", [1, 2, 3])
    def test_random_rulesets(self, seed):
        from banjax_tpu.matcher.prefilter import FusedPrefilter

        rng = random.Random(seed * 7919)
        words = ["wp", "admin", "login", "env", "cgi", "bak", "shell", "sql"]

        def gen_pattern():
            kind = rng.random()
            w1, w2 = rng.choice(words), rng.choice(words)
            if kind < 0.3:
                return rf"GET /{w1}/{w2}\.php"
            if kind < 0.5:
                return rf"({w1.upper()}|{w2}) /[a-z0-9]+/{w1}"
            if kind < 0.65:
                return rf"(?i){w1}{w2}[0-9]{{1,3}}"
            if kind < 0.75:
                return rf"^{w1} .*{w2}$"
            if kind < 0.85:
                return rf"/{w1}\.(php|asp|jsp)\?x={rng.randint(0, 9)}"
            if kind < 0.95:
                return rf"{w1}[a-z]*{w2}+"
            return rng.choice([r".*", rf"[a-z]{{{rng.randint(2, 6)}}}"])

        patterns = [gen_pattern() for _ in range(40)]
        compiled, plan = _shared_plan(patterns, min_filterable_fraction=0.1)
        if plan is None:
            pytest.skip("ruleset draw not filterable")

        # line stream: benign noise + substrings assembled from the same
        # vocabulary (maximizes near-miss factor hits)
        lines = []
        for _ in range(300):
            n = rng.randint(0, 5)
            parts = [rng.choice(words + ["GET", "/", ".php", "xyz", "123"])
                     for _ in range(n)]
            sep = rng.choice(["", " ", "/"])
            lines.append(sep.join(parts))
        cls_ids, lens, _, want = _single_stage_oracle(
            compiled, plan, lines, max_len=96
        )
        fp = FusedPrefilter(plan, "xla", cand_frac=1.0, pair_frac=1.0)
        got = fp.match_bits_encoded(cls_ids, lens)
        np.testing.assert_array_equal(got, want)
        # oracle the oracle: spot-check against Python re
        import re as _re

        for j in rng.sample(range(len(patterns)), 8):
            if j in plan.unsupported or not compiled.device_ok[j]:
                continue
            rx = _re.compile(patterns[j])
            for i in rng.sample(range(len(lines)), 20):
                if lens[i] < len(lines[i]):  # over-length: host path
                    continue
                assert bool(got[i, j]) == bool(rx.search(lines[i])), (
                    patterns[j], lines[i]
                )


class TestFusedPrefilter:
    """The single-device-call two-stage pipeline (FusedPrefilter): shared
    byte classes, on-device gate/compaction, sparse matched-row output."""

    def _plan(self, patterns):
        return _shared_plan(patterns)

    def _oracle(self, compiled, plan, lines, max_len=128):
        return _single_stage_oracle(compiled, plan, lines, max_len)

    @pytest.mark.parametrize("backend", ["xla", "pallas-interpret"])
    def test_parity_with_single_stage(self, backend):
        from banjax_tpu.matcher.prefilter import FusedPrefilter

        import bench

        patterns = bench.generate_rules(60, seed=9)
        lines = bench.generate_lines(300, patterns, seed=10, attack_rate=0.3)
        compiled, plan = self._plan(patterns)
        assert plan is not None
        cls_ids, lens, he, want = self._oracle(compiled, plan, lines)
        assert not he.any()
        fp = FusedPrefilter(plan, backend, cand_frac=1.0, pair_frac=1.0)
        bits = fp.match_bits_encoded(cls_ids, lens)
        np.testing.assert_array_equal(bits, want)

    def test_always_rules_and_empty_lines(self):
        from banjax_tpu.matcher.prefilter import FusedPrefilter

        patterns = [r".*", r"^$", r"GET /wp-login\.php", r"/xmlrpc\.php",
                    r"/\.env", r"(?i)sqlmap", r"POST /login[0-9]+"]
        lines = ["", "GET x.com GET /wp-login.php -", "plain benign line",
                 "POST a.b POST /login77 -", "SQLMAP probe"]
        compiled, plan = self._plan(patterns)
        assert plan is not None and plan.n_always >= 2
        cls_ids, lens, he, want = self._oracle(compiled, plan, lines, 64)
        fp = FusedPrefilter(plan, "xla")
        bits = fp.match_bits_encoded(cls_ids, lens)
        np.testing.assert_array_equal(bits, want)

    def test_unpacked_input_path_parity(self):
        """The plain-int32 input layout (used when a byte partition doesn't
        fit uint8) must match the packed default bit-for-bit."""
        from banjax_tpu.matcher.prefilter import FusedPrefilter

        import bench

        patterns = bench.generate_rules(30, seed=12)
        lines = bench.generate_lines(200, patterns, seed=13, attack_rate=0.2)
        compiled, plan = self._plan(patterns)
        assert plan is not None
        cls_ids, lens, _, want = self._oracle(compiled, plan, lines)
        fp = FusedPrefilter(plan, "xla", cand_frac=1.0, pair_frac=1.0)
        assert fp._pack_input  # packed is the default on LE hosts
        packed = fp.match_bits_encoded(cls_ids, lens)
        fp2 = FusedPrefilter(plan, "xla", cand_frac=1.0, pair_frac=1.0)
        fp2._pack_input = False
        unpacked = fp2.match_bits_encoded(cls_ids, lens)
        np.testing.assert_array_equal(packed, want)
        np.testing.assert_array_equal(unpacked, want)

    def test_overflow_raises(self):
        from banjax_tpu.matcher.prefilter import (
            FusedPrefilter,
            PrefilterOverflow,
        )

        patterns = [r"GET /wp-login\.php", r"/xmlrpc\.php", r"/\.env"]
        compiled, plan = self._plan(patterns)
        assert plan is not None
        # every line matches → matched rows exceed E = K/4
        lines = ["GET x GET /wp-login.php -"] * 256
        cls_ids, lens, _, _ = self._oracle(compiled, plan, lines, 64)
        fp = FusedPrefilter(plan, "xla", cand_frac=1.0)
        with pytest.raises(PrefilterOverflow):
            fp.match_bits_encoded(cls_ids, lens)

    def test_submit_collect_pipeline(self):
        from banjax_tpu.matcher.prefilter import FusedPrefilter

        import bench

        patterns = bench.generate_rules(40, seed=3)
        compiled, plan = self._plan(patterns)
        assert plan is not None
        fp = FusedPrefilter(plan, "xla", cand_frac=1.0, pair_frac=1.0)
        batches = [
            bench.generate_lines(100, patterns, seed=s, attack_rate=0.2)
            for s in (1, 2, 3)
        ]
        encoded = [self._oracle(compiled, plan, b) for b in batches]
        pending = [fp.submit(cls, lens) for cls, lens, _, _ in encoded]
        for p, (_, _, _, want) in zip(pending, encoded):
            np.testing.assert_array_equal(fp.collect(p), want)

    def test_runner_overflow_falls_back_single_stage(self):
        """TpuMatcher output is unchanged when the fused prefilter
        overflows (adversarial all-matching traffic)."""
        from banjax_tpu.config.schema import Config, RegexWithRate
        from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
        from banjax_tpu.decisions.static_lists import StaticDecisionLists
        from banjax_tpu.matcher.runner import TpuMatcher
        from tests.mock_banner import MockBanner

        rules = [
            RegexWithRate.from_yaml_dict(
                {"rule": f"r{i}", "regex": rx, "interval": 10,
                 "hits_per_interval": 10**6, "decision": "nginx_block"}
            )
            for i, rx in enumerate(
                [r"GET /wp-login\.php", r"/xmlrpc\.php", r"/\.env"]
            )
        ]
        now = 1700000000.0
        lines = [
            f"{now} 1.2.3.{i % 16} GET x.com GET /wp-login.php HTTP/1.1"
            for i in range(200)
        ]

        def run(prefilter):
            cfg = Config(
                regexes_with_rates=rules, matcher_backend="xla",
                matcher_prefilter=prefilter, matcher_batch_lines=256,
            )
            m = TpuMatcher(
                cfg, MockBanner(), StaticDecisionLists(cfg),
                RegexRateLimitStates(),
            )
            if prefilter and m._prefilter is not None:
                # force a tiny matched-row capacity so the batch overflows
                m._prefilter.cand_frac = 1.0 / 64
            return m.consume_lines(lines, now_unix=now)

        with_pf, without_pf = run(True), run(False)
        for a, b in zip(with_pf, without_pf):
            assert [r.rule_name for r in a.rule_results] == [
                r.rule_name for r in b.rule_results
            ]


class TestFactorMerging:
    """Teddy-style equal-length superimposition (prefilter._merge_factors)."""

    def _factors(self, pats):
        distinct = {}
        for pat in pats:
            fs = required_factors(compile_rule(pat))
            assert fs is not None, pat
            for f in fs:
                distinct.setdefault(tuple(p.cs for p in f), f)
        return list(distinct.values())

    def test_members_subset_of_bucket(self):
        """Every original factor maps into some bucket position-wise:
        same length, member class ⊆ merged class — the soundness
        precondition ("bucket missed ⟹ member absent")."""
        from banjax_tpu.matcher.prefilter import _merge_factors

        pats = [rf"GET /admin-{w}/x\.php" for w in
                ("alpha", "bravo", "civic", "delta", "eagle")]
        factors = _merge_factors(self._factors(pats), max_merge=8)
        originals = self._factors(pats)
        for f in originals:
            assert any(
                len(m) == len(f)
                and all(f[i].cs & ~m[i].cs == 0 for i in range(len(f)))
                for m in factors
            ), f
        # the five same-shape factors actually share buckets
        assert len(factors) < len(originals)

    def test_unequal_lengths_never_merge(self):
        from banjax_tpu.matcher.prefilter import _merge_factors

        factors = self._factors([r"abcdef", r"abcdefgh"])
        merged = _merge_factors(factors, max_merge=8)
        assert sorted(len(m) for m in merged) == sorted(
            len(f) for f in factors
        )

    def test_sel_budget_stops_wide_merges(self):
        """(?i) case-pair factors OR into wide classes; the sel guard must
        stop the bucket before it covers most of the alphabet."""
        from banjax_tpu.matcher.prefilter import _merge_factors, _pos_prob

        pats = [rf"(?i){a}{b}{c}scan" for a in "abcdef" for b in "klmnop"
                for c in "uvwxyz"]
        merged = _merge_factors(self._factors(pats), max_merge=64,
                                sel_max=1e-5)
        for m in merged:
            sel = 1.0
            for p in m:
                sel *= _pos_prob(p.cs)
            assert sel <= 1e-5

    def test_merge_disabled_is_identity(self):
        from banjax_tpu.matcher.prefilter import _merge_factors

        factors = self._factors([r"abcdef", r"uvwxyz"])
        assert _merge_factors(factors, max_merge=1) == factors

    def test_merged_plan_bitmap_still_exact(self):
        """End-to-end: an aggressively merged plan still produces the
        single-stage bitmap bit for bit (stage 2 pays for every stage-1
        false positive)."""
        patterns = (
            [rf"GET /admin-{w}/[a-z]+\.php" for w in
             ("alpha", "bravo", "civic", "delta")]
            + [rf"POST /login{d}[0-9]{{2}}" for d in range(4)]
            + [r"(?i)sqlmap|nikto"]
        )
        plan = build_plan(patterns, min_filterable_fraction=0.4,
                          factor_merge=64, factor_sel_max=1e-3)
        assert plan is not None
        import bench as _bench

        lines = _bench.generate_lines(512, patterns, seed=3,
                                      attack_rate=0.3)
        pf = PrefilterMatcher(plan, "xla", max_len=128, max_batch=256)
        bits, host_eval = pf.match_bits(lines)
        assert not host_eval.any()
        compiled = compile_rules(patterns)
        params = nfa_jax.match_params(compiled)
        cls_ids, lens, _ = encode_for_match(compiled, lines, 128)
        want = np.asarray(
            nfa_jax.match_batch(params, cls_ids, lens, compiled.n_rules)
        )
        for rid in plan.unsupported:
            want[:, rid] = 0
        assert (bits == want).all()
