"""SLO burn-rate engine (obs/slo.py): windowed burn math from synthetic
cumulative sources, breach transitions, and the on_breach hook — all
with an injected clock so windows advance deterministically."""

import pytest

from banjax_tpu.obs.registry import Histogram
from banjax_tpu.obs.slo import (
    SLO_BATCH_LATENCY,
    SLO_BREAKER_OPEN,
    SLO_BUDGET_TRIPS,
    SLO_SHED,
    SLO_STALE,
    SloEngine,
)
from banjax_tpu.obs.stats import PipelineStats


class FakeBreaker:
    def __init__(self):
        self.open_s = 0.0

    def open_seconds_total(self):
        return self.open_s


class FakeStats:
    def __init__(self):
        self.batch_latency_hist = Histogram()


class FakeMatcher:
    def __init__(self):
        self.stats = FakeStats()
        self.breaker = FakeBreaker()
        self.budget_trips = 0


class FakePipeline:
    def __init__(self):
        self.stats = PipelineStats()


class Clock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


def _engine(matcher, pipeline, clock, **kw):
    kw.setdefault("batch_latency_target", 0.99)
    kw.setdefault("shed_ratio_max", 0.01)
    kw.setdefault("stale_ratio_max", 0.01)
    kw.setdefault("breaker_open_ratio_max", 0.01)
    kw.setdefault("budget_trip_ratio_max", 0.01)
    return SloEngine(
        matcher_getter=lambda: matcher,
        pipeline_getter=lambda: pipeline,
        batch_budget_s_fn=lambda: 0.25,
        clock=clock,
        **kw,
    )


def test_healthy_stream_burns_zero():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    for _ in range(5):
        clock.t += 60
        for _ in range(100):
            m.stats.batch_latency_hist.observe(0.01)  # well within budget
        p.stats.note_admitted(1000)
        p.stats.note_processed(1000)
        assert eng.sample() == []
    burn = eng.burn_rates()
    assert burn[SLO_BATCH_LATENCY]["5m"] == 0.0
    assert burn[SLO_SHED]["5m"] == 0.0
    assert burn[SLO_STALE]["5m"] == 0.0
    assert burn[SLO_BREAKER_OPEN]["5m"] == 0.0
    assert not any(eng.breached().values())


def test_shed_burst_breaches_and_fires_once():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    breaches = []
    eng = _engine(m, p, clock,
                  on_breach=lambda name, burn: breaches.append(name))
    eng.sample()
    clock.t += 60
    p.stats.note_admitted(1000)
    p.stats.note_shed(500)     # 50% shed vs 1% budget → burn 50
    p.stats.note_processed(500)
    newly = eng.sample()
    assert SLO_SHED in newly
    assert breaches == [SLO_SHED]
    assert eng.breached()[SLO_SHED] is True
    assert eng.burn_rates()[SLO_SHED]["5m"] == pytest.approx(50.0, rel=0.01)
    # still breached on the next sample, but no re-fire (transition edge)
    clock.t += 60
    p.stats.note_admitted(10)
    p.stats.note_shed(10)
    assert eng.sample() == []
    assert breaches == [SLO_SHED]


def test_drain_errors_count_into_shed_slo():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    clock.t += 60
    p.stats.note_admitted(100)
    p.stats.note_drain_error(100)
    eng.sample()
    assert eng.breached()[SLO_SHED] is True


def test_batch_latency_burn_from_histogram_buckets():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    clock.t += 60
    for _ in range(90):
        m.stats.batch_latency_hist.observe(0.01)   # good
    for _ in range(10):
        m.stats.batch_latency_hist.observe(2.0)    # blows the 250 ms budget
    eng.sample()
    # 10% bad vs a 1% budget → burn 10 on every window
    assert eng.burn_rates()[SLO_BATCH_LATENCY]["5m"] == pytest.approx(
        10.0, rel=0.01
    )
    assert eng.breached()[SLO_BATCH_LATENCY] is True


def test_breaker_open_and_budget_trip_burn():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    clock.t += 100
    m.breaker.open_s += 50.0  # open half the span vs 1% budget → burn 50
    m.budget_trips += 10
    for _ in range(100):
        m.stats.batch_latency_hist.observe(0.01)
    eng.sample()
    assert eng.burn_rates()[SLO_BREAKER_OPEN]["5m"] == pytest.approx(
        50.0, rel=0.02
    )
    assert eng.burn_rates()[SLO_BUDGET_TRIPS]["5m"] == pytest.approx(
        10.0, rel=0.02
    )
    assert eng.breached()[SLO_BREAKER_OPEN] is True
    assert eng.breached()[SLO_BUDGET_TRIPS] is True


def test_fast_window_recovers_before_slow_window():
    """A spike ages out of the 5 m window while the 1 h window still
    remembers it — the multi-window AND keeps recovered systems from
    staying 'breached' forever, and young spikes from paging twice."""
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    clock.t += 60
    p.stats.note_admitted(1000)
    p.stats.note_shed(1000)
    eng.sample()
    assert eng.breached()[SLO_SHED] is True
    # 20 minutes of clean traffic: the 5 m window sees only good deltas
    for _ in range(20):
        clock.t += 60
        p.stats.note_admitted(1000)
        p.stats.note_processed(1000)
        eng.sample()
    burn = eng.burn_rates()
    assert burn[SLO_SHED]["5m"] == 0.0
    assert burn[SLO_SHED]["1h"] > 1.0  # the hour still remembers
    assert eng.breached()[SLO_SHED] is False  # AND over windows


def test_snapshot_shape_for_incident_bundles():
    m, p, clock = FakeMatcher(), FakePipeline(), Clock()
    eng = _engine(m, p, clock)
    eng.sample()
    snap = eng.snapshot()
    assert set(snap) == {"burn_rates", "breached", "windows", "targets"}
    assert snap["windows"] == {"5m": 300.0, "1h": 3600.0}


def test_rejects_bad_targets():
    with pytest.raises(ValueError):
        SloEngine(batch_latency_target=1.0)
    with pytest.raises(ValueError):
        SloEngine(shed_ratio_max=0.0)


def test_background_sampling_thread_starts_and_stops():
    m, p = FakeMatcher(), FakePipeline()
    eng = SloEngine(matcher_getter=lambda: m, pipeline_getter=lambda: p,
                    batch_budget_s_fn=lambda: 0.25)
    eng.start(0.05)
    import time as _time

    _time.sleep(0.2)
    eng.stop()
    assert len(eng._samples) >= 2


def test_collect_fn_replaces_local_collection_fleet_mode():
    """ISSUE 20: a fleet-mode engine burns an injected counter stream
    (obs/fleet.py fleet_collect) with the same window mechanics as a
    node burning its own pipeline."""
    clock = Clock()
    feed = {"admitted": 0.0, "processed": 0.0, "shed": 0.0, "stale": 0.0}
    breaches = []
    eng = SloEngine(
        collect_fn=lambda: dict(feed),
        shed_ratio_max=0.01,
        clock=clock,
        on_breach=lambda name, burn: breaches.append(name),
    )
    eng.sample()
    clock.t += 60
    feed.update(admitted=1000.0, processed=500.0, shed=500.0)
    newly = eng.sample()
    assert SLO_SHED in newly
    assert breaches == [SLO_SHED]
    assert eng.burn_rates()[SLO_SHED]["5m"] == pytest.approx(50.0, rel=0.01)


def test_collect_fn_failure_degrades_to_empty_sample():
    clock = Clock()

    def boom():
        raise RuntimeError("scrape machinery died")

    eng = SloEngine(collect_fn=boom, clock=clock)
    assert eng.sample() == []  # never raises out of the sampler
    assert all(v is False for v in eng.breached().values())
