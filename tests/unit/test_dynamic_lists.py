"""Dynamic expiring decision lists (reference: internal/decision.go:379-604)."""

import time

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision


def make_lists():
    return DynamicDecisionLists(start_sweeper=False)


def test_update_and_check():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.2.3.4", expires, Decision.CHALLENGE, False, "example.com")
    ed, ok = lists.check("", "1.2.3.4")
    assert ok
    assert ed.decision is Decision.CHALLENGE
    assert ed.domain == "example.com"


def test_never_downgrade_severity():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.2.3.4", expires, Decision.NGINX_BLOCK, False, "a.com")
    lists.update("1.2.3.4", expires, Decision.CHALLENGE, False, "b.com")
    ed, ok = lists.check("", "1.2.3.4")
    assert ok
    assert ed.decision is Decision.NGINX_BLOCK
    assert ed.domain == "a.com"  # the downgrade attempt was a no-op

    # an upgrade is applied
    lists.update("1.2.3.4", expires, Decision.IPTABLES_BLOCK, False, "c.com")
    ed, _ = lists.check("", "1.2.3.4")
    assert ed.decision is Decision.IPTABLES_BLOCK


def test_equal_severity_is_noop():
    lists = make_lists()
    e1 = time.time() + 60
    e2 = time.time() + 3600
    lists.update("1.2.3.4", e1, Decision.CHALLENGE, False, "a.com")
    lists.update("1.2.3.4", e2, Decision.CHALLENGE, False, "b.com")
    ed, _ = lists.check("", "1.2.3.4")
    assert ed.expires == e1  # newDecision <= existing → no-op (decision.go:417)


def test_lazy_expiry_on_read():
    lists = make_lists()
    lists.update("1.2.3.4", time.time() - 1, Decision.CHALLENGE, False, "a.com")
    ed, ok = lists.check("", "1.2.3.4")
    assert not ok
    # second read: entry was deleted
    ed, ok = lists.check("", "1.2.3.4")
    assert not ok and ed is None


def test_session_id_priority():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.2.3.4", expires, Decision.CHALLENGE, False, "a.com")
    lists.update_by_session_id("1.2.3.4", "sess-1", expires, Decision.NGINX_BLOCK, True, "a.com")
    ed, ok = lists.check("sess-1", "1.2.3.4")
    assert ok
    assert ed.decision is Decision.NGINX_BLOCK  # session hit wins over IP

    ed, ok = lists.check("other-sess", "1.2.3.4")
    assert ok
    assert ed.decision is Decision.CHALLENGE  # unknown session falls back to IP


def test_expired_session_does_not_fall_through():
    # quirk: a found-but-expired session entry returns ok=False without
    # checking the IP map (decision.go:487 early return)
    lists = make_lists()
    lists.update("1.2.3.4", time.time() + 60, Decision.CHALLENGE, False, "a.com")
    lists.update_by_session_id("1.2.3.4", "sess-1", time.time() - 1, Decision.NGINX_BLOCK, False, "a.com")
    ed, ok = lists.check("sess-1", "1.2.3.4")
    assert not ok


def test_check_by_domain():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.1.1.1", expires, Decision.ALLOW, False, "a.com")
    lists.update("2.2.2.2", expires, Decision.CHALLENGE, False, "a.com")
    lists.update("3.3.3.3", expires, Decision.IPTABLES_BLOCK, True, "a.com")
    lists.update("4.4.4.4", expires, Decision.NGINX_BLOCK, False, "b.com")
    lists.update_by_session_id("5.5.5.5", "sess-9", expires, Decision.CHALLENGE, True, "a.com")

    entries = lists.check_by_domain("a.com")
    keys = {e.ip_or_session_id for e in entries}
    # Allow entries are excluded (severity >= Challenge only)
    assert keys == {"2.2.2.2", "3.3.3.3", "sess-9"}
    bask = {e.ip_or_session_id: e.from_baskerville for e in entries}
    assert bask["3.3.3.3"] is True and bask["2.2.2.2"] is False


def test_remove_and_clear():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.1.1.1", expires, Decision.CHALLENGE, False, "a.com")
    lists.remove_by_ip("1.1.1.1")
    assert lists.check("", "1.1.1.1") == (None, False)

    lists.update("2.2.2.2", expires, Decision.CHALLENGE, False, "a.com")
    lists.update_by_session_id("2.2.2.2", "s", expires, Decision.CHALLENGE, False, "a.com")
    lists.clear()
    assert lists.check("s", "2.2.2.2") == (None, False)


def test_metrics():
    lists = make_lists()
    expires = time.time() + 60
    lists.update("1.1.1.1", expires, Decision.CHALLENGE, False, "a.com")
    lists.update("2.2.2.2", expires, Decision.NGINX_BLOCK, False, "a.com")
    lists.update("3.3.3.3", expires, Decision.IPTABLES_BLOCK, False, "a.com")
    lists.update("4.4.4.4", expires, Decision.ALLOW, False, "a.com")
    challenges, blocks = lists.metrics()
    assert challenges == 1
    assert blocks == 2
