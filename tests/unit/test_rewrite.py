"""Challenge-page HTML rewriting (reference: internal/http_server_test.go,
http_server.go:438-491)."""

from banjax_tpu.config.holder import _PAGES_DIR
from banjax_tpu.config.schema import Config
from banjax_tpu.httpapi.rewrite import (
    apply_args_to_password_page,
    apply_args_to_sha_inv_page,
    apply_cookie_domain,
    apply_cookie_max_age,
)


def sha_page() -> bytes:
    return (_PAGES_DIR / "sha-inverse-challenge.html").read_bytes()


def password_page() -> bytes:
    return (_PAGES_DIR / "password-protected-path.html").read_bytes()


def test_sha_page_difficulty_rewrite_hits_the_onload():
    config = Config(
        challenger_bytes=sha_page(),
        sha_inv_cookie_ttl_seconds=14400,
        sha_inv_expected_zero_bits=13,  # non-default so a comment hit would show
    )
    out = apply_args_to_sha_inv_page(config)
    assert b'onload="new_solver(13)"' in out
    assert b"new_solver(10)" not in out
    assert b'"deflect_challenge3=" + base64_cookie + ";max-age=14400"' in out


def test_password_page_max_age_and_domain():
    out = apply_args_to_password_page(password_page(), roaming=False, cookie_ttl=3600)
    assert b'"deflect_password3=" + base64_cookie + ";max-age=3600"' in out
    assert b"window.location.hostname" not in out

    out = apply_args_to_password_page(password_page(), roaming=True, cookie_ttl=3600)
    assert b';domain=" + window.location.hostname' in out


def test_rewrite_replaces_first_occurrence_only():
    page = b'x "c=" + base64_cookie y "c=" + base64_cookie z'
    out = apply_cookie_max_age(page, "c", 5)
    assert out == b'x "c=" + base64_cookie + ";max-age=5" y "c=" + base64_cookie z'


def test_rewrite_targets_unique_in_shipped_pages():
    # the server patches the FIRST occurrence; the target strings must appear
    # exactly once, inside the JS (a doc comment above the JS once broke this)
    assert sha_page().count(b'"deflect_challenge3=" + base64_cookie') == 1
    assert sha_page().count(b"new_solver(10)") == 1
    assert password_page().count(b'"deflect_password3=" + base64_cookie') == 1
