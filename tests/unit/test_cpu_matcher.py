"""CPU reference matcher (reference: internal/regex_rate_limiter_test.go)."""

import random
import string
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from tests.mock_banner import MockBanner


CONFIG_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: 'rule1'
    regex: 'GET example\.com GET .*'
    interval: 5
    hits_per_interval: 2
  - decision: challenge
    rule: 'rule2'
    regex: 'POST .*'
    interval: 5
    hits_per_interval: 1
per_site_regexes_with_rates:
  per-site.com:
    - decision: nginx_block
      hits_per_interval: 0
      interval: 1
      regex: .*blockme.*
      rule: "instant block"
global_decision_lists:
  allow:
    - 12.12.12.12
"""


def make_matcher(yaml_text=CONFIG_YAML):
    config = config_from_yaml_text(yaml_text)
    states = RegexRateLimitStates()
    banner = MockBanner()
    matcher = CpuMatcher(config, banner, StaticDecisionLists(config), states)
    return matcher, states, banner


def line(ts, ip="1.2.3.4", rest="GET example.com GET /whatever HTTP/1.1 Chrome/51 -"):
    return f"{ts:f} {ip} {rest}"


def test_window_transitions_via_consume_line():
    """The window-start / in-window / window-restart sequence
    (regex_rate_limiter_test.go:77-260)."""
    matcher, states, banner = make_matcher()
    now = time.time()

    matcher.consume_line(line(now))
    ip_states, ok = states.get("1.2.3.4")
    assert ok and ip_states["rule1"].num_hits == 1
    assert banner.bans == []

    matcher.consume_line(line(now + 4))
    ip_states, _ = states.get("1.2.3.4")
    assert ip_states["rule1"].num_hits == 2
    assert banner.bans == []

    # just past the 5s interval → window restarts
    matcher.consume_line(line(now + 5.5))
    ip_states, _ = states.get("1.2.3.4")
    assert ip_states["rule1"].num_hits == 1
    assert banner.bans == []

    # a POST trips rule2 (hits_per_interval=1) but not yet over
    matcher.consume_line(line(now + 6.5, rest="POST example.com POST /x HTTP/1.1 UA -"))
    ip_states, _ = states.get("1.2.3.4")
    assert ip_states["rule1"].num_hits == 1  # unchanged, regex didn't match
    assert ip_states["rule2"].num_hits == 1
    assert banner.bans == []

    # second POST inside the window: hits=2 > 1 → ban
    matcher.consume_line(line(now + 7.0, rest="POST example.com POST /x HTTP/1.1 UA -"))
    assert len(banner.bans) == 1
    assert banner.bans[0].ip == "1.2.3.4"
    assert banner.bans[0].decision is Decision.CHALLENGE
    assert banner.regex_ban_logs == [("1.2.3.4", "rule2")]


def test_malformed_lines_error():
    matcher, _, _ = make_matcher()
    assert matcher.consume_line("one two").error
    assert matcher.consume_line("notafloat 1.2.3.4 GET x GET / U").error
    assert matcher.consume_line(f"{time.time():f} 1.2.3.4 onlyoneword").error


def test_old_lines_dropped():
    matcher, states, _ = make_matcher()
    result = matcher.consume_line(line(time.time() - 11))
    assert result.old_line
    _, ok = states.get("1.2.3.4")
    assert not ok


def test_allowlisted_ip_exempted():
    matcher, states, banner = make_matcher()
    result = matcher.consume_line(
        line(time.time(), ip="12.12.12.12", rest="GET example.com GET /blockme HTTP/1.1 U -")
    )
    assert result.exempted
    _, ok = states.get("12.12.12.12")
    assert not ok


def test_per_site_rules_apply_before_global():
    matcher, _, banner = make_matcher()
    result = matcher.consume_line(
        line(time.time(), rest="GET per-site.com GET /blockme HTTP/1.1 U -")
    )
    names = [r.rule_name for r in result.rule_results]
    assert names[0] == "instant block"  # per-site first
    assert banner.bans[0].decision is Decision.NGINX_BLOCK
    assert banner.bans[0].domain == "per-site.com"


def test_hosts_to_skip():
    yaml_text = """
regexes_with_rates:
  - decision: challenge
    hits_per_interval: 0
    interval: 1
    regex: .*
    rule: "challenge all"
    hosts_to_skip:
      skipme.com: true
"""
    matcher, _, banner = make_matcher(yaml_text)
    result = matcher.consume_line(
        line(time.time(), rest="GET skipme.com GET / HTTP/1.1 U -")
    )
    assert result.rule_results[0].skip_host
    assert banner.bans == []

    result = matcher.consume_line(
        line(time.time(), rest="GET other.com GET / HTTP/1.1 U -")
    )
    assert not result.rule_results[0].skip_host
    assert len(banner.bans) == 1


def test_per_site_stress_each_line_trips_its_own_rule():
    """Generative stress (TestPerSiteRegexStress, regex_rate_limiter_test.go:
    298-360): N generated per-site rules, each line trips exactly its rule."""
    rng = random.Random(42)
    n = 400
    sites = []
    rule_lines = []
    for i in range(n):
        site = f"site{i}.example"
        token = "".join(rng.choices(string.ascii_lowercase, k=12))
        sites.append((site, token))
        rule_lines.append(
            f"  {site}:\n"
            f"    - decision: nginx_block\n"
            f"      hits_per_interval: 0\n"
            f"      interval: 1\n"
            f"      regex: .*{token}.*\n"
            f'      rule: "rule-{site}"\n'
        )
    yaml_text = "per_site_regexes_with_rates:\n" + "".join(rule_lines)
    matcher, _, banner = make_matcher(yaml_text)

    now = time.time()
    for i, (site, token) in enumerate(sites):
        ip = f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"
        result = matcher.consume_line(
            line(now, ip=ip, rest=f"GET {site} GET /{token} HTTP/1.1 U -")
        )
        fired = [r.rule_name for r in result.rule_results]
        assert fired == [f"rule-{site}"], f"line {i} fired {fired}"
    assert len(banner.bans) == n


def test_kafka_command_dispatch():
    """kafka.go:194-283 command handling through the shared dynamic lists."""
    from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
    from banjax_tpu.ingest.kafka_io import handle_command

    config = config_from_yaml_text(
        CONFIG_YAML
        + """
expiring_decision_ttl_seconds: 100
block_ip_ttl_seconds: 50
block_session_ttl_seconds: 60
sites_to_disable_baskerville:
  disabled.com: true
"""
    )
    lists = DynamicDecisionLists(start_sweeper=False)

    handle_command(config, {"Name": "challenge_ip", "Value": "1.2.3.4", "host": "a.com"}, lists)
    ed, ok = lists.check("", "1.2.3.4")
    assert ok and ed.decision is Decision.CHALLENGE and ed.from_baskerville

    handle_command(config, {"Name": "block_ip", "Value": "5.6.7.8", "host": "a.com"}, lists)
    ed, ok = lists.check("", "5.6.7.8")
    assert ok and ed.decision is Decision.NGINX_BLOCK
    # reference quirk: block_ip ttl defaults to block_session_ttl_seconds (60)
    assert ed.expires == pytest.approx(time.time() + 60, abs=2)

    handle_command(
        config,
        {"Name": "block_session", "Value": "9.9.9.9", "host": "a.com",
         "session_id": "sess%2Bid"},
        lists,
    )
    ed, ok = lists.check("sess+id", "0.0.0.0")  # url-decoded id is the key
    assert ok and ed.decision is Decision.NGINX_BLOCK
    # and block_session ttl defaults to block_ip_ttl_seconds (50)
    assert ed.expires == pytest.approx(time.time() + 50, abs=2)

    # reference quirk: disabled-baskerville hosts are only skipped when
    # debug is ALSO on; production stores the command (neutralized at serve
    # time by the chain's DIS-BASK check)
    handle_command(
        config, {"Name": "block_ip", "Value": "7.7.7.7", "host": "disabled.com"}, lists
    )
    _, ok = lists.check("", "7.7.7.7")
    assert ok
    config.debug = True
    handle_command(
        config, {"Name": "block_ip", "Value": "3.3.3.3", "host": "disabled.com"}, lists
    )
    _, ok = lists.check("", "3.3.3.3")
    assert not ok
    config.debug = False

    # malformed (short) values are ignored
    handle_command(config, {"Name": "block_ip", "Value": "1.2", "host": "a.com"}, lists)
    _, ok = lists.check("", "1.2")
    assert not ok
