"""Session cookie (reference: internal/session_cookie_test.go)."""

import time

import pytest

from banjax_tpu.crypto.session import (
    SESSION_ID_LENGTH,
    SessionCookieError,
    new_session_cookie,
    validate_session_cookie,
)


def test_create_and_validate():
    cookie = new_session_cookie("some_secret", 3600, "1.2.3.4")
    validate_session_cookie(cookie, "some_secret", time.time(), "1.2.3.4")


def test_wrong_ip_rejected():
    cookie = new_session_cookie("some_secret", 3600, "1.2.3.4")
    with pytest.raises(SessionCookieError):
        validate_session_cookie(cookie, "some_secret", time.time(), "5.6.7.8")


def test_wrong_secret_rejected():
    cookie = new_session_cookie("some_secret", 3600, "1.2.3.4")
    with pytest.raises(SessionCookieError):
        validate_session_cookie(cookie, "other_secret", time.time(), "1.2.3.4")


def test_expired_rejected():
    cookie = new_session_cookie("some_secret", -10, "1.2.3.4")
    with pytest.raises(SessionCookieError):
        validate_session_cookie(cookie, "some_secret", time.time(), "1.2.3.4")


def test_garbage_rejected():
    with pytest.raises(SessionCookieError):
        validate_session_cookie("!!!", "s", time.time(), "1.2.3.4")
    with pytest.raises(SessionCookieError):
        validate_session_cookie("dG9vc2hvcnQ=", "s", time.time(), "1.2.3.4")


def test_creation_speed():
    # reference prints the time for 1000 cookies (session_cookie_test.go:17-27)
    start = time.monotonic()
    for _ in range(1000):
        new_session_cookie("some_secret", 3600, "1.2.3.4")
    elapsed = time.monotonic() - start
    assert elapsed < 5.0
