"""Native slot manager vs the Python dict+LRU path: exact parity.

The C manager (native/slotmgr.c) replaces the per-distinct-IP Python
loop in DeviceWindows.slots_for_unique_ips; the dict loop stays as the
fallback and THE differential oracle.  Parity here is stronger than the
spill-is-lossless argument needs: slot ids, eviction victims and their
order, restore triggers, refusal points, growth chains, and free-stack
order must all match verbatim, so the two modes are interchangeable
mid-deployment (a box without a C compiler produces the same device
layout as one with it).
"""

import random
import re

import numpy as np
import pytest

from banjax_tpu.config.schema import Decision, RegexWithRate
from banjax_tpu.matcher.windows import DeviceWindows
from banjax_tpu.native import slotmgr

pytestmark = pytest.mark.skipif(
    slotmgr.create(8) is None,
    reason="native slotmgr unavailable (no C compiler)",
)

NS = 1_000_000_000


def make_rule(name="r", interval_s=5.0, hits=2) -> RegexWithRate:
    return RegexWithRate(
        rule=name, regex_string="x", regex=re.compile("x"),
        interval_ns=int(interval_s * NS), hits_per_interval=hits,
        decision=Decision.NGINX_BLOCK,
    )


def make_pair(capacity):
    """(native, dict-oracle) DeviceWindows at the same capacity."""
    nat = DeviceWindows([make_rule()], capacity=capacity,
                        native_slotmgr=True)
    assert nat.slotmgr_native, "native manager failed to engage"
    ora = DeviceWindows([make_rule()], capacity=capacity,
                        native_slotmgr=False)
    assert not ora.slotmgr_native
    return nat, ora


def assert_same_state(nat: DeviceWindows, ora: DeviceWindows, ctx=""):
    assert nat.capacity == ora.capacity, ctx
    assert nat._slot_ip == ora._slot_ip, ctx
    assert nat._pending_evict == ora._pending_evict, ctx
    assert nat._pending_restore == ora._pending_restore, ctx
    assert nat.eviction_count == ora.eviction_count, ctx
    assert nat.grow_count == ora.grow_count, ctx
    assert nat.occupancy == ora.occupancy, ctx
    assert nat._sm.assigned() == len(ora._slots), ctx
    assert nat._sm.free_count() == len(ora._free), ctx
    np.testing.assert_array_equal(
        nat._pin_counts, ora._pin_counts, err_msg=ctx
    )
    np.testing.assert_array_equal(
        nat._last_used, ora._last_used, err_msg=ctx
    )


def lockstep(nat, ora, ips, ctx=""):
    """One identical batch through both paths; returns the slots (or
    None on a matching refusal)."""
    a = nat.slots_for_unique_ips(ips)
    b = ora.slots_for_unique_ips(ips)
    assert (a is None) == (b is None), f"{ctx}: refusal diverged"
    if a is not None:
        np.testing.assert_array_equal(a, b, err_msg=ctx)
    assert_same_state(nat, ora, ctx)
    return a


def ip_of(i: int) -> str:
    return f"10.{(i >> 16) & 255}.{(i >> 8) & 255}.{i & 255}"


def test_basic_assign_hit_and_free_order():
    nat, ora = make_pair(8)
    s1 = lockstep(nat, ora, [ip_of(i) for i in range(5)])
    # free stack pops ascending — list(range(cap-1,-1,-1)).pop() parity
    assert s1.tolist() == [0, 1, 2, 3, 4]
    nat.release_pins(s1), ora.release_pins(s1)
    # hits keep their slots and stamp recency; one new ip takes slot 5
    s2 = lockstep(nat, ora, [ip_of(3), ip_of(0), ip_of(99)])
    assert s2.tolist() == [3, 0, 5]
    nat.release_pins(s2), ora.release_pins(s2)
    assert_same_state(nat, ora)


def test_eviction_victim_and_order_parity():
    """At capacity, victims are min-(last_used, slot) over unpinned
    slots untouched by this batch — both paths, identical sequence."""
    nat, ora = make_pair(4)
    s = lockstep(nat, ora, [ip_of(i) for i in range(4)])
    nat.release_pins(s), ora.release_pins(s)
    # refresh slots 2, 3 so 0 and 1 are the LRU victims, in slot order
    s = lockstep(nat, ora, [ip_of(2), ip_of(3)])
    nat.release_pins(s), ora.release_pins(s)
    s = lockstep(nat, ora, [ip_of(100), ip_of(101)])
    assert s.tolist() == [0, 1]
    assert nat._pending_evict == [0, 1]
    assert nat.eviction_count == 2
    nat.release_pins(s), ora.release_pins(s)


def test_refusal_when_all_pinned_leaves_partial_state():
    """Every slot pinned by an in-flight batch: a new distinct ip must
    refuse (None) in both paths, with identical partial placements."""
    nat, ora = make_pair(2)
    s = lockstep(nat, ora, [ip_of(0), ip_of(1)])  # pins both slots
    # one hit + two misses: the hit resolves, the first miss has no free
    # slot and no evictable victim -> refusal after identical state
    out = lockstep(nat, ora, [ip_of(0), ip_of(7), ip_of(8)], "refusal")
    assert out is None
    nat.release_pins(s), ora.release_pins(s)
    # after the split-retry pins are gone, the same ips place fine
    s2 = lockstep(nat, ora, [ip_of(7), ip_of(8)])
    assert s2 is not None


def test_grow_free_stack_order_parity(monkeypatch):
    """Grown slots drain AFTER every pre-grow free slot, ascending —
    the Python free-list splice order, replicated by sm_grow."""
    monkeypatch.setattr(DeviceWindows, "AUTO_START_CAPACITY", 32)
    nat, ora = make_pair(0)  # auto-grow mode
    cap0 = nat.capacity
    n = cap0 + 3  # force one doubling
    s = lockstep(nat, ora, [ip_of(i) for i in range(n)])
    assert s.tolist() == list(range(n))
    assert nat.capacity == cap0 * 2
    assert nat.grow_count == ora.grow_count == 1
    nat.release_pins(s), ora.release_pins(s)


def test_shadow_restore_trigger_parity():
    """A previously-evicted ip (present in the host shadow) re-admitting
    must append the same (slot, ip) restore in both modes."""
    nat, ora = make_pair(2)
    s = lockstep(nat, ora, [ip_of(0), ip_of(1)])
    nat.release_pins(s), ora.release_pins(s)
    for w in (nat, ora):  # counters spilled for ip 0, as apply would
        w._shadow[ip_of(0)] = {}
    s = lockstep(nat, ora, [ip_of(2), ip_of(3)])  # evicts 0 and 1
    nat.release_pins(s), ora.release_pins(s)
    s = lockstep(nat, ora, [ip_of(0)])  # returns: restore fires
    assert nat._pending_restore == ora._pending_restore
    assert len(nat._pending_restore) == 1
    assert nat._pending_restore[0][1] == ip_of(0)
    nat.release_pins(s), ora.release_pins(s)


def test_clear_parity():
    nat, ora = make_pair(4)
    s = lockstep(nat, ora, [ip_of(i) for i in range(4)])
    nat.release_pins(s), ora.release_pins(s)
    nat.clear(), ora.clear()
    assert nat._sm.assigned() == 0
    assert nat._sm.free_count() == 4
    s = lockstep(nat, ora, [ip_of(9), ip_of(8)])
    assert s.tolist() == [0, 1]  # full stack again, ascending
    nat.release_pins(s), ora.release_pins(s)


def test_non_ascii_ip_strings():
    """Oracle inputs (not real traffic) may be non-ASCII; the utf-8 span
    encode must keep parity."""
    nat, ora = make_pair(4)
    ips = ["1.2.3.4", "καφές", "1.2.3.4é", "漢字"]
    s = lockstep(nat, ora, ips)
    nat.release_pins(s), ora.release_pins(s)
    s = lockstep(nat, ora, ["καφές", "漢字", "x"])
    assert s.tolist()[:2] == [1, 3]
    nat.release_pins(s), ora.release_pins(s)


@pytest.mark.parametrize("capacity,seed", [(16, 1), (16, 2), (64, 3)])
def test_parity_fuzz_eviction_churn(capacity, seed):
    """Randomized lockstep: batches drawn from an ip pool ~4x capacity
    (constant eviction/restore churn), pins held across batches at
    random (refusal + partial-state parity), periodic shadow spills and
    clears.  Every batch asserts full-state equality."""
    rng = random.Random(seed)
    nat, ora = make_pair(capacity)
    pool = [ip_of(i) for i in range(capacity * 4)]
    held = []  # slots pinned by "in-flight" batches, released randomly
    for step in range(200):
        k = rng.randrange(1, capacity + 4)
        ips = rng.sample(pool, min(k, len(pool)))
        s = lockstep(nat, ora, ips, f"step {step}")
        if s is not None:
            if rng.random() < 0.7:
                nat.release_pins(s), ora.release_pins(s)
            else:
                held.append(s)
        while held and (s is None or rng.random() < 0.4):
            # a refusal means the runner splits — free an old batch so
            # the stream can make progress, exactly as apply_bitmap does
            h = held.pop(rng.randrange(len(held)))
            nat.release_pins(h), ora.release_pins(h)
        if rng.random() < 0.15:
            ip = rng.choice(pool)
            nat._shadow.setdefault(ip, {})
            ora._shadow.setdefault(ip, {})
        if rng.random() < 0.02:
            held.clear()
            nat.clear(), ora.clear()
            assert_same_state(nat, ora, f"step {step} clear")
    for h in held:
        nat.release_pins(h), ora.release_pins(h)
    assert_same_state(nat, ora, "final")
    assert nat.eviction_count > 0, "fuzz never churned an eviction"
    assert nat._pending_restore or nat.eviction_count > 0


def test_parity_fuzz_autogrow_chain(monkeypatch):
    """Auto-grow mode: the native path's one-shot doubling chain must
    land at the same capacity the dict path's grow-per-miss loop
    reaches, with identical slot ids before and after."""
    monkeypatch.setattr(DeviceWindows, "AUTO_START_CAPACITY", 64)
    rng = random.Random(7)
    nat, ora = make_pair(0)
    next_ip = 0
    for step in range(12):
        k = rng.randrange(50, 400)
        ips = [ip_of(next_ip + i) for i in range(k)]
        next_ip += k
        s = lockstep(nat, ora, ips, f"grow step {step}")
        assert s is not None
        nat.release_pins(s), ora.release_pins(s)
    assert nat.grow_count > 0


# ------------------------------------------------------- warm-tier hooks


def make_warm_pair(capacity, warm_capacity=256):
    """(native, dict-oracle) pair with the warm spill/refill hooks armed."""
    nat = DeviceWindows([make_rule()], capacity=capacity,
                        native_slotmgr=True, warm_tier_enabled=True,
                        warm_tier_capacity=warm_capacity)
    assert nat.slotmgr_native and nat._warm is not None
    ora = DeviceWindows([make_rule()], capacity=capacity,
                        native_slotmgr=False, warm_tier_enabled=True,
                        warm_tier_capacity=warm_capacity)
    assert not ora.slotmgr_native and ora._warm is not None
    return nat, ora


def assert_same_warm_state(nat: DeviceWindows, ora: DeviceWindows, ctx=""):
    """Warm-tier side of the parity: identical membership, identical
    per-IP window vectors, identical spill/refill/drop accounting, and
    identical shadow residency (a drop must keep the shadow entry in
    BOTH modes)."""
    assert nat.warm_spills == ora.warm_spills, ctx
    assert nat.warm_refills == ora.warm_refills, ctx
    assert nat.warm_dropped == ora.warm_dropped, ctx
    assert sorted(nat._shadow) == sorted(ora._shadow), ctx
    nk, ok_ = sorted(nat._warm.keys()), sorted(ora._warm.keys())
    assert nk == ok_, ctx
    for ip in nk:
        assert nat._warm.peek(ip) == ora._warm.peek(ip), (ctx, ip)


@pytest.mark.parametrize("capacity,seed", [(16, 11), (16, 12), (64, 13)])
def test_parity_fuzz_warm_spill_hooks(capacity, seed):
    """test_parity_fuzz_eviction_churn with the warm tier armed: shadow
    entries seeded with REAL window vectors so every eviction exercises
    the spill hook (shadow -> warm put) and every return exercises the
    refill hook (warm take -> shadow -> pending restore), natively and
    through the dict oracle in lockstep.  Each step also runs
    admission_mask over a random probe batch — the three membership
    passes (sm_contains_batch / shadow / warm.contains_batch) plus the
    estimate gate must agree bit-for-bit between the two modes."""
    rng = random.Random(seed)
    nat, ora = make_warm_pair(capacity)
    pool = [ip_of(i) for i in range(capacity * 4)]
    held = []
    seeded = 0
    for step in range(200):
        k = rng.randrange(1, capacity + 4)
        ips = rng.sample(pool, min(k, len(pool)))
        s = lockstep(nat, ora, ips, f"step {step}")
        assert_same_warm_state(nat, ora, f"step {step}")
        if s is not None:
            if rng.random() < 0.7:
                nat.release_pins(s), ora.release_pins(s)
            else:
                held.append(s)
        while held and (s is None or rng.random() < 0.4):
            h = held.pop(rng.randrange(len(held)))
            nat.release_pins(h), ora.release_pins(h)
        if rng.random() < 0.35:
            # spill payload: a real (rule_id -> (hits, start_s, start_ns))
            # vector, distinct per seeding so a content mismatch is loud
            ip = rng.choice(pool)
            seeded += 1
            vec = {0: (seeded, 1_700_000_000 + seeded, seeded * 7)}
            for w in (nat, ora):
                w._shadow.setdefault(ip, dict(vec))
        if rng.random() < 0.5:
            probe = rng.sample(pool, rng.randrange(1, capacity))
            est = np.zeros(len(probe), dtype=np.int64)
            a = nat.admission_mask(probe, estimates=est, min_estimate=1)
            b = ora.admission_mask(probe, estimates=est, min_estimate=1)
            np.testing.assert_array_equal(a, b, err_msg=f"step {step}")
            assert nat.slot_refusals == ora.slot_refusals, f"step {step}"
            assert nat.sketch_admissions == ora.sketch_admissions
    for h in held:
        nat.release_pins(h), ora.release_pins(h)
    assert_same_state(nat, ora, "final")
    assert_same_warm_state(nat, ora, "final")
    assert nat.eviction_count > 0, "fuzz never churned an eviction"
    assert nat.warm_spills > 0, "no eviction ever spilled to warm"
    assert nat.warm_refills > 0, "no returning IP ever refilled"
    assert nat.slot_refusals > 0, "admission probes never refused"


def test_warm_drop_keeps_shadow_in_both_modes():
    """A warm tier too small to place a spill: both modes must keep the
    shadow entry (lossless), report the drop, and stay in lockstep."""
    nat, ora = make_warm_pair(2, warm_capacity=1)
    vec = {0: (3, 1_700_000_123, 42)}
    n = 10
    for i in range(n):
        ip = ip_of(i)
        for w in (nat, ora):
            w._shadow.setdefault(ip, dict(vec))
        s = lockstep(nat, ora, [ip], f"fill {i}")
        nat.release_pins(s), ora.release_pins(s)
        assert_same_warm_state(nat, ora, f"fill {i}")
    assert nat.warm_dropped > 0, "tiny tier never dropped"
    # lossless: every evicted ip's vector is in the warm tier OR shadow
    for i in range(n - 2):
        ip = ip_of(i)
        in_warm = nat._warm.peek(ip) is not None
        assert in_warm or ip in nat._shadow, ip
