"""Wire v2 transport: binary frame codec fuzz/regression, the version
handshake, and the LinePipe windowed sender (ISSUE 18).

The codec tests drive every strict-decode branch in
`wire.decode_lines_v2` — torn frames, truncated offset tables,
oversized counts, non-monotone tables, non-UTF-8 blobs — plus a seeded
byte-flip fuzz pass: a corrupted frame must either decode to a valid
LinesV2 (flips inside the blob can legally alter text) or raise
FrameError, never anything else and never garbled structure.

The pipe tests run a real FabricNode on a real socket: delivery with
coalescing, the inflight window cap, negotiation down to JSON against
a pre-v2 peer, death on a wedged-but-connected peer (acks are the
liveness proof, not TCP connects), and retransmit-after-drop.
"""

import random
import socket
import threading
import time

import pytest

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import LinePipe, PeerClient, PeerUnavailable
from banjax_tpu.fabric.stats import FabricStats


# ---------------------------------------------------------------------------
# codec: roundtrip + strict decode
# ---------------------------------------------------------------------------


def test_v2_roundtrip_plain_unicode_empty_and_flags():
    lines = ["1.5 10.0.0.1 GET a GET /x HTTP/1.1 ua -",
             "naïve — ünïcode line ☂", "", "tab\tand space"]
    frame = wire.encode_lines_v2(7, lines, replay=True)
    length, ftype = wire._HEADER.unpack(frame[:wire._HEADER.size])
    assert ftype == wire.T_LINES_V2
    assert length == len(frame) - wire._HEADER.size + 1
    fr = wire.decode_lines_v2(frame[wire._HEADER.size:])
    assert fr == wire.LinesV2(seq=7, replay=True, lines=tuple(lines))

    empty = wire.encode_lines_v2(1, [])
    fr = wire.decode_lines_v2(empty[wire._HEADER.size:])
    assert fr.lines == () and fr.replay is False and fr.seq == 1


def _v2_body(lines, seq=3, replay=False):
    return wire.encode_lines_v2(seq, lines, replay)[wire._HEADER.size:]


def test_v2_every_truncation_raises_frame_error():
    body = _v2_body(["alpha", "bravo", "charlie"])
    for k in range(len(body)):
        with pytest.raises(wire.FrameError):
            wire.decode_lines_v2(body[:k])


def test_v2_oversized_count_rejected():
    body = bytearray(_v2_body(["x"]))
    # count field (u32 at offset 9) -> far beyond MAX_V2_LINES
    body[9:13] = (wire.MAX_V2_LINES + 1).to_bytes(4, "big")
    with pytest.raises(wire.FrameError):
        wire.decode_lines_v2(bytes(body))


def test_v2_offset_table_must_start_at_zero():
    body = bytearray(_v2_body(["ab", "cd"]))
    base = wire._V2_FIXED.size
    body[base:base + 4] = (1).to_bytes(4, "big")
    with pytest.raises(wire.FrameError):
        wire.decode_lines_v2(bytes(body))


def test_v2_offset_table_must_be_monotone():
    body = bytearray(_v2_body(["ab", "cd"]))
    base = wire._V2_FIXED.size
    # middle offset > final offset: non-monotone
    body[base + 4:base + 8] = (4000).to_bytes(4, "big")
    with pytest.raises(wire.FrameError):
        wire.decode_lines_v2(bytes(body))


def test_v2_blob_length_mismatch_rejected():
    body = _v2_body(["ab", "cd"])
    with pytest.raises(wire.FrameError):
        wire.decode_lines_v2(body + b"extra")


def test_v2_non_utf8_blob_rejected():
    body = bytearray(_v2_body(["abcd"]))
    body[-2] = 0xFF  # invalid UTF-8 continuation
    with pytest.raises(wire.FrameError):
        wire.decode_lines_v2(bytes(body))


def test_v2_fuzz_byteflips_never_desynchronize():
    rng = random.Random(20260807)
    lines = [f"{i}.0 10.0.{i % 7}.{i % 11} GET h GET /p HTTP/1.1 ua -"
             for i in range(32)]
    body = _v2_body(lines, seq=99)
    for _ in range(400):
        mut = bytearray(body)
        for _ in range(rng.randint(1, 3)):
            mut[rng.randrange(len(mut))] ^= 1 << rng.randrange(8)
        try:
            fr = wire.decode_lines_v2(bytes(mut))
        except wire.FrameError:
            continue  # loud rejection is the contract
        assert isinstance(fr, wire.LinesV2)  # or a *valid* decode
    for _ in range(200):  # random truncations too
        k = rng.randrange(len(body))
        with pytest.raises(wire.FrameError):
            wire.decode_lines_v2(body[:k])


def test_recv_frame_rejects_binary_frame_on_v1_session():
    a, b = socket.socketpair()
    try:
        a.sendall(wire.encode_lines_v2(1, ["x"]))
        b.settimeout(2)
        with pytest.raises(wire.FrameError, match="binary frame"):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


def test_recv_frame_any_mid_frame_stall_is_frame_error():
    a, b = socket.socketpair()
    try:
        frame = wire.encode_lines_v2(1, ["stalled line"])
        a.sendall(frame[: len(frame) // 2])  # header + partial body
        b.settimeout(0.1)
        with pytest.raises(wire.FrameError, match="mid-frame"):
            wire.recv_frame_any(b)
    finally:
        a.close()
        b.close()


def test_recv_frame_any_oversized_length_is_frame_error():
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1, wire.T_LINES))
        b.settimeout(2)
        with pytest.raises(wire.FrameError, match="bad frame length"):
            wire.recv_frame_any(b)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# version handshake
# ---------------------------------------------------------------------------


def _rpc(port, ftype, payload):
    with socket.create_connection(("127.0.0.1", port), timeout=5) as s:
        s.settimeout(5)
        wire.send_frame(s, ftype, payload)
        return wire.recv_frame(s)


def test_node_answers_version_handshake():
    node = FabricNode("127.0.0.1", 0, handlers={}).start()
    try:
        rt, rp = _rpc(node.port, wire.T_VERSION, {"wire": 2, "node": "x"})
        assert rt == wire.T_VERSION_R
        assert rp == {"wire": wire.WIRE_VERSION, "ring": True, "trace": True}
    finally:
        node.stop()

    norings = FabricNode(
        "127.0.0.1", 0, handlers={}, allow_rings=False
    ).start()
    try:
        rt, rp = _rpc(norings.port, wire.T_VERSION, {"wire": 2})
        assert rt == wire.T_VERSION_R and rp["ring"] is False
    finally:
        norings.stop()


# ---------------------------------------------------------------------------
# LinePipe: windowed pipelined sender
# ---------------------------------------------------------------------------


class _Sink:
    """A v2-aware receiving node that records delivered lines."""

    def __init__(self, ack_delay_s=0.0, allow_rings=True):
        self.lines = []
        self.frames = 0
        self.lock = threading.Lock()
        self.ack_delay_s = ack_delay_s
        self.node = FabricNode(
            "127.0.0.1", 0,
            handlers={
                wire.T_LINES: self._h_lines,
                wire.T_LINES_V2: self._h_lines_v2,
            },
            allow_rings=allow_rings,
        ).start()

    def _note(self, lines):
        if self.ack_delay_s:
            time.sleep(self.ack_delay_s)
        with self.lock:
            self.lines.extend(lines)
            self.frames += 1

    def _h_lines(self, payload):
        self._note(payload.get("lines", []))
        ack = {"n": len(payload.get("lines", []))}
        if "seq" in payload:
            ack["seq"] = payload["seq"]
        return wire.T_ACK, ack

    def _h_lines_v2(self, fr):
        self._note(fr.lines)
        return wire.T_ACK, {"seq": fr.seq, "n": len(fr.lines)}

    def stop(self):
        self.node.stop()


def test_pipe_delivers_everything_and_coalesces():
    sink = _Sink()
    stats = FabricStats()
    pipe = LinePipe("b", "127.0.0.1", sink.node.port, node_id="a",
                    stats=stats)
    try:
        groups = [[f"g{g}l{i}" for i in range(10)] for g in range(40)]
        for g in groups:
            pipe.submit(g)
        assert pipe.flush(20)
        sent = [ln for g in groups for ln in g]
        assert sorted(sink.lines) == sorted(sent)
        assert pipe.mode == "v2" and pipe.transport == "tcp"
        # coalescing: many submitted groups rode fewer frames
        assert 1 <= sink.frames < len(groups)
        assert stats.peek()["FabricAcksReceived"] == sink.frames
    finally:
        pipe.close()
        sink.stop()


def test_pipe_window_never_exceeds_inflight_cap():
    sink = _Sink(ack_delay_s=0.02)
    pipe = LinePipe("b", "127.0.0.1", sink.node.port, node_id="a",
                    inflight_frames=2, frame_max_bytes=64)
    try:
        for g in range(12):  # tiny frame_max: one group per frame
            pipe.submit([f"group-{g:03d}"])
        seen = 0
        deadline = time.monotonic() + 20
        while pipe.inflight() or time.monotonic() < deadline:
            n = pipe.inflight()
            seen = max(seen, n)
            if not n and not pipe.inflight():
                if pipe.flush(0.2):
                    break
            time.sleep(0.001)
        assert pipe.flush(20)
        assert seen <= 2
        assert len(sink.lines) == 12
    finally:
        pipe.close()
        sink.stop()


class _OldJsonNode:
    """A pre-v2 peer: answers T_ERR to the version probe (unknown
    frame type) and serves JSON T_LINES only — the sender must
    negotiate down losslessly."""

    def __init__(self):
        self.lines = []
        self._sock = socket.socket()
        self._sock.bind(("127.0.0.1", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self._stop = threading.Event()
        self._t = threading.Thread(target=self._serve, daemon=True)
        self._t.start()

    def _serve(self):
        self._sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._conn, args=(conn,), daemon=True
            ).start()

    def _conn(self, conn):
        conn.settimeout(0.5)
        try:
            while not self._stop.is_set():
                try:
                    ftype, payload = wire.recv_frame(conn)
                except socket.timeout:
                    continue
                except (wire.FrameError, OSError):
                    return
                if ftype == wire.T_LINES:
                    self.lines.extend(payload.get("lines", []))
                    ack = {"n": len(payload.get("lines", []))}
                    # deliberately NO seq echo: an old node predates it
                    wire.send_frame(conn, wire.T_ACK, ack)
                else:
                    wire.send_frame(
                        conn, wire.T_ERR,
                        {"error": f"unhandled frame type {ftype}"},
                    )
        finally:
            conn.close()

    def stop(self):
        self._stop.set()
        try:
            self._sock.close()
        except OSError:
            pass


def test_pipe_negotiates_down_to_json_against_old_peer():
    old = _OldJsonNode()
    pipe = LinePipe("b", "127.0.0.1", old.port, node_id="a")
    try:
        groups = [[f"legacy-{g}-{i}" for i in range(5)] for g in range(8)]
        for g in groups:
            pipe.submit(g)
        assert pipe.flush(20)
        assert pipe.mode == "json"
        assert sorted(old.lines) == sorted(
            ln for g in groups for ln in g
        )
    finally:
        pipe.close()
        old.stop()


def test_pipe_dies_on_wedged_peer_acks_are_the_liveness_proof():
    # a listener that accepts and then never answers: TCP connects fine,
    # so only the ack deadline can declare it dead
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(4)
    conns = []

    def _accept():
        srv.settimeout(0.2)
        while True:
            try:
                conns.append(srv.accept()[0])
            except socket.timeout:
                continue
            except OSError:
                return

    threading.Thread(target=_accept, daemon=True).start()
    pipe = LinePipe(
        "b", "127.0.0.1", srv.getsockname()[1], node_id="a",
        send_timeout_ms=100, max_attempts=2, wire_v2=False,
    )
    try:
        try:
            pipe.submit(["doomed"])
        except PeerUnavailable:
            pass  # already dead by submit time is fine too
        deadline = time.monotonic() + 10
        while not pipe.dead and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pipe.dead
        with pytest.raises(PeerUnavailable):
            pipe.submit(["after death"])
    finally:
        pipe.close()
        srv.close()
        for c in conns:
            c.close()


def test_pipe_retransmits_unacked_window_after_connection_drop():
    from banjax_tpu.resilience import failpoints

    sink = _Sink()
    # the node-side fabric.recv failpoint drops the connection AFTER a
    # frame is read and before it is dispatched: the classic lost-frame
    # shape the retransmit window exists for
    failpoints.arm("fabric.recv", count=1)
    pipe = LinePipe("b", "127.0.0.1", sink.node.port, node_id="a")
    try:
        groups = [[f"drop-{g}-{i}" for i in range(4)] for g in range(6)]
        for g in groups:
            pipe.submit(g)
            time.sleep(0.01)  # let frames hit the faulted read path
        assert pipe.flush(20)
        assert not pipe.dead
        # at-least-once across the drop: every line delivered (the
        # dropped frame was retransmitted on reconnect)
        sent = {ln for g in groups for ln in g}
        assert sent <= set(sink.lines)
        assert failpoints.fired_count("fabric.recv") == 1
    finally:
        failpoints.disarm()
        pipe.close()
        sink.stop()


def test_old_client_still_speaks_json_to_v2_node():
    """Mixed-version the other way: a plain PeerClient (v1 JSON) against
    a v2-aware node keeps working — T_LINES is served forever."""
    sink = _Sink()
    client = PeerClient("b", "127.0.0.1", sink.node.port)
    try:
        rt, rp = client.request(wire.T_LINES, {"lines": ["v1 line"]})
        assert rt == wire.T_ACK and rp["n"] == 1
        assert sink.lines == ["v1 line"]
    finally:
        client.close()
        sink.stop()
