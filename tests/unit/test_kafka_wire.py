"""Kafka transport tests: the pure-stdlib wire client against an in-process
fake broker (both protocol ladders), plus the reader/writer loops and the
command dispatch they feed (kafka.go:93-174, 194-283, 353-406)."""

import json
import ssl
import subprocess
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.ingest import kafka_io, kafka_wire
from banjax_tpu.ingest.kafka_wire import (
    WireKafkaTransport,
    _decode_message_set,
    _decode_record_batches,
    _encode_message_set_v1,
    _encode_record_batch_v2,
    _Reader,
    _varint,
    crc32c,
)
from tests.fake_kafka_broker import FakeKafkaBroker


def make_config(port, **overrides):
    cfg = config_from_yaml_text(
        "kafka_command_topic: caraml.commands\n"
        "kafka_report_topic: caraml.reports\n"
        f"kafka_brokers:\n  - 127.0.0.1:{port}\n"
        "kafka_max_wait_ms: 100\n"
    )
    for k, v in overrides.items():
        setattr(cfg, k, v)
    return cfg


# ---------------------------------------------------------------- primitives


def test_crc32c_vector():
    # RFC 3720 / iSCSI test vector
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0


def test_varint_roundtrip():
    for n in (0, 1, -1, 63, -64, 64, 300, -300, 2**31, -(2**31), 2**40):
        r = _Reader(_varint(n))
        assert r.varint() == n, n


def test_record_batch_roundtrip():
    batch = _encode_record_batch_v2(b"hello", 1234, offset=7)
    got = _decode_record_batches(batch)
    assert got == [(7, b"hello")]


def test_message_set_roundtrip_and_magic_fallback():
    ms = _encode_message_set_v1(b"old-school", 1234, offset=3)
    assert _decode_message_set(ms) == [(3, b"old-school")]
    # _decode_record_batches must detect magic<2 and fall back
    assert _decode_record_batches(ms) == [(3, b"old-school")]


# ---------------------------------------------------------------- transport


@pytest.mark.parametrize("mode", ["legacy", "modern"])
def test_produce_then_fetch_roundtrip(mode):
    broker = FakeKafkaBroker(mode=mode).start()
    try:
        cfg = make_config(broker.port)
        tx = WireKafkaTransport()
        # LastOffset semantics: a message sitting in the log BEFORE the
        # consumer starts must not be delivered (kafka.go LastOffset)
        broker.append("caraml.commands", 0, b"stale")

        it = tx.read_messages(cfg, "caraml.commands", 0)
        tx2 = WireKafkaTransport()
        tx2.send(cfg, "caraml.commands", b"cmd-1")
        tx2.send(cfg, "caraml.commands", b"cmd-2")
        assert next(it) == b"cmd-1"
        assert next(it) == b"cmd-2"
        tx.close()
        tx2.close()
    finally:
        broker.stop()


def test_send_round_robins_partitions():
    broker = FakeKafkaBroker(mode="modern", n_partitions=3).start()
    try:
        cfg = make_config(broker.port)
        tx = WireKafkaTransport()
        for i in range(6):
            tx.send(cfg, "caraml.reports", f"r{i}".encode())
        tx.close()
        counts = sorted(
            len(broker.logs.get(("caraml.reports", p), [])) for p in range(3)
        )
        assert counts == [2, 2, 2]
    finally:
        broker.stop()


def test_unreachable_broker_raises():
    cfg = make_config(1)  # nothing listens on port 1
    tx = WireKafkaTransport()
    with pytest.raises(ConnectionError):
        next(tx.read_messages(cfg, "caraml.commands", 0))
    with pytest.raises(ConnectionError):
        tx.send(cfg, "caraml.reports", b"x")


def test_default_transport_is_the_wire_client():
    """Round-1 regression: default_transport imported a module that did not
    exist and silently degraded to NullTransport."""
    tx = kafka_io.default_transport()
    assert isinstance(tx, WireKafkaTransport)


# ---------------------------------------------------------------- loops + dispatch


def test_kafka_reader_end_to_end_updates_decision_lists():
    broker = FakeKafkaBroker(mode="modern").start()
    try:
        cfg = make_config(broker.port)

        class Holder:
            def get(self):
                return cfg

        lists = DynamicDecisionLists(start_sweeper=False)
        reader = kafka_io.KafkaReader(Holder(), lists, WireKafkaTransport())
        reader.start()
        time.sleep(0.5)  # let the consumer position at the latest offset
        broker.append("caraml.commands", 0, json.dumps({
            "Name": "challenge_ip", "Value": "1.2.3.4", "host": "example.com",
        }).encode())
        deadline = time.time() + 5
        decision = None
        while time.time() < deadline:
            decision, _ = lists.check("", "1.2.3.4")
            if decision is not None:
                break
            time.sleep(0.05)
        reader.stop()
        assert decision is not None and decision.decision == Decision.CHALLENGE
    finally:
        broker.stop()


def test_kafka_writer_end_to_end_delivers_reports():
    broker = FakeKafkaBroker(mode="legacy").start()
    try:
        cfg = make_config(broker.port)

        class Holder:
            def get(self):
                return cfg

        writer = kafka_io.KafkaWriter(Holder(), WireKafkaTransport())
        writer.start()
        q = kafka_io.get_message_queue()
        q.put(b'{"name": "status"}')
        deadline = time.time() + 5
        while time.time() < deadline:
            if broker.logs.get(("caraml.reports", 0)):
                break
            time.sleep(0.05)
        writer.stop()
        assert broker.logs.get(("caraml.reports", 0)) == [b'{"name": "status"}']
    finally:
        broker.stop()


# ---------------------------------------------------------------- TLS / mTLS


def _make_certs(tmp_path):
    """Self-signed CA + server + client certs via the openssl binary."""
    try:
        subprocess.run(["openssl", "version"], capture_output=True, check=True)
    except (OSError, subprocess.CalledProcessError):
        pytest.skip("openssl binary unavailable")
    d = tmp_path

    def run(*args):
        subprocess.run(args, capture_output=True, check=True, cwd=d)

    run("openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
        "-keyout", "ca.key", "-out", "ca.pem", "-days", "1",
        "-subj", "/CN=fake-ca")
    # the server cert needs an IP SAN: with kafka_ssl_ca configured the
    # client now verifies the chain AND the 127.0.0.1 endpoint identity
    (d / "san.cnf").write_text("subjectAltName=IP:127.0.0.1,DNS:localhost\n")
    for name in ("server", "client"):
        run("openssl", "req", "-newkey", "rsa:2048", "-nodes",
            "-keyout", f"{name}.key", "-out", f"{name}.csr",
            "-subj", f"/CN={name}")
        ext = (["-extfile", "san.cnf"] if name == "server" else [])
        run("openssl", "x509", "-req", "-in", f"{name}.csr",
            "-CA", "ca.pem", "-CAkey", "ca.key", "-CAcreateserial",
            "-out", f"{name}.pem", "-days", "1", *ext)
    return d


def test_mtls_transport(tmp_path):
    certs = _make_certs(tmp_path)
    server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
    server_ctx.load_cert_chain(certs / "server.pem", certs / "server.key")
    server_ctx.load_verify_locations(certs / "ca.pem")
    server_ctx.verify_mode = ssl.CERT_REQUIRED  # require the client cert

    broker = FakeKafkaBroker(mode="modern", ssl_context=server_ctx).start()
    try:
        cfg = make_config(
            broker.port,
            kafka_security_protocol="ssl",
            kafka_ssl_ca=str(certs / "ca.pem"),
            kafka_ssl_cert=str(certs / "client.pem"),
            kafka_ssl_key=str(certs / "client.key"),
        )
        tx = WireKafkaTransport()
        tx.send(cfg, "caraml.reports", b"secure")
        tx.close()
        assert broker.logs.get(("caraml.reports", 0)) == [b"secure"]

        # without a client cert the mTLS handshake must fail
        plain = make_config(
            broker.port,
            kafka_security_protocol="ssl",
            kafka_ssl_ca=str(certs / "ca.pem"),
        )
        tx2 = WireKafkaTransport()
        with pytest.raises(ConnectionError):
            tx2.send(plain, "caraml.reports", b"nope")
    finally:
        broker.stop()


def test_gzip_compressed_batches_decode():
    import gzip as _gzip
    import struct as _struct

    # a record-batch v2 whose records payload is gzip-compressed (attrs=1)
    record_body = (b"\x00" + _varint(0) + _varint(0) + _varint(-1) +
                   _varint(6) + b"zipped" + _varint(0))
    record = _varint(len(record_body)) + record_body
    compressed = _gzip.compress(record)
    after_crc = _struct.pack(">hiqqqhii", 1, 0, 0, 0, -1, -1, -1, 1) + compressed
    crc = crc32c(after_crc)
    batch = _struct.pack(">ibI", -1, 2, crc) + after_crc
    full = _struct.pack(">qi", 0, len(batch)) + batch
    assert _decode_record_batches(full) == [(0, b"zipped")]


# ---------------------------------------------------------------- snappy (C17)


@pytest.fixture
def _snappy_counter():
    kafka_wire.reset_skipped_batches()
    yield
    kafka_wire.reset_skipped_batches()


def _record_batch_with_codec(payload: bytes, attrs: int, n_records: int = 1):
    import struct as _struct

    after_crc = _struct.pack(
        ">hiqqqhii", attrs, 0, 0, 0, -1, -1, -1, n_records
    ) + payload
    crc = crc32c(after_crc)
    batch = _struct.pack(">ibI", -1, 2, crc) + after_crc
    return _struct.pack(">qi", 0, len(batch)) + batch


# a PRE-ENCODED snappy fixture (not produced by our own encoder): literal
# "abcd" + an overlapping back-copy (len 12, offset 4) — the RLE idiom —
# decoding to b"abcdabcdabcdabcd"
SNAPPY_FIXTURE = bytes([16, (4 - 1) << 2]) + b"abcd" + bytes(
    [((12 - 1) << 2) | 2, 4, 0]
)


def test_snappy_raw_block_fixture_decodes():
    assert kafka_wire.snappy_decompress(SNAPPY_FIXTURE) == b"abcdabcdabcdabcd"


def test_snappy_copy1_and_long_literal_forms():
    # copy with 1-byte offset (tag kind 1): literal "abcdefgh" then
    # copy(len=4, offset=8) -> "abcdefghabcd"
    block = bytes([12, (8 - 1) << 2]) + b"abcdefgh" + bytes(
        [((4 - 4) << 2) | 1, 8]
    )
    assert kafka_wire.snappy_decompress(block) == b"abcdefghabcd"
    # 2-byte literal length form (upper-6-bits 61)
    data = bytes(range(256)) * 2
    block = kafka_wire.snappy_compress(data)
    assert kafka_wire.snappy_decompress(block) == data


def test_snappy_roundtrip_through_compressor():
    for payload in (b"", b"x", b"hello snappy " * 50, bytes(range(256)) * 300):
        assert kafka_wire.snappy_decompress(
            kafka_wire.snappy_compress(payload)
        ) == payload


def test_snappy_truncated_and_bad_offset_raise():
    with pytest.raises(kafka_wire.KafkaWireError):
        kafka_wire.snappy_decompress(bytes([16, (8 - 1) << 2]) + b"ab")
    # copy offset beyond what has been produced
    with pytest.raises(kafka_wire.KafkaWireError):
        kafka_wire.snappy_decompress(
            bytes([8, (2 - 1) << 2]) + b"ab" + bytes([((4 - 4) << 2) | 1, 99])
        )


def test_snappy_record_batch_v2_decodes(_snappy_counter):
    record_body = (b"\x00" + _varint(0) + _varint(0) + _varint(-1) +
                   _varint(7) + b"snapped" + _varint(0))
    record = _varint(len(record_body)) + record_body
    full = _record_batch_with_codec(kafka_wire.snappy_compress(record), attrs=2)
    assert _decode_record_batches(full) == [(0, b"snapped")]
    assert kafka_wire.skipped_batch_count() == 0


def test_snappy_xerial_framed_message_set_decodes(_snappy_counter):
    import struct as _struct

    inner = _encode_message_set_v1(b"old-snappy", 1234, offset=5)
    raw = kafka_wire.snappy_compress(inner)
    framed = (b"\x82SNAPPY\x00" + _struct.pack(">ii", 1, 1)
              + _struct.pack(">i", len(raw)) + raw)
    wrapper = _encode_message_set_v1(framed, 1234, offset=5)
    # flip the wrapper's attrs byte to codec 2 (offset: 8 offset + 4 size
    # + 4 crc + 1 magic = attrs at byte 17)
    wrapper = wrapper[:17] + bytes([2]) + wrapper[18:]
    assert _decode_message_set(wrapper) == [(5, b"old-snappy")]
    assert kafka_wire.skipped_batch_count() == 0


def test_lz4_zstd_batches_are_counted_not_silently_dropped(_snappy_counter):
    for attrs, codec in ((3, "lz4"), (4, "zstd")):
        full = _record_batch_with_codec(b"\x00\x01\x02", attrs=attrs)
        assert _decode_record_batches(full) == []
    assert kafka_wire.skipped_batch_count() == 2


def test_corrupt_snappy_batch_is_counted_not_fatal(_snappy_counter):
    full = _record_batch_with_codec(b"\xff\xff\xff\xff", attrs=2)
    assert _decode_record_batches(full) == []  # skipped, not raised
    assert kafka_wire.skipped_batch_count() == 1


def test_skipped_batches_surface_on_metrics_line(_snappy_counter):
    import io as _io
    import json as _json

    from banjax_tpu.decisions.rate_limit import (
        FailedChallengeRateLimitStates,
        RegexRateLimitStates,
    )
    from banjax_tpu.obs.metrics import write_metrics_line

    def metrics_line():
        out = _io.StringIO()
        write_metrics_line(
            out, DynamicDecisionLists(start_sweeper=False),
            RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        )
        return _json.loads(out.getvalue())

    # clean stream: the reference's exact key set, no additive key
    assert "KafkaSkippedBatches" not in metrics_line()
    _decode_record_batches(_record_batch_with_codec(b"\x00", attrs=3))
    assert metrics_line()["KafkaSkippedBatches"] == 1


# ---------------------------------------------------------------- lz4 (codec 3)


def test_lz4_roundtrip_through_compressor():
    for payload in (b"", b"x", b"hello lz4 " * 500, bytes(range(256)) * 400):
        assert kafka_wire.lz4_decompress(
            kafka_wire.lz4_compress(payload)
        ) == payload


def test_lz4_block_with_back_reference_decodes():
    # literals "abcd" + match(offset=4, len=4+4) -> "abcdabcdabcd": the
    # overlapping-copy idiom a real encoder emits for repeats
    blk = bytes([0x44]) + b"abcd" + bytes([0x04, 0x00])
    assert kafka_wire._lz4_decode_block(blk) == b"abcdabcdabcd"
    # extended literal (15 + extra byte) and extended match length forms
    lit = b"x" * 20
    blk = bytes([0xFF, 20 - 15]) + lit + bytes([0x04, 0x00, 15 - 15])
    # token: lit=15(+5)=20, mlen=15(+0)+4=19, offset 4
    out = kafka_wire._lz4_decode_block(blk)
    assert out == lit + b"x" * 19


def test_lz4_xxh32_vectors():
    # reference vectors from the xxHash spec
    assert kafka_wire.xxh32(b"") == 0x02CC5D05
    assert kafka_wire.xxh32(b"Hello World") == 0xB1FD16EE


def test_lz4_truncated_and_bad_offset_raise():
    good = kafka_wire.lz4_compress(b"payload bytes here")
    with pytest.raises(kafka_wire.KafkaWireError):
        kafka_wire.lz4_decompress(good[:10])
    with pytest.raises(kafka_wire.KafkaWireError):
        kafka_wire.lz4_decompress(b"\x00\x01\x02\x03garbage")
    # match offset pointing before the start of the output
    with pytest.raises(kafka_wire.KafkaWireError):
        kafka_wire._lz4_decode_block(bytes([0x14]) + b"a" + bytes([0x09, 0x00]))


def test_lz4_record_batch_v2_decodes(_snappy_counter):
    record_body = (b"\x00" + _varint(0) + _varint(0) + _varint(-1) +
                   _varint(6) + b"lz4win" + _varint(0))
    record = _varint(len(record_body)) + record_body
    full = _record_batch_with_codec(kafka_wire.lz4_compress(record), attrs=3)
    assert _decode_record_batches(full) == [(0, b"lz4win")]
    assert kafka_wire.skipped_batch_count() == 0


def test_lz4_message_set_wrapper_decodes(_snappy_counter):
    inner = _encode_message_set_v1(b"old-lz4", 1234, offset=9)
    wrapper = _encode_message_set_v1(
        kafka_wire.lz4_compress(inner), 1234, offset=9
    )
    wrapper = wrapper[:17] + bytes([3]) + wrapper[18:]  # attrs -> codec 3
    assert _decode_message_set(wrapper) == [(9, b"old-lz4")]
    assert kafka_wire.skipped_batch_count() == 0


def test_corrupt_lz4_and_zstd_still_skip_counted(_snappy_counter):
    # a corrupt lz4 batch is counted + skipped (never fatal); zstd stays
    # skip-counted unconditionally — the KafkaSkippedBatches contract
    assert _decode_record_batches(
        _record_batch_with_codec(b"\x00\x01\x02", attrs=3)
    ) == []
    assert _decode_record_batches(
        _record_batch_with_codec(b"(\xb5/\xfd data", attrs=4)
    ) == []
    assert kafka_wire.skipped_batch_count() == 2


# ------------------------------------------------- kafka -> pipeline routing


def test_kafka_reader_routes_commands_through_pipeline():
    """ROADMAP PR 2 follow-up: with a pipeline wired, the reader admits
    each message into the scheduler's buffer (shared backpressure and
    accounting) and the drain thread dispatches it — decision lists end
    up identical to the inline path."""
    import threading

    from banjax_tpu.pipeline import PipelineScheduler

    cfg = make_config(0)

    class Holder:
        def get(self):
            return cfg

    class ListTransport(kafka_io.KafkaTransport):
        def __init__(self, msgs):
            self.msgs = msgs
            self.done = threading.Event()

        def read_messages(self, config, topic, partition):
            for m in self.msgs:
                yield m
            self.done.set()
            while not self.done.wait(0.05):
                pass  # park: reader keeps iterating until stop()

        def close(self):
            self.done.set()

    msgs = [
        json.dumps({"Name": "challenge_ip", "Value": f"5.6.7.{i}",
                    "host": "example.com"}).encode()
        for i in range(5)
    ] + [b"not json"]

    class NullMatcher:
        def consume_lines(self, lines, now_unix=None):
            return [None for _ in lines]

    sched = PipelineScheduler(lambda: NullMatcher())
    sched.start()
    lists = DynamicDecisionLists(start_sweeper=False)
    transport = ListTransport(msgs)
    reader = kafka_io.KafkaReader(
        Holder(), lists, transport, pipeline=sched
    )
    reader.start()
    assert transport.done.wait(5)
    assert sched.flush(30)
    reader.stop()
    sched.stop()
    for i in range(5):
        decision, _ = lists.check("", f"5.6.7.{i}")
        assert decision is not None and decision.decision == Decision.CHALLENGE
    s = sched.stats
    assert s.command_items == 6  # the bad message is counted too, not lost
    assert s.admitted_lines == s.processed_lines + s.shed_lines
