"""Differential tests: rule compiler + device NFA vs Python re (the oracle).

The acceptance bar for the TPU matcher is byte-identical match decisions
against the CpuMatcher path, which uses Python `re` (itself mirroring the
Go regexp behavior of /root/reference/internal/regex_rate_limiter.go:234).
These tests compile pattern sets with rulec, run the jitted shift-and scan
on the 8-virtual-device CPU backend, and assert the match bitmap equals
re.search on every (pattern, line) pair — the generalization of the
reference's generative stress test
(/root/reference/internal/regex_rate_limiter_test.go:298-360).
"""

import random
import re

import numpy as np
import pytest

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.rulec import (
    UnsupportedPattern,
    compile_rule,
    compile_rules,
)


def run_device_match(patterns, lines, n_shards=1, max_len=128):
    compiled = compile_rules(patterns, n_shards=n_shards)
    params = nfa_jax.match_params(compiled)
    cls_ids, lens, host_eval = encode_for_match(compiled, lines, max_len)
    assert not host_eval.any(), "test lines must be device-evaluable"
    out = nfa_jax.match_batch(params, cls_ids, lens, compiled.n_rules)
    return np.asarray(out), compiled


def assert_matches_re(patterns, lines, n_shards=1):
    matched, compiled = run_device_match(patterns, lines, n_shards=n_shards)
    # every pattern given to this helper must actually compile for the
    # device — a silent host fallback would make the comparison vacuous
    fell_back = [patterns[i] for i in compiled.unsupported]
    assert not fell_back, f"unexpected host fallback: {fell_back}"
    for j, pat in enumerate(patterns):
        rx = re.compile(pat)
        for i, line in enumerate(lines):
            expected = rx.search(line) is not None
            got = bool(matched[i, j])
            assert got == expected, (
                f"pattern {pat!r} line {line!r}: device={got} re={expected}"
            )
    return compiled


LINES = [
    "",
    "a",
    "b",
    "ab",
    "ba",
    "abc",
    "aab",
    "abab",
    "hello world",
    "GET /wp-login.php HTTP/1.1",
    "POST /xmlrpc.php HTTP/1.1",
    "GET / HTTP/1.1",
    "aaaa",
    "xyzzy",
    "0123456789",
    "a-b_c.d",
    "foo  bar",
    "PUT /a/b/c?x=1&y=2",
    "Mozilla/5.0 (X11; Linux x86_64)",
    "....",
    "aXbXc",
    "tab\there",
    "trailing space ",
    " leading",
    "abba",
    "aa",
    "A",
    "AB",
    "Hello World",
]


class TestBasicConstructs:
    def test_literal(self):
        assert_matches_re(["abc", "a", "z"], LINES)

    def test_dot(self):
        assert_matches_re(["a.c", "...", "^.$"], LINES)

    def test_classes(self):
        assert_matches_re(
            [r"[ab]c", r"[^a]b", r"[a-z]+", r"[0-9]{3}", r"[\d]", r"[a-cx-z]"],
            LINES,
        )

    def test_escapes(self):
        assert_matches_re([r"\d+", r"\w+", r"\s", r"\.", r"a\-b", r"\S+"], LINES)

    def test_anchors(self):
        assert_matches_re(
            [r"^a", r"a$", r"^ab$", r"^$", r"^", r"$", r"\Aab", r"ab\Z"], LINES
        )

    def test_alternation(self):
        assert_matches_re([r"a|b", r"ab|ba", r"^(a|b)b$", r"x|", r"(GET|POST) /"], LINES)

    def test_quantifiers(self):
        assert_matches_re(
            [r"a*b", r"a+b", r"a?b", r"a{2}", r"a{2,}", r"a{1,3}b", r"ba{0,2}"],
            LINES,
        )

    def test_star_of_class(self):
        assert_matches_re([r"[ab]*c", r"a[^b]*b", r".*", r".+", r"x.*y"], LINES)

    def test_groups(self):
        assert_matches_re(
            [r"(ab){2}", r"(a|b){2,3}", r"(?:ab)?c", r"((a)(b))", r"(ab){1,3}"],
            LINES,
        )

    def test_nested_quantified_groups(self):
        assert_matches_re([r"(a+)", r"(a*)b", r"(a?){2}b", r"(a|b+){2}"], LINES)

    def test_case_insensitive(self):
        assert_matches_re([r"(?i)hello", r"(?i)a", r"(?i:ab)", r"(?i)[a-z]+"], LINES)

    def test_lazy_quantifiers_same_language(self):
        assert_matches_re([r"a*?b", r"a+?", r"a??b", r"a{1,2}?b"], LINES)

    def test_realistic_rules(self):
        assert_matches_re(
            [
                r"GET /wp-login\.php",
                r"POST /xmlrpc\.php",
                r"(GET|POST) /[a-z-]*\.php",
                r"^GET .* HTTP/1\.1$",
                r"Mozilla/\d+\.\d+",
                r"HTTP/1\.[01]$",
            ],
            LINES,
        )


class TestDegenerateAndUnsupported:
    def test_always_match_short_circuit(self):
        compiled = compile_rules([r".*", r"a"])
        assert compiled.always_match[0]
        assert not compiled.always_match[1]
        # degenerate rules contribute no branches (SURVEY §7.3 hard part 1)
        assert all(r != 0 for r in compiled.branch_rule)

    def test_empty_only(self):
        matched, compiled = run_device_match([r"^$"], ["", "a"])
        assert compiled.empty_only[0]
        assert matched[0, 0] == 1 and matched[1, 0] == 0

    @pytest.mark.parametrize(
        "pattern",
        [r"(ab)*", r"(ab)+x", r"\bword\b", r"(?m)^a", r"a{40}{40}", r"(abc|def){100}"],
    )
    def test_unsupported_fall_back(self, pattern):
        with pytest.raises(UnsupportedPattern):
            compile_rule(pattern)

    def test_unsupported_marked_not_fatal(self):
        compiled = compile_rules([r"a", r"(ab)+", r"b"])
        assert list(compiled.device_ok) == [True, False, True]
        assert 1 in compiled.unsupported

    def test_dead_branch_dropped(self):
        matched, _ = run_device_match([r"a^b", r"a$b"], ["ab", "a^b"])
        assert matched.sum() == 0


class TestSharding:
    def test_sharded_layout_matches_unsharded(self):
        patterns = [r"a+b", r"^GET /", r"[0-9]{2,4}", r"x|yz", r"wp-login"]
        m1, _ = run_device_match(patterns, LINES, n_shards=1)
        m4, c4 = run_device_match(patterns, LINES, n_shards=4)
        assert (m1 == m4).all()
        assert c4.n_shards == 4

    def test_no_branch_straddles_shard_boundary(self):
        patterns = [r"abcdefgh" * 8, r"a{30}", r"[a-z]{33}"]
        c = compile_rules(patterns, n_shards=2)
        span = c.words_per_shard * 32
        # accept bit and its branch start must be in the same shard
        starts = {}
        for k in range(len(c.acc_word)):
            end_bit = int(c.acc_word[k]) * 32 + int(c.acc_mask[k]).bit_length() - 1
            starts[k] = end_bit
        for k, end_bit in starts.items():
            assert end_bit < c.n_shards * span


class TestFuzzDifferential:
    """Generative differential test à la the reference's TestPerSiteRegexStress."""

    def test_random_patterns_vs_re(self):
        rng = random.Random(20260729)
        alphabet = "abxy01 /."

        def gen_atom(depth):
            r = rng.random()
            if r < 0.35:
                return re.escape(rng.choice(alphabet))
            if r < 0.5:
                return rng.choice([r"\d", r"\w", r"[ab]", r"[^x]", "."])
            if r < 0.6 and depth < 2:
                return "(" + gen_pattern(depth + 1) + ")"
            return re.escape(rng.choice(alphabet))

        def gen_piece(depth):
            atom = gen_atom(depth)
            r = rng.random()
            if r < 0.2:
                return atom + rng.choice(["*", "+", "?"])
            if r < 0.25:
                return atom + "{%d,%d}" % (rng.randint(0, 2), rng.randint(2, 4))
            return atom

        def gen_pattern(depth=0):
            seq = "".join(gen_piece(depth) for _ in range(rng.randint(1, 5)))
            if rng.random() < 0.2:
                seq = seq + "|" + "".join(gen_piece(depth) for _ in range(rng.randint(1, 3)))
            return seq

        patterns = []
        while len(patterns) < 60:
            p = gen_pattern()
            if rng.random() < 0.15:
                p = "^" + p
            if rng.random() < 0.15:
                p = p + "$"
            try:
                re.compile(p)
                compile_rule(p)
            except UnsupportedPattern:
                continue
            except re.error:
                continue
            patterns.append(p)

        lines = [
            "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 24)))
            for _ in range(120)
        ]
        assert_matches_re(patterns, lines, n_shards=1)
        assert_matches_re(patterns, lines, n_shards=4)


def test_align_branches_word_alignment_and_equivalence():
    """align_branches=True: no <=32-position branch straddles a word, the
    packed tensors still match the dense packing bit-for-bit through the
    matcher, and carry_free is reported correctly."""
    import numpy as np

    from banjax_tpu.matcher import nfa_jax
    from banjax_tpu.matcher.encode import encode_for_match
    from banjax_tpu.matcher.rulec import compile_rule, pack_programs

    pats = [r"GET /wp-login\.php", r"/xmlrpc\.php", r"(?i)sqlmap|nikto",
            r"POST /login[0-9]{1,3}", r"^HEAD /x\.cgi$"]
    programs = [compile_rule(p) for p in pats]
    dense = pack_programs(programs)
    aligned = pack_programs(programs, align_branches=True)
    assert aligned.carry_free
    assert aligned.n_words >= dense.n_words  # alignment may pad
    lines = ["GET x GET /wp-login.php -", "POST a POST /login77 -",
             "NIKTO scan", "HEAD /x.cgi", "benign"]
    for packed in (dense, aligned):
        cls_ids, lens, _ = encode_for_match(packed, lines, 64)
        got = np.asarray(nfa_jax.match_batch(
            nfa_jax.match_params(packed), cls_ids, lens, packed.n_rules
        ))
        import re as _re

        for j, p in enumerate(pats):
            for i, line in enumerate(lines):
                assert bool(got[i, j]) == bool(_re.search(p, line)), (p, line)


def test_align_branches_long_branch_not_carry_free():
    """A >32-position branch must straddle words: carry_free stays False
    so the kernel keeps its cross-word carry."""
    from banjax_tpu.matcher.rulec import compile_rule, pack_programs

    packed = pack_programs(
        [compile_rule("a" * 40)], align_branches=True
    )
    assert not packed.carry_free
