"""Native shm decision table: C/Python parity, refusal, fail-open.

The serving fast path's data plane (native/decisiontable.c wrapped by
native/decisiontable.py) and its in-process fallback must agree on
every semantic the fast path relies on: bounded capacity with REFUSAL
(never eviction of a live entry), expired-slot reuse, the session
counter's zero clamp, and torn-read fail-open.  The mirror tests pin
the DynamicDecisionLists -> table contract: every mutation lands in the
table under the list's lock, and a broken table only ever counts a
mirror error — it never surfaces to the caller.
"""

import time

import pytest

from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.httpapi.serve_stats import get_stats as serve_stats
from banjax_tpu.native import decisiontable as dt


@pytest.fixture(params=["native", "py"])
def table(request):
    if request.param == "native":
        if not dt.available():
            pytest.skip("native decisiontable unavailable (no C compiler)")
        t = dt.ShmDecisionTable(capacity=8)
        yield t
        t.close()
        t.unlink()
    else:
        yield dt.PyDecisionTable(capacity=8)


def test_put_get_roundtrip(table):
    now = time.time()
    assert table.put("1.2.3.4", int(Decision.ALLOW), now + 60,
                     from_baskerville=False, domain="example.com")
    assert table.put("5.6.7.8", int(Decision.NGINX_BLOCK), now + 90,
                     from_baskerville=True, domain="other.com")
    decision, expires, bask = table.get("1.2.3.4")
    assert decision == int(Decision.ALLOW)
    assert expires == pytest.approx(now + 60, abs=1e-6)
    assert bask is False
    decision, expires, bask = table.get("5.6.7.8")
    assert decision == int(Decision.NGINX_BLOCK)
    assert bask is True
    assert table.get("9.9.9.9") is None
    assert len(table) == 2


def test_overwrite_delete_clear(table):
    now = time.time()
    table.put("1.2.3.4", int(Decision.CHALLENGE), now + 60)
    table.put("1.2.3.4", int(Decision.IPTABLES_BLOCK), now + 120)
    decision, expires, _ = table.get("1.2.3.4")
    assert decision == int(Decision.IPTABLES_BLOCK)
    assert expires == pytest.approx(now + 120, abs=1e-6)
    assert len(table) == 1

    assert table.delete("1.2.3.4") is True
    assert table.delete("1.2.3.4") is False  # already gone
    assert table.get("1.2.3.4") is None

    table.put("2.2.2.2", int(Decision.ALLOW), now + 60)
    table.clear()
    assert len(table) == 0
    assert table.get("2.2.2.2") is None


def test_capacity_rounds_to_power_of_two():
    t = dt.PyDecisionTable(capacity=5)
    assert t.capacity == 8
    if dt.available():
        n = dt.ShmDecisionTable(capacity=5)
        assert n.capacity == 8
        n.close()
        n.unlink()


def test_full_table_refuses_and_counts(table):
    """A full table REFUSES new inserts (counted) rather than evicting a
    live entry — a refused IP simply rides the chain."""
    now = time.time()
    for i in range(table.capacity):
        assert table.put(f"10.0.0.{i}", int(Decision.ALLOW), now + 3600,
                         now=now)
    assert len(table) == table.capacity
    assert table.dropped == 0

    assert table.put("10.0.1.1", int(Decision.ALLOW), now + 3600,
                     now=now) is False
    assert table.dropped == 1
    assert table.get("10.0.1.1") is None
    # every pre-existing entry survived the refusal
    for i in range(table.capacity):
        assert table.get(f"10.0.0.{i}") is not None

    # overwriting an EXISTING key is not an insert — still allowed
    assert table.put("10.0.0.0", int(Decision.NGINX_BLOCK), now + 7200,
                     now=now)
    assert table.get("10.0.0.0")[0] == int(Decision.NGINX_BLOCK)


def test_full_table_reuses_expired_slot(table):
    now = time.time()
    for i in range(table.capacity - 1):
        table.put(f"10.0.0.{i}", int(Decision.ALLOW), now + 3600, now=now)
    table.put("10.9.9.9", int(Decision.ALLOW), now - 5, now=now)  # expired

    # full, but one entry is past its expiry: the new insert takes it
    assert table.put("10.0.2.2", int(Decision.CHALLENGE), now + 60, now=now)
    assert table.get("10.0.2.2") is not None
    assert table.dropped == 0


def test_session_counter_clamps_at_zero(table):
    assert table.session_count() == 0
    assert table.session_add(2) == 2
    assert table.session_add(1) == 3
    assert table.session_add(-1) == 2
    # the counter never goes negative: a worker that decrements on
    # lazy-expiry after a primary restart must not wedge the guard open
    assert table.session_add(-10) == 0
    assert table.session_count() == 0


def test_long_and_empty_keys(table):
    now = time.time()
    long_ip = "x" * 200  # truncated to KEY_MAX internally
    assert table.put(long_ip, int(Decision.ALLOW), now + 60)
    got = table.get(long_ip)
    # Py keeps full keys; native truncates — both must roundtrip
    assert got is not None and got[0] == int(Decision.ALLOW)
    assert table.put("", int(Decision.CHALLENGE), now + 60)
    assert table.get("")[0] == int(Decision.CHALLENGE)


def test_closed_table_fails_open(table):
    now = time.time()
    table.put("1.2.3.4", int(Decision.ALLOW), now + 60)
    table.close()
    assert table.get("1.2.3.4") is None
    assert table.put("5.6.7.8", int(Decision.ALLOW), now + 60) is False
    assert len(table) == 0
    if isinstance(table, dt.ShmDecisionTable):
        table._shm = __import__(
            "multiprocessing.shared_memory", fromlist=["SharedMemory"]
        ).SharedMemory(create=True, size=1024)  # give unlink a target
        table.unlink()


# ---------------------------------------------------------- native-only


@pytest.fixture
def native_table():
    if not dt.available():
        pytest.skip("native decisiontable unavailable (no C compiler)")
    t = dt.ShmDecisionTable(capacity=64)
    yield t
    t.close()
    t.unlink()


def test_attach_by_name_shares_entries(native_table):
    """Worker attach: a second handle on the same shm name reads the
    owner's entries (the fastserve worker path)."""
    now = time.time()
    native_table.put("1.2.3.4", int(Decision.ALLOW), now + 60)
    reader = dt.ShmDecisionTable(name=native_table.name)
    try:
        assert reader.capacity == native_table.capacity
        assert reader.owner is False
        got = reader.get("1.2.3.4")
        assert got is not None and got[0] == int(Decision.ALLOW)
        # and writes through either handle are visible to the other
        native_table.put("5.6.7.8", int(Decision.NGINX_BLOCK), now + 60)
        assert reader.get("5.6.7.8")[0] == int(Decision.NGINX_BLOCK)
        assert reader.session_count() == native_table.session_count()
    finally:
        reader.close()


def test_attach_rejects_foreign_segment():
    if not dt.available():
        pytest.skip("native decisiontable unavailable (no C compiler)")
    from multiprocessing import shared_memory

    shm = shared_memory.SharedMemory(create=True, size=4096)
    try:
        with pytest.raises(RuntimeError):
            dt.ShmDecisionTable(name=shm.name)
    finally:
        shm.close()
        shm.unlink()


def test_wedged_slot_reads_as_miss(native_table):
    """A write wedged mid-flight (odd seqlock) must read as a MISS —
    the chain serves the request — and recover once the writer lands."""
    now = time.time()
    native_table.put("1.2.3.4", int(Decision.ALLOW), now + 60)
    native_table._test_wedge("1.2.3.4")
    try:
        assert native_table.get("1.2.3.4") is None
    finally:
        native_table._test_unwedge("1.2.3.4")
    assert native_table.get("1.2.3.4")[0] == int(Decision.ALLOW)


def test_create_factory_fallback_paths():
    t = dt.create_decision_table(capacity=16)
    try:
        assert t is not None
        assert t.capacity == 16
    finally:
        t.close()
        t.unlink()
    # attach-by-name is native-only: with a bogus name the factory
    # returns None (the worker serves through the chain) instead of a
    # Py table that would silently shadow the primary's
    assert dt.create_decision_table(name="bogus-nonexistent-seg") is None


# ---------------------------------------------------------- mirror hooks


@pytest.fixture
def mirrored():
    stats = serve_stats()
    stats.reset()
    lists = DynamicDecisionLists(start_sweeper=False)
    table = dt.PyDecisionTable(capacity=32)
    lists.set_mirror(table)
    yield lists, table
    lists.close()
    stats.reset()


def test_mirror_update_and_remove(mirrored):
    lists, table = mirrored
    now = time.time()
    lists.update("1.2.3.4", now + 60, Decision.CHALLENGE, False, "example.com")
    assert table.get("1.2.3.4")[0] == int(Decision.CHALLENGE)

    # monotonic severity: a weaker decision neither updates nor mirrors
    lists.update("1.2.3.4", now + 999, Decision.ALLOW, False, "example.com")
    decision, expires, _ = table.get("1.2.3.4")
    assert decision == int(Decision.CHALLENGE)
    assert expires == pytest.approx(now + 60, abs=1e-6)

    lists.remove_by_ip("1.2.3.4")
    assert table.get("1.2.3.4") is None


def test_mirror_lazy_expiry_and_clear(mirrored):
    lists, table = mirrored
    now = time.time()
    lists.update("1.2.3.4", now - 1, Decision.NGINX_BLOCK, False, "d")
    assert table.get("1.2.3.4") is not None
    # check() lazily deletes the expired entry — mirrored
    ed, ok = lists.check("", "1.2.3.4")
    assert ed is not None and ok is False
    assert table.get("1.2.3.4") is None

    lists.update("5.6.7.8", now + 60, Decision.CHALLENGE, False, "d")
    lists.clear()
    assert len(table) == 0


def test_mirror_session_count(mirrored):
    lists, table = mirrored
    now = time.time()
    lists.update_by_session_id("1.1.1.1", "sess-a", now + 60,
                               Decision.NGINX_BLOCK, False, "d")
    assert table.session_count() == 1
    # re-inserting the same session id does not double-count
    lists.update_by_session_id("1.1.1.1", "sess-a", now + 90,
                               Decision.IPTABLES_BLOCK, False, "d")
    assert table.session_count() == 1

    lists.update_by_session_id("2.2.2.2", "sess-b", now - 1,
                               Decision.NGINX_BLOCK, False, "d")
    assert table.session_count() == 2
    # lazy expiry of the session entry decrements the mirror count
    ed, ok = lists.check("sess-b", "2.2.2.2")
    assert ed is not None and ok is False
    assert table.session_count() == 1


def test_broken_mirror_counts_never_raises(mirrored):
    lists, _ = mirrored

    class Broken:
        def put(self, *a, **k):
            raise RuntimeError("shm gone")

        def delete(self, *a, **k):
            raise RuntimeError("shm gone")

        def session_add(self, *a, **k):
            raise RuntimeError("shm gone")

        def clear(self):
            raise RuntimeError("shm gone")

    lists.set_mirror(Broken())
    before = serve_stats().mirror_errors_total
    now = time.time()
    lists.update("1.2.3.4", now + 60, Decision.CHALLENGE, False, "d")
    lists.remove_by_ip("1.2.3.4")
    lists.update_by_session_id("1.1.1.1", "s", now + 60,
                               Decision.NGINX_BLOCK, False, "d")
    lists.clear()
    # the authority dict kept working; every failure was only counted
    assert serve_stats().mirror_errors_total == before + 4
