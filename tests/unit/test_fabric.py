"""Fabric building blocks: hash ring, wire frames, dedupe/replication,
router ledger, and the schema-stability contract for every new
registry family and config key (ISSUE 15)."""

import io
import json
import socket
import threading

import pytest

from banjax_tpu.config.schema import Config, config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.fabric import wire
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.fabric.node import FabricNode
from banjax_tpu.fabric.peer import PeerClient, PeerUnavailable
from banjax_tpu.fabric.replication import (
    DecisionReplicator,
    FabricDeduper,
    ReplicatingBanner,
)
from banjax_tpu.fabric.router import FabricRouter, ip_of_line
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.obs import registry
from banjax_tpu.obs.exposition import parse_text_format, render_prometheus
from banjax_tpu.obs.metrics import write_metrics_line
from banjax_tpu.scenarios.shapes import RULES_YAML

# ---------------------------------------------------------------------------
# consistent-hash ring
# ---------------------------------------------------------------------------


def test_ring_is_deterministic_across_instances():
    a = ConsistentHashRing(["w0", "w1", "w2"], vnodes=64)
    b = ConsistentHashRing(["w2", "w0", "w1"], vnodes=64)  # order-free
    keys = [f"10.{i >> 8}.{i & 255}.7" for i in range(512)]
    assert [a.owner(k) for k in keys] == [b.owner(k) for k in keys]


def test_ring_partition_covers_every_key_exactly_once():
    ring = ConsistentHashRing(["w0", "w1", "w2"], vnodes=32)
    keys = [f"192.0.{i}.1" for i in range(200)]
    parts = ring.partition(keys)
    seen = sorted(i for idxs in parts.values() for i in idxs)
    assert seen == list(range(len(keys)))


def test_ring_exclusion_moves_only_the_dead_nodes_keys():
    """Killing one node hands ONLY its keys to successors; everyone
    else's ownership is untouched — the zero-reshuffle property the
    takeover leans on."""
    ring = ConsistentHashRing(["w0", "w1", "w2"], vnodes=64)
    keys = [f"172.16.{i >> 8}.{i & 255}" for i in range(1024)]
    before = {k: ring.owner(k) for k in keys}
    after = {k: ring.owner(k, alive={"w0", "w1"}) for k in keys}
    for k in keys:
        if before[k] != "w2":
            assert after[k] == before[k], k
        else:
            assert after[k] in ("w0", "w1"), k
    # and a rejoin restores the exact original ownership
    assert {k: ring.owner(k) for k in keys} == before


def test_ring_join_steals_only_from_successors_and_stays_balanced():
    """Satellite 3 (ISSUE 16): inserting a node moves ONLY the keys the
    newcomer now owns — every key it does NOT own keeps its exact old
    owner, i.e. the joiner steals exclusively from its ring successors
    and never shuffles ownership between pre-existing members.  The
    post-join split also stays balanced at the production vnode count."""
    ids = ["w0", "w1", "w2"]
    ring = ConsistentHashRing(ids, vnodes=64)
    grown = ConsistentHashRing(ids + ["w3"], vnodes=64)
    keys = [f"172.16.{i >> 8}.{i & 255}" for i in range(2048)]
    moved = 0
    for k in keys:
        before, after = ring.owner(k), grown.owner(k)
        if after != before:
            assert after == "w3", (k, before, after)
            moved += 1
    assert 0 < moved < len(keys)  # took some keys, not everything
    # the joiner's share is its fair fraction of the moved mass
    fr = grown.ownership_fractions(samples=4096)
    assert set(fr) == {"w0", "w1", "w2", "w3"}
    assert abs(sum(fr.values()) - 1.0) < 1e-9
    # ownership-balance bound at vnodes=64: everyone holds between a
    # third and twice their fair share (generous band: hash variance)
    assert all(0.25 / 3 < f < 0.5 for f in fr.values()), fr


def test_ring_rejects_degenerate_shapes():
    with pytest.raises(ValueError):
        ConsistentHashRing([])
    with pytest.raises(ValueError):
        ConsistentHashRing(["w0"], vnodes=0)
    ring = ConsistentHashRing(["w0", "w1"])
    with pytest.raises(ValueError):
        ring.owner("1.2.3.4", alive=set())


def test_ring_ownership_fractions_sum_to_one():
    ring = ConsistentHashRing(["w0", "w1", "w2", "w3"], vnodes=64)
    fr = ring.ownership_fractions(samples=2048)
    assert abs(sum(fr.values()) - 1.0) < 1e-9
    assert set(fr) == {"w0", "w1", "w2", "w3"}
    # vnodes keep the split roughly even (generous band: hash variance)
    assert all(0.05 < f < 0.6 for f in fr.values()), fr


def test_ip_of_line_extracts_reference_field_two():
    assert ip_of_line("1722.5 9.9.9.9 GET h GET / HTTP/1.1 ua -") == "9.9.9.9"
    assert ip_of_line("weird") == "weird"  # degenerate: hash the line


# ---------------------------------------------------------------------------
# wire frames
# ---------------------------------------------------------------------------


def test_wire_roundtrip_over_socketpair():
    a, b = socket.socketpair()
    try:
        wire.send_frame(a, wire.T_LINES, {"lines": ["x", "y"], "route": True})
        ftype, payload = wire.recv_frame(b)
        assert ftype == wire.T_LINES
        assert payload == {"lines": ["x", "y"], "route": True}
        wire.send_frame(b, wire.T_ACK, {})
        assert wire.recv_frame(a) == (wire.T_ACK, {})
    finally:
        a.close()
        b.close()


def test_wire_oversized_and_torn_frames_fail_loudly():
    a, b = socket.socketpair()
    try:
        with pytest.raises(wire.FrameError):
            wire.send_frame(a, wire.T_LINES, {"pad": "x" * wire.MAX_FRAME_BYTES})
        # oversized length header on the read side
        a.sendall(wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1, wire.T_LINES))
        with pytest.raises(wire.FrameError):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()
    # torn mid-frame: peer closes after the header
    a, b = socket.socketpair()
    try:
        a.sendall(wire._HEADER.pack(100, wire.T_LINES))
        a.close()
        with pytest.raises(wire.FrameError):
            wire.recv_frame(b)
    finally:
        b.close()


def test_wire_non_object_payload_rejected():
    a, b = socket.socketpair()
    try:
        body = json.dumps([1, 2, 3]).encode()
        a.sendall(wire._HEADER.pack(1 + len(body), wire.T_ACK) + body)
        with pytest.raises(wire.FrameError):
            wire.recv_frame(b)
    finally:
        a.close()
        b.close()


# ---------------------------------------------------------------------------
# replication + dedupe
# ---------------------------------------------------------------------------


class _MemTransport:
    def __init__(self, fail_times: int = 0):
        self.sent = []
        self.fail_times = fail_times

    def send(self, config, topic, value):
        if self.fail_times > 0:
            self.fail_times -= 1
            raise OSError("injected produce failure")
        assert isinstance(value, bytes)  # the wire transport contract
        self.sent.append((topic, value))


def test_replicator_applies_locally_then_produces_tagged_bytes():
    applied = []
    tr = _MemTransport()
    rep = DecisionReplicator(
        "w0", tr, "cmds", local_apply=applied.append
    )
    rep.publish("9.9.9.9", Decision.NGINX_BLOCK, "site.com")
    rep.publish("8.8.8.8", Decision.CHALLENGE, "")
    assert [c["Value"] for c in applied] == ["9.9.9.9", "8.8.8.8"]
    cmds = [json.loads(v) for _, v in tr.sent]
    assert [c["Name"] for c in cmds] == ["block_ip", "challenge_ip"]
    assert all(c["fabric_origin"] == "w0" for c in cmds)
    assert [c["fabric_seq"] for c in cmds] == [1, 2]  # monotonic


def test_replicator_retries_once_then_counts_and_drops():
    stats = FabricStats()
    rep = DecisionReplicator(
        "w0", _MemTransport(fail_times=99), "cmds", stats=stats
    )
    rep.publish("9.9.9.9", Decision.NGINX_BLOCK, "d")
    assert stats.peek()["FabricReplicationErrors"] == 2  # both attempts
    assert stats.peek()["FabricReplicatedDecisions"] == 0


def test_deduper_suppresses_own_origin_and_duplicates():
    applied = []
    stats = FabricStats()
    dd = FabricDeduper("w0", applied.append, stats=stats)
    own = {"Name": "block_ip", "Value": "1.1.1.1",
           "fabric_origin": "w0", "fabric_seq": 1}
    peer = {"Name": "block_ip", "Value": "2.2.2.2",
            "fabric_origin": "w1", "fabric_seq": 1}
    untagged = {"Name": "block_ip", "Value": "3.3.3.3"}
    dd.dispatch(json.dumps(own))
    dd.dispatch(json.dumps(peer).encode())  # bytes and str both accepted
    dd.dispatch(json.dumps(peer))           # duplicate (origin, seq)
    dd.dispatch(json.dumps(untagged))       # operator curl: passthrough
    dd.dispatch(b"not json")                # must not raise
    assert [c["Value"] for c in applied] == ["2.2.2.2", "3.3.3.3"]
    assert stats.peek()["FabricDuplicatesSuppressed"] == 2
    assert stats.peek()["FabricReplicatedApplied"] == 1


def test_deduper_seen_set_is_bounded():
    dd = FabricDeduper("w0", lambda cmd: None, max_seen=8)
    for seq in range(64):
        dd.dispatch(json.dumps(
            {"Name": "block_ip", "Value": "1.1.1.1",
             "fabric_origin": "w1", "fabric_seq": seq}
        ))
    assert len(dd._seen) == 8


def test_replicating_banner_passes_through_and_publishes():
    class Inner:
        def __init__(self):
            self.calls = []

        def ban_or_challenge_ip(self, config, ip, decision, domain):
            self.calls.append(ip)

        def log_regex_ban(self, *a):
            return "host-local"

    tr = _MemTransport()
    inner = Inner()
    rb = ReplicatingBanner(inner, DecisionReplicator("w0", tr, "cmds"))
    rb.ban_or_challenge_ip(None, "9.9.9.9", Decision.NGINX_BLOCK, "d")
    assert inner.calls == ["9.9.9.9"]
    assert len(tr.sent) == 1
    assert rb.log_regex_ban() == "host-local"  # __getattr__ delegation


# ---------------------------------------------------------------------------
# router ledger + takeover (fake peers, no sockets)
# ---------------------------------------------------------------------------


class _FakePeer:
    """Duck-types PeerClient.request; flips to dead on demand."""

    def __init__(self, peer_id):
        self.peer_id = peer_id
        self.host, self.port = "127.0.0.1", 0  # describe() reads these
        self.breaker = type("B", (), {"state": "closed"})()
        self.lines = []
        self.dead = False

    def request(self, ftype, payload):
        if self.dead:
            raise PeerUnavailable(f"{self.peer_id} dead")
        self.lines.extend(payload["lines"])
        return wire.T_ACK, {"n": len(payload["lines"])}

    def connect_to(self, host, port):
        self.dead = False


def _router(n=3, grace_ms=0.0):
    ids = [f"w{i}" for i in range(n)]
    ring = ConsistentHashRing(ids, vnodes=64)
    local = []
    peers = {
        wid: (None if wid == "w0" else _FakePeer(wid)) for wid in ids
    }
    stats = FabricStats()
    r = FabricRouter(
        "w0", ring, peers, lambda ls: local.extend(ls) or len(ls),
        stats=stats, takeover_grace_ms=grace_ms,
    )
    return r, local, peers, stats


def _lines(n):
    return [f"1000.0 10.1.{i >> 8}.{i & 255} GET h GET / HTTP/1.1 ua -"
            for i in range(n)]


def test_router_disposition_sums_to_len_and_matches_ring():
    r, local, peers, stats = _router()
    lines = _lines(300)
    out = r.route(lines)
    assert out["local"] + out["forwarded"] + out["shed"] == len(lines)
    assert out["shed"] == 0
    assert len(local) == out["local"]
    assert sum(len(p.lines) for p in peers.values() if p) == out["forwarded"]
    # every line landed where the ring says its IP lives
    for wid, peer in peers.items():
        if peer:
            assert all(
                r.ring.owner(ip_of_line(ln)) == wid for ln in peer.lines
            )


def test_router_dead_peer_triggers_takeover_and_journal_replay():
    r, local, peers, stats = _router()
    first = _lines(200)
    r.route(first)
    held_by_w1 = list(peers["w1"].lines)
    assert held_by_w1  # the scenario must actually exercise w1
    peers["w1"].dead = True
    more = _lines(50)
    out = r.route(more)  # detection happens inside this route call
    assert out["local"] + out["forwarded"] + out["shed"] == len(more)
    peek = stats.peek()
    assert peek["FabricTakeovers"] == 1
    assert stats.last_takeover["peer"] == "w1"
    # the whole w1 journal was replayed through routing to survivors
    assert peek["FabricReplayedLines"] == len(held_by_w1)
    survivors = set(local) | set(peers["w2"].lines)
    assert set(held_by_w1) <= survivors  # zero lost lines
    # ledger: local + forwarded + shed == routed + replayed
    assert (
        peek["FabricLocalLines"] + peek["FabricForwardedLines"]
        + peek["FabricShedLines"]
        == len(first) + len(more) + peek["FabricReplayedLines"]
    )


def test_router_all_peers_dead_sheds_counted_never_silent():
    r, local, peers, stats = _router(n=2)
    peers["w1"].dead = True
    r.route(_lines(40))
    # single survivor: everything is local now, nothing shed
    assert stats.peek()["FabricShedLines"] == 0
    r.alive.clear()  # no alive owner at all (shutdown race shape)
    out = r.route(_lines(8))
    assert out == {"local": 0, "forwarded": 0, "shed": 8, "skipped": 0}
    assert stats.peek()["FabricShedLines"] == 8


def test_router_replay_skips_lines_owned_by_survivors_no_double_ban():
    """Dedupe regression (n2 kill precision 0.969697 in PERF round 16):
    the driver's takeover-replay re-sends whole acked chunks, but an
    acked chunk was *fully routed* — survivor-owned lines in it were
    already processed by their (still alive) owners.  Replaying those
    lines double-counts them and can push an IP over a rate threshold
    twice -> duplicate ban.  Replay must re-route only lines whose
    pre-death owner actually crashed."""
    r, local, peers, stats = _router()
    chunk = _lines(120)
    by_owner = {}
    for ln in chunk:
        by_owner.setdefault(r.ring.owner(ip_of_line(ln)), []).append(ln)
    assert by_owner.get("w1") and (by_owner.get("w0") or by_owner.get("w2"))
    r.route(chunk)  # the chunk was acked: every line reached its owner
    processed_before = len(local) + len(peers["w2"].lines)

    peers["w1"].dead = True
    # isolate the DRIVER-side replay: in production the driver journal
    # (whole chunks fed to the victim) and this node's forward journal
    # (victim-owned lines from chunks fed to THIS node) cover disjoint
    # lines, so drop the forward journal before declaring death
    r._journal["w1"].clear()
    r.mark_dead("w1", reason="SIGKILL")
    stats_before = stats.peek()
    out = r.route(chunk, replay=True)  # driver journal replays the chunk

    victim_owned = len(by_owner.get("w1", []))
    survivor_owned = len(chunk) - victim_owned
    assert out["skipped"] == survivor_owned
    assert out["local"] + out["forwarded"] + out["shed"] == victim_owned
    peek = stats.peek()
    assert (
        peek["FabricReplaySkippedLines"]
        - stats_before["FabricReplaySkippedLines"]
        == survivor_owned
    )
    # survivors saw every survivor-owned line exactly once in total:
    # only the victim's lines were processed a second time
    processed_after = len(local) + len(peers["w2"].lines)
    assert processed_after - processed_before == victim_owned
    # full ledger with the skip column
    assert (
        peek["FabricLocalLines"] + peek["FabricForwardedLines"]
        + peek["FabricShedLines"] + peek["FabricReplaySkippedLines"]
        == 2 * len(chunk)
    )


def test_router_replay_keeps_crashed_owned_lines_recall_intact():
    """The skip filter must never touch recall: every line whose
    pre-death owner crashed is re-routed to a survivor."""
    r, local, peers, stats = _router()
    chunk = _lines(120)
    victim_lines = [
        ln for ln in chunk if r.ring.owner(ip_of_line(ln)) == "w1"
    ]
    r.route(chunk)
    peers["w1"].dead = True
    r._journal["w1"].clear()  # isolate the driver-side replay (above)
    r.mark_dead("w1", reason="SIGKILL")
    local_before = set(local)
    w2_before = set(peers["w2"].lines)
    r.route(chunk, replay=True)
    replayed_to = (set(local) - local_before) | (
        set(peers["w2"].lines) - w2_before
    )
    assert replayed_to == set(victim_lines)


def test_router_replay_with_no_crashed_peers_is_passthrough():
    """The dedupe filter keys on the crashed set.  With nobody crashed
    (all peers healthy, or the victim already rejoined via mark_alive)
    a replay routes everything — PR 11's legacy replay shape, which
    graceful-leave and rebalance paths still rely on."""
    r, local, peers, stats = _router()
    out = r.route(_lines(60), replay=True)
    assert out["skipped"] == 0
    assert out["local"] + out["forwarded"] + out["shed"] == 60
    # rejoin clears the crashed set again
    peers["w1"].dead = True
    r.mark_dead("w1", reason="test")
    peers["w1"].dead = False
    r.mark_alive("w1", host="127.0.0.1", port=1)
    out = r.route(_lines(60), replay=True)
    assert out["skipped"] == 0


def test_router_non_replay_route_never_skips():
    r, local, peers, stats = _router()
    r.mark_dead("w1", reason="test")
    peers["w1"].dead = True
    out = r.route(_lines(80))  # fresh traffic, not a replay
    assert out["skipped"] == 0
    assert out["local"] + out["forwarded"] + out["shed"] == 80


def test_router_mark_alive_is_pure_membership_no_replay():
    r, local, peers, stats = _router()
    r.route(_lines(200))
    peers["w1"].dead = True
    r.mark_dead("w1", reason="test")
    replayed_after_takeover = stats.peek()["FabricReplayedLines"]
    r.mark_alive("w1", host="127.0.0.1", port=1)
    assert stats.peek()["FabricReplayedLines"] == replayed_after_takeover
    assert "w1" in r.alive
    d = r.describe()
    assert d["peers"]["w1"]["alive"] is True
    assert d["last_takeover"]["peer"] == "w1"


def test_router_mark_dead_is_nonblocking_and_deadline_polled():
    """Satellite 1 (ISSUE 16): mark_dead with a nonzero grace window
    must return immediately (the grace is a deadline, not a sleep) and
    routing must stay live during the window; the journal replay fires
    from the route()-entry poll once the deadline passes."""
    now = [1000.0]
    parked = threading.Event()  # grace thread parks here forever
    ids = ["w0", "w1", "w2"]
    ring = ConsistentHashRing(ids, vnodes=64)
    local = []
    peers = {"w0": None, "w1": _FakePeer("w1"), "w2": _FakePeer("w2")}
    stats = FabricStats()
    r = FabricRouter(
        "w0", ring, peers, lambda ls: local.extend(ls) or len(ls),
        stats=stats, takeover_grace_ms=10_000.0,
        clock=lambda: now[0], sleep=lambda s: parked.wait(30.0),
    )
    try:
        r.route(_lines(200))
        held = list(peers["w1"].lines)
        assert held
        peers["w1"].dead = True
        import time as _time
        t0 = _time.monotonic()
        r.mark_dead("w1", reason="test")
        assert _time.monotonic() - t0 < 1.0  # no 10s stall
        assert r.takeover_pending("w1")
        assert stats.peek()["FabricTakeovers"] == 0  # replay deferred
        # routing stays live mid-window: w1's keys reroute, nothing shed
        out = r.route(_lines(30))
        assert out["shed"] == 0
        assert out["local"] + out["forwarded"] == 30
        assert r.takeover_pending("w1")  # still inside the window
        now[0] += 11.0  # the deadline passes
        r.route(_lines(5))  # entry poll completes the takeover
        assert not r.takeover_pending()
        peek = stats.peek()
        assert peek["FabricTakeovers"] == 1
        assert peek["FabricReplayedLines"] == len(held)
        assert stats.last_takeover["peer"] == "w1"
    finally:
        parked.set()


def test_router_poll_completes_takeover_without_traffic():
    """The gossip tick calls poll(): a takeover completes even when no
    further route() call ever arrives (quiet-fleet death)."""
    now = [0.0]
    parked = threading.Event()
    r, local, peers, stats = _router()
    r._clock, r._sleep = (lambda: now[0]), (lambda s: parked.wait(30.0))
    r.takeover_grace_s = 5.0
    try:
        r.route(_lines(120))
        peers["w1"].dead = True
        r.mark_dead("w1", reason="test")
        r.poll()
        assert r.takeover_pending("w1")  # deadline not reached
        now[0] += 6.0
        r.poll()
        assert not r.takeover_pending()
        assert stats.peek()["FabricTakeovers"] == 1
    finally:
        parked.set()


def test_router_add_node_inserts_live_and_routes_to_joiner():
    """add_node rebuilds the ring with the joiner included; subsequent
    routing sends the stolen ranges to it, and a re-add of an existing
    member degrades to mark_alive (no ring rebuild)."""
    r, local, peers, stats = _router()
    before_ids = r.ring.node_ids
    owner_before = {f"10.9.{i}.1": r.ring.owner(f"10.9.{i}.1")
                    for i in range(128)}
    joiner = _FakePeer("w3")
    r.add_node("w3", joiner)
    assert "w3" in r.ring.node_ids and "w3" in r.alive
    # exclusivity: any key that moved, moved to the joiner
    for k, before in owner_before.items():
        after = r.ring.owner(k)
        assert after == before or after == "w3", (k, before, after)
    lines = _lines(400)
    out = r.route(lines)
    assert out["shed"] == 0
    assert joiner.lines  # the joiner actually owns (and receives) keys
    assert all(r.ring.owner(ip_of_line(ln)) == "w3" for ln in joiner.lines)
    # journal exists for the joiner: its chunks are replayable later
    assert len(r._journal["w3"]) > 0
    # re-adding an existing id must not rebuild the ring
    ring_obj = r.ring
    r.add_node("w1", peers["w1"])
    assert r.ring is ring_obj


def test_router_mark_left_clears_journal_no_replay_and_self_drain():
    """A graceful leaver's journal is dropped WITHOUT replay (it
    drained before departing — replay could only double-process); our
    own id leaving is the pure-membership self-drain handback."""
    r, local, peers, stats = _router()
    r.route(_lines(300))
    assert len(r._journal["w1"]) > 0
    r.mark_left("w1")
    assert "w1" not in r.alive
    assert len(r._journal["w1"]) == 0
    assert stats.peek()["FabricReplayedLines"] == 0
    assert stats.peek()["FabricTakeovers"] == 0
    assert r.describe()["peers"]["w1"]["alive"] is False
    # the remaining traffic still routes fully (w1's keys rerouted)
    out = r.route(_lines(50))
    assert out["shed"] == 0
    # self-drain: after mark_left(self) nothing is processed locally
    local_before = len(local)
    r.mark_left("w0")
    assert "w0" not in r.alive
    out = r.route(_lines(40))
    assert out["local"] == 0 and out["shed"] == 0
    assert len(local) == local_before


def test_router_gossip_merge_consumes_piggybacked_digests():
    """Forwarded-chunk acks carry membership digests; the router feeds
    them to the installed gossip_merge hook (convergence rides the
    data path)."""

    class _GossipyPeer(_FakePeer):
        def request(self, ftype, payload):
            rtype, rp = super().request(ftype, payload)
            rp["gossip"] = [["w9", "alive", 3, "127.0.0.1", 1]]
            return rtype, rp

    ids = ["w0", "w1"]
    ring = ConsistentHashRing(ids, vnodes=64)
    merged = []
    r = FabricRouter(
        "w0", ring, {"w0": None, "w1": _GossipyPeer("w1")},
        lambda ls: len(ls), stats=FabricStats(), takeover_grace_ms=0.0,
    )
    r.gossip_merge = merged.append
    r.route(_lines(64))
    assert merged and merged[0] == [["w9", "alive", 3, "127.0.0.1", 1]]


# ---------------------------------------------------------------------------
# node <-> peer over real sockets
# ---------------------------------------------------------------------------


def test_node_peer_request_response_and_t_err():
    got = []

    def h_lines(payload):
        got.extend(payload["lines"])
        return wire.T_ACK, {"n": len(payload["lines"])}

    def h_boom(payload):
        raise RuntimeError("handler exploded")

    node = FabricNode("127.0.0.1", 0, handlers={
        wire.T_LINES: h_lines, wire.T_STATS: h_boom,
    }).start()
    client = PeerClient("n", "127.0.0.1", node.port, send_timeout_ms=500)
    try:
        rtype, rp = client.request(wire.T_LINES, {"lines": ["a", "b"]})
        assert (rtype, rp["n"]) == (wire.T_ACK, 2)
        assert got == ["a", "b"]
        # handler exception answers T_ERR and keeps the connection
        with pytest.raises(OSError, match="handler exploded"):
            client.request(wire.T_STATS, {})
        # unhandled frame type also answers T_ERR
        with pytest.raises(OSError, match="unhandled frame type"):
            client.request(wire.T_SNAPSHOT, {})
        # connection still fine afterwards
        rtype, _ = client.request(wire.T_LINES, {"lines": ["c"]})
        assert rtype == wire.T_ACK
    finally:
        client.close()
        node.stop()


def test_peer_unavailable_after_retry_budget_against_dead_port():
    # bind-then-close: a port with nothing listening
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    client = PeerClient(
        "ghost", "127.0.0.1", port, send_timeout_ms=100, max_attempts=2,
        backoff=None,
    )
    with pytest.raises(PeerUnavailable):
        client.request(wire.T_PING, {})


# ---------------------------------------------------------------------------
# schema stability: registry families, line keys, config keys
# ---------------------------------------------------------------------------


def test_fabric_stats_peek_keys_are_all_registry_declared():
    peek = FabricStats().peek()
    assert set(peek) == {
        "FabricForwardedLines", "FabricReceivedLines", "FabricLocalLines",
        "FabricShedLines", "FabricReplayedLines",
        "FabricReplicatedDecisions", "FabricReplicationErrors",
        "FabricDuplicatesSuppressed", "FabricReplicatedApplied",
        "FabricTakeovers",
        "FabricMembershipSuspects", "FabricMembershipConfirmedDead",
        "FabricMembershipRefuted", "FabricMembershipJoined",
        "FabricMembershipLeft", "FabricGossipBytes",
        # ISSUE 18: wire v2 transport counters
        "FabricReplaySkippedLines", "FabricFramesSent",
        "FabricFrameBytes", "FabricAcksReceived",
        "FabricInflightFrames", "FabricRingOccupancy",
    }
    for key in peek:
        assert registry.is_declared_line_key(key), key


def test_fabric_prom_families_exist_with_stable_names():
    expected = {
        "banjax_fabric_peer_up",
        "banjax_fabric_forwarded_lines_total",
        "banjax_fabric_received_lines_total",
        "banjax_fabric_local_lines_total",
        "banjax_fabric_shed_lines_total",
        "banjax_fabric_replayed_lines_total",
        "banjax_fabric_replicated_decisions_total",
        "banjax_fabric_replication_errors_total",
        "banjax_fabric_duplicate_suppressed_total",
        "banjax_fabric_replicated_applied_total",
        "banjax_fabric_takeovers_total",
        "banjax_fabric_takeover_duration_seconds",
        "banjax_fabric_membership_state",
        "banjax_fabric_membership_suspects_total",
        "banjax_fabric_membership_confirmed_dead_total",
        "banjax_fabric_membership_refuted_total",
        "banjax_fabric_membership_joined_total",
        "banjax_fabric_membership_left_total",
        "banjax_fabric_gossip_bytes_total",
        "banjax_fabric_membership_detection_seconds",
        # ISSUE 18: wire v2 transport families
        "banjax_fabric_frames_total",
        "banjax_fabric_frame_bytes",
        "banjax_fabric_acks_total",
        "banjax_fabric_inflight_frames",
        "banjax_fabric_ack_rtt_seconds",
        "banjax_fabric_ring_occupancy",
        "banjax_fabric_replay_skipped_lines_total",
    }
    assert expected <= set(registry.PROM_FAMILIES), (
        expected - set(registry.PROM_FAMILIES)
    )


def test_fabric_families_render_on_both_surfaces_and_parse():
    stats = FabricStats()
    stats.note_local(5)
    stats.note_forwarded(3)
    stats.note_received(2)
    stats.note_takeover("w9", 0.25, 7)
    stats.note_peer("w9", False)
    text = render_prometheus(
        DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        fabric=stats,
    )
    fams = parse_text_format(text)
    undeclared = [f for f in fams if f not in registry.PROM_FAMILIES]
    assert not undeclared, undeclared
    scalars = {
        s[0]: s[2] for ent in fams.values() for s in ent["samples"]
        if not s[1]
    }
    assert scalars["banjax_fabric_local_lines_total"] == 5
    assert scalars["banjax_fabric_forwarded_lines_total"] == 3
    assert scalars["banjax_fabric_takeovers_total"] == 1
    labeled = {
        (s[0], tuple(sorted(s[1].items()))): s[2]
        for ent in fams.values() for s in ent["samples"] if s[1]
    }
    assert labeled[("banjax_fabric_peer_up", (("peer", "w9"),))] == 0
    out = io.StringIO()
    write_metrics_line(
        out, DynamicDecisionLists(start_sweeper=False),
        RegexRateLimitStates(), FailedChallengeRateLimitStates(),
        fabric=stats,
    )
    line = json.loads(out.getvalue())
    assert line["FabricLocalLines"] == 5
    assert line["FabricTakeovers"] == 1


def test_fabric_config_keys_schema_stable():
    cfg = Config()
    assert cfg.fabric_enabled is False
    assert cfg.fabric_node_id == ""
    assert cfg.fabric_listen == ""
    assert cfg.fabric_peers == {}
    assert cfg.fabric_vnodes == 64
    assert cfg.fabric_send_timeout_ms == 2000.0
    assert cfg.fabric_takeover_grace_ms == 500.0
    # ISSUE 16: gossip membership knobs (defaults keep gossip on)
    assert cfg.fabric_gossip_interval_ms == 1000.0
    assert cfg.fabric_suspect_timeout_ms == 3000.0
    assert cfg.fabric_indirect_probes == 2
    assert cfg.fabric_graceful_leave_ms == 5000.0
    # ISSUE 18: wire v2 transport knobs
    assert cfg.fabric_inflight_frames == 8
    assert cfg.fabric_wire_v2 is True
    assert cfg.fabric_frame_max_bytes == 1 << 20
    assert cfg.fabric_shm_enabled is False
    assert cfg.fabric_shm_ring_bytes == 1 << 21
    good = config_from_yaml_text(RULES_YAML + """
fabric_enabled: true
fabric_node_id: shard-a
fabric_listen: 0.0.0.0:4480
fabric_peers:
  shard-a: 10.0.0.1:4480
  shard-b: 10.0.0.2:4480
fabric_vnodes: 16
fabric_send_timeout_ms: 750
fabric_takeover_grace_ms: 100
fabric_gossip_interval_ms: 500
fabric_suspect_timeout_ms: 1500
fabric_indirect_probes: 3
fabric_graceful_leave_ms: 2000
fabric_inflight_frames: 16
fabric_wire_v2: false
fabric_frame_max_bytes: 65536
fabric_shm_ring_bytes: 1048576
""")
    assert good.fabric_enabled and good.fabric_node_id == "shard-a"
    assert good.fabric_peers["shard-b"] == "10.0.0.2:4480"
    assert good.fabric_vnodes == 16
    assert good.fabric_gossip_interval_ms == 500.0
    assert good.fabric_suspect_timeout_ms == 1500.0
    assert good.fabric_indirect_probes == 3
    assert good.fabric_graceful_leave_ms == 2000.0
    assert good.fabric_inflight_frames == 16
    assert good.fabric_wire_v2 is False
    assert good.fabric_frame_max_bytes == 65536
    assert good.fabric_shm_ring_bytes == 1048576
    # gossip can be disabled outright (static PR 11 fabric)
    off = config_from_yaml_text(RULES_YAML + "\nfabric_gossip_interval_ms: 0")
    assert off.fabric_gossip_interval_ms == 0.0


def test_flight_recorder_bundle_gains_fabric_json(tmp_path):
    """Satellite 6: incident bundles capture the fabric snapshot —
    peer table, hash-range ownership, last takeover — when a fabric_fn
    is wired (cli passes _fabric_snapshot)."""
    from banjax_tpu.obs.flightrec import FlightRecorder

    r, local, peers, stats = _router()
    r.route(_lines(64))
    peers["w1"].dead = True
    r.mark_dead("w1", reason="test")
    rec = FlightRecorder(
        str(tmp_path / "incidents"), min_interval_s=0.0,
        fabric_fn=lambda: {"enabled": True, **r.describe(),
                           "stats": stats.peek()},
    )
    name = rec.notify("fabric-takeover", "w1")
    doc = json.loads(
        (tmp_path / "incidents" / name / "fabric.json").read_text()
    )
    assert doc["enabled"] is True
    assert doc["peers"]["w1"]["alive"] is False
    assert doc["last_takeover"]["peer"] == "w1"
    assert abs(sum(doc["ownership"].values()) - 1.0) < 1e-9
    assert doc["stats"]["FabricTakeovers"] == 1


@pytest.mark.parametrize("snippet, match", [
    ("fabric_vnodes: 0", "fabric_vnodes"),
    ("fabric_send_timeout_ms: 0", "fabric_send_timeout_ms"),
    ("fabric_takeover_grace_ms: -1", "fabric_takeover_grace_ms"),
    ("fabric_enabled: true", "requires fabric_node_id"),
    ("fabric_enabled: true\nfabric_node_id: a\n"
     "fabric_listen: 0.0.0.0:1\nfabric_peers:\n  b: 1.2.3.4:1",
     "missing this node's own id"),
    ("fabric_gossip_interval_ms: 500\nfabric_suspect_timeout_ms: 400",
     "fabric_suspect_timeout_ms"),
    ("fabric_indirect_probes: -1", "fabric_indirect_probes"),
    ("fabric_graceful_leave_ms: -1", "fabric_graceful_leave_ms"),
])
def test_fabric_config_validation_errors(snippet, match):
    with pytest.raises(ValueError, match=match):
        config_from_yaml_text(RULES_YAML + "\n" + snippet)
