"""Unit tier for the challenge plane's two engines:

  * matcher/kernels/pow_verify.py — the batched sha256 leading-zero-bits
    kernel against hashlib + the O(1) bit counter, across lane-padding
    edge shapes and degenerate payloads;
  * challenge/failures.py — the bounded failed-challenge state: exact
    reference transitions, the LRU bound, lossless spill/refill, the
    spill-priority protection (offender evidence beats churner noise),
    and the construction seam.

The end-to-end differentials live in
tests/differential/test_challenge_differential.py.
"""

import hashlib
import threading
import time

import numpy as np
import pytest

from banjax_tpu.challenge.failures import (
    BoundedFailedChallengeStates,
    make_failed_challenge_states,
)
from banjax_tpu.challenge.verifier import DeviceVerifier, cpu_zero_bits
from banjax_tpu.crypto.challenge import count_zero_bits_from_left
from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates
from banjax_tpu.matcher.kernels.pow_verify import (
    POW_MESSAGE_BYTES,
    leading_zero_bits_batch,
    pack_pow_messages,
    pow_selftest,
)

# ---------------------------------------------------------------- kernel


def _ref_bits(payload: bytes) -> int:
    return count_zero_bits_from_left(hashlib.sha256(payload).digest())


def test_pow_selftest_passes_on_interpret():
    pow_selftest(interpret=True)


@pytest.mark.parametrize("batch", [1, 5, 127, 128, 130])
def test_kernel_matches_hashlib_across_lane_padding_shapes(batch):
    """Batch sizes straddling the 128-lane boundary: padding lanes must
    never leak into real results."""
    rng = np.random.default_rng(batch)
    payloads = [rng.bytes(POW_MESSAGE_BYTES) for _ in range(batch)]
    got = leading_zero_bits_batch(payloads, interpret=True)
    assert got.shape == (batch,)
    assert [int(b) for b in got] == [_ref_bits(p) for p in payloads]


def test_kernel_degenerate_payloads():
    """All-zero and all-ones payloads plus near-misses — the clz cascade
    and the live-digest masking have no branch untested."""
    payloads = [
        b"\x00" * POW_MESSAGE_BYTES,
        b"\xff" * POW_MESSAGE_BYTES,
        b"\x00" * (POW_MESSAGE_BYTES - 1) + b"\x01",
        b"\x80" + b"\x00" * (POW_MESSAGE_BYTES - 1),
    ]
    got = leading_zero_bits_batch(payloads, interpret=True)
    assert [int(b) for b in got] == [_ref_bits(p) for p in payloads]
    assert all(cpu_zero_bits(p) == _ref_bits(p) for p in payloads)


def test_pack_rejects_wrong_length_payloads():
    with pytest.raises(ValueError):
        pack_pow_messages([b"short"])


def test_pack_pads_to_full_lanes():
    words, n = pack_pow_messages([b"\x01" * POW_MESSAGE_BYTES] * 3)
    assert n == 3
    assert words.shape[0] == 16
    assert words.shape[1] % 128 == 0


def test_concurrent_submits_all_get_correct_bits():
    """Leader/follower micro-batching under real thread contention:
    every caller gets its own payload's answer — from the device batch,
    or CPU-inline when the bounded queue refuses it (the HTTP-path
    contract, same as verify_sha_inv's fallback)."""
    from banjax_tpu.challenge.verifier import DeviceUnavailable

    device = DeviceVerifier(batch_max=8, interpret=True)
    rng = np.random.default_rng(7)
    payloads = [rng.bytes(POW_MESSAGE_BYTES) for _ in range(24)]
    results = [None] * len(payloads)

    def work(i):
        try:
            results[i] = device.submit(payloads[i])
        except DeviceUnavailable:
            results[i] = cpu_zero_bits(payloads[i])

    threads = [threading.Thread(target=work, args=(i,))
               for i in range(len(payloads))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert results == [_ref_bits(p) for p in payloads]
    assert device.counters()["lanes_verified"] > 0


def test_selftest_failure_permanently_disables_device(monkeypatch):
    """A kernel that disagrees with hashlib must never verify real
    traffic: the first-use differential trips and the device path stays
    off for the verifier's lifetime."""
    def bad_selftest(interpret=None):
        raise RuntimeError("mismatch")

    # the verifier lazy-imports the selftest from the kernel module, so
    # the patch goes on the source
    monkeypatch.setattr(
        "banjax_tpu.matcher.kernels.pow_verify.pow_selftest", bad_selftest
    )
    device = DeviceVerifier(batch_max=4, interpret=True)
    assert not device.available()
    assert "selftest" in (device.counters()["disabled_reason"] or "")


# --------------------------------------------------------- bounded state


class _Cfg:
    too_many_failed_challenges_interval_seconds = 30
    too_many_failed_challenges_threshold = 3
    challenge_failure_state_max = 0


class _Clock:
    def __init__(self, start_ns=1_700_000_000_000_000_000):
        self.ns = start_ns

    def __call__(self):
        return self.ns


def test_bounded_matches_reference_transitions_exactly():
    """No eviction pressure: every apply() is bit-identical to the
    reference port, including the strictly-greater window restart and
    the exceed-resets-to-0 quirk."""
    cfg = _Cfg()
    clock = _Clock()
    bounded = BoundedFailedChallengeStates(64, now_ns_fn=clock)
    reference = FailedChallengeRateLimitStates()
    ref_clock = {"ns": clock.ns}

    def ref_apply(ip):
        real = time.time_ns
        time.time_ns = lambda: ref_clock["ns"]
        try:
            return reference.apply(ip, cfg)
        finally:
            time.time_ns = real

    steps = [("a", 0), ("a", 1), ("a", 1), ("a", 1),       # exceed at 4th
             ("a", 31), ("b", 0), ("b", 40), ("b", 0)]     # restarts
    for ip, advance_s in steps:
        clock.ns += advance_s * 1_000_000_000
        ref_clock["ns"] = clock.ns
        got = bounded.apply(ip, cfg)
        want = ref_apply(ip)
        assert (got.match_type, got.exceeded) == (want.match_type, want.exceeded)
    assert sorted(bounded.format_states().splitlines()) == sorted(
        reference.format_states().splitlines()
    )


def test_bound_holds_and_spilled_offender_refills_losslessly():
    """Past the cap the LRU evicts; an offender with real evidence
    (hits >= 2) parks in the spill tier and its EXACT (hits, start)
    state comes back on re-entry — the ban lands on the same apply() it
    would have unbounded."""
    cfg = _Cfg()
    clock = _Clock()
    bounded = BoundedFailedChallengeStates(4, now_ns_fn=clock)

    bounded.apply("offender", cfg)       # hits 1
    bounded.apply("offender", cfg)       # hits 2
    for i in range(64):                  # churn the offender out
        bounded.apply(f"churn-{i}", cfg)
    assert len(bounded) <= 4
    assert bounded.counters()["evictions_total"] >= 60
    assert bounded.counters()["spill_writes"] >= 1
    # hits 3 then 4 > 3: the exceed fires exactly as unbounded would
    assert not bounded.apply("offender", cfg).exceeded
    assert bounded.apply("offender", cfg).exceeded
    assert bounded.counters()["spill_refills"] >= 1


def test_spill_priority_keeps_the_stronger_entry():
    """Slot collision: the entry with more hits wins the slot; the
    weaker one is the counted loss.  Exercised directly so the test
    does not depend on finding natural collisions under the LRU."""
    from banjax_tpu.decisions.rate_limit import NumHitsAndIntervalStart

    bounded = BoundedFailedChallengeStates(4)
    mask = bounded._sp_mask
    slot_of = lambda ip: (bounded._fingerprint(ip) >> 17) & mask
    strong = "10.0.0.1"
    weak = None
    for i in range(200_000):
        cand = f"11.{(i >> 16) & 0xFF}.{(i >> 8) & 0xFF}.{i & 0xFF}"
        if slot_of(cand) == slot_of(strong) and cand != strong:
            weak = cand
            break
    assert weak is not None, "no colliding ip found in the search space"

    bounded._spill_put(strong, NumHitsAndIntervalStart(3, 100))
    bounded._spill_put(weak, NumHitsAndIntervalStart(1, 200))
    assert bounded.counters()["spill_drops"] == 1
    kept = bounded._spill_take(strong)
    assert kept is not None and kept.num_hits == 3
    assert bounded._spill_take(weak) is None


def test_one_shot_churners_never_touch_the_spill_table():
    """The sketch gate: distinct one-time failers (the 1M-flood
    population) are evicted without a spill write, so parked offender
    state cannot be displaced by churn volume."""
    cfg = _Cfg()
    bounded = BoundedFailedChallengeStates(8, sketch_width=1 << 16)
    for i in range(512):
        bounded.apply(f"12.0.{(i >> 8) & 0xFF}.{i & 0xFF}", cfg)
    c = bounded.counters()
    assert c["entries"] <= 8
    assert c["gate_skips"] > 0
    assert c["spill_writes"] == 0


def test_factory_dispatches_on_the_config_cap():
    cfg = _Cfg()
    assert isinstance(
        make_failed_challenge_states(cfg), FailedChallengeRateLimitStates
    )
    cfg.challenge_failure_state_max = 100
    bounded = make_failed_challenge_states(cfg)
    assert isinstance(bounded, BoundedFailedChallengeStates)
    assert bounded._max == 100
    with pytest.raises(ValueError):
        BoundedFailedChallengeStates(0)
