"""Direct unit coverage for matcher/workset.py — the columnar work-batch
interface both the native and fallback gates provide (NativeWork/ListWork/
LazyResults/LazyLine). The differential suite covers these end-to-end; here
the interface contracts are pinned in isolation."""

import numpy as np
import pytest

from banjax_tpu import native
from banjax_tpu.matcher.encode import ParsedLine
from banjax_tpu.matcher.workset import (
    LazyLine,
    LazyResults,
    ListWork,
    NativeWork,
    unique_spans,
)


def _native_batch(lines, max_len=64):
    b2c = np.zeros(257, dtype=np.int32)
    return native.parse_encode_batch(lines, b2c, max_len, 2e9, 1e18)


@pytest.fixture()
def nb():
    if not native.available():
        pytest.skip("no C compiler")
    lines = [
        f"1700000000.{i:06d} 10.0.0.{i % 3} GET h{i % 2}.com GET /p{i} x"
        for i in range(8)
    ]
    return _native_batch(lines)


def _work_from(nb, rows=None):
    rows = np.arange(nb.n, dtype=np.int64) if rows is None else rows
    text = nb.text()
    ips_u, ip_inv = unique_spans(
        nb.ip_off[rows], nb.ip_len[rows], lambda k: nb.ip(int(rows[k])),
        blob=nb.blob, text=text,
    )
    hosts_u, host_inv = unique_spans(
        nb.host_off[rows], nb.host_len[rows], lambda k: nb.host(int(rows[k])),
        blob=nb.blob, text=text,
    )
    return NativeWork(nb, rows, ips_u, ip_inv, hosts_u, host_inv,
                      nb.ts_ns[rows].astype(np.int64), {})


def test_native_work_rows_and_lazy_rest(nb):
    w = _work_from(nb)
    assert len(w) == 8
    i, p = w[3]
    assert i == 3
    assert p.ip == "10.0.0.0" and p.host == "h1.com"
    assert isinstance(p, LazyLine) and p._rest is None  # not yet decoded
    assert p.rest.startswith("GET h1.com GET /p3")
    assert p.error is False and p.old_line is False


def test_native_work_slicing_compacts_uniques(nb):
    w = _work_from(nb)
    ips, inv = w.unique_ips()
    assert ips == ["10.0.0.0", "10.0.0.1", "10.0.0.2"]  # first appearance
    assert inv.tolist() == [0, 1, 2, 0, 1, 2, 0, 1]
    sl = w[0:2]  # rows 0-1: only two ips present
    ips2, inv2 = sl.unique_ips()
    assert ips2 == ["10.0.0.0", "10.0.0.1"]
    assert inv2.tolist() == [0, 1]
    # host_idx maps through a host-row table; unknown hosts -> 0
    hi = sl.host_idx({"h1.com": 5})
    assert hi.tolist() == [0, 5]


def test_native_work_defer_map_overrides(nb):
    p = ParsedLine(timestamp_ns=123, ip="9.9.9.9", host="d.com", rest="R")
    w = _work_from(nb)
    w.defer_map[2] = p
    i, got = w[2]
    assert i == 2 and got is p


def test_list_work_interface():
    mk = lambda ip, host, ts: ParsedLine(
        timestamp_ns=ts, ip=ip, host=host, rest="r"
    )
    lw = ListWork([(0, mk("a", "h", 5)), (1, mk("b", "h", 6)),
                   (2, mk("a", "g", 10**25))])
    ips, inv = lw.unique_ips()
    assert ips == ["a", "b"] and inv.tolist() == [0, 1, 0]
    assert lw.host_idx({"g": 3}).tolist() == [0, 0, 3]
    ts = lw.ts_array()
    assert ts.dtype == np.int64
    assert ts[2] == 2**63 - 1  # out-of-int64 clamps instead of raising
    sl = lw[1:]
    assert isinstance(sl, ListWork) and len(sl) == 2


def test_lazy_results_materialize_on_access():
    r = LazyResults(4)
    assert len(r) == 4
    r[1].error = True
    assert r._items[0] is None          # untouched stays unmaterialized
    assert r[1].error and not r[2].error
    assert [x.error for x in r] == [False, True, False, False]
    assert [x.error for x in r[1:3]] == [True, False]


def test_unique_spans_fallback_and_native_agree_on_nuls():
    blob = b"a\x00b a\x00b a\x00c"
    offs = np.asarray([0, 4, 8], dtype=np.int64)
    lens = np.asarray([3, 3, 3], dtype=np.int32)

    def dec(k):
        return blob[int(offs[k]) : int(offs[k]) + int(lens[k])].decode()

    s1, i1 = unique_spans(offs, lens, dec)  # scalar fallback
    assert s1 == ["a\x00b", "a\x00c"] and i1.tolist() == [0, 0, 1]
    if native.available():
        s2, i2 = unique_spans(offs, lens, dec, blob=blob)
        assert s2 == s1 and i2.tolist() == i1.tolist()
