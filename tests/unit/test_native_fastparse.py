"""Native fastparse vs the Python reference parse/encode — byte-identical
on every line, including the adversarial timestamp/shape corner cases the
C side must defer on."""

import time

import numpy as np
import pytest

from banjax_tpu import native
from banjax_tpu.matcher.encode import encode_for_match, parse_line
from banjax_tpu.matcher.rulec import compile_rules

pytestmark = pytest.mark.skipif(
    not native.available(), reason="no C compiler in this environment"
)

COMPILED = compile_rules([r"GET /wp-login\.php", r"(GET|POST) /[a-z]*\.php", r".*"])
MAX_LEN = 96
NOW = 1_753_800_000.0

LINES = [
    f"{NOW:.6f} 1.2.3.4 GET example.com GET /wp-login.php HTTP/1.1 UA",
    f"{NOW - 5:.3f} 10.0.0.1 POST site.org POST /x.php HTTP/1.1 -",
    f"{NOW - 11:.6f} 9.9.9.9 GET old.com GET / HTTP/1.1 UA",  # stale
    "not enough",                       # 1 space: parse error
    "",                                 # empty: error
    f"{NOW:.6f} 5.5.5.5 nospace",       # rest with no space: error
    f"{NOW:.6f} 5.5.5.5 a b",           # rest with 1 space: error
    f"{NOW:.6f} 5.5.5.5 a b ",          # trailing space: 3 parts, empty rest2
    "nan 1.2.3.4 GET h.com GET /",      # nan ts: Python error (defer path)
    "inf 1.2.3.4 GET h.com GET /",      # inf ts: Python error
    "1_700_000_000 1.2.3.4 GET h.com GET /",  # underscores: Python ACCEPTS
    "1e30 1.2.3.4 GET h.com GET /",     # int64 overflow: Python error
    "-5.5 1.2.3.4 GET h.com GET /",     # negative ts: valid, very old
    f"{NOW:.6f} 8.8.8.8 GET h.com GET /café HTTP/1.1",  # non-ASCII
    f"{NOW:.6f} 7.7.7.7 GET h.com GET /{'a' * 200} HTTP/1.1",  # over max_len
    f"{NOW:.6f} 6.6.6.6 GET h.com GET / HTTP/1.1 " + "x" * (MAX_LEN - 30),
    f"  {NOW:.6f} 1.2.3.4 GET h.com GET /",  # leading space: empty ts field
]


def test_differential_vs_python_reference():
    nb = native.parse_encode_batch(
        LINES, COMPILED.byte_to_class, MAX_LEN, NOW, 10.0
    )
    assert nb is not None and nb.n == len(LINES)
    for i, line in enumerate(LINES):
        want = parse_line(line, NOW, 10.0)
        f = int(nb.flags[i])
        if f & native.FLAG_DEFER:
            continue  # contract: caller re-parses with Python — always safe
        assert bool(f & native.FLAG_ERROR) == want.error, (i, line)
        if want.error:
            continue
        assert bool(f & native.FLAG_OLD) == want.old_line, (i, line)
        assert nb.ip(i) == want.ip
        assert int(nb.ts_ns[i]) == want.timestamp_ns, (i, line)
        if want.old_line:
            continue
        assert nb.host(i) == want.host
        assert nb.rest(i) == want.rest
        cls_ref, lens_ref, host_eval_ref = encode_for_match(
            COMPILED, [want.rest], MAX_LEN
        )
        assert bool(f & native.FLAG_HOST_EVAL) == bool(host_eval_ref[0]), (i, line)
        if not host_eval_ref[0]:
            assert nb.lens[i] == lens_ref[0]
            assert (nb.cls_ids[i] == cls_ref[0]).all(), (i, line)


def test_defer_covers_python_divergences():
    """Every line whose timestamp text C cannot prove plain must defer —
    in particular the underscore form Python float() accepts."""
    nb = native.parse_encode_batch(
        LINES, COMPILED.byte_to_class, MAX_LEN, NOW, 10.0
    )
    for i, line in enumerate(LINES):
        ts_field = line.split(" ", 1)[0] if " " in line else line
        exotic = any(c in ts_field for c in "_") or ts_field.lower() in (
            "nan", "inf", "-inf", "+inf", "infinity",
        ) or ts_field == "1e30"
        if exotic:
            assert int(nb.flags[i]) & native.FLAG_DEFER, (i, line)


def test_random_fuzz_against_reference():
    rng = np.random.default_rng(0)
    charset = list("abc ./:0123456789eE+-_é")
    lines = []
    for _ in range(500):
        n = int(rng.integers(0, 60))
        lines.append("".join(charset[int(k)] for k in rng.integers(0, len(charset), n)))
    nb = native.parse_encode_batch(lines, COMPILED.byte_to_class, MAX_LEN, NOW, 10.0)
    for i, line in enumerate(lines):
        f = int(nb.flags[i])
        if f & native.FLAG_DEFER:
            continue
        want = parse_line(line, NOW, 10.0)
        assert bool(f & native.FLAG_ERROR) == want.error, repr(line)
        if want.error:
            continue
        assert bool(f & native.FLAG_OLD) == want.old_line, repr(line)
        assert int(nb.ts_ns[i]) == want.timestamp_ns, repr(line)
        if not want.old_line:
            assert nb.ip(i) == want.ip and nb.host(i) == want.host
            assert nb.rest(i) == want.rest


def test_throughput_beats_python_parse():
    """The native pass must be well ahead of the Python loop (the point)."""
    lines = [
        f"{NOW:.6f} 10.{i % 256}.{i % 17}.{i % 251} GET example.com GET "
        f"/path/{i} HTTP/1.1 Mozilla/5.0 | 200"
        for i in range(20_000)
    ]
    t0 = time.perf_counter()
    nb = native.parse_encode_batch(lines, COMPILED.byte_to_class, MAX_LEN, NOW, 10.0)
    native_s = time.perf_counter() - t0
    assert not (np.asarray(nb.flags) & native.FLAG_DEFER).any()
    t0 = time.perf_counter()
    parsed = [parse_line(l, NOW, 10.0) for l in lines]
    encode_for_match(COMPILED, [p.rest for p in parsed], MAX_LEN)
    python_s = time.perf_counter() - t0
    print(f"native {len(lines)/native_s:,.0f} lps vs python {len(lines)/python_s:,.0f} lps")
    assert native_s * 2 < python_s  # conservative: usually 10-30x


def test_fast_timestamp_path_bit_identical_to_python_float():
    """The C fast_ts integer fast path must agree bit-for-bit with Python
    int(float(ts) * 1e9) on every shape it accepts; shapes it rejects must
    defer/error into the Python re-parse path (exactness contract of
    fastparse.c). Fuzzes plain, long-fraction, huge-mantissa, exponent,
    and malformed timestamps."""
    import random

    import numpy as np

    from banjax_tpu import native
    from banjax_tpu.native import FLAG_DEFER, FLAG_ERROR, ParseScratch

    rng = random.Random(1234)
    cases = []
    for _ in range(2000):
        kind = rng.random()
        if kind < 0.3:
            cases.append(
                f"{rng.randrange(10**9, 2 * 10**9)}.{rng.randrange(10**6):06d}"
            )
        elif kind < 0.5:
            fd = rng.randrange(1, 18)
            cases.append(f"{rng.randrange(10**9)}.{rng.randrange(10**fd):0{fd}d}")
        elif kind < 0.6:
            cases.append(str(rng.randrange(10 ** rng.randrange(1, 19))))
        elif kind < 0.7:  # mantissa past 2^53: must take the strtod path
            cases.append(f"{rng.randrange(10**17, 10**18)}.{rng.randrange(10**6):06d}")
        elif kind < 0.8:  # exponent form: strtod path
            cases.append(f"{rng.randrange(10**9)}e{rng.randrange(-3, 4)}")
        elif kind < 0.9:
            cases.append(f"{rng.randrange(10**9)}.{'9' * rng.randrange(1, 25)}")
        else:
            cases.append(rng.choice(
                ["1_000.5", "inf", "nan", "0x1p3",
                 f".{rng.randrange(10**6)}", f"{rng.randrange(10**6)}."]
            ))
    # deterministic int64-overflow boundary shapes: the fast-path mantissa
    # accumulator must bail BEFORE m*10 wraps (a wrapped value can sneak
    # under the 2^53 check and silently misparse)
    cases += [
        "922337203685477580", "9223372036854775807", "9223372036854775808",
        "92233720368547758089", "92233720368547758085.5",
        "922337203685477580.8", "18446744073709551616",
    ]
    b2c = np.zeros(257, dtype=np.int32)
    lines = [f"{ts} 1.2.3.4 GET h.com GET / x" for ts in cases]
    pb = native.parse_encode_batch(lines, b2c, 64, 2e9, 1e18, ParseScratch())
    if pb is None:
        pytest.skip("no C compiler in this environment")
    for i, ts in enumerate(cases):
        if int(pb.flags[i]) & (FLAG_DEFER | FLAG_ERROR):
            continue  # python re-parse path: exact by construction
        want = int(float(ts) * 1e9)  # raises -> C wrongly accepted it
        assert int(pb.ts_ns[i]) == want, ts


def test_unique_spans_fallback_matches_native():
    """The scalar fallback of workset.unique_spans (native lib absent)
    produces the same first-appearance-ordered tables as the C dedup."""
    import numpy as np

    from banjax_tpu.matcher.workset import unique_spans

    blob = b"zz one two one three two zz one"
    words = blob.split(b" ")
    offs, lens, pos = [], [], 0
    for w in words:
        offs.append(pos)
        lens.append(len(w))
        pos += len(w) + 1
    offs = np.asarray(offs, dtype=np.int64)
    lens = np.asarray(lens, dtype=np.int32)

    def decode(k):
        return blob[int(offs[k]) : int(offs[k]) + int(lens[k])].decode()

    s_fallback, inv_fallback = unique_spans(offs, lens, decode)  # no blob
    assert s_fallback == ["zz", "one", "two", "three"]
    assert inv_fallback.tolist() == [0, 1, 2, 1, 3, 2, 0, 1]

    from banjax_tpu import native

    if native.available():
        s_nat, inv_nat = unique_spans(
            offs, lens, decode, blob=blob, text=blob.decode()
        )
        assert s_nat == s_fallback
        assert inv_nat.tolist() == inv_fallback.tolist()


def test_allowlist_cache_invalidated_on_reload():
    """The (host, ip) allowlist cache must drop when the static lists are
    rebuilt (hot reload): an IP removed from the allow list must stop
    being exempted immediately."""
    import time as _time

    from banjax_tpu.config.schema import config_from_yaml_text
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.decisions.static_lists import StaticDecisionLists
    from banjax_tpu.matcher.runner import TpuMatcher
    from tests.mock_banner import MockBanner

    yaml_a = """
regexes_with_rates:
  - decision: nginx_block
    rule: insta
    regex: .*hitme.*
    interval: 60
    hits_per_interval: 0
global_decision_lists:
  allow:
    - 7.7.7.7
"""
    cfg = config_from_yaml_text(yaml_a)
    sl = StaticDecisionLists(cfg)
    m = TpuMatcher(cfg, MockBanner(), sl, RegexRateLimitStates())
    now = _time.time()
    line = f"{now:.6f} 7.7.7.7 GET h.com GET /hitme HTTP/1.1 UA"
    r1 = m.consume_lines([line], now)[0]
    assert r1.exempted

    # reload: allow list emptied
    cfg2 = config_from_yaml_text(yaml_a.replace("    - 7.7.7.7\n", ""))
    sl.update_from_config(cfg2)
    r2 = m.consume_lines([line], now)[0]
    assert not r2.exempted and r2.rule_results
