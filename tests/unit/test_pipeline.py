"""Streaming pipeline scheduler units (banjax_tpu/pipeline/): the
adaptive sizer policy, the TpuMatcher split protocol (begin/submit/
collect/finish), drain-time staleness, backpressure + shed accounting,
the idle device probe, and the pipeline-derived breaker latency budget.

Everything here runs on the CPU backend (tests/conftest.py pins
JAX_PLATFORMS=cpu) — tier-1 marker hygiene for the pipeline suite.
"""

import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.api import ConsumeLineResult
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs.stats import PipelineStats
from banjax_tpu.pipeline import AdaptiveBatchSizer, PipelineScheduler
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import CLOSED, OPEN
from tests.mock_banner import MockBanner

RULES_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: r1
    regex: 'GET /attack.*'
    interval: 5
    hits_per_interval: 2
"""


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def make_matcher(device_windows=False, **cfg_overrides):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = device_windows
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    states = RegexRateLimitStates()
    banner = MockBanner()
    m = TpuMatcher(cfg, banner, StaticDecisionLists(cfg), states)
    return m, states, banner


def lines_at(now, n, path="/attack"):
    return [
        f"{now:.6f} 1.2.3.{i % 9} GET h.com GET {path}{i % 3} HTTP/1.1 ua -"
        for i in range(n)
    ]


# ---------------------------------------------------------------------------
# adaptive sizer
# ---------------------------------------------------------------------------


class TestAdaptiveBatchSizer:
    def test_grows_when_under_half_budget(self):
        s = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=4096,
                               start_batch=256)
        for _ in range(4):
            s.observe(256, {"encode": 2.0, "device": 10.0, "drain": 1.0})
        assert s.target() == 512

    def test_shrinks_when_over_budget(self):
        s = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=4096,
                               start_batch=1024)
        for _ in range(4):
            s.observe(1024, {"encode": 20.0, "device": 200.0, "drain": 10.0})
        assert s.target() == 512

    def test_clamps_at_bounds(self):
        s = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=256,
                               start_batch=256)
        for _ in range(8):
            s.observe(256, {"device": 1.0})
        assert s.target() == 256  # fast but already at max
        s2 = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=256,
                                start_batch=64)
        for _ in range(8):
            s2.observe(64, {"device": 500.0})
        assert s2.target() == 64  # slow but already at min

    def test_trickle_batches_do_not_drive_sizing(self):
        s = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=4096,
                               start_batch=1024)
        # tiny batches, fast: say nothing about the 1024 bucket's latency
        for _ in range(10):
            s.observe(8, {"device": 0.5})
        assert s.target() == 1024

    def test_trickle_over_budget_still_shrinks(self):
        # a SLOW tiny batch is evidence regardless of its size
        s = AdaptiveBatchSizer(100.0, min_batch=64, max_batch=4096,
                               start_batch=1024)
        for _ in range(4):
            s.observe(8, {"device": 300.0})
        assert s.target() == 512

    def test_settle_prevents_single_sample_moves(self):
        s = AdaptiveBatchSizer(100.0, start_batch=1024, settle=3)
        s.observe(1024, {"device": 1.0})  # first full batch: compile, skipped
        s.observe(1024, {"device": 1.0})
        s.observe(1024, {"device": 1.0})
        assert s.target() == 1024  # two counted samples < settle=3
        s.observe(1024, {"device": 1.0})
        assert s.target() == 2048

    def test_power_of_two_normalization_and_validation(self):
        s = AdaptiveBatchSizer(100.0, min_batch=100, max_batch=5000,
                               start_batch=3000)
        assert s.target() == 2048
        assert s.min_batch == 64 and s.max_batch == 4096
        with pytest.raises(ValueError):
            AdaptiveBatchSizer(0.0)

    def test_snapshot_keys(self):
        s = AdaptiveBatchSizer(100.0)
        s.observe(1024, {"encode": 1.0, "device": 2.0, "drain": 3.0})
        snap = s.snapshot()
        assert snap["PipelineBatchTarget"] == s.target()
        assert snap["PipelineStageDeviceEwmaMs"] == 2.0

    def test_efficiency_guard_shrinks_back_from_worse_bucket(self):
        """Latency headroom alone must not hold a bucket that is per-line
        WORSE than the one below (the cache-bound backend shape)."""
        s = AdaptiveBatchSizer(250.0, min_batch=64, max_batch=8192,
                               start_batch=1024)
        # 1024: 50 ms total (~0.049 ms/line) → under half budget → grow
        for _ in range(4):
            s.observe(1024, {"device": 50.0})
        assert s.target() == 2048
        # 2048 turns out per-line worse (0.122 vs 0.049) though 250 ms
        # still fits the budget
        for _ in range(4):
            s.observe(2048, {"device": 250.0 * 0.9})
        assert s.target() == 1024
        # and growth back into the measured-worse bucket stays blocked
        for _ in range(6):
            s.observe(1024, {"device": 50.0})
        assert s.target() == 1024

    def test_efficiency_guard_allows_growth_when_upper_is_better(self):
        s = AdaptiveBatchSizer(500.0, min_batch=64, max_batch=8192,
                               start_batch=1024)
        for _ in range(4):
            s.observe(1024, {"device": 100.0})
        assert s.target() == 2048
        # amortization pays: per-line improves at 2048 → keeps growing
        for _ in range(4):
            s.observe(2048, {"device": 150.0})
        assert s.target() == 4096

    def test_blocked_grow_retries_after_decay(self):
        from banjax_tpu.pipeline import sizer as sizer_mod

        s = AdaptiveBatchSizer(250.0, min_batch=64, max_batch=8192,
                               start_batch=2048)
        # poison the upper bucket's record (e.g. a first-visit compile)
        s._per_line_at[4096] = 10.0
        for _ in range(sizer_mod._RETRY_BLOCKED + 6):
            s.observe(2048, {"device": 50.0})
            if s.target() != 2048:
                break
        # the stale record was eventually forgotten and growth retried
        assert s.target() == 4096


# ---------------------------------------------------------------------------
# split protocol (matcher-level, no threads)
# ---------------------------------------------------------------------------


class TestSplitProtocol:
    @pytest.mark.parametrize("device_windows", [False, True])
    def test_split_equals_sync(self, device_windows):
        now = time.time()
        lines = lines_at(now, 40) + [
            f"{now:.6f} 5.5.5.5 GET h.com GET /benign HTTP/1.1 ua -",
            "garbage",
        ]
        sync_m, sync_states, sync_banner = make_matcher(device_windows)
        want = sync_m.consume_lines(lines, now)

        m, states, banner = make_matcher(device_windows)
        state = m.pipeline_begin(lines, now)
        m.pipeline_submit(state)
        m.pipeline_collect(state)
        got, n_stale = m.pipeline_finish(state, now)
        assert n_stale == 0
        for a, b in zip(want, got):
            assert (a.error, a.old_line, a.exempted) == (
                b.error, b.old_line, b.exempted
            )
            assert [
                (r.rule_name, r.regex_match, r.seen_ip) for r in a.rule_results
            ] == [
                (r.rule_name, r.regex_match, r.seen_ip) for r in b.rule_results
            ]
        assert sync_banner.regex_ban_logs == banner.regex_ban_logs
        sync_view = (
            sync_m.device_windows if device_windows else sync_states
        )
        view = m.device_windows if device_windows else states
        assert sync_view.format_states() == view.format_states()

    @pytest.mark.parametrize("device_windows", [False, True])
    def test_stale_at_drain_time_is_dropped_and_counted(self, device_windows):
        now = time.time()
        lines = lines_at(now, 20)
        # pallas_single_kernel=off: drop-at-DRAIN is the two-program/
        # classic contract (the single-kernel path commits at submit and
        # takes the cut there — tests/unit/test_fused_single_kernel.py)
        m, states, banner = make_matcher(
            device_windows, pallas_single_kernel="off"
        )
        state = m.pipeline_begin(lines, now)
        m.pipeline_submit(state)
        m.pipeline_collect(state)
        # the batch sat in the pipeline past the 10 s cutoff: age is
        # measured at effector drain time, so every line drops old_line
        results, n_stale = m.pipeline_finish(state, now + 30)
        assert n_stale == 20
        assert all(r.old_line and not r.rule_results for r in results)
        assert banner.bans == [] and banner.regex_ban_logs == []
        # no window state was touched for stale lines
        if device_windows:
            assert len(m.device_windows) == 0
        else:
            assert len(states) == 0

    def test_partial_staleness_keeps_fresh_lines(self):
        now = time.time()
        fresh = lines_at(now, 10)
        old = lines_at(now - 8, 5)  # fresh at parse, stale at drain+3
        m, states, banner = make_matcher()
        state = m.pipeline_begin(old + fresh, now)
        m.pipeline_submit(state)
        m.pipeline_collect(state)
        results, n_stale = m.pipeline_finish(state, now + 3)
        assert n_stale == 5
        assert all(r.old_line for r in results[:5])
        assert all(not r.old_line for r in results[5:])
        assert sum(len(r.rule_results) for r in results[5:]) > 0

    def test_parse_time_old_lines_are_not_double_counted(self):
        now = time.time()
        m, _, _ = make_matcher()
        state = m.pipeline_begin(lines_at(now - 100, 6), now)
        m.pipeline_submit(state)
        m.pipeline_collect(state)
        results, n_stale = m.pipeline_finish(state, now)
        # already old at parse: normal old_line results, not pipeline-stale
        assert n_stale == 0
        assert all(r.old_line for r in results)


class TestFusedTwoPhaseSplit:
    """The fused matcher+windows two-phase protocol under the split calls
    (device windows on → submit dispatches program A, finish commits)."""

    def test_multi_chunk_batch_commits_in_order(self):
        """A batch wider than matcher_batch_lines splits into several
        two-phase chunks; their B-applies commit strictly in chunk order
        at finish — identical to the sync fused path."""
        now = time.time()
        # mixed traffic: mostly benign so the candidate gate holds
        lines = [
            f"{now:.6f} 1.2.{i % 5}.{i % 9} GET h.com GET "
            f"/{'attack' if i % 11 == 0 else 'page'}{i % 3} HTTP/1.1 ua -"
            for i in range(300)
        ]
        sync_m, _, sync_banner = make_matcher(
            device_windows=True, matcher_batch_lines=64
        )
        want = sync_m.consume_lines(lines, now)

        m, _, banner = make_matcher(
            device_windows=True, matcher_batch_lines=64
        )
        state = m.pipeline_begin(lines, now)
        assert state.get("fused_eligible")
        m.pipeline_submit(state)
        assert len(state["fused"]) > 1, "expected several two-phase chunks"
        m.pipeline_collect(state)
        got, n_stale = m.pipeline_finish(state, now)
        assert n_stale == 0
        assert m.pipelined_fused_chunks == len(
            [1 for _ in range(0, 300, 64)]
        ) - m.pipelined_fused_fallbacks
        for a, b in zip(want, got):
            assert [
                (r.rule_name, r.regex_match, r.seen_ip,
                 r.rate_limit_result and r.rate_limit_result.exceeded)
                for r in a.rule_results
            ] == [
                (r.rule_name, r.regex_match, r.seen_ip,
                 r.rate_limit_result and r.rate_limit_result.exceeded)
                for r in b.rule_results
            ]
        assert sync_banner.regex_ban_logs == banner.regex_ban_logs
        assert sync_m.device_windows.format_states() == \
            m.device_windows.format_states()

    def test_pipeline_fused_false_restores_classic_protocol(self):
        now = time.time()
        m, _, _ = make_matcher(device_windows=True, pipeline_fused=False)
        state = m.pipeline_begin(lines_at(now, 20), now)
        assert not state.get("fused_eligible")
        m.pipeline_submit(state)
        assert state.get("fused") is None and state["pend"] is not None
        m.pipeline_collect(state)
        results, _ = m.pipeline_finish(state, now)
        assert m.pipelined_fused_chunks == 0

    def test_abort_frees_turns_for_later_batches(self):
        """pipeline_abort on an un-finished batch must free its order
        turns: a later batch's finish would otherwise deadlock."""
        now = time.time()
        m, _, _ = make_matcher(device_windows=True)
        s1 = m.pipeline_begin(lines_at(now, 10), now)
        m.pipeline_submit(s1)
        assert s1.get("fused")
        s2 = m.pipeline_begin(lines_at(now, 10), now)
        m.pipeline_submit(s2)
        m.pipeline_abort(s1)  # batch 1 dies before its drain
        m.pipeline_collect(s2)
        results, _ = m.pipeline_finish(s2, now)  # must not hang
        assert any(r.rule_results for r in results)
        # pins fully released: every slot usable again
        assert (m.device_windows._pin_counts == 0).all()


# ---------------------------------------------------------------------------
# scheduler (threads)
# ---------------------------------------------------------------------------


class _CollectingSink:
    def __init__(self):
        self.lock = threading.Lock()
        self.lines = []
        self.results = []

    def __call__(self, lines, results):
        with self.lock:
            self.lines.extend(lines)
            if results is not None:
                self.results.extend(results)


class TestScheduler:
    def test_end_to_end_parity_and_order(self):
        now = time.time()
        lines = lines_at(now, 700)
        sync_m, sync_states, sync_banner = make_matcher()
        want = sync_m.consume_lines(lines, now)

        m, states, banner = make_matcher()
        sink = _CollectingSink()
        sched = PipelineScheduler(
            lambda: m, on_results=sink, now_fn=lambda: now
        )
        sched.start()
        for i in range(0, len(lines), 53):
            sched.submit(lines[i : i + 53])
        assert sched.flush(60)
        sched.stop()
        assert sink.lines == lines  # admission order preserved
        assert len(sink.results) == len(want)
        assert sync_banner.regex_ban_logs == banner.regex_ban_logs
        assert sync_states.format_states() == states.format_states()
        snap = sched.snapshot()
        assert snap["PipelineAdmittedLines"] == len(lines)
        assert snap["PipelineProcessedLines"] == len(lines)
        assert snap["PipelineShedLines"] == 0

    def test_generic_matcher_without_split_protocol(self):
        """A matcher with no pipeline_begin (the CpuMatcher shape) drains
        generically through consume_lines — same results, fallback
        counted."""

        class PlainMatcher:
            def consume_lines(self, lines, now_unix=None):
                return [ConsumeLineResult() for _ in lines]

        sink = _CollectingSink()
        sched = PipelineScheduler(
            PlainMatcher, on_results=sink,  # getter: a fresh instance is fine
        )
        sched.start()
        sched.submit(["a b c d e f g"] * 10)
        assert sched.flush(10)
        sched.stop()
        assert len(sink.results) == 10
        assert sched.snapshot()["PipelineFallbackBatches"] >= 1

    def test_backpressure_sheds_oldest_and_accounts_every_line(self):
        """Sustained overload: tiny buffer, no blocking, a slow matcher —
        lines are shed oldest-first, counted, and the accounting invariant
        holds exactly after a flush."""

        class SlowMatcher:
            def consume_lines(self, lines, now_unix=None):
                time.sleep(0.05)
                return [ConsumeLineResult() for _ in lines]

        m = SlowMatcher()
        sink = _CollectingSink()
        sched = PipelineScheduler(
            lambda: m, ring_size=1, buffer_lines=64, max_block_ms=0.0,
            min_batch=64, max_batch=64, on_results=sink,
        )
        sched.start()
        for _ in range(40):
            sched.submit(["w x y z a b c"] * 16)
        assert sched.flush(60)
        sched.stop()
        s = sched.stats
        assert s.admitted_lines == 40 * 16
        assert s.shed_lines > 0
        assert len(sink.results) == s.processed_lines
        # the invariant the tentpole promises: admitted lines are either
        # processed or counted — never silently lost
        assert s.admitted_lines == (
            s.processed_lines + s.shed_lines + s.drain_error_lines
        )

    def test_oversized_single_chunk_sheds_its_own_head(self):
        class PlainMatcher:
            def consume_lines(self, lines, now_unix=None):
                return [ConsumeLineResult() for _ in lines]

        sched = PipelineScheduler(
            lambda: PlainMatcher(), buffer_lines=32, max_block_ms=0.0,
        )
        sched.start()
        sched.submit([f"l{i} a b c d e f" for i in range(100)])
        assert sched.flush(10)
        sched.stop()
        s = sched.stats
        assert s.shed_lines == 68
        assert s.admitted_lines == s.processed_lines + s.shed_lines

    def test_snapshot_metric_keys(self):
        m, _, _ = make_matcher()
        sched = PipelineScheduler(lambda: m)
        sched.start()
        now = time.time()
        sched.submit(lines_at(now, 10))
        assert sched.flush(30)
        sched.stop()
        snap = sched.snapshot()
        for key in (
            "PipelineAdmittedLines", "PipelineProcessedLines",
            "PipelineShedLines", "PipelineStaleDroppedLines",
            "PipelineBatches", "PipelineFallbackBatches",
            "PipelineBatchTarget", "PipelineStageDeviceEwmaMs",
            "PipelineBufferedLines", "PipelineInflightBatches",
            "PipelineRingSize", "PipelineDeviceP99Ms",
        ):
            assert key in snap, key


# ---------------------------------------------------------------------------
# idle probe + pipeline-derived breaker budget
# ---------------------------------------------------------------------------


class TestProbeAndBudget:
    def test_probe_succeeds_on_healthy_device(self):
        m, _, banner = make_matcher()
        assert m.probe() is True
        assert m.breaker.state == CLOSED
        assert banner.bans == []  # a probe has no side effects

    def test_probe_failure_trips_breaker_while_idle(self):
        m, _, _ = make_matcher(breaker_failure_threshold=1)
        failpoints.arm("matcher.device")
        assert m.probe() is False
        assert m.breaker.state == OPEN

    def test_scheduler_probe_thread_surfaces_wedged_device(self):
        m, _, _ = make_matcher(breaker_failure_threshold=1)
        m.probe()  # warm the device path before arming the failpoint
        failpoints.arm("matcher.device")
        sched = PipelineScheduler(lambda: m, probe_seconds=0.05)
        sched.start()
        deadline = time.monotonic() + 5
        while m.breaker.state != OPEN and time.monotonic() < deadline:
            time.sleep(0.02)
        sched.stop()
        assert m.breaker.state == OPEN
        assert sched.stats.probe_failed >= 1

    def test_effective_budget_prefers_config_over_source(self):
        m, _, _ = make_matcher(matcher_latency_budget_ms=123.0)
        m.set_latency_budget_source(lambda: 9.9)
        assert m.effective_latency_budget_s() == pytest.approx(0.123)

    def test_effective_budget_derives_from_pipeline_p99(self):
        m, _, _ = make_matcher()  # budget unset
        assert m.effective_latency_budget_s() == 0.0
        stats = PipelineStats()
        m.set_latency_budget_source(stats.suggested_latency_budget_s)
        assert m.effective_latency_budget_s() == 0.0  # no samples yet
        stats.observe_device(0.004)  # 4 ms p99 → 3x = 12 ms → 50 ms floor
        assert m.effective_latency_budget_s() == pytest.approx(0.05)
        for _ in range(300):
            stats.observe_device(0.1)  # 100 ms p99 → 300 ms budget
        assert m.effective_latency_budget_s() == pytest.approx(0.3, rel=0.1)


# ---------------------------------------------------------------------------
# soak (excluded from tier-1: -m 'not slow')
# ---------------------------------------------------------------------------


@pytest.mark.slow
def test_sustained_stream_soak():
    """Minutes-scale shape in miniature: a sustained mixed stream through
    the full scheduler with probe thread on — accounting exact at the
    end, no drift, breaker closed."""
    m, states, banner = make_matcher()
    sched = PipelineScheduler(lambda: m, probe_seconds=0.2)
    sched.start()
    now = time.time()
    total = 0
    t_end = time.monotonic() + 8
    i = 0
    while time.monotonic() < t_end:
        n = 17 + (i % 91)
        sched.submit(lines_at(now, n))
        total += n
        i += 1
        if i % 40 == 0:
            time.sleep(0.05)  # let the idle probe get a look in
    assert sched.flush(120)
    sched.stop()
    s = sched.stats
    assert s.admitted_lines == total
    assert s.processed_lines + s.shed_lines + s.drain_error_lines == total
    assert s.drain_error_lines == 0
    assert m.breaker.state == CLOSED
