"""Incident flight recorder (obs/flightrec.py): bundle layout and
atomicity, capture debounce, retention pruning, path-traversal guards
on the read surface, and the module-level trigger hook."""

import json
import os

import pytest

from banjax_tpu.obs import flightrec, provenance, trace
from banjax_tpu.obs.flightrec import FlightRecorder


@pytest.fixture(autouse=True)
def _clean_modules():
    yield
    flightrec.install(None)
    provenance.configure(enabled=True)
    trace.configure(enabled=False)


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def _recorder(tmp_path, **kw):
    kw.setdefault("min_interval_s", 0.0)
    return FlightRecorder(str(tmp_path / "incidents"), **kw)


def test_bundle_layout_and_contents(tmp_path):
    provenance.configure(enabled=True, ring_size=64)
    provenance.record(provenance.SOURCE_KAFKA, "4.4.4.4", "NginxBlock",
                      rule="block_ip")
    tracer = trace.configure(enabled=True, ring_size=64)
    tid = tracer.new_trace()
    with tracer.span("drain", tid, parent=0):
        pass
    rec = _recorder(
        tmp_path,
        metrics_text_fn=lambda: "# HELP x y\n# TYPE x counter\nx 1\n",
        config_hash_fn=lambda: "abc123",
    )
    name = rec.notify("breaker-trip", "matcher-device")
    assert name is not None and name.startswith("incident-")
    bundle = tmp_path / "incidents" / name
    assert sorted(os.listdir(bundle)) == [
        "meta.json", "metrics.prom", "provenance.json", "trace.json",
        "traffic.json",
    ]
    # no traffic_fn installed: the section says so instead of vanishing
    traffic_doc = json.loads((bundle / "traffic.json").read_text())
    assert traffic_doc == {"enabled": False}
    trace_doc = json.loads((bundle / "trace.json").read_text())
    assert any(e.get("ph") == "X" for e in trace_doc["traceEvents"])
    prov_doc = json.loads((bundle / "provenance.json").read_text())
    assert prov_doc["records"][-1]["ip"] == "4.4.4.4"
    assert prov_doc["counters"]["kafka/NginxBlock"] == 1
    meta = json.loads((bundle / "meta.json").read_text())
    assert meta["reason"] == "breaker-trip"
    assert meta["detail"] == "matcher-device"
    assert meta["config_hash"] == "abc123"
    assert (bundle / "metrics.prom").read_text().endswith("x 1\n")
    # no stranded tmp dirs: publish is rename-atomic
    assert not [e for e in os.listdir(tmp_path / "incidents")
                if e.endswith(".tmp")]
    assert rec.incident_count == 1


def test_debounce_bounds_capture_rate(tmp_path):
    clock = Clock()
    rec = _recorder(tmp_path, min_interval_s=60.0, clock=clock)
    assert rec.notify("shed-burst") is not None
    clock.t += 30.0
    assert rec.notify("shed-burst") is None       # inside the interval
    clock.t += 31.0
    assert rec.notify("breaker-trip") is not None  # past it
    assert rec.incident_count == 2


def test_prune_keeps_newest(tmp_path):
    clock = Clock()
    rec = _recorder(tmp_path, keep=3, clock=clock)
    names = []
    for i in range(6):
        clock.t += 1
        names.append(rec.notify(f"r{i}"))
    listed = [e["name"] for e in rec.list_incidents()]
    assert len(listed) == 3
    assert set(listed) <= set(names[-3:]) | set(names)  # newest retained
    for stale in names[:3]:
        assert stale not in listed


def test_list_and_read_surface(tmp_path):
    rec = _recorder(tmp_path, metrics_text_fn=lambda: "m 1\n")
    name = rec.notify("slo-shed_ratio", "burn 50")
    entries = rec.list_incidents()
    assert entries[0]["name"] == name
    assert entries[0]["reason"] == "slo-shed_ratio"
    assert "meta.json" in entries[0]["files"]
    assert rec.read_file(name, "metrics.prom") == b"m 1\n"
    assert rec.read_file(name, "nope.json") is None
    # traversal attempts are refused, not resolved
    assert rec.read_file("../" + name, "meta.json") is None
    assert rec.read_file(name, "../../etc/passwd") is None
    assert rec.read_file("incident-evil/..", "meta.json") is None


def test_capture_failure_never_propagates(tmp_path):
    def boom():
        raise RuntimeError("render failed")

    rec = _recorder(tmp_path, metrics_text_fn=boom)
    name = rec.notify("breaker-trip")
    # the bundle still lands, with the failure noted in metrics.prom
    assert name is not None
    data = rec.read_file(name, "metrics.prom")
    assert b"capture failed" in data


def test_module_hook_noop_without_recorder(tmp_path):
    flightrec.install(None)
    assert flightrec.notify("breaker-trip") is None
    rec = _recorder(tmp_path)
    flightrec.install(rec)
    assert flightrec.notify("breaker-trip") is not None
    assert flightrec.installed() is rec


def test_bundle_traffic_section_from_sketch(tmp_path):
    """A recorder wired with a traffic_fn (cli passes the matcher's
    sketch snapshot) lands the flood view in traffic.json — heavy
    hitters, cardinality and rule pressure as of the incident."""
    import numpy as np

    from banjax_tpu.obs.sketch import TrafficSketch

    sk = TrafficSketch(["r0"], width=1024, pull_seconds=3600.0)
    sk.note_assignments(["6.6.6.6"], np.asarray([0]))
    sk.update(np.zeros(32, dtype=np.int32), 32)
    sk.note_rule_events([0, 0, 0])
    rec = _recorder(tmp_path, traffic_fn=sk.incident_snapshot)
    name = rec.notify("shed-burst", "flood")
    assert name is not None
    doc = json.loads(
        (tmp_path / "incidents" / name / "traffic.json").read_text()
    )
    assert doc["enabled"] is True
    assert doc["top"][0]["ip"] == "6.6.6.6"
    assert doc["top"][0]["est_count"] >= 32
    assert doc["rule_pressure"] == [
        {"rule": "r0", "index": 0, "events": 3}
    ]
    # the incident pull is FORCED: fresh even under a long interval
    assert doc["lines_total"] == 32


def test_bundle_traffic_section_survives_a_failing_fn(tmp_path):
    rec = _recorder(tmp_path, traffic_fn=lambda: 1 / 0)
    name = rec.notify("breaker-trip")
    assert name is not None
    doc = json.loads(
        (tmp_path / "incidents" / name / "traffic.json").read_text()
    )
    assert doc["enabled"] is False and "error" in doc


def test_bundle_peers_tree_from_fleet_capture(tmp_path):
    """ISSUE 20: a cluster incident bundle grows a peers/<node_id>/
    tree with each ALIVE member's contribution, listed in meta.json
    and readable through the nested read surface."""
    rec = _recorder(
        tmp_path,
        metrics_text_fn=lambda: "m 1\n",
        fleet_capture_fn=lambda incident: {
            "w1": {"metrics.prom": "m 2\n",
                   "fabric.json": '{"enabled": true}'},
            "w2": {"error.txt": "capture failed: dead\n"},
        },
    )
    name = rec.notify("fabric-takeover", "w2 died")
    bundle = tmp_path / "incidents" / name
    assert (bundle / "peers" / "w1" / "metrics.prom").read_text() == "m 2\n"
    assert (bundle / "peers" / "w2" / "error.txt").read_text().startswith(
        "capture failed"
    )
    meta = json.loads((bundle / "meta.json").read_text())
    assert "peers/w1/metrics.prom" in meta["files"]
    assert "peers/w2/error.txt" in meta["files"]
    # nested read surface
    assert rec.read_file(name, "peers/w1/metrics.prom") == b"m 2\n"
    assert rec.read_file(name, "peers/nope/metrics.prom") is None
    # traversal through the nested form is refused, not resolved
    assert rec.read_file(name, "peers/../meta.json") is None
    assert rec.read_file(name, "peers/w1/../../meta.json") is None
    assert rec.read_file(name, "peers/w1/.hidden") is None


def test_bundle_fleet_capture_failure_never_propagates(tmp_path):
    def boom(incident):
        raise RuntimeError("fan-out exploded")

    rec = _recorder(tmp_path, fleet_capture_fn=boom)
    name = rec.notify("breaker-trip")
    assert name is not None  # the local bundle still lands
    bundle = tmp_path / "incidents" / name
    assert not (bundle / "peers").exists()


def test_bundle_fleet_capture_sanitizes_hostile_names(tmp_path):
    """Hostile node ids / file names from a compromised peer are
    basenamed into the bundle — nothing ever lands outside it, and
    dot-prefixed names are dropped."""
    rec = _recorder(
        tmp_path,
        fleet_capture_fn=lambda incident: {
            "../evil": {"x": "contained"},
            "w1": {"../../escape": "contained", "ok.txt": "yes",
                   ".hidden": "dropped"},
        },
    )
    name = rec.notify("chaos")
    bundle = tmp_path / "incidents" / name
    assert (bundle / "peers" / "w1" / "ok.txt").read_text() == "yes"
    # traversal components are stripped: the payloads land INSIDE the
    # bundle under their basenames, never beside/above it
    assert not (tmp_path / "incidents" / "evil").exists()
    assert not (tmp_path / "escape").exists()
    assert (bundle / "peers" / "evil" / "x").read_text() == "contained"
    assert (bundle / "peers" / "w1" / "escape").read_text() == "contained"
    assert not (bundle / "peers" / "w1" / ".hidden").exists()
    meta = json.loads((bundle / "meta.json").read_text())
    assert "peers/w1/ok.txt" in meta["files"]
    assert all(".." not in f for f in meta["files"])
