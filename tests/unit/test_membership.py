"""SWIM membership transitions (fabric/membership.py, ISSUE 16):
incarnation precedence, suspicion/refutation, the exactly-once
announcement funnel, graceful leave, and the failpoint-droppable merge
path.  Everything here is socket-free — a router spy records the side
effects and an injected clock drives suspicion expiry."""

import types

import pytest

from banjax_tpu.fabric.membership import (
    ALIVE,
    DEAD,
    LEFT,
    SUSPECT,
    SwimMembership,
)
from banjax_tpu.fabric.stats import FabricStats
from banjax_tpu.fabric import wire
from banjax_tpu.resilience import failpoints


class _RouterSpy:
    """Records membership-driven side effects in call order."""

    def __init__(self, ring_ids=("w0", "w1", "w2")):
        self.ring = types.SimpleNamespace(node_ids=tuple(ring_ids))
        self.calls = []

    def mark_dead(self, nid, reason=""):
        self.calls.append(("mark_dead", nid))

    def mark_alive(self, nid, host=None, port=None):
        self.calls.append(("mark_alive", nid))

    def add_node(self, nid, client):
        self.calls.append(("add_node", nid))

    def mark_left(self, nid):
        self.calls.append(("mark_left", nid))

    def poll(self):
        pass


def _ms(router=None, stats=None, suspect_timeout_ms=3000.0, clock=None,
        seed_peers=("w1", "w2")):
    ms = SwimMembership(
        "w0", "127.0.0.1", 1, router=router, stats=stats,
        gossip_interval_ms=1000.0, suspect_timeout_ms=suspect_timeout_ms,
        clock=clock or (lambda: 0.0), rng_seed=7,
    )
    ms.seed({nid: ("127.0.0.1", 1) for nid in seed_peers})
    return ms


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


# ---------------------------------------------------------------------------
# precedence + incarnation
# ---------------------------------------------------------------------------


def test_higher_incarnation_always_wins():
    ms = _ms()
    ms.merge([["w1", SUSPECT, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == SUSPECT
    # ALIVE at a HIGHER incarnation outranks the suspicion
    ms.merge([["w1", ALIVE, 1, "127.0.0.1", 1]])
    assert ms.status_of("w1") == ALIVE
    # a stale SUSPECT at the old incarnation no longer bites
    ms.merge([["w1", SUSPECT, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == ALIVE


def test_equal_incarnation_more_severe_status_wins():
    ms = _ms()
    ms.merge([["w1", SUSPECT, 0, "127.0.0.1", 1]])
    # ALIVE at the SAME incarnation does NOT clear a suspicion
    ms.merge([["w1", ALIVE, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == SUSPECT
    ms.merge([["w1", DEAD, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == DEAD
    # and DEAD is not revived by a same-incarnation ALIVE either
    ms.merge([["w1", ALIVE, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == DEAD


def test_left_is_terminal_per_incarnation_rejoin_needs_bump():
    router = _RouterSpy()
    ms = _ms(router=router)
    ms.merge([["w1", LEFT, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == LEFT
    assert ("mark_left", "w1") in router.calls
    ms.merge([["w1", ALIVE, 0, "127.0.0.1", 1]])
    assert ms.status_of("w1") == LEFT  # same incarnation: still gone
    router.calls.clear()
    ms.merge([["w1", ALIVE, 1, "127.0.0.1", 1]])  # the node came back
    assert ms.status_of("w1") == ALIVE
    assert ("mark_alive", "w1") in router.calls  # already in the ring


def test_malformed_digest_rows_are_skipped_not_fatal():
    ms = _ms()
    events = ms.merge([
        ["w1"],                       # too short
        "not-a-row",                  # wrong shape
        ["w2", "no-such-status", 0, "h", 1],
        ["w1", SUSPECT, 0, "127.0.0.1", 1],
    ])
    assert events == [("suspect", "w1")]
    assert ms.status_of("w2") == ALIVE  # untouched by the bogus status


# ---------------------------------------------------------------------------
# self-refutation
# ---------------------------------------------------------------------------


def test_self_suspicion_is_refuted_by_incarnation_bump():
    stats = FabricStats()
    ms = _ms(stats=stats)
    assert ms.describe()["incarnation"] == 0
    events = ms.merge([["w0", SUSPECT, 0, "127.0.0.1", 1]])
    assert events == [("self_refute", "w0")]
    d = ms.describe()
    assert d["incarnation"] == 1  # outbid the suspicion
    assert d["members"]["w0"]["status"] == ALIVE
    assert stats.peek()["FabricMembershipRefuted"] == 1
    # the refutation rides the next digest: ALIVE at the bumped inc
    row = [r for r in ms.digest() if r[0] == "w0"][0]
    assert (row[1], row[2]) == (ALIVE, 1)
    # even a DEAD rumor about self is outbid, never accepted
    ms.merge([["w0", DEAD, 1, "127.0.0.1", 1]])
    assert ms.describe()["incarnation"] == 2
    assert ms.status_of("w0") == ALIVE


# ---------------------------------------------------------------------------
# suspicion expiry -> confirmed dead (injected clock)
# ---------------------------------------------------------------------------


def test_suspicion_expires_to_dead_and_fires_router_mark_dead():
    now = [100.0]
    router = _RouterSpy()
    stats = FabricStats()
    ms = _ms(router=router, stats=stats, suspect_timeout_ms=2000.0,
             clock=lambda: now[0])
    ms.merge([["w1", SUSPECT, 0, "127.0.0.1", 1]])
    assert stats.peek()["FabricMembershipSuspects"] == 1
    now[0] += 1.0
    ms._expire_suspicions()  # before the deadline: nothing happens
    assert ms.status_of("w1") == SUSPECT
    assert router.calls == []
    now[0] += 1.5  # past the 2s suspect window
    ms._expire_suspicions()
    assert ms.status_of("w1") == DEAD
    assert router.calls == [("mark_dead", "w1")]
    peek = stats.peek()
    assert peek["FabricMembershipConfirmedDead"] == 1
    # detection time was banked: last-alive was at seed (t=100)
    _bounds, _buckets, _total, count = stats.detection_snapshot()
    assert count == 1
    assert ms.describe()["suspects"] == []


def test_refutation_before_expiry_cancels_the_death():
    now = [0.0]
    router = _RouterSpy()
    stats = FabricStats()
    ms = _ms(router=router, stats=stats, suspect_timeout_ms=2000.0,
             clock=lambda: now[0])
    ms.merge([["w1", SUSPECT, 0, "127.0.0.1", 1]])
    now[0] += 1.0
    ms.merge([["w1", ALIVE, 1, "127.0.0.1", 1]])  # the refutation lands
    assert ("mark_alive", "w1") in router.calls
    assert stats.peek()["FabricMembershipRefuted"] == 1
    now[0] += 10.0
    ms._expire_suspicions()  # the old deadline must be gone
    assert ms.status_of("w1") == ALIVE
    assert ("mark_dead", "w1") not in router.calls
    assert stats.peek()["FabricMembershipConfirmedDead"] == 0


# ---------------------------------------------------------------------------
# joins: brand-new member -> add_node (ring insertion)
# ---------------------------------------------------------------------------


def test_gossip_discovered_newcomer_ring_inserted_via_peer_factory():
    router = _RouterSpy(ring_ids=("w0", "w1", "w2"))
    made = []
    ms = SwimMembership(
        "w0", "127.0.0.1", 1, router=router,
        peer_factory=lambda nid, h, p: made.append((nid, h, p)) or "client",
        rng_seed=7,
    )
    ms.seed({"w1": ("127.0.0.1", 1), "w2": ("127.0.0.1", 1)})
    events = ms.merge([["w3", ALIVE, 0, "127.0.0.1", 99]])
    assert events == [("joined", "w3")]
    assert made == [("w3", "127.0.0.1", 99)]
    assert ("add_node", "w3") in router.calls
    # the same digest row again is absorbed silently (already alive)
    assert ms.merge([["w3", ALIVE, 0, "127.0.0.1", 99]]) == []


# ---------------------------------------------------------------------------
# exactly-once announcement funnel (satellite 6)
# ---------------------------------------------------------------------------


def test_note_peer_up_is_exactly_once_across_paths():
    """READY/PEER_UP handshake and gossip discovery both funnel through
    note_peer_up/_apply: only the FIRST observation of a revival fires
    a router action."""
    router = _RouterSpy()
    stats = FabricStats()
    ms = _ms(router=router, stats=stats)
    ms.note_peer_down("w1")
    assert router.calls == [("mark_dead", "w1")]
    router.calls.clear()
    assert ms.note_peer_up("w1", host="127.0.0.1", port=2) is True
    assert router.calls == [("mark_alive", "w1")]
    assert stats.peek()["FabricMembershipJoined"] == 1
    # duplicate announcements (harness handshake racing gossip): no-ops
    assert ms.note_peer_up("w1", host="127.0.0.1", port=2) is False
    gossip_echo = ms.merge(
        [[r[0], r[1], r[2], r[3], r[4]] for r in ms.digest()
         if r[0] == "w1"]
    )
    assert gossip_echo == []
    assert router.calls == [("mark_alive", "w1")]  # still exactly one
    assert stats.peek()["FabricMembershipJoined"] == 1


def test_note_peer_down_noop_on_already_dead_or_unknown():
    router = _RouterSpy()
    ms = _ms(router=router)
    assert ms.note_peer_down("w1") is True
    assert ms.note_peer_down("w1") is False  # already dead
    assert ms.note_peer_down("ghost") is False  # never a member
    assert router.calls == [("mark_dead", "w1")]


# ---------------------------------------------------------------------------
# graceful leave
# ---------------------------------------------------------------------------


def test_begin_leave_bumps_incarnation_and_returns_goodbye_digest():
    stats = FabricStats()
    ms = _ms(stats=stats)
    digest = ms.begin_leave()
    me = [r for r in digest if r[0] == "w0"][0]
    assert (me[1], me[2]) == (LEFT, 1)
    assert ms.status_of("w0") == LEFT
    assert stats.peek()["FabricMembershipLeft"] == 1
    assert stats.member_states_snapshot()["w0"] == LEFT
    # a survivor merging the goodbye fires mark_left exactly once
    router = _RouterSpy()
    peer = _ms(router=router, seed_peers=())
    peer.seed({"w0": ("127.0.0.1", 1)})
    # (peer is w0 too in _ms; build a distinct observer instead)
    obs = SwimMembership("w1", "127.0.0.1", 2, router=router, rng_seed=7)
    obs.seed({"w0": ("127.0.0.1", 1), "w2": ("127.0.0.1", 1)})
    assert obs.merge(digest) == [("left", "w0")]
    assert router.calls == [("mark_left", "w0")]
    assert obs.merge(digest) == []  # the goodbye re-delivered: no-op


# ---------------------------------------------------------------------------
# merge failpoint (satellite 2)
# ---------------------------------------------------------------------------


def test_membership_update_failpoint_drops_the_whole_update():
    ms = _ms()
    failpoints.arm("fabric.membership.update", mode="error", count=1)
    assert ms.merge([["w1", DEAD, 5, "127.0.0.1", 1]]) == []
    assert ms.status_of("w1") == ALIVE  # the rumor was dropped
    # gossip re-delivers: the next merge (failpoint exhausted) lands
    assert ms.merge([["w1", DEAD, 5, "127.0.0.1", 1]]) == [
        ("confirmed_dead", "w1")
    ]
    assert ms.status_of("w1") == DEAD
    assert failpoints.fired_count("fabric.membership.update") == 1


def test_gossip_failpoint_sites_are_registered():
    for site in ("fabric.gossip.ping", "fabric.gossip.ack",
                 "fabric.membership.update"):
        assert site in failpoints.KNOWN_SITES, site


# ---------------------------------------------------------------------------
# digest round-trip + wire handlers
# ---------------------------------------------------------------------------


def test_digest_round_trip_converges_two_tables():
    a = SwimMembership("wa", "127.0.0.1", 1, rng_seed=1)
    b = SwimMembership("wb", "127.0.0.1", 2, rng_seed=2)
    a.seed({"wb": ("127.0.0.1", 2), "wc": ("127.0.0.1", 3)})
    a.merge([["wc", SUSPECT, 0, "127.0.0.1", 3]])
    b.merge(a.digest(), via="wa")
    assert b.status_of("wa") == ALIVE
    assert b.status_of("wc") == SUSPECT
    # convergent: merging back produces no further events
    assert a.merge(b.digest(), via="wb") == []


def test_handle_ping_merges_and_answers_ack_with_digest():
    ms = _ms()
    rtype, rp = ms.handle_ping({
        "from": "w1", "digest": [["w9", ALIVE, 0, "127.0.0.1", 9]],
    })
    assert rtype == wire.T_GOSSIP_ACK
    assert rp["node_id"] == "w0"
    assert ms.status_of("w9") == ALIVE  # learned from the prober
    assert {r[0] for r in rp["digest"]} == {"w0", "w1", "w2", "w9"}


def test_handle_join_announces_once_and_returns_members():
    router = _RouterSpy()
    ms = _ms(router=router)
    rtype, rp = ms.handle_join(
        {"node_id": "w7", "host": "127.0.0.1", "port": 77}
    )
    assert rtype == wire.T_JOIN_R
    assert ("add_node", "w7") in router.calls or \
        ("mark_alive", "w7") in router.calls
    assert {r[0] for r in rp["members"]} >= {"w0", "w1", "w2", "w7"}
    n_calls = len(router.calls)
    ms.handle_join({"node_id": "w7", "host": "127.0.0.1", "port": 77})
    assert len(router.calls) == n_calls  # duplicate join: no new action


def test_probe_order_is_round_robin_over_shuffled_members():
    ms = _ms(seed_peers=("w1", "w2", "w3"))
    seen = [ms._next_probe_target()[0] for _ in range(3)]
    assert sorted(seen) == ["w1", "w2", "w3"]  # each probed once/round
    again = [ms._next_probe_target()[0] for _ in range(3)]
    assert sorted(again) == ["w1", "w2", "w3"]
    # dead members drop out of the schedule
    ms.merge([["w2", DEAD, 1, "127.0.0.1", 1]])
    third = [ms._next_probe_target()[0] for _ in range(4)]
    assert "w2" not in third
