"""Fixed-window rate-limit semantics (reference: internal/rate_limit.go)."""

import re

from banjax_tpu.config.schema import Config, RegexWithRate
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RateLimitMatchType,
    RegexRateLimitStates,
)

NS = 1_000_000_000


def make_rule(interval_s=10, hits=3, name="r"):
    return RegexWithRate(
        rule=name,
        regex_string=".*",
        regex=re.compile(".*"),
        interval_ns=interval_s * NS,
        hits_per_interval=hits,
        decision=Decision.CHALLENGE,
    )


def test_first_hit_new_ip():
    states = RegexRateLimitStates()
    seen, result = states.apply("1.2.3.4", make_rule(), 100 * NS)
    assert not seen
    assert not result.exceeded
    assert len(states) == 1


def test_inside_interval_counts_up_and_exceeds():
    states = RegexRateLimitStates()
    rule = make_rule(interval_s=10, hits=3)
    t0 = 100 * NS
    states.apply("ip", rule, t0)
    for i in range(1, 3):
        seen, result = states.apply("ip", rule, t0 + i)
        assert seen
        assert result.match_type is RateLimitMatchType.INSIDE_INTERVAL
        assert not result.exceeded
    # 4th hit: num_hits=4 > 3 → exceeded
    _, result = states.apply("ip", rule, t0 + 3)
    assert result.exceeded


def test_window_restart_outside_interval():
    states = RegexRateLimitStates()
    rule = make_rule(interval_s=10, hits=3)
    t0 = 100 * NS
    states.apply("ip", rule, t0)
    # strictly greater than interval → restart
    _, result = states.apply("ip", rule, t0 + 10 * NS + 1)
    assert result.match_type is RateLimitMatchType.OUTSIDE_INTERVAL
    assert not result.exceeded
    # exactly the interval boundary → still inside
    states2 = RegexRateLimitStates()
    states2.apply("ip", rule, t0)
    _, result = states2.apply("ip", rule, t0 + 10 * NS)
    assert result.match_type is RateLimitMatchType.INSIDE_INTERVAL


def test_reset_to_zero_on_exceed_quirk():
    # After an exceed, hits reset to 0, so the next hits count 1,2,...
    states = RegexRateLimitStates()
    rule = make_rule(interval_s=1000, hits=2)
    t = 100 * NS
    states.apply("ip", rule, t)          # hits=1
    states.apply("ip", rule, t + 1)      # hits=2
    _, r = states.apply("ip", rule, t + 2)  # hits=3 > 2 → exceeded, reset to 0
    assert r.exceeded
    _, r = states.apply("ip", rule, t + 3)  # hits=1
    assert not r.exceeded
    _, r = states.apply("ip", rule, t + 4)  # hits=2
    assert not r.exceeded
    _, r = states.apply("ip", rule, t + 5)  # hits=3 → exceeded again
    assert r.exceeded


def test_new_rule_for_seen_ip_is_first_time():
    states = RegexRateLimitStates()
    t = 100 * NS
    states.apply("ip", make_rule(name="a"), t)
    seen, result = states.apply("ip", make_rule(name="b"), t)
    assert seen
    assert result.match_type is RateLimitMatchType.FIRST_TIME


def test_zero_hits_per_interval_instant_exceed():
    # rules like "instant ban" use hits_per_interval: 0 → every hit exceeds
    states = RegexRateLimitStates()
    rule = make_rule(interval_s=1, hits=0)
    _, r = states.apply("ip", rule, 100 * NS)
    assert r.exceeded
    _, r = states.apply("ip", rule, 101 * NS)
    assert r.exceeded


def test_get_returns_deep_copy():
    states = RegexRateLimitStates()
    rule = make_rule()
    states.apply("ip", rule, 100 * NS)
    copy1, ok = states.get("ip")
    assert ok
    copy1[rule.rule].num_hits = 999
    copy2, _ = states.get("ip")
    assert copy2[rule.rule].num_hits == 1
    _, ok = states.get("nope")
    assert not ok


def test_failed_challenge_states():
    states = FailedChallengeRateLimitStates()
    config = Config(
        too_many_failed_challenges_interval_seconds=1000,
        too_many_failed_challenges_threshold=3,
    )
    for _ in range(3):
        r = states.apply("ip", config)
        assert not r.exceeded
    r = states.apply("ip", config)
    assert r.exceeded
    assert len(states) == 1
