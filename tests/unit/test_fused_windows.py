"""Fused matcher+windows pipeline (matcher/fused_windows.py): one device
dispatch per batch, byte-identical to the serial CPU reference — including
every overflow fallback, which must leave the device window state untouched
(the write gate) and still produce identical output via the classic path."""

import time

import numpy as np
import pytest
import yaml

import bench
from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from tests.mock_banner import MockBanner


def _rules_yaml(patterns, hits=3, interval=20):
    return yaml.safe_dump({
        "regexes_with_rates": [
            {"rule": f"r{i}", "regex": p, "interval": interval,
             "hits_per_interval": hits, "decision": "nginx_block"}
            for i, p in enumerate(patterns)
        ]
    })


def _mk(cls, yaml_text, **ov):
    cfg = config_from_yaml_text(yaml_text)
    for k, v in ov.items():
        setattr(cfg, k, v)
    banner = MockBanner()
    return cls(cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates()), banner


def _key(res):
    return [
        (x.rule_name, x.regex_match, x.skip_host, x.seen_ip,
         None if x.rate_limit_result is None else
         (int(x.rate_limit_result.match_type), x.rate_limit_result.exceeded))
        for x in res.rule_results
    ]


def _drive_pair(patterns, lines, now, **tpu_overrides):
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(TpuMatcher, y, matcher_device_windows=True, **tpu_overrides)
    want = [cpu.consume_line(l, now) for l in lines]
    batch = tpu_overrides.get("matcher_batch_lines", 128)
    got = []
    for s in range(0, len(lines), batch):
        got.extend(tpu.consume_lines(lines[s : s + batch], now))
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert cb.regex_ban_logs == tb.regex_ban_logs
    return tpu


def _lines(patterns, n, now, attack_rate, n_ips=24, seed=3):
    rests = bench.generate_lines(n, patterns, seed=seed,
                                 attack_rate=attack_rate)
    return [
        f"{now + i * 0.0005:.6f} 10.9.{i % n_ips}.1 {r}"
        for i, r in enumerate(rests)
    ]


def test_pipeline_engages_and_matches_oracle():
    patterns = bench.generate_rules(60, seed=31) + [r".*", r"^$"]
    now = time.time()
    lines = _lines(patterns[:-2], 300, now, attack_rate=0.05) + [
        f"{now:.6f} 10.9.0.1 "  # empty rest: ^$ matches
    ]
    tpu = _drive_pair(
        patterns, lines, now + 1,
        matcher_batch_lines=128, matcher_prefilter_cand_frac=0.5,
    )
    assert tpu._fw_pipeline is not None
    assert tpu._fw_pipeline.fused_batches > 0
    assert tpu._fw_pipeline.fallback_batches == 0


def test_candidate_overflow_falls_back_identically():
    """All-matching traffic exceeds the candidate capacity: the pipeline's
    dense bitmap is incomplete, so the batch recomputes single-stage and
    replays classic — output still identical, state never corrupted."""
    patterns = bench.generate_rules(40, seed=32)
    now = time.time()
    lines = _lines(patterns, 200, now, attack_rate=1.0)
    tpu = _drive_pair(
        patterns, lines, now + 1,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0 / 64,
    )
    assert tpu._fw_pipeline is not None
    assert tpu._fw_pipeline.fallback_batches > 0


def test_event_overflow_falls_back_identically():
    """More window events than max_events: the gate drops every state
    write, and the classic apply (which splits) replays the batch."""
    patterns = bench.generate_rules(30, seed=33) + [r".*"]
    now = time.time()
    lines = _lines(patterns[:-1], 256, now, attack_rate=0.1)
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(
        TpuMatcher, y, matcher_device_windows=True,
        matcher_batch_lines=256, matcher_prefilter_cand_frac=1.0,
    )
    # shrink max_events below the per-batch event count (every line fires .*)
    tpu.device_windows.max_events = max(tpu.compiled.n_rules, 64)
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert tpu._fw_pipeline.fallback_batches > 0


def test_multi_chunk_burst_pipelines_identically():
    """One consume_lines call larger than matcher_batch_lines goes through
    the cross-chunk pipelined submit path (chunk N+1 in flight while N
    collects) — output identical to the serial reference."""
    patterns = bench.generate_rules(30, seed=35)
    now = time.time()
    lines = _lines(patterns, 400, now, attack_rate=0.1, n_ips=40, seed=9)
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(
        TpuMatcher, y, matcher_device_windows=True,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0,
    )
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)  # ONE call: 7 chunks pipeline
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert tpu._fw_pipeline.fused_batches >= 6


def test_multi_chunk_with_tight_slot_capacity():
    """Pipelined chunks + a slot capacity too small for two chunks' pins:
    the drain-and-retry path must keep output identical."""
    patterns = bench.generate_rules(20, seed=36)
    now = time.time()
    lines = _lines(patterns, 300, now, attack_rate=0.2, n_ips=90, seed=10)
    y = _rules_yaml(patterns)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(
        TpuMatcher, y, matcher_device_windows=True,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0,
        matcher_window_capacity=48,
    )
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans


def test_mixed_overflow_chunks_keep_apply_order():
    """The ordering hazard the two-program split exists for: a burst where
    SOME chunks overflow (classic fallback) and others ride the fused
    apply, with the same IPs hitting the same rules across chunks. Any
    out-of-order window application shifts which exact hit trips the
    limit — the oracle comparison catches one event of reordering."""
    patterns = bench.generate_rules(25, seed=37)
    now = time.time()
    # alternate benign-ish and attack-heavy 64-line stretches so chunk
    # overflow status flips mid-burst, all on a small shared IP pool
    lines = []
    for stretch in range(6):
        rate = 1.0 if stretch % 2 else 0.05
        rests = bench.generate_lines(64, patterns, seed=40 + stretch,
                                     attack_rate=rate)
        for i, r in enumerate(rests):
            k = len(lines)
            lines.append(
                f"{now + k * 0.0004:.6f} 10.11.{k % 6}.1 {r}"
            )
    y = _rules_yaml(patterns, hits=4, interval=30)
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(
        TpuMatcher, y, matcher_device_windows=True,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=0.25,
    )
    want = [cpu.consume_line(l, now + 1) for l in lines]
    got = tpu.consume_lines(lines, now + 1)  # ONE call: 6 chunks overlap
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    fw = tpu._fw_pipeline
    assert fw.fused_batches > 0 and fw.fallback_batches > 0, (
        fw.fused_batches, fw.fallback_batches,
    )


def test_pipeline_with_eviction_churn():
    """Slot eviction/spill/restore under the pipeline stays lossless."""
    patterns = bench.generate_rules(25, seed=34)
    now = time.time()
    lines = _lines(patterns, 400, now, attack_rate=0.3, n_ips=60, seed=8)
    tpu = _drive_pair(
        patterns, lines, now + 1,
        matcher_batch_lines=64, matcher_prefilter_cand_frac=1.0,
        matcher_window_capacity=16,
    )
    assert tpu.device_windows.eviction_count > 0
    assert tpu._fw_pipeline.fused_batches > 0

@pytest.mark.parametrize("seed", [11, 22, 33, 44])
def test_generative_overflow_interleaving_stress(seed):
    """Randomized knob combinations chosen to force every fallback edge at
    once — candidate overflow, pair overflow, event overflow, slot-refusal
    splits, eviction churn, multi-chunk overlap — across multiple bursts
    on a shared IP pool, byte-identical to the serial CPU reference."""
    import random

    rng = random.Random(seed)
    patterns = [r"GET /attack[0-9]+", r"(?i)scanbot", r"POST /x[a-z]{1,3}",
                r"/probe\.php"]
    now = time.time()
    knobs = dict(
        matcher_batch_lines=rng.choice([32, 64, 96]),
        matcher_prefilter_cand_frac=rng.choice([1.0 / 64, 0.1, 1.0]),
        matcher_window_capacity=rng.choice([0, 8, 16]),
    )
    tpu = None
    y = _rules_yaml(patterns, hits=rng.choice([0, 2, 5]),
                    interval=rng.choice([5, 60]))
    cpu, cb = _mk(CpuMatcher, y)
    tpu, tb = _mk(TpuMatcher, y, matcher_device_windows=True, **knobs)
    if rng.random() < 0.5:
        tpu.device_windows.max_events = max(tpu.compiled.n_rules, 16)
    want, got = [], []
    for burst in range(3):
        n = rng.choice([64, 160, 256])
        lines = _lines(
            patterns, n, now + burst, attack_rate=rng.choice([0.1, 0.6, 1.0]),
            n_ips=rng.choice([4, 24, 200]), seed=seed * 10 + burst,
        )
        want.extend(cpu.consume_line(l, now + burst) for l in lines)
        got.extend(tpu.consume_lines(lines, now + burst))
    assert [_key(a) for a in want] == [_key(b) for b in got]
    assert cb.bans == tb.bans
    assert cb.regex_ban_logs == tb.regex_ban_logs
    # full counter-state parity too (spills restored, no torn fallbacks)
    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates as _R
    assert cpu.rate_limit_states.format_states() == \
        tpu.device_windows.format_states()


def test_jit_program_variants_stay_bounded():
    """Production sends ever-varying batch sizes and line lengths; the
    power-of-two bucketing must keep the number of compiled device
    programs SMALL and convergent — an unbounded jit cache is a slow
    memory leak and a per-batch recompile stall in the hot path."""
    import random

    rng = random.Random(3)
    patterns = [r"GET /at[a-z]+", r"/probe\.php"]
    y = _rules_yaml(patterns, hits=3)
    tpu, _ = _mk(TpuMatcher, y, matcher_device_windows=True,
                 matcher_batch_lines=128)
    now = time.time()
    for i in range(30):
        n = rng.randint(1, 300)
        # vary line lengths too (pads L_p buckets)
        tail = "x" * rng.randint(0, 60)
        lines = [
            f"{now + i:.6f} 10.3.{k % 7}.1 GET h.com GET /at{k}{tail} "
            f"HTTP/1.1 UA -"
            for k in range(n)
        ]
        tpu.consume_lines(lines, now + i)
    fw = tpu._fw_pipeline
    assert fw is not None
    counts = {
        "pipeline_match_programs": len(fw._match_fns),
        "pipeline_apply_programs": len(fw._apply_fns),
    }
    if tpu._prefilter is not None:
        counts["prefilter_programs"] = len(tpu._prefilter._fns)
    assert counts["pipeline_match_programs"] > 0  # the soak really compiled
    assert all(v <= 8 for v in counts.values()), counts
