"""CoalescedLog (httpapi/server.py): batched whole-line O_APPEND writes.

The log helper trades a per-request flush syscall for one delayed
os.write per 50 ms window; lines must come out whole and in order, the
delayed flush must actually fire, and pending lines must survive an
explicit drain (the shutdown path)."""

import asyncio
import os

from banjax_tpu.httpapi.server import CoalescedLog


def _run(coro):
    return asyncio.run(coro)


def test_lines_batched_and_flushed_on_timer(tmp_path):
    p = tmp_path / "log.txt"

    async def scenario():
        with open(p, "a", encoding="utf-8") as f:
            lg = CoalescedLog(f, delay=0.02)
            for i in range(5):
                lg.write(f"line-{i}\n")
            # nothing on disk yet: writes are buffered in the line list
            assert os.path.getsize(p) == 0
            await asyncio.sleep(0.08)
            assert p.read_text() == "".join(f"line-{i}\n" for i in range(5))
            # a second window batches independently
            lg.write("after\n")
            await asyncio.sleep(0.08)
            assert p.read_text().endswith("after\n")

    _run(scenario())


def test_explicit_drain_flushes_pending(tmp_path):
    p = tmp_path / "log.txt"

    async def scenario():
        with open(p, "a", encoding="utf-8") as f:
            lg = CoalescedLog(f, delay=60.0)  # timer won't fire in-test
            lg.write("pending-1\n")
            lg.write("pending-2\n")
            lg._flush()  # the shutdown drain path
            assert p.read_text() == "pending-1\npending-2\n"

    _run(scenario())


def test_multiprocess_style_interleaving_is_line_atomic(tmp_path):
    """Two CoalescedLogs on the same O_APPEND file (the multi-worker
    layout): flushed batches interleave at line boundaries only."""
    p = tmp_path / "log.txt"

    async def scenario():
        with open(p, "a", encoding="utf-8") as f1, \
                open(p, "a", encoding="utf-8") as f2:
            a = CoalescedLog(f1, delay=0.01)
            b = CoalescedLog(f2, delay=0.01)
            for i in range(50):
                a.write(f"a{i}\n")
                b.write(f"b{i}\n")
            await asyncio.sleep(0.1)
        lines = p.read_text().splitlines()
        assert sorted(lines) == sorted(
            [f"a{i}" for i in range(50)] + [f"b{i}" for i in range(50)]
        )
        # each writer's own lines stay in order
        a_lines = [l for l in lines if l.startswith("a")]
        b_lines = [l for l in lines if l.startswith("b")]
        assert a_lines == [f"a{i}" for i in range(50)]
        assert b_lines == [f"b{i}" for i in range(50)]

    _run(scenario())


def test_write_after_close_is_swallowed(tmp_path):
    p = tmp_path / "log.txt"

    async def scenario():
        f = open(p, "a", encoding="utf-8")
        lg = CoalescedLog(f, delay=0.01)
        lg.write("x\n")
        f.close()
        # the delayed flush hits a closed file: swallowed, not raised
        await asyncio.sleep(0.05)

    _run(scenario())
