"""Config schema parsing (reference: internal/config_test.go, config.go:87-131)."""

import pytest

from banjax_tpu.config.schema import Config, config_from_yaml_text
from banjax_tpu.decisions.model import Decision


REGEX_RULE_YAML = """
regexes_with_rates:
  - decision: nginx_block
    hits_per_interval: 800
    interval: 30
    regex: .*
    rule: "All sites/methods: 800 req/30 sec"
    hosts_to_skip:
      example.com: true
  - decision: challenge
    hits_per_interval: 45
    interval: 60
    regex: "^POST .*"
    rule: "All sites/POST: 45 req/60 sec"
"""


def test_regex_with_rate_unmarshal():
    cfg = config_from_yaml_text(REGEX_RULE_YAML)
    assert len(cfg.regexes_with_rates) == 2
    r0 = cfg.regexes_with_rates[0]
    assert r0.rule == "All sites/methods: 800 req/30 sec"
    assert r0.decision is Decision.NGINX_BLOCK
    assert r0.hits_per_interval == 800
    assert r0.interval_ns == 30 * 1_000_000_000
    assert r0.hosts_to_skip == {"example.com": True}
    assert r0.regex.search("anything at all")

    r1 = cfg.regexes_with_rates[1]
    assert r1.regex.search("POST /login HTTP/1.1")
    assert not r1.regex.search("GET /login HTTP/1.1")


def test_fractional_interval_truncates_like_go():
    cfg = config_from_yaml_text(
        """
regexes_with_rates:
  - decision: allow
    hits_per_interval: 1
    interval: 0.5
    regex: x
    rule: r
"""
    )
    assert cfg.regexes_with_rates[0].interval_ns == 500_000_000


def test_bad_regex_fails_load():
    with pytest.raises(ValueError):
        config_from_yaml_text(
            """
regexes_with_rates:
  - decision: allow
    hits_per_interval: 1
    interval: 1
    regex: "(?invalid"
    rule: bad
"""
        )


def test_bad_decision_fails_load():
    with pytest.raises(ValueError):
        config_from_yaml_text(
            """
regexes_with_rates:
  - decision: obliterate
    hits_per_interval: 1
    interval: 1
    regex: x
    rule: bad
"""
        )


def test_per_site_regexes():
    cfg = config_from_yaml_text(
        """
per_site_regexes_with_rates:
  localhost:
    - decision: nginx_block
      hits_per_interval: 0
      interval: 1
      regex: .*blockme.*
      rule: "instant block"
"""
    )
    assert list(cfg.per_site_regexes_with_rates) == ["localhost"]
    assert cfg.per_site_regexes_with_rates["localhost"][0].decision is Decision.NGINX_BLOCK


def test_scalar_and_map_keys():
    cfg = config_from_yaml_text(
        """
config_version: 2021-03-22_00:00:00
expiring_decision_ttl_seconds: 300
iptables_ban_seconds: 300
kafka_brokers:
  - localhost:9094
sha_inv_expected_zero_bits: 10
sitewide_sha_inv_list:
  example.com: block
use_user_agent_in_cookie:
  localhost: true
"""
    )
    assert cfg.expiring_decision_ttl_seconds == 300
    assert cfg.kafka_brokers == ["localhost:9094"]
    assert cfg.sha_inv_expected_zero_bits == 10
    assert cfg.sitewide_sha_inv_list == {"example.com": "block"}
    assert cfg.use_user_agent_in_cookie == {"localhost": True}
    # defaults for untouched keys
    assert cfg.matcher == "cpu"
    assert cfg.debug is False


def test_re2_incompatible_constructs_rejected():
    # Go's RE2 rejects lookaround and backreferences; so must we
    for bad in [r"(?=bot).*crawl", r"(a)\1", r"(?<!x)y", r"(?P<g>a)(?P=g)"]:
        with pytest.raises(ValueError):
            config_from_yaml_text(
                f"""
regexes_with_rates:
  - decision: allow
    hits_per_interval: 1
    interval: 1
    regex: '{bad}'
    rule: bad
"""
            )
    # but the same tokens inside a character class are literal and fine
    cfg = config_from_yaml_text(
        """
regexes_with_rates:
  - decision: allow
    hits_per_interval: 1
    interval: 1
    regex: '[(?=]+x'
    rule: ok
"""
    )
    assert cfg.regexes_with_rates[0].regex.search("(?=x")


def test_wrong_typed_scalars_fail_load():
    # Go yaml.v2 fails the load on type mismatches; so do we
    with pytest.raises(ValueError):
        config_from_yaml_text('sha_inv_expected_zero_bits: "10"')
    with pytest.raises(ValueError):
        config_from_yaml_text("iptables_ban_seconds: banana")
    with pytest.raises(ValueError):
        config_from_yaml_text("debug: 1")
    with pytest.raises(ValueError):
        config_from_yaml_text("kafka_brokers: not-a-list")


def test_python311_only_regex_constructs_rejected():
    # atomic groups and possessive quantifiers are RE2-invalid
    for bad in [r"(?>abc)x", r"a*+b", r"a++", r"x{2,3}+"]:
        with pytest.raises(ValueError):
            config_from_yaml_text(
                f"""
regexes_with_rates:
  - {{decision: allow, hits_per_interval: 1, interval: 1, regex: '{bad}', rule: r}}
"""
            )
    # a literal closing brace before + is valid RE2 and must pass
    cfg = config_from_yaml_text(
        """
regexes_with_rates:
  - {decision: allow, hits_per_interval: 1, interval: 1, regex: 'a}+', rule: r}
"""
    )
    assert cfg.regexes_with_rates[0].regex.search("a}}}")


def test_provenance_slo_flightrec_keys_defaults_and_validation():
    cfg = config_from_yaml_text("")
    assert cfg.provenance_enabled is True
    assert cfg.provenance_ring_size == 2048
    assert cfg.slo_enabled is True
    assert cfg.slo_sample_seconds == 15.0
    assert cfg.slo_batch_latency_target == 0.99
    assert cfg.slo_shed_ratio_max == 0.001
    assert cfg.flightrec_dir == ""
    assert cfg.flightrec_min_interval_s == 60.0
    assert cfg.flightrec_keep == 16

    cfg = config_from_yaml_text(
        "provenance_ring_size: 128\n"
        "slo_batch_latency_target: 0.999\n"
        "flightrec_dir: /tmp/incidents\n"
        "flightrec_keep: 4\n"
    )
    assert cfg.provenance_ring_size == 128
    assert cfg.slo_batch_latency_target == 0.999
    assert cfg.flightrec_dir == "/tmp/incidents"
    assert cfg.flightrec_keep == 4

    for bad in (
        "provenance_ring_size: 0",
        "slo_batch_latency_target: 1.0",
        "slo_batch_latency_target: 0",
        "slo_shed_ratio_max: 0",
        "slo_stale_ratio_max: -1",
        "slo_breaker_open_ratio_max: 0",
        "slo_budget_trip_ratio_max: 0",
        "slo_sample_seconds: -1",
        "flightrec_min_interval_s: -1",
        "flightrec_keep: 0",
        "flightrec_provenance_records: 0",
        'provenance_enabled: "yes"',
    ):
        with pytest.raises(ValueError):
            config_from_yaml_text(bad)


def test_failpoints_admin_key_default_and_typing():
    cfg = config_from_yaml_text("")
    assert cfg.failpoints_admin_enabled is True

    cfg = config_from_yaml_text("failpoints_admin_enabled: false\n")
    assert cfg.failpoints_admin_enabled is False

    with pytest.raises(ValueError, match="failpoints_admin_enabled"):
        config_from_yaml_text('failpoints_admin_enabled: "yes"\n')


def test_mega_state_tiering_keys_defaults_and_validation():
    cfg = config_from_yaml_text("")
    assert cfg.slot_admission_enabled is False
    assert cfg.slot_admission_min_estimate == 0
    assert cfg.warm_tier_enabled is False
    assert cfg.warm_tier_capacity == 1 << 20

    cfg = config_from_yaml_text(
        "matcher_device_windows: true\n"
        "traffic_sketch_enabled: true\n"
        "slot_admission_enabled: true\n"
        "slot_admission_min_estimate: 9\n"
        "warm_tier_enabled: true\n"
        "warm_tier_capacity: 4096\n"
    )
    assert cfg.slot_admission_enabled is True
    assert cfg.slot_admission_min_estimate == 9
    assert cfg.warm_tier_enabled is True
    assert cfg.warm_tier_capacity == 4096

    for bad in (
        # admission requires both the sketch and device windows
        "slot_admission_enabled: true",
        # ... sketch on by default, so it must be REFUSED when off
        "slot_admission_enabled: true\nmatcher_device_windows: true\n"
        "traffic_sketch_enabled: false",
        "slot_admission_enabled: true\ntraffic_sketch_enabled: true",
        # warm tier requires device windows
        "warm_tier_enabled: true",
        "warm_tier_capacity: 0",
        "warm_tier_capacity: -4",
        # Go yaml.v2 strictness: wrong-typed values fail the load
        'slot_admission_enabled: "yes"',
        'slot_admission_min_estimate: "9"',
        "warm_tier_capacity: banana",
    ):
        with pytest.raises(ValueError):
            config_from_yaml_text(bad)


def test_challenge_plane_keys_defaults_and_validation():
    cfg = config_from_yaml_text("")
    assert cfg.challenge_device_verify is False
    assert cfg.challenge_verify_batch_max == 256
    assert cfg.challenge_failure_state_max == 0  # unbounded = reference

    cfg = config_from_yaml_text(
        "challenge_device_verify: true\n"
        "challenge_verify_batch_max: 64\n"
        "challenge_failure_state_max: 4096\n"
    )
    assert cfg.challenge_device_verify is True
    assert cfg.challenge_verify_batch_max == 64
    assert cfg.challenge_failure_state_max == 4096

    for bad in (
        "challenge_verify_batch_max: 0",
        "challenge_verify_batch_max: -1",
        "challenge_failure_state_max: -1",
        # Go yaml.v2 strictness: wrong-typed values fail the load
        'challenge_device_verify: "yes"',
        'challenge_verify_batch_max: "64"',
        "challenge_failure_state_max: banana",
    ):
        with pytest.raises(ValueError):
            config_from_yaml_text(bad)


def test_serve_fastpath_and_ipset_keys_defaults_and_validation():
    cfg = config_from_yaml_text("")
    assert cfg.serve_fastpath_enabled is True
    assert cfg.serve_decision_table_capacity == 65536
    assert cfg.ipset_netlink_enabled is True

    cfg = config_from_yaml_text(
        "serve_fastpath_enabled: false\n"
        "serve_decision_table_capacity: 1024\n"
        "ipset_netlink_enabled: false\n"
    )
    assert cfg.serve_fastpath_enabled is False
    assert cfg.serve_decision_table_capacity == 1024
    assert cfg.ipset_netlink_enabled is False

    for bad in (
        "serve_decision_table_capacity: 0",
        "serve_decision_table_capacity: -1",
        # Go yaml.v2 strictness: wrong-typed values fail the load
        'serve_fastpath_enabled: "yes"',
        'serve_decision_table_capacity: "1024"',
        "ipset_netlink_enabled: banana",
    ):
        with pytest.raises(ValueError):
            config_from_yaml_text(bad)


def test_fleet_observability_keys_defaults_and_validation():
    """ISSUE 20: the fleet observability plane's four config keys."""
    cfg = config_from_yaml_text("")
    assert cfg.fabric_trace_propagation is False
    assert cfg.fleet_metrics_enabled is False
    assert cfg.fleet_scrape_timeout_ms == 750.0
    assert cfg.flightrec_fleet_capture is False

    cfg = config_from_yaml_text(
        "fabric_trace_propagation: true\n"
        "fleet_metrics_enabled: true\n"
        "fleet_scrape_timeout_ms: 250\n"
        "flightrec_fleet_capture: true\n"
    )
    assert cfg.fabric_trace_propagation is True
    assert cfg.fleet_metrics_enabled is True
    assert cfg.fleet_scrape_timeout_ms == 250.0
    assert cfg.flightrec_fleet_capture is True

    for bad in (
        "fleet_scrape_timeout_ms: 0",
        "fleet_scrape_timeout_ms: -5",
        'fleet_metrics_enabled: "yes"',
        'fabric_trace_propagation: "on"',
        'flightrec_fleet_capture: 1.5',
    ):
        with pytest.raises(ValueError):
            config_from_yaml_text(bad)
