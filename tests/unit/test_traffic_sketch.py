"""Traffic-sketch accuracy (ISSUE 8): count-min top-K recall and HLL
relative error fuzzed on skewed (Zipf) and all-distinct synthetic
feeds against exact host-side counts, the conservative-estimate
invariant, slot-table reassignment semantics, and the matcher-level
sampling surface (pull throttle, /traffic summary shape, the
SingleKernelDepthIgnored satellite)."""

import time

import numpy as np
import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.obs import registry
from banjax_tpu.obs.sketch import TrafficSketch, hash_ip, hll_estimate
from tests.mock_banner import MockBanner

RULES_YAML = """
regexes_with_rates:
  - decision: nginx_block
    rule: heavy
    regex: 'GET /attack.*'
    interval: 60
    hits_per_interval: 5
  - decision: nginx_block
    rule: quiet
    regex: 'POST /never.*'
    interval: 60
    hits_per_interval: 5
"""


def _sketch(**kw):
    kw.setdefault("depth", 4)
    kw.setdefault("width", 8192)
    kw.setdefault("hll_p", 12)
    kw.setdefault("pull_seconds", 0.0)
    kw.setdefault("topk", 32)
    kw.setdefault("max_candidates", 8192)
    return TrafficSketch(["heavy", "quiet"], **kw)


def _feed_ids(sketch, ids, pool, slot_of, batch=1024):
    """Stream integer ip-ids through the sketch the way the matcher
    does: distinct (ip, slot) assignments per batch, then one row-level
    update keyed on slots."""
    for s in range(0, len(ids), batch):
        chunk = ids[s : s + batch]
        ips, uslots = [], []
        for i in dict.fromkeys(chunk.tolist()):  # first-appearance order
            if i not in slot_of:
                slot_of[i] = len(slot_of)
            ips.append(pool[i])
            uslots.append(slot_of[i])
        sketch.note_assignments(ips, np.asarray(uslots))
        rows = np.asarray([slot_of[i] for i in chunk], dtype=np.int32)
        sketch.update(rows, len(chunk))


def test_zipf_topk_recall_and_conservative_estimates():
    """The acceptance shape: top-K recall >= 0.9 at k=32 on a Zipf feed
    vs exact counts, and every count-min point estimate conservative
    (never below the true count)."""
    rng = np.random.default_rng(11)
    n_pool = 4096
    pool = [f"10.{i >> 16 & 255}.{i >> 8 & 255}.{i & 255}" for i in range(n_pool)]
    ids = np.minimum(rng.zipf(1.15, 131072) - 1, n_pool - 1)
    exact = np.bincount(ids, minlength=n_pool)

    sk = _sketch()
    _feed_ids(sk, ids, pool, {})
    summary = sk.pull(force=True)
    assert summary["lines_total"] == len(ids)

    k = 32
    kth = np.sort(exact)[-k]
    # ties at the boundary make "the" true top-K ambiguous: a predicted
    # entry is a hit when its TRUE count reaches the kth-largest count
    predicted = [row["ip"] for row in summary["top"][:k]]
    assert len(predicted) == k
    ip_to_id = {ip: i for i, ip in enumerate(pool)}
    hits = sum(1 for ip in predicted if exact[ip_to_id[ip]] >= kth)
    recall = hits / k
    assert recall >= 0.9, f"top-{k} recall {recall} < 0.9"

    # conservative: estimates never undercount (count-min invariant)
    for row in summary["top"]:
        assert row["est_count"] >= exact[ip_to_id[row["ip"]]]
    # the single heaviest source is ranked first
    assert ip_to_id[predicted[0]] == int(np.argmax(exact))
    # heavy-hitter share is its estimate over the folded lines
    assert summary["heavy_hitter_share"] == pytest.approx(
        summary["top"][0]["est_count"] / len(ids), abs=1e-3
    )

    # HLL on the skewed feed: distinct present, not line volume
    true_distinct = int((exact > 0).sum())
    est = summary["distinct_ips_estimate"]
    assert abs(est - true_distinct) / true_distinct < 0.15


def test_all_distinct_hll_relative_error():
    """The all-distinct worst case (rotating-proxy shape): every line a
    new source; HLL must track cardinality within a few percent while
    count-min sees no heavy hitter."""
    n = 32768
    pool = [f"203.{i >> 16 & 255}.{i >> 8 & 255}.{i & 255}" for i in range(n)]
    ids = np.arange(n)
    sk = _sketch()
    _feed_ids(sk, ids, pool, {})
    summary = sk.pull(force=True)
    est = summary["distinct_ips_estimate"]
    assert abs(est - n) / n < 0.15, f"HLL estimate {est} vs true {n}"
    # no source sent more than one line; conservative estimates stay small
    assert summary["top"][0]["est_count"] <= 32


def test_slot_reassignment_rebinds_the_hash():
    """An evicted slot reassigned to a new IP must count for the NEW
    IP: the slot->hash table refresh is what keeps sketch keys stable
    across slot churn."""
    sk = _sketch(width=1024)
    sk.note_assignments(["1.1.1.1"], np.asarray([0]))
    sk.update(np.zeros(10, dtype=np.int32), 10)
    # slot 0 evicted and handed to 2.2.2.2
    sk.note_assignments(["2.2.2.2"], np.asarray([0]))
    sk.update(np.zeros(5, dtype=np.int32), 5)
    assert sk.estimate_ip("1.1.1.1") >= 10
    assert sk.estimate_ip("2.2.2.2") >= 5
    # conservative but not conflated (different hashes, different buckets
    # with overwhelming probability at width 1024 x depth 4)
    assert sk.estimate_ip("2.2.2.2") < 15


def test_candidate_lru_is_bounded():
    sk = _sketch(max_candidates=64)
    pool = [f"9.9.{i >> 8}.{i & 255}" for i in range(512)]
    slot_of = {}
    _feed_ids(sk, np.arange(512), pool, slot_of, batch=128)
    assert len(sk._candidates) <= 64
    # the most recent IPs are the ones retained
    assert pool[-1] in sk._candidates


def test_rule_pressure_is_exact_from_events():
    sk = _sketch()
    sk.note_rule_events([0, 0, 1, 0])
    sk.note_rule_events(iter([1]))
    summary = sk.pull(force=True)
    pressure = {r["rule"]: r["events"] for r in summary["rule_pressure"]}
    assert pressure == {"heavy": 3, "quiet": 2}
    # out-of-range ids are dropped, not crashed on
    sk.note_rule_events([99, -3])
    assert sk.pull(force=True)["rule_pressure"][0]["events"] == 3


def test_pull_is_throttled_to_the_sampling_interval():
    sk = _sketch(pull_seconds=3600.0)
    sk.note_assignments(["4.4.4.4"], np.asarray([0]))
    sk.update(np.zeros(8, dtype=np.int32), 8)
    first = sk.pull()
    assert sk.pull_count == 1
    sk.update(np.zeros(8, dtype=np.int32), 8)
    # within the interval: the cached summary is shared, no new d2h
    assert sk.pull() is first
    assert sk.pull_count == 1
    # force refreshes regardless (the incident-bundle path)
    forced = sk.incident_snapshot()
    assert sk.pull_count == 2
    assert forced["enabled"] is True
    assert forced["lines_total"] == 16


def test_hll_estimate_small_range_correction():
    regs = np.zeros(4096, dtype=np.int32)
    assert hll_estimate(regs) == 0.0
    regs[:100] = 1
    est = hll_estimate(regs)
    assert 50 < est < 300  # linear-counting regime, loose sanity


def test_hash_ip_is_stable_and_32bit():
    h = hash_ip("192.0.2.7")
    assert h == hash_ip("192.0.2.7")
    assert 0 <= h <= 0xFFFF_FFFF
    assert h != hash_ip("192.0.2.8")


# ---- matcher-level integration -------------------------------------------


def _matcher(**cfg_over):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    for k, v in cfg_over.items():
        setattr(cfg, k, v)
    return TpuMatcher(
        cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates()
    ), cfg


def test_matcher_sketch_sees_skewed_flood():
    """Replayed skewed flood through the real fused matcher path: the
    hot IP tops /traffic/top's heap, the distinct estimate tracks the
    pool, and the attacked rule carries the pressure."""
    m, _ = _matcher()
    assert m.traffic_sketch is not None
    now = time.time()
    lines = []
    for i in range(600):
        if i % 3 == 0:
            ip = "66.66.66.66"                      # the heavy hitter
            lines.append(
                f"{now:.6f} {ip} GET h.com GET /attack{i} HTTP/1.1 ua -"
            )
        else:
            ip = f"10.0.{(i // 3) % 4}.{(i // 3) % 50}"
            lines.append(
                f"{now:.6f} {ip} GET h.com GET /page{i} HTTP/1.1 ua -"
            )
    m.consume_lines(lines, now)
    summary = m.traffic_sketch.pull(force=True)
    assert summary["lines_total"] == 600
    assert summary["top"][0]["ip"] == "66.66.66.66"
    assert summary["top"][0]["est_count"] >= 200
    pressure = {r["rule"]: r["events"] for r in summary["rule_pressure"]}
    assert pressure.get("heavy", 0) == 200
    assert "quiet" not in pressure
    true_distinct = len({l.split(" ")[1] for l in lines})
    assert (
        abs(summary["distinct_ips_estimate"] - true_distinct)
        / true_distinct < 0.2
    )


def test_matcher_sketch_disabled_by_config():
    m, _ = _matcher(traffic_sketch_enabled=False)
    assert m.traffic_sketch is None


def test_single_kernel_depth_ignored_gauge():
    """The PR 7 silent-ignore surfaced: drain_resolve_depth > 1 with the
    single-kernel path active flags SingleKernelDepthIgnored on the
    snapshot (and the key is registry-declared)."""
    m, _ = _matcher(drain_resolve_depth=3)
    if not (m._fw_pipeline is not None and m._fw_pipeline.single_kernel):
        pytest.skip("single-kernel path unavailable on this backend")
    assert m.single_kernel_depth_ignored is True
    snap = m.stats.peek(m.device_windows, m)
    assert snap["SingleKernelDepthIgnored"] is True
    assert registry.is_declared_line_key("SingleKernelDepthIgnored")
    # depth 1 (the serial drain) is NOT a lie — nothing is ignored
    m1, _ = _matcher(drain_resolve_depth=1)
    assert m1.single_kernel_depth_ignored is False
    assert m1.stats.peek(m1.device_windows, m1)[
        "SingleKernelDepthIgnored"
    ] is False


def test_traffic_keys_on_snapshot_and_registry():
    m, _ = _matcher()
    now = time.time()
    m.consume_lines(
        [f"{now:.6f} 7.7.7.{i % 9} GET h.com GET /q HTTP/1.1" for i in range(64)],
        now,
    )
    snap = m.stats.peek(m.device_windows, m)
    for key in ("TrafficSketchLines", "TrafficDistinctIpsEst",
                "TrafficHeavyHitterShare", "TrafficSketchPullBytes",
                "TrafficSketchPullAgeSeconds"):
        assert key in snap, key
        assert registry.is_declared_line_key(key), key
    assert snap["TrafficSketchLines"] == 64
    assert snap["TrafficSketchPullBytes"] > 0


def test_pull_records_a_trace_span():
    from banjax_tpu.obs import trace

    tracer = trace.configure(enabled=True, ring_size=64)
    try:
        sk = _sketch()
        sk.update(np.zeros(4, dtype=np.int32), 4)
        sk.pull(force=True)
        names = [s["name"] for s in tracer.snapshot()]
        assert "sketch-pull" in names
    finally:
        trace.configure(enabled=False)
