"""Bot-score integrity check (reference: internal/integrity_check_test.go)."""

import base64
import json

from banjax_tpu.crypto.integrity import (
    IntegrityCheckPayload,
    calc_bot_score,
    calc_bot_score_from_cookie,
    calc_fingerprint,
)


def human_payload() -> IntegrityCheckPayload:
    return IntegrityCheckPayload(
        webdriver=False,
        has_plugins=True,
        gpu_renderer="ANGLE (Apple, Apple M1, OpenGL 4.1)",
        cpu=8,
        memory=8,
        screen_width=2560,
        screen_height=1440,
        window_inner_width=1200,
        window_inner_height=900,
        color_depth=30,
        lang_length=2,
        language="en-US",
        languages=["en-US", "en"],
        timezone="Europe/Berlin",
        platform="MacIntel",
        canvas_fp="abc",
        webgl_fp="def",
        math_fp="ghi",
        webcam=True,
    )


def test_human_scores_zero():
    score, top_factor, wrapper = calc_bot_score(human_payload())
    assert score == 0.0
    assert top_factor == ""
    assert wrapper.hash != ""


def test_webdriver_dominates():
    p = human_payload()
    p.webdriver = True
    score, top_factor, _ = calc_bot_score(p)
    assert top_factor == "webdriver"
    assert 0 < score < 1


def test_headless_stack_scores_high():
    p = IntegrityCheckPayload(
        webdriver=True,
        has_plugins=False,
        gpu_renderer="Google SwiftShader",
        cpu=1,
        memory=1,
        screen_width=800,
        screen_height=600,
        window_inner_width=800,
        window_inner_height=600,
        color_depth=16,
        lang_length=0,
    )
    score, top_factor, _ = calc_bot_score(p)
    assert score == 1.0  # all 31/31 factors fire
    assert top_factor == "webdriver"


def test_empty_payload_scores_one():
    score, top_factor, _ = calc_bot_score_from_cookie("")
    assert score == 1.0
    assert top_factor == "no_payload"


def test_invalid_payload_scores_one():
    score, top_factor, _ = calc_bot_score_from_cookie("not-base64!!")
    assert score == 1.0
    assert top_factor == "err_payload"
    score, top_factor, _ = calc_bot_score_from_cookie(
        base64.standard_b64encode(b"not json").decode()
    )
    assert top_factor == "err_payload"


def test_cookie_roundtrip():
    payload_json = json.dumps(human_payload().to_json_dict())
    b64 = base64.standard_b64encode(payload_json.encode()).decode()
    score, top_factor, wrapper = calc_bot_score_from_cookie(b64)
    assert score == 0.0
    assert wrapper.hash == calc_fingerprint(human_payload())


def test_fingerprint_is_stable_and_sensitive():
    fp1 = calc_fingerprint(human_payload())
    fp2 = calc_fingerprint(human_payload())
    assert fp1 == fp2
    p = human_payload()
    p.canvas_fp = "changed"
    assert calc_fingerprint(p) != fp1


def test_software_renderer_detection():
    for renderer in ("Google SwiftShader", "llvmpipe (LLVM 12.0.0)", "Mesa OffScreen"):
        p = human_payload()
        p.gpu_renderer = renderer
        score, _, _ = calc_bot_score(p)
        assert score > 0


def test_go_json_type_mismatches_score_one():
    # Go's json.Unmarshal rejects these; we must too (score 1.0, err_payload)
    for doc in ['{"webdriver": "false"}', '{"cpu": "8"}', '{"cpu": 1.5}',
                '{"screen": "x"}', '{"languages": [1]}', '[]', '"x"']:
        b64 = base64.standard_b64encode(doc.encode()).decode()
        score, top, _ = calc_bot_score_from_cookie(b64)
        assert (score, top) == (1.0, "err_payload"), doc


def test_json_null_is_zero_payload():
    # Go: unmarshal of null is a no-op -> zero payload gets scored normally
    b64 = base64.standard_b64encode(b"null").decode()
    score, top, _ = calc_bot_score_from_cookie(b64)
    assert top != "err_payload"
    assert 0 < score <= 1.0
    # field-level null keeps the zero value, other fields still checked
    b64 = base64.standard_b64encode(b'{"cpu": null, "webdriver": true}').decode()
    score, top, _ = calc_bot_score_from_cookie(b64)
    assert top == "webdriver"
