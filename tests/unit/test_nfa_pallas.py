"""Differential tests: the Pallas NFA kernel vs the XLA scan vs Python re.

The Pallas kernel (banjax_tpu/matcher/kernels/nfa_match.py) must produce a
match bitmap identical to nfa_jax.match_batch for any compiled ruleset —
that invariant is what lets TpuMatcher switch device backends without any
observable Decision change. Tests run the kernel in interpret mode (plain
JAX on the CPU backend); the compiled TPU path is exercised by bench.py on
real hardware.
"""

import random
import re

import numpy as np
import pytest

from banjax_tpu.matcher import nfa_jax
from banjax_tpu.matcher.encode import encode_for_match
from banjax_tpu.matcher.kernels import nfa_match
from banjax_tpu.matcher.rulec import UnsupportedPattern, compile_rule, compile_rules

REALISTIC_RULES = [
    r"GET /wp-login\.php",
    r"POST /xmlrpc\.php",
    r"(GET|POST) /[a-z-]*\.php",
    r"^GET .* HTTP/1\.1$",
    r"Mozilla/\d+\.\d+ \(compatible; [A-Za-z]+/\d+",
    r"POST /[a-z0-9/]*login[a-z0-9/]*",
    r"[0-9]{1,3}(\.[0-9]{1,3}){3}",
    r"(?i)sqlmap|nikto|nessus",
    r"/\.env$",
    r"/(wp-content|wp-includes)/.*\.php",
    r"HTTP/1\.[01]$",
    r"(admin|administrator|phpmyadmin)/",
]

REALISTIC_LINES = [
    "GET example.com GET /wp-login.php HTTP/1.1",
    "POST example.com POST /xmlrpc.php HTTP/1.1",
    "GET example.com GET /index.html HTTP/1.1",
    "POST example.com POST /user/login HTTP/1.1",
    "GET example.com GET /.env HTTP/1.1",
    "GET example.com GET /wp-content/plugins/x.php HTTP/1.1",
    "GET example.com GET /assets/app.js HTTP/1.1",
    "GET example.com GET /phpmyadmin/ HTTP/1.0",
    "sqlmap/1.5 probe run",
    "client 10.22.0.19 did a thing",
    "",
    "x",
]


def run_both(patterns, lines, n_shards=1, max_len=96, block_b=256):
    compiled = compile_rules(patterns, n_shards=n_shards)
    cls_ids, lens, host_eval = encode_for_match(compiled, lines, max_len)
    assert not host_eval.any(), "test lines must be device-evaluable"
    ref = np.asarray(
        nfa_jax.match_batch(
            nfa_jax.match_params(compiled), cls_ids, lens, compiled.n_rules
        )
    )
    prep = nfa_match.prepare(compiled)
    got = nfa_match.match_batch_pallas(
        prep, cls_ids, lens, block_b=block_b, interpret=True
    )
    return got, ref, compiled


def assert_equal_and_oracle(patterns, lines, **kw):
    got, ref, compiled = run_both(patterns, lines, **kw)
    np.testing.assert_array_equal(got, ref)
    for j, pat in enumerate(patterns):
        if not compiled.device_ok[j]:
            continue
        rx = re.compile(pat)
        for i, line in enumerate(lines):
            assert bool(got[i, j]) == (rx.search(line) is not None), (pat, line)


class TestPallasKernel:
    def test_realistic_rules_single_shard(self):
        assert_equal_and_oracle(REALISTIC_RULES, REALISTIC_LINES)

    @pytest.mark.parametrize("n_shards", [2, 4])
    def test_sharded_grid(self, n_shards):
        assert_equal_and_oracle(REALISTIC_RULES, REALISTIC_LINES, n_shards=n_shards)

    def test_batch_padding(self):
        # batch sizes around the block boundary: 1, block-1, block, block+1
        for n in (1, 3, 8):
            lines = (REALISTIC_LINES * 3)[:n]
            assert_equal_and_oracle(REALISTIC_RULES, lines, block_b=8)

    def test_long_branch_cross_word_carry(self):
        # a 90-char literal spans 3 words: exercises the lane-roll carry
        lit = "abcdefghij" * 9
        pats = [re.escape(lit), re.escape(lit[:40]) + r"\d+" + re.escape(lit[50:])]
        lines = [lit, lit[:40] + "123" + lit[50:], lit[:-1], "zzz" + lit + "zzz"]
        assert_equal_and_oracle(pats, lines, max_len=128)

    def test_anchors_and_empty(self):
        pats = [r"^abc", r"abc$", r"^abc$", r"^$", r"a*"]
        lines = ["abc", "xabc", "abcx", "", "a", "zz"]
        assert_equal_and_oracle(pats, lines)

    def test_fuzz_vs_xla_scan(self):
        rng = random.Random(20260730)
        alphabet = "abxy01 /."

        def gen_pattern():
            parts = []
            for _ in range(rng.randint(1, 5)):
                atom = rng.choice(
                    [re.escape(rng.choice(alphabet)), r"\d", r"[ab]", ".", r"\w"]
                )
                if rng.random() < 0.25:
                    atom += rng.choice(["*", "+", "?"])
                parts.append(atom)
            p = "".join(parts)
            if rng.random() < 0.15:
                p = "^" + p
            if rng.random() < 0.15:
                p = p + "$"
            return p

        patterns = []
        while len(patterns) < 50:
            p = gen_pattern()
            try:
                re.compile(p)
                compile_rule(p)
            except (UnsupportedPattern, re.error):
                continue
            patterns.append(p)
        lines = [
            "".join(rng.choice(alphabet) for _ in range(rng.randint(0, 30)))
            for _ in range(100)
        ]
        assert_equal_and_oracle(patterns, lines, n_shards=2, block_b=64)

    def test_vmem_guard(self):
        compiled = compile_rules([r"a{4000,5000}b{4000,5000}c{4000,5000}" + "d" * 120000])
        if compiled.device_ok[0]:
            with pytest.raises(nfa_match.PallasUnsupported):
                nfa_match.prepare(compiled)

    def test_roll_branch_matches_fallback(self):
        """The pltpu.roll carry path — the branch compiled Mosaic runs in
        production — must agree bit-for-bit with the concatenate fallback.
        Uses 90-char literals so state genuinely crosses word boundaries
        (the roll is exactly the cross-word carry)."""
        import jax.numpy as jnp

        lit = "abcdefghij" * 9
        pats = [re.escape(lit), re.escape(lit[:40]) + r"\d+" + re.escape(lit[50:])]
        lines = [lit, lit[:40] + "123" + lit[50:], lit[:-1], "zz" + lit + "zz"]
        compiled = compile_rules(pats)
        cls_ids, lens, _ = encode_for_match(compiled, lines, 128)
        prep = nfa_match.prepare(compiled)
        B, L = 8, 96
        cls_t = np.zeros((L, B), dtype=np.int32)
        cls_t[: cls_ids.shape[1], : len(lines)] = cls_ids[:, :L].T
        lens_p = np.zeros(B, dtype=np.int32)
        lens_p[: len(lines)] = lens
        outs = {}
        for roll in (False, True):
            call = nfa_match._build_raw_call(
                B, L, prep.n_classes_p, prep.n_shards, prep.wps_p,
                block_b=8, interpret=True, cols=8, force_roll=roll,
            )
            maxtile = np.asarray([-(-int(lens_p.max()) // 8)], dtype=np.int32)
            outs[roll] = np.asarray(
                call(jnp.asarray(maxtile), jnp.asarray(cls_t),
                     jnp.asarray(lens_p[None, :]), prep.btab_t, prep.masks_t)
            )
        np.testing.assert_array_equal(outs[True], outs[False])
        assert outs[True].any(), "carry test must produce accept bits"

    @pytest.mark.parametrize("cols", [8, 32])
    def test_wide_byte_tiles(self, cols):
        """cols=32 (the TPU production tile width) is semantics-identical
        to the default 8-column tile."""
        compiled = compile_rules(REALISTIC_RULES)
        cls_ids, lens, _ = encode_for_match(compiled, REALISTIC_LINES, 96)
        prep = nfa_match.prepare(compiled)
        got = nfa_match.match_batch_pallas(
            prep, cls_ids, lens, block_b=8, interpret=True, cols=cols
        )
        ref = np.asarray(
            nfa_jax.match_batch(
                nfa_jax.match_params(compiled), cls_ids, lens, compiled.n_rules
            )
        )
        np.testing.assert_array_equal(got, ref)


class TestRunnerBackend:
    def test_tpu_matcher_pallas_interpret_end_to_end(self):
        """TpuMatcher with the pallas-interpret backend produces the same
        RuleResults as with the XLA backend."""
        from banjax_tpu.config.schema import Config, RegexWithRate
        from banjax_tpu.decisions.model import Decision
        from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
        from banjax_tpu.decisions.static_lists import StaticDecisionLists
        from banjax_tpu.matcher.runner import TpuMatcher
        from tests.mock_banner import MockBanner

        rule = RegexWithRate.from_yaml_dict(
            {
                "rule": "wp probe",
                "regex": r"GET /wp-login\.php",
                "interval": 10,
                "hits_per_interval": 1,
                "decision": "nginx_block",
            }
        )

        def mk(backend):
            cfg = Config(
                regexes_with_rates=[rule], matcher_backend=backend
            )
            banner = MockBanner()
            m = TpuMatcher(
                cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates()
            )
            now = 1700000000.0
            lines = [
                f"{now} 1.2.3.4 GET example.com GET /wp-login.php HTTP/1.1",
                f"{now} 1.2.3.4 GET example.com GET /wp-login.php HTTP/1.1",
                f"{now} 5.6.7.8 GET example.com GET /ok.html HTTP/1.1",
            ]
            results = m.consume_lines(lines, now_unix=now)
            return results, banner

        r_xla, b_xla = mk("xla")
        r_pal, b_pal = mk("pallas-interpret")
        assert b_pal.bans == b_xla.bans and b_pal.bans
        for a, b in zip(r_xla, r_pal):
            assert len(a.rule_results) == len(b.rule_results)
            for ra, rb in zip(a.rule_results, b.rule_results):
                assert ra.rule_name == rb.rule_name
                assert ra.regex_match == rb.regex_match


def test_word_align_32_and_128_agree(monkeypatch):
    """The sub-lane (32) and conservative lane (128) shard paddings produce
    identical match bitmaps — the padding is dead words only (interpret
    mode; the compiled-Mosaic tiling of the 32-row slabs is verified on
    hardware by bench.py's pallas parity assert)."""
    from banjax_tpu.matcher import rulec as rulec_mod
    from banjax_tpu.matcher.kernels import nfa_match as nm

    patterns = [r"GET /admin/[a-z]+\.php", r"(?i)sqlmap", r"POST /wp[0-9]{1,3}"]
    lines = ["GET /admin/shell.php x", "Mozilla SQLMap/1.0", "POST /wp42",
             "benign / nothing", ""]
    outs = {}
    for align in (32, 128):
        monkeypatch.setattr(rulec_mod, "KERNEL_WORD_ALIGN", align)
        monkeypatch.setattr(nm, "KERNEL_WORD_ALIGN", align)
        compiled = compile_rules(patterns, n_shards="auto")
        prep = nm.prepare(compiled)
        assert prep.wps_p % align == 0
        cls, lens, _ = encode_for_match(compiled, lines, 64)
        outs[align] = nm.match_batch_pallas(prep, cls, lens, interpret=True)
    assert (outs[32] == outs[128]).all()
