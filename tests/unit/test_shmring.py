"""SPSC shared-memory ring (native/shmring.py + shmring.c): the
co-located-shard transport behind LinePipe's shm mode (ISSUE 18).

Both arms run where possible: the compiled C ring (futex waits) and
the layout-compatible pure-Python fallback.  The contract under test:
all-or-nothing frame writes, wraparound correctness, loud FrameError
on oversize or torn frames, None (not garbage) on timeout.
"""

import threading

import pytest

from banjax_tpu.fabric import wire
from banjax_tpu.native import shmring
from banjax_tpu.native.shmring import (
    RING_HEADER,
    RingTimeout,
    ShmRing,
    read_frame,
    write_frame,
)


def _arms():
    arms = ["py"]
    if shmring.available():
        arms.insert(0, "native")
    return arms


@pytest.fixture(params=_arms())
def arm(request, monkeypatch):
    if request.param == "py":
        # force the pure-Python fallback even when the .so compiled
        monkeypatch.setattr(shmring, "_load", lambda: None)
    return request.param


def test_create_attach_roundtrip(arm):
    owner = ShmRing(capacity=1 << 14)
    try:
        other = ShmRing(name=owner.name, capacity=1 << 14)
        try:
            assert not owner.readable()
            owner.write(b"hello-ring", timeout_s=1.0)
            assert other.read(10, timeout_s=1.0) == b"hello-ring"
            # and the other direction through the same buffer
            other.write(b"back", timeout_s=1.0)
            assert owner.read(4, timeout_s=1.0) == b"back"
        finally:
            other.close()
    finally:
        owner.close()


def test_wraparound_many_times_preserves_bytes(arm):
    cap = 1 << 12
    ring = ShmRing(capacity=cap)
    try:
        total = 0
        for i in range(200):  # ~12x the capacity in traffic
            blob = bytes([i & 0xFF]) * (100 + (i * 37) % 150)
            ring.write(blob, timeout_s=1.0)
            got = ring.read(len(blob), timeout_s=1.0)
            assert got == blob
            total += len(blob)
        assert total > 4 * cap
        assert ring.readable() == 0 and ring.occupancy() == 0.0
    finally:
        ring.close()


def test_interleaved_producer_consumer_threads(arm):
    ring = ShmRing(capacity=1 << 12)
    frames = [
        wire.encode_lines_v2(i, [f"l{i}-{j}" for j in range(8)])
        for i in range(100)
    ]

    got = []

    def consume():
        while len(got) < len(frames):
            out = read_frame(ring, idle_timeout_s=5.0)
            if out is None:
                return
            got.append(out)

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    try:
        for f in frames:
            write_frame(ring, f, timeout_s=5.0)
        t.join(timeout=20)
        assert not t.is_alive()
        assert len(got) == len(frames)
        for i, (ftype, body) in enumerate(got):
            assert ftype == wire.T_LINES_V2
            fr = wire.decode_lines_v2(body)
            assert fr.seq == i and len(fr.lines) == 8
    finally:
        ring.close()


def test_oversize_frame_is_frame_error_not_a_hang(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        with pytest.raises(wire.FrameError):
            ring.write(b"x" * (1 << 13), timeout_s=0.2)
        # a frame helper hits the same wall
        big = wire.encode_lines_v2(1, ["y" * (1 << 13)])
        with pytest.raises(wire.FrameError):
            write_frame(ring, big, timeout_s=0.2)
    finally:
        ring.close()


def test_full_ring_write_times_out_loudly(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        fill = b"z" * ((1 << 12) - 1)
        ring.write(fill, timeout_s=1.0)
        with pytest.raises(RingTimeout):
            ring.write(b"overflow", timeout_s=0.05)
        # drain, then the same write lands
        assert ring.read(len(fill), timeout_s=1.0) == fill
        ring.write(b"overflow", timeout_s=1.0)
        assert ring.read(8, timeout_s=1.0) == b"overflow"
    finally:
        ring.close()


def test_read_timeout_returns_none(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        assert ring.read(8, timeout_s=0.05) is None
        assert read_frame(ring, idle_timeout_s=0.05) is None
    finally:
        ring.close()


def test_occupancy_tracks_buffered_bytes(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        assert ring.readable() == 0 and ring.occupancy() == 0.0
        ring.write(b"a" * 100, timeout_s=1.0)
        ring.write(b"b" * 50, timeout_s=1.0)
        assert ring.readable() == 150
        assert ring.occupancy() == pytest.approx(150 / (1 << 12))
        ring.read(100, timeout_s=1.0)
        assert ring.readable() == 50
    finally:
        ring.close()


def test_torn_frame_header_without_body_is_frame_error(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        # header promises a 10-byte body that never arrives
        ring.write(wire._HEADER.pack(11, wire.T_ACK), timeout_s=1.0)
        with pytest.raises(wire.FrameError, match="torn"):
            read_frame(ring, idle_timeout_s=0.5)
    finally:
        ring.close()


def test_bad_frame_length_in_ring_is_frame_error(arm):
    ring = ShmRing(capacity=1 << 12)
    try:
        ring.write(
            wire._HEADER.pack(wire.MAX_FRAME_BYTES + 1, wire.T_ACK),
            timeout_s=1.0,
        )
        with pytest.raises(wire.FrameError):
            read_frame(ring, idle_timeout_s=0.5)
    finally:
        ring.close()


def test_capacity_must_be_power_of_two(arm):
    with pytest.raises(ValueError):
        ShmRing(capacity=3000)


def test_attach_inherits_capacity_from_segment_header(arm):
    owner = ShmRing(capacity=1 << 12)
    try:
        # the header, not the caller's guess, is authoritative
        other = ShmRing(name=owner.name, capacity=1 << 13)
        try:
            assert other.capacity == 1 << 12
        finally:
            other.close()
    finally:
        owner.close()


def test_attach_to_non_ring_segment_is_loud(arm):
    from multiprocessing import shared_memory

    seg = shared_memory.SharedMemory(create=True, size=RING_HEADER + 64)
    try:
        with pytest.raises(RuntimeError, match="not a fabric ring"):
            ShmRing(name=seg.name)
    finally:
        seg.close()
        seg.unlink()


def test_python_and_native_layouts_interoperate():
    """The fallback must speak the exact same header layout: bytes
    written by the native ring are read back by the Python path."""
    if not shmring.available():
        pytest.skip("native ring not compiled")
    native = ShmRing(capacity=1 << 12)
    try:
        pyside = ShmRing(name=native.name, capacity=1 << 12)
        pyside._lib = None  # force the _py_* path on this handle
        try:
            native.write(b"native->py", timeout_s=1.0)
            assert pyside.read(10, timeout_s=1.0) == b"native->py"
            pyside.write(b"py->native", timeout_s=1.0)
            assert native.read(10, timeout_s=1.0) == b"py->native"
        finally:
            pyside.close()
    finally:
        native.close()


def test_header_offsets_are_frozen():
    # layout stability: shmring.c and the Python fallback agree on these
    assert RING_HEADER == 64
    assert (shmring._OFF_MAGIC, shmring._OFF_SIZE,
            shmring._OFF_HEAD, shmring._OFF_TAIL) == (0, 8, 16, 24)
