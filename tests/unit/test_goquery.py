"""Go net/url QueryEscape/QueryUnescape parity (utils/goquery.py).

The escape fast path (urllib quote_plus) is differential-tested against
the explicit byte loop that mirrors Go's algorithm; unescape keeps Go's
fail-on-malformed behavior (url.QueryUnescape returns an error where
urllib would pass bad escapes through — challenge_response.go:77-84
depends on the failure)."""

import random

import pytest

from banjax_tpu.utils.goquery import (
    go_query_escape,
    go_query_escape_ref,
    go_query_unescape,
)


def test_escape_differential_fuzz():
    rng = random.Random(3)
    cases = ["", " ", "+", "a b+c/d=e~f_g-h.i", "héllo wörld", "€✓",
             "\x00\x7f\xff", "=" * 40]
    for _ in range(3000):
        cases.append(
            "".join(chr(rng.randint(0, 0x2FF)) for _ in range(rng.randint(0, 24)))
        )
    for s in cases:
        assert go_query_escape(s) == go_query_escape_ref(s), repr(s)


def test_escape_known_values():
    # url.QueryEscape fixed points
    assert go_query_escape("a b") == "a+b"
    assert go_query_escape("a+b") == "a%2Bb"
    assert go_query_escape("AZaz09-_.~") == "AZaz09-_.~"
    assert go_query_escape("/=&?") == "%2F%3D%26%3F"


def test_round_trip():
    rng = random.Random(4)
    for _ in range(500):
        s = "".join(chr(rng.randint(0, 0x24F)) for _ in range(rng.randint(0, 20)))
        assert go_query_unescape(go_query_escape(s)) == s


def test_unescape_fails_on_malformed_like_go():
    for bad in ("%", "%z1", "%1", "abc%G0", "%%%"):
        with pytest.raises(ValueError):
            go_query_unescape(bad)


def test_unescape_plus_is_space():
    assert go_query_unescape("a+b%20c") == "a b c"
