"""Decision provenance ledger (obs/provenance.py): per-source rings,
counters, explain/tail queries, the disabled fast path, and thread
safety under concurrent recording."""

import threading

import pytest

from banjax_tpu.obs import provenance, trace


@pytest.fixture(autouse=True)
def _fresh_ledger():
    ledger = provenance.configure(enabled=True, ring_size=64)
    yield ledger
    provenance.configure(enabled=True)
    trace.configure(enabled=False)


def test_record_and_explain_roundtrip(_fresh_ledger):
    provenance.record(provenance.SOURCE_RATE_LIMIT, "9.9.9.9",
                      "NginxBlock", rule="crawler", rule_index=3, hits=51)
    provenance.record(provenance.SOURCE_KAFKA, "9.9.9.9", "Challenge",
                      rule="challenge_ip")
    provenance.record(provenance.SOURCE_KAFKA, "8.8.8.8", "NginxBlock",
                      rule="block_ip")

    recs = provenance.get_ledger().explain("9.9.9.9")
    assert [r["source"] for r in recs] == ["rate_limit", "kafka"]
    assert recs[0]["rule"] == "crawler"
    assert recs[0]["rule_index"] == 3
    assert recs[0]["hits"] == 51
    assert recs[0]["decision"] == "NginxBlock"
    assert recs[0]["time_unix"] > 0 and recs[0]["t_monotonic"] > 0
    # records come back oldest-first across sources
    assert recs[0]["t_monotonic"] <= recs[1]["t_monotonic"]
    assert provenance.get_ledger().explain("1.1.1.1") == []


def test_counters_per_source_and_decision(_fresh_ledger):
    for _ in range(3):
        provenance.record(provenance.SOURCE_STATIC, "1.2.3.4", "Allow")
    provenance.record(provenance.SOURCE_STATIC, "1.2.3.4", "NginxBlock")
    c = provenance.get_ledger().counters()
    assert c[("static_list", "Allow")] == 3
    assert c[("static_list", "NginxBlock")] == 1


def test_ring_wraps_keeping_newest(_fresh_ledger):
    ledger = provenance.configure(enabled=True, ring_size=16)
    for i in range(40):
        ledger.record(provenance.SOURCE_EXPIRY, f"10.0.0.{i}", "Challenge")
    recs = ledger.tail(100)
    assert len(recs) == 16
    assert recs[-1]["ip"] == "10.0.0.39"
    assert recs[0]["ip"] == "10.0.0.24"
    # counters keep the full total even after the ring wrapped
    assert ledger.counters()[("expiry", "Challenge")] == 40


def test_disabled_ledger_records_nothing():
    ledger = provenance.configure(enabled=False)
    provenance.record(provenance.SOURCE_KAFKA, "1.2.3.4", "NginxBlock")
    assert ledger.explain("1.2.3.4") == []
    assert ledger.counters() == {}
    assert ledger.total_records() == 0


def test_trace_id_defaults_to_ambient_span(_fresh_ledger):
    tracer = trace.configure(enabled=True, ring_size=64)
    tid = tracer.new_trace()
    with tracer.span("drain", tid, parent=0):
        provenance.record(provenance.SOURCE_RATE_LIMIT, "7.7.7.7",
                          "NginxBlock", rule="r")
    provenance.record(provenance.SOURCE_RATE_LIMIT, "7.7.7.7",
                      "NginxBlock", rule="r")
    recs = provenance.get_ledger().explain("7.7.7.7")
    assert recs[0]["trace_id"] == tid   # inside the span: attributed
    assert recs[1]["trace_id"] == 0     # outside: no ambient trace


def test_unknown_source_never_raises(_fresh_ledger):
    provenance.record("not-a-source", "1.1.1.1", "Allow")
    assert provenance.get_ledger().explain("1.1.1.1")  # filed, not lost


def test_concurrent_recording_is_consistent(_fresh_ledger):
    ledger = provenance.configure(enabled=True, ring_size=4096)
    n_threads, per_thread = 4, 250

    def writer(k):
        for i in range(per_thread):
            ledger.record(provenance.SOURCE_KAFKA, f"10.{k}.0.{i % 256}",
                          "NginxBlock", rule=f"t{k}")

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert ledger.total_records() == n_threads * per_thread
    assert ledger.counters()[("kafka", "NginxBlock")] == (
        n_threads * per_thread
    )
    assert len(ledger.tail(10_000)) == n_threads * per_thread
