"""Shared-memory failed-challenge rate limiter (native/shmstate.c).

Differential against the pure-Python FailedChallengeRateLimitStates
(decisions/rate_limit.py) — same window quirks (strict >, exceed resets
hits to 0; rate_limit.go:125-156) — plus the multi-process counting
property the table exists for.
"""

import multiprocessing
import random
import time
import types

import pytest

from banjax_tpu.decisions.rate_limit import FailedChallengeRateLimitStates
from banjax_tpu.native import shm

pytestmark = pytest.mark.skipif(
    not shm.available(), reason="no C compiler for native shmstate"
)


def _cfg(interval_s=60, threshold=3):
    return types.SimpleNamespace(
        too_many_failed_challenges_interval_seconds=interval_s,
        too_many_failed_challenges_threshold=threshold,
    )


def test_differential_sequential():
    cfg = _cfg(interval_s=1, threshold=3)
    table = shm.ShmFailedChallengeStates(capacity=1024)
    py = FailedChallengeRateLimitStates()
    rng = random.Random(7)
    ips = [f"10.1.{i // 256}.{i % 256}" for i in range(80)]
    try:
        for step in range(3000):
            ip = rng.choice(ips)
            a = table.apply(ip, cfg)
            b = py.apply(ip, cfg)
            assert (a.match_type, a.exceeded) == (b.match_type, b.exceeded), (
                step, ip, a, b,
            )
        assert len(table) == len(py)
        assert table.dropped == 0
    finally:
        table.close()
        table.unlink()


def test_window_rollover_differential():
    """OUTSIDE_INTERVAL transitions with a real elapsed interval."""
    cfg = _cfg(interval_s=0, threshold=2)  # every >0ns gap rolls the window
    table = shm.ShmFailedChallengeStates(capacity=64)
    py = FailedChallengeRateLimitStates()
    try:
        for _ in range(20):
            a = table.apply("9.9.9.9", cfg)
            b = py.apply("9.9.9.9", cfg)
            assert (a.match_type, a.exceeded) == (b.match_type, b.exceeded)
            time.sleep(0.001)
    finally:
        table.close()
        table.unlink()


def test_format_states_shape():
    cfg = _cfg()
    table = shm.ShmFailedChallengeStates(capacity=64)
    try:
        table.apply("1.2.3.4", cfg)
        table.apply("1.2.3.4", cfg)
        out = table.format_states()
        # same line shape as FailedChallengeRateLimitStates.format_states
        assert out.startswith("1.2.3.4,: interval_start: ")
        assert ", num hits: 2\n" in out
    finally:
        table.close()
        table.unlink()


def test_attach_shares_state():
    cfg = _cfg()
    owner = shm.ShmFailedChallengeStates(capacity=64)
    try:
        owner.apply("5.5.5.5", cfg)
        attached = shm.ShmFailedChallengeStates(name=owner.name)
        r = attached.apply("5.5.5.5", cfg)
        assert r.match_type.name == "INSIDE_INTERVAL"
        assert len(attached) == 1
        attached.close()
    finally:
        owner.close()
        owner.unlink()


def test_full_window_steals_stalest_expired():
    """With every slot in the probe window expired, a new key steals one
    (semantically identical to an OUTSIDE_INTERVAL restart)."""
    cfg = _cfg(interval_s=0, threshold=100)  # everything expires instantly
    table = shm.ShmFailedChallengeStates(capacity=64)  # tiny: forces fills
    try:
        for i in range(500):
            r = table.apply(f"ip-{i}", cfg)
            assert r.match_type.name == "FIRST_TIME"
        time.sleep(0.001)
        assert table.dropped == 0  # expired slots always stealable
        assert len(table) <= 64
    finally:
        table.close()
        table.unlink()


def test_full_window_unexpired_degrades_with_dropped_count():
    cfg = _cfg(interval_s=3600, threshold=100)
    table = shm.ShmFailedChallengeStates(capacity=64)
    try:
        for i in range(500):
            table.apply(f"ip-{i}", cfg)
        # 64 slots, probe window 64: once full and nothing expired, new
        # keys degrade to unstored first hits
        assert table.dropped > 0
        r = table.apply("brand-new-ip", cfg)
        assert r.match_type.name == "FIRST_TIME" and not r.exceeded
    finally:
        table.close()
        table.unlink()


def _hammer(name: str, n: int, q) -> None:
    t = shm.ShmFailedChallengeStates(name=name)
    cfg = _cfg(interval_s=3600, threshold=3)
    exceeded = 0
    for _ in range(n):
        if t.apply("77.77.77.77", cfg).exceeded:
            exceeded += 1
    t.close()
    q.put(exceeded)


def test_multiprocess_counting_exact():
    """4 processes x 1000 applies on ONE ip: with threshold T the counter
    cycles 1..T+1 (exceed resets to 0), so exactly N // (T+1) exceeds must
    be observed across all processes — the per-slot lock serializes every
    transition, no hit may be lost or double-counted."""
    ctx = multiprocessing.get_context("spawn")
    table = shm.ShmFailedChallengeStates(capacity=256)
    try:
        q = ctx.Queue()
        per = 1000
        procs = [
            ctx.Process(target=_hammer, args=(table.name, per, q))
            for _ in range(4)
        ]
        for p in procs:
            p.start()
        for p in procs:
            p.join(timeout=60)
            assert p.exitcode == 0
        total_exceeded = sum(q.get(timeout=5) for _ in range(4))
        assert total_exceeded == (4 * per) // 4  # T=3 -> cycle length 4
        assert table.dropped == 0
    finally:
        table.close()
        table.unlink()


def test_overlong_keys_truncate_consistently():
    """Keys beyond the 104-byte slot field truncate; the SAME overlong
    key keeps counting as one stream (truncation is deterministic), and
    the python-limiter differential only applies to in-range keys (real
    IPs are <= 45 chars)."""
    cfg = _cfg(interval_s=60, threshold=3)
    table = shm.ShmFailedChallengeStates(capacity=64)
    try:
        long_key = "x" * 300
        r1 = table.apply(long_key, cfg)
        r2 = table.apply(long_key, cfg)
        assert r1.match_type.name == "FIRST_TIME"
        assert r2.match_type.name == "INSIDE_INTERVAL"
        # a different key sharing the first 104 bytes intentionally maps
        # to the same counter (documented truncation)
        r3 = table.apply("x" * 104 + "DIFFERENT", cfg)
        assert r3.match_type.name == "INSIDE_INTERVAL"
        assert len(table) == 1
    finally:
        table.close()
        table.unlink()


def test_empty_ip_counts_like_python_limiter():
    """The zero-length-key sentinel: '' must accumulate (and exceed) like
    the python limiter, not reset every time (shmstate.c marks empty
    slots with key_len 0, so '' maps to a one-NUL sentinel)."""
    cfg = _cfg(interval_s=60, threshold=2)
    table = shm.ShmFailedChallengeStates(capacity=64)
    py = FailedChallengeRateLimitStates()
    try:
        for _ in range(6):
            a = table.apply("", cfg)
            b = py.apply("", cfg)
            assert (a.match_type, a.exceeded) == (b.match_type, b.exceeded)
        # introspection shows the empty key, not the sentinel byte
        assert table.format_states().startswith(",: interval_start: ")
    finally:
        table.close()
        table.unlink()
