"""Shared mesh-vs-oracle harness: TpuMatcher in mesh mode against the CPU
reference matcher. Used by both tests/unit/test_parallel_mesh.py and the
driver's __graft_entry__.dryrun_multichip so the comparison contract (the
result-key tuple and the Banner effect sequence) lives in exactly one place.
"""

from __future__ import annotations

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.matcher.runner import TpuMatcher
from tests.mock_banner import MockBanner


def result_key(r):
    """The full observable content of one ConsumeLineResult."""
    return (
        r.error, r.old_line, r.exempted,
        tuple(
            (
                rr.rule_name, rr.regex_match, rr.skip_host, rr.seen_ip,
                None if rr.rate_limit_result is None else (
                    int(rr.rate_limit_result.match_type),
                    rr.rate_limit_result.exceeded,
                ),
            )
            for rr in r.rule_results
        ),
    )


def build_matcher(cls, yaml_text, mesh_devices=0, mesh_rp=0,
                  interpret=False, device_windows=False):
    cfg = config_from_yaml_text(yaml_text)
    cfg.matcher_mesh_devices = mesh_devices
    cfg.matcher_mesh_rp = mesh_rp
    if mesh_devices and interpret:
        cfg.matcher_backend = "pallas-interpret"
    cfg.matcher_device_windows = device_windows
    banner = MockBanner()
    m = cls(cfg, banner, StaticDecisionLists(cfg), RegexRateLimitStates())
    return m, banner


def assert_mesh_matches_cpu_oracle(
    yaml_text, lines, now, n_devices, rp, *,
    interpret=False, device_windows=False,
):
    """Consume `lines` through CpuMatcher and a mesh-mode TpuMatcher; assert
    identical ConsumeLineResult streams and Banner side effects. Returns the
    mesh TpuMatcher for further inspection."""
    cpu_m, cpu_b = build_matcher(CpuMatcher, yaml_text)
    tpu_m, tpu_b = build_matcher(
        TpuMatcher, yaml_text, mesh_devices=n_devices, mesh_rp=rp,
        interpret=interpret, device_windows=device_windows,
    )
    assert tpu_m._mesh_matcher is not None, "mesh mode did not engage"
    want = [cpu_m.consume_line(l, now) for l in lines]
    got = tpu_m.consume_lines(lines, now)
    assert [result_key(r) for r in got] == [result_key(r) for r in want], (
        "mesh TpuMatcher diverged from the CPU oracle"
    )
    assert [(b.ip, b.decision, b.domain) for b in tpu_b.bans] == [
        (b.ip, b.decision, b.domain) for b in cpu_b.bans
    ], "Banner side effects diverged"
    mm = tpu_m._mesh_matcher
    if mm.plan is not None:
        # a filterable ruleset must actually go through the fused two-stage
        # path (or its counted overflow fallback) — not silently skip it
        assert mm.fused_batches + mm.fallback_batches > 0, (
            "fused mesh prefilter never ran"
        )
    return tpu_m


def assert_pipelined_mesh_matches_cpu_oracle(
    yaml_text, lines, now, n_devices, rp, *,
    interpret=False, device_windows=False,
):
    """The streaming pipeline scheduler driving a mesh-mode TpuMatcher
    (sharded submit → per-shard merge at collect → ordered window commit
    at drain) against the CPU reference.  Returns the shed-line count
    (asserted 0) so dryruns can print it."""
    import threading

    from banjax_tpu.pipeline import PipelineScheduler

    cpu_m, cpu_b = build_matcher(CpuMatcher, yaml_text)
    want = [cpu_m.consume_line(l, now) for l in lines]

    tpu_m, tpu_b = build_matcher(
        TpuMatcher, yaml_text, mesh_devices=n_devices, mesh_rp=rp,
        interpret=interpret, device_windows=device_windows,
    )
    assert tpu_m._mesh_matcher is not None, "mesh mode did not engage"
    collected = []
    lock = threading.Lock()

    def sink(batch_lines, results):
        with lock:
            collected.append((batch_lines, results))

    sched = PipelineScheduler(
        lambda: tpu_m, on_results=sink, now_fn=lambda: now,
    )
    sched.start()
    step = max(1, len(lines) // 5)
    for i in range(0, len(lines), step):
        sched.submit(lines[i : i + step])
    assert sched.flush(300), "pipelined mesh stream did not drain"
    sched.stop()

    got_lines = [l for ls, _ in collected for l in ls]
    got = [r for _, rs in collected for r in rs]
    assert got_lines == list(lines), "admission order broken"
    assert [result_key(r) for r in got] == [result_key(r) for r in want], (
        "pipelined mesh TpuMatcher diverged from the CPU oracle"
    )
    assert [(b.ip, b.decision, b.domain) for b in tpu_b.bans] == [
        (b.ip, b.decision, b.domain) for b in cpu_b.bans
    ], "Banner side effects diverged"
    # the sharded drain actually merged per-shard pulls (not a silent
    # single-array fallback)
    assert tpu_m._mesh_matcher.last_shard_merge_ms, "per-shard merge never ran"
    snap = sched.snapshot()
    assert snap["PipelineShedLines"] == 0
    return snap["PipelineShedLines"]
