"""Admin-surface auth (ROADMAP PR 1 open item): /healthz, /metrics and
/debug/trace gain constant-time bearer-token auth when the listener
binds non-loopback and `admin_token` is set; the default loopback
listener stays open, reference-style.  Covers the aiohttp layout's
routes end-to-end and the fast layout's natively-served /healthz."""

import asyncio

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi import server as server_mod
from banjax_tpu.httpapi.fastserve import FastPathServer, _ParsedRequest
from banjax_tpu.httpapi.server import admin_auth_ok, is_loopback_host
from banjax_tpu.obs import trace
from banjax_tpu.resilience.health import HealthRegistry
from tests.mock_banner import MockBanner

RULES_YAML = """
regexes_with_rates:
  - decision: nginx_block
    rule: r
    regex: 'GET .*'
    interval: 5
    hits_per_interval: 100
"""

TOKEN = "sekrit-scraper-token"
ADMIN_ROUTES = ("/healthz", "/metrics", "/debug/trace",
                "/decisions/explain?ip=9.9.9.9", "/debug/incidents",
                "/traffic/top", "/debug/failpoints")
N_ADMIN = len(ADMIN_ROUTES)


def _deps(cfg):
    class Holder:
        def get(self):
            return cfg

    health = HealthRegistry()
    health.register("tailer").ok()
    return server_mod.ServerDeps(
        config_holder=Holder(),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=DynamicDecisionLists(start_sweeper=False),
        protected_paths=PasswordProtectedPaths(cfg),
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(),
        health=health,
    )


def test_loopback_host_predicate():
    for host in ("", "127.0.0.1", "127.1.2.3", "::1", "[::1]", "localhost"):
        assert is_loopback_host(host), host
    for host in ("0.0.0.0", "10.0.0.5", "192.168.1.1", "::", "fe80::1"):
        assert not is_loopback_host(host), host


def test_admin_auth_matrix():
    cfg = config_from_yaml_text(RULES_YAML)
    # no token: open everywhere (bind-time warning is the guard)
    assert admin_auth_ok(cfg, "0.0.0.0", "")
    cfg.admin_token = TOKEN
    # loopback stays open by default even with a token set
    assert admin_auth_ok(cfg, "127.0.0.1", "")
    # non-loopback: bearer required, constant-time match
    assert not admin_auth_ok(cfg, "0.0.0.0", "")
    assert not admin_auth_ok(cfg, "0.0.0.0", "Bearer wrong")
    assert not admin_auth_ok(cfg, "0.0.0.0", TOKEN[:-1])
    assert admin_auth_ok(cfg, "0.0.0.0", f"Bearer {TOKEN}")
    # a raw token (no Bearer prefix) is accepted too — curl ergonomics
    assert admin_auth_ok(cfg, "0.0.0.0", TOKEN)


def _drive_app(cfg, listen_host, requests):
    """Run each (path, headers) against a built app; returns statuses."""
    from aiohttp.test_utils import TestClient, TestServer

    deps = _deps(cfg)

    async def go():
        app = server_mod.build_app(deps, listen_host=listen_host)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            out = []
            for path, headers in requests:
                r = await client.get(path, headers=headers)
                out.append(r.status)
            return out
        finally:
            await client.close()

    return asyncio.run(go())


def test_aiohttp_admin_routes_open_on_loopback():
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.admin_token = TOKEN
    statuses = _drive_app(
        cfg, "127.0.0.1", [(p, {}) for p in ADMIN_ROUTES]
    )
    assert statuses == [200] * N_ADMIN


def test_aiohttp_admin_routes_gated_non_loopback():
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.admin_token = TOKEN
    bare = [(p, {}) for p in ADMIN_ROUTES]
    wrong = [(p, {"Authorization": "Bearer nope"}) for p in ADMIN_ROUTES]
    good = [(p, {"Authorization": f"Bearer {TOKEN}"}) for p in ADMIN_ROUTES]
    statuses = _drive_app(cfg, "0.0.0.0", bare + wrong + good)
    assert statuses[:N_ADMIN] == [401] * N_ADMIN
    assert statuses[N_ADMIN:2 * N_ADMIN] == [401] * N_ADMIN
    assert statuses[2 * N_ADMIN:] == [200] * N_ADMIN


def test_aiohttp_non_admin_routes_stay_open_non_loopback():
    """The gate covers ONLY the admin surface: /info and /auth_request
    keep serving without a token (nginx calls them unauthenticated)."""
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.admin_token = TOKEN
    statuses = _drive_app(cfg, "0.0.0.0", [("/info", {})])
    assert statuses == [200]


def test_metrics_route_serves_parseable_exposition():
    from aiohttp.test_utils import TestClient, TestServer

    from banjax_tpu.obs.exposition import parse_text_format

    cfg = config_from_yaml_text(RULES_YAML)
    deps = _deps(cfg)

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/metrics")
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            return await r.text()
        finally:
            await client.close()

    text = asyncio.run(go())
    fams = parse_text_format(text)
    assert "banjax_health_status" in fams
    assert "banjax_expiring_challenges" in fams


def test_debug_trace_route_dumps_and_clears_ring():
    from aiohttp.test_utils import TestClient, TestServer

    tracer = trace.configure(enabled=True, ring_size=64)
    try:
        tid = tracer.new_trace()
        with tracer.span("drain", tid, parent=0):
            pass
        cfg = config_from_yaml_text(RULES_YAML)
        deps = _deps(cfg)

        async def go():
            app = server_mod.build_app(deps)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/debug/trace", params={"clear": "1"})
                assert r.status == 200
                return await r.json()
            finally:
                await client.close()

        payload = asyncio.run(go())
        names = [e["name"] for e in payload["traceEvents"]
                 if e["ph"] == "X"]
        assert "drain" in names
        assert payload["otherData"]["enabled"] is True
        assert tracer.snapshot() == []  # ?clear=1 emptied the ring
    finally:
        trace.configure(enabled=False)


class _FakeProto:
    peer = "10.0.0.9"
    transport = None

    def __init__(self):
        self.sent = b""

    def write(self, data: bytes) -> None:
        self.sent += data


def _fast_request(path, headers=None):
    return _ParsedRequest("GET", path, "", dict(headers or {}), b"",
                          True, b"")


@pytest.mark.parametrize(
    "listen_host,auth,expect",
    [
        ("127.0.0.1", "", b"HTTP/1.1 200"),
        ("0.0.0.0", "", b"HTTP/1.1 401"),
        ("0.0.0.0", "Bearer nope", b"HTTP/1.1 401"),
        ("0.0.0.0", f"Bearer {TOKEN}", b"HTTP/1.1 200"),
    ],
)
def test_fastserve_native_healthz_auth(listen_host, auth, expect):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.admin_token = TOKEN
    deps = _deps(cfg)
    fps = FastPathServer(deps, proxy_sock="/nonexistent",
                         listen_host=listen_host)
    proto = _FakeProto()
    headers = {"authorization": auth} if auth else {}
    req = _fast_request("/healthz", headers)
    assert fps.is_hot(req)  # healthz is served natively
    fps.handle_hot(proto, req)
    assert proto.sent.startswith(expect), proto.sent[:80]
    if expect.endswith(b"401"):
        assert b"WWW-Authenticate: Bearer" in proto.sent


def test_new_admin_routes_are_worker_proxied():
    """Workers own no ledger/recorder: the new observability routes must
    be in COLD_ROUTES (reverse-proxied to the primary) and registered by
    install_proxy_routes on a worker app — same path as /metrics."""
    from aiohttp import web

    from banjax_tpu.httpapi.workers import COLD_ROUTES, install_proxy_routes

    for route in ("/decisions/explain", "/debug/incidents",
                  "/metrics", "/debug/trace", "/healthz", "/traffic/top",
                  "/debug/failpoints"):
        assert route in COLD_ROUTES, route

    app = web.Application()
    install_proxy_routes(app, "/nonexistent-primary.sock")
    registered = {r.resource.canonical for r in app.router.routes()
                  if r.resource is not None}
    assert "/decisions/explain" in registered
    assert "/debug/incidents" in registered
    assert "/traffic/top" in registered
    assert "/debug/failpoints" in registered


def test_worker_layout_proxies_new_routes_behind_auth():
    """The full worker layout end-to-end: a build_app(worker_proxy_sock=…)
    application proxies /decisions/explain and /debug/incidents to the
    primary's aiohttp app over a unix socket, and the primary's admin
    gate (non-loopback + token) answers through the proxy."""
    import tempfile

    from aiohttp import web

    cfg = config_from_yaml_text(RULES_YAML)
    cfg.admin_token = TOKEN
    deps = _deps(cfg)

    async def go():
        with tempfile.TemporaryDirectory() as td:
            sock = f"{td}/primary.sock"
            # primary: the real app, gated as a non-loopback listener
            primary = server_mod.build_app(deps, listen_host="0.0.0.0")
            prunner = web.AppRunner(primary)
            await prunner.setup()
            await web.UnixSite(prunner, sock).start()
            # worker: proxy-only app
            worker = server_mod.build_app(deps, worker_proxy_sock=sock,
                                          listen_host="0.0.0.0")
            from aiohttp.test_utils import TestClient, TestServer

            client = TestClient(TestServer(worker))
            await client.start_server()
            try:
                out = []
                for path in ("/decisions/explain?ip=9.9.9.9",
                             "/debug/incidents", "/traffic/top"):
                    r = await client.get(path)
                    out.append(r.status)
                    r = await client.get(
                        path, headers={"Authorization": f"Bearer {TOKEN}"}
                    )
                    out.append((r.status, await r.json()))
                return out
            finally:
                await client.close()
                await prunner.cleanup()

    out = asyncio.run(go())
    assert out[0] == 401                       # explain: gated via proxy
    assert out[1][0] == 200
    assert out[1][1]["ip"] == "9.9.9.9"
    assert out[2] == 401                       # incidents: gated via proxy
    assert out[3][0] == 200
    assert out[3][1]["incidents"] == []
    assert out[4] == 401                       # traffic: gated via proxy
    assert out[5][0] == 200
    # no matcher wired into these deps: the route degrades honestly
    assert out[5][1]["enabled"] is False


def test_decisions_explain_route_payload():
    from banjax_tpu.decisions.model import Decision
    from banjax_tpu.obs import provenance

    provenance.configure(enabled=True, ring_size=64)
    try:
        cfg = config_from_yaml_text(RULES_YAML)
        deps = _deps(cfg)
        provenance.record(provenance.SOURCE_KAFKA, "6.6.6.6",
                          Decision.NGINX_BLOCK, rule="block_ip")
        deps.dynamic_lists.update("6.6.6.6", 9999999999.0,
                                  Decision.NGINX_BLOCK, True, "h.com")
        from aiohttp.test_utils import TestClient, TestServer

        async def go():
            app = server_mod.build_app(deps)
            client = TestClient(TestServer(app))
            await client.start_server()
            try:
                r = await client.get("/decisions/explain",
                                     params={"ip": "6.6.6.6"})
                missing = await client.get("/decisions/explain")
                return r.status, await r.json(), missing.status
            finally:
                await client.close()

        status, payload, missing_status = asyncio.run(go())
        assert status == 200
        assert missing_status == 400  # ip param required
        assert payload["ledger_enabled"] is True
        assert payload["records"][0]["source"] == "kafka"
        assert payload["records"][0]["rule"] == "block_ip"
        assert payload["active_decision"]["decision"] == "NginxBlock"
        assert payload["active_decision"]["from_baskerville"] is True
    finally:
        provenance.configure(enabled=True)


def test_traffic_top_route_payload():
    """GET /traffic/top with a real device-windows matcher wired in:
    top-K heavy hitters with estimated counts, the HLL cardinality,
    rule pressure, and the ?k= bound (ISSUE 8 acceptance surface)."""
    import time

    from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
    from banjax_tpu.matcher.runner import TpuMatcher

    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    matcher = TpuMatcher(
        cfg, MockBanner(), StaticDecisionLists(cfg), RegexRateLimitStates()
    )
    now = time.time()
    lines = [
        f"{now:.6f} 5.5.5.5 GET h.com GET /flood{i} HTTP/1.1 ua -"
        if i % 2 else
        f"{now:.6f} 10.1.{i % 5}.{i % 30} GET h.com GET /ok HTTP/1.1 ua -"
        for i in range(200)
    ]
    matcher.consume_lines(lines, now)

    deps = _deps(cfg)
    deps.matcher_getter = lambda: matcher

    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            full = await client.get("/traffic/top",
                                    params={"refresh": "1"})
            k1 = await client.get("/traffic/top", params={"k": "1"})
            bad = await client.get("/traffic/top", params={"k": "zzz"})
            return (full.status, await full.json(),
                    k1.status, await k1.json(), bad.status)
        finally:
            await client.close()

    status, payload, k_status, k_payload, bad_status = asyncio.run(go())
    assert status == 200 and k_status == 200
    assert bad_status == 400
    assert payload["enabled"] is True
    assert payload["lines_total"] == 200
    assert payload["top"][0]["ip"] == "5.5.5.5"
    assert payload["top"][0]["est_count"] >= 100
    assert payload["distinct_ips_estimate"] > 0
    assert payload["rule_pressure"][0]["rule"] == "r"
    assert payload["sketch"]["pull_age_seconds"] is not None
    assert len(k_payload["top"]) == 1
    assert k_payload["k"] == 1


def test_traffic_top_without_sketch_reports_disabled():
    cfg = config_from_yaml_text(RULES_YAML)
    deps = _deps(cfg)  # no matcher_getter wired at all

    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/traffic/top")
            return r.status, await r.json()
        finally:
            await client.close()

    status, payload = asyncio.run(go())
    assert status == 200
    assert payload["enabled"] is False and payload["top"] == []


def test_debug_failpoints_route_lists_arms_and_disarms():
    """GET lists sites + armed points; POST arms (count/probability),
    spec-arms, and disarms — and the armed point actually fires."""
    from banjax_tpu.resilience import failpoints

    failpoints.disarm()
    cfg = config_from_yaml_text(RULES_YAML)
    deps = _deps(cfg)

    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            out = {}
            r = await client.get("/debug/failpoints")
            out["list"] = (r.status, await r.json())
            r = await client.post("/debug/failpoints", json={
                "arm": [{"name": "pipeline.submit", "count": 2,
                         "probability": 1.0}],
                "spec": "kafka.read=error:1",
            })
            out["arm"] = (r.status, await r.json())
            r = await client.post("/debug/failpoints",
                                  json={"disarm": ["kafka.read"]})
            out["disarm"] = (r.status, await r.json())
            r = await client.post("/debug/failpoints",
                                  json={"arm": [{"mode": "error"}]})
            out["bad"] = r.status
            r = await client.post("/debug/failpoints",
                                  json={"disarm_all": True})
            out["disarm_all"] = (r.status, await r.json())
            return out
        finally:
            await client.close()

    try:
        out = asyncio.run(go())
    finally:
        failpoints.disarm()
    status, payload = out["list"]
    assert status == 200
    assert "pipeline.submit" in payload["sites"]
    assert payload["armed"] == []
    status, payload = out["arm"]
    assert status == 200
    armed = {fp["name"]: fp for fp in payload["armed"]}
    assert armed["pipeline.submit"]["count"] == 2
    assert armed["kafka.read"]["count"] == 1
    status, payload = out["disarm"]
    assert [fp["name"] for fp in payload["armed"]] == ["pipeline.submit"]
    assert out["bad"] == 400  # arm entry without a name
    assert out["disarm_all"][1]["armed"] == []


def test_debug_failpoints_disabled_by_config():
    from banjax_tpu.resilience import failpoints

    cfg = config_from_yaml_text(RULES_YAML)
    cfg.failpoints_admin_enabled = False
    deps = _deps(cfg)

    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r1 = await client.get("/debug/failpoints")
            r2 = await client.post(
                "/debug/failpoints",
                json={"arm": [{"name": "pipeline.submit"}]},
            )
            return r1.status, r2.status
        finally:
            await client.close()

    assert asyncio.run(go()) == (403, 403)
    assert not failpoints.is_armed("pipeline.submit")


def test_debug_failpoints_worker_proxied_post():
    """POST through the worker proxy reaches the primary's module-level
    failpoint table (the soak's no-restart operator path)."""
    import tempfile

    from aiohttp import web

    from banjax_tpu.resilience import failpoints

    failpoints.disarm()
    cfg = config_from_yaml_text(RULES_YAML)
    deps = _deps(cfg)

    async def go():
        with tempfile.TemporaryDirectory() as td:
            sock = f"{td}/primary.sock"
            primary = server_mod.build_app(deps)
            prunner = web.AppRunner(primary)
            await prunner.setup()
            await web.UnixSite(prunner, sock).start()
            worker = server_mod.build_app(deps, worker_proxy_sock=sock)
            from aiohttp.test_utils import TestClient, TestServer

            client = TestClient(TestServer(worker))
            await client.start_server()
            try:
                r = await client.post("/debug/failpoints", json={
                    "arm": [{"name": "decision_chain", "count": 1}],
                })
                payload = await r.json()
                return r.status, payload
            finally:
                await client.close()
                await prunner.cleanup()

    try:
        status, payload = asyncio.run(go())
        assert status == 200
        assert [fp["name"] for fp in payload["armed"]] == ["decision_chain"]
        assert failpoints.is_armed("decision_chain")
    finally:
        failpoints.disarm()
