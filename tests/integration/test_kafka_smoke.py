"""Real-broker Kafka smoke (VERDICT r2 item 8).

The wire-protocol client (banjax_tpu/ingest/kafka_wire.py) is unit-tested
against tests/fake_kafka_broker.py — but a same-author fake can encode the
same misreading of the Kafka spec on both sides. This module drives the
SAME code paths against a genuine broker. Gated on BANJAX_KAFKA_BROKER
because the test image has no broker; one-command run (documented in
deploy/README.md):

    docker compose -f deploy/docker-compose.yml --profile kafka up -d kafka
    BANJAX_KAFKA_BROKER=127.0.0.1:9094 \
        python -m pytest tests/integration/test_kafka_smoke.py -q

Covers, end to end through a real broker: produce (the writer's transport
send), consume-from-latest (the reader's pinned-partition fetch,
kafka.go:112-129 semantics), and a challenge_ip command landing in
DynamicDecisionLists exactly as the Baskerville path does
(/root/reference/internal/kafka.go:194-253).
"""

import json
import os
import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.ingest.kafka_io import KafkaReader
from banjax_tpu.ingest.kafka_wire import WireKafkaTransport

BROKER = os.environ.get("BANJAX_KAFKA_BROKER")

pytestmark = pytest.mark.skipif(
    not BROKER, reason="set BANJAX_KAFKA_BROKER=host:port (see deploy compose)"
)


def _config(topic: str):
    return config_from_yaml_text(
        f"""
kafka_brokers:
  - "{BROKER}"
kafka_command_topic: {topic}
kafka_report_topic: {topic}-reports
kafka_max_wait_ms: 250
expiring_decision_ttl_seconds: 30
"""
    )


class _Holder:
    def __init__(self, config):
        self._config = config

    def get(self):
        return self._config


def test_produce_consume_roundtrip():
    topic = f"banjax-smoke-{int(time.time())}"
    cfg = _config(topic)
    tx = WireKafkaTransport()
    try:
        tx.send(cfg, topic, b'{"warm": true}')  # creates the topic
        it = tx.read_messages(cfg, topic, 0)  # LastOffset: starts at tail

        got = {}

        def consume():
            got["msg"] = next(it)

        t = threading.Thread(target=consume, daemon=True)
        t.start()
        # a tail-positioned consumer only sees messages produced AFTER it
        # attaches; its attach time is unobservable, so keep producing
        # fresh sequence-tagged messages until one comes through (no fixed
        # sleeps — robust against a cold broker)
        sent = set()
        deadline = time.time() + 30
        seq = 0
        while time.time() < deadline and "msg" not in got:
            payload = json.dumps({"seq": seq}).encode()
            sent.add(payload)
            tx.send(cfg, topic, payload)
            seq += 1
            t.join(timeout=0.5)
        assert got.get("msg") in sent
    finally:
        tx.close()


def test_challenge_ip_command_end_to_end():
    topic = f"banjax-smoke-cmd-{int(time.time())}"
    cfg = _config(topic)
    producer = WireKafkaTransport()
    lists = DynamicDecisionLists(start_sweeper=False)
    reader = KafkaReader(_Holder(cfg), lists, transport=WireKafkaTransport())
    try:
        producer.send(cfg, topic, b'{"warm": true}')
        reader.start()
        # the reader attaches at the tail at an unobservable moment: resend
        # the (idempotent) command until it lands instead of fixed sleeps
        cmd = json.dumps(
            {"Name": "challenge_ip", "Value": "203.0.113.9",
             "host": "example.com"}
        ).encode()
        deadline = time.time() + 30
        entry = None
        while time.time() < deadline:
            producer.send(cfg, topic, cmd)
            time.sleep(1.0)
            entry, ok = lists.check("", "203.0.113.9")
            if ok and entry is not None:
                break
        assert entry is not None, "challenge_ip never landed"
        assert entry.decision is Decision.CHALLENGE
    finally:
        reader.stop()
        producer.close()
