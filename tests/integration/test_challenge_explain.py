"""Challenge-plane acceptance: a ban born from a FAILED VERIFICATION
(the full issuance -> verify -> failure path, not a bare
too_many_failed_challenges call) shows up in /decisions/explain with the
challenge_failure source, the sha_inv rule, and a trace id that joins
the challenge.sha_inv verification span in /debug/trace — one id from
the cookie check to the ban record."""

import asyncio
import time

import pytest

from banjax_tpu.challenge.failures import make_failed_challenge_states
from banjax_tpu.challenge.verifier import DeviceVerifier
from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import FailAction
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi import server as server_mod
from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    RequestInfo,
    send_or_validate_sha_challenge,
)
from banjax_tpu.obs import provenance, trace
from tests.mock_banner import MockBanner

CONFIG_YAML = r"""
config_version: challenge-explain-test
regexes_with_rates: []
iptables_ban_seconds: 10
kafka_brokers: [localhost:9092]
server_log_file: /tmp/banjax-challenge-explain-test.log
expiring_decision_ttl_seconds: 300
too_many_failed_challenges_interval_seconds: 60
too_many_failed_challenges_threshold: 2
sha_inv_cookie_ttl_seconds: 300
sha_inv_expected_zero_bits: 8
hmac_secret: secret
session_cookie_hmac_secret: session_secret
disable_kafka: true
challenge_failure_state_max: 1024
challenge_device_verify: true
"""

IP = "44.44.44.44"


@pytest.fixture(autouse=True)
def _fresh_obs():
    provenance.configure(enabled=True, ring_size=512)
    trace.configure(enabled=True, ring_size=4096)
    yield
    provenance.configure(enabled=True)
    trace.configure(enabled=False)


def test_failed_verification_ban_joins_the_challenge_span():
    config = config_from_yaml_text(CONFIG_YAML)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    state = ChainState(
        config=config,
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        failed_challenge_states=make_failed_challenge_states(config),
        banner=MockBanner(dynamic),
        challenge_verifier=DeviceVerifier(batch_max=8, interpret=True),
    )
    req = RequestInfo(
        client_ip=IP, requested_host="example.com", requested_path="/",
        client_user_agent="probe", method="GET",
        cookies={"deflect_challenge3": "garbage-cookie"},
    )
    exceeded = False
    for _ in range(3):  # threshold 2 → the 3rd failure exceeds
        _, _, rate = send_or_validate_sha_challenge(
            state, req, FailAction.BLOCK
        )
        exceeded = exceeded or rate.exceeded
    assert exceeded

    recs = [r for r in provenance.get_ledger().explain(IP)
            if r["source"] == "challenge_failure"]
    assert recs, "challenge-failure ban did not land in the ledger"
    rec = recs[-1]
    assert rec["rule"] == "failed challenge sha_inv"
    assert rec["hits"] == 2
    assert rec["decision"] == "IptablesBlock"
    assert rec["trace_id"] != 0, "ban not attributed to the verify span"

    spans = trace.get_tracer().snapshot()
    joined = [s for s in spans if s["trace_id"] == rec["trace_id"]]
    assert any(s["name"] == "challenge.sha_inv" for s in joined), (
        "the ban's trace id does not join a challenge.sha_inv span"
    )

    # the same record served over HTTP by /decisions/explain
    deps = server_mod.ServerDeps(
        config_holder=type("H", (), {"get": lambda self: config})(),
        static_lists=state.static_lists,
        dynamic_lists=dynamic,
        protected_paths=state.protected_paths,
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=state.failed_challenge_states,
        banner=state.banner,
    )
    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/decisions/explain", params={"ip": IP})
            assert r.status == 200
            return await r.json()
        finally:
            await client.close()

    payload = asyncio.run(go())
    http_recs = [r for r in payload["records"]
                 if r["source"] == "challenge_failure"]
    assert http_recs and http_recs[-1]["trace_id"] == rec["trace_id"]
