"""ISSUE 6 acceptance: GET /decisions/explain returns source/rule/
window-count/trace-id provenance for bans produced by all four decision
sources (static+UA lists, regex rate limiter, Kafka commands, challenge
failures — PAPER.md §0), and a forced SLO breach under failpoints
produces a loadable incident bundle (valid Perfetto JSON + parseable
metrics snapshot) listed by /debug/incidents."""

import asyncio
import json
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.model import Decision
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.httpapi import server as server_mod
from banjax_tpu.httpapi.decision_chain import (
    ChainState,
    RequestInfo,
    decision_for_nginx,
    too_many_failed_challenges,
)
from banjax_tpu.ingest.kafka_io import handle_command
from banjax_tpu.matcher.cpu_ref import CpuMatcher
from banjax_tpu.obs import flightrec, provenance, trace
from banjax_tpu.obs.exposition import parse_text_format, render_prometheus
from banjax_tpu.obs.flightrec import FlightRecorder
from banjax_tpu.obs.slo import SloEngine
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.resilience import failpoints
from banjax_tpu.matcher.runner import TpuMatcher
from tests.mock_banner import MockBanner

CONFIG_YAML = r"""
config_version: provenance-test
regexes_with_rates:
  - decision: nginx_block
    rule: "rate_limit_rule"
    regex: 'GET .*'
    interval: 60
    hits_per_interval: 3
global_decision_lists:
  nginx_block:
    - 70.80.90.100
global_user_agent_decision_lists:
  nginx_block:
    - "BadBot"
iptables_ban_seconds: 10
kafka_brokers: [localhost:9092]
server_log_file: /tmp/banjax-prov-test.log
expiring_decision_ttl_seconds: 300
too_many_failed_challenges_interval_seconds: 60
too_many_failed_challenges_threshold: 2
hmac_secret: secret
session_cookie_hmac_secret: session_secret
disable_kafka: true
"""


@pytest.fixture(autouse=True)
def _fresh_obs():
    provenance.configure(enabled=True, ring_size=512)
    yield
    provenance.configure(enabled=True)
    flightrec.install(None)
    trace.configure(enabled=False)
    failpoints.disarm()


def _chain_state(config, dynamic):
    return ChainState(
        config=config,
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(dynamic),
    )


def _req(ip, ua="mozilla", host="example.com"):
    return RequestInfo(client_ip=ip, requested_host=host,
                       requested_path="/", client_user_agent=ua,
                       method="GET", cookies={})


def _explain(deps, ip):
    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get("/decisions/explain", params={"ip": ip})
            assert r.status == 200
            return await r.json()
        finally:
            await client.close()

    return asyncio.run(go())


def test_explain_covers_all_four_decision_sources():
    config = config_from_yaml_text(CONFIG_YAML)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    state = _chain_state(config, dynamic)
    now = time.time()

    # source 1: static IP list hit (global nginx_block)
    resp, _ = decision_for_nginx(state, _req("70.80.90.100"))
    assert resp.status == 403
    # source 1b: UA list hit
    resp, _ = decision_for_nginx(state, _req("71.71.71.71", ua="BadBot"))
    assert resp.status == 403

    # source 2: regex rate limiter firing a ban (4th hit > 3/interval)
    matcher = CpuMatcher(config, MockBanner(dynamic), state.static_lists,
                         RegexRateLimitStates())
    for _ in range(4):
        matcher.consume_line(
            f"{now:f} 9.9.9.9 GET example.com GET /x HTTP/1.1 ua", now
        )

    # source 3: a Kafka block_ip command
    handle_command(config, {"Name": "block_ip", "Value": "5.6.7.8",
                            "host": "example.com"}, dynamic)

    # source 4: too many failed challenges (threshold 2 → 3rd exceeds)
    for _ in range(3):
        too_many_failed_challenges(state, _req("3.3.3.3"), "password")

    deps = server_mod.ServerDeps(
        config_holder=type("H", (), {"get": lambda self: config})(),
        static_lists=state.static_lists,
        dynamic_lists=dynamic,
        protected_paths=state.protected_paths,
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=state.failed_challenge_states,
        banner=state.banner,
    )

    static_recs = _explain(deps, "70.80.90.100")["records"]
    assert any(r["source"] == "static_list" and r["rule"] == "GlobalBlock"
               and r["decision"] == "NginxBlock" for r in static_recs)

    ua_recs = _explain(deps, "71.71.71.71")["records"]
    assert any(r["source"] == "ua_list" and r["rule"] == "GlobalUABlock"
               for r in ua_recs)

    rate_payload = _explain(deps, "9.9.9.9")
    rate = [r for r in rate_payload["records"]
            if r["source"] == "rate_limit"]
    assert rate and rate[0]["rule"] == "rate_limit_rule"
    assert rate[0]["hits"] == 4  # window count at fire time (3 + 1)
    assert rate_payload["active_decision"]["decision"] == "NginxBlock"

    kafka_recs = _explain(deps, "5.6.7.8")["records"]
    assert any(r["source"] == "kafka" and r["rule"] == "block_ip"
               and r["decision"] == "NginxBlock" for r in kafka_recs)

    fc_recs = _explain(deps, "3.3.3.3")["records"]
    assert any(r["source"] == "challenge_failure"
               and r["rule"] == "failed challenge password"
               and r["hits"] == 2
               and r["decision"] == "IptablesBlock" for r in fc_recs)


def test_rate_limit_provenance_carries_admitting_batch_trace_id():
    """A ban fired on a traced pipeline drain thread is attributed to the
    admitting batch's trace id — the explain record joins straight into
    the /debug/trace Perfetto dump."""
    trace.configure(enabled=True, ring_size=8192)
    config = config_from_yaml_text(CONFIG_YAML)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    matcher = TpuMatcher(config, MockBanner(dynamic),
                         StaticDecisionLists(config),
                         RegexRateLimitStates())
    now = time.time()
    sched = PipelineScheduler(lambda: matcher, now_fn=lambda: now)
    sched.start()
    sched.submit([
        f"{now:f} 6.6.6.6 GET example.com GET /x HTTP/1.1 ua"
        for _ in range(8)
    ])
    assert sched.flush(120)
    sched.stop()
    matcher.close()

    recs = [r for r in provenance.get_ledger().explain("6.6.6.6")
            if r["source"] == "rate_limit"]
    assert recs, "rate-limit ban did not land in the ledger"
    tids = {r["trace_id"] for r in recs}
    assert tids and 0 not in tids, "ban not attributed to a traced batch"
    span_tids = {s["trace_id"] for s in trace.get_tracer().snapshot()}
    assert tids <= span_tids, "ledger trace ids missing from the span ring"


def test_forced_slo_breach_produces_loadable_incident_bundle(tmp_path):
    """Failpoint pipeline.drain=error forces drain losses → the shed SLO
    breaches → the flight recorder captures a bundle that is listed by
    /debug/incidents and loads: trace.json is valid Perfetto JSON,
    metrics.prom parses under the strict exposition parser."""
    trace.configure(enabled=True, ring_size=4096)
    config = config_from_yaml_text(CONFIG_YAML)
    dynamic = DynamicDecisionLists(start_sweeper=False)
    states = RegexRateLimitStates()
    fc_states = FailedChallengeRateLimitStates()
    matcher = TpuMatcher(config, MockBanner(dynamic),
                         StaticDecisionLists(config), states)
    now = time.time()
    sched = PipelineScheduler(lambda: matcher, now_fn=lambda: now)

    engine = SloEngine(
        matcher_getter=lambda: matcher,
        pipeline_getter=lambda: sched,
        batch_budget_s_fn=lambda: 0.25,
        shed_ratio_max=0.001,
    )
    recorder = FlightRecorder(
        str(tmp_path / "incidents"), min_interval_s=0.0,
        metrics_text_fn=lambda: render_prometheus(
            dynamic, states, fc_states, matcher=matcher, pipeline=sched,
            slo=engine, flightrec=flightrec.installed(),
        ),
        config_hash_fn=lambda: "testhash",
        slo_getter=lambda: engine,
    )
    flightrec.install(recorder)
    breaches = []

    def on_breach(name, burn):
        breaches.append(name)
        flightrec.notify(f"slo-{name}", f"burn {burn}")

    engine._on_breach = on_breach

    engine.sample()
    sched.start()
    failpoints.arm_from_spec("pipeline.drain=error:999")
    try:
        sched.submit([
            f"{now:f} 10.0.0.{i % 256} GET example.com GET /x HTTP/1.1 ua"
            for i in range(512)
        ])
        assert sched.flush(120)
    finally:
        failpoints.disarm()
    newly = engine.sample()
    sched.stop()
    matcher.close()

    assert "shed_ratio" in newly and breaches == ["shed_ratio"]
    # PR 9: the drain failure itself now captures evidence (reason
    # "drain-error") before the SLO breach bundle lands — the breach
    # bundle is no longer alone
    assert recorder.incident_count >= 2

    # the SLO bundle loads: Perfetto JSON + strictly-parseable metrics
    incidents = recorder.list_incidents()
    by_reason = {}
    for ent in incidents:
        by_reason.setdefault(ent["reason"], ent)
    assert "drain-error" in by_reason
    slo_bundle = by_reason["slo-shed_ratio"]
    name = slo_bundle["name"]
    trace_doc = json.loads(recorder.read_file(name, "trace.json"))
    assert {e["ph"] for e in trace_doc["traceEvents"]} >= {"X", "M"}
    fams = parse_text_format(
        recorder.read_file(name, "metrics.prom").decode()
    )
    assert "banjax_slo_burn_rate" in fams
    assert "banjax_slo_breached" in fams
    assert "banjax_pipeline_drain_error_lines_total" in fams
    meta = json.loads(recorder.read_file(name, "meta.json"))
    assert meta["config_hash"] == "testhash"
    assert meta["slo"]["breached"]["shed_ratio"] is True

    # ... and /debug/incidents serves it
    deps = server_mod.ServerDeps(
        config_holder=type("H", (), {"get": lambda self: config})(),
        static_lists=StaticDecisionLists(config),
        dynamic_lists=dynamic,
        protected_paths=PasswordProtectedPaths(config),
        regex_states=states,
        failed_challenge_states=fc_states,
        banner=MockBanner(dynamic),
        flightrec_getter=lambda: recorder,
    )
    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps)
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            listing = await (await client.get("/debug/incidents")).json()
            manifest = await (await client.get(
                "/debug/incidents", params={"name": name}
            )).json()
            raw = await client.get(
                "/debug/incidents", params={"name": name,
                                            "file": "trace.json"}
            )
            missing = await client.get(
                "/debug/incidents", params={"name": name,
                                            "file": "../secret"}
            )
            return listing, manifest, raw.status, await raw.json(), \
                missing.status
        finally:
            await client.close()

    listing, manifest, raw_status, raw_doc, missing_status = asyncio.run(go())
    assert listing["enabled"] is True
    assert name in {e["name"] for e in listing["incidents"]}
    assert manifest["reason"] == "slo-shed_ratio"
    assert raw_status == 200 and "traceEvents" in raw_doc
    assert missing_status == 404
