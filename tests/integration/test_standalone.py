"""In-process end-to-end tests against the real server in standalone mode.

Model: the reference's integration tier (banjax_integration_test.go +
banjax_base_test.go) — run the real supervisor with -standalone-testing in a
temp dir, drive real HTTP against 127.0.0.1:8081, including the async
regex-banner path (requests write the fake nginx log, the tailer picks lines
up, the matcher bans) and SIGHUP-equivalent hot reload under load.
"""

import base64
import hashlib
import os
import shutil
import time
from pathlib import Path

import pytest
import requests

from banjax_tpu.cli import BanjaxApp
from banjax_tpu.utils import go_query_unescape
from banjax_tpu.crypto.challenge import parse_cookie, solve_challenge_for_testing

FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"
BASE = "http://localhost:8081"


@pytest.fixture()
def app_factory(tmp_path, monkeypatch):
    """Copies a fixture config into a temp cwd and runs the real app there
    (banjax_base_test.go:32-81 setUp)."""
    apps = []
    monkeypatch.chdir(tmp_path)

    def start(fixture_name: str) -> BanjaxApp:
        config_path = tmp_path / "banjax-config.yaml"
        shutil.copy(FIXTURES / fixture_name, config_path)
        app = BanjaxApp(str(config_path), standalone_testing=True, debug=False)
        app.start_background()
        apps.append(app)
        return app

    yield start
    for app in apps:
        app.stop_background()


def auth(path="/", ip=None, cookies=None, host=None, method="GET", ua=None):
    headers = {}
    if ip:
        headers["X-Client-IP"] = ip
    if ua:
        headers["X-Client-User-Agent"] = ua
    return requests.request(
        method, f"{BASE}/auth_request", params={"path": path},
        headers=headers, cookies=cookies or {}, timeout=5,
    )


def test_basic_routes_and_decisions(app_factory):
    app = app_factory("banjax-config-test.yaml")

    # /info
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.status_code == 200
    assert r.json()["config_version"] == "2022-01-02_00:00:00"

    # default allow
    r = auth("/")
    assert r.status_code == 200
    assert r.headers["X-Accel-Redirect"] == "@access_granted"
    assert "deflect_session" in r.cookies

    # global challenge IP gets the PoW page
    r = auth("/", ip="8.8.8.8")
    assert r.status_code == 429
    assert "deflect_challenge3" in r.cookies
    assert b"new_solver(10)" in r.content

    # CIDR challenge
    r = auth("/", ip="192.168.1.77")
    assert r.status_code == 429

    # global block
    r = auth("/", ip="70.80.90.100")
    assert r.status_code == 403
    assert r.headers["X-Accel-Redirect"] == "@access_denied"

    # solve the challenge like the page JS would and get through
    r = auth("/", ip="8.8.8.8")
    unsolved = go_query_unescape(r.cookies["deflect_challenge3"])
    solved = solve_challenge_for_testing(unsolved, 10)
    r = auth("/", ip="8.8.8.8", cookies={"deflect_challenge3": solved})
    assert r.status_code == 200
    assert r.headers["X-Banjax-Decision"] == "ShaChallengePassed"

    # introspection endpoints
    r = requests.get(f"{BASE}/decision_lists", timeout=5)
    assert r.status_code == 200 and "per_site" in r.text
    r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
    assert r.status_code == 200
    r = requests.get(f"{BASE}/is_banned", params={"ip": "1.2.3.4"}, timeout=5)
    assert r.status_code == 200 and r.json()["expiringDecision"] is None
    r = requests.get(f"{BASE}/is_banned", timeout=5)
    assert r.status_code == 400
    r = requests.get(f"{BASE}/banned", params={"domain": "x.com"}, timeout=5)
    assert r.status_code == 200 and r.json()["entries"] == []
    r = requests.get(f"{BASE}/ipset/list", timeout=5)
    assert r.status_code == 200
    r = requests.post(f"{BASE}/unban", data={"ip": "9.9.9.9"}, timeout=5)
    assert r.status_code == 400  # not banned


def test_password_protected_path_flow(app_factory):
    app = app_factory("banjax-config-test.yaml")

    # protected path serves the password page with per-site ttl applied
    r = auth("wp-admin/x")
    assert r.status_code == 401
    assert "deflect_password3" in r.cookies
    assert b"max-age=3600" in r.content  # per-site ttl for localhost:8081

    # exception path passes
    r = auth("wp-admin/admin-ajax.php")
    assert r.status_code == 200

    # build the solved password cookie like the page JS would
    unsolved = go_query_unescape(auth("wp-admin/x").cookies["deflect_password3"])
    hmac_b, _, expiry = parse_cookie(unsolved)
    solution = hashlib.sha256(hmac_b + hashlib.sha256(b"password").digest()).digest()
    solved = base64.standard_b64encode(hmac_b + solution + expiry).decode()
    r = auth("wp-admin/x", cookies={"deflect_password3": solved})
    assert r.status_code == 200

    # wrong password still 401
    bad_solution = hashlib.sha256(hmac_b + hashlib.sha256(b"wrong").digest()).digest()
    bad = base64.standard_b64encode(hmac_b + bad_solution + expiry).decode()
    r = auth("wp-admin/x", cookies={"deflect_password3": bad})
    assert r.status_code == 401


def test_failed_challenge_lockout(app_factory):
    """401 x threshold, then 403 (banjax_integration_test.go:232-250)."""
    app = app_factory("banjax-config-test.yaml")
    ip = "13.13.13.13"
    statuses = []
    for _ in range(7):
        r = auth("wp-admin/x", ip=ip, cookies={"deflect_password3": "garbage"})
        statuses.append(r.status_code)
    assert statuses == [401] * 6 + [403]
    # the ban landed in the expiring list
    r = requests.get(f"{BASE}/is_banned", params={"ip": ip}, timeout=5)
    body = r.json()
    assert body["expiringDecision"] is not None
    # standalone testing: no real ipset call, but the decision is IptablesBlock
    assert body["expiringDecision"]["Decision"] == "IptablesBlock"

    # unban of an IptablesBlock checks the ipset; standalone has no ipset
    # entry, so the reference's "ip is not banned" arm fires (400)
    r = requests.post(f"{BASE}/unban", data={"ip": ip}, timeout=5)
    assert r.status_code == 400 and r.json()["unban"] is False

    # a NginxBlock entry unbans straight from the expiring list
    import time as _time
    from banjax_tpu.decisions.model import Decision
    app.dynamic_lists.update("14.14.14.14", _time.time() + 60,
                             Decision.NGINX_BLOCK, False, "localhost:8081")
    r = requests.post(f"{BASE}/unban", data={"ip": "14.14.14.14"}, timeout=5)
    assert r.status_code == 200 and r.json()["unban"] is True
    r = requests.get(f"{BASE}/is_banned", params={"ip": "14.14.14.14"}, timeout=5)
    assert r.json()["expiringDecision"] is None


def test_regex_banner_bans_after_delay(app_factory):
    """The async tailer path (banjax_integration_test.go:293-385): a request
    whose path matches an instant-ban rule gets banned for the NEXT request."""
    app = app_factory("banjax-config-test-regex-banner.yaml")

    ip = "44.44.44.44"
    r = auth("/challengeme", ip=ip)
    assert r.status_code == 200  # first request passes; the log line is async

    deadline = time.time() + 5
    challenged = False
    while time.time() < deadline:
        r = auth("/", ip=ip)
        if r.status_code == 429:
            challenged = True
            break
        time.sleep(0.1)
    assert challenged, "tailer should have inserted the challenge decision"

    # allowlisted IP is exempt from regex rules
    r = auth("/challengeme", ip="12.12.12.12")
    assert r.status_code == 200
    time.sleep(1.0)
    r = auth("/", ip="12.12.12.12")
    assert r.status_code == 200

    # hosts_to_skip: the challenge-all rule skips localhost:8081, so a
    # plain / request does not get challenged
    r = auth("/", ip="55.55.55.55")
    time.sleep(1.0)
    r = auth("/", ip="55.55.55.55")
    assert r.status_code == 200

    # ban log line was written
    ban_log = Path("banning-log-file.txt").read_text()
    assert '"trigger":"instant challenge"' in ban_log
    assert f'"client_ip":"{ip}"' in ban_log


def test_hot_reload_under_load(app_factory, tmp_path):
    """SIGHUP semantics (banjax_base_test.go:218-242): swap the config file,
    reload, and verify behavior + /info version change with requests in flight."""
    import threading

    app = app_factory("banjax-config-test.yaml")
    assert auth("wp-admin/x").status_code == 401  # wp-admin protected

    stop = threading.Event()
    errors = []

    def hammer():
        while not stop.is_set():
            try:
                auth("/", ip="8.8.8.8")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        shutil.copy(FIXTURES / "banjax-config-test-reload.yaml",
                    tmp_path / "banjax-config.yaml")
        app.reload()  # the SIGHUP handler body
    finally:
        stop.set()
        t.join()
    assert not errors

    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2022-01-02_00:00:01"
    # wp-admin no longer protected; wp-admin2 now is
    assert auth("wp-admin/x").status_code == 200
    assert auth("wp-admin2/x").status_code == 401
    # new per-site block entry applies
    assert auth("/", ip="50.50.50.50").status_code == 403


def test_fail_open_on_handler_crash(app_factory):
    """The CustomRecovery fail-open contract (http_server.go:110-135)."""
    app = app_factory("banjax-config-test.yaml")

    # sabotage a component the handler touches to force an exception
    app.server_deps()  # sanity
    original = app.static_lists.check_per_site
    app.static_lists.check_per_site = None  # type: ignore # next call raises TypeError
    try:
        r = auth("/", ip="1.2.3.4")
        assert r.status_code == 500
        assert r.headers["X-Accel-Redirect"] == "@fail_open"
        assert "X-Banjax-Error" in r.headers
    finally:
        app.static_lists.check_per_site = original
    # and the server still serves
    assert auth("/").status_code == 200
