"""In-process end-to-end tests against the real server in standalone mode.

Model: the reference's integration tier (banjax_integration_test.go +
banjax_base_test.go) — run the real supervisor with -standalone-testing in a
temp dir, drive real HTTP against 127.0.0.1:8081, including the async
regex-banner path (requests write the fake nginx log, the tailer picks lines
up, the matcher bans) and SIGHUP-equivalent hot reload under load.
"""

import base64
import hashlib
import os
import shutil
import time
from pathlib import Path

import pytest
import requests

from banjax_tpu.cli import BanjaxApp
from banjax_tpu.utils import go_query_unescape
from banjax_tpu.crypto.challenge import parse_cookie, solve_challenge_for_testing

FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"
BASE = "http://localhost:8081"


# app_factory: shared fixture in tests/conftest.py (also used by the perf
# tier's HTTP benchmark mirrors)


def auth(path="/", ip=None, cookies=None, host=None, method="GET", ua=None):
    headers = {}
    if ip:
        headers["X-Client-IP"] = ip
    if ua:
        headers["X-Client-User-Agent"] = ua
    return requests.request(
        method, f"{BASE}/auth_request", params={"path": path},
        headers=headers, cookies=cookies or {}, timeout=5,
    )


def test_basic_routes_and_decisions(app_factory):
    app = app_factory("banjax-config-test.yaml")

    # /info
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.status_code == 200
    assert r.json()["config_version"] == "2022-01-02_00:00:00"

    # default allow
    r = auth("/")
    assert r.status_code == 200
    assert r.headers["X-Accel-Redirect"] == "@access_granted"
    assert "deflect_session" in r.cookies

    # global challenge IP gets the PoW page
    r = auth("/", ip="8.8.8.8")
    assert r.status_code == 429
    assert "deflect_challenge3" in r.cookies
    assert b"new_solver(10)" in r.content

    # CIDR challenge
    r = auth("/", ip="192.168.1.77")
    assert r.status_code == 429

    # global block
    r = auth("/", ip="70.80.90.100")
    assert r.status_code == 403
    assert r.headers["X-Accel-Redirect"] == "@access_denied"

    # solve the challenge like the page JS would and get through
    r = auth("/", ip="8.8.8.8")
    unsolved = go_query_unescape(r.cookies["deflect_challenge3"])
    solved = solve_challenge_for_testing(unsolved, 10)
    r = auth("/", ip="8.8.8.8", cookies={"deflect_challenge3": solved})
    assert r.status_code == 200
    assert r.headers["X-Banjax-Decision"] == "ShaChallengePassed"

    # introspection endpoints
    r = requests.get(f"{BASE}/decision_lists", timeout=5)
    assert r.status_code == 200 and "per_site" in r.text
    r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
    assert r.status_code == 200
    r = requests.get(f"{BASE}/is_banned", params={"ip": "1.2.3.4"}, timeout=5)
    assert r.status_code == 200 and r.json()["expiringDecision"] is None
    r = requests.get(f"{BASE}/is_banned", timeout=5)
    assert r.status_code == 400
    r = requests.get(f"{BASE}/banned", params={"domain": "x.com"}, timeout=5)
    assert r.status_code == 200 and r.json()["entries"] == []
    r = requests.get(f"{BASE}/ipset/list", timeout=5)
    assert r.status_code == 200
    r = requests.post(f"{BASE}/unban", data={"ip": "9.9.9.9"}, timeout=5)
    assert r.status_code == 400  # not banned


def test_password_protected_path_flow(app_factory):
    app = app_factory("banjax-config-test.yaml")

    # protected path serves the password page with per-site ttl applied
    r = auth("wp-admin/x")
    assert r.status_code == 401
    assert "deflect_password3" in r.cookies
    assert b"max-age=3600" in r.content  # per-site ttl for localhost:8081

    # exception path passes
    r = auth("wp-admin/admin-ajax.php")
    assert r.status_code == 200

    # build the solved password cookie like the page JS would
    unsolved = go_query_unescape(auth("wp-admin/x").cookies["deflect_password3"])
    hmac_b, _, expiry = parse_cookie(unsolved)
    solution = hashlib.sha256(hmac_b + hashlib.sha256(b"password").digest()).digest()
    solved = base64.standard_b64encode(hmac_b + solution + expiry).decode()
    r = auth("wp-admin/x", cookies={"deflect_password3": solved})
    assert r.status_code == 200

    # wrong password still 401
    bad_solution = hashlib.sha256(hmac_b + hashlib.sha256(b"wrong").digest()).digest()
    bad = base64.standard_b64encode(hmac_b + bad_solution + expiry).decode()
    r = auth("wp-admin/x", cookies={"deflect_password3": bad})
    assert r.status_code == 401


def test_failed_challenge_lockout(app_factory):
    """401 x threshold, then 403 (banjax_integration_test.go:232-250)."""
    app = app_factory("banjax-config-test.yaml")
    ip = "13.13.13.13"
    statuses = []
    for _ in range(7):
        r = auth("wp-admin/x", ip=ip, cookies={"deflect_password3": "garbage"})
        statuses.append(r.status_code)
    assert statuses == [401] * 6 + [403]
    # the ban landed in the expiring list
    r = requests.get(f"{BASE}/is_banned", params={"ip": ip}, timeout=5)
    body = r.json()
    assert body["expiringDecision"] is not None
    # standalone testing: no real ipset call, but the decision is IptablesBlock
    assert body["expiringDecision"]["Decision"] == "IptablesBlock"

    # unban of an IptablesBlock checks the ipset; standalone has no ipset
    # entry, so the reference's "ip is not banned" arm fires (400)
    r = requests.post(f"{BASE}/unban", data={"ip": ip}, timeout=5)
    assert r.status_code == 400 and r.json()["unban"] is False

    # a NginxBlock entry unbans straight from the expiring list
    import time as _time
    from banjax_tpu.decisions.model import Decision
    app.dynamic_lists.update("14.14.14.14", _time.time() + 60,
                             Decision.NGINX_BLOCK, False, "localhost:8081")
    r = requests.post(f"{BASE}/unban", data={"ip": "14.14.14.14"}, timeout=5)
    assert r.status_code == 200 and r.json()["unban"] is True
    r = requests.get(f"{BASE}/is_banned", params={"ip": "14.14.14.14"}, timeout=5)
    assert r.json()["expiringDecision"] is None


def test_regex_banner_bans_after_delay(app_factory):
    """The async tailer path (banjax_integration_test.go:293-385): a request
    whose path matches an instant-ban rule gets banned for the NEXT request."""
    app = app_factory("banjax-config-test-regex-banner.yaml")

    ip = "44.44.44.44"
    r = auth("/challengeme", ip=ip)
    assert r.status_code == 200  # first request passes; the log line is async

    deadline = time.time() + 5
    challenged = False
    while time.time() < deadline:
        r = auth("/", ip=ip)
        if r.status_code == 429:
            challenged = True
            break
        time.sleep(0.1)
    assert challenged, "tailer should have inserted the challenge decision"

    # allowlisted IP is exempt from regex rules
    r = auth("/challengeme", ip="12.12.12.12")
    assert r.status_code == 200
    time.sleep(1.0)
    r = auth("/", ip="12.12.12.12")
    assert r.status_code == 200

    # hosts_to_skip: the challenge-all rule skips localhost:8081, so a
    # plain / request does not get challenged
    r = auth("/", ip="55.55.55.55")
    time.sleep(1.0)
    r = auth("/", ip="55.55.55.55")
    assert r.status_code == 200

    # ban log line was written
    ban_log = Path("banning-log-file.txt").read_text()
    assert '"trigger":"instant challenge"' in ban_log
    assert f'"client_ip":"{ip}"' in ban_log


def test_hot_reload_under_load(app_factory, tmp_path):
    """SIGHUP semantics (banjax_base_test.go:218-242): swap the config file,
    reload, and verify behavior + /info version change with requests in flight."""
    import threading

    app = app_factory("banjax-config-test.yaml")
    assert auth("wp-admin/x").status_code == 401  # wp-admin protected

    stop = threading.Event()
    errors = []

    def hammer():
        while not stop.is_set():
            try:
                auth("/", ip="8.8.8.8")
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    t = threading.Thread(target=hammer)
    t.start()
    try:
        shutil.copy(FIXTURES / "banjax-config-test-reload.yaml",
                    tmp_path / "banjax-config.yaml")
        app.reload()  # the SIGHUP handler body
    finally:
        stop.set()
        t.join()
    assert not errors

    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2022-01-02_00:00:01"
    # wp-admin no longer protected; wp-admin2 now is
    assert auth("wp-admin/x").status_code == 200
    assert auth("wp-admin2/x").status_code == 401
    # new per-site block entry applies
    assert auth("/", ip="50.50.50.50").status_code == 403


def test_fail_open_on_handler_crash(app_factory):
    """The CustomRecovery fail-open contract (http_server.go:110-135)."""
    app = app_factory("banjax-config-test.yaml")

    # sabotage a component the handler touches to force an exception
    app.server_deps()  # sanity
    original = app.static_lists.check_per_site
    app.static_lists.check_per_site = None  # type: ignore # next call raises TypeError
    try:
        r = auth("/", ip="1.2.3.4")
        assert r.status_code == 500
        assert r.headers["X-Accel-Redirect"] == "@fail_open"
        assert "X-Banjax-Error" in r.headers
    finally:
        app.static_lists.check_per_site = original
    # and the server still serves
    assert auth("/").status_code == 200


def _reload_to(app, tmp_path, fixture_name: str) -> None:
    """Swap the on-disk config and run the SIGHUP handler body
    (banjax_base_test.go reloadConfig)."""
    shutil.copy(FIXTURES / fixture_name, tmp_path / "banjax-config.yaml")
    app.reload()


def test_cidr_matrix_and_reload(app_factory, tmp_path):
    """The CIDR decision-mask matrices driven through the real server +
    reload (banjax_integration_test.go:42-66 with
    fixtures/banjax-config-test-reload-cidr.yaml)."""
    app = app_factory("banjax-config-test.yaml")

    # a CIDR string sent AS a client IP is not an IP: skipped, not matched
    assert auth("/global_mask_noban", ip="192.168.1.0/24").status_code == 200
    # member of the global challenge mask 192.168.1.0/24
    assert auth("/global_mask_64_ban", ip="192.168.1.64").status_code == 429
    # outside every mask
    assert auth("/global_mask_bypass", ip="192.168.87.87").status_code == 200
    # per-site challenge mask 192.168.0.0/24 (localhost:8081)
    assert auth("/per_site_mask_noban", ip="192.168.0.0/24").status_code == 200
    assert auth("/per_site_mask_128_ban", ip="192.168.0.128").status_code == 429
    # per-site password ttl present pre-reload (max-age=3600)
    assert b"max-age=3600" in auth("wp-admin/x").content

    _reload_to(app, tmp_path, "banjax-config-test-reload-cidr.yaml")
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2022-03-02_00:00:01"
    # new global nginx_block mask 192.168.2.0/24
    assert auth("/global_mask_64_nginx_block", ip="192.168.2.64").status_code == 403
    # the 192.168.1.0/24 challenge mask is gone
    assert auth("/global_mask_64_no_cha", ip="192.168.1.64").status_code == 200
    # per-site: challenge mask removed, nginx_block mask added
    assert auth("/per_site_mask_noban_128", ip="192.168.0.128").status_code == 200
    assert auth("/per_site_mask_noban_128", ip="192.168.3.128").status_code == 403
    # per-site ttl dropped: password page falls back to the global default
    assert b"max-age=14400" in auth("wp-admin/x").content


def test_sitewide_sha_inv_reload_cycle(app_factory, tmp_path):
    """sitewide_sha_inv_list on -> challenge everything -> off again
    (banjax_integration_test.go:409-435), including actually SOLVING the
    sitewide challenge while it is on."""
    app = app_factory("banjax-config-test.yaml")
    assert auth("/1").status_code == 200  # list off

    _reload_to(app, tmp_path, "banjax-config-test-sha-inv.yaml")
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2022-02-03_00:00:02"
    r = auth("/2")
    assert r.status_code == 429  # every path challenged now
    assert "deflect_challenge3" in r.cookies
    unsolved = go_query_unescape(r.cookies["deflect_challenge3"])
    solved = solve_challenge_for_testing(unsolved, 10)
    r = auth("/2", cookies={"deflect_challenge3": solved})
    assert r.status_code == 200
    assert r.headers["X-Banjax-Decision"] == "ShaChallengePassed"

    _reload_to(app, tmp_path, "banjax-config-test.yaml")
    assert auth("/3").status_code == 200  # list off again


def test_persite_fail_allowlisted_lockout_cycle(app_factory, tmp_path):
    """Failed-password lockout at threshold 3 for an ALLOWLISTED client:
    401 x3, one 403 (the lockout fires and resets), then 401 again — the
    per-site allow (exact IP and CIDR member alike) exempts the client
    from the expiring block the lockout inserted
    (banjax_integration_test.go:232-250 with
    fixtures/banjax-config-test-persite-fail.yaml)."""
    app = app_factory("banjax-config-test.yaml")
    _reload_to(app, tmp_path, "banjax-config-test-persite-fail.yaml")
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2023-08-23_00:00:01"

    for ip in ("92.92.92.92", "192.168.1.87"):
        statuses = [auth("/wp-admin", ip=ip).status_code for _ in range(5)]
        assert statuses == [401, 401, 401, 403, 401], (ip, statuses)


def test_user_agent_precedence_matrix(app_factory, tmp_path):
    """Global UA block/challenge patterns and the per-site UA allow
    override, including precedence against a global challenge IP
    (banjax_integration_test.go:437-463 + TestPerSiteUserAgentDecisionLists
    with fixtures/banjax-config-test-ua.yaml)."""
    app = app_factory("banjax-config-test.yaml")
    _reload_to(app, tmp_path, "banjax-config-test-ua.yaml")
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.json()["config_version"] == "2025-01-01_00:00:01"

    ahrefs = "Mozilla/5.0 (compatible; AhrefsBot/7.0; +http://ahrefs.com/robot/)"
    semrush = "Mozilla/5.0 (compatible; SemrushBot/7.0; +http://www.semrush.com/bot.html)"
    ff_mac = "Mozilla/5.0 (Macintosh; Intel Mac OS X 10.15; rv:149.0) Gecko/20100101 Firefox/149.0"
    ff_win = "Mozilla/5.0 (Windows NT 10.0; Win64; x64; rv:149.0) Gecko/20100101 Firefox/149.0"
    gbot = "Mozilla/5.0 (compatible; Googlebot/2.1; +http://www.google.com/bot.html)"
    gpt = "Mozilla/5.0 (compatible; GPTBot/1.0; +https://openai.com/gptbot)"

    assert auth("/ua_ahref", ua=ahrefs).status_code == 403
    assert auth("/ua_semrush", ua=semrush).status_code == 403
    assert auth("/ua_firefox_mac", ua=ff_mac).status_code == 429
    assert auth("/ua_firefox_win", ua=ff_win).status_code == 200
    assert auth("/ua_googlebot", ua=gbot).status_code == 200

    # precedence against the global challenge IP 8.8.8.8:
    assert auth("/ua_ip_challenge", ip="8.8.8.8").status_code == 429
    # per-site UA allow overrides the global IP challenge
    assert auth("/ua_gptbot_override", ip="8.8.8.8", ua=gpt).status_code == 200
    # no per-site rule for AhrefsBot: the IP challenge fires before the
    # global UA block
    assert auth("/ua_ahref_challenged_ip", ip="8.8.8.8", ua=ahrefs).status_code == 429


def test_regex_banner_via_tpu_matcher(app_factory, tmp_path):
    """The same async tailer→ban flow as test_regex_banner_bans_after_delay
    but with `matcher: tpu` (batched device path, device windows on, XLA
    backend under CI's CPU) — the full production seam: request → access
    log → tailer batch → TpuMatcher consume_lines → Banner → dynamic
    lists → next request challenged."""
    src = (FIXTURES / "banjax-config-test-regex-banner.yaml").read_text()
    tpu_fixture = tmp_path / "regex-banner-tpu.yaml"
    tpu_fixture.write_text(src + (
        "matcher: tpu\n"
        "matcher_backend: xla\n"
        "matcher_batch_lines: 64\n"
        "matcher_device_windows: true\n"
        "matcher_window_capacity: 0\n"
    ))
    # app_factory copies from FIXTURES; write the variant there-adjacent by
    # copying into the temp cwd ourselves and starting on it
    shutil.copy(tpu_fixture, tmp_path / "banjax-config.yaml")
    app = BanjaxApp(
        str(tmp_path / "banjax-config.yaml"), standalone_testing=True,
        debug=False,
    )
    app.start_background()
    try:
        from banjax_tpu.matcher.runner import TpuMatcher

        _, matcher = app._current_matcher()
        assert isinstance(matcher, TpuMatcher)
        assert matcher.device_windows is not None

        ip = "46.46.46.46"
        r = auth("/challengeme", ip=ip)
        assert r.status_code == 200  # first request passes; log line is async

        deadline = time.time() + 8
        challenged = False
        while time.time() < deadline:
            r = auth("/", ip=ip)
            if r.status_code == 429:
                challenged = True
                break
            time.sleep(0.1)
        assert challenged, "TPU matcher path should have inserted the challenge"

        # allowlist exemption flows through the TPU gate too
        r = auth("/challengeme", ip="12.12.12.12")
        assert r.status_code == 200
        time.sleep(1.0)
        assert auth("/", ip="12.12.12.12").status_code == 200

        ban_log = Path("banning-log-file.txt").read_text()
        assert '"trigger":"instant challenge"' in ban_log
        assert f'"client_ip":"{ip}"' in ban_log
    finally:
        app.stop_background()
