"""Multi-worker (SO_REUSEPORT) serving mode, end-to-end.

The reference is one Go process (http_server.go:32); our multi-process
mode (httpapi/workers.py) must preserve its decision semantics across
process boundaries: shared failed-challenge counting (native shm table),
ban propagation (worker -> primary -> broadcast), cold-route proxying,
and SIGHUP reload fan-out.  Each request below uses a FRESH connection so
the kernel's SO_REUSEPORT hashing spreads them across the processes.
"""

import os
import time
from pathlib import Path

import pytest
import requests

from banjax_tpu.native import shm

pytestmark = pytest.mark.skipif(
    not shm.available(), reason="no C compiler for native shmstate"
)

BASE = "http://localhost:8081"
_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"


def _auth(path, ip, cookies=None):
    # NO session: a fresh TCP connection per request, so consecutive
    # requests land on different SO_REUSEPORT listeners
    return requests.get(
        f"{BASE}/auth_request", params={"path": path},
        headers={"X-Client-IP": ip}, cookies=cookies or {}, timeout=5,
    )


@pytest.fixture()
def workers_app(app_factory, tmp_path):
    custom = tmp_path / "banjax-config-workers.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_workers: 2\n"
    )
    app = app_factory(str(custom))
    # wait until both worker processes hold the port (they answer /info)
    deadline = time.time() + 15
    while time.time() < deadline:
        if all(p.poll() is None for p in app._supervisor._procs):
            try:
                requests.get(f"{BASE}/info", timeout=2)
                break
            except requests.RequestException:
                pass
        time.sleep(0.2)
    time.sleep(1.0)  # let late binders finish
    assert all(p.poll() is None for p in app._supervisor._procs), (
        "worker process died at startup"
    )
    return app


def test_workers_failed_challenge_lockout_across_processes(workers_app):
    """The failed-challenge lockout (banjax_integration_test.go:232-250)
    with every 401 potentially served by a different process: the shm
    table must count them as one stream."""
    ip = "23.23.23.23"
    statuses = [
        _auth("wp-admin/x", ip, {"deflect_password3": "garbage"}).status_code
        for _ in range(6)
    ]
    # threshold 5 in the fixture: six failures all render the password page
    # (the exceed lands on the 6th; its response is still 401)
    assert statuses == [401] * 6, statuses

    # the ban propagates to every replica within the broadcast latency
    deadline = time.time() + 5
    banned = False
    while time.time() < deadline:
        if _auth("wp-admin/x", ip).status_code == 403:
            banned = True
            break
        time.sleep(0.1)
    assert banned, "ban did not propagate to the serving process"

    # ... and is authoritative on the primary (cold route, any process
    # proxies it there)
    r = requests.get(f"{BASE}/is_banned", params={"ip": ip}, timeout=5)
    body = r.json()
    assert body["expiringDecision"] is not None
    assert body["expiringDecision"]["Decision"] == "IptablesBlock"

    # once banned, EVERY process serves 403 (spread over fresh conns)
    codes = {_auth("/", ip).status_code for _ in range(6)}
    assert codes == {403}, codes


def test_workers_cold_routes_proxied(workers_app):
    """All primary-owned routes answer correctly regardless of which
    process the kernel hands the connection to."""
    for _ in range(4):  # several fresh connections -> several processes
        r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
        assert r.status_code == 200 and "failed challenges:" in r.text
        r = requests.get(f"{BASE}/decision_lists", timeout=5)
        assert r.status_code == 200 and "per_site:" in r.text
        r = requests.get(f"{BASE}/ipset/list", timeout=5)
        assert r.status_code == 200 and "entries" in r.json()
        r = requests.get(f"{BASE}/info", timeout=5)
        assert r.status_code == 200 and "config_version" in r.json()


def test_workers_shared_fc_states_visible_in_introspection(workers_app):
    ip = "24.24.24.24"
    for _ in range(2):
        _auth("wp-admin/x", ip, {"deflect_password3": "garbage"})
    # the proxied /rate_limit_states reads the SAME shm table the workers
    # counted into
    r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
    assert f"{ip},: interval_start: " in r.text


def test_workers_reload_fans_out(workers_app, tmp_path):
    """SIGHUP on the primary rewrites worker config too (config_version
    served by every process converges on the new value)."""
    app = workers_app
    cfg_path = Path(app.config_holder.path)
    new_version = "2033-03-03_03:03:03"
    text = cfg_path.read_text().replace(
        "config_version: 2022-01-02_00:00:00",
        f"config_version: {new_version}",
    )
    assert new_version in text, "fixture version marker changed"
    cfg_path.write_text(text)

    app.reload()  # the SIGHUP body; broadcasts {op: reload}

    deadline = time.time() + 10
    seen = set()
    while time.time() < deadline:
        seen = {
            requests.get(f"{BASE}/info", timeout=5).json()["config_version"]
            for _ in range(6)
        }
        if seen == {new_version}:
            break
        time.sleep(0.2)
    assert seen == {new_version}, f"stale config still served: {seen}"


def test_workers_survive_worker_kill_and_respawn(workers_app):
    """Killing one worker must not take the service down — remaining
    listeners keep answering — and the supervisor's monitor respawns the
    dead slot (with backoff) so capacity heals."""
    app = workers_app
    victim = app._supervisor._procs[0]
    victim.terminate()
    victim.wait(timeout=5)
    deadline = time.time() + 5
    ok = 0
    while time.time() < deadline and ok < 10:
        try:
            r = _auth("/", f"30.30.30.{ok + 1}")
            if r.status_code == 200:
                ok += 1
        except requests.RequestException:
            pass  # a connection may land on the dead listener's backlog
        time.sleep(0.05)
    assert ok >= 10, "service did not keep answering after a worker died"

    # the monitor (1s interval + 1s first backoff) replaces the process
    deadline = time.time() + 15
    while time.time() < deadline:
        newproc = app._supervisor._procs[0]
        if newproc.pid != victim.pid and newproc.poll() is None:
            break
        time.sleep(0.25)
    newproc = app._supervisor._procs[0]
    assert newproc.pid != victim.pid and newproc.poll() is None, (
        "worker slot 0 was not respawned"
    )
    assert app._supervisor.respawn_count >= 1

    # the respawned worker came up healthy: it survives a serving burst
    # (a broken listener would crash/exit on arrival) and the service
    # answers throughout
    deadline = time.time() + 10
    served = 0
    while time.time() < deadline and served < 8:
        if _auth("/", "30.30.31.1").status_code == 200:
            served += 1
    assert served >= 8
    assert newproc.poll() is None, "respawned worker died during serving"


def test_workers_soak_load_reload_kill(workers_app, tmp_path):
    """Race soak for the multi-process serving stack: sustained hot-path
    load while the config hot-reloads and a worker is killed mid-stream.
    Every response must be a valid decision (no 5xx, no connection
    resets leaking to the client as errors), and the stack must end
    healthy."""
    import threading

    app = workers_app
    errors: list = []
    codes: set = set()
    stop = threading.Event()

    def load(tid: int) -> None:
        n = 0
        s = requests.Session()  # keep-alive: exercises in-flight kills
        while not stop.is_set() and n < 400:
            ip = f"31.31.{tid}.{(n % 250) + 1}"
            try:
                r = s.get(
                    f"{BASE}/auth_request", params={"path": "/"},
                    headers={"X-Client-IP": ip}, timeout=5,
                )
                codes.add(r.status_code)
                if r.status_code >= 500:
                    errors.append((tid, n, r.status_code))
            except requests.RequestException:
                # a killed worker's in-flight connection may reset; the
                # CLIENT retries (nginx does the same via upstream retry)
                s = requests.Session()
            n += 1

    threads = [threading.Thread(target=load, args=(t,)) for t in range(3)]
    for t in threads:
        t.start()
    try:
        time.sleep(0.5)
        app.reload()  # SIGHUP body mid-load (broadcasts to workers)
        time.sleep(0.5)
        victim = app._supervisor._procs[1]
        victim.terminate()  # kill a worker mid-load
        time.sleep(1.0)
        app.reload()  # reload again while a slot is respawning
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)

    assert not errors, f"5xx under soak: {errors[:5]}"
    assert codes <= {200, 429, 403, 401}, codes
    # stack healthy afterwards: every route answers
    r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
    assert r.status_code == 200
    assert _auth("/", "32.32.32.1").status_code == 200


def test_http_workers_auto_on_single_core(app_factory, tmp_path):
    """http_workers: -1 resolves to cores-1 (0 on this 1-core box — the
    single-process layout, no supervisor)."""
    custom = tmp_path / "banjax-config-auto.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_workers: -1\n"
    )
    app = app_factory(str(custom))
    expected = max(0, (os.cpu_count() or 1) - 1)
    if expected == 0:
        assert app._supervisor is None
    else:
        assert app._supervisor is not None
        assert app._supervisor.n_workers == expected
    r = requests.get(f"{BASE}/info", timeout=5)
    assert r.status_code == 200


def test_workers_with_fast_path_disabled(app_factory, tmp_path):
    """The aiohttp worker layout (http_fast_path: false + workers) still
    serves hot and cold routes with shared fc counting — the pre-fastserve
    topology must not rot while it remains configurable."""
    custom = tmp_path / "banjax-config-aio-workers.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_workers: 2\nhttp_fast_path: false\n"
    )
    app = app_factory(str(custom))
    deadline = time.time() + 15
    while time.time() < deadline:
        if all(p.poll() is None for p in app._supervisor._procs):
            try:
                requests.get(f"{BASE}/info", timeout=2)
                break
            except requests.RequestException:
                pass
        time.sleep(0.2)
    time.sleep(1.0)
    assert all(p.poll() is None for p in app._supervisor._procs)

    ip = "26.26.26.26"
    statuses = [
        _auth("wp-admin/x", ip, {"deflect_password3": "garbage"}).status_code
        for _ in range(3)
    ]
    assert statuses == [401] * 3
    r = requests.get(f"{BASE}/rate_limit_states", timeout=5)
    assert r.status_code == 200 and f"{ip},: interval_start: " in r.text
    r = requests.get(f"{BASE}/is_banned", params={"ip": ip}, timeout=5)
    assert r.status_code == 200
