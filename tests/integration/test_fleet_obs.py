"""Fleet observability end-to-end (ISSUE 20): two REAL worker
processes over real sockets.  One episode proves the acceptance
narrative in order:

  1. a chunk tailed at w0 whose lines hash to w1 produces a ban whose
     provenance on w1 carries ``(origin_node=w0, origin_trace_id)``,
     and that trace id joins a ``fabric.route`` span in w0's ring and
     a ``fabric.remote-drain`` span in w1's ring — the cross-host
     trace join, across process boundaries;
  2. a federated scrape over the live peer wire merges both nodes'
     expositions into one strictly-parseable payload with summed
     fleet counters and per-instance gauges;
  3. T_FLIGHTREC fan-out returns each ALIVE member's capture files;
  4. after SIGKILLing w1 mid-scrape the next merge is partial but
     honest: still parseable, w1 flagged unreachable + stale."""

import json
import threading
import time

from banjax_tpu.fabric import wire
from banjax_tpu.fabric.harness import _fake_broker, _spawn
from banjax_tpu.fabric.hashring import ConsistentHashRing
from banjax_tpu.obs.exposition import parse_text_format
from banjax_tpu.obs.fleet import PEER_CAPTURE_FILES, FleetScraper, capture_fleet
from banjax_tpu.scenarios.shapes import T0

_READY_TIMEOUT_S = 420.0

LOCAL_TEXT = (
    "# HELP banjax_x_total t\n# TYPE banjax_x_total counter\n"
    "banjax_x_total 1\n"
)


def _hello(workers):
    return {
        "peers": {w.wid: ["127.0.0.1", w.port] for w in workers.values()},
        "vnodes": 64,
        "send_timeout_ms": 2000.0,
        "grace_ms": 200.0,
        "inflight_frames": 8,
        "wire_v2": True,
        "shm": False,
        "trace_propagation": True,
    }


def _owned_ip(owner):
    ring = ConsistentHashRing(("w0", "w1"), vnodes=64)
    i = 0
    while True:
        ip = f"10.{(i >> 8) & 255}.{i & 255}.7"
        if ring.owner(ip) == owner:
            return ip
        i += 1


def _probe_lines(ip, n=20):
    # login_probe: 8 hits / 5 s -> iptables_block; 20 hits in 2 s bans
    return [
        f"{T0 + i * 0.1:.6f} {ip} GET example.com GET /wp-login.php "
        "HTTP/1.1 scanner -"
        for i in range(n)
    ]


def _spans(files):
    return json.loads(files["trace.json"])["traceEvents"]


def test_fleet_observability_episode(tmp_path):
    broker = _fake_broker()
    broker.start()
    workers = {}
    try:
        for wid in ("w0", "w1"):
            workers[wid] = _spawn(
                wid, broker.port, str(tmp_path / f"{wid}.err"),
                extra_args=("--trace-propagation", "1"),
            )
        threads = [
            threading.Thread(
                target=w.read_ready, args=(_READY_TIMEOUT_S,), daemon=True
            )
            for w in workers.values()
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(_READY_TIMEOUT_S + 5)
        bad = [w.wid for w in workers.values() if w.port is None]
        assert not bad, f"workers failed to start: {bad}"

        hello = _hello(workers)
        for w in workers.values():
            w.request(wire.T_HELLO, hello)

        # ---- 1. forwarded-ban trace join -----------------------------
        ip = _owned_ip("w1")  # tailed at w0, owned by w1
        workers["w0"].request(
            wire.T_LINES, {"lines": _probe_lines(ip), "route": True}
        )
        workers["w0"].request(wire.T_FLUSH, {"timeout": 600})
        workers["w1"].request(wire.T_FLUSH, {"timeout": 600})

        explain = None
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            explain = workers["w1"].request(wire.T_EXPLAIN, {"ip": ip})
            if explain["records"]:
                break
            time.sleep(0.25)
        assert explain["node_id"] == "w1"
        assert explain["records"], f"no ban recorded for {ip}"
        origin_recs = [
            r for r in explain["records"] if r.get("origin_node") == "w0"
        ]
        assert origin_recs, explain["records"]
        origin_tid = origin_recs[0]["origin_trace_id"]
        assert origin_tid > 0

        # the SAME trace id appears in BOTH processes' span rings:
        # w0 allocated it at admission (fabric.route), w1 opened the
        # linked owner-side drain span (fabric.remote-drain) under it
        cap0 = workers["w0"].request(
            wire.T_FLIGHTREC, {"incident": "join-probe", "from": "t"}
        )
        cap1 = workers["w1"].request(
            wire.T_FLIGHTREC, {"incident": "join-probe", "from": "t"}
        )
        route_tids = {
            e["args"]["trace_id"] for e in _spans(cap0["files"])
            if e["name"] == "fabric.route"
        }
        drain = [
            e for e in _spans(cap1["files"])
            if e["name"] == "fabric.remote-drain"
        ]
        assert origin_tid in route_tids
        assert any(
            e["args"]["trace_id"] == origin_tid
            and e["args"]["origin_node"] == "w0"
            for e in drain
        ), drain

        # ---- 2. federated metrics over the live peer wire ------------
        def pull(w):
            def _pull():
                r = w.request(wire.T_STATS, {"metrics": True})
                if "metrics_text" not in r:
                    raise OSError(r.get("metrics_error", "no metrics"))
                return r["metrics_text"]

            return _pull

        scraper = FleetScraper(
            "driver", lambda: LOCAL_TEXT,
            peers_fn=lambda: {w.wid: pull(w) for w in workers.values()},
        )
        merged = scraper.scrape()
        parsed = parse_text_format(merged)  # strict parse of the merge
        # both engines processed lines: the summed fleet counter covers
        # the whole chunk regardless of which shard drained it
        total = sum(
            v for _n, _l, v in
            parsed["banjax_pipeline_processed_lines_total"]["samples"]
        )
        assert total >= 20
        unreach = {
            labels["instance"]: v
            for _n, labels, v in
            parsed["banjax_fleet_peer_unreachable"]["samples"]
        }
        assert unreach == {"driver": 0, "w0": 0, "w1": 0}
        # gauges carry instance labels per node
        health_insts = {
            labels.get("instance")
            for _n, labels, _v in
            parsed["banjax_pipeline_buffered_lines"]["samples"]
        }
        assert {"w0", "w1"} <= health_insts

        # ---- 3. cluster incident capture fan-out ---------------------
        def cap(w):
            def _cap(incident):
                r = w.request(
                    wire.T_FLIGHTREC, {"incident": incident, "from": "t"}
                )
                return r["files"]

            return _cap

        bundles = capture_fleet(
            "inc-episode",
            lambda: {w.wid: cap(w) for w in workers.values()},
        )
        for wid in ("w0", "w1"):
            assert set(PEER_CAPTURE_FILES) <= set(bundles[wid]), wid
            parse_text_format(bundles[wid]["metrics.prom"])

        # ---- 4. SIGKILL one member: partial but honest ---------------
        workers["w1"].kill()
        workers["w1"].proc.wait(timeout=10)
        merged = scraper.scrape()
        parsed = parse_text_format(merged)  # STILL strictly parseable
        unreach = {
            labels["instance"]: v
            for _n, labels, v in
            parsed["banjax_fleet_peer_unreachable"]["samples"]
        }
        assert unreach["w1"] == 1
        assert unreach["w0"] == 0
        stale = {
            labels["instance"]: v
            for _n, labels, v in
            parsed["banjax_fleet_peer_staleness_seconds"]["samples"]
        }
        assert stale["w1"] >= 0.0
        # the dead member's cached families are still merged in
        assert {"w0", "w1"} <= {
            labels.get("instance")
            for _n, labels, _v in
            parsed["banjax_pipeline_buffered_lines"]["samples"]
        }
    finally:
        for w in workers.values():
            try:
                w.shutdown()
            except Exception:  # noqa: BLE001 — teardown best-effort
                w.proc.kill()
        broker.stop()
