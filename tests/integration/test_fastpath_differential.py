"""Fast-path differential: decision-table hits vs the unchanged chain.

Referenced by httpapi/fastpath.py as its byte-identity proof.  The
strongest comparison runs on ONE app: the `serve.fastpath.lookup`
failpoint forces a request through the full decision chain, disarming
it lets the compiled fast path serve the identical request — status
line, header order, X-Accel-Redirect, cookies and body must match to
the byte (fresh session/challenge randomness normalized on both sides).
A second suite pins expiry-boundary agreement on BOTH HTTP layouts
(`http_fast_path` true/false), and the table-full case proves a refused
IP serves identically through the chain.
"""

import re
import socket
import time
from pathlib import Path

import pytest

from banjax_tpu.crypto.session import new_session_cookie
from banjax_tpu.decisions.model import Decision
from banjax_tpu.httpapi.serve_stats import get_stats
from banjax_tpu.resilience import failpoints
from banjax_tpu.utils import go_query_escape

_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"
HOST = "eligible.example.net"  # in no per-site/password list: fast-path eligible
SECRET = "session_secret"  # fixture session_cookie_hmac_secret


@pytest.fixture(autouse=True)
def _clean():
    failpoints.disarm()
    get_stats().reset()
    yield
    failpoints.disarm()
    get_stats().reset()


def _fastserve_app(app_factory, tmp_path, extra=""):
    cfg = tmp_path / "cfg-fpdiff.yaml"
    cfg.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_fast_path: true\nserve_fastpath_enabled: true\n"
        + extra
    )
    app = app_factory(str(cfg))
    time.sleep(0.5)
    return app


def _raw_request(ip, path="/", host=HOST, cookie=None, method="GET"):
    head = (
        f"{method} /auth_request?path={path} HTTP/1.1\r\n"
        f"Host: {host}\r\n"
        f"X-Client-IP: {ip}\r\n"
        "X-Client-User-Agent: mozilla\r\n"
    )
    if cookie:
        head += f"Cookie: {cookie}\r\n"
    head += "Connection: close\r\n\r\n"

    s = socket.create_connection(("127.0.0.1", 8081), timeout=5)
    try:
        s.sendall(head.encode())
        out = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            out += chunk
    finally:
        s.close()
    return out


# fresh randomness on both sides of the diff: minted session values
# (echoed into a header and a Set-Cookie) and challenge payloads
_MASKS = (
    (re.compile(rb"(X-Deflect-Session: )(\S+)"), rb"\1MASKED"),
    (re.compile(rb"(deflect_session=)([^;\r\n]+)"), rb"\1MASKED"),
    (re.compile(rb"(deflect_challenge3=)([^;\r\n]+)"), rb"\1MASKED"),
    (re.compile(rb"[A-Za-z0-9+/=]{40,}"), rb"MASKEDB64"),
)


def _norm(raw: bytes) -> bytes:
    for pat, repl in _MASKS:
        raw = pat.sub(repl, raw)
    return raw


def _diff_one(desc, **kw):
    """The same request through the chain (failpoint armed) and the fast
    path (disarmed) — normalized bytes must be identical."""
    stats = get_stats()
    failpoints.arm("serve.fastpath.lookup")
    try:
        faults_before = stats.prom_snapshot()["faults_total"]
        chain = _raw_request(**kw)
        assert stats.prom_snapshot()["faults_total"] == faults_before + 1, desc
    finally:
        failpoints.disarm("serve.fastpath.lookup")
    hits_before = stats.prom_snapshot()["hits_total"]
    fast = _raw_request(**kw)
    assert stats.prom_snapshot()["hits_total"] == hits_before + 1, desc
    assert _norm(fast) == _norm(chain), (
        desc, _norm(fast)[:400], _norm(chain)[:400]
    )
    return fast


def _session(ip, ttl=3600):
    return "deflect_session=" + go_query_escape(
        new_session_cookie(SECRET, ttl, ip)
    )


def test_fastpath_hits_are_byte_identical_to_chain(app_factory, tmp_path):
    app = _fastserve_app(app_factory, tmp_path)
    now = time.time()
    lists = app.dynamic_lists
    lists.update("43.0.0.1", now + 600, Decision.ALLOW, False, "d")
    lists.update("43.0.0.2", now + 600, Decision.NGINX_BLOCK, False, "d")
    lists.update("43.0.0.3", now + 600, Decision.IPTABLES_BLOCK, False, "d")
    lists.update("43.0.0.4", now + 600, Decision.CHALLENGE, False, "d")

    raw = _diff_one("allow, cookie echo", ip="43.0.0.1",
                    cookie=_session("43.0.0.1"))
    assert raw.startswith(b"HTTP/1.1 200")
    assert b"X-Deflect-Session-New: false\r\n" in raw

    raw = _diff_one("allow, mint", ip="43.0.0.1")
    assert b"X-Deflect-Session-New: true\r\n" in raw
    assert b"Set-Cookie: deflect_session=" in raw

    raw = _diff_one("allow, foreign-ip cookie re-mints", ip="43.0.0.1",
                    cookie=_session("99.99.99.99"))
    assert b"X-Deflect-Session-New: true\r\n" in raw

    raw = _diff_one("nginx block", ip="43.0.0.2",
                    cookie=_session("43.0.0.2"))
    assert raw.startswith(b"HTTP/1.1 403")
    assert b"X-Accel-Redirect: @access_denied\r\n" in raw

    raw = _diff_one("iptables block", ip="43.0.0.3")
    assert raw.startswith(b"HTTP/1.1 403")

    raw = _diff_one("challenge", ip="43.0.0.4")
    assert b"deflect_challenge3=" in raw

    raw = _diff_one("HEAD allow", ip="43.0.0.1", method="HEAD",
                    cookie=_session("43.0.0.1"))
    head, _, tail = raw.partition(b"\r\n\r\n")
    assert tail == b"", "HEAD leaked body bytes"

    app.stop_background()


def test_misses_defer_to_chain_identically(app_factory, tmp_path):
    """Ineligible/miss requests return None from the fast path on both
    arms — the diff still holds (trivially through the chain) and the
    miss reasons land in the counters."""
    app = _fastserve_app(app_factory, tmp_path)
    now = time.time()
    app.dynamic_lists.update("43.1.0.1", now + 600, Decision.ALLOW, False, "d")

    stats = get_stats()
    # password-protected host: chain territory (fixture lists localhost)
    a = _raw_request(ip="43.1.0.1", host="localhost")
    b = _raw_request(ip="43.1.0.1", host="localhost")
    assert _norm(a) == _norm(b)
    # unknown IP: table miss
    _raw_request(ip="43.1.0.99")
    misses = stats.prom_snapshot()["misses"]
    assert misses.get("ineligible", 0) >= 2
    assert misses.get("table", 0) >= 1
    app.stop_background()


def test_table_full_refusal_serves_through_chain(app_factory, tmp_path):
    app = _fastserve_app(app_factory, tmp_path,
                         extra="serve_decision_table_capacity: 2\n")
    table = app.decision_table
    assert table is not None and table.capacity == 2
    now = time.time()
    ips = [f"43.2.0.{i}" for i in range(1, 6)]
    for ip in ips:
        app.dynamic_lists.update(ip, now + 600, Decision.ALLOW, False, "d")
    assert len(table) == 2
    assert table.dropped >= 3  # refusals counted, never evictions

    # every IP — mirrored or refused — serves the same allow contract,
    # and a refused IP is still byte-identical chain vs fast path (both
    # arms ride the chain; the diff must hold trivially)
    for ip in ips:
        raw = _raw_request(ip=ip, cookie=_session(ip))
        assert raw.startswith(b"HTTP/1.1 200"), ip
        assert b"X-Banjax-Decision: ExpiringAccessGranted\r\n" in raw, ip
    refused = next(ip for ip in ips if table.get(ip) is None)
    _diff_one_refused = _raw_request(ip=refused, cookie=_session(refused))
    armed = None
    failpoints.arm("serve.fastpath.lookup")
    try:
        armed = _raw_request(ip=refused, cookie=_session(refused))
    finally:
        failpoints.disarm("serve.fastpath.lookup")
    assert _norm(_diff_one_refused) == _norm(armed)
    app.stop_background()


@pytest.mark.parametrize("fast_path", [True, False],
                         ids=["fastserve", "aiohttp"])
def test_expiry_boundary_agreement_both_layouts(app_factory, tmp_path,
                                                fast_path):
    """An entry crossing its expiry must flip exactly once, from the
    granted contract to the same response an unknown IP gets — on the
    fastserve layout (fast path + chain lazy-delete) AND the aiohttp
    layout (chain only)."""
    cfg = tmp_path / f"cfg-exp-{fast_path}.yaml"
    cfg.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + f"\nhttp_fast_path: {str(fast_path).lower()}\n"
    )
    app = app_factory(str(cfg))
    time.sleep(0.5)

    import requests as rq

    def shape(ip):
        r = rq.get(
            "http://localhost:8081/auth_request", params={"path": "/"},
            headers={"X-Client-IP": ip, "Host": HOST}, timeout=5,
        )
        return (r.status_code, r.headers.get("X-Banjax-Decision"),
                r.headers.get("X-Accel-Redirect"))

    unknown = shape("43.3.0.99")  # what "no decision" looks like here

    expiry = time.time() + 1.2
    app.dynamic_lists.update("43.3.0.1", expiry, Decision.ALLOW, False, "d")
    seen = []
    while time.time() < expiry + 0.6:
        seen.append(shape("43.3.0.1"))
        time.sleep(0.1)

    granted = (200, "ExpiringAccessGranted", "@access_granted")
    assert seen[0] == granted
    assert seen[-1] == unknown
    flips = sum(1 for a, b in zip(seen, seen[1:]) if a != b)
    assert flips == 1, seen
    if fast_path:
        # the expired entry was seen by the fast path at least once
        # before the chain lazily deleted it
        snap = get_stats().prom_snapshot()
        assert snap["hits"].get("allow", 0) >= 1
    app.stop_background()
