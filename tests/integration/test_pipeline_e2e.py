"""The pipeline scheduler wired into the real app: tailer → scheduler →
matcher → Banner, plus the health and metrics surfaces (cli.py wiring).
"""

import json
import time

import requests

BASE = "http://localhost:8081"


def _append_log(path, lines):
    with open(path, "a", encoding="utf-8") as f:
        f.write("".join(l + "\n" for l in lines))


def test_pipeline_enabled_app_end_to_end(app_factory, tmp_path):
    app = app_factory("banjax-config-test-pipeline.yaml")
    assert app.pipeline is not None

    # the real tailer follows the standalone log file from EOF
    assert app.tailer.opened.wait(5)
    now = time.time()
    _append_log(
        "testing-log-file.txt",
        [
            f"{now:.6f} 44.44.44.{i} GET example.com GET /blockme "
            "HTTP/1.1 ua -"
            for i in range(40)
        ],
    )

    # the instant-block rule must ban every IP through the async pipeline
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        if app.pipeline.stats.processed_lines >= 40:
            break
        time.sleep(0.05)
    assert app.pipeline.stats.processed_lines >= 40
    challenges, blocks = app.dynamic_lists.metrics()
    assert challenges + blocks == 40

    # pipeline is a health component on /healthz
    r = requests.get(f"{BASE}/healthz", timeout=5)
    assert r.status_code == 200
    assert "pipeline" in r.json()["components"]

    # and its counters ride the metrics line
    snap = app.pipeline.snapshot()
    assert snap["PipelineAdmittedLines"] >= 40
    assert snap["PipelineShedLines"] == 0

    from io import StringIO

    from banjax_tpu.obs.metrics import write_metrics_line

    out = StringIO()
    write_metrics_line(
        out, app.dynamic_lists, app.regex_states,
        app.failed_challenge_states, app._matcher, None, app.health,
        app.pipeline,
    )
    line = json.loads(out.getvalue())
    assert line["PipelineProcessedLines"] >= 40
    assert line["Health_pipeline"] == "healthy"
