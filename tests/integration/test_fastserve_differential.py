"""Wire-contract differential: fastserve vs the aiohttp layout.

The native protocol server (httpapi/fastserve.py) must emit the same
responses as the aiohttp application for the same requests — status,
content type, decision headers, cookie names/attributes, and body bytes
(bodies are config-deterministic; cookie VALUES are random/expiry-bound
and compared by shape).  Runs the same request corpus against both
layouts (`http_fast_path: true` / `false`) and diffs.
"""

import time
from pathlib import Path

import pytest
import requests

BASE = "http://localhost:8081"
_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"

CORPUS = [
    # (method, path-with-query, headers, cookies, data)
    ("GET", "/auth_request?path=/", {"X-Client-IP": "41.41.41.1"}, {}, None),
    ("GET", "/auth_request?path=wp-admin/x", {"X-Client-IP": "41.41.41.2"}, {}, None),
    ("GET", "/auth_request?path=wp-admin/x", {"X-Client-IP": "41.41.41.3"},
     {"deflect_password3": "garbage"}, None),
    ("GET", "/auth_request?path=wp-admin/admin-ajax.php",
     {"X-Client-IP": "41.41.41.4"}, {}, None),
    ("POST", "/auth_request?path=/", {"X-Client-IP": "41.41.41.5"}, {}, None),
    ("GET", "/auth_request?path=/x", {"X-Client-IP": "8.8.8.8"}, {}, None),  # challenge-listed
    ("GET", "/auth_request?path=/y", {"X-Client-IP": "70.80.90.100"}, {}, None),  # nginx_block
    ("GET", "/info", {}, {}, None),
    ("GET", "/is_banned?ip=5.6.7.8", {}, {}, None),
    ("GET", "/decision_lists", {}, {}, None),
    ("GET", "/rate_limit_states", {}, {}, None),
    ("GET", "/banned?domain=example.com", {}, {}, None),
    ("POST", "/unban", {}, {}, {"ip": "1.2.3.4"}),
    ("GET", "/ipset/list", {}, {}, None),
    ("GET", "/nonexistent", {}, {}, None),
    # route/method edge cases: both layouts must agree (404/405 from the
    # aiohttp router, not a fast-path misroute)
    ("POST", "/info", {}, {}, None),
    ("GET", "/auth_requested", {"X-Client-IP": "41.41.41.6"}, {}, None),
    ("GET", "/auth_request/sub", {"X-Client-IP": "41.41.41.7"}, {}, None),
    ("HEAD", "/decision_lists", {}, {}, None),
    ("HEAD", "/auth_request?path=/", {"X-Client-IP": "41.41.41.8"}, {}, None),
    ("GET", "/favicon.ico", {}, {}, None),
]

# headers whose values must match exactly between the two layouts
_HEADERS_COMPARED = (
    "Content-Type", "Cache-Control", "X-Accel-Redirect", "X-Banjax-Decision",
    "X-Deflect-Session-New",
)


def _start_layout(app_factory, tmp_path, fast: bool, tag: str):
    """Boot the app with http_fast_path toggled (shared by both
    differential tests so the bootstrap/settle protocol cannot drift)."""
    cfg = tmp_path / f"cfg-{tag}.yaml"
    cfg.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + f"\nhttp_fast_path: {'true' if fast else 'false'}\n"
    )
    app = app_factory(str(cfg))
    time.sleep(0.5)
    return app


def _capture(app_factory, tmp_path, fast: bool, tag: str):
    app = _start_layout(app_factory, tmp_path, fast, tag)
    rows = []
    for method, path, headers, cookies, data in CORPUS:
        headers = dict(headers, Host="localhost:8081")
        r = requests.request(
            method, f"{BASE}{path}", headers=headers, cookies=cookies,
            data=data, timeout=5, allow_redirects=False,
        )
        cookie_shapes = []
        for sc in r.raw.headers.getlist("Set-Cookie"):
            name = sc.split("=", 1)[0]
            attrs = sorted(
                a.strip().split("=", 1)[0].lower()
                for a in sc.split(";")[1:]
            )
            cookie_shapes.append((name, tuple(attrs)))
        rows.append({
            "req": (method, path),
            "status": r.status_code,
            "headers": {
                h: r.headers.get(h) for h in _HEADERS_COMPARED
            },
            "cookies": sorted(cookie_shapes),
            "body_len": len(r.content),
            "body": r.content if len(r.content) < 65536 else None,
        })
    app.stop_background()
    return rows


def test_fastserve_matches_aiohttp_wire_contract(app_factory, tmp_path):
    slow = _capture(app_factory, tmp_path, fast=False, tag="aio")
    fast = _capture(app_factory, tmp_path, fast=True, tag="fast")
    for s, f in zip(slow, fast):
        assert s["req"] == f["req"]
        ctx = s["req"]
        assert s["status"] == f["status"], (ctx, s["status"], f["status"])
        assert s["headers"] == f["headers"], (ctx, s["headers"], f["headers"])
        assert s["cookies"] == f["cookies"], (ctx, s["cookies"], f["cookies"])
        if ctx[1].startswith("/auth_request"):
            # bodies are config-deterministic (challenge/password pages,
            # empty bodies); dynamic-route bodies may embed timestamps
            assert s["body"] == f["body"], (ctx, s["body_len"], f["body_len"])


def _random_requests(seed: int, n: int):
    """Reproducible randomized request corpus: methods, hot/cold paths,
    header casing, query encodings, cookie values (valid + invalid
    escapes)."""
    import random as _random
    from urllib.parse import quote

    rng = _random.Random(seed)
    methods = ["GET", "GET", "GET", "POST", "HEAD"]
    paths = [
        "/auth_request", "/info", "/is_banned", "/banned",
        "/rate_limit_states", "/decision_lists", "/nope",
    ]
    query_paths = [
        "/", "wp-admin/x", "/wp-admin//", "wp-admin/admin-ajax.php",
        "a b", "/x?y=1&z=2", "ünïcode/päth", "%2e%2e/etc", "", "/" * 40,
    ]
    cookie_vals = [
        "garbage", "a%2Bb", "bad%zz", "", "x" * 120, "sp ace",
    ]
    out = []
    for i in range(n):
        method = rng.choice(methods)
        path = rng.choice(paths)
        target = path
        if path == "/auth_request":
            target += "?path=" + quote(rng.choice(query_paths), safe="")
        elif path == "/is_banned" and rng.random() < 0.8:
            target += f"?ip=10.9.{i % 250}.1"
        elif path == "/banned" and rng.random() < 0.8:
            target += "?domain=example.com"
        headers = {}
        ip_hdr = rng.choice(["X-Client-IP", "x-client-ip", "X-CLIENT-IP"])
        headers[ip_hdr] = f"10.8.{i % 250}.{rng.randint(1, 250)}"
        if rng.random() < 0.5:
            headers["X-Client-User-Agent"] = rng.choice(
                ["mozilla", "sqlmap/1.7", ""]
            )
        cookies = {}
        if rng.random() < 0.5:
            cookies[rng.choice(["deflect_password3", "deflect_session",
                                "other"])] = rng.choice(cookie_vals)
        out.append((method, target, headers, cookies))
    return out


def _drive_corpus(corpus):
    import http.client

    conn = http.client.HTTPConnection("localhost", 8081, timeout=5)
    rows = []
    for method, target, headers, cookies in corpus:
        hdrs = dict(headers, Host="localhost:8081")
        if cookies:
            hdrs["Cookie"] = "; ".join(f"{k}={v}" for k, v in cookies.items())
        conn.request(method, target, headers=hdrs)
        r = conn.getresponse()
        body = r.read()
        cookie_shapes = sorted(
            (v.split("=", 1)[0],
             tuple(sorted(a.strip().split("=", 1)[0].lower()
                          for a in v.split(";")[1:])))
            for k, v in r.getheaders() if k.lower() == "set-cookie"
        )
        rows.append({
            "req": (method, target),
            "status": r.status,
            "ct": r.getheader("Content-Type"),
            "decision": r.getheader("X-Banjax-Decision"),
            "accel": r.getheader("X-Accel-Redirect"),
            "cookies": cookie_shapes,
            "body_len": len(body),
        })
    conn.close()
    return rows


def test_fastserve_generative_differential(app_factory, tmp_path):
    """Randomized request fuzz: the two layouts must agree on status,
    content type, decision headers, and cookie shapes for every request
    in a reproducible 60-case random corpus."""
    corpus = _random_requests(seed=17, n=60)

    def run(fast, tag):
        app = _start_layout(app_factory, tmp_path, fast, f"g{tag}")
        rows = _drive_corpus(corpus)
        app.stop_background()
        return rows

    slow = run(False, "aio")
    fast = run(True, "fast")
    for s, f in zip(slow, fast):
        assert s == f, (s, f)


def test_fastserve_handles_fragmented_and_pipelined_requests(app_factory, tmp_path):
    """The hand parser must survive byte-dribbled heads and two requests
    arriving in one TCP segment."""
    import socket as sk

    cfg = tmp_path / "cfg-frag.yaml"
    cfg.write_text((_FIXTURES / "banjax-config-test.yaml").read_text())
    app_factory(str(cfg))
    time.sleep(0.5)

    # fragmented: send the request a few bytes at a time
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    payload = (b"GET /auth_request?path=/ HTTP/1.1\r\nHost: localhost:8081\r\n"
               b"X-Client-IP: 42.42.42.1\r\nConnection: keep-alive\r\n\r\n")
    for i in range(0, len(payload), 7):
        s.sendall(payload[i : i + 7])
        time.sleep(0.002)
    resp = s.recv(65536)
    assert resp.startswith(b"HTTP/1.1 200"), resp[:80]

    # pipelined: two requests in one segment on the same connection
    s.sendall(payload + payload)
    got = b""
    deadline = time.time() + 5
    while got.count(b"HTTP/1.1 200") < 2 and time.time() < deadline:
        got += s.recv(65536)
    assert got.count(b"HTTP/1.1 200") == 2, got[:200]
    s.close()


def test_fastserve_bad_requests(app_factory, tmp_path):
    import socket as sk

    cfg = tmp_path / "cfg-bad.yaml"
    cfg.write_text((_FIXTURES / "banjax-config-test.yaml").read_text())
    app_factory(str(cfg))
    time.sleep(0.5)

    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    s.sendall(b"NONSENSE\r\n\r\n")
    resp = s.recv(65536)
    assert b"400" in resp.split(b"\r\n", 1)[0], resp[:80]
    s.close()

    # chunked requests are rejected outright (501) rather than smuggling
    # their body bytes into the next parse
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    s.sendall(
        b"POST /auth_request HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"Transfer-Encoding: chunked\r\n\r\n"
        b"5\r\nhello\r\n0\r\n\r\n"
    )
    resp = s.recv(65536)
    assert resp.split(b"\r\n", 1)[0].endswith(b"501 Not Implemented"), resp[:80]
    s.close()

    # conflicting Content-Length values: 400 (RFC 7230), no last-wins
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    s.sendall(
        b"POST /auth_request HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"Content-Length: 0\r\nContent-Length: 5\r\n\r\nhello"
    )
    resp = s.recv(65536)
    assert b"400" in resp.split(b"\r\n", 1)[0], resp[:80]
    s.close()

    # oversized Content-Length: 413, connection closed, nothing re-parsed
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    s.sendall(
        b"POST /auth_request HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"Content-Length: 99999999999\r\n\r\n"
    )
    resp = s.recv(65536)
    assert b"413" in resp.split(b"\r\n", 1)[0], resp[:80]
    s.close()

    # HEAD on the hot route: headers only, Content-Length present, no body
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    s.sendall(
        b"HEAD /auth_request?path=/ HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"X-Client-IP: 42.42.42.10\r\n\r\n"
    )
    time.sleep(0.3)
    resp = s.recv(65536)
    head, _, tail = resp.partition(b"\r\n\r\n")
    assert b"content-length" in head.lower(), head
    assert tail == b"", f"HEAD response leaked {len(tail)} body bytes"
    s.close()

    # POST body present and consumed (route ignores it; must not desync
    # the connection)
    s = sk.create_connection(("127.0.0.1", 8081), timeout=5)
    body = b"a=1&b=2"
    s.sendall(
        b"POST /auth_request?path=/ HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"X-Client-IP: 42.42.42.9\r\nContent-Length: %d\r\n"
        b"Content-Type: application/x-www-form-urlencoded\r\n\r\n%b"
        % (len(body), body)
    )
    resp = s.recv(65536)
    assert resp.startswith(b"HTTP/1.1 200"), resp[:80]
    # connection still usable after the body
    s.sendall(
        b"GET /auth_request?path=/ HTTP/1.1\r\nHost: localhost:8081\r\n"
        b"X-Client-IP: 42.42.42.9\r\n\r\n"
    )
    resp = s.recv(65536)
    assert resp.startswith(b"HTTP/1.1 200"), resp[:80]
    s.close()
