"""Test harness config: force an 8-device virtual CPU mesh.

The driver tests multi-chip sharding without hardware by running JAX on the
host platform with 8 virtual devices; real-TPU benchmarking happens outside
pytest (bench.py).
"""

import os
import sys

# hard override: the session may export JAX_PLATFORMS=axon (the real TPU
# tunnel); tests must run on the 8-virtual-device CPU backend regardless —
# bench.py is what runs on the real chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the axon TPU plugin ignores the env var; the config knob wins
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import shutil  # noqa: E402
from pathlib import Path  # noqa: E402

import pytest  # noqa: E402

_FIXTURES = Path(__file__).resolve().parent / "fixtures"


@pytest.fixture()
def app_factory(tmp_path, monkeypatch):
    """Shared standalone-server bootstrap (banjax_base_test.go:32-81
    setUp): copy a fixture config into a temp cwd, run the real app there,
    tear it down after. Used by the integration tier AND the perf tier's
    HTTP benchmark mirrors — one copy, no drift."""
    from banjax_tpu.cli import BanjaxApp

    apps = []
    monkeypatch.chdir(tmp_path)

    def start(fixture_name: str) -> "BanjaxApp":
        config_path = tmp_path / "banjax-config.yaml"
        shutil.copy(_FIXTURES / fixture_name, config_path)
        app = BanjaxApp(str(config_path), standalone_testing=True, debug=False)
        app.start_background()
        apps.append(app)
        return app

    yield start
    for app in apps:
        app.stop_background()
