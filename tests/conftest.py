"""Test harness config: force an 8-device virtual CPU mesh.

The driver tests multi-chip sharding without hardware by running JAX on the
host platform with 8 virtual devices; real-TPU benchmarking happens outside
pytest (bench.py).
"""

import os
import sys

# hard override: the session may export JAX_PLATFORMS=axon (the real TPU
# tunnel); tests must run on the 8-virtual-device CPU backend regardless —
# bench.py is what runs on the real chip
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the axon TPU plugin ignores the env var; the config knob wins
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


import shutil  # noqa: E402
import threading  # noqa: E402
import time  # noqa: E402
from pathlib import Path  # noqa: E402

import pytest  # noqa: E402

_FIXTURES = Path(__file__).resolve().parent / "fixtures"


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 run (-m 'not slow')"
    )
    config.addinivalue_line(
        "markers", "allow_leaks: opt out of the leaked thread/process guard"
    )


def _live_child_pids():
    """PIDs of live (non-zombie) direct children, excluding the
    multiprocessing resource tracker (session-lived by design)."""
    if not os.path.isdir("/proc"):
        return set()
    me = os.getpid()
    out = set()
    for entry in os.listdir("/proc"):
        if not entry.isdigit():
            continue
        try:
            with open(f"/proc/{entry}/stat", "rb") as f:
                stat = f.read().decode("latin-1")
            # fields after the parenthesized comm: state is 1st, ppid 2nd
            rest = stat.rsplit(")", 1)[1].split()
            state, ppid = rest[0], int(rest[1])
            if ppid != me or state == "Z":
                continue
            with open(f"/proc/{entry}/cmdline", "rb") as f:
                cmdline = f.read()
            if b"resource_tracker" in cmdline:
                continue
            out.add(int(entry))
        except (OSError, IndexError, ValueError):
            continue  # raced a process exit
    return out


@pytest.fixture(autouse=True)
def _leak_guard(request):
    """Fail any test that leaves a non-daemon thread or a live child
    process behind — a leaked worker keeps ports/shm segments alive and
    poisons every later test in the session.  Teardown of the test's own
    fixtures (e.g. app_factory stopping the app) runs BEFORE this check.
    Mark a test `@pytest.mark.allow_leaks` to opt out."""
    if request.node.get_closest_marker("allow_leaks"):
        yield
        return
    threads_before = set(threading.enumerate())
    children_before = _live_child_pids()
    yield

    def leaked():
        lt = [
            t for t in threading.enumerate()
            if t not in threads_before and t.is_alive() and not t.daemon
        ]
        lc = _live_child_pids() - children_before
        return lt, lc

    # grace window: joins/waitpids triggered by fixture teardown may still
    # be settling when we first look
    deadline = time.monotonic() + 3.0
    lt, lc = leaked()
    while (lt or lc) and time.monotonic() < deadline:
        time.sleep(0.05)
        lt, lc = leaked()
    if lt or lc:
        pytest.fail(
            f"test leaked non-daemon threads {[t.name for t in lt]} "
            f"and/or live child processes {sorted(lc)}"
        )


@pytest.fixture(autouse=True)
def _challenge_stats_isolation():
    """The challenge-plane counters are a process singleton
    (banjax_tpu/challenge/stats.py); once active they add Challenge*
    keys to the metrics line and banjax_challenge_* families to
    /metrics.  Reset after every test so the reference-schema tests see
    a challenge-quiet process regardless of ordering."""
    yield
    try:
        from banjax_tpu.challenge.stats import get_stats

        get_stats().reset()
    except Exception:  # noqa: BLE001 — isolation must never fail a test
        pass


@pytest.fixture()
def app_factory(tmp_path, monkeypatch):
    """Shared standalone-server bootstrap (banjax_base_test.go:32-81
    setUp): copy a fixture config into a temp cwd, run the real app there,
    tear it down after. Used by the integration tier AND the perf tier's
    HTTP benchmark mirrors — one copy, no drift."""
    from banjax_tpu.cli import BanjaxApp

    apps = []
    monkeypatch.chdir(tmp_path)

    def start(fixture_name: str) -> "BanjaxApp":
        config_path = tmp_path / "banjax-config.yaml"
        shutil.copy(_FIXTURES / fixture_name, config_path)
        app = BanjaxApp(str(config_path), standalone_testing=True, debug=False)
        app.start_background()
        apps.append(app)
        return app

    yield start
    for app in apps:
        app.stop_background()
