"""Fail-open drills for the compiled serving path and the kernel edge.

`serve.fastpath.lookup` armed: every /auth_request rides the unchanged
decision chain, responses stay byte-identical (normalized), and each
suppressed consultation is a counted fault — never an error surfaced to
nginx.  `ipset.netlink.send` armed: every coalesced batch routes to the
per-entry subprocess fallback with zero bans lost, and the netlink path
resumes the moment the failpoint is disarmed.
"""

import re
import socket
import time
from pathlib import Path

import pytest

from banjax_tpu.decisions.model import Decision
from banjax_tpu.effectors import ipset_netlink as nl
from banjax_tpu.effectors.ipset_stats import get_stats as ipset_stats
from banjax_tpu.httpapi.serve_stats import get_stats as serve_stats
from banjax_tpu.resilience import failpoints

_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"
HOST = "eligible.example.net"


@pytest.fixture(autouse=True)
def _clean():
    failpoints.disarm()
    serve_stats().reset()
    ipset_stats().reset()
    yield
    failpoints.disarm()
    serve_stats().reset()
    ipset_stats().reset()


_MASK = re.compile(rb"(X-Deflect-Session: |deflect_session=)([^;\r\n]+)")


def _get(ip):
    s = socket.create_connection(("127.0.0.1", 8081), timeout=5)
    try:
        s.sendall(
            (f"GET /auth_request?path=/ HTTP/1.1\r\nHost: {HOST}\r\n"
             f"X-Client-IP: {ip}\r\nConnection: close\r\n\r\n").encode()
        )
        out = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            out += chunk
    finally:
        s.close()
    return _MASK.sub(rb"\1MASKED", out)


def test_armed_fastpath_lookup_fails_open_byte_identical(
    app_factory, tmp_path
):
    cfg = tmp_path / "cfg-fp-fault.yaml"
    cfg.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_fast_path: true\nserve_fastpath_enabled: true\n"
    )
    app = app_factory(str(cfg))
    time.sleep(0.5)
    app.dynamic_lists.update(
        "44.0.0.1", time.time() + 600, Decision.ALLOW, False, "d"
    )
    stats = serve_stats()

    baseline = _get("44.0.0.1")  # fast-path hit
    assert baseline.startswith(b"HTTP/1.1 200")
    assert stats.prom_snapshot()["hits"]["allow"] == 1

    failpoints.arm("serve.fastpath.lookup", count=3)
    for i in range(3):
        assert _get("44.0.0.1") == baseline, f"armed request {i} diverged"
    snap = stats.prom_snapshot()
    assert snap["faults_total"] == 3
    assert snap["hits_total"] == 1  # no hit while armed
    assert failpoints.fired_count("serve.fastpath.lookup") == 3

    # the bounded arming is exhausted: the fast path serves again
    assert _get("44.0.0.1") == baseline
    assert stats.prom_snapshot()["hits"]["allow"] == 2
    app.stop_background()


class _FakeSock:
    def __init__(self):
        self.sent = []

    def send(self, buf):
        self.sent.append(buf)

    def recv(self, _n):
        import struct

        n = self.sent[-1].count(
            struct.pack("=HH", (nl.NFNL_SUBSYS_IPSET << 8) | nl.IPSET_CMD_ADD,
                        nl.NLM_F_REQUEST | nl.NLM_F_ACK)
        )
        return b"".join(
            struct.pack("=IHHII", 20, nl.NLMSG_ERROR, 0, i + 1, 0)
            + struct.pack("=i", 0)
            for i in range(n)
        )

    def close(self):
        pass


class _FakeIpset:
    name = "banjax"

    def __init__(self):
        self.added = []

    def add(self, ip, timeout):
        self.added.append((ip, timeout))


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while not pred() and time.time() < deadline:
        time.sleep(0.01)
    assert pred(), "condition not reached"


def test_armed_netlink_send_falls_back_lossless():
    ipset = _FakeIpset()
    sock = _FakeSock()
    w = nl.IpsetBatchWriter(ipset, flush_interval=0.01)
    w._socket = lambda: sock
    try:
        failpoints.arm("ipset.netlink.send", count=None)
        for i in range(6):
            w.enqueue(f"10.5.0.{i}", 300)
        # every ban landed through the subprocess shim, none lost
        _wait(lambda: len(ipset.added) == 6)
        assert sorted(ipset.added) == sorted(
            (f"10.5.0.{i}", 300) for i in range(6)
        )
        assert sock.sent == []  # netlink never completed a send
        snap = ipset_stats().prom_snapshot()
        assert snap["errors"].get("netlink", 0) >= 1
        assert snap["fallback_total"] == 6
        assert failpoints.fired_count("ipset.netlink.send") >= 1

        # disarm: netlink resumes (new writer so the breaker state from
        # the drill cannot route around it)
        failpoints.disarm("ipset.netlink.send")
    finally:
        w.close()

    w2 = nl.IpsetBatchWriter(ipset, flush_interval=0.01)
    w2._socket = lambda: sock
    try:
        before = ipset_stats().prom_snapshot()["batch_entries_total"]
        w2.enqueue("10.5.1.1", 300)
        _wait(lambda: ipset_stats().prom_snapshot()["batch_entries_total"]
              == before + 1)
        assert len(sock.sent) >= 1
    finally:
        w2.close()
