"""Challenge-plane failpoints, each at its real site with its real
contract (resilience/failpoints.py KNOWN_SITES):

  * challenge.issue / challenge.verify sit on the HTTP request path and
    FAIL OPEN — a fault propagates out of decision_for_nginx and becomes
    the reference's 500 + X-Accel-Redirect: @fail_open recovery, on both
    HTTP layouts, and the app serves normally once disarmed;
  * challenge.device_verify is SWALLOWED — the verifier falls back to
    the CPU oracle, the breaker opens after the threshold, decisions
    never change, and the device path recovers through the half-open
    probe after the cooldown.
"""

import time
from pathlib import Path

import pytest
import requests

from banjax_tpu.challenge.verifier import DeviceVerifier, verify_sha_inv
from banjax_tpu.crypto.challenge import (
    new_challenge_cookie_at,
    solve_challenge_for_testing,
)
from banjax_tpu.resilience import failpoints

BASE = "http://localhost:8081"
_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"

SECRET = "fault-secret"
ZERO_BITS = 8


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def _start(app_factory, tmp_path, fast_path: bool) -> None:
    custom = tmp_path / "banjax-config-challenge-faults.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + f"\nhttp_fast_path: {str(fast_path).lower()}\ndisable_kafka: true\n"
    )
    app_factory(str(custom))


def _challenge_request(cookies=None):
    # 8.8.8.8 is challenge-listed in the fixture's global lists, so this
    # request rides the sha_inv issuance/verification path
    return requests.get(
        f"{BASE}/auth_request", params={"path": "/x"},
        headers={"X-Client-IP": "8.8.8.8"}, cookies=cookies or {},
        timeout=5,
    )


@pytest.mark.parametrize("fast_path", [True, False], ids=["fastserve", "aiohttp"])
def test_issue_fault_fails_open_then_recovers(app_factory, tmp_path, fast_path):
    _start(app_factory, tmp_path, fast_path)

    failpoints.arm("challenge.issue")
    r = _challenge_request()
    assert r.status_code == 500
    assert r.headers.get("X-Accel-Redirect") == "@fail_open"
    assert "challenge.issue" in r.headers.get("X-Banjax-Error", "")

    failpoints.disarm("challenge.issue")
    r = _challenge_request()
    assert r.status_code == 429  # the challenge page, cookie attached
    assert "deflect_challenge3" in r.cookies


@pytest.mark.parametrize("fast_path", [True, False], ids=["fastserve", "aiohttp"])
def test_verify_fault_fails_open_then_recovers(app_factory, tmp_path, fast_path):
    _start(app_factory, tmp_path, fast_path)

    # the verify failpoint sits ahead of cookie parsing: any presented
    # cookie reaches it
    failpoints.arm("challenge.verify")
    r = _challenge_request(cookies={"deflect_challenge3": "whatever"})
    assert r.status_code == 500
    assert r.headers.get("X-Accel-Redirect") == "@fail_open"
    assert "challenge.verify" in r.headers.get("X-Banjax-Error", "")

    failpoints.disarm("challenge.verify")
    r = _challenge_request(cookies={"deflect_challenge3": "whatever"})
    assert r.status_code == 429  # a bad cookie is a fresh challenge, not a 500


def test_device_verify_fault_is_swallowed_and_breaker_recovers():
    """The device failpoint never reaches a caller: every verification
    during the outage answers from the CPU oracle, the breaker opens at
    the threshold, and one half-open probe restores the device path
    after the cooldown."""
    device = DeviceVerifier(
        batch_max=4, interpret=True, breaker_threshold=3,
        breaker_cooldown_s=0.2,
    )
    cookie = solve_challenge_for_testing(
        new_challenge_cookie_at(SECRET, int(time.time()) + 300, "5.5.5.5"),
        ZERO_BITS,
    )

    failpoints.arm("challenge.device_verify", mode="error")
    try:
        for _ in range(6):
            # accepts keep flowing throughout the injected outage
            verify_sha_inv(SECRET, cookie, time.time(), "5.5.5.5",
                           ZERO_BITS, device=device)
    finally:
        failpoints.disarm("challenge.device_verify")

    counters = device.counters()
    assert counters["faults"] >= 3
    assert counters["breaker_trips"] >= 1
    assert not device.available()

    # past the cooldown the half-open probe runs on the device again
    time.sleep(0.25)
    assert device.available()
    verify_sha_inv(SECRET, cookie, time.time(), "5.5.5.5",
                   ZERO_BITS, device=device)
    assert device.counters()["dispatches"] >= 1
