"""Log-rotation robustness: the tailer's no-drop / no-dup contract.

The pre-fix gap: rotation detection (idle stat, inode change) closed
the old file handle immediately — bytes appended between the tailer's
last read and the rotation, and any buffered partial line, died with
the handle.  The fix drains the old inode to EOF before closing and
flushes the never-terminated trailing line (the old file is final).

Driven by the log_rotation scenario shape plus targeted unit cases.
"""

import os
import threading
import time

import pytest

from banjax_tpu.ingest.tailer import LogTailer
from banjax_tpu.resilience import failpoints
from banjax_tpu.scenarios import generate
from banjax_tpu.scenarios.shapes import LineChunk, Rotation


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


class _Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self.lines = []

    def __call__(self, batch):
        with self._lock:
            self.lines.extend(batch)

    def snapshot(self):
        with self._lock:
            return list(self.lines)

    def wait_for(self, n, timeout=30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if len(self.snapshot()) >= n:
                return True
            time.sleep(0.02)
        return False


def _start_tailer(tmp_path, sink):
    path = str(tmp_path / "access.log")
    open(path, "w").close()
    tailer = LogTailer(path, sink)
    tailer.start()
    assert tailer.opened.wait(10)
    return path, tailer


def _wait_opened_again(tailer, timeout=10.0):
    assert tailer.opened.wait(timeout)


def test_rotation_drains_bytes_written_after_last_read(tmp_path):
    """Bytes appended to the OLD file immediately before the rename —
    the exact race the drain fix closes — must still be delivered."""
    sink = _Sink()
    path, tailer = _start_tailer(tmp_path, sink)
    try:
        with open(path, "a") as f:
            f.write("alpha\nbravo\n")
        assert sink.wait_for(2)
        # append + rotate back-to-back: the tailer has NOT read these yet
        with open(path, "a") as f:
            f.write("charlie\ndelta\n")
        os.replace(path, path + ".1")
        with open(path, "a") as f:
            f.write("echo\n")
        assert sink.wait_for(5), sink.snapshot()
        assert sink.snapshot() == [
            "alpha", "bravo", "charlie", "delta", "echo"
        ]
    finally:
        tailer.stop()


def test_rotation_flushes_the_unterminated_trailing_line(tmp_path):
    """A final line the writer never newline-terminated is still a line
    once the file is rotated away (the old inode is final) — the
    deterministic witness for the partial-buffer half of the fix."""
    sink = _Sink()
    path, tailer = _start_tailer(tmp_path, sink)
    try:
        with open(path, "a") as f:
            f.write("first\nsecond-no-newline")
        os.replace(path, path + ".1")
        with open(path, "a") as f:
            f.write("third\n")
        assert sink.wait_for(3), sink.snapshot()
        assert sink.snapshot() == ["first", "second-no-newline", "third"]
    finally:
        tailer.stop()


def test_truncation_still_reopens_from_start(tmp_path):
    sink = _Sink()
    path, tailer = _start_tailer(tmp_path, sink)
    try:
        with open(path, "a") as f:
            f.write("one\ntwo\n")
        assert sink.wait_for(2)
        tailer.opened.clear()
        with open(path, "w") as f:  # truncate in place (copytruncate)
            f.write("three\n")
        assert sink.wait_for(3), sink.snapshot()
        assert sink.snapshot() == ["one", "two", "three"]
    finally:
        tailer.stop()


def test_rotation_scenario_stream_no_drop_no_dup(tmp_path):
    """The log_rotation shape end-to-end against a bare tailer: every
    generated line delivered exactly once, in order, across three
    mid-burst rotations (with the chunk before each rotation left
    newline-unterminated)."""
    sc = generate("log_rotation", seed=31, scale=0.5)
    sink = _Sink()
    path, tailer = _start_tailer(tmp_path, sink)
    expected = sc.lines()
    try:
        rot = 0
        events = sc.events
        for i, ev in enumerate(events):
            if isinstance(ev, LineChunk):
                nxt = events[i + 1] if i + 1 < len(events) else None
                text = "\n".join(ev.lines)
                if not isinstance(nxt, Rotation):
                    text += "\n"
                with open(path, "a") as f:
                    f.write(text)
            elif isinstance(ev, Rotation):
                # wait until the tailer holds this generation (a double
                # rotation inside one poll tick would orphan a file even
                # for a correct follower)
                _wait_opened_again(tailer)
                tailer.opened.clear()
                rot += 1
                os.replace(path, f"{path}.{rot}")
                open(path, "a").close()
        assert rot >= 2
        assert sink.wait_for(len(expected)), (
            f"delivered {len(sink.snapshot())} of {len(expected)}"
        )
        assert sink.snapshot() == expected  # exactly once, in order
    finally:
        tailer.stop()


def test_rotation_reopen_failure_retries_without_loss(tmp_path):
    """tailer.open armed for the rotation reopen: the retry loop
    recovers and the new generation's lines all arrive."""
    sink = _Sink()
    path, tailer = _start_tailer(tmp_path, sink)
    try:
        with open(path, "a") as f:
            f.write("pre\n")
        assert sink.wait_for(1)
        failpoints.arm("tailer.open", count=2)
        tailer.opened.clear()
        os.replace(path, path + ".1")
        with open(path, "a") as f:
            f.write("post-a\npost-b\n")
        assert sink.wait_for(3, timeout=45), sink.snapshot()
        assert sink.snapshot() == ["pre", "post-a", "post-b"]
        assert failpoints.fired_count("tailer.open") == 2
    finally:
        tailer.stop()
