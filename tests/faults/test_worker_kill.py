"""Worker-crash healing: SIGKILL one SO_REUSEPORT worker via the
supervisor's fault hook; the monitor must respawn it, requests must keep
being served throughout, and the supervisor health component must recover."""

import time
from pathlib import Path

import pytest
import requests

from banjax_tpu.native import shm
from banjax_tpu.resilience.health import HealthStatus

pytestmark = pytest.mark.skipif(
    not shm.available(), reason="no C compiler for native shmstate"
)

BASE = "http://localhost:8081"
_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"


def test_kill_worker_respawns_and_health_recovers(app_factory, tmp_path):
    custom = tmp_path / "banjax-config-kill.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + "\nhttp_workers: 2\ndisable_kafka: true\n"
    )
    app = app_factory(str(custom))
    sup = app._supervisor
    deadline = time.time() + 20
    while time.time() < deadline:
        if all(p.poll() is None for p in sup._procs):
            try:
                requests.get(f"{BASE}/info", timeout=2)
                break
            except requests.RequestException:
                pass
        time.sleep(0.2)
    assert all(p.poll() is None for p in sup._procs), "workers never started"

    sup.kill_worker(0)  # SIGKILL: the OOM-kill shape

    # requests keep flowing while one worker is down (the primary and the
    # surviving worker still hold the SO_REUSEPORT socket)
    for _ in range(5):
        r = requests.get(
            f"{BASE}/auth_request", params={"path": "/x"},
            headers={"X-Client-IP": "4.4.4.4"}, timeout=5,
        )
        assert r.status_code == 200

    # the monitor (1 s interval + 1 s respawn backoff) heals the slot
    deadline = time.time() + 15
    while time.time() < deadline:
        if sup.respawn_count >= 1 and all(p.poll() is None for p in sup._procs):
            break
        time.sleep(0.2)
    assert sup.respawn_count >= 1
    assert all(p.poll() is None for p in sup._procs), "worker not respawned"

    # supervisor health returns to HEALTHY once all workers are back
    deadline = time.time() + 10
    while time.time() < deadline:
        status, _, _ = app.health.get("worker-supervisor").effective_status()
        if status == HealthStatus.HEALTHY:
            break
        time.sleep(0.2)
    assert status == HealthStatus.HEALTHY
