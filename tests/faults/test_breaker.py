"""Circuit breaker state machine: trip, open rejection, half-open probe,
recovery, re-trip — all on an injected clock."""

import pytest

from banjax_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make(threshold=3, recovery=30.0):
    clk = Clock()
    return CircuitBreaker(failure_threshold=threshold,
                          recovery_seconds=recovery, clock=clk), clk


def test_trips_after_consecutive_failures_only():
    br, _ = make(threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()  # success resets the consecutive count
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED
    br.record_failure()
    assert br.state == OPEN
    assert br.trip_count == 1


def test_open_rejects_until_recovery_then_half_open_single_probe():
    br, clk = make(threshold=1, recovery=10.0)
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow()
    clk.t = 9.9
    assert not br.allow()
    clk.t = 10.0
    assert br.allow()  # the half-open probe
    assert br.state == HALF_OPEN
    assert not br.allow()  # only ONE probe at a time
    br.record_success()
    assert br.state == CLOSED
    assert br.allow()


def test_half_open_failure_reopens_with_fresh_recovery_window():
    br, clk = make(threshold=1, recovery=10.0)
    br.record_failure()
    clk.t = 10.0
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == OPEN
    assert br.trip_count == 2
    clk.t = 19.9  # recovery restarts from the re-trip
    assert not br.allow()
    clk.t = 20.0
    assert br.allow()


def test_on_trip_callback_and_validation():
    trips = []
    br = CircuitBreaker(failure_threshold=1, recovery_seconds=1.0,
                        name="x", on_trip=trips.append)
    br.record_failure()
    assert trips == ["x"]
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)


# --------------------------------------------------- rolling window (PR 2)


def test_window_trips_on_interleaved_failures():
    """threshold 3 in a 6-outcome window: F S F S F trips even though the
    consecutive counter keeps resetting — the flapping-device mode."""
    clk = Clock()
    br = CircuitBreaker(failure_threshold=3, recovery_seconds=10.0,
                        clock=clk, window_size=6)
    br.record_failure()
    br.record_success()
    br.record_failure()
    br.record_success()
    assert br.state == CLOSED
    br.record_failure()
    assert br.state == OPEN
    assert br.trip_count == 1


def test_without_window_interleaved_failures_never_trip():
    br, _ = make(threshold=3)
    for _ in range(10):
        br.record_failure()
        br.record_success()
    assert br.state == CLOSED


def test_old_failures_age_out_of_the_window():
    """A window of 4: a failure pushed out by successes no longer counts,
    but two failures landing within the window (non-consecutively) trip."""
    clk = Clock()
    br = CircuitBreaker(failure_threshold=2, recovery_seconds=10.0,
                        clock=clk, window_size=4)
    br.record_failure()
    for _ in range(4):
        br.record_success()  # the failure is now outside the window
    br.record_failure()  # window [S,S,S,F]: 1 failure → no trip
    assert br.state == CLOSED
    br.record_success()
    br.record_failure()  # window [S,F,S,F]: 2 within 4, non-consecutive
    assert br.state == OPEN


def test_window_clears_on_trip_so_recovery_starts_clean():
    clk = Clock()
    br = CircuitBreaker(failure_threshold=2, recovery_seconds=10.0,
                        clock=clk, window_size=4)
    br.record_failure()
    br.record_failure()
    assert br.state == OPEN
    clk.t = 10.0
    assert br.allow()  # half-open probe
    br.record_success()
    assert br.state == CLOSED
    # one failure after recovery: the pre-trip history must not count
    br.record_failure()
    assert br.state == CLOSED


def test_window_size_validation():
    with pytest.raises(ValueError):
        CircuitBreaker(window_size=-1)
    from banjax_tpu.config.schema import config_from_yaml_text

    with pytest.raises(ValueError):
        config_from_yaml_text(
            "breaker_failure_threshold: 3\nbreaker_window_size: 2\n"
        )
    cfg = config_from_yaml_text(
        "breaker_failure_threshold: 3\nbreaker_window_size: 8\n"
    )
    assert cfg.breaker_window_size == 8


def test_open_seconds_total_accumulates_across_cycles():
    """obs/slo.py's breaker-open burn source: OPEN time accumulates
    monotonically across trip→recover cycles, including the in-progress
    stretch, and HALF_OPEN/CLOSED time never counts."""
    from banjax_tpu.resilience.breaker import CircuitBreaker

    t = {"now": 0.0}
    br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                        clock=lambda: t["now"])
    assert br.open_seconds_total() == 0.0
    br.record_failure()  # trips OPEN at t=0
    t["now"] = 4.0
    assert br.open_seconds_total() == 4.0  # in-progress stretch counts
    t["now"] = 10.0
    assert br.allow()  # OPEN → HALF_OPEN probe; 10 s banked
    assert br.open_seconds_total() == 10.0
    t["now"] = 12.0
    assert br.open_seconds_total() == 10.0  # HALF_OPEN time is not open
    br.record_failure()  # probe fails: re-OPEN at t=12
    t["now"] = 15.0
    assert br.open_seconds_total() == 13.0  # 10 banked + 3 in progress
    t["now"] = 22.0
    assert br.allow()
    br.record_success()  # probe succeeds → CLOSED; 10+10 banked
    assert br.open_seconds_total() == 20.0
    t["now"] = 100.0
    assert br.open_seconds_total() == 20.0  # closed time never counts
