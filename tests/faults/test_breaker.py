"""Circuit breaker state machine: trip, open rejection, half-open probe,
recovery, re-trip — all on an injected clock."""

import pytest

from banjax_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN, CircuitBreaker


class Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def make(threshold=3, recovery=30.0):
    clk = Clock()
    return CircuitBreaker(failure_threshold=threshold,
                          recovery_seconds=recovery, clock=clk), clk


def test_trips_after_consecutive_failures_only():
    br, _ = make(threshold=3)
    br.record_failure()
    br.record_failure()
    br.record_success()  # success resets the consecutive count
    br.record_failure()
    br.record_failure()
    assert br.state == CLOSED
    br.record_failure()
    assert br.state == OPEN
    assert br.trip_count == 1


def test_open_rejects_until_recovery_then_half_open_single_probe():
    br, clk = make(threshold=1, recovery=10.0)
    br.record_failure()
    assert br.state == OPEN
    assert not br.allow()
    clk.t = 9.9
    assert not br.allow()
    clk.t = 10.0
    assert br.allow()  # the half-open probe
    assert br.state == HALF_OPEN
    assert not br.allow()  # only ONE probe at a time
    br.record_success()
    assert br.state == CLOSED
    assert br.allow()


def test_half_open_failure_reopens_with_fresh_recovery_window():
    br, clk = make(threshold=1, recovery=10.0)
    br.record_failure()
    clk.t = 10.0
    assert br.allow()
    br.record_failure()  # probe failed
    assert br.state == OPEN
    assert br.trip_count == 2
    clk.t = 19.9  # recovery restarts from the re-trip
    assert not br.allow()
    clk.t = 20.0
    assert br.allow()


def test_on_trip_callback_and_validation():
    trips = []
    br = CircuitBreaker(failure_threshold=1, recovery_seconds=1.0,
                        name="x", on_trip=trips.append)
    br.record_failure()
    assert trips == ["x"]
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)
