"""Pipeline stage-boundary faults: the tentpole's no-silent-loss
contract under injected failures.

Every admitted line must be either processed (a result exists for it)
or counted as shed — across encode failures, device submit/collect
failures (which also drive the breaker → CPU-reference drain), drain
failures, and sustained overload.
"""

import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.breaker import OPEN
from banjax_tpu.resilience.health import HealthRegistry
from tests.mock_banner import MockBanner

RULES_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: r1
    regex: 'GET /attack.*'
    interval: 5
    hits_per_interval: 0
"""


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


class _Sink:
    def __init__(self):
        self._lock = threading.Lock()
        self.lines = []
        self.results = []

    def __call__(self, lines, results):
        with self._lock:
            self.lines.extend(lines)
            if results is not None:
                self.results.extend(results)


def build(threshold=3, health=None, device_windows=False):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.breaker_failure_threshold = threshold
    cfg.matcher_device_windows = device_windows
    states = RegexRateLimitStates()
    banner = MockBanner()
    m = TpuMatcher(
        cfg, banner, StaticDecisionLists(cfg), states, health=health
    )
    return m, banner


def run_stream(m, n_chunks=12, chunk=25, **sched_kw):
    now = time.time()
    sink = _Sink()
    sched = PipelineScheduler(
        lambda: m, on_results=sink, now_fn=lambda: now, **sched_kw
    )
    sched.start()
    lines = []
    for c in range(n_chunks):
        batch = [
            f"{now:.6f} 9.9.{c}.{i} GET h.com GET /attack HTTP/1.1 ua -"
            for i in range(chunk)
        ]
        lines.extend(batch)
        sched.submit(batch)
    assert sched.flush(120)
    sched.stop()
    return lines, sink, sched


def assert_accounted(sched, sink, lines):
    """The invariant: admitted == processed + shed(+drain errors), and a
    result object exists for every processed line."""
    s = sched.stats
    assert s.admitted_lines == len(lines)
    assert s.admitted_lines == (
        s.processed_lines + s.shed_lines + s.drain_error_lines
    )
    assert len(sink.results) == s.processed_lines


def test_collect_failpoint_loses_nothing(caplog):
    """The acceptance fault: a failpoint in the collect stage — every
    admitted line is still processed (the failed batch re-runs through
    consume_lines on the drain thread; its device dispatch succeeds there,
    so the device is NOT wedged and the breaker rightly stays closed —
    the wedged-device trip is the matcher.device test below)."""
    m, banner = build()
    failpoints.arm("pipeline.collect")
    lines, sink, sched = run_stream(m)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)  # zero lost
    assert sched.stats.fallback_batches >= 1
    # with hits_per_interval 0 every attack line bans: effects all fired
    assert len(banner.regex_ban_logs) == len(lines)


def test_submit_failpoint_falls_back_without_loss():
    m, banner = build(threshold=2)
    failpoints.arm("pipeline.submit")
    lines, sink, sched = run_stream(m)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)
    assert sched.stats.fallback_batches >= 1
    assert len(banner.regex_ban_logs) == len(lines)


def test_encode_failpoint_drains_generically():
    m, banner = build()
    failpoints.arm("pipeline.encode", count=3)
    lines, sink, sched = run_stream(m)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)
    assert sched.stats.fallback_batches >= 1
    # encode failures are host-side: they must NOT charge the breaker
    assert m.breaker.trip_count == 0
    assert len(banner.regex_ban_logs) == len(lines)


def test_drain_failpoint_counts_lines_as_shed():
    m, _ = build()
    failpoints.arm("pipeline.drain", count=1)
    lines, sink, sched = run_stream(m)
    assert_accounted(sched, sink, lines)
    assert sched.stats.drain_error_lines > 0
    assert sched.stats.processed_lines == (
        len(lines) - sched.stats.drain_error_lines
    )


def test_matcher_device_failpoint_open_breaker_drains_ring_via_cpu():
    """A wedged device (matcher.device armed unlimited): the breaker
    opens mid-stream and the remaining ring drains through the CPU
    reference matcher — results keep coming, nothing is lost."""
    health = HealthRegistry()
    m, banner = build(threshold=2, health=health)
    failpoints.arm("matcher.device")
    lines, sink, sched = run_stream(m, n_chunks=16)
    assert_accounted(sched, sink, lines)
    assert sched.stats.processed_lines == len(lines)
    assert m.breaker.state == OPEN
    assert m.fallback_batches >= 1  # consume_lines routed to the CPU ref
    assert len(banner.regex_ban_logs) == len(lines)
    assert health.snapshot()["components"]["matcher"]["status"] != "healthy"


def test_overload_shed_plus_collect_fault_still_accounts():
    """Compound failure: sustained overload (tiny buffer, no block) while
    the collect stage is failing — shed and processed still sum to
    admitted."""
    m, _ = build()
    failpoints.arm("pipeline.collect")
    lines, sink, sched = run_stream(
        m, n_chunks=30, chunk=20,
        ring_size=1, buffer_lines=40, max_block_ms=0.0,
        min_batch=64, max_batch=64,
    )
    assert_accounted(sched, sink, lines)
    assert sched.stats.shed_lines > 0


class TestFusedTwoPhaseFaults:
    """The same no-silent-loss contract with the fused matcher+windows
    two-phase path active (device windows on → program A at submit, the
    window commit at drain).  The extra hazard class here is LEAKED ORDER
    TURNS: a chunk whose apply never runs must free its resolve/collect
    turns and slot pins, or every later fused drain deadlocks — which
    these streams would surface as a flush() timeout."""

    def test_fused_stream_accounts_and_engages(self):
        m, banner = build(device_windows=True)
        lines, sink, sched = run_stream(m)
        assert_accounted(sched, sink, lines)
        assert sched.stats.processed_lines == len(lines)
        # the two-phase path ran (commit or counted overflow fallback)
        assert m.pipelined_fused_chunks + m.pipelined_fused_fallbacks > 0
        assert len(banner.regex_ban_logs) == len(lines)

    def test_device_failpoint_under_fused_path_loses_nothing(self):
        """matcher.device armed: fused submits fail → entries abandoned →
        batches drain generically via the CPU reference.  No deadlock, no
        loss, breaker opens."""
        m, banner = build(threshold=2, device_windows=True)
        failpoints.arm("matcher.device")
        lines, sink, sched = run_stream(m, n_chunks=16)
        assert_accounted(sched, sink, lines)
        assert sched.stats.processed_lines == len(lines)
        assert m.breaker.state == OPEN
        assert len(banner.regex_ban_logs) == len(lines)

    def test_failed_then_recovered_device_does_not_wedge_fused_drains(self):
        """Phase A streams with the device failing (fused submits abandon
        their entries, batches drain generically); phase B disarms and
        streams again THROUGH THE SAME matcher — the fused path must
        engage and drain (a leaked order turn from phase A would hang
        phase B's flush)."""
        m, banner = build(threshold=100, device_windows=True)
        now = time.time()
        sink = _Sink()
        sched = PipelineScheduler(
            lambda: m, on_results=sink, now_fn=lambda: now
        )
        sched.start()
        lines = []
        failpoints.arm("matcher.device", count=8)
        for c in range(8):
            batch = [
                f"{now:.6f} 9.9.{c}.{i} GET h.com GET /attack HTTP/1.1 ua -"
                for i in range(25)
            ]
            lines.extend(batch)
            sched.submit(batch)
            assert sched.flush(60)  # one batch per chunk, failpoint per batch
        failpoints.disarm()
        for c in range(8, 14):
            batch = [
                f"{now:.6f} 9.9.{c}.{i} GET h.com GET /attack HTTP/1.1 ua -"
                for i in range(25)
            ]
            lines.extend(batch)
            sched.submit(batch)
        assert sched.flush(60), "phase B hung — leaked fused order turn"
        sched.stop()
        assert_accounted(sched, sink, lines)
        assert sched.stats.processed_lines == len(lines)
        assert m.pipelined_fused_chunks + m.pipelined_fused_fallbacks > 0
        assert len(banner.regex_ban_logs) == len(lines)

    def test_drain_failpoint_under_fused_path_frees_turns(self):
        """pipeline.drain fires before pipeline_finish: the batch's
        two-phase chunks are settled by pipeline_abort — the stream after
        the failed batch still drains (no leaked turn deadlock)."""
        m, _ = build(device_windows=True)
        failpoints.arm("pipeline.drain", count=2)
        lines, sink, sched = run_stream(m, n_chunks=14)
        assert_accounted(sched, sink, lines)
        assert sched.stats.drain_error_lines > 0
        assert sched.stats.processed_lines == (
            len(lines) - sched.stats.drain_error_lines
        )

    def test_collect_failpoint_under_fused_path(self):
        m, banner = build(device_windows=True)
        failpoints.arm("pipeline.collect", count=3)
        lines, sink, sched = run_stream(m)
        assert_accounted(sched, sink, lines)
        assert sched.stats.processed_lines == len(lines)
        assert len(banner.regex_ban_logs) == len(lines)


class TestCommandRouting:
    """Kafka command messages through the admission buffer: the
    admitted == processed + shed invariant spans both producers."""

    def test_commands_share_accounting_with_lines(self):
        m, _ = build()
        now = time.time()
        sink = _Sink()
        handled = []
        sched = PipelineScheduler(
            lambda: m, on_results=sink, now_fn=lambda: now
        )
        sched.start()
        total = 0
        for c in range(8):
            batch = [
                f"{now:.6f} 9.9.{c}.{i} GET h.com GET /attack HTTP/1.1 ua -"
                for i in range(10)
            ]
            sched.submit(batch)
            sched.submit_commands(
                [f"cmd-{c}-{k}".encode() for k in range(3)], handled.append
            )
            total += 13
        assert sched.flush(60)
        sched.stop()
        s = sched.stats
        assert s.admitted_lines == total
        assert s.admitted_lines == (
            s.processed_lines + s.shed_lines + s.drain_error_lines
        )
        assert s.command_items == 24
        assert handled == [
            f"cmd-{c}-{k}".encode() for c in range(8) for k in range(3)
        ], "commands executed out of admission order"
        # on_results only sees log lines, never command items
        assert len(sink.lines) == total - 24

    def test_command_overload_sheds_and_counts(self):
        m, _ = build()
        handled = []
        sched = PipelineScheduler(
            lambda: m, ring_size=1, buffer_lines=16, max_block_ms=0.0,
            min_batch=64, max_batch=64,
        )
        sched.start()
        for c in range(40):
            sched.submit_commands(
                [f"c{c}-{k}".encode() for k in range(4)], handled.append
            )
        assert sched.flush(60)
        sched.stop()
        s = sched.stats
        assert s.admitted_lines == 160
        assert s.shed_lines > 0
        assert s.admitted_lines == (
            s.processed_lines + s.shed_lines + s.drain_error_lines
        )
        assert len(handled) == s.processed_lines

    def test_bad_command_loses_itself_not_the_batch(self):
        m, _ = build()
        good = []

        def handler(raw):
            if raw == b"boom":
                raise ValueError("bad command")
            good.append(raw)

        sched = PipelineScheduler(lambda: m)
        sched.start()
        sched.submit_commands([b"a", b"boom", b"b"], handler)
        assert sched.flush(30)
        sched.stop()
        assert good == [b"a", b"b"]
        assert sched.stats.processed_lines == 3  # boom counted, logged


def test_pipeline_registers_health_and_degrades_on_shed():
    health = HealthRegistry()
    m, _ = build()
    comp = health.register("pipeline")
    now = time.time()
    sched = PipelineScheduler(
        lambda: m, buffer_lines=16, max_block_ms=0.0, health=comp,
        now_fn=lambda: now,
    )
    sched.start()
    sched.submit(
        [f"{now:.6f} 1.1.1.{i} GET h.com GET /x HTTP/1.1 ua -"
         for i in range(64)]
    )
    snap = health.snapshot()
    assert snap["components"]["pipeline"]["status"] == "degraded"
    assert sched.flush(30)
    sched.stop()
    # a healthy drain restores the component
    assert health.snapshot()["components"]["pipeline"]["status"] == "healthy"
