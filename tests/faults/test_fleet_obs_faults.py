"""Fleet observability under injected faults (ISSUE 20): an armed
``obs.fleet.pull`` degrades the federated scrape to partial-but-200
through the real /metrics route, an armed ``obs.fleet.capture`` turns a
peer's bundle tree into an error.txt while the local capture still
lands, and a dead owner mid-explain falls back to the local answer —
flagged, never a 500."""

import asyncio
import json

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.dynamic_lists import DynamicDecisionLists
from banjax_tpu.decisions.protected_paths import PasswordProtectedPaths
from banjax_tpu.decisions.rate_limit import (
    FailedChallengeRateLimitStates,
    RegexRateLimitStates,
)
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.fabric.peer import PeerUnavailable
from banjax_tpu.httpapi import server as server_mod
from banjax_tpu.obs.exposition import parse_text_format
from banjax_tpu.obs.fleet import FleetScraper, capture_fleet
from banjax_tpu.obs.flightrec import FlightRecorder
from banjax_tpu.resilience import failpoints
from banjax_tpu.resilience.health import HealthRegistry
from tests.mock_banner import MockBanner

RULES_YAML = """
regexes_with_rates:
  - decision: nginx_block
    rule: r
    regex: 'GET .*'
    interval: 5
    hits_per_interval: 100
"""

LOCAL_TEXT = (
    "# HELP banjax_x_total t\n# TYPE banjax_x_total counter\n"
    "banjax_x_total 3\n"
)
PEER_TEXT = LOCAL_TEXT.replace(" 3", " 4")


@pytest.fixture(autouse=True)
def _disarm():
    yield
    failpoints.disarm()


class FakeFabricService:
    """owner_of/explain_remote/node_id — what the explain proxy uses."""

    def __init__(self, node_id, owner, remote_payload=None, fail=False):
        self.node_id = node_id
        self._remote_payload = remote_payload
        self._fail = fail
        svc = self

        class _Router:
            @staticmethod
            def owner_of(ip):
                return owner

        self.router = _Router()

    def explain_remote(self, owner, ip):
        if self._fail:
            raise PeerUnavailable(f"{owner} is down")
        return dict(self._remote_payload)


def _deps(cfg, fleet=None, fabric_service=None):
    class Holder:
        def get(self):
            return cfg

    health = HealthRegistry()
    health.register("tailer").ok()
    return server_mod.ServerDeps(
        config_holder=Holder(),
        static_lists=StaticDecisionLists(cfg),
        dynamic_lists=DynamicDecisionLists(start_sweeper=False),
        protected_paths=PasswordProtectedPaths(cfg),
        regex_states=RegexRateLimitStates(),
        failed_challenge_states=FailedChallengeRateLimitStates(),
        banner=MockBanner(),
        health=health,
        fleet_getter=(lambda: fleet),
        fabric_service_getter=(lambda: fabric_service),
    )


def _get(deps, path):
    from aiohttp.test_utils import TestClient, TestServer

    async def go():
        app = server_mod.build_app(deps, listen_host="127.0.0.1")
        client = TestClient(TestServer(app))
        await client.start_server()
        try:
            r = await client.get(path)
            return r.status, await r.text()
        finally:
            await client.close()

    return asyncio.run(go())


def test_metrics_fleet_armed_pull_stays_200_and_parseable():
    cfg = config_from_yaml_text(RULES_YAML)
    scraper = FleetScraper(
        "w0", lambda: LOCAL_TEXT,
        peers_fn=lambda: {"w1": lambda: PEER_TEXT},
    )
    failpoints.arm("obs.fleet.pull")  # every pull faults
    status, text = _get(_deps(cfg, fleet=scraper), "/metrics?fleet=1")
    assert status == 200
    parsed = parse_text_format(text)  # strictly parseable while degraded
    unreach = {
        labels["instance"]: v
        for _n, labels, v in
        parsed["banjax_fleet_peer_unreachable"]["samples"]
    }
    assert unreach == {"w0": 0, "w1": 1}
    assert failpoints.fired_count("obs.fleet.pull") >= 1


def test_metrics_fleet_404_when_scraper_absent():
    cfg = config_from_yaml_text(RULES_YAML)
    status, _ = _get(_deps(cfg, fleet=None), "/metrics?fleet=1")
    assert status == 404
    # the plain scrape keeps working regardless
    status, text = _get(_deps(cfg, fleet=None), "/metrics")
    assert status == 200
    parse_text_format(text)


def test_explain_proxy_dead_owner_falls_back_local_flagged():
    cfg = config_from_yaml_text(RULES_YAML)
    svc = FakeFabricService("w0", owner="w1", fail=True)
    status, text = _get(
        _deps(cfg, fabric_service=svc), "/decisions/explain?ip=9.9.9.9"
    )
    assert status == 200
    doc = json.loads(text)
    assert doc["node_id"] == "w0"
    assert doc["owner_unreachable"] == "w1"
    assert doc["records"] == []


def test_explain_proxy_live_owner_tagged_with_owning_node():
    cfg = config_from_yaml_text(RULES_YAML)
    remote = {
        "ip": "9.9.9.9", "ledger_enabled": True,
        "records": [["9.9.9.9", "NginxBlock"]], "active_decision": None,
        "node_id": "w1",
    }
    svc = FakeFabricService("w0", owner="w1", remote_payload=remote)
    status, text = _get(
        _deps(cfg, fabric_service=svc), "/decisions/explain?ip=9.9.9.9"
    )
    assert status == 200
    doc = json.loads(text)
    assert doc["owning_node"] == "w1"
    assert doc["proxied"] is True
    assert doc["records"] == [["9.9.9.9", "NginxBlock"]]


def test_explain_owned_locally_skips_the_proxy():
    cfg = config_from_yaml_text(RULES_YAML)
    svc = FakeFabricService("w0", owner="w0", fail=True)  # proxy would blow
    status, text = _get(
        _deps(cfg, fabric_service=svc), "/decisions/explain?ip=9.9.9.9"
    )
    assert status == 200
    doc = json.loads(text)
    assert doc["node_id"] == "w0"
    assert "owning_node" not in doc
    assert "owner_unreachable" not in doc


def test_capture_failpoint_yields_error_txt_local_bundle_lands(tmp_path):
    failpoints.arm("obs.fleet.capture")
    rec = FlightRecorder(
        str(tmp_path / "incidents"), min_interval_s=0.0,
        metrics_text_fn=lambda: LOCAL_TEXT,
        fleet_capture_fn=lambda incident: capture_fleet(
            incident,
            lambda: {"w1": lambda i: {"metrics.prom": PEER_TEXT}},
        ),
    )
    name = rec.notify("fabric-takeover", "drill")
    assert name is not None
    bundle = tmp_path / "incidents" / name
    # local capture landed whole; the faulted peer is an error.txt
    assert (bundle / "metrics.prom").read_text() == LOCAL_TEXT
    err = (bundle / "peers" / "w1" / "error.txt").read_text()
    assert "obs.fleet.capture" in err or "capture failed" in err
    assert failpoints.fired_count("obs.fleet.capture") == 1
