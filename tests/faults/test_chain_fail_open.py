"""Decision-chain fail-open, end to end on both HTTP layouts: a crash in
decision_for_nginx (injected via the decision_chain failpoint) must produce
the reference's recovery contract — 500 + X-Accel-Redirect: @fail_open +
X-Banjax-Error — and the exception text must be CR/LF-sanitized so it
cannot split the response (ADVICE r5)."""

from pathlib import Path

import pytest
import requests

from banjax_tpu.resilience import failpoints

BASE = "http://localhost:8081"
_FIXTURES = Path(__file__).resolve().parent.parent / "fixtures"

INJECTED = "boom\r\nX-Injected: owned\r\n\r\nHTTP/1.1 200 OK"


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


@pytest.mark.parametrize("fast_path", [True, False], ids=["fastserve", "aiohttp"])
def test_fail_open_with_hostile_exception_text(app_factory, tmp_path, fast_path):
    custom = tmp_path / "banjax-config-failopen.yaml"
    custom.write_text(
        (_FIXTURES / "banjax-config-test.yaml").read_text()
        + f"\nhttp_fast_path: {str(fast_path).lower()}\ndisable_kafka: true\n"
    )
    app_factory(str(custom))

    failpoints.arm("decision_chain", message=INJECTED)
    r = requests.get(
        f"{BASE}/auth_request", params={"path": "/x"},
        headers={"X-Client-IP": "3.3.3.3"}, timeout=5,
    )
    # the fail-open contract (http_server.go:110-135)
    assert r.status_code == 500
    assert r.headers.get("X-Accel-Redirect") == "@fail_open"
    assert "boom" in r.headers.get("X-Banjax-Error", "")
    # sanitized: the CRLF payload must not become its own header or split
    # the response into a smuggled second one
    assert "X-Injected" not in r.headers
    assert "owned" in r.headers["X-Banjax-Error"]

    # disarmed → the chain serves normally again on the same app
    failpoints.disarm("decision_chain")
    r = requests.get(
        f"{BASE}/auth_request", params={"path": "/x"},
        headers={"X-Client-IP": "3.3.3.3"}, timeout=5,
    )
    assert r.status_code == 200
