"""Fault composition for the single-kernel fused path + the state-aware
turn-release fix (fused_windows._free_turn / _release_chunk_pins).

The bug being regression-locked: abandon() used to sweep dead turns for
both _resolve_seq and _collect_seq unconditionally, so a chunk settled
by two paths (a submit-failure abandon racing a teardown abort, or an
abandon after fallback_done) could mark the same turn dead twice and
double-release slot pins — the double pin release can free a pin held
by a DIFFERENT in-flight chunk on the same slot.  Settlement is now
tracked per chunk (pins_released / turns_freed) so every path is
idempotent."""

import threading
import time

import pytest

from banjax_tpu.config.schema import config_from_yaml_text
from banjax_tpu.decisions.rate_limit import RegexRateLimitStates
from banjax_tpu.decisions.static_lists import StaticDecisionLists
from banjax_tpu.matcher.runner import TpuMatcher
from banjax_tpu.pipeline import PipelineScheduler
from banjax_tpu.resilience import failpoints
from tests.mock_banner import MockBanner

RULES_YAML = r"""
regexes_with_rates:
  - decision: nginx_block
    rule: r1
    regex: 'GET /attack.*'
    interval: 5
    hits_per_interval: 2
"""


@pytest.fixture(autouse=True)
def _clean_failpoints():
    failpoints.disarm()
    yield
    failpoints.disarm()


def make_matcher(**cfg_overrides):
    cfg = config_from_yaml_text(RULES_YAML)
    cfg.matcher_device_windows = True
    for k, v in cfg_overrides.items():
        setattr(cfg, k, v)
    banner = MockBanner()
    m = TpuMatcher(cfg, banner, StaticDecisionLists(cfg),
                   RegexRateLimitStates())
    assert m._fw_pipeline is not None
    return m, banner


def lines_at(now, n, path="/attack"):
    return [
        f"{now:.6f} 1.2.3.{i % 9} GET h.com GET {path}{i % 3} HTTP/1.1 ua -"
        for i in range(n)
    ]


def mixed_lines(now, n):
    """Mostly-benign mix: the stage-1 gate holds, so fused chunks commit
    instead of overflowing the candidate capacity."""
    return [
        f"{now:.6f} 1.2.3.{i % 9} GET h.com GET "
        f"/{'attack' if i % 13 == 0 else 'page'}{i % 3} HTTP/1.1 ua -"
        for i in range(n)
    ]


def _quiescent(fw):
    """Every turn settled, no dead-turn residue, no leaked pins."""
    with fw._cv:
        assert fw._next_seq == fw._resolve_seq == fw._collect_seq, (
            fw._next_seq, fw._resolve_seq, fw._collect_seq,
        )
        assert not fw._dead["_resolve_seq"] and not fw._dead["_collect_seq"], (
            fw._dead,
        )
    assert (fw.windows._pin_counts == 0).all()


@pytest.mark.parametrize("single_kernel", ["on", "off"])
def test_submit_failpoint_settles_turns_once(single_kernel):
    """pipeline.submit fires mid-stream: the failed batch drains
    generically (classic path, no fused turns), LATER fused batches keep
    committing, and the turn counters/dead sets/pins settle exactly —
    the double-sweep would leave dead-set residue or negative-clamped
    pins behind."""
    now = time.time()
    m, _ = make_matcher(pallas_single_kernel=single_kernel,
                        matcher_prefilter_cand_frac=1.0)
    collected = []
    lock = threading.Lock()

    def sink(ls, rs):
        with lock:
            collected.append((ls, rs))

    sched = PipelineScheduler(lambda: m, on_results=sink,
                              now_fn=lambda: now)
    sched.start()
    for i in range(0, 200, 40):
        sched.submit(mixed_lines(now, 40))
    assert sched.flush(120)
    failpoints.arm("pipeline.submit", count=1)
    for i in range(0, 200, 40):
        sched.submit(mixed_lines(now, 40))
    assert sched.flush(120)
    sched.stop()

    assert failpoints.fired_count("pipeline.submit") == 1
    snap = sched.stats.peek()
    assert snap["PipelineAdmittedLines"] == \
        snap["PipelineProcessedLines"] + snap["PipelineShedLines"] + \
        snap["PipelineDrainErrorLines"]
    assert m.pipelined_fused_chunks > 0
    _quiescent(m._fw_pipeline)


@pytest.mark.parametrize("single_kernel", ["on", "off"])
def test_double_abort_is_idempotent(single_kernel):
    """pipeline_abort called twice on the same un-finished batch (a
    device-failure abort racing a drain-failure abort does exactly this)
    must settle each chunk's turns and pins once; a later batch then
    drains normally."""
    now = time.time()
    m, _ = make_matcher(pallas_single_kernel=single_kernel)
    s1 = m.pipeline_begin(lines_at(now, 30), now)
    m.pipeline_submit(s1, now=now)
    entries = list(s1.get("fused") or [])
    assert entries
    # teardown path one: explicit abandon of the first chunk (the
    # submit-failure cleanup), then the full abort sweeps ALL entries —
    # including the already-settled one
    m._fw_pipeline.abandon(entries[0]["pend"])
    s1["fused"] = entries
    m.pipeline_abort(s1)
    s1["fused"] = entries
    m.pipeline_abort(s1)  # and once more, for the race

    s2 = m.pipeline_begin(lines_at(now, 30), now)
    m.pipeline_submit(s2, now=now)
    m.pipeline_collect(s2)
    results, _ = m.pipeline_finish(s2, now)
    assert any(r.rule_results for r in results)
    _quiescent(m._fw_pipeline)


def test_abandon_after_fallback_cannot_double_release_pins():
    """An overflowing chunk's fallback releases its pins via apply_bitmap
    (fallback_done marks them settled); a teardown abandon arriving after
    that must NOT decrement them again — with another batch in flight on
    the same slots, the double release would let the LRU evict pinned
    state."""
    now = time.time()
    # cand_frac 1/64 + all-matching lines: every chunk overflows
    m, _ = make_matcher(
        pallas_single_kernel="on", matcher_batch_lines=64,
        matcher_prefilter_cand_frac=1.0 / 64,
    )
    lines = [
        f"{now:.6f} 5.5.5.{i % 7} GET h.com GET /attack{i} HTTP/1.1 ua -"
        for i in range(64)
    ]
    s = m.pipeline_begin(lines, now)
    m.pipeline_submit(s, now=now)
    entries = list(s["fused"])
    m.pipeline_collect(s)
    results, _ = m.pipeline_finish(s, now)  # overflow → classic fallback
    assert m._fw_pipeline.sk_fallbacks > 0
    # teardown replays the settled entries through abandon: a no-op
    for e in entries:
        m._fw_pipeline.abandon(e["pend"])
    _quiescent(m._fw_pipeline)


def test_resolve_failpoint_under_single_kernel_loses_only_its_chunk():
    """matcher.resolve firing at the drain of a single-kernel chunk marks
    only that chunk's lines as errors; later batches drain fine (turns
    freed by the state-aware settlement)."""
    now = time.time()
    m, _ = make_matcher(pallas_single_kernel="on")
    failpoints.arm("matcher.resolve", count=1)
    s1 = m.pipeline_begin(lines_at(now, 20), now)
    m.pipeline_submit(s1, now=now)
    m.pipeline_collect(s1)
    results, _ = m.pipeline_finish(s1, now)
    assert all(r.error for r in results)
    failpoints.disarm()
    s2 = m.pipeline_begin(lines_at(now, 20), now)
    m.pipeline_submit(s2, now=now)
    m.pipeline_collect(s2)
    results2, _ = m.pipeline_finish(s2, now)
    assert any(r.rule_results for r in results2)
    assert not any(r.error for r in results2)
    _quiescent(m._fw_pipeline)
